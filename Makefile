# Build targets for the native runtime pieces and the test/bench entry
# points. The Python package itself needs no build step; the native
# scheduler also auto-builds on first import (quest_tpu/native/__init__.py)
# — this Makefile is the explicit path.

CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -fPIC -Wall -Wextra
SHELL := /bin/bash


NATIVE_DIR := quest_tpu/native
NATIVE_SO := $(NATIVE_DIR)/_qts.so

.PHONY: all native test verify verify-static verify-faults verify-telemetry verify-elastic verify-batch verify-introspect verify-governor verify-serve verify-pod verify-optimizer verify-chaos verify-sparse verify-mega verify-obs verify-coldstart verify-regress bench docs clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_DIR)/scheduler.cc
	$(CXX) $(CXXFLAGS) -shared $< -o $@

test: native
	python -m pytest tests/ -q

# Static analysis gate (docs/design.md §23): qlint over the full tree
# (zero unsuppressed findings, every suppression justified) plus the
# @sharded_contract declarations verified against compiled HLO on an
# 8-shard CPU dryrun.  Budget: < 10 s.  XLA_FLAGS must be set before
# the jax backend initializes, hence here and not inside the module.
verify-static:
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python -m quest_tpu.analysis --contracts

# Multi-tenant serving layer (docs/design.md §24): continuous batcher,
# admission control, weighted fair scheduling, and the pinned
# preempt-to-checkpoint bit-identity contract — plus the saturation
# guard (continuous >= 2x batch-at-once circuits/sec on the same
# Poisson trace, loaded interactive p99 <= 2x unloaded).  The
# throughput number itself joins the regression trajectory as
# bench_suite config 12 (scripts/bench_regress.py normalizes
# config12:circuits_per_sec from the committed BENCH_r*.json rounds).
verify-serve:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/bench_serve.py

# Circuit optimizer (docs/design.md §26): the pre-planner rewrite
# contract suite (parity on every path, bit-identical cancellation,
# plan-key retrace, drift==0) plus the A/B guard — amplitude parity,
# no exchange regression on any workload, >= 1.5x window-remap
# exchange reduction on the config-6-style churn.  The headline
# speedup joins the regression trajectory as bench_suite config 14.
verify-optimizer:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_optimizer.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/bench_optimizer.py

# Serving-layer fault tolerance (docs/design.md §27): the retry /
# quarantine / failover / heal unit suite plus the seeded chaos harness
# — three seeds covering bank faults, checkpoint-IO faults, shard AND
# host loss + mesh heal, OOM bisection, and NaN poison, asserting
# bit-identical completions vs the fault-free replay, zero cross-tenant
# propagation, bounded-step idle, and 100% non-poison availability.
verify-chaos:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_serve_resilience.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 QT_TOPOLOGY=2x4 python scripts/chaos_serve.py

# Permutation fast paths + sparse state prep (docs/design.md §28): the
# parity/fold/admission contract suite plus the QT_PERM_FAST on/off A/B
# — amplitude parity on every workload, model_drift_total == 0 in both
# arms, the relabel-only stream pinned to zero window exchanges AND
# zero compiled collectives on its canonical read, and >= 5x wall-clock
# over the dense baseline.  The headline speedups join the regression
# trajectory as bench_suite config 16.
verify-sparse:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_permfast.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/bench_sparse.py

# Window megakernel (docs/design.md §29): the parity/fallback/routing
# contract suite plus the QT_MEGAKERNEL on/off A/B — scalar run gates
# >= 1.3x on the dense-window drain (parity <= 1e-10, drift == 0 both
# arms); the 8-device dryrun re-checks parity/drift/routing on the
# SHARDED dispatch path (--floor 0: the overhead win is calibrated
# single-device).  The speedup joins the regression trajectory as
# bench_suite config 17.
verify-mega:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_megakernel.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu python scripts/bench_megakernel.py
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/bench_megakernel.py --n 18 --depth 3 --reps 1 --floor 0

# Observability front door (docs/design.md §30): request-scoped
# tracing, the flight recorder, /metrics over live HTTP, and per-op
# wall-time attribution — the telemetry + serve-resilience suites
# (which pin the span-tree, flight-dump, and byte-identical /metrics
# contracts) plus the overhead guard, which now ALSO gates trace mode
# under the same < 5% budget.
verify-obs:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py tests/test_serve_resilience.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	python scripts/bench_telemetry.py

# Cold-start elimination (docs/design.md §31): the persistent AOT
# executable cache + serve warm pools — the invalidation-matrix /
# corruption / cross-process / warm-pool suite, then the fresh-process
# gate: a cached child must deserialize instead of compiling (hits>=1,
# puts==0), land within 2x steady state (+ deserialize allowance), and
# reproduce the compiled run bit-identically.
verify-coldstart:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_aotcache.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu python scripts/bench_coldstart.py --check

# The tier-1 gate, verbatim from ROADMAP.md: CPU backend, not-slow
# marker, collection errors surfaced, pass count echoed.
verify: verify-static verify-serve verify-optimizer verify-chaos verify-sparse verify-mega verify-coldstart
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Fault-injection / resilience suite (tests marked `faults`): simulated
# preemptions, mid-save kills, corrupt checkpoints, transient IO errors,
# NaN injection + watchdog policies (quest_tpu/resilience.py).
verify-faults:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults -p no:cacheprovider -p no:xdist -p no:randomly

# Elastic recovery (docs/design.md §19): mesh-portable checkpoints
# (8->4/8->1/8->16 bit-identical resume), guarded collectives, and
# degraded-mesh failover — plus the MTTR benchmark with its
# detect/rollback/reshard/resume phase breakdown.
verify-elastic:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py tests/test_resilience.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	python scripts/bench_failover.py

# Telemetry layer (quest_tpu/telemetry.py): the unit/integration suite
# plus the micro-benchmark guard — enabled-mode accounting must cost
# < 5% over QT_TELEMETRY=off on a 1k-gate fusion drain.
verify-telemetry:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	python scripts/bench_telemetry.py

# Batched execution (docs/design.md §20): register banks, ensemble
# scheduling, trajectory sampling — the bit-parity/retrace/convergence
# suite plus the batched-vs-looped throughput guard (>= 4x circuits/sec
# at batch 16).
verify-batch:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_batch.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu python scripts/bench_batch.py

# Execution introspection (docs/design.md §21): plan explainer, HLO
# audit / collective budgets, and the predicted-vs-measured
# reconciliation contract (explainCircuit == cost model == telemetry
# counters, model_drift_total == 0 on the 8-shard dryrun).
verify-introspect:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_introspect.py -q -p no:cacheprovider -p no:xdist -p no:randomly

# Memory-governed execution (docs/design.md §22): HBM budgeting,
# admission control, spill-to-host eviction, the degradation ladder,
# and OOM recovery — plus the overhead guard (governed path must cost
# < 1% over QT_MEM_POLICY=off on a 1k-gate drain).
verify-governor:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_governor.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q -k "Oom or oom" -p no:cacheprovider -p no:xdist -p no:randomly
	python scripts/bench_governor.py

# Pod-scale topology layer (docs/design.md §25): the hierarchical
# DCN x ICI model, tier-classified exchange accounting, HLO placement
# pins, host-loss failover — plus the planner A/B guard (tier-aware
# remap must cut modeled AND measured DCN bytes >= 2x vs flat planning
# on the emulated slow-DCN 2x4 churn workload, bit-identically).  The
# reduction joins the regression trajectory as bench_suite config 13.
verify-pod:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_topology.py -q -p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python scripts/bench_pod.py

# Regression gate over the committed BENCH_r*.json trajectory: every
# normalized metric must stay within 15% of its drift-resistant median
# baseline (scripts/bench_regress.py; --current FILE gates a fresh run).
verify-regress:
	python scripts/bench_regress.py --threshold 0.15

bench: native
	python bench.py

docs:
	python scripts/gen_api_reference.py

clean:
	rm -f $(NATIVE_SO)
	find . -name __pycache__ -type d -exec rm -rf {} +
