# Build targets for the native runtime pieces and the test/bench entry
# points. The Python package itself needs no build step; the native
# scheduler also auto-builds on first import (quest_tpu/native/__init__.py)
# — this Makefile is the explicit path.

CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -fPIC -Wall -Wextra

NATIVE_DIR := quest_tpu/native
NATIVE_SO := $(NATIVE_DIR)/_qts.so

.PHONY: all native test bench docs clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_DIR)/scheduler.cc
	$(CXX) $(CXXFLAGS) -shared $< -o $@

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

docs:
	python scripts/gen_api_reference.py

clean:
	rm -f $(NATIVE_SO)
	find . -name __pycache__ -type d -exec rm -rf {} +
