"""f64-on-TPU evidence for the BASELINE.md north star.

Runs config 1 (12q hadamard + controlledRotateX chain + calcProbOfOutcome)
and a config-2-shaped random circuit at qreal = double (set_precision(2),
jax_enable_x64) on the current default backend, dumping the probability
and the full amplitude array.  Run once on the TPU and once with
QT_F64_CPU=1 (forces the CPU backend); compare_f64.py diffs the dumps.

The reference's north star asks for bit-exact calcProbOfOutcome between
the TPU and CPU backends at double precision; XLA's TPU f64 is software
emulation, so the honest claim is measured here, not assumed.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("QT_F64_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np

import quest_tpu as qt

qt.set_precision(2)


def config1(env):
    n = 12
    q = qt.createQureg(n, env)
    qt.hadamard(q, 0)
    for t in range(1, n):
        qt.controlledRotateX(q, t - 1, t, 0.3 + 0.01 * t)
    t0 = time.perf_counter()
    p = qt.calcProbOfOutcome(q, n - 1, 0)
    wall = time.perf_counter() - t0
    return np.asarray(q.amps), p, wall


def config2(env, n):
    rng = np.random.default_rng(7)
    q = qt.createQureg(n, env)
    with qt.gateFusion(q):
        for d in range(6):
            for t in range(n):
                u, _ = np.linalg.qr(rng.standard_normal((2, 2))
                                    + 1j * rng.standard_normal((2, 2)))
                qt.unitary(q, t, u)
            for t in range(d % 2, n - 1, 2):
                qt.controlledNot(q, t, t + 1)
    t0 = time.perf_counter()
    p = qt.calcProbOfOutcome(q, n - 1, 0)
    wall = time.perf_counter() - t0
    return np.asarray(q.amps), p, wall


if __name__ == "__main__":
    tag = "cpu" if os.environ.get("QT_F64_CPU") == "1" else jax.default_backend()
    env = qt.createQuESTEnv(num_devices=1)
    n2 = int(os.environ.get("QT_F64_N2", "20"))
    a1, p1, w1 = config1(env)
    t0 = time.perf_counter()
    a2, p2, w2 = config2(env, n2)
    total2 = time.perf_counter() - t0
    np.savez(f"/tmp/f64_{tag}.npz", a1=a1, p1=p1, a2=a2, p2=p2)
    print(f"backend={tag} dtype={a1.dtype} "
          f"cfg1: p={p1!r} cfg2(n={n2}): p={p2!r} "
          f"cfg2 total={total2:.2f}s")
