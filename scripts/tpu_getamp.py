"""On-chip proof of layout-safe element access at 28q+ (VERDICT r3 item 3):
after a chained fused-QFT plan leaves the state in the canonical tiled
view, getAmp-class reads (ops/element.get_amp_pair) and a setAmps-class
ranged write (set_amp_range) complete in milliseconds with NO full-state
relayout — the access pattern that previously OOM'd at 30q by the
round-3 analysis (BASELINE.md).

Correctness oracle: QFT of |0..0> is the uniform state, so EVERY
amplitude must read 2^(-n/2) + 0i at any index.

Writes scripts/tpu_getamp_result.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tpu_getamp_result.json")


def log(*a):
    print(f"[{time.strftime('%H:%M:%S')}]", *a, flush=True)


def run(n):
    import jax.numpy as jnp
    import numpy as np

    from quest_tpu import circuit as C
    from quest_tpu.models.circuits import zero_state_canonical
    from quest_tpu.ops import element as E

    res = {"n": n}
    log(f"building {n}q chained fused QFT ...")
    t0 = time.time()
    a = zero_state_canonical(n)
    a = C.fused_qft(a, n, 0, n)
    a.block_until_ready()
    res["qft_s"] = round(time.time() - t0, 1)
    log(f"QFT done in {res['qft_s']} s; reading amplitudes ...")

    expect = 2.0 ** (-n / 2)
    rng = np.random.default_rng(0)
    idxs = [0, 1, (1 << n) - 1] + [int(x) for x in
                                   rng.integers(0, 1 << n, size=13)]
    t0 = time.time()
    vals = [np.asarray(E.get_amp_pair(a, i)) for i in idxs]
    res["getamp_16_reads_s"] = round(time.time() - t0, 4)
    err = max(abs(v[0] - expect) + abs(v[1]) for v in vals)
    res["getamp_max_err"] = float(err)
    log(f"16 reads in {res['getamp_16_reads_s']} s, max err {err:.2e}")

    # ranged write straddling a tile boundary, then read back
    start = (1 << 14) - 3
    vals2 = np.asarray([[0.125] * 6, [-0.25] * 6], np.float32)
    t0 = time.time()
    a = E.set_amp_range(a, start, vals2)
    back = np.asarray(E.get_amp_pair(a, start + 4))
    res["set_plus_read_s"] = round(time.time() - t0, 4)
    res["set_roundtrip_err"] = float(abs(back[0] - 0.125) + abs(back[1] + 0.25))
    log(f"ranged write+read {res['set_plus_read_s']} s, "
        f"err {res['set_roundtrip_err']:.2e}")
    res["ok"] = bool(err < 1e-6 * expect + 1e-9
                     and res["set_roundtrip_err"] < 1e-7)
    return res


def main():
    import jax

    log("claiming device ...")
    devs = jax.devices()
    log(f"devices: {devs}")
    out = {"devices": str(devs), "runs": []}
    for n in (28, 30):
        try:
            out["runs"].append(run(n))
        except Exception as e:  # OOM at 30q would reproduce the old trap
            out["runs"].append({"n": n, "error": repr(e)[:500]})
            log(f"{n}q FAILED: {e!r}")
    out["ok"] = all(r.get("ok") for r in out["runs"])
    with open(RESULT, "w") as f:
        json.dump(out, f, indent=2)
    log(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
