"""Memory-governor overhead guard: admission + drain-prediction
accounting with an (unconstrained) HBM budget active must cost < 1%
of a 1k-gate fusion drain (ISSUE 9 acceptance).

The workload matches bench_telemetry.py's instrumentation-heaviest
shape: 1000 dense gates issued through the imperative API inside ONE
gateFusion drain, then a state read.  The gate is the DIRECT
measurement: the governed path adds exactly (a) one admission check
per register creation and (b) one predictor walk + ledger round-trip
per drain, so both are timed in isolation (thousands of iterations,
sub-microsecond noise floor) and compared against the measured drain
wall-clock.  A paired off/on wall-clock A/B is also reported
(ab_overhead) as a cross-check, but is informational only — on shared
CI hosts run-to-run drift is 10-25%, unusably above a 1% budget, while
the hook measurement is stable.

Usage: python scripts/bench_governor.py [--n 12] [--gates 1000]
       [--reps 7] [--budget 0.01] [--no-check]
Exits non-zero when the overhead exceeds the budget (unless --no-check).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import fusion, governor  # noqa: E402


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def main():
    n = _arg("--n", 12)
    gates = _arg("--gates", 1000)
    reps = _arg("--reps", 7)
    budget = _arg("--budget", 0.01, float)
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(17)
    g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    u, _ = np.linalg.qr(g)
    cx = np.array([[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]],
                  dtype=complex)

    def issue(q):
        with qt.gateFusion(q):
            k = 0
            while k < gates:
                for t in range(n):
                    qt.unitary(q, t, u)
                    k += 1
                for t in range(n - 1):
                    qt.twoQubitUnitary(q, t, t + 1, cx)
                    k += 1

    def run():
        q = qt.createQureg(n, env)
        issue(q)
        return qt.calcTotalProb(q)

    def set_mode(governed):
        if governed:
            os.environ["QT_HBM_BUDGET_BYTES"] = str(1 << 40)
            os.environ["QT_MEM_POLICY"] = "degrade"
        else:
            os.environ.pop("QT_HBM_BUDGET_BYTES", None)
            os.environ["QT_MEM_POLICY"] = "off"

    def timed():
        t0 = time.perf_counter()
        run()
        return time.perf_counter() - t0

    try:
        for governed in (False, True):  # warm plan + executor caches
            set_mode(governed)
            governor.reset()
            run()

        # informational paired A/B: alternate arms within each pair so
        # host drift cancels, decide on the median of per-pair ratios
        offs, ons = [], []
        for _ in range(reps):
            set_mode(False)
            offs.append(timed())
            set_mode(True)
            ons.append(timed())
        ratios = sorted(on / off for on, off in zip(ons, offs))
        ab_overhead = ratios[len(ratios) // 2] - 1.0

        # the gated measurement: time the exact hooks the governed path
        # adds.  Per run that is ONE admission check (createQureg) and
        # ONE govern_drain walk over the full planned program.
        set_mode(True)
        governor.reset()
        q = qt.createQureg(n, env)
        fusion.start_gate_fusion(q)
        k = 0
        while k < gates:
            for t in range(n):
                qt.unitary(q, t, u)
                k += 1
            for t in range(n - 1):
                qt.twoQubitUnitary(q, t, t + 1, cx)
                k += 1
        program, arrays, _fp, nloc, nsh = fusion.plan_items_quiet(
            q, list(q._fusion.gates))
        q._fusion.gates.clear()
        fusion.stop_gate_fusion(q)

        iters = 200
        t0 = time.perf_counter()
        for _ in range(iters):
            governor.govern_drain(q, program, arrays, nloc=nloc, nsh=nsh)
            governor.end_drain()
        drain_hook_s = (time.perf_counter() - t0) / iters

        t0 = time.perf_counter()
        for _ in range(iters):
            governor.admit_new(q, "createQureg")
        admit_hook_s = (time.perf_counter() - t0) / iters
    finally:
        os.environ.pop("QT_HBM_BUDGET_BYTES", None)
        os.environ.pop("QT_MEM_POLICY", None)
        governor.reset()

    off_best = min(offs)
    hook_s = drain_hook_s + admit_hook_s
    overhead = hook_s / off_best
    rec = {
        "bench": "governor_admission_overhead_1k_gate_drain",
        "n": n,
        "gates": gates,
        "backend": jax.default_backend(),
        "off_seconds": round(off_best, 5),
        "on_seconds": round(min(ons), 5),
        "govern_drain_hook_seconds": round(drain_hook_s, 7),
        "admission_hook_seconds": round(admit_hook_s, 7),
        "overhead": round(overhead, 5),
        "ab_overhead": round(ab_overhead, 4),
        "budget": budget,
        "ok": overhead <= budget,
    }
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    if overhead > budget:
        print(f"FAIL: governed-path hook overhead {overhead:.2%} "
              f"exceeds the {budget:.0%} budget", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
