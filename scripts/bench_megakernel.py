#!/usr/bin/env python
"""Window-megakernel A/B (ISSUE 18 / docs/design.md §29):
QT_MEGAKERNEL=on vs off on a dense-window drain.

Two measurements over the same random dense circuit (the bench.py
config-2 generator shape — per-layer 1q Haar unitaries + an alternating
CNOT ladder, every target shard-local so the planner forms dense fused
windows):

* ``plan``  — the planned program executed as a chained device loop
  (circuit.execute_plan_chained): device/XLA truth of the fused route
  with zero per-call harness overhead.  The two arms are timed
  INTERLEAVED and the headline ``megakernel_speedup_x`` is the MEDIAN
  of the per-rep paired off/on ratios (gates >= 1.3x): shared-machine
  load drift moves both halves of a pair together, so the paired
  median survives contention that makes a best-of quotient swing by
  tens of percent.  The megawin route does every grouped pass per
  state block load where the per-pass route pays one full HBM
  (interpret: full-state materialization) round trip per gate stack.
* ``drain`` — the same circuit drained through the full fusion path
  (gateFusion) in both arms under the process mesh, with
  QT_PERM_FAST=off pinned in BOTH arms (this is the DENSE-window A/B;
  perm-splitting the CNOT ladders leaves nothing groupable at small
  n): amplitude parity <= 1e-10 between arms (the megakernel reuses
  the per-pass kernel's block body, so the diff is exactly 0.0),
  ``model_drift_total == 0`` in BOTH arms (§21 prices the grouping
  identically by construction), the on arm actually routes through
  megawin groups (``megakernel_dispatch_total{route=mega}`` > 0), and
  the per-window HBM-round-trip gauge drops.

Usage: python scripts/bench_megakernel.py [--n 14] [--depth 60]
       [--reps 4] [--floor 1.3] [--no-check]
``make verify-mega`` runs it twice: once scalar (the speedup gate — the
megakernel's overhead win is calibrated against a single-device
process) and once on the 8-device virtual mesh with ``--n 18 --floor
0`` so the drain half exercises the SHARDED dispatch route (parity,
drift, and megawin routing under shard_map; nloc = n-3 must reach 15
before a sharded remap window holds more than one fused window to
group).  --no-check skips every gating assert; --floor overrides just
the speedup floor (0 disables it).
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import circuit as C  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402
from quest_tpu.models import circuits  # noqa: E402

PARITY_TOL = 1e-10
SPEEDUP_FLOOR = 1.3


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _haar_units(n, depth, seed=7):
    """(depth, n) complex Haar 2x2s — one per (layer, qubit)."""
    rng = np.random.default_rng(seed)
    z = (rng.standard_normal((depth, n, 2, 2))
         + 1j * rng.standard_normal((depth, n, 2, 2)))
    us = np.empty_like(z)
    for d in range(depth):
        for t in range(n):
            q, r = np.linalg.qr(z[d, t])
            us[d, t] = q * (np.diag(r) / np.abs(np.diag(r)))
    return us


def _plan_ab(n, depth, us, k, reps):
    """Both QT_MEGAKERNEL arms of the chained-plan loop, INTERLEAVED:
    each rep times off then on back to back and contributes one paired
    off/on ratio — the shared-machine drift that moves a whole rep
    moves both arms of the pair, so the median ratio is the
    drift-resistant speedup (a best-of-reps quotient is not: one slow
    draw on either side swings it by tens of percent)."""
    us_soa = np.stack([us.real, us.imag], axis=2)
    arms = {}
    for flag in ("off", "on"):
        os.environ["QT_MEGAKERNEL"] = flag
        plan = C.plan_circuit(circuits.bench_gate_list(n, depth, us_soa), n)
        arms[flag] = {"plan": plan, "st": C.stats(plan),
                      "ops": C.plan_to_device(plan, jnp.float32)}

    def once(flag):
        os.environ["QT_MEGAKERNEL"] = flag
        a = circuits.zero_state_canonical(n)
        t0 = time.perf_counter()
        for _ in range(k):
            a = C.execute_plan_chained(a, arms[flag]["ops"], n)
        amp = float(circuits.amp00_canonical(a))
        return time.perf_counter() - t0, amp

    once("off")  # compile + warm both executables
    once("on")
    best = {"off": float("inf"), "on": float("inf")}
    amp = {}
    ratios = []
    for _ in range(reps):
        s_off, amp["off"] = once("off")
        s_on, amp["on"] = once("on")
        best["off"] = min(best["off"], s_off)
        best["on"] = min(best["on"], s_on)
        ratios.append(s_off / max(s_on, 1e-9))
    out = {}
    for flag in ("off", "on"):
        st = arms[flag]["st"]
        out[flag] = {"megakernel": flag,
                     "seconds": round(best[flag], 4),
                     "programs_per_iter": len(arms[flag]["plan"]),
                     "megawin_groups": st.get("megawin", 0),
                     "megawin_grouped_ops": st.get("megawin_ops", 0),
                     "prob_check": amp[flag]}
    return out, round(statistics.median(ratios), 2)


def _apply_layers(q, n, depth, us):
    """The same circuit through the QuEST API, for the fusion drain."""
    for d in range(depth):
        for t in range(n):
            qt.unitary(q, t, us[d, t])
        for t in range(n - 1):
            if (d + t) % 2 == 0:
                qt.controlledNot(q, t, t + 1)


def _drain_arm(env, flag, n, depth, us, reps):
    """One arm of the full fusion-path drain: parity amplitudes, drift,
    and the megakernel route telemetry."""
    os.environ["QT_MEGAKERNEL"] = flag
    best = float("inf")
    amps = None
    drift = mega = fallback = 0
    trips = None
    for rep in range(reps + 1):  # rep 0 = warm-up/compile
        T.reset()
        q = qt.createQureg(n, env)
        qt.initDebugState(q)
        qt.startGateFusion(q)
        _apply_layers(q, n, depth, us)
        t0 = time.perf_counter()
        qt.stopGateFusion(q)
        amps = np.asarray(q.amps)  # canonical read joins the timed cost
        seconds = time.perf_counter() - t0
        if rep:
            best = min(best, seconds)
        drift = int(T.counter_total("model_drift_total"))
        mega = int(T.counter_sum("megakernel_dispatch_total", route="mega"))
        fallback = int(T.counter_sum("megakernel_dispatch_total",
                                     route="fallback"))
        trips = T.gauge_max("window_hbm_round_trips")
    return {"megakernel": flag, "seconds": round(best, 4),
            "drift": drift, "mega_dispatches": mega,
            "fallback_dispatches": fallback,
            "hbm_round_trips_per_window": trips}, amps


def run(n=14, depth=60, reps=4, devices=None):
    """``devices`` pins the mesh width (None = every visible device).
    The scalar speedup calibration wants devices=1 even when a virtual
    8-device mesh is forced process-wide (bench_suite's CPU smoke mode):
    sharding a small-n drain leaves nloc < the 14-qubit window and no
    fused windows form at all."""
    env = qt.createQuESTEnv() if devices is None \
        else qt.createQuESTEnv(num_devices=devices)
    prev_mode = T.mode_name()
    prev_flag = os.environ.get("QT_MEGAKERNEL")
    T.configure("on")
    prev_perm = os.environ.get("QT_PERM_FAST")
    try:
        us = _haar_units(n, depth)
        plans, speedup = _plan_ab(n, depth, us, 3, reps)
        plan_off, plan_on = plans["off"], plans["on"]
        # The drain half measures ROUTING (parity, drift, telemetry), and
        # this is the DENSE-window A/B: pin QT_PERM_FAST=off in both arms
        # so the CNOT ladders fuse into the dense windows the megakernel
        # targets instead of splitting every dense run down to a single
        # window (at n=14 a perm-split dense run is one 1q layer = one
        # winfused op, which nothing can group).
        os.environ["QT_PERM_FAST"] = "off"
        drain_off, a_off = _drain_arm(env, "off", n, depth, us, max(1, reps - 1))
        drain_on, a_on = _drain_arm(env, "on", n, depth, us, max(1, reps - 1))
    finally:
        for key, val in (("QT_MEGAKERNEL", prev_flag),
                         ("QT_PERM_FAST", prev_perm)):
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        T.reset()
        T.configure(prev_mode)
    return {
        "bench": "megakernel_ab",
        "n": n, "depth": depth, "reps": reps,
        "backend": jax.default_backend(),
        "devices": env.num_devices,
        "plan": {"off": plan_off, "on": plan_on},
        "drain": {"off": drain_off, "on": drain_on},
        "megakernel_speedup_x": speedup,
        "drain_speedup_x": round(
            drain_off["seconds"] / max(drain_on["seconds"], 1e-9), 2),
        "max_abs_err": float(np.abs(a_on - a_off).max()),
    }


def main():
    rec = run(n=_arg("--n", 14), depth=_arg("--depth", 60),
              reps=_arg("--reps", 4), devices=_arg("--devices", None))
    floor = _arg("--floor", SPEEDUP_FLOOR, float)
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    ok = True
    if rec["max_abs_err"] > PARITY_TOL:
        print(f"FAIL: on/off amplitude mismatch {rec['max_abs_err']:.3e} "
              "— the megakernel must be bit-identical to the per-pass "
              "route (same block body, same order)", file=sys.stderr)
        ok = False
    for arm in ("off", "on"):
        if rec["drain"][arm]["drift"]:
            print(f"FAIL: {arm}-arm model_drift_total="
                  f"{rec['drain'][arm]['drift']} (§21 must price both "
                  "QT_MEGAKERNEL arms identically)", file=sys.stderr)
            ok = False
    if not rec["drain"]["on"]["mega_dispatches"]:
        print("FAIL: on arm dispatched no megawin groups — the dense "
              "windows did not route through the megakernel",
              file=sys.stderr)
        ok = False
    if rec["drain"]["off"]["mega_dispatches"]:
        print("FAIL: off arm dispatched megawin groups "
              f"({rec['drain']['off']['mega_dispatches']})",
              file=sys.stderr)
        ok = False
    t_off = rec["drain"]["off"]["hbm_round_trips_per_window"]
    t_on = rec["drain"]["on"]["hbm_round_trips_per_window"]
    if t_off is not None and t_on is not None and not t_on < t_off:
        print(f"FAIL: HBM round trips per window did not drop "
              f"(off={t_off} on={t_on})", file=sys.stderr)
        ok = False
    if floor and rec["megakernel_speedup_x"] < floor:
        print(f"FAIL: megakernel_speedup_x {rec['megakernel_speedup_x']}x "
              f"below the {floor}x acceptance floor",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
