"""Seeded chaos harness for the serving layer (``make verify-chaos``).

For each seed this builds ONE deterministic open-loop arrival trace
(multi-tenant, mixed priorities, one NaN-poisoned job) and replays it
twice through :class:`quest_tpu.serve.SimServer`:

- a **fault-free baseline** run, recording every job's canonical
  amplitudes, measurement outcomes, and final RNG key state;
- a **chaos** run under a seed-derived FaultPlan covering an injected
  bank fault, transient checkpoint-IO failures, a shard/host loss
  followed by a mesh heal, a synthetic OOM (double-armed on odd seeds to
  escape the governor's retry and exercise the bisection), and a
  persistent NaN poison on one job.

The acceptance invariants asserted per seed (docs/design.md §27):

(a) every job completed under chaos is BIT-IDENTICAL to the baseline —
    amplitudes, outcome/probability pairs, and measurement key state;
(b) no cross-tenant propagation: the only failed jobs are the poisoned
    ones (every other tenant's every job completes);
(c) the server reaches idle within a bounded step count (no deadlock or
    livelock) with empty queues and no resident banks;
(d) availability over non-poison jobs is 100%;
(e) observability (docs/design.md §30): every quarantine and failover
    incident in the chaos arm produced a parseable flight-recorder
    dump (valid JSON carrying the incident reason and the event ring);
(f) every completed chaos job's request trace reconstructs via
    ``SimServer.tracez`` as a COMPLETE well-nested span tree — admit,
    bank_join, at least one executed window, then complete, in that
    order — with the retry visible for every job the chaos killed and
    re-ran;
(g) warm pool one failover ahead (docs/design.md §31): the whole
    harness runs with QT_AOT_CACHE + prewarm enabled, so the chaos
    arm's deserialized executables must stay bit-identical to the
    baseline's compiled ones (covered by (a)); after the run the
    prewarm backlog must be drained, and when the chaos arm ends on a
    degraded mesh its post-failover device count must already be
    covered by a prewarmed warm-set variant — the shrunk-mesh
    executable the failover restored onto never waits on a fresh XLA
    compile, keeping MTTR flat.

Usage: python scripts/chaos_serve.py [--seeds 11,12,37]
Exits non-zero on any violated invariant; emits one JSON line per seed
plus an aggregate (chaos_availability_pct, failover MTTR) for
bench_suite config 15.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("QT_TOPOLOGY", "2x4")
# the window-stepped serving path suppresses the optimizer; keep both
# arms on the literal gate stream (bench_serve.py rationale)
os.environ.setdefault("QT_OPTIMIZER", "off")
# fast, deterministic backoff so retried jobs return within the bound
os.environ.setdefault("QT_RETRY_BASE_SECONDS", "0.001")
os.environ.setdefault("QT_RETRY_ATTEMPTS", "3")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import circuit as C  # noqa: E402
from quest_tpu import resilience as R  # noqa: E402
from quest_tpu import serve as S  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402

N = 4           # qubits per job (16 amps >= 8 devices sharded)
DEPTH = 3       # layers -> 2*N*DEPTH gates per circuit
WINDOW = 4
NUM_JOBS = 12
TENANTS = ("alice", "bob", "carol")
STEP_BOUND = 2000  # generous: windows + retries + backoff-wait steps


def _h(t):
    m = np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2)
    return C.Gate((t,), np.stack([m.real, m.imag]))


def _rz(t, theta):
    d = np.exp(1j * np.array([-theta / 2, theta / 2]))
    return C.Gate((t,), np.stack([np.diag(d.real), np.diag(d.imag)]))


def _circ(theta, depth=DEPTH, n=N):
    gates = []
    for d in range(depth):
        for q in range(n):
            gates.append(_h(q))
            gates.append(_rz(q, theta + 0.1 * q + d))
    return gates


def _trace(seed):
    """Deterministic arrival trace: (tenant, theta, priority, measure)
    per job, in submission order.  One shared circuit STRUCTURE (thetas
    differ) so arrivals coalesce into banks."""
    rng = np.random.RandomState(seed)
    jobs = []
    for i in range(NUM_JOBS):
        tenant = TENANTS[int(rng.randint(len(TENANTS)))]
        theta = float(rng.uniform(0.1, 2.8))
        prio = S.INTERACTIVE if rng.rand() < 0.25 else S.BATCH
        jobs.append((tenant, theta, prio, (0, N - 1)))
    return jobs


def _schedule(seed):
    """The seed-derived fault plan spec.  Every seed covers a transient
    bank fault, IO faults, infrastructure loss + heal, one poisoned job,
    and an OOM (double-armed on odd seeds so it escapes the governor's
    single retry and drives the bisection path)."""
    rng = np.random.RandomState(seed ^ 0x5EED)
    loss_kind = "host_loss" if seed % 2 == 0 else "shard_loss"
    loss_at = int(rng.randint(6, 10))
    heal_at = loss_at + int(rng.randint(4, 8))
    oom_at = int(rng.randint(2, 5))
    parts = [
        f"bank_fault@{int(rng.randint(2, 6))}",
        "io@2",
        f"{loss_kind}@{loss_at}",
        f"heal@{heal_at}",
        f"oom@{oom_at}",
    ]
    if seed % 2 == 1:
        parts.append(f"oom@{oom_at}")  # second arm: escape the OOM net
    poison_jid = int(rng.randint(0, NUM_JOBS))
    parts.append(f"poison_job@{poison_jid}")
    return ",".join(parts), {poison_jid}


def _load_dumps(paths):
    """Parse flight dumps BEFORE the server's close() removes its
    checkpoint root (the default dump dir lives under it)."""
    docs = []
    for path in paths:
        with open(path) as f:
            docs.append(json.load(f))
    return docs


def _run(env, jobs_spec, plan_spec):
    """Replay one trace; returns {jid: record} plus the server stats."""
    plan = R.FaultPlan(plan_spec) if plan_spec else None
    # high breaker threshold: ALL trace jobs share one structure
    # fingerprint, so an open breaker would reject innocent same-tenant
    # arrivals at submit() — the open/half-open/closed lifecycle is
    # pinned by tests/test_serve_resilience.py instead
    server = S.SimServer(env, window=WINDOW, max_batch=4, retries=4,
                         watchdog=1,
                         quarantine=(100, 3600.0), faults=plan,
                         prewarm=True)
    handles = []
    try:
        # submit in waves with steps between them: arrivals interleave
        # with execution (the continuous-batching admission point)
        for i, (tenant, theta, prio, measure) in enumerate(jobs_spec):
            handles.append(server.submit(
                _circ(theta), num_qubits=N, tenant=tenant,
                priority=prio, measure=measure))
            if i % 3 == 2:
                for _ in range(2):
                    server.step()
        steps = server.run_until_idle(max_steps=STEP_BOUND)
        stats = server.stats()
        warm = {
            "joined": server.prewarm_join(timeout=120.0),
            "healthz": {k: server._healthz()[k]
                        for k in ("warm_pool_depth", "prewarm_backlog")},
            "ndevs": sorted({spec["ndev"]
                             for spec in server.export_warmset()}),
        }
        out = {}
        for h in handles:
            out[h.id] = {
                "tenant": h.tenant,
                "state": h.state,
                "attempts": h.attempts,
                "amps": None if h.amps is None
                else np.asarray(h.amps).tobytes(),
                "outcomes": tuple(h.outcomes),
                "key": None if h.key_state is None
                else (np.asarray(h.key_state["key"]).tobytes(),
                      int(h.key_state["counter"])),
            }
        dumps = _load_dumps(server.flight_dumps)
        traces = {h.id: server.tracez(h) for h in handles}
        return out, stats, steps, plan, dumps, traces, warm
    finally:
        server.close()


def run_seed(seed):
    """One seed's A/B replay + invariant checks; returns the record."""
    R.seed_backoff_jitter([seed])
    env = qt.createQuESTEnv()
    qt.seedQuEST(env, [seed])
    base, base_stats, base_steps, _, _, _, _ = _run(env, _trace(seed), "")

    R.seed_backoff_jitter([seed])
    env = qt.createQuESTEnv()
    qt.seedQuEST(env, [seed])
    plan_spec, poisoned = _schedule(seed)
    chaos, stats, steps, plan, dumps, traces, warm = _run(
        env, _trace(seed), plan_spec)

    violations = []
    # (c) bounded idle: run_until_idle returned because nothing was
    # runnable, not because it hit the bound
    if steps >= STEP_BOUND:
        violations.append(f"step bound hit ({steps})")
    if stats["queued"] or stats["banks"]:
        violations.append(
            f"not idle: queued={stats['queued']} banks={stats['banks']}")
    # (b)+(d): only poisoned jobs may fail; everything else completes
    failed = {j for j, rec in chaos.items() if rec["state"] != "done"}
    if not failed <= poisoned:
        violations.append(
            f"non-poison failures: {sorted(failed - poisoned)}")
    non_poison = [j for j in chaos if j not in poisoned]
    completed = [j for j in non_poison if chaos[j]["state"] == "done"]
    availability = 100.0 * len(completed) / max(1, len(non_poison))
    if availability < 100.0:
        violations.append(f"availability {availability:.1f}% < 100%")
    # cross-tenant isolation, stated directly: every tenant that owns no
    # poisoned job has ALL of its jobs completed
    poison_tenants = {chaos[j]["tenant"] for j in poisoned if j in chaos}
    for j, rec in chaos.items():
        if rec["tenant"] not in poison_tenants \
                and rec["state"] != "done":
            violations.append(
                f"tenant {rec['tenant']} (no poison) lost job {j}")
    # (a) bit-identity of every completed job vs the fault-free run
    identical = 0
    for j in completed:
        b, c = base[j], chaos[j]
        if (b["amps"] == c["amps"] and b["outcomes"] == c["outcomes"]
                and b["key"] == c["key"]):
            identical += 1
        else:
            violations.append(f"job {j} diverged from fault-free run")
    # the plan must actually have fired (log covers each armed kind)
    fired = {e.split("@")[0] for e in plan.log}
    for kind in ("bank_fault", "heal", "poison_job"):
        if kind not in fired:
            violations.append(f"armed {kind} never fired (log={plan.log})")
    # (e) every quarantine/failover incident left a parseable flight
    # dump (already json.load-ed by _run; structure checked here)
    reasons = []
    for doc in dumps:
        if not (isinstance(doc, dict) and doc.get("reason")
                and isinstance(doc.get("events"), list)):
            violations.append(f"malformed flight dump: {doc!r:.120}")
            continue
        reasons.append(doc["reason"])
    for expected in ("quarantine", "failover"):
        if expected not in reasons:
            violations.append(
                f"no flight dump for the {expected} incident "
                f"(got {reasons})")
    # (f) every completed chaos job reconstructs as a complete,
    # well-nested span tree with the lifecycle in causal order and the
    # retry visible when chaos killed its bank
    for j in completed:
        tz = traces.get(j)
        if tz is None or not tz.get("complete") or tz.get("open"):
            violations.append(f"job {j}: trace incomplete ({tz!r:.120})")
            continue
        names = [e["name"] for e in tz["events"]]
        order = [names.index(n) for n in
                 ("serve.admit", "serve.bank_join", "serve.window",
                  "serve.complete")
                 if n in names]
        if len(order) != 4 or order != sorted(order):
            violations.append(f"job {j}: lifecycle out of order {names}")
        roots = tz.get("tree") or []
        if len(roots) != 1 or roots[0]["name"] != "job" \
                or not roots[0].get("children"):
            violations.append(
                f"job {j}: span tree not rooted at one 'job' span")
        if chaos[j]["attempts"] > 1 and "serve.retry" not in names:
            violations.append(
                f"job {j}: {chaos[j]['attempts']} attempts but no "
                f"serve.retry in its trace")
    # (g) warm pool one failover ahead: backlog drained, and a degraded
    # end state was already covered by a prewarmed shrunk-mesh variant
    from quest_tpu import aotcache as A
    if A.enabled():
        if not warm["joined"] or warm["healthz"]["prewarm_backlog"]:
            violations.append(
                f"prewarm backlog not drained ({warm})")
        if warm["healthz"]["warm_pool_depth"] < 1:
            violations.append("warm pool empty after chaos run")
        if stats["degraded"] and stats["devices"] not in warm["ndevs"]:
            violations.append(
                f"degraded mesh ({stats['devices']} devices) has no "
                f"prewarmed variant (warmset ndevs={warm['ndevs']}) — "
                f"failover MTTR would pay a fresh compile")

    return {
        "seed": seed,
        "plan": plan_spec,
        "warm_pool": warm,
        "violations": violations,
        "availability_pct": availability,
        "completed": len(completed),
        "non_poison": len(non_poison),
        "bit_identical": identical,
        "quarantined": sorted(failed & poisoned),
        "steps": steps,
        "baseline_steps": base_steps,
        "devices_after": stats["devices"],
        "degraded_after": stats["degraded"],
        "flight_dump_reasons": reasons,
        "traces_complete": sum(
            1 for j in completed
            if traces.get(j) and traces[j].get("complete")),
    }


def run(seeds=(11, 12, 37)):
    """Entry point shared with bench_suite config 15."""
    import shutil
    import tempfile

    from quest_tpu import aotcache as A

    t0 = time.perf_counter()
    # the whole harness runs against one AOT cache directory with the
    # serve warm pools on (invariant (g)): the baseline arm compiles
    # and persists, the chaos arm deserializes — so bit-identity (a)
    # doubles as the cached-executable determinism pin, and every
    # failover lands on a prewarmed shrunk-mesh variant
    own_cache = os.environ.get(A._DIR_ENV) is None
    if own_cache:
        os.environ[A._DIR_ENV] = tempfile.mkdtemp(prefix="qt_chaos_aot_")
    records = []
    ok = True
    try:
        for seed in seeds:
            rec = run_seed(int(seed))
            records.append(rec)
            ok = ok and not rec["violations"]
            print(json.dumps(rec))
        aot = A.stats()
    finally:
        if own_cache:
            shutil.rmtree(os.environ.pop(A._DIR_ENV), ignore_errors=True)
    mttr = T.gauge_max("serve_failover_mttr_seconds")
    agg = {
        "seeds": list(map(int, seeds)),
        "ok": ok,
        "availability_pct": min(r["availability_pct"] for r in records),
        "bit_identical": sum(r["bit_identical"] for r in records),
        "completed": sum(r["completed"] for r in records),
        "failover_mttr_seconds": None if mttr is None else float(mttr),
        "failovers": int(T.counter_total("serve_failovers_total")),
        "heals": int(T.counter_total("serve_heals_total")),
        "bank_retries": int(T.counter_total("serve_bank_retries_total")),
        "quarantined": int(
            T.counter_total("serve_jobs_quarantined_total")),
        "aot_cache": {k: aot[k] for k in
                      ("hits", "misses", "puts", "errors")},
        "seconds": round(time.perf_counter() - t0, 3),
    }
    print(json.dumps({"aggregate": agg}))
    return agg


def main():
    raw = "11,12,37"
    if "--seeds" in sys.argv:
        raw = sys.argv[sys.argv.index("--seeds") + 1]
    agg = run(tuple(int(s) for s in raw.split(",")))
    if not agg["ok"]:
        print("chaos_serve: INVARIANT VIOLATED", file=sys.stderr)
        return 1
    print(f"chaos_serve: OK — availability={agg['availability_pct']:.1f}% "
          f"bit_identical={agg['bit_identical']} "
          f"failovers={agg['failovers']} heals={agg['heals']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
