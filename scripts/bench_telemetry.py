"""Telemetry overhead guard: enabled-mode accounting on a 1k-gate fusion
drain must cost < 5% over QT_TELEMETRY=off (ISSUE 4 acceptance — the
off path must also be statistically indistinguishable from pre-PR
dispatch latency, which this A/B bounds from above: the off path is one
module-global int test per hook).  The SAME budget now also gates
``trace`` mode (§30): Chrome-event capture plus per-group attribution
sync must stay under 5% on this workload too.

The workload is the instrumentation-heaviest shape: 1000 dense gates
issued through the imperative API inside ONE gateFusion drain (each
gate call pays a dispatch-family counter, the drain pays the plan-cache
/ window / span hooks), then a state read.  Identical gate matrices
every repetition, so the plan cache and compiled-executor cache are
warm and the measured time is dominated by exactly the host dispatch
loop telemetry instruments.

Usage: python scripts/bench_telemetry.py [--n 12] [--gates 1000]
       [--reps 5] [--budget 0.05] [--no-check]
Exits non-zero when the overhead exceeds the budget (unless --no-check).
"""

import gc
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import telemetry  # noqa: E402


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def main():
    n = _arg("--n", 12)
    gates = _arg("--gates", 1000)
    reps = _arg("--reps", 7)
    budget = _arg("--budget", 0.05, float)
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(17)
    g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    u, _ = np.linalg.qr(g)
    cx = np.array([[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]],
                  dtype=complex)

    def run():
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            k = 0
            while k < gates:
                for t in range(n):
                    qt.unitary(q, t, u)
                    k += 1
                for t in range(n - 1):
                    qt.twoQubitUnitary(q, t, t + 1, cx)
                    k += 1
        return qt.calcTotalProb(q)

    modes = ("off", "on", "trace")
    for mode in modes:
        telemetry.configure(mode)
        run()  # warm caches under every mode (plan cache, jit executor)
    # interleave the modes WITHIN each rep and ROTATE the order each rep
    # (off/on/trace, on/trace/off, ...) so neither slow host drift nor
    # PERIODIC noise (hypervisor steal with a period near the rep cycle)
    # can land on one mode rep after rep; the per-mode best-of then
    # compares like with like
    best = {m: math.inf for m in modes}
    gc.collect()
    gc.disable()  # a collection pause lands on whichever mode triggers
    try:          # it — freeze the collector so none does
        for rep in range(reps):
            for i in range(len(modes)):
                mode = modes[(rep + i) % len(modes)]
                telemetry.configure(mode)
                t0 = time.perf_counter()
                run()
                best[mode] = min(best[mode], time.perf_counter() - t0)
    finally:
        gc.enable()
    telemetry.configure()  # back to the env-var default
    telemetry.reset()      # drop the trace buffer this bench filled
    off_best, on_s, trace_s = best["off"], best["on"], best["trace"]
    overhead = on_s / off_best - 1.0
    trace_overhead = trace_s / off_best - 1.0
    rec = {
        "bench": "telemetry_overhead_1k_gate_drain",
        "n": n,
        "gates": gates,
        "backend": jax.default_backend(),
        "off_seconds": round(off_best, 5),
        "on_seconds": round(on_s, 5),
        "trace_seconds": round(trace_s, 5),
        "overhead": round(overhead, 4),
        "trace_overhead": round(trace_overhead, 4),
        "budget": budget,
        "ok": overhead <= budget and trace_overhead <= budget,
    }
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    if overhead > budget or trace_overhead > budget:
        print(f"FAIL: telemetry overhead on={overhead:.1%} "
              f"trace={trace_overhead:.1%} exceeds the {budget:.0%} "
              f"budget", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
