"""Dependency-free line coverage for quest_tpu via sys.monitoring (PEP 669).

The environment bakes no coverage.py, so this implements the same
line-coverage measurement with the CPython 3.12 monitoring API: LINE
events restricted to files under quest_tpu/, each line DISABLEd after its
first hit (near-zero steady-state overhead), executable-line sets taken
from the compiled code objects' co_lines tables.

Usage: python scripts/coverage_run.py [pytest args...]
Writes a per-file table + total to stdout and coverage.json.

Mirrors the role of the reference's coverage workflow
(.github/workflows/coverage.yml + QUEST_ENABLE_COVERAGE, lcov/codecov).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "quest_tpu")
sys.path.insert(0, REPO)

covered: dict = {}   # filename -> set of line numbers

TOOL = 3  # sys.monitoring tool id (coverage slot is 1; use a free one)


def _on_line(code, line):
    # record every first hit and filter at report time: the package may be
    # imported under a different path spelling (sys.path vs cwd), so a
    # prefix test here would silently drop everything
    covered.setdefault(code.co_filename, set()).add(line)
    return sys.monitoring.DISABLE


def executable_lines(path):
    """All line numbers carrying code, from the compiled module's code
    objects (recursively through co_consts)."""
    with open(path) as f:
        src = f.read()
    try:
        root = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines = set()
    stack = [root]
    while stack:
        code = stack.pop()
        for _, _, ln in code.co_lines():
            if ln is not None and ln > 0:
                lines.add(ln)
        for const in code.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def main():
    sys.monitoring.use_tool_id(TOOL, "quest_tpu-coverage")
    sys.monitoring.register_callback(TOOL, sys.monitoring.events.LINE, _on_line)
    sys.monitoring.set_events(TOOL, sys.monitoring.events.LINE)

    import pytest

    args = sys.argv[1:] or ["tests/", "-q"]
    rc = pytest.main(args)

    sys.monitoring.set_events(TOOL, 0)
    sys.monitoring.free_tool_id(TOOL)

    by_real = {}
    for fn, lines in covered.items():
        by_real.setdefault(os.path.realpath(fn), set()).update(lines)

    rows = []
    tot_exec = tot_cov = 0
    for dirpath, _, files in os.walk(PKG):
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            ex = executable_lines(path)
            cov = by_real.get(os.path.realpath(path), set()) & ex
            if not ex:
                continue
            rows.append((os.path.relpath(path, REPO), len(cov), len(ex)))
            tot_exec += len(ex)
            tot_cov += len(cov)

    print(f"\n{'file':48s} {'lines':>7s} {'cov':>6s} {'%':>6s}")
    for rel, c, e in rows:
        print(f"{rel:48s} {e:7d} {c:6d} {100.0 * c / e:5.1f}%")
    pct = 100.0 * tot_cov / tot_exec if tot_exec else 0.0
    print(f"{'TOTAL':48s} {tot_exec:7d} {tot_cov:6d} {pct:5.1f}%")

    with open(os.path.join(REPO, "coverage.json"), "w") as f:
        json.dump(
            {
                "total_pct": round(pct, 1),
                "covered": tot_cov,
                "executable": tot_exec,
                "files": {r: {"covered": c, "executable": e}
                          for r, c, e in rows},
            },
            f, indent=1,
        )
    print("wrote coverage.json")
    return rc


if __name__ == "__main__":
    sys.exit(main())
