#!/usr/bin/env python3
"""Cold-start elimination bench + gate (docs/design.md §31).

Measures what the persistent AOT executable cache actually buys: the
first-request latency of a FRESH PROCESS.  The parent launches the same
child workload twice against one QT_AOT_CACHE directory:

  run 1 (uncached)  empty cache — the child pays the full XLA compile
                    on its first drain, and persists the executable;
  run 2 (cached)    fresh process, warm disk — the first drain must
                    deserialize instead of compiling.

Each child reports its first-drain wall time, its steady-state drain
time (same program structure, in-memory executor tier), its aot_cache_*
counters, and an amplitude checksum.  The parent emits a bench_suite
style record with ``coldstart_speedup_x = uncached.first /
cached.first`` — higher is better; bench_regress treats it as a rate.

``--check`` turns the run into the verify-coldstart gate:

  - the cached child must HIT the disk tier (hits >= 1, puts == 0 —
    a put would mean it silently recompiled);
  - cached first-request <= 2x its own steady-state (plus a small
    absolute slack for host timer noise) — cold start eliminated;
  - cached first-request strictly below the uncached one;
  - both children's amplitude checksums bit-identical — the
    deserialized executable computes exactly what the compiled one did.

Usage:
  python scripts/bench_coldstart.py            # bench, print record
  python scripts/bench_coldstart.py --check    # gate, exit 1 on fail
  python scripts/bench_coldstart.py --child    # (internal) one process
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# workload: sharded (8-way) 10-qubit circuit, deep enough that XLA
# compilation dominates a cold first drain on every host we run on
N = 10
DEPTH = 6
STEADY_REPS = 3


def _drain(qt, env, theta):
    import numpy as np

    q = qt.createQureg(N, env)
    qt.startGateFusion(q)
    for d in range(DEPTH):
        for k in range(N):
            qt.hadamard(q, k)
            qt.rotateZ(q, k, theta + 0.1 * k + d)
        for k in range(N - 1):
            qt.controlledNot(q, k, k + 1)
    qt.stopGateFusion(q)
    return np.asarray(q.amps)


def child() -> None:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import quest_tpu as qt
    from quest_tpu import aotcache as A

    qt.set_precision(2)
    env = qt.createQuESTEnv()
    t0 = time.perf_counter()
    amps = _drain(qt, env, 0.3)
    first = time.perf_counter() - t0
    steady = float("inf")
    for _ in range(STEADY_REPS):
        t0 = time.perf_counter()
        _drain(qt, env, 0.3)
        steady = min(steady, time.perf_counter() - t0)
    print("CHILD " + json.dumps({
        "first_s": round(first, 4),
        "steady_s": round(steady, 4),
        "aot": A.stats(),
        "checksum": repr(float(np.sum(
            amps * amps * np.arange(amps.size).reshape(amps.shape)))),
    }), flush=True)


def _run_child(cache_dir: str) -> dict:
    env = dict(os.environ,
               QT_AOT_CACHE=cache_dir,
               PYTHONPATH=os.pathsep.join([REPO] + sys.path))
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        capture_output=True, text=True, timeout=900, env=env)
    if out.returncode != 0:
        sys.stderr.write(out.stderr)
        raise SystemExit(f"coldstart child failed ({out.returncode})")
    for line in out.stdout.splitlines():
        if line.startswith("CHILD "):
            return json.loads(line[len("CHILD "):])
    raise SystemExit("coldstart child emitted no report:\n" + out.stdout)


def run(check: bool = False) -> dict:
    cache_dir = tempfile.mkdtemp(prefix="qt_coldstart_aot_")
    t0 = time.perf_counter()
    try:
        uncached = _run_child(cache_dir)
        cached = _run_child(cache_dir)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    speedup = uncached["first_s"] / max(cached["first_s"], 1e-9)
    rec = {
        "config": "coldstart",
        "metric": "coldstart_speedup_x",
        "value": round(speedup, 2),
        "unit": "x_first_request",
        "seconds": round(time.perf_counter() - t0, 3),
        "uncached_first_s": uncached["first_s"],
        "cached_first_s": cached["first_s"],
        "cached_steady_s": cached["steady_s"],
        "uncached_aot": uncached["aot"],
        "cached_aot": cached["aot"],
        "bit_identical": uncached["checksum"] == cached["checksum"],
    }
    print(json.dumps(rec), flush=True)
    if check:
        fails = []
        if cached["aot"]["hits"] < 1:
            fails.append("cached child never hit the disk tier")
        if cached["aot"]["puts"] != 0:
            fails.append("cached child recompiled (puts != 0)")
        if uncached["aot"]["puts"] < 1:
            fails.append("uncached child persisted nothing")
        # cold start eliminated: first request within 2x steady state
        # plus a 1s absolute allowance for the one-time executable
        # deserialization — on the CPU CI arm a steady drain is ~50ms
        # while deserialize_and_load of the persisted executable is
        # ~0.5s, so a pure-relative bound would gate on deserialization
        # speed rather than on compile avoidance.  A regression that
        # reintroduces the compile (3s+ here) still fails this bound.
        if cached["first_s"] > 2.0 * cached["steady_s"] + 1.0:
            fails.append(
                f"cached first request {cached['first_s']}s exceeds "
                f"2x steady state {cached['steady_s']}s + deserialize "
                f"allowance")
        if cached["first_s"] >= 0.5 * uncached["first_s"]:
            fails.append("cached first request not ≫ faster than "
                         "uncached (compile not avoided?)")
        if not rec["bit_identical"]:
            fails.append("cached run not bit-identical to compiled run")
        if fails:
            for f in fails:
                print("FAIL coldstart:", f, file=sys.stderr)
            raise SystemExit(1)
        print("verify-coldstart OK: first request "
              f"{uncached['first_s']}s cold -> {cached['first_s']}s "
              f"warm ({rec['value']}x), steady {cached['steady_s']}s")
    return rec


def main() -> None:
    if "--child" in sys.argv:
        child()
        return
    run(check="--check" in sys.argv)


if __name__ == "__main__":
    main()
