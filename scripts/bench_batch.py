"""Batched-vs-looped A/B (ISSUE round-11 acceptance): running B copies
of one circuit as a single (B, 2, 2^n) BatchedQureg bank must beat B
independent scalar runs by >= 4x circuits/sec at B=16 on the dryrun
mesh.

The workload is a depth-D layered ansatz (per-qubit 1q unitaries + a
CNOT ladder) issued through the public camelCase API, so both arms pay
the same capture path; the batched arm drains ONE vmapped window
program where the looped arm drains B scalar programs.  Both arms warm
their compile caches before timing — the measured quantity is steady
state throughput (circuits/sec) and per-circuit latency, which is what
an ensemble/trajectory workload sees.

Usage: python scripts/bench_batch.py [--n 10] [--depth 4] [--reps 3]
       [--batches 1,4,16,64] [--speedup-at 16] [--budget 4.0]
       [--no-check]
Exits non-zero when the speedup budget fails (unless --no-check).
"""

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _circuit(q, n, depth, mats):
    for d in range(depth):
        for t in range(n):
            qt.unitary(q, t, mats[d * n + t])
        for t in range(n - 1):
            qt.controlledNot(q, t, t + 1)


def run_ab(n, depth, batches, reps):
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(23)
    mats = []
    for _ in range(depth * max(1, n)):
        g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
        u, _r = np.linalg.qr(g)
        mats.append(u)

    def looped(B):
        for _ in range(B):
            q = qt.createQureg(n, env)
            with qt.gateFusion(q):
                _circuit(q, n, depth, mats)
            q.amps.block_until_ready()

    def batched(B):
        bq = qt.createBatchedQureg(n, env, B)
        _circuit(bq, n, depth, mats)
        bq.amps.block_until_ready()

    def best_of(fn, B):
        fn(B)  # warm the plan + executor caches for this batch shape
        best = math.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(B)
            best = min(best, time.perf_counter() - t0)
        return best

    rows = []
    for B in batches:
        loop_s = best_of(looped, B)
        bank_s = best_of(batched, B)
        rows.append({
            "batch": B,
            "looped_seconds": round(loop_s, 5),
            "batched_seconds": round(bank_s, 5),
            "looped_circuits_per_sec": round(B / loop_s, 2),
            "batched_circuits_per_sec": round(B / bank_s, 2),
            "batched_per_circuit_ms": round(1e3 * bank_s / B, 3),
            "speedup": round(loop_s / bank_s, 2),
        })
    return env, rows


def main():
    n = _arg("--n", 10)
    depth = _arg("--depth", 4)
    reps = _arg("--reps", 3)
    batches = _arg("--batches", [1, 4, 16, 64],
                   lambda s: [int(x) for x in s.split(",")])
    speedup_at = _arg("--speedup-at", 16)
    budget = _arg("--budget", 4.0, float)

    env, rows = run_ab(n, depth, batches, reps)
    gate_count = depth * (2 * n - 1)
    rec = {
        "bench": "batched_vs_looped",
        "n": n,
        "depth": depth,
        "gates_per_circuit": gate_count,
        "backend": jax.default_backend(),
        "devices": env.num_devices,
        "results": rows,
    }
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    at = next((r for r in rows if r["batch"] == speedup_at), None)
    if at is None:
        print(f"FAIL: batch {speedup_at} not in the sweep", file=sys.stderr)
        return 1
    if at["speedup"] < budget:
        print(f"FAIL: batched speedup {at['speedup']:.2f}x at batch "
              f"{speedup_at} is below the {budget:.1f}x budget",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
