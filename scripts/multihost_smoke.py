"""Multi-HOST (multi-process) validation of the distributed backend.

The reference scales across nodes with MPI (QuEST_cpu_distributed.c);
quest_tpu's analogue is jax.distributed + the shard_map/ppermute kernel
layer riding whatever links connect the processes (ICI within a slice,
DCN/TCP across).  This script actually runs TWO OS PROCESSES (gloo
collectives over TCP — the DCN stand-in), each owning half of an
8-device mesh, and drives the explicit distributed kernels across the
process boundary:

  * total_prob_sharded      — psum spanning both processes
  * apply_matrix_1q_sharded — ppermute exchange on the top (cross-
                              process) qubit; H twice restores the state
  * fused_qft_sharded       — QFT|0..0> = uniform state: every local
                              shard must read 2^(-n/2) everywhere
  * trotter_scan_sharded    — a term stream then its exact inverse
                              restores the state
  * expec_pauli_sum_scan_sharded — known <Z-string> values on |0..0>

Each process checks its OWN addressable shards (no full-state gather —
the same discipline the big-state paths follow).

Before the multi-process arm, a SINGLE-HOST smoke always runs: the
multi-tenant serve loop (quest_tpu.serve.SimServer — continuous
batching, preempt-to-checkpoint, resume) on the forced-8-device CPU
mesh, so the serving layer's scheduler is exercised on a sharded mesh
even where no multi-host runtime exists.  When the two-process arm
cannot initialize (no gloo/distributed runtime in the environment), the
script emits a STRUCTURED skip record ({"multihost": {"status":
"skip", ...}}) and exits 0 — a missing runtime is not a pass and not a
failure, and downstream log scrapers can tell the three apart.

Exit code 0 = single-host smoke passed AND the multi-process arm either
passed or was skipped-with-reason.  Run: python scripts/multihost_smoke.py
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVE_WORKER = r"""
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import quest_tpu as qt
from quest_tpu import circuit as C
from quest_tpu import serve as S
from quest_tpu import telemetry as T

env = qt.createQuESTEnv()
assert env.num_devices == 8, env.num_devices
n = 6
rng = np.random.default_rng(3)

def circ(depth):
    gates = []
    for _ in range(depth):
        for t in range(n):
            g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal(
                (2, 2))
            u, _r = np.linalg.qr(g)
            gates.append(C.Gate((t,), np.stack([u.real, u.imag])))
    return gates

T.reset()
srv = S.SimServer(env, window=4, max_batch=8)
try:
    batch = [srv.submit(circ(4), num_qubits=n, seed=i)
             for i in range(6)]
    for _ in range(2):
        srv.step()           # start the bank, run its first windows
    live = srv.submit(circ(1), num_qubits=n, priority=S.INTERACTIVE,
                      seed=99)
    srv.run_until_idle(max_steps=500)
    assert all(j.state == S.DONE for j in batch + [live]), \
        [j.state for j in batch + [live]]
    norms = [float(np.sum(np.asarray(j.amps) ** 2)) for j in batch]
    assert all(abs(x - 1.0) < 1e-5 for x in norms), norms  # f32 default
    pre = T.counter_total("preemptions_total")
    res = T.counter_total("serve_resumes_total")
    assert pre >= 1 and res >= 1, (pre, res)
    print(json.dumps({"serve_smoke": {
        "status": "pass", "devices": env.num_devices,
        "jobs": len(batch) + 1,
        "preemptions": pre, "resumes": res,
        "windows": T.counter_total("serve_windows_total")}}),
        flush=True)
finally:
    srv.close()
"""

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
pid = int(sys.argv[1])
try:
    jax.distributed.initialize(coordinator_address="127.0.0.1:%(port)d",
                               num_processes=2, process_id=pid)
except Exception as e:  # noqa: BLE001 - init failure IS the signal
    # no multi-host runtime here: report it distinctly so the driver
    # emits a structured skip instead of a silent pass or a bogus FAIL
    print(f"[p{pid}] INIT UNAVAILABLE: {type(e).__name__}: {e}",
          flush=True)
    sys.exit(77)
print(f"[p{pid}] INIT OK", flush=True)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quest_tpu.env import AMP_AXIS
from quest_tpu.parallel import dist as PAR
from quest_tpu.ops import paulis as PA

devs = jax.devices()
assert len(devs) == 8, devs
mesh = Mesh(np.array(devs), (AMP_AXIS,))
n = 12
dim = 1 << n
sh = NamedSharding(mesh, P(None, AMP_AXIS))

def make_state(vec2):
    # global (2, dim) array from a full host vector: each process
    # materialises only its addressable shards
    return jax.make_array_from_callback(
        (2, dim), sh, lambda idx: vec2[idx])

def local_shards(g):
    return [(s.index, np.asarray(s.data)) for s in g.addressable_shards]

def check(name, ok):
    print(f"[p{pid}] {name}: {'ok' if ok else 'FAIL'}", flush=True)
    if not ok:
        sys.exit(1)

# capability probe: some jaxlib builds accept distributed.initialize
# but cannot actually run cross-process computations on this backend —
# that is a missing runtime (structured skip), not a failure
try:
    PAR.total_prob_sharded(make_state(np.zeros((2, dim))), mesh=mesh)
except Exception as e:
    msg = str(e)
    if "implemented" in msg or "UNIMPLEMENTED" in msg:
        print(f"[p{pid}] INIT UNAVAILABLE: cross-process computations "
              f"unsupported on this backend ({type(e).__name__})",
              flush=True)
        sys.exit(77)
    raise

rng = np.random.default_rng(0)   # same seed on both processes
v = rng.standard_normal((2, dim))
v /= np.sqrt((v ** 2).sum())

# -- psum across the process boundary
g = make_state(v)
tp = PAR.total_prob_sharded(g, mesh=mesh)
check("total_prob psum", abs(float(tp) - 1.0) < 1e-12)

# -- ppermute exchange on the top qubit (owned by opposite processes)
h = np.array([[[1, 1], [1, -1]], [[0, 0], [0, 0]]]) / np.sqrt(2)
g = make_state(v)
for _ in range(2):
    g = PAR.apply_matrix_1q_sharded(
        g, jnp.asarray(h), mesh=mesh, num_qubits=n, target=n - 1)
before = {tuple(map(str, i)): d for i, d in
          [(i, v[i]) for i, _ in local_shards(make_state(v))]}
err = max(np.abs(d - v[i]).max() for i, d in local_shards(g))
check("H^2 on cross-process qubit restores state", err < 1e-12)

# -- QFT of |0..0> -> uniform amplitudes on every shard
z = np.zeros((2, dim)); z[0, 0] = 1.0
g = PAR.fused_qft_sharded(make_state(z), mesh=mesh, num_qubits=n)
expect = 2.0 ** (-n / 2)
err = 0.0
for i, d in local_shards(g):
    err = max(err, np.abs(d[0] - expect).max(), np.abs(d[1]).max())
check("fused QFT -> uniform state", err < 1e-12)

# -- Trotter stream then its inverse restores the state
T = 6
codes = rng.integers(0, 4, size=(T, n)).astype(np.int32)
angles = rng.normal(size=T)
g = make_state(v)
g = PAR.trotter_scan_sharded(g, jnp.asarray(codes), jnp.asarray(angles),
                             mesh=mesh, num_qubits=n, rep_qubits=n)
g = PAR.trotter_scan_sharded(g, jnp.asarray(codes[::-1].copy()),
                             jnp.asarray(-angles[::-1].copy()),
                             mesh=mesh, num_qubits=n, rep_qubits=n)
err = max(np.abs(d - v[i]).max() for i, d in local_shards(g))
check("trotter + inverse restores state", err < 1e-10)

# -- expectation of Z-strings on |0..0>: every Z/I term contributes its
#    coefficient; an X/Y-containing term contributes 0
codes_e = np.zeros((3, n), np.int32)
codes_e[1, 0] = 3; codes_e[1, 5] = 3        # Z0 Z5
codes_e[2, 2] = 1                           # X2 -> 0
coeffs = np.array([0.5, 0.25, 10.0])
e = PAR.expec_pauli_sum_scan_sharded(
    make_state(z), jnp.asarray(codes_e), jnp.asarray(coeffs),
    mesh=mesh, num_qubits=n)
check("expec Z-strings across processes", abs(float(e) - 0.75) < 1e-12)

# -- the PUBLIC API end to end across processes: env discovery, a
#    sharded register, gates, reductions, and the seeded measurement
#    stream (same outcome on every process — the reference's broadcast-
#    seed semantics, QuEST_cpu_distributed.c:1384-1395)
import quest_tpu as qt
env = qt.createQuESTEnv()
check("createQuESTEnv spans processes", env.num_ranks == 8)
q = qt.createQureg(n, env)
qt.hadamard(q, 0)
for t in range(1, n):
    qt.controlledNot(q, t - 1, t)
check("API GHZ prob",
      abs(qt.calcProbOfOutcome(q, n - 1, 0) - 0.5) < 1e-6)  # f32 register
qt.seedQuEST(env, [42])
o1 = qt.measure(q, n - 1)
outs, probs = qt.measureSequence(q, range(4))
check("API measure + sequence ran", o1 in (0, 1) and len(outs) == 4)
q2 = qt.createQureg(n, env)
qt.applyFullQFT(q2)   # |0..0> -> uniform via the sharded fused QFT
err = 0.0
for i, d in local_shards(q2.amps):
    err = max(err, np.abs(d[0] - expect).max(), np.abs(d[1]).max())
check("API applyFullQFT (sharded route)", err < 1e-6)
h3 = qt.createPauliHamil(n, 3)
qt.initPauliHamil(h3, coeffs, codes_e)
q3 = qt.createQureg(n, env)
check("API calcExpecPauliHamil",
      abs(qt.calcExpecPauliHamil(q3, h3) - 0.75) < 1e-6)
qt.applyTrotterCircuit(q3, h3, 0.3, 1, 1)
check("API applyTrotterCircuit totalProb",
      abs(qt.calcTotalProb(q3) - 1.0) < 1e-6)

print(f"[p{pid}] ALL OK", flush=True)
"""


def run_serve_smoke():
    """The single-host arm: serve loop on the forced-8-device mesh."""
    path = "/tmp/qt_serve_smoke_worker.py"
    with open(path, "w") as f:
        f.write(SERVE_WORKER % {"repo": REPO})
    p = subprocess.run([sys.executable, path], stdout=subprocess.PIPE,
                       stderr=subprocess.STDOUT, text=True, timeout=600)
    print(p.stdout)
    return p.returncode == 0


def run_multihost():
    """The two-process arm.  Returns 'pass', 'fail', or a skip reason
    string when the distributed runtime is unavailable."""
    port = 12431
    src = WORKER % {"repo": REPO, "port": port}
    path = "/tmp/qt_multihost_worker.py"
    with open(path, "w") as f:
        f.write(src)
    procs = [subprocess.Popen([sys.executable, path, str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs, codes = [], []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        print(out)
        outs.append(out)
        codes.append(p.returncode)
    if all(c == 0 for c in codes):
        return "pass"
    if any(c == 77 or "INIT UNAVAILABLE" in o
           for c, o in zip(codes, outs)):
        reason = next((line for o in outs for line in o.splitlines()
                       if "INIT UNAVAILABLE" in line),
                      "jax.distributed initialize failed")
        return reason
    return "fail"


def main():
    serve_ok = run_serve_smoke()
    try:
        mh = run_multihost()
    except Exception as e:  # noqa: BLE001 - spawn/timeout = no runtime
        mh = f"spawn failed: {type(e).__name__}: {e}"
    if mh == "pass":
        print(json.dumps({"multihost": {"status": "pass"}}), flush=True)
    elif mh == "fail":
        print(json.dumps({"multihost": {"status": "fail"}}), flush=True)
    else:
        # structured skip: visible in logs, distinguishable from both a
        # pass and a silent no-op
        print(json.dumps({"multihost": {"status": "skip",
                                        "reason": mh}}), flush=True)
    ok = serve_ok and mh != "fail"
    print("MULTIHOST SMOKE:", "PASS" if ok else "FAIL")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
