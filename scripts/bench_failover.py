"""MTTR of a degraded-mesh failover on the dryrun mesh.

Runs the elastic-recovery brickwork workload through
resilience.run_resumable with an injected ``shard_loss`` mid-run and
reports the mean-time-to-recovery with its phase breakdown — the four
gauges the failover path stamps (resilience._failover /
_execute_windows):

  detect    window start -> the guard's ShardLossError reaching the
            driver (includes the retry budget the guard burned first)
  rollback  picking + reading the last-good generation, resharded onto
            the surviving mesh (one elastic restore does both IOs)
  reshard   rebinding the register to the shrunken env + restored state
  resume    the first post-failover window completing on the new mesh
            (dominated by recompiling the window plans for the new
            shard split)

Also cross-checks the recovered state: the post-failover amplitudes must
be bitwise those of an uninterrupted run on the shrunken mesh.

Usage: python scripts/bench_failover.py [--n 10] [--depth 32] [--every 16]
                                        [--window 2] [--reps 3]
"""

import json
import os
import shutil
import sys
import tempfile
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("QT_RETRY_BASE_SECONDS", "0.001")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import circuit as C  # noqa: E402
from quest_tpu import resilience as R  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402

PHASES = ("detect", "rollback", "reshard", "resume")


def _arg(flag, default):
    return int(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _gates(n, depth):
    rng = np.random.default_rng(11)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    soa = np.stack([u.real, u.imag])
    gates = []
    for _ in range(depth):
        gates.append(C.Gate((0, 1), soa))          # shard-local
        gates.append(C.Gate((n - 2, n - 1), soa))  # sharded targets
    return gates


def _phase_gauges():
    return {p: float(T._GAUGES.get((f"failover_{p}_seconds", ()), 0.0))
            for p in PHASES}


def main():
    n = _arg("--n", 10)
    depth = _arg("--depth", 32)
    every = _arg("--every", 16)
    window = _arg("--window", 2)
    reps = _arg("--reps", 3)
    T.configure("on")
    env = qt.createQuESTEnv()
    gates = _gates(n, depth)

    # reference: uninterrupted run on the mesh the failover shrinks TO
    target = qt.createQuESTEnv(num_devices=env.num_devices // 2)
    qt.seedQuEST(target, [3])
    q_ref = qt.createQureg(n, target)
    d_ref = tempfile.mkdtemp(prefix="qt_bench_fo_ref_")
    try:
        qt.run_resumable(q_ref, gates, d_ref, every=every)
        ref = np.asarray(q_ref.amps)
    finally:
        shutil.rmtree(d_ref, ignore_errors=True)

    samples = []
    bitwise_ok = True
    total_s = []
    for rep in range(reps):
        R.DEGRADATIONS.pop(
            f"mesh_failover_{env.num_devices}to{target.num_devices}", None)
        qt.seedQuEST(env, [3])
        q = qt.createQureg(n, env)
        d = tempfile.mkdtemp(prefix="qt_bench_fo_")
        t0 = time.perf_counter()
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                qt.run_resumable(q, gates, d, every=every,
                                 faults=qt.FaultPlan(f"shard_loss@{window}"))
            total_s.append(time.perf_counter() - t0)
            bitwise_ok &= bool(np.array_equal(np.asarray(q.amps), ref))
            samples.append(_phase_gauges())
        finally:
            shutil.rmtree(d, ignore_errors=True)

    mttr = [sum(s.values()) for s in samples]
    out = {
        "metric": f"{n}q depth-{depth} shard-loss failover MTTR "
                  f"(every={every}, window={window})",
        "reps": reps,
        "devices_before": env.num_devices,
        "devices_after": target.num_devices,
        "recovered_bitwise_vs_target_mesh": bitwise_ok,
        "mttr_seconds_best": round(min(mttr), 4),
        "mttr_seconds_median": round(sorted(mttr)[len(mttr) // 2], 4),
        "phases_best": {p: round(min(s[p] for s in samples), 4)
                        for p in PHASES},
        "phases_median": {
            p: round(sorted(s[p] for s in samples)[len(samples) // 2], 4)
            for p in PHASES},
        "run_seconds_median": round(sorted(total_s)[len(total_s) // 2], 4),
        "failovers_total": int(T.counter_total("failovers_total")),
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
