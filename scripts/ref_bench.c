/* Reference-QuEST baseline driver for BASELINE.md / bench.py vs_baseline.
 *
 * Replicates the bench.py workload shape exactly: N-qubit state-vector,
 * DEPTH layers of (N single-qubit unitaries + brick-wall CNOT ladder),
 * then calcProbOfOutcome — run against the UNMODIFIED reference QuEST
 * sources (/root/reference), CPU multithreaded backend, double precision.
 *
 * Build (see scripts/build_ref_bench.sh):
 *   gcc -O2 -fopenmp -std=c99 -I$REF/QuEST/include -I$REF/QuEST/src \
 *       scripts/ref_bench.c $REF/QuEST/src/QuEST.c ... -lm -o .refbuild/ref_bench
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#include "QuEST.h"

static double now_sec(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

int main(int argc, char** argv) {
    int n = argc > 1 ? atoi(argv[1]) : 26;
    int depth = argc > 2 ? atoi(argv[2]) : 20;

    QuESTEnv env = createQuESTEnv();
    Qureg q = createQureg(n, env);

    /* one arbitrary fixed 1q unitary (values don't affect the rate);
       QuEST validates unitarity, so build exactly:
       U = [[a, -conj(b)], [b, conj(a)]], |a|^2+|b|^2 = 1 */
    ComplexMatrix2 u;
    double ar = 0.6, ai = 0.3, br = 0.64807406984, bi = 0.35;
    double norm = sqrt(ar*ar + ai*ai + br*br + bi*bi);
    ar /= norm; ai /= norm; br /= norm; bi /= norm;
    u.real[0][0] = ar;  u.imag[0][0] = ai;
    u.real[0][1] = -br; u.imag[0][1] = bi;
    u.real[1][0] = br;  u.imag[1][0] = bi;
    u.real[1][1] = ar;  u.imag[1][1] = -ai;

    initZeroState(q);
    long gates = 0;
    double t0 = now_sec();
    for (int d = 0; d < depth; ++d) {
        for (int t = 0; t < n; ++t) {
            unitary(q, t, u);
            ++gates;
        }
        for (int t = d % 2; t < n - 1; t += 2) {
            controlledNot(q, t, t + 1);
            ++gates;
        }
    }
    qreal prob = calcProbOfOutcome(q, n - 1, 0);
    double dt = now_sec() - t0;

    double amps = (double)gates * pow(2.0, n);
    printf("{\"n\": %d, \"depth\": %d, \"gates\": %ld, \"seconds\": %.3f, "
           "\"amp_updates_per_sec\": %.4g, \"prob\": %.6f}\n",
           n, depth, gates, dt, amps / dt, (double)prob);

    destroyQureg(q, env);
    destroyQuESTEnv(env);
    return 0;
}
