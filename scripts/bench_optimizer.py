"""Circuit-optimizer A/B (ISSUE 13 acceptance): QT_OPTIMIZER=on vs off
on the workloads the rewrite targets, measuring what the optimizer
claims to improve — executed gate count, window-remap exchange
dispatches, and wall clock — with amplitude parity checked between arms.

Three workloads, all on the 8-shard dryrun mesh:

* ``random``  — a config-2-style seeded random circuit (H/X/S/T/rotations/
  CNOT/CZ/SWAP mix): the honest generic stream, where wins come from
  incidental same-target runs merging;
* ``qft``     — a QFT-like phase-heavy ladder (H + controlled-phase
  chains): maximal diagonal-coalescing surface, the reordering pass
  clusters the commuting phase gates around the H barriers;
* ``churn``   — the config-6-style alternating shard-local /
  sharded-target stream: commutation-aware reordering clusters gates by
  target locality so the window planner emits far fewer remap sigmas.

Per arm the script records best-of-``reps`` drain wall-clock, the
telemetry ``exchanges_total{op=window_remap}`` counter, the optimizer's
own gates in/out, and ``model_drift_total`` (must stay 0 — §21 prices
the optimized stream).  The headline metric is ``optimizer_speedup_x``
(total off-seconds / on-seconds across workloads).

Usage: python scripts/bench_optimizer.py [--n 10] [--depth 24]
       [--reps 3] [--no-check]
Needs the 8-device virtual mesh (make verify-optimizer).  --no-check
skips the gating asserts (parity, drift, exchange non-regression).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import optimizer as OPT  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402

if jax.default_backend() == "cpu":
    qt.set_precision(2)  # f64 parity tolerance for the CPU dryrun

# amplitude-parity budget between arms (reordering changes the floating
# point evaluation order; cancel/merge alone is bit-identical)
PARITY_TOL = 1e-10 if qt.get_precision() == 2 else 1e-4


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _unitary(rng, k):
    d = 1 << k
    g = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
    u, _r = np.linalg.qr(g)
    return u


def _random_ops(q, n, depth, seed=7):
    rng = np.random.default_rng(seed)
    for _ in range(depth):
        kind = int(rng.integers(0, 9))
        t = int(rng.integers(0, n))
        u = int(rng.integers(0, n - 1))
        th = float(rng.uniform(0, 2 * np.pi))
        [lambda: qt.hadamard(q, t),
         lambda: qt.pauliX(q, t),
         lambda: qt.tGate(q, t),
         lambda: qt.sGate(q, t),
         lambda: qt.rotateZ(q, t, th),
         lambda: qt.rotateX(q, t, th),
         lambda: qt.controlledNot(q, u, u + 1),
         lambda: qt.controlledPhaseFlip(q, u, u + 1),
         lambda: qt.phaseShift(q, t, th)][kind]()


def _qft_ops(q, n, depth, seed=0):
    del seed
    for _ in range(max(1, depth // (n * 2))):
        for t in range(n):
            qt.hadamard(q, t)
            for u in range(t + 1, n):
                qt.controlledPhaseShift(q, u, t, np.pi / (1 << (u - t)))


def _churn_ops(q, n, depth, seed=11):
    """Config-6-style remap churn: a repeating cycle of disjoint 2q
    unitaries covering MORE qubits than fit shard-local, so the raw
    window planner breaks a window every cycle; the optimizer merges the
    per-pair repeats into one gate each, collapsing the churn."""
    rng = np.random.default_rng(seed)
    pairs = [(i, i + 1) for i in range(0, n - 1, 2)]
    mats = {p: _unitary(rng, 2) for p in pairs}
    for i in range(depth):
        p = pairs[i % len(pairs)]
        qt.multiQubitUnitary(q, list(p), mats[p])


WORKLOADS = {"random": _random_ops, "qft": _qft_ops, "churn": _churn_ops}


def _run_arm(env, build, mode, n, depth, reps):
    """One optimizer arm of one workload: best-of-reps fused drain."""
    qt.setCircuitOptimizer(mode)
    best = float("inf")
    amps = None
    gates_in = gates_out = 0
    exchanges = drift = 0
    for rep in range(reps + 1):  # rep 0 = warm-up/compile
        T.reset()
        q = qt.createQureg(n, env)
        qt.startGateFusion(q)
        build(q, n, depth)
        gates_in = len(q._fusion.gates)
        t0 = time.perf_counter()
        qt.stopGateFusion(q)
        amps = np.asarray(q.amps)
        seconds = time.perf_counter() - t0
        if rep:
            best = min(best, seconds)
        gates_out = gates_in - int(
            T.counter_total("optimizer_gates_removed_total"))
        exchanges = int(T.counter_sum("exchanges_total", op="window_remap"))
        drift = int(T.counter_total("model_drift_total"))
    return {"mode": mode, "seconds": round(best, 4),
            "gates_in": gates_in, "gates_out": gates_out,
            "window_remap_exchanges": exchanges, "drift": drift}, amps


def run(n=10, depth=24, reps=3):
    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        raise RuntimeError(
            "bench_optimizer needs the 8-device virtual mesh — run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    prev_mode = T.mode_name()
    T.configure("on")
    results = {}
    try:
        for name, build in WORKLOADS.items():
            off, a_off = _run_arm(env, build, "off", n, depth, reps)
            on, a_on = _run_arm(env, build, "on", n, depth, reps)
            results[name] = {
                "off": off, "on": on,
                "speedup_x": round(off["seconds"]
                                   / max(on["seconds"], 1e-9), 2),
                "exchange_reduction_x": round(
                    off["window_remap_exchanges"]
                    / max(on["window_remap_exchanges"], 1), 2),
                "max_abs_err": float(np.abs(a_on - a_off).max()),
            }
    finally:
        qt.setCircuitOptimizer(None)
        T.reset()
        T.configure(prev_mode)
    total_off = sum(r["off"]["seconds"] for r in results.values())
    total_on = sum(r["on"]["seconds"] for r in results.values())
    return {
        "bench": "optimizer_ab",
        "n": n, "depth": depth, "reps": reps,
        "backend": jax.default_backend(),
        "devices": env.num_devices,
        "mode_default": OPT.mode(),
        "workloads": results,
        "optimizer_speedup_x": round(total_off / max(total_on, 1e-9), 2),
    }


def main():
    rec = run(n=_arg("--n", 10), depth=_arg("--depth", 24),
              reps=_arg("--reps", 3))
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    ok = True
    for name, r in rec["workloads"].items():
        if r["max_abs_err"] > PARITY_TOL:
            print(f"FAIL: {name} on/off amplitude mismatch "
                  f"{r['max_abs_err']:.3e} — the rewrite must be "
                  f"semantics-preserving", file=sys.stderr)
            ok = False
        for arm in ("off", "on"):
            if r[arm]["drift"]:
                print(f"FAIL: {name}/{arm} model_drift_total="
                      f"{r[arm]['drift']} (§21 must price the stream "
                      f"actually drained)", file=sys.stderr)
                ok = False
        if r["on"]["window_remap_exchanges"] > \
                r["off"]["window_remap_exchanges"]:
            print(f"FAIL: {name} optimized drain issued MORE window-remap "
                  f"exchanges ({r['on']['window_remap_exchanges']} > "
                  f"{r['off']['window_remap_exchanges']})", file=sys.stderr)
            ok = False
    if rec["workloads"]["churn"]["on"]["gates_out"] >= \
            rec["workloads"]["churn"]["on"]["gates_in"]:
        print("FAIL: churn optimizer removed nothing", file=sys.stderr)
        ok = False
    if rec["workloads"]["churn"]["exchange_reduction_x"] < 1.5:
        print("FAIL: churn exchange reduction "
              f"{rec['workloads']['churn']['exchange_reduction_x']}x is "
              "below the 1.5x budget", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
