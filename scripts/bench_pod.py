"""Pod-topology A/B (ISSUE 12 acceptance): the tier-aware remap planner
must cut cross-host (DCN) exchange traffic >= 2x vs flat planning on an
emulated slow-DCN 2x4 topology, with bit-identical amplitudes.

Both arms run the SAME config-6-style churn workload — a periodic
stream of 2q/3q unitaries cycling more distinct hot qubits than fit in
a shard (so every fusion window evicts something it will want back) —
on the 8-shard CPU dryrun read as 2 hosts x 4 chips (``QT_TOPOLOGY=2x4``,
mesh bit 2 = the host axis).  The flat arm (``QT_TOPOLOGY_PLANNER=flat``)
evicts in request order and keeps parking soon-reused qubits on the
cross-host mesh bit, paying a DCN hop to fetch them back every cycle;
the hierarchical arm parks the coldest evictee there, so after warmup
the DCN slot holds a dead qubit and the churn stays on ICI.

Two numbers gate, both per arm:

* MODELED per-tier bytes — ``explainCircuit`` totals (the tier-aware
  cost model, windows + final canonical read);
* MEASURED per-tier bytes — the ``exchange_bytes_total{tier}`` counters
  after actually draining (``model_drift_total`` must stay 0, so the
  two agree by construction — measuring both proves it end to end).

Usage: python scripts/bench_pod.py [--n 10] [--reps 10]
       [--budget 2.0] [--no-check]
Needs the 8-device virtual mesh: run under
XLA_FLAGS=--xla_force_host_platform_device_count=8 (make verify-pod).
Exits non-zero when either reduction lands under the budget (unless
--no-check).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("QT_TOPOLOGY", "2x4")
os.environ.setdefault("QT_TIER_WEIGHT_DCN", "8")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402
from quest_tpu.parallel import topology as TOPO  # noqa: E402

# one period of the churn stream (qubit tuples per gate).  With n=10 and
# nloc=7 the working set cycles 10 logical qubits through 7 local slots:
# every window needs qubits parked on BOTH mesh tiers, which is exactly
# where the flat planner's request-order eviction pairing goes wrong.
PERIOD = [(7, 9), (0, 8, 9), (1, 7, 8), (5, 9), (2, 3, 8), (1, 2, 6)]


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _unitary(rng, k):
    d = 1 << k
    g = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
    u, _r = np.linalg.qr(g)
    return u


def _gates(n, reps, seed=11):
    rng = np.random.default_rng(seed)
    stream = []
    for _ in range(reps):
        for ts in PERIOD:
            assert max(ts) < n
            stream.append((ts, _unitary(rng, len(ts))))
    return stream


def _run_arm(env, planner, n, reps):
    """One planner arm: dry-run model totals, then drain + measure."""
    os.environ[TOPO.PLANNER_ENV] = planner
    stream = _gates(n, reps)

    # modeled: the dry-run explainer on a buffered (undrained) qureg
    q = qt.createQureg(n, env)
    qt.startGateFusion(q)
    for ts, u in stream:
        qt.multiQubitUnitary(q, list(ts), u)
    report = qt.explainCircuit(q)
    # window totals + the final canonical read (reported separately,
    # mirroring exchange_bytes vs exchange_bytes_with_read)
    modeled = dict(report["totals"]["tier_bytes"])
    if report["final_remap"]:
        for tier, b in report["final_remap"]["tier_bytes"].items():
            modeled[tier] = modeled.get(tier, 0) + b
    weighted = report["totals"]["weighted_exchange_cost"]

    # measured: drain the same buffer for real and read the counters
    T.reset()
    t0 = time.perf_counter()
    amps = np.asarray(q.amps)
    seconds = time.perf_counter() - t0
    measured = {
        tier: int(T.counter_sum("exchange_bytes_total",
                                op="window_remap", tier=tier)
                  + T.counter_sum("exchange_bytes_total",
                                  op="remap", tier=tier))
        for tier in TOPO.TIERS}
    drift = T.counter_total("model_drift_total")
    return {"planner": planner, "modeled": modeled, "measured": measured,
            "weighted_cost": weighted, "drift": int(drift),
            "seconds": round(seconds, 4)}, amps


def _ratio(a, b):
    return round(a / b, 2) if b else float("inf") if a else 1.0


def run(n=10, reps=10):
    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        raise RuntimeError(
            "bench_pod needs the 8-device virtual mesh — run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    topo = TOPO.resolve(env.num_devices)
    if topo.dcn_bits == 0:
        raise RuntimeError(
            f"QT_TOPOLOGY={os.environ.get('QT_TOPOLOGY')} resolved flat "
            f"on {env.num_devices} devices — the A/B needs a host axis")
    prev_mode = T.mode_name()
    prev_planner = os.environ.get(TOPO.PLANNER_ENV)
    T.configure("on")
    try:
        flat, amps_flat = _run_arm(env, "flat", n, reps)
        hier, amps_hier = _run_arm(env, "hier", n, reps)
    finally:
        T.reset()
        T.configure(prev_mode)
        if prev_planner is None:
            os.environ.pop(TOPO.PLANNER_ENV, None)
        else:
            os.environ[TOPO.PLANNER_ENV] = prev_planner
    return {
        "bench": "pod_topology_ab",
        "n": n, "reps": reps, "gates": reps * len(PERIOD),
        "topology": topo.describe(),
        "tier_weights": TOPO.tier_weights(),
        "backend": jax.default_backend(),
        "devices": env.num_devices,
        "flat": flat, "hier": hier,
        "modeled_dcn_reduction": _ratio(flat["modeled"].get("dcn", 0),
                                        hier["modeled"].get("dcn", 0)),
        "measured_dcn_reduction": _ratio(flat["measured"].get("dcn", 0),
                                         hier["measured"].get("dcn", 0)),
        "weighted_cost_reduction": _ratio(flat["weighted_cost"],
                                          hier["weighted_cost"]),
        "bit_identical": bool(np.array_equal(amps_flat, amps_hier)),
    }


def main():
    budget = _arg("--budget", 2.0, float)
    rec = run(n=_arg("--n", 10), reps=_arg("--reps", 10))
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    ok = True
    if not rec["bit_identical"]:
        print("FAIL: flat and hierarchical planner amplitudes differ — "
              "topology must never change WHAT is computed",
              file=sys.stderr)
        ok = False
    for arm in ("flat", "hier"):
        if rec[arm]["drift"]:
            print(f"FAIL: {arm} arm ended with model_drift_total="
                  f"{rec[arm]['drift']} (predicted != measured)",
                  file=sys.stderr)
            ok = False
        if rec[arm]["modeled"] != rec[arm]["measured"]:
            print(f"FAIL: {arm} arm modeled tier bytes "
                  f"{rec[arm]['modeled']} != measured "
                  f"{rec[arm]['measured']}", file=sys.stderr)
            ok = False
    for kind in ("modeled", "measured"):
        red = rec[f"{kind}_dcn_reduction"]
        if red < budget:
            print(f"FAIL: {kind} DCN byte reduction {red}x is below the "
                  f"{budget:.1f}x budget", file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
