"""Single-chip scaling runs: config-2 shape and full QFT at 28-30q.

One program per size (no K-diff double compile: at these sizes compile
dominates the session budget); device time estimated as wall minus the
measured scalar-fetch overhead, both reported.  Results recorded in
BASELINE.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from functools import partial
import numpy as np

import quest_tpu as qt
from quest_tpu import circuit as C
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels


def fetch_overhead():
    s = jnp.float32(1.0)
    f = jax.jit(lambda x: x + 1)
    float(f(s))
    t0 = time.perf_counter()
    for _ in range(5):
        float(f(s))
    return (time.perf_counter() - t0) / 5


def run_random(n, depth=20):
    cnot = np.zeros((2, 4, 4), np.float32)
    cnot[0] = np.array(
        [[1, 0, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0], [0, 1, 0, 0]], np.float32)
    fn, us = circuits.build_random_circuit(n, depth, seed=7)

    def build_gates(us):
        gates = []
        for d in range(depth):
            for q in range(n):
                gates.append(C.Gate((q,), us[d, q]))
            for q in range(d % 2, n - 1, 2):
                gates.append(C.Gate((q, q + 1), cnot))
        return gates

    @partial(jax.jit, donate_argnums=0)
    def prog(amps, us):
        amps = C.apply_circuit(amps, build_gates(us), n)
        return calculations.calc_prob_of_outcome_statevec(
            amps, num_qubits=n, target=n - 1, outcome=0)

    def fresh():
        return jnp.asarray(kernels.init_zero_state(1 << n, np.float32))

    t0 = time.perf_counter()
    p = float(prog(fresh(), us))
    compile_s = time.perf_counter() - t0
    best = None
    for _ in range(3):
        a = fresh()
        t0 = time.perf_counter()
        p = float(prog(a, us))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return {"workload": f"{n}q depth-{depth} random", "compile_s": round(compile_s, 1),
            "wall_s": round(best, 3), "prob": p}


def run_qft(n):
    @partial(jax.jit, donate_argnums=0)
    def prog(amps):
        amps = C.fused_qft(amps, n, 0, n)
        return amps[0, 0]

    def fresh():
        return jnp.asarray(kernels.init_zero_state(1 << n, np.float32))

    t0 = time.perf_counter()
    float(prog(fresh()))
    compile_s = time.perf_counter() - t0
    best = None
    for _ in range(3):
        a = fresh()
        t0 = time.perf_counter()
        float(prog(a))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return {"workload": f"{n}q full QFT", "compile_s": round(compile_s, 1),
            "wall_s": round(best, 3)}


if __name__ == "__main__":
    ov = fetch_overhead()
    print(json.dumps({"fetch_overhead_s": round(ov, 3)}), flush=True)
    for arg in sys.argv[1:]:
        kind, n = arg.split(":")
        try:
            r = run_random(int(n)) if kind == "rand" else run_qft(int(n))
            r["device_s_est"] = round(r["wall_s"] - ov, 3)
            print(json.dumps(r), flush=True)
        except Exception as e:
            print(json.dumps({"workload": arg, "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
