"""Single-chip scaling runs: config-2 shape and full QFT at 28-31q.

Default execution is CHAINED (circuit.execute_plan_chained): each pass is
its own cached jitted program and the state stays in the canonical
(2, nb, 128, 128) view between calls, so
  * compile cost = a few seconds per distinct pass signature (the
    monolithic whole-circuit trace took 7-14 min at 28-29q), and
  * no full-state layout copy at program boundaries (the copy that OOMed
    the 30q monolithic program: 8 GB args + 8 GB copy > 15.75 GB HBM).
Set QT_SCALE_MONOLITHIC=1 for the old one-program path.

Timing: steady-state best-of-N wall, device estimate = wall minus the
measured scalar-fetch overhead, and a K-diff (2 circuits minus 1) arm.
Results recorded in BASELINE.md / BENCH notes.

Usage: python scripts/bench_scale.py rand:30 qft:30 ...
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from functools import partial
import numpy as np

import quest_tpu as qt
from quest_tpu import circuit as C
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels

MONO = os.environ.get("QT_SCALE_MONOLITHIC") == "1"
REPS = int(os.environ.get("QT_SCALE_REPS", "5"))
# the canonical-view helpers need n >= 15 (nb >= 2 tiles); small sizes
# run the monolithic path, where compile cost is a non-issue anyway
CHAIN_MIN_QUBITS = 15


def fetch_overhead():
    s = jnp.float32(1.0)
    f = jax.jit(lambda x: x + 1)
    float(f(s))
    t0 = time.perf_counter()
    for _ in range(5):
        float(f(s))
    return (time.perf_counter() - t0) / 5


# shared canonical-view helpers live in quest_tpu.models.circuits
_zero_canonical = circuits.zero_state_canonical
_amp00 = circuits.amp00_canonical
_prob_top_zero = circuits.prob_top_zero_canonical
build_gates = circuits.bench_gate_list


def run_random(n, depth=20):
    fn, us = circuits.build_random_circuit(n, depth, seed=7)
    us = np.asarray(us)
    mono = MONO or n < CHAIN_MIN_QUBITS

    if mono:
        @partial(jax.jit, donate_argnums=0)
        def prog(amps, us):
            amps = C.apply_circuit(amps, build_gates(n, depth, us), n)
            return calculations.calc_prob_of_outcome_statevec(
                amps, num_qubits=n, target=n - 1, outcome=0)

        def run_once():
            a = jnp.asarray(kernels.init_zero_state(1 << n, np.float32))
            t0 = time.perf_counter()
            p = float(prog(a, us))
            return time.perf_counter() - t0, p
    else:
        t0 = time.perf_counter()
        ops = C.plan_to_device(C.plan_circuit(build_gates(n, depth, us), n),
                               jnp.float32)
        plan_s = time.perf_counter() - t0

        def run_once(k=1):
            a = _zero_canonical(n)
            t0 = time.perf_counter()
            for _ in range(k):
                a = C.execute_plan_chained(a, ops, n)
            p = float(_prob_top_zero(a))
            return time.perf_counter() - t0, p

    t0 = time.perf_counter()
    _, p = run_once()
    compile_s = time.perf_counter() - t0
    best = None
    for _ in range(REPS):
        dt, p = run_once()
        best = dt if best is None else min(best, dt)
    r = {"workload": f"{n}q depth-{depth} random",
         "mode": "monolithic" if mono else "chained",
         "compile_s": round(compile_s, 1), "wall_s": round(best, 3), "prob": p}
    if not mono:
        r["plan_s"] = round(plan_s, 2)
        # K-diff: two chained circuits minus one (removes fetch + dispatch)
        t2 = min(run_once(2)[0] for _ in range(3))
        r["kdiff_device_s"] = round(t2 - best, 3)
        r["passes"] = len(ops)
    return r


def run_qft(n):
    mono = MONO or n < CHAIN_MIN_QUBITS
    if mono:
        @partial(jax.jit, donate_argnums=0)
        def prog(amps):
            amps = C.fused_qft(amps, n, 0, n)
            return amps[0, 0]

        def run_once(k=1):
            a = jnp.asarray(kernels.init_zero_state(1 << n, np.float32))
            t0 = time.perf_counter()
            float(prog(a))
            return time.perf_counter() - t0
    else:
        last_amp0 = [None]

        def run_once(k=1):
            a = _zero_canonical(n)
            t0 = time.perf_counter()
            for _ in range(k):
                a = C.fused_qft(a, n, 0, n)
            last_amp0[0] = float(_amp00(a))
            return time.perf_counter() - t0

    t0 = time.perf_counter()
    run_once()
    compile_s = time.perf_counter() - t0
    best = min(run_once() for _ in range(REPS))
    r = {"workload": f"{n}q full QFT",
         "mode": "monolithic" if mono else "chained",
         "compile_s": round(compile_s, 1), "wall_s": round(best, 3)}
    if not mono:
        # oracle self-check: QFT|0> is uniform, amp[0] = 2^(-n/2)
        r["amp0"] = last_amp0[0]
        r["amp0_expect"] = 2.0 ** (-n / 2)
        t2 = min(run_once(2) for _ in range(3))
        r["kdiff_device_s"] = round(t2 - best, 3)
    return r


if __name__ == "__main__":
    ov = fetch_overhead()
    print(json.dumps({"fetch_overhead_s": round(ov, 3), "mode":
                      "monolithic" if MONO else "chained"}), flush=True)
    for arg in sys.argv[1:]:
        kind, n = arg.split(":")
        try:
            r = run_random(int(n)) if kind == "rand" else run_qft(int(n))
            r["device_s_est"] = round(r["wall_s"] - ov, 3)
            print(json.dumps(r), flush=True)
        except Exception as e:
            print(json.dumps({"workload": arg, "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
