"""Moved: the sharded-collective contract check is product code now —
``python -m quest_tpu.analysis --contracts`` (quest_tpu/analysis/hlocheck.py)."""
import os, sys  # noqa: E401
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from quest_tpu.analysis import hlocheck  # noqa: E402
sys.exit(hlocheck.main())
