"""Real-chip evidence for the one-kernel-set contract (VERDICT r3 item 1):
the shard_map composites (Trotter scan, PauliSum expectation scan, the
general-run fused QFT, and a gateFusion drain program) execute their
per-shard Pallas kernels on a REAL TPU device under a 1-device mesh and
match the unsharded paths bit-for-bit-level (f32 tolerance).

This is the same three-way evidence pattern the r3 full-register sharded
QFT got: virtual-mesh oracle parity (tests/test_distributed.py) + HLO
collective pinning (tests/test_distributed_hlo.py) + this on-chip run.

Writes scripts/tpu_sharded_contract_result.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tpu_sharded_contract_result.json")


def log(*a):
    print(f"[{time.strftime('%H:%M:%S')}]", *a, flush=True)


def main():
    log("importing jax ...")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    log("waiting for device claim ...")
    t0 = time.time()
    devs = jax.devices()
    log(f"claim granted after {time.time() - t0:.0f}s: {devs}")

    from quest_tpu import circuit as CIRC
    from quest_tpu import fusion
    from quest_tpu.env import AMP_AXIS
    from quest_tpu.ops import cplx, fused
    from quest_tpu.ops import paulis as P
    from quest_tpu.parallel import dist as PAR

    mesh = Mesh(np.asarray(devs[:1]), (AMP_AXIS,))
    results = {"devices": str(devs), "mesh": str(mesh)}
    rng = np.random.default_rng(7)

    def rand_state(n):
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    def maxdiff(x, y):
        return float(jnp.max(jnp.abs(x - y)))

    # -- 1. Trotter scan: sharded(1-dev mesh) vs unsharded ------------------
    n = 20
    T = 8
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T).astype(np.float64))
    s0 = rand_state(n)
    log("trotter_scan_sharded compile+run ...")
    t0 = time.time()
    a1 = PAR.trotter_scan_sharded(jnp.copy(s0), codes, angles, mesh=mesh,
                                  num_qubits=n, rep_qubits=n)
    a1.block_until_ready()
    results["trotter_sharded_s"] = time.time() - t0
    a2 = P.trotter_scan(jnp.copy(s0), codes, angles, num_qubits=n,
                        rep_qubits=n)
    d = maxdiff(a1, a2)
    results["trotter_maxdiff"] = d
    log(f"trotter maxdiff {d:.3e}")

    # -- 2. PauliSum expectation scan --------------------------------------
    s0 = rand_state(n)
    coeffs = jnp.asarray(rng.normal(size=T).astype(np.float64))
    t0 = time.time()
    e1 = PAR.expec_pauli_sum_scan_sharded(s0, codes, coeffs, mesh=mesh,
                                          num_qubits=n)
    e1.block_until_ready()
    results["expec_sharded_s"] = time.time() - t0
    e2 = P.expec_pauli_sum_scan(s0, codes, coeffs, num_qubits=n)
    d = abs(float(e1) - float(e2))
    results["expec_absdiff"] = d
    results["expec_value"] = float(e2)
    log(f"expec diff {d:.3e} (value {float(e2):.6f})")

    # -- 3. density fused QFT (general-run kernel) -------------------------
    nq = 10
    nn = 2 * nq
    s0 = rand_state(nn)
    runs = ((0, nq, False), (nq, nq, True))
    log("fused_qft_runs_sharded compile+run ...")
    t0 = time.time()
    q1 = PAR.fused_qft_runs_sharded(jnp.copy(s0), mesh=mesh, num_qubits=nn,
                                    runs=runs)
    q1.block_until_ready()
    results["qft_runs_sharded_s"] = time.time() - t0
    q2 = CIRC.fused_qft(jnp.copy(s0), nn, 0, nq, shifts=(0, nq))
    q2 = q2.reshape(q1.shape)
    d = maxdiff(q1, q2)
    results["density_qft_maxdiff"] = d
    log(f"density qft maxdiff {d:.3e}")

    # -- 3b. small-shard fully-local run: the dense window passes of
    # CIRC.fused_qft execute per shard INSIDE the shard_map body at the
    # smallest window-sized shard (nloc = 15) — the configuration the
    # adjacent fused_qft_sharded kernel guards against promoting into
    # scoped VMEM; proves it compiles and matches on real hardware.
    n15 = 15
    s0 = rand_state(n15)
    t0 = time.time()
    w1 = PAR.fused_qft_runs_sharded(jnp.copy(s0), mesh=mesh, num_qubits=n15,
                                    runs=((0, n15, False),))
    w1.block_until_ready()
    results["small_shard_qft_s"] = time.time() - t0
    w2 = CIRC.fused_qft(jnp.copy(s0), n15, 0, n15).reshape(w1.shape)
    d = maxdiff(w1, w2)
    results["small_shard_qft_maxdiff"] = d
    log(f"small-shard (nloc=15) qft maxdiff {d:.3e}")

    # -- 4. gateFusion drain program under the 1-device mesh ---------------
    n = 20
    s0 = rand_state(n)

    def ru(k):
        d = 1 << k
        a = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
        q, r = np.linalg.qr(a)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag]).astype(np.float32)

    gates = []
    for t in range(n):
        gates.append(CIRC.Gate((t,), ru(1)))
    for t in range(0, n - 1, 2):
        gates.append(CIRC.Gate((t, t + 1), ru(2)))
    program, arrays = fusion._split_items(gates, n, False)
    prec = fused.matmul_precision_name()
    log("drain program (sharded runner) compile+run ...")
    t0 = time.time()
    r1 = fusion._plan_runner(n, program, mesh, prec)(jnp.copy(s0),
                                                     tuple(arrays), ())
    r1.block_until_ready()
    results["drain_sharded_s"] = time.time() - t0
    r2 = fusion._plan_runner(n, program, None, prec)(jnp.copy(s0),
                                                     tuple(arrays), ())
    d = maxdiff(r1, r2)
    results["drain_maxdiff"] = d
    log(f"drain maxdiff {d:.3e}")

    ok = (results["trotter_maxdiff"] < 1e-5
          and results["expec_absdiff"] < 1e-4
          and results["density_qft_maxdiff"] < 1e-5
          and results["small_shard_qft_maxdiff"] < 1e-5
          and results["drain_maxdiff"] < 1e-5)
    results["ok"] = bool(ok)
    with open(RESULT, "w") as f:
        json.dump(results, f, indent=2)
    log("result:", json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
