"""Generate docs/reference.md: every public camelCase API function with
signature and docstring (the analogue of the reference's doxygen HTML
tree, docs/ + doxyconfig/)."""
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

jax.config.update("jax_platforms", "cpu")
import quest_tpu as qt

GROUPS = [
    ("Environment", ["createQuESTEnv", "destroyQuESTEnv", "syncQuESTEnv",
                     "syncQuESTSuccess", "reportQuESTEnv", "getEnvironmentString",
                     "initDistributed", "copyStateToGPU", "copyStateFromGPU",
                     "seedQuEST", "seedQuESTDefault", "invalidQuESTInputError"]),
    ("Registers", ["createQureg", "createDensityQureg", "createCloneQureg",
                   "destroyQureg", "reportState", "reportStateToScreen",
                   "reportQuregParams", "getNumQubits", "getNumAmps",
                   "cloneQureg"]),
    ("Matrices and operators", ["createComplexMatrixN", "destroyComplexMatrixN",
                                "initComplexMatrixN", "getStaticComplexMatrixN",
                                "createPauliHamil", "destroyPauliHamil",
                                "createPauliHamilFromFile", "initPauliHamil",
                                "reportPauliHamil", "createDiagonalOp",
                                "destroyDiagonalOp", "syncDiagonalOp",
                                "initDiagonalOp", "initDiagonalOpFromPauliHamil",
                                "createDiagonalOpFromPauliHamilFile",
                                "setDiagonalOpElems"]),
    ("State initialisation", ["initBlankState", "initZeroState", "initPlusState",
                              "initClassicalState", "initPureState",
                              "initDebugState", "initStateFromAmps", "setAmps"]),
    ("Unitaries", ["phaseShift", "controlledPhaseShift", "multiControlledPhaseShift",
                   "controlledPhaseFlip", "multiControlledPhaseFlip", "sGate",
                   "tGate", "compactUnitary", "unitary", "rotateX", "rotateY",
                   "rotateZ", "rotateAroundAxis", "controlledRotateX",
                   "controlledRotateY", "controlledRotateZ",
                   "controlledRotateAroundAxis", "controlledCompactUnitary",
                   "controlledUnitary", "multiControlledUnitary", "pauliX",
                   "pauliY", "pauliZ", "hadamard", "controlledNot",
                   "multiControlledMultiQubitNot", "multiQubitNot",
                   "controlledPauliY", "swapGate", "sqrtSwapGate",
                   "multiStateControlledUnitary", "multiRotateZ",
                   "multiRotatePauli", "multiControlledMultiRotateZ",
                   "multiControlledMultiRotatePauli", "twoQubitUnitary",
                   "controlledTwoQubitUnitary", "multiControlledTwoQubitUnitary",
                   "multiQubitUnitary", "controlledMultiQubitUnitary",
                   "multiControlledMultiQubitUnitary"]),
    ("Measurement and collapse", ["calcProbOfOutcome", "calcProbOfAllOutcomes",
                                  "collapseToOutcome", "measure",
                                  "measureWithStats", "measureSequence"]),
    ("Decoherence", ["mixDephasing", "mixTwoQubitDephasing", "mixDepolarising",
                     "mixDamping", "mixTwoQubitDepolarising", "mixPauli",
                     "mixDensityMatrix", "mixKrausMap", "mixTwoQubitKrausMap",
                     "mixMultiQubitKrausMap"]),
    ("Calculations", ["getAmp", "getRealAmp", "getImagAmp", "getProbAmp",
                      "getDensityAmp", "calcTotalProb", "calcInnerProduct",
                      "calcDensityInnerProduct", "calcPurity", "calcFidelity",
                      "calcExpecPauliProd", "calcExpecPauliSum",
                      "calcExpecPauliHamil", "calcExpecDiagonalOp",
                      "calcHilbertSchmidtDistance"]),
    ("Composite operators", ["setWeightedQureg", "applyPauliSum", "applyPauliHamil",
                             "applyTrotterCircuit", "applyMatrix2", "applyMatrix4",
                             "applyMatrixN", "applyMultiControlledMatrixN",
                             "applyDiagonalOp", "applyPhaseFunc",
                             "applyPhaseFuncOverrides", "applyMultiVarPhaseFunc",
                             "applyMultiVarPhaseFuncOverrides",
                             "applyNamedPhaseFunc", "applyNamedPhaseFuncOverrides",
                             "applyParamNamedPhaseFunc",
                             "applyParamNamedPhaseFuncOverrides", "applyFullQFT",
                             "applyQFT"]),
    ("QASM recording", ["startRecordingQASM", "stopRecordingQASM",
                        "clearRecordedQASM", "printRecordedQASM",
                        "writeRecordedQASMToFile"]),
    ("Beyond reference parity", ["gateFusion", "startGateFusion", "stopGateFusion",
                                 "saveQureg", "loadQureg", "writeStateToFile",
                                 "readStateFromFile", "initStateOfSingleQubit",
                                 "initStateFromSingleFile", "compareStates",
                                 "setDensityAmps", "set_precision"]),
]


def main():
    out = ["# quest_tpu API reference",
           "",
           "Generated from docstrings by `scripts/gen_api_reference.py`"
           " (`make docs`).  Reference-parity citations (`file:line`) point"
           " into the QuEST sources the function mirrors.", ""]
    listed = set()
    for title, names in GROUPS:
        out.append(f"## {title}")
        out.append("")
        for name in names:
            fn = getattr(qt, name, None)
            if fn is None:
                continue
            listed.add(name)
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            doc = inspect.getdoc(fn) or ""
            out.append(f"### `{name}{sig}`")
            out.append("")
            if doc:
                out.append(doc)
                out.append("")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "reference.md")
    with open(path, "w") as f:
        f.write("\n".join(out))
    print(f"wrote {path}: {len(listed)} functions")


if __name__ == "__main__":
    main()
