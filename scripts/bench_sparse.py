"""Permutation fast-path + sparse-init A/B (ISSUE 15 acceptance):
QT_PERM_FAST=on vs off on the workloads the §28 lowering targets, with
amplitude parity checked between arms.

Three workloads, all on the 8-shard dryrun mesh:

* ``relabel`` — a SWAP-only chain on shard-LOCAL bits: the on arm folds
  the entire stream into the lazy qubit permutation (zero dispatched
  window ops) and the deferred canonical-read remap must compile to
  ZERO collectives (pinned via introspect.audit under
  CollectiveBudget(exact={})); the off arm pays a dense 4x4 window
  matmul per SWAP;
* ``ripple``  — a ripple-carry-adder-style CNOT/Toffoli chain (the
  bench_suite config-16 shape): gather/XOR lowering vs dense window
  matmuls;
* ``sparse``  — sparse clustered state preparation
  (initSparseClusteredState, arXiv:2504.08705): time-to-admitted
  register (the sparse description admits at O(k) cost, densifying
  lazily) vs the dense host-array initStateFromAmps round-trip.

Per arm the script records best-of-``reps`` wall clock and
``model_drift_total`` (must stay 0 — §21 prices the lowered stream
too).  Headline metrics: ``perm_speedup_x`` (off/on seconds across
relabel+ripple, gated >= 5x) and ``sparse_init_speedup_x``.

Usage: python scripts/bench_sparse.py [--n 18] [--depth 60] [--reps 2]
       [--no-check]
Needs the 8-device virtual mesh (make verify-sparse).  --no-check
skips the gating asserts (speedup, parity, drift, zero-collective).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402
from quest_tpu.parallel import dist as PAR  # noqa: E402

if jax.default_backend() == "cpu":
    qt.set_precision(2)  # f64 parity tolerance for the CPU dryrun

PARITY_TOL = 1e-10 if qt.get_precision() == 2 else 1e-4
SPEEDUP_FLOOR = 5.0


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _relabel_ops(q, n, depth):
    """SWAP-only churn on shard-local bits (relabel-only stream)."""
    nloc = n - 3
    rng = np.random.default_rng(13)
    for _ in range(depth):
        a, b = (int(v) for v in rng.choice(nloc, size=2, replace=False))
        qt.swapGate(q, a, b)


def _ripple_ops(q, n, depth):
    """Ripple-carry-adder-style CNOT/Toffoli chain (config-16 shape)."""
    for r in range(max(1, depth // (2 * (n - 2)))):
        qt.pauliX(q, r % n)
        for i in range(n - 2):
            qt.controlledNot(q, i, i + 1)
            qt.multiControlledMultiQubitNot(q, [i, i + 1], [i + 2])
        for i in range(n - 1):
            qt.controlledNot(q, i, i + 1)


def _run_gate_arm(env, build, flag, n, depth, reps):
    """One QT_PERM_FAST arm of one gate workload: best-of-reps drain."""
    os.environ["QT_PERM_FAST"] = flag
    best = float("inf")
    amps = None
    drift = exchanges = 0
    perm_for_audit = None
    for rep in range(reps + 1):  # rep 0 = warm-up/compile
        T.reset()
        q = qt.createQureg(n, env)
        qt.initDebugState(q)
        qt.startGateFusion(q)
        build(q, n, depth)
        t0 = time.perf_counter()
        qt.stopGateFusion(q)
        _ = q._amps_raw()  # drain (no canonical remap yet)
        exchanges = int(T.counter_sum("exchanges_total", op="window_remap"))
        perm_for_audit = q._perm
        amps = np.asarray(q.amps)  # canonical read joins the timed cost
        seconds = time.perf_counter() - t0
        if rep:
            best = min(best, seconds)
        drift = int(T.counter_total("model_drift_total"))
    return {"perm_fast": flag, "seconds": round(best, 4),
            "window_remap_exchanges": exchanges,
            "drift": drift}, amps, perm_for_audit


def _audit_relabel_read(env, n, perm):
    """Compile the deferred canonical-read remap of a relabel-only
    stream and histogram its collectives (must be empty: the fold left
    only shard-local movement)."""
    if perm is None:
        return {}
    q = qt.createQureg(n, env)
    qt.initDebugState(q)

    def canonical_read(a):
        return PAR.remap_sharded(a, mesh=env.mesh, num_qubits=n,
                                 sigma=PAR.canonical_sigma(perm))

    with qt.CollectiveBudget(exact={}):
        rep = qt.audit(canonical_read, q._amps_raw())
    return dict(rep.collectives)


def _run_sparse_arm(env, n, sparse, reps):
    """Time-to-initialized-register for a sparse CLUSTERED state
    (arXiv:2504.08705): the sparse description admits at O(k) cost
    (densify deferred to first touch); the dense arm builds and ships
    the full 2^n host arrays.  Parity checked untimed."""
    nblocks = 1 << max(0, n - 12)
    blen = 4
    rng = np.random.default_rng(29)
    bases = np.sort(rng.choice((1 << n) // blen, size=nblocks,
                               replace=False)) * blen
    blocks = rng.standard_normal((nblocks, blen)) \
        / np.sqrt(nblocks * blen)
    best = float("inf")
    amps = None
    for rep in range(reps + 1):
        q = qt.createQureg(n, env)
        if sparse:
            t0 = time.perf_counter()
            qt.initSparseClusteredState(q, bases, blocks)
            seconds = time.perf_counter() - t0
        else:
            t0 = time.perf_counter()
            re = np.zeros(1 << n)
            for base, block in zip(bases, blocks):
                re[base:base + blen] = block
            qt.initStateFromAmps(q, re, np.zeros(1 << n))
            seconds = time.perf_counter() - t0
        if rep:
            best = min(best, seconds)
        amps = np.asarray(q.amps)  # untimed: densify + parity read
    return {"sparse": sparse, "seconds": round(best, 5),
            "nonzeros": int(nblocks * blen)}, amps


def run(n=18, depth=60, reps=2):
    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        raise RuntimeError(
            "bench_sparse needs the 8-device virtual mesh — run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    prev_mode = T.mode_name()
    prev_flag = os.environ.get("QT_PERM_FAST")
    T.configure("on")
    results = {}
    try:
        for name, build in (("relabel", _relabel_ops),
                            ("ripple", _ripple_ops)):
            off, a_off, _p = _run_gate_arm(env, build, "off", n, depth,
                                           reps)
            on, a_on, perm = _run_gate_arm(env, build, "on", n, depth,
                                           reps)
            results[name] = {
                "off": off, "on": on,
                "speedup_x": round(off["seconds"]
                                   / max(on["seconds"], 1e-9), 2),
                "max_abs_err": float(np.abs(a_on - a_off).max()),
            }
            if name == "relabel":
                results[name]["read_collectives"] = \
                    _audit_relabel_read(env, n, perm)
        os.environ["QT_PERM_FAST"] = "on"
        dense, a_dense = _run_sparse_arm(env, n, False, reps)
        sparse, a_sparse = _run_sparse_arm(env, n, True, reps)
        results["sparse"] = {
            "dense": dense, "sparse": sparse,
            "speedup_x": round(dense["seconds"]
                               / max(sparse["seconds"], 1e-9), 2),
            "max_abs_err": float(np.abs(a_sparse - a_dense).max()),
        }
    finally:
        if prev_flag is None:
            os.environ.pop("QT_PERM_FAST", None)
        else:
            os.environ["QT_PERM_FAST"] = prev_flag
        T.reset()
        T.configure(prev_mode)
    perm_off = sum(results[w]["off"]["seconds"]
                   for w in ("relabel", "ripple"))
    perm_on = sum(results[w]["on"]["seconds"]
                  for w in ("relabel", "ripple"))
    return {
        "bench": "sparse_permfast_ab",
        "n": n, "depth": depth, "reps": reps,
        "backend": jax.default_backend(),
        "devices": env.num_devices,
        "workloads": results,
        "perm_speedup_x": round(perm_off / max(perm_on, 1e-9), 2),
        "sparse_init_speedup_x": results["sparse"]["speedup_x"],
    }


def main():
    rec = run(n=_arg("--n", 18), depth=_arg("--depth", 60),
              reps=_arg("--reps", 2))
    print(json.dumps(rec), flush=True)
    if "--no-check" in sys.argv:
        return 0
    ok = True
    for name in ("relabel", "ripple", "sparse"):
        r = rec["workloads"][name]
        if r["max_abs_err"] > PARITY_TOL:
            print(f"FAIL: {name} on/off amplitude mismatch "
                  f"{r['max_abs_err']:.3e} — the lowering must be "
                  f"semantics-preserving", file=sys.stderr)
            ok = False
    for name in ("relabel", "ripple"):
        for arm in ("off", "on"):
            if rec["workloads"][name][arm]["drift"]:
                print(f"FAIL: {name}/{arm} model_drift_total="
                      f"{rec['workloads'][name][arm]['drift']} (§21 must "
                      f"price the lowered stream too)", file=sys.stderr)
                ok = False
    if rec["workloads"]["relabel"]["on"]["window_remap_exchanges"]:
        print("FAIL: relabel-only stream dispatched window exchanges "
              f"({rec['workloads']['relabel']['on']}"
              ") — the fold must be zero-motion", file=sys.stderr)
        ok = False
    if sum(rec["workloads"]["relabel"]["read_collectives"].values()):
        print("FAIL: relabel-only canonical read compiled collectives "
              f"{rec['workloads']['relabel']['read_collectives']}",
              file=sys.stderr)
        ok = False
    if rec["perm_speedup_x"] < SPEEDUP_FLOOR:
        print(f"FAIL: perm_speedup_x {rec['perm_speedup_x']}x below the "
              f"{SPEEDUP_FLOOR}x acceptance floor", file=sys.stderr)
        ok = False
    if rec["sparse_init_speedup_x"] < 1.0:
        print("FAIL: sparse init slower than the dense host round-trip "
              f"({rec['sparse_init_speedup_x']}x)", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
