#!/bin/sh
# Build the reference-QuEST baseline driver (scripts/ref_bench.c) against
# the unmodified reference sources, CPU multithreaded backend, double
# precision — the configuration BASELINE.md cites for vs_baseline.
set -e
REF=${REF:-/root/reference}
OUT=${OUT:-/root/repo/.refbuild}
mkdir -p "$OUT"
gcc -O2 -fopenmp -std=c99 -DQuEST_PREC=2 \
    -I"$REF/QuEST/include" -I"$REF/QuEST/src" \
    /root/repo/scripts/ref_bench.c \
    "$REF/QuEST/src/QuEST.c" \
    "$REF/QuEST/src/QuEST_common.c" \
    "$REF/QuEST/src/QuEST_qasm.c" \
    "$REF/QuEST/src/QuEST_validation.c" \
    "$REF/QuEST/src/mt19937ar.c" \
    "$REF/QuEST/src/CPU/QuEST_cpu.c" \
    "$REF/QuEST/src/CPU/QuEST_cpu_local.c" \
    -lm -o "$OUT/ref_bench"
echo "built $OUT/ref_bench"
