"""Probe: window-pass kernel variants at 26q, K-diff timed on the chip.

V0: current concat-based real-rep kernel (fused.apply_window_stack)
V1: separate-channel kernel — 4 matmuls per side, no concat/slice/stack
V2: V1 with channel-separate output writes
V3: masked variants of V0/V1 (mask multiply cost)
"""

import json
import sys
import time
from functools import partial

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from quest_tpu import circuit as C
from quest_tpu.ops import fused

N = 26
HI_PREC = jax.lax.Precision.HIGHEST


def log(**kw):
    print(json.dumps(kw), flush=True)


def sep_kernel(apply_a, apply_b, with_mask=False):
    def kernel(a_ref, ma_ref, mb_ref, *rest):
        mask_ref, o_ref = (rest[0], rest[1]) if with_mask else (None, rest[0])
        x = a_ref[...]                       # (2, R, 128, M, 128)
        xr, xi = x[0], x[1]
        d_lane = (((2,), (0,)), ((), ()))    # contract lane axis (dim 3 of xr -> after indexing (R,128,M,128): lanes = dim 3)
        dd = (((3,), (0,)), ((), ()))
        if apply_a:
            Ar = ma_ref[0]
            Ai = ma_ref[1]
            f = partial(jax.lax.dot_general, dimension_numbers=dd,
                        precision=HI_PREC, preferred_element_type=jnp.float32)
            ar = f(xr, Ar) - f(xi, Ai)
            ai = f(xr, Ai) + f(xi, Ar)
        else:
            ar, ai = xr, xi
        if apply_b:
            Br = mb_ref[0]
            Bi = mb_ref[1]
            db = (((1,), (1,)), ((), ()))    # contract window axis of (R,128,M,128) with B row dim? B[w', w]: contract dim 1
            g = partial(jax.lax.dot_general, dimension_numbers=db,
                        precision=HI_PREC, preferred_element_type=jnp.float32)
            # g(B, y) contracts B dim1 with y dim1 -> out (128w', R, M, 128)
            orr = g(Br, ar) - g(Bi, ai)
            oii = g(Br, ai) + g(Bi, ar)
            orr = jnp.moveaxis(orr, 0, 1)
            oii = jnp.moveaxis(oii, 0, 1)
        else:
            orr, oii = ar, ai
        if with_mask:
            mr = mask_ref[0][:, None, :]
            mi = mask_ref[1][:, None, :]
            orr, oii = orr * mr - oii * mi, orr * mi + oii * mr
        o_ref[0] = orr
        o_ref[1] = oii

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "k", "apply_a", "apply_b",
                                   "with_mask"),
         donate_argnums=0)
def sep_window(amps, ma, mb, mask=None, *, num_qubits, k, apply_a=True,
               apply_b=True, with_mask=False):
    n = num_qubits
    in_shape = amps.shape
    hi = 1 << (n - k - 7)
    mid = 1 << (k - 7)
    M = min(mid, 8 if apply_a else 16)
    while mid % M:
        M //= 2
    R = 1
    view = amps.reshape(2, hi, 128, mid, 128)
    state_spec = pl.BlockSpec((2, R, 128, M, 128), lambda i, j: (0, i, 0, j, 0))
    in_specs = [state_spec,
                pl.BlockSpec((2, 128, 128), lambda i, j: (0, 0, 0)),
                pl.BlockSpec((2, 128, 128), lambda i, j: (0, 0, 0))]
    ops = [view, ma, mb]
    if with_mask:
        in_specs.append(pl.BlockSpec((2, 128, 128), lambda i, j: (0, 0, 0)))
        ops.append(mask)
    out = pl.pallas_call(
        sep_kernel(apply_a, apply_b, with_mask),
        grid=(hi // R, mid // M),
        in_specs=in_specs,
        out_specs=state_spec,
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
    )(*ops)
    return out.reshape(in_shape)


def main():
    log(devices=str(jax.devices()))
    rng = np.random.default_rng(0)

    def rand_u7():
        d = 128
        z = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
        q, r = np.linalg.qr(z)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag]).astype(np.float32)

    a_soa = jnp.asarray(rand_u7())
    b_soa = jnp.asarray(rand_u7())
    mask = jnp.asarray(np.stack([np.cos(np.outer(np.arange(128), np.arange(128)) * 1e-3),
                                 np.sin(np.outer(np.arange(128), np.arange(128)) * 1e-3)]).astype(np.float32))
    nb = 1 << (N - 14)
    fresh = lambda: jnp.zeros((2, nb, 128, 128), jnp.float32).at[0, 0, 0, 0].set(1.0)

    # correctness: sep vs current at k=14
    a1 = fused.apply_window_stack(fresh(), a_soa[None], b_soa[None], num_qubits=N, k=14)
    a2 = sep_window(fresh(), a_soa, b_soa, num_qubits=N, k=14)
    d01 = float(jnp.max(jnp.abs(a1 - a2)))
    m1 = fused.apply_window_stack(fresh(), a_soa[None], b_soa[None], mask, num_qubits=N, k=14)
    m2 = sep_window(fresh(), a_soa, b_soa, mask, num_qubits=N, k=14, with_mask=True)
    d02 = float(jnp.max(jnp.abs(m1 - m2)))
    log(check_AB=d01, check_mask=d02)

    def timer(fn, r1=8, r2=40):
        def run(reps):
            a = fresh()
            t0 = time.perf_counter()
            for _ in range(reps):
                a = fn(a)
            s = float(jnp.sum(a[:1, :1, :1, :1]))
            return time.perf_counter() - t0
        run(1)
        t1 = min(run(r1) for _ in range(4))
        t2 = min(run(r2) for _ in range(4))
        return (t2 - t1) / (r2 - r1) * 1e3

    cases = {
        "V0 A+B k=14": lambda a: fused.apply_window_stack(a, a_soa[None], b_soa[None], num_qubits=N, k=14),
        "V1 sep A+B k=14": lambda a: sep_window(a, a_soa, b_soa, num_qubits=N, k=14),
        "V0 A+B+mask k=14": lambda a: fused.apply_window_stack(a, a_soa[None], b_soa[None], mask, num_qubits=N, k=14),
        "V1 sep A+B+mask": lambda a: sep_window(a, a_soa, b_soa, mask, num_qubits=N, k=14, with_mask=True),
        "V0 B-only k=14": lambda a: fused.apply_window_stack(a, a_soa[None], b_soa[None], num_qubits=N, k=14, apply_a=False),
        "V1 sep B-only": lambda a: sep_window(a, a_soa, b_soa, num_qubits=N, k=14, apply_a=False),
        "V1 sep B+mask": lambda a: sep_window(a, a_soa, b_soa, mask, num_qubits=N, k=14, apply_a=False, with_mask=True),
    }
    for name, fn in cases.items():
        log(stage=name, per_pass_ms=round(timer(fn), 2))


if __name__ == "__main__":
    main()
