"""Where does the direct-rotation term's 2.2 ms (24q, quiet session) go?
Theoretical floor is ~3 HBM passes (~0.5 ms).  Scan variants whose flip
mask touches ONLY the row (hi) axis, ONLY the lane (lo) axis, both, or
neither.

CAVEATS on interpretation: "none" (all-Z codes) is NOT a gather-free
control — the traced fm=0 still executes both identity-index takes
(codes are scan-carried, XLA cannot fold them) and it is the only mode
with nonzero parity-sign work, while the X-only modes pay gathers but
no parity mask.  So mode differences bound, rather than cleanly
attribute, per-axis gather cost.  The first recorded run
(probe_gather_axes_result.json) was additionally drift-invalidated
(mode orderings physically impossible: "rows" < 0 < "none"); re-run on
a quiet session before drawing tuning conclusions.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.ops import paulis as P

    n = 24
    LO = P._GATHER_LO_BITS
    rng = np.random.default_rng(0)
    res = {"n": n}
    KHI = 8
    T = 16

    def state():
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    def marginal(label, run_k, reps=5, khi=KHI):
        run_k(1)
        run_k(khi)
        t1s, tks = [], []
        for _ in range(reps):
            t1s.append(run_k(1))
            tks.append(run_k(khi))
        m = round((statistics.median(tks) - min(t1s)) / (khi - 1), 5)
        res[label] = m
        print(label, m, flush=True)

    angles = jnp.asarray(rng.normal(size=T))

    def scan_with_mask(mask_mode):
        """The real direct-rotation scan body, codes chosen so the flip
        mask hits only the requested axis."""
        if mask_mode == "none":
            codes = np.full((T, n), 3, np.int32)        # all Z: no flip
        elif mask_mode == "lanes":
            codes = np.zeros((T, n), np.int32)
            codes[:, :LO] = rng.integers(0, 2, size=(T, LO)) * 1  # X on lo
        elif mask_mode == "rows":
            codes = np.zeros((T, n), np.int32)
            codes[:, LO:] = rng.integers(0, 2, size=(T, n - LO)) * 1
        else:  # both
            codes = rng.integers(0, 4, size=(T, n)).astype(np.int32)
        cj = jnp.asarray(codes)

        def run_k(k):
            a = state()
            t0 = time.perf_counter()
            for _ in range(k):
                a = P.trotter_scan(a, cj, angles, num_qubits=n,
                                   rep_qubits=n)
            float(jnp.sum(a[0, :1]))
            return time.perf_counter() - t0

        return run_k

    for mode in ("none", "lanes", "rows", "both"):
        marginal(f"scan_flip_{mode}", scan_with_mask(mode))

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_gather_axes_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
