"""Window-pass cost with relay-overhead-free K-differencing.

A scalar fetch through the axon relay costs ~100 ms, so absolute chain
timings are dominated by it.  T(K2) - T(K1) cancels the fetch and the
dispatch, leaving (K2-K1) passes of pure device time.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from quest_tpu.ops import fused

N = 26
AMPS = 1 << N
BYTES_PER_PASS = 2 * 2 * 4 * AMPS
K1, K2 = 10, 40
REPS = 3


def rand_u(rng, d):
    m = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
    q, _ = np.linalg.qr(m)
    return np.stack([q.real, q.imag]).astype(np.float32)


def chain_fn(K, rank, apply_a, apply_b, precision, k):
    kwargs = dict(num_qubits=N, k=k, apply_a=apply_a, apply_b=apply_b,
                  precision=precision)

    @jax.jit
    def chain(a, ma, mb):
        for _ in range(K):
            a = fused.apply_window_stack(a, ma, mb, **kwargs)
        return a[0, 0]

    return chain


def bench(label, rank, apply_a=True, apply_b=True, precision="highest", k=7):
    rng = np.random.default_rng(0)
    ma = jnp.asarray(np.stack([rand_u(rng, 128) for _ in range(rank)]))
    mb = jnp.asarray(np.stack([rand_u(rng, 128) for _ in range(rank)]))
    a = jnp.zeros((2, AMPS), jnp.float32).at[0, 0].set(1.0)
    c1 = chain_fn(K1, rank, apply_a, apply_b, precision, k)
    c2 = chain_fn(K2, rank, apply_a, apply_b, precision, k)
    try:
        float(c1(a, ma, mb)); float(c2(a, ma, mb))  # compile+warm
        best = None
        for _ in range(REPS):
            t0 = time.perf_counter(); float(c1(a, ma, mb)); t1 = time.perf_counter() - t0
            t0 = time.perf_counter(); float(c2(a, ma, mb)); t2 = time.perf_counter() - t0
            dt = (t2 - t1) / (K2 - K1)
            best = dt if best is None else min(best, dt)
    except Exception as e:
        print(f"{label:40s} FAILED: {type(e).__name__}: {str(e)[:100]}")
        return None
    gbs = BYTES_PER_PASS / best / 1e9
    print(f"{label:40s} {best*1e3:7.2f} ms/pass  {gbs:7.1f} GB/s")
    return best


if __name__ == "__main__":
    print(f"backend={jax.default_backend()}  n={N}  diff K={K1}->{K2}, best of {REPS}")
    bench("rank1 A+B  highest", 1)
    bench("rank1 A+B  default", 1, precision="default")
    bench("rank1 B-only highest", 1, apply_a=False)
    bench("rank1 A-only highest", 1, apply_b=False)
    bench("rank2 A+B  highest", 2)
    bench("rank4 A+B  highest", 4)
    bench("rank2 A+B  default", 2, precision="default")
    bench("rank4 A+B  default", 4, precision="default")
    bench("rank1 A+B  highest k=13", 1, k=13)
    bench("rank1 A+B  highest k=19", 1, k=19)
