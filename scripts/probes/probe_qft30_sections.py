"""30q QFT section timing by large-K contrast ((T[4x]-T[1x])/3): where
do the 0.39-0.45 s go — the radix-4 high-layer sweeps, the cluster
pass, the low-fold window pass, or the in-place palindromic reversal?
"""

import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    print("devices:", jax.devices(), flush=True)

    from quest_tpu import circuit as C
    from quest_tpu.models.circuits import amp00_canonical, zero_state_canonical
    from quest_tpu.ops import fused

    n = 30
    res = {"n": n}
    KHI = 4

    def marginal(label, apply_once, reps=4):
        def run_k(k):
            a = zero_state_canonical(n)
            t0 = time.perf_counter()
            for _ in range(k):
                a = apply_once(a)
            float(amp00_canonical(a))  # layout-safe sync
            return time.perf_counter() - t0

        run_k(1)
        run_k(KHI)
        ds = []
        for _ in range(reps):
            t1 = run_k(1)
            t4 = run_k(KHI)
            ds.append((t4 - t1) / (KHI - 1))
        ds.sort()
        res[label] = {"median": round(ds[len(ds) // 2], 4),
                      "min": round(min(ds), 4)}
        print(label, res[label], flush=True)

    # whole QFT
    marginal("full_qft", lambda a: C.fused_qft(a, n, 0, n))

    # high layers only (radix-4 multi_hi sweeps, t = 29..14)
    def high_only(a):
        # the canonical 4-d view IS the (2, HI, 128, 128) shape the
        # kernel uses: pass it directly (an EAGER reshape would relayout
        # the whole 8 GB state -- the exact trap ops/element.py guards)
        return fused.apply_qft_multilayer_ladders(
            a, num_qubits=n, t_top=n - 1)

    marginal("high_plus_cluster", high_only)

    # reversal only (the in-place palindromic path: 4 window passes +
    # sigma_swap DMA)
    rev_ops = C.bit_reversal_ops(n, [(0, n)], np.float32)

    def rev_only(a):
        return C.execute_plan(a, rev_ops, n)

    marginal("bit_reversal", rev_only)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_qft30_sections_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
