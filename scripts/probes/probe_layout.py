"""Probe: does a flat (2, 2^n) state param force a full-state layout copy
at the jit boundary, and does a canonical (2, nb, 128, 128) param avoid it?

Uses compiled.memory_analysis() (temp bytes) at 26q, then steady timing.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as C
from quest_tpu.ops import fused, kernels

N = int(os.environ.get("QT_PROBE_QUBITS", "26"))


def log(**kw):
    print(json.dumps(kw), flush=True)


def main():
    log(devices=str(jax.devices()))
    rng = np.random.default_rng(0)

    def rand_soa(k):
        d = 1 << k
        z = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
        q, r = np.linalg.qr(z)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag]).astype(np.float32)

    a128 = C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None]
    b128 = C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None]
    nb = 1 << (N - 14)

    @partial(jax.jit, donate_argnums=0)
    def flat_pass(amps, ma, mb):
        return fused._apply_window_stack_jit(
            amps, ma, mb, num_qubits=N, k=14)

    @partial(jax.jit, donate_argnums=0)
    def canon_pass(amps4, ma, mb):
        out = fused._apply_window_stack_jit(
            amps4.reshape(2, -1), ma, mb, num_qubits=N, k=14)
        return out.reshape(2, nb, 128, 128)

    flat = jax.ShapeDtypeStruct((2, 1 << N), jnp.float32)
    canon = jax.ShapeDtypeStruct((2, nb, 128, 128), jnp.float32)
    m = jax.ShapeDtypeStruct((1, 2, 128, 128), jnp.float32)

    for name, fn, st in (("flat", flat_pass, flat), ("canon", canon_pass, canon)):
        t0 = time.perf_counter()
        comp = fn.lower(st, m, m).compile()
        cs = time.perf_counter() - t0
        ma = comp.memory_analysis()
        log(stage=f"{name} k=14 n={N}", compile_s=round(cs, 1),
            temp_mb=round(ma.temp_size_in_bytes / 1e6, 1),
            arg_mb=round(ma.argument_size_in_bytes / 1e6, 1),
            out_mb=round(ma.output_size_in_bytes / 1e6, 1),
            alias_mb=round(ma.alias_size_in_bytes / 1e6, 1))

    # steady-state timing comparison (K-diff style: 8 passes vs 4 passes)
    def chain(fn, st0, reps):
        a = st0
        for _ in range(reps):
            a = fn(a, jnp.asarray(a128), jnp.asarray(b128))
        return a

    for name, fn, shape in (("flat", flat_pass, (2, 1 << N)),
                            ("canon", canon_pass, (2, nb, 128, 128))):
        a = jnp.zeros(shape, jnp.float32)
        a = chain(fn, a, 2)
        a.block_until_ready()
        ts = []
        for reps in (4, 8, 4, 8, 4, 8):
            a = jnp.zeros(shape, jnp.float32)
            t0 = time.perf_counter()
            a = chain(fn, a, reps)
            a.block_until_ready()
            ts.append((reps, time.perf_counter() - t0))
        t4 = min(t for r, t in ts if r == 4)
        t8 = min(t for r, t in ts if r == 8)
        log(stage=f"{name} chained timing", per_pass_ms=round((t8 - t4) / 4 * 1e3, 2),
            t4=round(t4, 3), t8=round(t8, 3))


if __name__ == "__main__":
    main()
