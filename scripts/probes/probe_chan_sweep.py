import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time
import numpy as np, jax, jax.numpy as jnp
from quest_tpu.ops import fused as F

n, nn = 13, 26
prog = tuple(("depol", t, t + n) for t in range(n))
probs = tuple(0.05 for _ in range(n))
_init = jax.jit(lambda: jnp.full((2, 1 << nn), 0.001, jnp.float32))
def fresh():
    return _init()

MULT = 4
def sweep1(a):
    return F.apply_pair_channel_sweep(a, prog, probs, num_bits=nn)
def sweepN(a):
    for _ in range(1 + MULT):
        a = jax.lax.optimization_barrier(F.apply_pair_channel_sweep(a, prog, probs, num_bits=nn))
    return a

j1 = jax.jit(sweep1, donate_argnums=0)
jN = jax.jit(sweepN, donate_argnums=0)
t0=time.time(); float(np.asarray(j1(fresh())[0,0])); print(f"compile1 {time.time()-t0:.0f}s", flush=True)
t0=time.time(); float(np.asarray(jN(fresh())[0,0])); print(f"compileN {time.time()-t0:.0f}s", flush=True)
b1 = bN = 9e9
for _ in range(5):
    t0 = time.perf_counter(); float(np.asarray(j1(fresh())[0,0])); b1 = min(b1, time.perf_counter()-t0)
    t0 = time.perf_counter(); float(np.asarray(jN(fresh())[0,0])); bN = min(bN, time.perf_counter()-t0)
print(f"sweep 13ch block: {(bN-b1)/MULT*1e3:.2f} ms K-diff (1x {b1*1e3:.0f} ms)", flush=True)
