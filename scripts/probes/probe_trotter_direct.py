"""Probe an alternative Trotter-term formulation at 24q (VERDICT r5
item 1): instead of rotate-layer -> parity-phase -> unrotate-layer
(~6 window passes + 2 phases per term), apply the rotation DIRECTLY:

    e^{-i th/2 P} psi = cos(th/2) psi - i sin(th/2) (P psi)
    (P psi)[i] = c * s[i] * psi[i ^ flipmask]      (P^2 = I)

with s the +/-1 parity sign of the Z/Y mask and c = (-i)^{#Y}; the whole
term is ONE elementwise combine reading psi at i and i^flip — if the
dynamic-flip permutation is cheap.  Candidate flip implementations:

  a. flat dynamic gather  psi[iota ^ fm]          (XLA gather at 2^24)
  b. row/col split: (hi,lo) view, gather rows by iota_hi^fm_hi and
     lanes by iota_lo^fm_lo (two small index vectors, one take per axis)
  c. bit-serial: 24x where(bit_k(fm), flip_axis_k(psi), psi)

Also measured: per-pass cost of the existing window layer at 24q (is it
HBM-bound or overhead-bound at this size?), and a plain-XLA einsum layer
variant.
"""

import json
import os
import statistics
import sys
import time
from functools import partial

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    print("devices:", jax.devices(), flush=True)

    from quest_tpu.ops import paulis as P

    n = 24
    rng = np.random.default_rng(0)
    res = {"n": n}
    KHI = 8

    def state():
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    def marginal(label, run_k, reps=5, khi=KHI):
        run_k(1)
        run_k(khi)
        ds = []
        for _ in range(reps):
            t1 = run_k(1)
            tk = run_k(khi)
            ds.append((tk - t1) / (khi - 1))
        res[label] = {"median": round(statistics.median(ds), 5),
                      "min": round(min(ds), 5)}
        print(label, res[label], flush=True)

    T = 16
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))

    # ---- masks from codes (traced): flip = X|Y bits, par = Z|Y bits ----
    def term_masks(cd):
        fm = jnp.uint32(0)
        zlo = jnp.uint32(0)
        ny = jnp.uint32(0)
        for q in range(n):
            is_x = (cd[q] == 1).astype(jnp.uint32)
            is_y = (cd[q] == 2).astype(jnp.uint32)
            is_z = (cd[q] == 3).astype(jnp.uint32)
            fm = fm | ((is_x | is_y) << q)
            zlo = zlo | ((is_y | is_z) << q)
            ny = ny + is_y
        return fm, zlo, ny

    LO = 12
    HI = n - LO

    def direct_term_rowcol(a, cd, ang):
        """(hi, lo) split: flip rows via one hi-index take, lanes via one
        lo-index take."""
        fm, zm, ny = term_masks(cd)
        dt = a.dtype
        s = P._parity_sign_dynamic(zm, jnp.uint32(0), n, dt)
        # c = (-i)^{ny}: rotate (re,im) by ny*(-90deg)
        k = ny % 4
        c_re = jnp.where(k == 0, 1.0, jnp.where(k == 2, -1.0, 0.0)).astype(dt)
        c_im = jnp.where(k == 1, -1.0, jnp.where(k == 3, 1.0, 0.0)).astype(dt)
        idx_lo = jax.lax.iota(jnp.uint32, 1 << LO) ^ (fm & ((1 << LO) - 1))
        idx_hi = jax.lax.iota(jnp.uint32, 1 << HI) ^ (fm >> LO)
        v = a.reshape(2, 1 << HI, 1 << LO)
        pv = jnp.take(jnp.take(v, idx_hi, axis=1), idx_lo, axis=2)
        pv = pv.reshape(2, -1)
        # P psi = (c_re + i c_im) * s * pv  (elementwise complex)
        pr = s * (c_re * pv[0] - c_im * pv[1])
        pi = s * (c_re * pv[1] + c_im * pv[0])
        co = jnp.cos(0.5 * ang).astype(dt)
        si = jnp.sin(0.5 * ang).astype(dt)
        # out = cos*psi - i sin * (P psi)
        return jnp.stack([co * a[0] + si * pi, co * a[1] - si * pr])

    def direct_term_flat(a, cd, ang):
        fm, zm, ny = term_masks(cd)
        dt = a.dtype
        s = P._parity_sign_dynamic(zm, jnp.uint32(0), n, dt)
        k = ny % 4
        c_re = jnp.where(k == 0, 1.0, jnp.where(k == 2, -1.0, 0.0)).astype(dt)
        c_im = jnp.where(k == 1, -1.0, jnp.where(k == 3, 1.0, 0.0)).astype(dt)
        idx = jax.lax.iota(jnp.uint32, 1 << n) ^ fm
        pv = jnp.take(a, idx, axis=1)
        pr = s * (c_re * pv[0] - c_im * pv[1])
        pi = s * (c_re * pv[1] + c_im * pv[0])
        co = jnp.cos(0.5 * ang).astype(dt)
        si = jnp.sin(0.5 * ang).astype(dt)
        return jnp.stack([co * a[0] + si * pi, co * a[1] - si * pr])

    def direct_term_bitserial(a, cd, ang):
        fm, zm, ny = term_masks(cd)
        dt = a.dtype
        s = P._parity_sign_dynamic(zm, jnp.uint32(0), n, dt)
        k = ny % 4
        c_re = jnp.where(k == 0, 1.0, jnp.where(k == 2, -1.0, 0.0)).astype(dt)
        c_im = jnp.where(k == 1, -1.0, jnp.where(k == 3, 1.0, 0.0)).astype(dt)
        pv = a
        for q in range(n):
            flipped = jax.lax.rev(
                pv.reshape(2, 1 << (n - 1 - q), 2, 1 << q), (2,)
            ).reshape(2, -1)
            pv = jnp.where((fm >> q) & 1, flipped, pv)
        pr = s * (c_re * pv[0] - c_im * pv[1])
        pi = s * (c_re * pv[1] + c_im * pv[0])
        co = jnp.cos(0.5 * ang).astype(dt)
        si = jnp.sin(0.5 * ang).astype(dt)
        return jnp.stack([co * a[0] + si * pi, co * a[1] - si * pr])

    def scan_of(term_fn):
        @jax.jit
        def prog(a, cds, angs):
            def body(carry, inp):
                cd, ang = inp
                return term_fn(carry, cd, ang.astype(carry.dtype)), None
            out, _ = jax.lax.scan(body, a, (cds, angs))
            return out
        return prog

    # correctness vs trotter_scan first
    a0 = state()
    ref = P.trotter_scan(jnp.array(a0), codes, angles,
                         num_qubits=n, rep_qubits=n)
    for name, fn in [("rowcol", direct_term_rowcol),
                     ("flat", direct_term_flat)]:
        got = scan_of(fn)(jnp.array(a0), codes, angles)
        md = float(jnp.max(jnp.abs(got - ref)))
        res[f"maxdiff_{name}"] = md
        print(f"maxdiff_{name}: {md:.2e}", flush=True)

    # bitserial dropped: its 24 where(flip)-chained full-state
    # intermediates exceed HBM at compile (16.1G > 15.75G)
    for name, fn in [("rowcol", direct_term_rowcol),
                     ("flat", direct_term_flat)]:
        prog = scan_of(fn)

        def run_k(k, prog=prog):
            a = state()
            t0 = time.perf_counter()
            for _ in range(k):
                a = prog(a, codes, angles)
            float(jnp.sum(a[0, :1]))
            return time.perf_counter() - t0

        marginal(f"direct_{name}_T16", run_k)

    # ---- reference point: existing scan, same codes ----
    def run_scan(k):
        a = state()
        t0 = time.perf_counter()
        for _ in range(k):
            a = P.trotter_scan(a, codes, angles, num_qubits=n, rep_qubits=n)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("window_scan_T16", run_scan)

    # ---- plain-XLA einsum layer (no pallas) for comparison ----
    mats = jnp.asarray(rng.standard_normal((n, 2, 2, 2)).astype(np.float32))

    def einsum_layer(a, m):
        # contract qubits in 4 groups of 6: build 64x64 SoA mats by kron
        v = a.reshape((2,) + (64,) * 4)
        for g in range(4):
            acc_r = jnp.asarray(np.eye(1, dtype=np.float32))
            acc_i = jnp.zeros((1, 1), jnp.float32)
            for q in range(6 * g, 6 * g + 6):
                mr, mi = m[q, 0], m[q, 1]
                acc_r, acc_i = (jnp.kron(mr, acc_r) - jnp.kron(mi, acc_i),
                                jnp.kron(mr, acc_i) + jnp.kron(mi, acc_r))
            ax = 4 - g
            vr = jnp.moveaxis(v, ax, -1)
            rr = jnp.einsum("ij,...j->...i", acc_r, vr[0])
            ri = jnp.einsum("ij,...j->...i", acc_i, vr[0])
            ir = jnp.einsum("ij,...j->...i", acc_r, vr[1])
            ii = jnp.einsum("ij,...j->...i", acc_i, vr[1])
            v = jnp.moveaxis(jnp.stack([rr - ii, ri + ir]), -1, ax)
        return v.reshape(2, -1)

    @partial(jax.jit, static_argnames="k")
    def einsum_prog(a, m, k):
        for _ in range(k):
            a = einsum_layer(a, m)
        return a

    def run_einsum(k):
        a = state()
        t0 = time.perf_counter()
        a = einsum_prog(a, mats, k)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("einsum_layer_per_pass", run_einsum)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_trotter_direct_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
