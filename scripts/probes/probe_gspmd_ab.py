"""GSPMD vs explicit-collective A/B for the 1q sharded-target gate
(VERDICT r4 item 7 / SURVEY.md §7 layer 5: "benchmark both").

Representative op: a dense 1q gate on the TOP qubit of an n-qubit
register amplitude-sharded over an 8-device mesh — the simplest op whose
amplitude pairs straddle shards (the reference's exchangeStateVectors
case, QuEST_cpu_distributed.c:489-517).

A: explicit path — dist.apply_matrix_1q_sharded (shard_map, ONE
   hypercube ppermute, pinned by tests/test_distributed_hlo.py).
B: GSPMD path — the ordinary kernels.apply_matrix jitted with sharded
   in/out shardings; XLA's sharding propagation decides the collectives.

Measured on the virtual 8-device CPU mesh: the optimized-HLO collective
histogram + exchanged-byte estimate for both, plus wall-clock (CPU wall
is indicative only; the structural histogram is the durable evidence).
On the real chip, a 1-device mesh run checks both paths execute and
agree bitwise (a 1-mesh ppermute is the identity permutation).
"""

import json
import os
import re
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

_COLLECTIVE_OPS = (
    "all-reduce", "all-reduce-start", "collective-permute",
    "collective-permute-start", "all-gather", "all-gather-start",
    "all-to-all", "reduce-scatter",
)


def hist_of(txt):
    h = {}
    for op in _COLLECTIVE_OPS:
        c = txt.count(f" {op}(")
        if c:
            h[op] = h.get(op, 0) + c
    return h


def collective_bytes(txt):
    """Rough exchanged-data estimate: sum of output-shape elements of
    collective instructions (f32)."""
    total = 0
    for line in txt.splitlines():
        m = re.search(r"= (\S+)\[([\d,]*)\][^ ]* (?:all-to-all|all-gather|"
                      r"collective-permute|all-reduce)(?:-start)?\(", line)
        if m and m.group(2):
            elems = 1
            for d in m.group(2).split(","):
                elems *= int(d)
            total += elems * 4
    return total


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    res = {"backend": jax.default_backend()}

    import quest_tpu as qt
    from quest_tpu.ops import kernels
    from quest_tpu.parallel import dist as PAR

    env = qt.createQuESTEnv()
    ndev = env.num_ranks
    res["ndev"] = ndev
    n = 20 if not on_tpu else 24
    res["n"] = n

    h2 = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
    m = jnp.asarray(np.stack([h2, np.zeros((2, 2))]), jnp.float32)

    rng = np.random.default_rng(0)
    a_host = rng.standard_normal((2, 1 << n)).astype(np.float32)
    a_host /= np.sqrt((a_host ** 2).sum())
    amps = jax.device_put(jnp.asarray(a_host), env.amp_sharding())

    def explicit(a):
        if ndev == 1:
            # r=0: every target is local — the explicit layer routes the
            # ordinary kernel (the same reduction both paths share); the
            # chip run checks execution + agreement at that fixed point
            return kernels.apply_matrix(a, m, num_qubits=n,
                                        targets=(n - 1,))
        return PAR.apply_matrix_1q_sharded(
            a, m, mesh=env.mesh, num_qubits=n, target=n - 1)

    def gspmd(a):
        out = kernels.apply_matrix(a, m, num_qubits=n, targets=(n - 1,))
        return jax.lax.with_sharding_constraint(out, env.amp_sharding())

    jg = jax.jit(gspmd)

    if ndev > 1:
        txt_a = jax.jit(explicit).lower(amps).compile().as_text()
        txt_b = jg.lower(amps).compile().as_text()
        res["explicit_hlo"] = hist_of(txt_a)
        res["gspmd_hlo"] = hist_of(txt_b)
        res["explicit_bytes"] = collective_bytes(txt_a)
        res["gspmd_bytes"] = collective_bytes(txt_b)
        print("explicit:", res["explicit_hlo"], res["explicit_bytes"],
              "bytes", flush=True)
        print("gspmd:   ", res["gspmd_hlo"], res["gspmd_bytes"],
              "bytes", flush=True)

    # numerical agreement
    out_a = np.asarray(explicit(jax.device_put(jnp.asarray(a_host),
                                               env.amp_sharding())))
    out_b = np.asarray(jg(jax.device_put(jnp.asarray(a_host),
                                         env.amp_sharding())))
    res["maxdiff"] = float(np.max(np.abs(out_a - out_b)))
    print("maxdiff:", res["maxdiff"], flush=True)

    # wall per application (chained, single fetch) — INTERLEAVED t1/tk
    # pairs per rep, like bench.kdiff_stats: phase-separated baselines
    # let monotone chip drift between the phases corrupt the marginal
    # (the first version of this probe recorded a physically impossible
    # -0.496 s/op on the drifting chip that way)
    def wall(fn, reps=5, k=8):
        jfn = jax.jit(fn)

        def run_k(kk):
            a = jax.device_put(jnp.asarray(a_host), env.amp_sharding())
            t0 = time.perf_counter()
            for _ in range(kk):
                a = jfn(a)
            float(jnp.sum(a[0, :1]))
            return time.perf_counter() - t0

        run_k(1)
        run_k(k)
        t1s, tks = [], []
        for _ in range(reps):
            t1s.append(run_k(1))
            tks.append(run_k(k))
        return round((statistics.median(tks) - min(t1s)) / (k - 1), 5)

    res["explicit_wall_per_op"] = wall(explicit)
    res["gspmd_wall_per_op"] = wall(gspmd)
    print("wall explicit:", res["explicit_wall_per_op"],
          "gspmd:", res["gspmd_wall_per_op"], flush=True)

    suffix = "tpu" if on_tpu else "cpu"
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       f"probe_gspmd_ab_{suffix}.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    if os.environ.get("QT_AB_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    main()
