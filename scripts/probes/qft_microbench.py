"""Time the pieces of the fused QFT at 26q: ladder passes vs the final
bit-reversal permute. One-jit chain methodology."""
import os, sys, time
from functools import partial
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, numpy as np, jax.numpy as jnp
from quest_tpu.ops import kernels

N = 26
K = 8
nbytes = 2 * (1 << N) * 4

def timeit(label, prog, *args):
    s = kernels.init_zero_state(1 << N, np.float32)
    out = prog(s, *args); float(out)
    best = 1e9
    for _ in range(3):
        s = kernels.init_zero_state(1 << N, np.float32)
        float(np.asarray(s[0, 0]))
        t0 = time.perf_counter()
        out = prog(s, *args); float(out)
        best = min(best, (time.perf_counter() - t0) / K)
    print(f"{label}: {best*1e3:7.2f} ms/pass {2*nbytes/best/1e9:7.1f} GB/s",
          flush=True)

for t in (25, 19, 13, 7):
    @partial(jax.jit, donate_argnums=0)
    def lad(s, _t=t):
        for _ in range(K):
            s = kernels.apply_qft_ladder(s, num_qubits=N, target=_t)
        return s[0, 0]
    timeit(f"ladder t={t:2d}", lad)

perm = tuple(N - 1 - i for i in range(N))
@partial(jax.jit, donate_argnums=0)
def rev(s):
    for _ in range(K):
        s = kernels.permute_qubits(s, num_qubits=N, perm=perm)
    return s[0, 0]
timeit("bit-reversal permute", rev)

# swap-based alternative: 13 pairwise bit swaps
@partial(jax.jit, donate_argnums=0)
def swaps(s):
    for _ in range(K):
        for i in range(N // 2):
            s = kernels.swap_qubit_amps(s, num_qubits=N, qb1=i, qb2=N-1-i)
    return s[0, 0]
timeit("13 pairwise swaps  ", swaps)
