"""On-chip breakdown of the 26q fused QFT: where do the ~146 ms go?

Times each stage as a composable state->state program, K-differenced
(T[run twice] - T[run once] inside the same measurement discipline) so
the fixed relay fetch/dispatch overhead cancels.  Stages:

  - ladders: the 19 Pallas ladder layers (t = 25..7) chained
  - lowpass: the <=7-qubit dense window pass
  - reversal: bit_reversal_ops (3 window passes + 1 axis permute)
  - permute-only: just the group-order axis permutation
  - full: circuit.fused_qft monolithic under one jit (canonical in/out)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as CIRC
from quest_tpu.models import circuits
from quest_tpu.ops import kernels

N = int(os.environ.get("QT_N", "26"))
REPS = int(os.environ.get("QT_REPS", "5"))


def canon(n):
    return circuits.zero_state_canonical(n)


def kdiff(label, fn1, fn2):
    """min T[fn2] - min T[fn1] with a device fetch each, over REPS."""
    best1 = best2 = 1e9
    out = fn1(canon(N))
    float(np.asarray(jnp.sum(out[:1, :1, :1, :1])))  # warm compile 1
    out = fn2(canon(N))
    float(np.asarray(jnp.sum(out[:1, :1, :1, :1])))  # warm compile 2
    for _ in range(REPS):
        s = canon(N)
        t0 = time.perf_counter()
        out = fn1(s)
        float(np.asarray(jnp.sum(out[:1, :1, :1, :1])))
        best1 = min(best1, time.perf_counter() - t0)
        s = canon(N)
        t0 = time.perf_counter()
        out = fn2(s)
        float(np.asarray(jnp.sum(out[:1, :1, :1, :1])))
        best2 = min(best2, time.perf_counter() - t0)
    print(f"{label}: {(best2 - best1) * 1e3:8.2f} ms"
          f"   (1x {best1 * 1e3:7.2f}  2x {best2 * 1e3:7.2f})", flush=True)
    return best2 - best1


def ladders(a):
    for t in range(N - 1, 6, -1):
        a = kernels.apply_qft_ladder(a, num_qubits=N, target=t)
    return a


def lowpass(a):
    dt = np.float32
    dense = [CIRC.Gate(tuple(range(0, qq + 1)), CIRC._qft_layer_dense(qq, False, dt))
             for qq in range(6, -1, -1)]
    return CIRC.execute_plan(a, CIRC.plan_circuit(dense, N), N)


def reversal(a):
    ops = CIRC.bit_reversal_ops(N, [(0, N)], np.float32)
    return CIRC.execute_plan(a, ops, N)


def permute_only(a):
    ops = [op for op in CIRC.bit_reversal_ops(N, [(0, N)], np.float32)
           if op[0] == "permute"]
    return CIRC.execute_plan(a, ops, N)


def full(a):
    return CIRC.fused_qft(a, N, 0, N)


def ladder_one(a, t=20):
    return kernels.apply_qft_ladder(a, num_qubits=N, target=t)


def main():
    mult = int(os.environ.get("QT_MULT", "4"))

    def rep(stage, k):
        def f(a):
            for _ in range(k):
                a = stage(a)
            return a
        return f

    stages = [("ladders(19)", ladders), ("reversal", reversal),
              ("permute-only", permute_only), ("lad-t25", lambda a: ladder_one(a, 25)),
              ("lad-t20", lambda a: ladder_one(a, 20)),
              ("lad-t14", lambda a: ladder_one(a, 14)),
              ("lad-t10", lambda a: ladder_one(a, 10)),
              ("lad-t7", lambda a: ladder_one(a, 7)),
              ("full-mono", full)]
    for label, stage in stages:
        j1 = jax.jit(rep(stage, 1), donate_argnums=0)
        j2 = jax.jit(rep(stage, 1 + mult), donate_argnums=0)
        d = kdiff(label, j1, j2)
        print(f"   -> per-unit {d / mult * 1e3:7.2f} ms", flush=True)


if __name__ == "__main__":
    main()
