"""On-chip validation + timing of the multilayer QFT at 26q.

Correctness: multilayer vs per-layer fused path on the same random state
(both f32, same input), plus amp0 = 2^-n/2 self-check on |0>.
Timing: K-diff with QT_MULT extra reps (default 4).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as CIRC
from quest_tpu.models import circuits

N = int(os.environ.get("QT_N", "26"))
REPS = int(os.environ.get("QT_REPS", "5"))
MULT = int(os.environ.get("QT_MULT", "4"))


def main():
    os.environ.setdefault("QT_QFT_MULTILAYER", "1")

    def ml(a):
        return CIRC._fused_qft_multilayer(a, N, N, None)

    # correctness: multilayer vs per-layer on a small-but-canonical state
    nchk = min(N, 17)
    rng = np.random.default_rng(0)
    v = rng.standard_normal(1 << nchk) + 1j * rng.standard_normal(1 << nchk)
    v /= np.linalg.norm(v)
    soa = np.stack([v.real, v.imag]).astype(np.float32)
    out = np.asarray(CIRC._fused_qft_multilayer(
        jnp.asarray(soa), nchk, nchk, None))
    got = out[0] + 1j * out[1]
    want = np.fft.ifft(v, norm="ortho")
    print(f"{nchk}q on-chip multilayer vs ifft: "
          f"{np.abs(got - want).max():.3e}", flush=True)

    # amp0 self-check at N on |0>: QFT|0> has all amps = 2^-N/2
    z = circuits.zero_state_canonical(N)
    t0 = time.perf_counter()
    outz = jax.jit(ml, donate_argnums=0)(z)
    a0 = float(np.asarray(outz.reshape(2, -1)[0, 0]))
    print(f"{N}q compile+first: {time.perf_counter() - t0:.1f} s; "
          f"amp0 {a0:.6e} vs {2 ** (-N / 2):.6e}", flush=True)

    # K-diff timing
    j1 = jax.jit(ml, donate_argnums=0)

    def mlk(a):
        for _ in range(1 + MULT):
            a = ml(a)
        return a

    j2 = jax.jit(mlk, donate_argnums=0)
    best1 = best2 = 1e9
    out = j2(circuits.zero_state_canonical(N))
    float(np.asarray(out.reshape(2, -1)[0, 0]))
    for _ in range(REPS):
        s = circuits.zero_state_canonical(N)
        t0 = time.perf_counter()
        out = j1(s)
        float(np.asarray(out.reshape(2, -1)[0, 0]))
        best1 = min(best1, time.perf_counter() - t0)
        s = circuits.zero_state_canonical(N)
        t0 = time.perf_counter()
        out = j2(s)
        float(np.asarray(out.reshape(2, -1)[0, 0]))
        best2 = min(best2, time.perf_counter() - t0)
    d = (best2 - best1) / MULT
    print(f"{N}q multilayer QFT device (K-diff/{MULT}): {d * 1e3:.2f} ms"
          f"   (1x {best1 * 1e3:.2f}  {1 + MULT}x {best2 * 1e3:.2f})",
          flush=True)


if __name__ == "__main__":
    main()
