"""Measure window-pass cost on the real TPU vs precision/rank/side.

Methodology (memory: per-call device fetches through the axon relay are
ms-noisy): chain K identical passes inside ONE jit, fetch one scalar, and
divide.  Prints GB/s of effective HBM traffic per pass (read+write of the
2 x 4 x 2^n byte f32 SoA state) so the roofline gap is explicit.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from quest_tpu.ops import fused

N = 26
K = 20
AMPS = 1 << N
BYTES_PER_PASS = 2 * 2 * 4 * AMPS  # read + write, SoA f32


def rand_u(rng, d):
    m = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
    q, _ = np.linalg.qr(m)
    return np.stack([q.real, q.imag]).astype(np.float32)


def bench(label, rank, apply_a, apply_b, precision, k=7, block_amps=None):
    rng = np.random.default_rng(0)
    mats_a = np.stack([rand_u(rng, 128) for _ in range(rank)])
    mats_b = np.stack([rand_u(rng, 128) for _ in range(rank)])
    amps = np.zeros((2, AMPS), np.float32)
    amps[0, 0] = 1.0
    kwargs = dict(num_qubits=N, k=k, apply_a=apply_a, apply_b=apply_b,
                  precision=precision)
    if block_amps is not None:
        kwargs["block_amps"] = block_amps

    @jax.jit
    def chain(a, ma, mb):
        for _ in range(K):
            a = fused.apply_window_stack(a, ma, mb, **kwargs)
        return a[0, 0]

    a = jnp.asarray(amps)
    ma, mb = jnp.asarray(mats_a), jnp.asarray(mats_b)
    try:
        float(chain(a, ma, mb))  # compile + warm
        t0 = time.perf_counter()
        r = float(chain(a, ma, mb))
        dt = (time.perf_counter() - t0) / K
    except Exception as e:
        print(f"{label:44s} FAILED: {type(e).__name__}: {str(e)[:120]}")
        return
    gbs = BYTES_PER_PASS / dt / 1e9
    print(f"{label:44s} {dt*1e3:8.2f} ms/pass  {gbs:7.1f} GB/s  (check {r:.3e})")


if __name__ == "__main__":
    print(f"backend={jax.default_backend()}  n={N}  K={K} chained passes")
    for prec in ["highest", "high", "default"]:
        bench(f"rank1 A+B  {prec}", 1, True, True, prec)
    for prec in ["highest", "high", "default"]:
        bench(f"rank1 B-only {prec}", 1, False, True, prec)
    bench("rank1 A-only highest", 1, True, False, "highest")
    bench("rank1 A-only high", 1, True, False, "high")
    for prec in ["highest", "high"]:
        bench(f"rank2 A+B  {prec}", 2, True, True, prec)
        bench(f"rank4 A+B  {prec}", 4, True, True, prec)
    # window offset k=13 (strided DMA) to see relocation-free pass cost
    bench("rank1 A+B  high  k=13", 1, True, True, "high", k=13)
    bench("rank1 A+B  high  k=19", 1, True, True, "high", k=19)
    # bigger blocks at high (less scoped VMEM for temporaries?)
    bench("rank1 A+B  high  blocks=16", 1, True, True, "high",
          block_amps=16 * fused.BLOCK_AMPS)
