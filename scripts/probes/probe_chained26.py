"""Probe: real 26q bench circuit, chained executor vs monolithic numbers.

Reports compile wall, steady wall, K-diff device time per circuit.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as C
from quest_tpu.models import circuits
from quest_tpu.ops import calculations

N = int(os.environ.get("QT_PROBE_QUBITS", "26"))
DEPTH = 20


def log(**kw):
    print(json.dumps(kw), flush=True)


def main():
    log(devices=str(jax.devices()))
    fn, us = circuits.build_random_circuit(N, DEPTH, seed=7)
    us = np.asarray(us)
    cnot = np.zeros((2, 4, 4), np.float32)
    cnot[0] = np.array(
        [[1, 0, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0], [0, 1, 0, 0]], np.float32)
    gates = []
    for d in range(DEPTH):
        for q in range(N):
            gates.append(C.Gate((q,), us[d, q]))
        for q in range(d % 2, N - 1, 2):
            gates.append(C.Gate((q, q + 1), cnot))

    t0 = time.perf_counter()
    ops = C.plan_to_device(C.plan_circuit(gates, N), jnp.float32)
    log(plan_s=round(time.perf_counter() - t0, 2), passes=len(ops))

    nb = 1 << (N - 14)

    def fresh():
        return jnp.zeros((2, nb, 128, 128), jnp.float32).at[0, 0, 0, 0].set(1.0)

    def run(k=1):
        a = fresh()
        t0 = time.perf_counter()
        for _ in range(k):
            a = C.execute_plan_chained(a, ops, N)
        p = float(calculations.calc_prob_of_outcome_statevec(
            a, num_qubits=N, target=N - 1, outcome=0))
        return time.perf_counter() - t0, p

    t0 = time.perf_counter()
    _, p = run()
    log(stage="chained compile+first", s=round(time.perf_counter() - t0, 1), prob=p)

    t1s = [run(1)[0] for _ in range(5)]
    t2s = [run(2)[0] for _ in range(5)]
    log(stage="chained steady", wall_1x=round(min(t1s), 4),
        wall_2x=round(min(t2s), 4),
        kdiff_device_s=round(min(t2s) - min(t1s), 4),
        t1s=[round(t, 4) for t in t1s], t2s=[round(t, 4) for t in t2s],
        prob=p)


if __name__ == "__main__":
    main()
