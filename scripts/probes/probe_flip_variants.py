"""INTERLEAVED A/B of traced-XOR-flip implementations for the direct
Pauli rotation (paulis._flip_gather, currently take(rows) + take(lanes)
at a 12-bit split; ~2.2 ms/term quiet vs ~0.5 ms HBM floor).

Variants (each embedded in the same scan + rotation-combine structure so
the comparison is end-to-end per term):

  A. current: take(axis=rows 2^12) + take(axis=lanes 2^12)
  B. rows + mid + MXU lane permutation: view (2, 2^12, 2^5, 128);
     take rows (16 KB rows), take the 32-wide mid axis, then XOR the low
     7 lane bits by right-multiplying with a dynamically built 128x128
     0/1 permutation matrix (P[i, j] = [j == i ^ fm7]) — lane shuffles
     become one MXU pass instead of a 4096-wide lane gather.
  C. like B but lane bits via take on the 128 axis (isolates whether the
     wide lane gather in A is the cost).

Timing: interleaved per-rep rotation A->B->C->A->... with paired large-K
contrast per variant — RELATIVE ordering survives drift because every
variant samples every chip regime (the round-5 lesson: phase-separated
timings on this chip are meaningless).
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.ops import paulis as P

    n = 24
    rng = np.random.default_rng(0)
    res = {"n": n}
    T = 16
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))

    def state():
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    LO = 12
    MID = 5
    LANE = 7

    def flip_a(amps, fm_lo, fm_hi):
        return P._flip_gather(amps, fm_lo, fm_hi, n)

    def flip_b(amps, fm_lo, fm_hi):
        hi = n - LO
        v = amps.reshape(2, 1 << hi, 1 << MID, 128)
        idx_hi = jax.lax.iota(jnp.uint32, 1 << hi) ^ fm_hi
        v = jnp.take(v, idx_hi, axis=1)
        idx_mid = jax.lax.iota(jnp.uint32, 1 << MID) ^ (fm_lo >> LANE)
        v = jnp.take(v, idx_mid, axis=2)
        lane = jax.lax.iota(jnp.uint32, 128)
        perm = (lane[:, None] ^ (fm_lo & jnp.uint32(127))
                == lane[None, :]).astype(amps.dtype)
        v = jnp.matmul(v, perm, precision=jax.lax.Precision.HIGHEST)
        return v.reshape(2, -1)

    def flip_c(amps, fm_lo, fm_hi):
        hi = n - LO
        v = amps.reshape(2, 1 << hi, 1 << MID, 128)
        idx_hi = jax.lax.iota(jnp.uint32, 1 << hi) ^ fm_hi
        v = jnp.take(v, idx_hi, axis=1)
        idx_mid = jax.lax.iota(jnp.uint32, 1 << MID) ^ (fm_lo >> LANE)
        v = jnp.take(v, idx_mid, axis=2)
        idx_lane = jax.lax.iota(jnp.uint32, 128) ^ (fm_lo & jnp.uint32(127))
        v = jnp.take(v, idx_lane, axis=3)
        return v.reshape(2, -1)

    def scan_of(flip_fn):
        @jax.jit
        def prog(a, cds, angs):
            def body(carry, inp):
                cd, ang = inp
                dt = carry.dtype
                fm_lo, fm_hi, zlo, zhi, ny = P._direct_masks(cd, n, 0, n)
                s = P._parity_sign_dynamic(zlo, zhi, n, dt)
                c_re, c_im = P._iexp_factor(ny, dt)
                pv = flip_fn(carry, fm_lo, fm_hi)
                pr = s * (c_re * pv[0] - c_im * pv[1])
                pi = s * (c_re * pv[1] + c_im * pv[0])
                theta = jnp.where((fm_lo | fm_hi | zlo | zhi) == 0,
                                  jnp.asarray(0.0, dt), ang.astype(dt))
                co, si = jnp.cos(0.5 * theta), jnp.sin(0.5 * theta)
                out = jnp.stack([co * carry[0] + si * pi,
                                 co * carry[1] - si * pr])
                return out, None
            out, _ = jax.lax.scan(body, a, (cds, angs))
            return out
        return prog

    progs = {"A_take_take": scan_of(flip_a),
             "B_mxu_lane_perm": scan_of(flip_b),
             "C_take3": scan_of(flip_c)}

    # correctness: all three must match the production scan
    a0 = state()
    ref = P.trotter_scan(jnp.array(a0), codes, angles, num_qubits=n,
                         rep_qubits=n)
    for name, prog in progs.items():
        got = prog(jnp.array(a0), codes, angles)
        md = float(jnp.max(jnp.abs(got - ref)))
        res[f"maxdiff_{name}"] = md
        print(f"maxdiff_{name}: {md:.2e}", flush=True)
        assert md < 1e-6, (name, md)

    # interleaved timing: one (T1, T8) pair per variant per round
    KHI = 8
    ROUNDS = 5
    a_dev = state()

    def run_k(prog, k):
        a = jnp.array(a_dev)
        t0 = time.perf_counter()
        for _ in range(k):
            a = prog(a, codes, angles)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    for prog in progs.values():           # warm every program first
        run_k(prog, 1)
        run_k(prog, KHI)
    margs = {k: [] for k in progs}
    for _ in range(ROUNDS):
        for name, prog in progs.items():
            t1 = run_k(prog, 1)
            tk = run_k(prog, KHI)
            margs[name].append((tk - t1) / (KHI - 1))
    for name, ds in margs.items():
        res[name] = {"median": round(statistics.median(ds), 5),
                     "min": round(min(ds), 5)}
        print(name, res[name], flush=True)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_flip_variants_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
