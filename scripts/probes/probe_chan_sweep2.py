import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time
import numpy as np, jax, jax.numpy as jnp
from quest_tpu.ops import fused as F

nn = 26
rng = np.random.default_rng(1)
def fresh():
    return jnp.asarray(rng.standard_normal((2, 1 << nn)).astype(np.float32))

cases = {
    "sublane-only(6ch)": tuple(("depol", t, t + 13) for t in range(7, 13)),
    "lane-only(3ch)": tuple(("depol", t, t + 13) for t in range(1, 4)),
    "inblock-only(1ch)": (("depol", 0, 13),),
}
for name, prog in cases.items():
    probs = tuple(0.05 for _ in prog)
    j = jax.jit(lambda a, _p=prog, _pr=probs: F.apply_pair_channel_sweep(a, _p, _pr, num_bits=nn), donate_argnums=0)
    t0 = time.time(); float(np.asarray(j(fresh())[0, 0]))
    print(f"{name}: compile+1st {time.time()-t0:.0f}s", flush=True)
    b = 9e9
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(j(fresh())[0, 0])); b = min(b, time.perf_counter()-t0)
    print(f"{name}: wall {b*1e3:.0f} ms", flush=True)
