"""Drift-resistant config-5 decomposition: marginal device time via
large-K contrast ((T[8x] - T[1x]) / 7, best of reps) instead of the 2x
K-diff the chip's session drift swamped.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    print("devices:", jax.devices(), flush=True)
    from functools import partial

    from quest_tpu.ops import paulis as P

    n = 24
    rng = np.random.default_rng(0)
    res = {"n": n}

    def state():
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    KHI = 8

    def marginal(label, run_k, reps=5):
        run_k(1)
        run_k(KHI)
        ds = []
        for _ in range(reps):
            t1 = run_k(1)
            t8 = run_k(KHI)
            ds.append((t8 - t1) / (KHI - 1))
        ds.sort()
        res[label] = {"median": round(ds[len(ds) // 2], 5),
                      "min": round(min(ds), 5)}
        print(label, res[label], flush=True)

    T = 16
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))

    def run_scan(k):
        a = state()
        t0 = time.perf_counter()
        for _ in range(k):
            a = P.trotter_scan(a, codes, angles, num_qubits=n, rep_qubits=n)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("trotter_scan_T16_per_call", run_scan)

    mats = jnp.asarray(rng.standard_normal((n, 2, 2, 2)).astype(np.float32))

    @partial(jax.jit, static_argnames="k")
    def layer_prog(a, m, k):
        for _ in range(k):
            a = P._product_layer(a, m, n)
        return a

    def run_layer(k):
        a = state()
        t0 = time.perf_counter()
        a = layer_prog(a, mats, k)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("product_layer_per_pass", run_layer)

    @partial(jax.jit, static_argnames="k")
    def phase_prog(a, k):
        zlo = jnp.uint32(0x00AAAAAA)
        zhi = jnp.uint32(0)
        for _ in range(k):
            a = P._parity_phase_mask(a, jnp.float32(0.3), zlo, zhi, n)
        return a

    def run_phase(k):
        a = state()
        t0 = time.perf_counter()
        a = phase_prog(a, k)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("parity_phase_per_pass", run_phase)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_trotter2_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
