"""Pallas fused direct-rotation term vs the take-take XLA form
(INTERLEAVED A/B — see probe_flip_variants.py for the XLA-level sweep
this continues; take-take measured 0.076 s/16 terms, ~3x above the HBM
floor, with both alternative XLA formulations slower).

The Pallas kernel does the whole term in ONE HBM pass per block:
  out = cos*x + sin * s ⊙ ((-i)^{#Y} * x[i ^ fm])
with the XOR permutation decomposed as
  - block-level row XOR: the flip input's BlockSpec index_map reads
    block (i ^ (fm_row >> 8)) — pure DMA redirection, zero data cost;
  - in-block row XOR (8 bits): a 256x256 dynamically built 0/1
    permutation matmul (Mosaic has no rev lowering; MXU cost is trivial
    next to the DMA);
  - lane XOR (7 bits): one 128x128 dynamically built 0/1 permutation
    matmul on the MXU.
Parity signs factor as s_row (x) s_lane, precomputed OUTSIDE the kernel
(tiny vectors) so no popcount lowers inside Mosaic.
"""

import json
import os
import statistics
import sys
import time
from functools import partial

sys.path.insert(0, "/root/repo")


def build(n):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from quest_tpu.ops import paulis as P

    LANE = 7
    BR = 256                       # rows per block
    R = 1 << (n - LANE)

    def kernel(meta, fvals, x_ref, f_ref, srow_ref, slane_ref, out_ref):
        rb = meta[1]               # in-block row XOR (8 bits)
        fl = meta[2]               # lane XOR (7 bits)
        x = x_ref[...]             # (2, BR, 128)
        f = f_ref[...]
        # in-block row XOR as a 256x256 permutation matmul (Mosaic has
        # no rev lowering; the MXU cost is trivial next to the DMA)
        ri = lax.broadcasted_iota(jnp.int32, (BR, BR), 0)
        rj = lax.broadcasted_iota(jnp.int32, (BR, BR), 1)
        prow = ((ri ^ rb) == rj).astype(x.dtype)
        f = jnp.concatenate([
            jnp.dot(prow, f[0], preferred_element_type=x.dtype,
                    precision=lax.Precision.HIGHEST)[None],
            jnp.dot(prow, f[1], preferred_element_type=x.dtype,
                    precision=lax.Precision.HIGHEST)[None],
        ])
        li = lax.broadcasted_iota(jnp.int32, (128, 128), 0)
        lj = lax.broadcasted_iota(jnp.int32, (128, 128), 1)
        perm = ((li ^ fl) == lj).astype(x.dtype)
        pv = jnp.dot(f.reshape(2 * BR, 128), perm,
                     preferred_element_type=x.dtype,
                     precision=lax.Precision.HIGHEST).reshape(2, BR, 128)
        s = srow_ref[...][:, 0][None, :, None] * slane_ref[...][0][None, None, :]
        co = fvals[0, 0]
        si = fvals[0, 1]
        c_re = fvals[0, 2]
        c_im = fvals[0, 3]
        pr = s[0] * (c_re * pv[0] - c_im * pv[1])
        pi = s[0] * (c_re * pv[1] + c_im * pv[0])
        out_ref[0, :, :] = co * x[0] + si * pi
        out_ref[1, :, :] = co * x[1] - si * pr

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R // BR,),
        in_specs=[
            pl.BlockSpec((1, 4), lambda i, meta: (0, 0)),
            pl.BlockSpec((2, BR, 128), lambda i, meta: (0, i, 0)),
            pl.BlockSpec((2, BR, 128), lambda i, meta: (0, i ^ meta[0], 0)),
            pl.BlockSpec((BR, 1), lambda i, meta: (i, 0)),
            pl.BlockSpec((1, 128), lambda i, meta: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, BR, 128), lambda i, meta: (0, i, 0)),
    )

    def term(amps, cd, ang):
        import numpy as np

        dt = amps.dtype
        fm_lo, fm_hi, zlo, zhi, ny = P._direct_masks(cd, n, 0, n)
        fm = fm_lo.astype(jnp.uint32) | (fm_hi << P._GATHER_LO_BITS if
                                         n > P._GATHER_LO_BITS else 0)
        # recombine then re-split for the kernel's (block, inblock, lane)
        fm_lane = (fm & jnp.uint32(127)).astype(jnp.int32)
        fm_row = (fm >> 7).astype(jnp.int32)
        meta = jnp.stack([fm_row >> 8, fm_row & 255, fm_lane])
        s_full = P._parity_sign_dynamic(zlo, zhi, n, dt)
        # parity factorises: s(r*128 + l) = s_row(r) * s_lane(l)
        s_lane = lax.dynamic_slice(s_full, (0,), (128,)).reshape(1, 128)
        s_row = s_full.reshape(R, 128)[:, :1]  # value at lane 0 per row
        theta = jnp.where((fm_lo | fm_hi | zlo | zhi) == 0,
                          jnp.asarray(0.0, dt), ang.astype(dt))
        c_re, c_im = P._iexp_factor(ny, dt)
        fvals = jnp.stack([jnp.cos(0.5 * theta), jnp.sin(0.5 * theta),
                           c_re, c_im]).reshape(1, 4)
        view = amps.reshape(2, R, 128)
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        )(meta, fvals, view, view, s_row, s_lane)
        return out.reshape(amps.shape)

    @jax.jit
    def prog(a, cds, angs):
        def body(carry, inp):
            cd, ang = inp
            return term(carry, cd, ang), None
        out, _ = jax.lax.scan(body, a, (cds, angs))
        return out

    return prog


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.ops import paulis as P

    n = 24
    rng = np.random.default_rng(0)
    res = {"n": n}
    T = 16
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))
    a0 = rng.standard_normal((2, 1 << n)).astype(np.float32)
    a0 /= np.sqrt((a0 ** 2).sum())
    a_dev = jnp.asarray(a0)

    prog_pl = build(n)
    ref = P.trotter_scan(jnp.array(a_dev), codes, angles, num_qubits=n,
                         rep_qubits=n)
    got = prog_pl(jnp.array(a_dev), codes, angles)
    md = float(jnp.max(jnp.abs(got - ref)))
    res["maxdiff_pallas"] = md
    print(f"maxdiff_pallas: {md:.2e}", flush=True)
    assert md < 1e-6, md

    def run_take(k):
        a = jnp.array(a_dev)
        t0 = time.perf_counter()
        for _ in range(k):
            a = P.trotter_scan(a, codes, angles, num_qubits=n,
                               rep_qubits=n)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    def run_pl(k):
        a = jnp.array(a_dev)
        t0 = time.perf_counter()
        for _ in range(k):
            a = prog_pl(a, codes, angles)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    K = 8
    for f in (run_take, run_pl):
        f(1)
        f(K)
    m_take, m_pl = [], []
    for _ in range(5):
        t1 = run_take(1); tk = run_take(K)
        m_take.append((tk - t1) / (K - 1))
        t1 = run_pl(1); tk = run_pl(K)
        m_pl.append((tk - t1) / (K - 1))
    res["take_take"] = {"median": round(statistics.median(m_take), 5),
                        "min": round(min(m_take), 5)}
    res["pallas_fused"] = {"median": round(statistics.median(m_pl), 5),
                           "min": round(min(m_pl), 5)}
    print("take_take:", res["take_take"], flush=True)
    print("pallas_fused:", res["pallas_fused"], flush=True)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_flip_pallas_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
