"""Config-5 decomposition (VERDICT r4 item 1): where do the bench's
0.57-0.62 s per-iteration K-diffs go, when the drift-resistant large-K
marginal of the trotter scan alone is ~0.106 s?

Suspects, measured separately via large-K contrast ((T[Kx]-T[1x])/(K-1),
median of reps):

  A. full bench iteration through the public API (calcExpecPauliHamil,
     which float()s the result -> one relay round-trip PER iteration,
     + applyTrotterCircuit, which rebuilds + re-uploads the (32,24)
     codes table and (32,) angles host->device PER call)
  B. applyTrotterCircuit alone (API, host schedule + H2D per call)
  C. calcExpecPauliHamil alone (API, float() sync per call)
  D. device truth: ONE jitted [expec + trotter] program per iteration,
     value kept on device, single fetch at the end
  E. trotter_scan jitted entry alone at the bench schedule shape (32,24)
  F. expec_pauli_sum_scan jitted entry alone at (16,24)
  G. the bare relay fetch: float() of an already-computed scalar

If A >> D + G, the bench form (per-iteration sync + per-call H2D) is the
artifact, not kernel time.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    print("devices:", jax.devices(), flush=True)

    import quest_tpu as qt
    from quest_tpu.api_ops import _trotter_schedule
    from quest_tpu.ops import paulis as P

    env = qt.createQuESTEnv()
    n, terms = 24, 16
    rng = np.random.default_rng(7)
    hamil = qt.createPauliHamil(n, terms)
    qt.initPauliHamil(hamil, rng.standard_normal(terms),
                      rng.integers(0, 4, size=(terms, n)))

    res = {"n": n, "terms": terms}
    KHI = 8

    def marginal(label, run_k, reps=5, khi=KHI):
        run_k(1)
        run_k(khi)
        ds = []
        for _ in range(reps):
            t1 = run_k(1)
            tk = run_k(khi)
            ds.append((tk - t1) / (khi - 1))
        res[label] = {"median": round(statistics.median(ds), 5),
                      "min": round(min(ds), 5),
                      "max": round(max(ds), 5)}
        print(label, res[label], flush=True)

    # --- A: full bench iteration (public API, float per iteration) ---
    def run_bench(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            qt.calcExpecPauliHamil(psi, hamil)
            qt.applyTrotterCircuit(psi, hamil, 0.1, 2, 1)
        return time.perf_counter() - t0

    marginal("A_api_full_iter", run_bench)

    # --- B: applyTrotterCircuit alone ---
    def run_trotter_api(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            qt.applyTrotterCircuit(psi, hamil, 0.1, 2, 1)
        qt.calcTotalProb(psi)
        return time.perf_counter() - t0

    marginal("B_api_trotter_only", run_trotter_api)

    # --- C: calcExpecPauliHamil alone (state fixed) ---
    def run_expec_api(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            qt.calcExpecPauliHamil(psi, hamil)
        return time.perf_counter() - t0

    marginal("C_api_expec_only", run_expec_api)

    # --- D: device truth, one jitted [expec+trotter] per iter, no sync ---
    seq = _trotter_schedule(terms, 0.1, 2, 1)
    t_idx = np.asarray([t for t, _ in seq])
    facs = np.asarray([f for _, f in seq])
    codes_tr = jnp.asarray(
        np.asarray(hamil.pauli_codes)[t_idx].astype(np.int32))
    angles_tr = jnp.asarray(
        2.0 * facs * np.asarray(hamil.term_coeffs, np.float64)[t_idx])
    codes_ex = jnp.asarray(np.asarray(hamil.pauli_codes, np.int32))
    coeffs_ex = jnp.asarray(np.asarray(hamil.term_coeffs, np.float64))
    print("trotter schedule len:", len(seq), flush=True)
    res["schedule_len"] = len(seq)

    from quest_tpu.ops import kernels

    def state():
        a = kernels.init_plus_state(1 << n, np.float32)
        return jnp.asarray(a)

    def run_device(k):
        a = state()
        es = []
        t0 = time.perf_counter()
        for _ in range(k):
            es.append(P.expec_pauli_sum_scan(a, codes_ex, coeffs_ex,
                                             num_qubits=n))
            a = P.trotter_scan(a, codes_tr, angles_tr,
                               num_qubits=n, rep_qubits=n)
        float(es[-1])
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("D_device_expec_plus_trotter", run_device)

    # --- E: trotter_scan alone, bench schedule shape ---
    def run_tscan(k):
        a = state()
        t0 = time.perf_counter()
        for _ in range(k):
            a = P.trotter_scan(a, codes_tr, angles_tr,
                               num_qubits=n, rep_qubits=n)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    marginal("E_trotter_scan_sched32", run_tscan)

    # --- F: expec scan alone ---
    def run_escan(k):
        a = state()
        e = None
        t0 = time.perf_counter()
        for _ in range(k):
            e = P.expec_pauli_sum_scan(a, codes_ex, coeffs_ex, num_qubits=n)
        float(e)
        return time.perf_counter() - t0

    marginal("F_expec_scan_T16", run_escan)

    # --- G: bare relay fetch of a ready scalar ---
    s = jnp.sum(state()[0, :4])
    s.block_until_ready()
    fs = []
    for _ in range(10):
        t0 = time.perf_counter()
        float(s)
        fs.append(time.perf_counter() - t0)
    res["G_ready_scalar_fetch"] = {
        "median": round(statistics.median(fs), 5), "min": round(min(fs), 5)}
    print("G_ready_scalar_fetch", res["G_ready_scalar_fetch"], flush=True)

    # host-side schedule+convert cost in applyTrotterCircuit (no dispatch)
    ts = []
    for _ in range(20):
        t0 = time.perf_counter()
        seq2 = _trotter_schedule(terms, 0.1, 2, 1)
        ti = np.asarray([t for t, _ in seq2])
        fc = np.asarray([f for _, f in seq2])
        cs = np.asarray(hamil.pauli_codes)[ti].astype(np.int32)
        an = 2.0 * fc * np.asarray(hamil.term_coeffs, np.float64)[ti]
        jnp.asarray(cs).block_until_ready()
        jnp.asarray(an).block_until_ready()
        ts.append(time.perf_counter() - t0)
    res["H_host_schedule_plus_h2d"] = {
        "median": round(statistics.median(ts), 5), "min": round(min(ts), 5)}
    print("H_host_schedule_plus_h2d", res["H_host_schedule_plus_h2d"],
          flush=True)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_config5_decomp_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
