"""Probe: per-program compile costs at 30q on the real chip.

E1: one window pass (k=14) as its own jitted program (chained-execution unit)
E2: lax.scan over stacked pass tables (2-pass body, 10 iterations)
E3: one QFT ladder pass (target=25)
E4: calc_prob_of_outcome at 30q

Each stage prints a JSON line with compile seconds and steady wall.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from quest_tpu import circuit as C
from quest_tpu.ops import calculations, fused, kernels

N = int(os.environ.get("QT_PROBE_QUBITS", "30"))


def log(**kw):
    print(json.dumps(kw), flush=True)


def fresh():
    return jnp.asarray(kernels.init_zero_state(1 << N, np.float32))


def main():
    t0 = time.perf_counter()
    log(devices=str(jax.devices()), init_s=round(time.perf_counter() - t0, 1))

    rng = np.random.default_rng(0)

    def rand_soa(k):
        d = 1 << k
        z = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
        q, r = np.linalg.qr(z)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag]).astype(np.float32)

    a128 = C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None]
    b128 = C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None]

    # E1: one window pass k=14, standalone jit (already a jit in fused.py)
    amps = fresh()
    t0 = time.perf_counter()
    amps = fused.apply_window_stack(amps, a128, b128, num_qubits=N, k=14)
    amps.block_until_ready()
    c1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    amps = fused.apply_window_stack(amps, a128, b128, num_qubits=N, k=14)
    amps.block_until_ready()
    w1 = time.perf_counter() - t0
    log(stage="E1 window k=14", compile_s=round(c1, 1), steady_s=round(w1, 3))

    # E1b: second distinct k (k=20) — incremental compile cost of one more sig
    t0 = time.perf_counter()
    amps = fused.apply_window_stack(amps, a128, b128, num_qubits=N, k=20)
    amps.block_until_ready()
    c1b = time.perf_counter() - t0
    log(stage="E1b window k=20", compile_s=round(c1b, 1))

    # E2: scan over stacked tables: body = 2 window passes (k=7, k=14)
    P = 10
    As = jnp.asarray(np.repeat(a128[None], P, axis=0))
    Bs = jnp.asarray(np.repeat(b128[None], P, axis=0))

    @partial(jax.jit, donate_argnums=0)
    def scan_prog(amps, As, Bs):
        def body(a, xs):
            aa, bb = xs
            a = fused.apply_window_stack(a, aa, bb, num_qubits=N, k=7)
            a = fused.apply_window_stack(a, aa, bb, num_qubits=N, k=14)
            return a, None
        a, _ = jax.lax.scan(body, amps, (As, Bs))
        return a

    t0 = time.perf_counter()
    amps = scan_prog(amps, As, Bs)
    amps.block_until_ready()
    c2 = time.perf_counter() - t0
    t0 = time.perf_counter()
    amps = scan_prog(amps, As, Bs)
    amps.block_until_ready()
    w2 = time.perf_counter() - t0
    log(stage="E2 scan 10x(k7+k14)", compile_s=round(c2, 1), steady_s=round(w2, 3),
        per_pass_ms=round(w2 / (2 * P) * 1e3, 1))

    # E3: QFT ladder target=25
    t0 = time.perf_counter()
    amps = fused.apply_qft_ladder_pallas(amps, num_qubits=N, target=25)
    amps.block_until_ready()
    c3 = time.perf_counter() - t0
    t0 = time.perf_counter()
    amps = fused.apply_qft_ladder_pallas(amps, num_qubits=N, target=25)
    amps.block_until_ready()
    w3 = time.perf_counter() - t0
    log(stage="E3 qft ladder t=25", compile_s=round(c3, 1), steady_s=round(w3, 3))

    # E4: prob reduction
    t0 = time.perf_counter()
    p = float(calculations.calc_prob_of_outcome_statevec(
        amps, num_qubits=N, target=N - 1, outcome=0))
    c4 = time.perf_counter() - t0
    log(stage="E4 calc_prob", compile_s=round(c4, 1), prob=p)


if __name__ == "__main__":
    main()
