"""Microbenchmark of individual HBM-pass kernels on the real TPU.

Times one pass of each kernel flavor at 26 qubits to find where the
headline circuit's 91 passes spend their time, and prototypes an
"offset-window" cluster kernel whose sublane cluster sits at an arbitrary
contiguous bit window [k, k+7) — a zero-copy alternative to segswap
relocation (the BlockSpec views the strided rows directly).
"""

import sys
import os
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
import jax
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from quest_tpu.ops import fused, kernels

N = int(os.environ.get("QT_MB_QUBITS", "26"))
REPS = 5
DIM = fused.CLUSTER_DIM
LANE = fused.LANE_QUBITS


CHAIN = 8


def timeit(fn, state):
    """Per-pass time of a donating state->state kernel: chain CHAIN calls,
    fetch one element (forces completion through the relay), subtract the
    measured fetch round-trip, divide."""
    s = fn(state)            # compile + first run
    float(s[0, 0])
    t0 = time.perf_counter()
    float(s[0, 0])
    roundtrip = time.perf_counter() - t0
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(CHAIN):
            s = fn(s)
        float(s[0, 0])
        times.append((time.perf_counter() - t0 - roundtrip) / CHAIN)
    return max(min(times), 1e-9)


# ---------------------------------------------------------------------------
# offset-window prototype: sublane cluster at bits [k, k+7), lane at [0,7)
# ---------------------------------------------------------------------------


def _offset_kernel(rank, apply_a):
    def kernel(a_ref, ma_ref, mb_ref, o_ref):
        x = a_ref[...]                   # (2, 1, 128, 1, 128)
        xr, xi = x[0, :, :, 0], x[1, :, :, 0]    # (1, 128, 128)
        xc0 = jnp.concatenate([xr, xi], axis=-1)
        acc = None
        for r in range(rank):
            if apply_a:
                xc = jax.lax.dot_general(
                    xc0, ma_ref[r],
                    dimension_numbers=(((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
            else:
                xc = xc0
            yr, yi = xc[..., :DIM], xc[..., DIM:]
            yc = jnp.concatenate([yr, yi], axis=1)       # (1, 256, 128)
            out = jax.lax.dot_general(
                mb_ref[r], yc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )                                            # (256, 1, 128)
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)                    # (1, 256, 128)
        out = jnp.stack([acc[:, :DIM], acc[:, DIM:]], axis=0)
        o_ref[...] = out.reshape(2, 1, DIM, 1, DIM)

    return kernel


@partial(jax.jit, static_argnames=("num_qubits", "k", "apply_a"),
         donate_argnums=0)
def apply_offset_cluster(amps, mats_a, mats_b, *, num_qubits, k, apply_a=True):
    """Cluster pass with lane cluster on bits [0,7) and sublane cluster on
    bits [k, k+7), any 7 <= k <= n-7. No data relocation: the view
    (2, hi, 128, mid, 128) exposes the window as the sublane axis."""
    n = num_qubits
    rank = mats_a.shape[0]
    hi = 1 << (n - k - 7)
    mid = 1 << (k - 7)
    ma = jax.vmap(fused.lane_real_rep)(jnp.asarray(mats_a, amps.dtype))
    mb = jax.vmap(fused.sublane_real_rep)(jnp.asarray(mats_b, amps.dtype))
    view = amps.reshape(2, hi, DIM, mid, DIM)
    out = pl.pallas_call(
        _offset_kernel(rank, apply_a),
        grid=(hi, mid),
        in_specs=[
            pl.BlockSpec((2, 1, DIM, 1, DIM), lambda i, j: (0, i, 0, j, 0)),
            pl.BlockSpec((rank, 2 * DIM, 2 * DIM), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((rank, 2 * DIM, 2 * DIM), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, 1, DIM, 1, DIM),
                               lambda i, j: (0, i, 0, j, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
        interpret=jax.default_backend() != "tpu",
    )(view, ma, mb)
    return out.reshape(2, -1)


def fresh_state():
    return kernels.init_zero_state(1 << N, np.float32)


def rand_cluster(rank, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((rank, 2, DIM, DIM)), jnp.float32)


def main():
    nbytes = 2 * (1 << N) * 4
    print(f"N={N}: state {nbytes/2**30:.2f} GiB, pass traffic "
          f"{2*nbytes/2**30:.2f} GiB (r+w)")

    results = {}

    def rec(name, t):
        results[name] = t
        print(f"{name:28s} {t*1e3:8.2f} ms {2*nbytes/t/1e9:8.1f} GB/s", flush=True)

    for rank in (1, 2, 4):
        a, b = rand_cluster(rank, 1), rand_cluster(rank, 2)
        amps = fresh_state()
        f = partial(fused.apply_cluster_stack, num_qubits=N)
        t = timeit(lambda s: f(s, a, b), amps)
        rec(f"cluster rank{rank}", t)

    # swapfused m=3
    for rank in (1, 4):
        a, b = rand_cluster(rank, 3), rand_cluster(rank, 4)
        amps = fresh_state()
        t = timeit(
            lambda s: fused.apply_swap_cluster_stack(
                s, a, b, num_qubits=N, h=N - 3, b=7, m=3), amps)
        rec(f"swapfused m=3 rank{rank}", t)

    # standalone segswap m=7
    amps = fresh_state()
    t = timeit(lambda s: kernels.swap_bit_segments(
        s, num_qubits=N, a=N - 7, b=7, m=7), amps)
    rec("segswap m=7", t)

    # offset window at several k
    for k in (7, 13, N - 7):
        for rank in (1, 2, 4):
            a, b = rand_cluster(rank, 5), rand_cluster(rank, 6)
            amps = fresh_state()
            t = timeit(
                lambda s: apply_offset_cluster(
                    s, a, b, num_qubits=N, k=k), amps)
            rec(f"offset k={k} rank{rank}", t)
        # B-only variant (lane identity skipped)
        a, b = rand_cluster(1, 7), rand_cluster(1, 8)
        amps = fresh_state()
        t = timeit(
            lambda s: apply_offset_cluster(
                s, a, b, num_qubits=N, k=k, apply_a=False), amps)
        rec(f"offset k={k} B-only", t)




if __name__ == "__main__":
    main()
