import os, sys, time
from functools import partial
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, numpy as np
from quest_tpu.ops import kernels
N = 26
nbytes = 2 * (1 << N) * 4

def t1(label, fn):
    s = kernels.init_zero_state(1 << N, np.float32)
    s = fn(s); float(np.asarray(s[0, 0]))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        s = fn(s); float(np.asarray(s[0, 0]))
        best = min(best, time.perf_counter() - t0)
    print(f"{label}: {best*1e3:7.2f} ms {2*nbytes/best/1e9:7.1f} GB/s", flush=True)

perm = tuple(N - 1 - i for i in range(N))
t1("bit-reversal permute", lambda s: kernels.permute_qubits(s, num_qubits=N, perm=perm))
for t in (25, 19, 13, 7):
    t1(f"ladder t={t:2d}", lambda s, _t=t: kernels.apply_qft_ladder(s, num_qubits=N, target=_t))
t1("swap(0,25)", lambda s: kernels.swap_qubit_amps(s, num_qubits=N, qb1=0, qb2=25))
