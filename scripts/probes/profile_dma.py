"""Find the achievable HBM roofline for the window-pass access pattern.

Compares: XLA elementwise (x*2) on the flat SoA array; a Pallas copy-only
kernel with the window block specs; copy with different block sizes; and
the B-only matmul kernel — to separate DMA cost from compute cost.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, "/root/repo")

from quest_tpu.ops import fused

N = 26
K = 20
AMPS = 1 << N
BYTES_PER_PASS = 2 * 2 * 4 * AMPS
C = 128


def timed(label, chain, *args):
    try:
        float(chain(*args))
        t0 = time.perf_counter()
        r = float(chain(*args))
        dt = (time.perf_counter() - t0) / K
    except Exception as e:
        print(f"{label:52s} FAILED: {type(e).__name__}: {str(e)[:100]}")
        return
    print(f"{label:52s} {dt*1e3:8.2f} ms/pass  {BYTES_PER_PASS/dt/1e9:7.1f} GB/s")


def copy_kernel(a_ref, o_ref):
    o_ref[...] = a_ref[...]


def scale_kernel(a_ref, o_ref):
    o_ref[...] = a_ref[...] * 2.0


def make_pallas_chain(kernel, R, alias, donate=True):
    hi = AMPS // (C * C)

    def one(a):
        view = a.reshape(2, hi, C, C)
        out = pl.pallas_call(
            kernel,
            grid=(hi // R,),
            in_specs=[pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0))],
            out_specs=pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
            input_output_aliases={0: 0} if alias else {},
        )(view)
        return out.reshape(2, -1)

    @jax.jit
    def chain(a):
        for _ in range(K):
            a = one(a)
        return a[0, 0]

    return chain


def make_xla_chain(f):
    @jax.jit
    def chain(a):
        for _ in range(K):
            a = f(a)
        return a[0, 0]

    return chain


if __name__ == "__main__":
    print(f"backend={jax.default_backend()}  n={N}")
    amps = np.zeros((2, AMPS), np.float32)
    amps[0, 0] = 1.0
    a = jnp.asarray(amps)

    timed("XLA x*0.5 elementwise", make_xla_chain(lambda x: x * 0.5), a)
    x4 = a.reshape(2, AMPS // (C * C), C, C)
    timed("XLA x*0.5 on 4-d view",
          make_xla_chain(lambda x: x * 0.5), x4)
    for R in (4, 8, 16, 32, 64):
        timed(f"pallas copy R={R} aliased", make_pallas_chain(copy_kernel, R, True), a)
    for R in (8, 32):
        timed(f"pallas copy R={R} no-alias", make_pallas_chain(copy_kernel, R, False), a)
    for R in (8, 32):
        timed(f"pallas x2  R={R} aliased", make_pallas_chain(scale_kernel, R, True), a)
