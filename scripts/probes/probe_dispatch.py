"""Probe: true per-pass device cost per signature, K-differenced around a
host fetch (the relay acks block_until_ready at enqueue; only a fetch
syncs)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as C
from quest_tpu.ops import fused

N = int(os.environ.get("QT_PROBE_QUBITS", "26"))


def log(**kw):
    print(json.dumps(kw), flush=True)


def main():
    log(devices=str(jax.devices()))
    rng = np.random.default_rng(0)

    def rand_soa(k):
        d = 1 << k
        z = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
        q, r = np.linalg.qr(z)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag]).astype(np.float32)

    a128 = jnp.asarray(C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None])
    b128 = jnp.asarray(C.embed_in_cluster(rand_soa(7), tuple(range(7)))[None])
    mask = jnp.asarray(np.stack([np.ones((128, 128)), np.zeros((128, 128))])
                       .astype(np.float32))
    nb = 1 << (N - 14)

    def fresh():
        return jnp.zeros((2, nb, 128, 128), jnp.float32).at[0, 0, 0, 0].set(1.0)

    def run(ks, reps, masked=False, b_only=False):
        a = fresh()
        t0 = time.perf_counter()
        for _ in range(reps):
            for k in ks:
                a = fused.apply_window_stack(
                    a, a128, b128, mask if masked else None,
                    num_qubits=N, k=k, apply_a=not b_only)
        float(a[0, 0, 0, 0])  # fetch = the only reliable sync
        return time.perf_counter() - t0

    def kdiff(name, ks, r1, r2, **kw):
        run(ks, 1, **kw)  # compile warm
        t1 = min(run(ks, r1, **kw) for _ in range(3))
        t2 = min(run(ks, r2, **kw) for _ in range(3))
        n_extra = (r2 - r1) * len(ks)
        log(stage=name, per_pass_ms=round((t2 - t1) / n_extra * 1e3, 2),
            t1=round(t1, 4), t2=round(t2, 4))

    kdiff("A+B k=14", [14], 4, 12)
    kdiff("A+B alt k=14/15/17/18", [14, 15, 17, 18], 1, 3)
    kdiff("B-only k=14", [14], 4, 12, b_only=True)
    kdiff("A+B masked k=7", [7], 4, 12, masked=True)
    kdiff("B-only masked k=7", [7], 4, 12, masked=True, b_only=True)
    kdiff("A+B k=8 (4d view)", [8], 4, 12)
    kdiff("B-only k=8 (4d view)", [8], 4, 12, b_only=True)
    kdiff("B-only k=12", [12], 4, 12, b_only=True)


if __name__ == "__main__":
    main()
