"""Prototype rank-R window-kernel variants to cut the HIGHEST-precision
matmul cost (rank-4 pass measured 18.6 ms vs 1.6 ms HBM floor).

Variants (all k=7, rank R, A+B):
  v0  current per-rank HIGHEST dots (baseline)
  v1  bf16_3x split with the state split HOISTED out of the rank loop and
      matrix splits precomputed outside the kernel
  v2  wide lane dot (one (.,256)@(256,256R)) + per-rank sublane HIGHEST
  v3  v2 lane widening + bf16_3x everywhere (hoisted)
All compared for accuracy against a HIGHEST reference on a small state.
"""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, "/root/repo")

from quest_tpu.ops import fused

N = 26
AMPS = 1 << N
BYTES = 2 * 2 * 4 * AMPS
C = 128
K1, K2 = 5, 20
bf16, f32 = jnp.bfloat16, jnp.float32


def rand_u(rng, d):
    m = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
    q, _ = np.linalg.qr(m)
    return np.stack([q.real, q.imag]).astype(np.float32)


def split(x):
    xh = x.astype(bf16)
    return xh, (x - xh.astype(f32)).astype(bf16)


def dot3(xh, xl, mh, ml, dims):
    d = partial(jax.lax.dot_general, dimension_numbers=dims,
                preferred_element_type=f32)
    return d(xh, mh) + d(xh, ml) + d(xl, mh)


# --- v1: hoisted bf16_3x kernel -------------------------------------------

def v1_kernel(rank):
    def kernel(a_ref, mah_ref, mal_ref, mbh_ref, mbl_ref, o_ref):
        x = a_ref[...]
        xr, xi = x[0], x[1]
        xc0 = jnp.concatenate([xr, xi], axis=-1)
        xh, xl = split(xc0)                      # hoisted: once per block
        acc = None
        for r in range(rank):
            xc = dot3(xh, xl, mah_ref[r], mal_ref[r], (((2,), (0,)), ((), ())))
            yr, yi = xc[..., :C], xc[..., C:]
            yc = jnp.concatenate([yr, yi], axis=1)
            ych, ycl = split(yc)
            out = dot3(mbh_ref[r], mbl_ref[r].astype(bf16), ych, ycl,
                       (((1,), (1,)), ((), ())))  # note: m-first operand order
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)
        o_ref[...] = jnp.stack([acc[:, :C], acc[:, C:]], axis=0)

    return kernel


def dot3_m_first(mh, ml, xh, xl, dims):
    d = partial(jax.lax.dot_general, dimension_numbers=dims,
                preferred_element_type=f32)
    return d(mh, xh) + d(ml, xh) + d(mh, xl)


def v1_kernel_fixed(rank):
    def kernel(a_ref, mah_ref, mal_ref, mbh_ref, mbl_ref, o_ref):
        x = a_ref[...]
        xc0 = jnp.concatenate([x[0], x[1]], axis=-1)
        xh, xl = split(xc0)
        acc = None
        for r in range(rank):
            xc = dot3(xh, xl, mah_ref[r], mal_ref[r], (((2,), (0,)), ((), ())))
            yr, yi = xc[..., :C], xc[..., C:]
            yc = jnp.concatenate([yr, yi], axis=1)
            ych, ycl = split(yc)
            out = dot3_m_first(mbh_ref[r], mbl_ref[r], ych, ycl,
                               (((1,), (1,)), ((), ())))
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)
        o_ref[...] = jnp.stack([acc[:, :C], acc[:, C:]], axis=0)

    return kernel


def run_v1(a, mas, mbs, rank, blocks):
    mah, mal = split(jax.vmap(fused.lane_real_rep)(mas))
    mbh, mbl = split(jax.vmap(fused.sublane_real_rep)(mbs))
    hi = AMPS // (C * C)
    R = blocks
    view = a.reshape(2, hi, C, C)
    out = pl.pallas_call(
        v1_kernel_fixed(rank),
        grid=(hi // R,),
        in_specs=[pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0))]
        + [pl.BlockSpec((rank, 2 * C, 2 * C), lambda i: (0, 0, 0))] * 4,
        out_specs=pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
    )(view, mah, mal, mbh, mbl)
    return out.reshape(2, -1)


# --- v2: wide lane dot + per-rank sublane HIGHEST -------------------------

def v2_kernel(rank):
    def kernel(a_ref, maw_ref, mb_ref, o_ref):
        x = a_ref[...]
        xc0 = jnp.concatenate([x[0], x[1]], axis=-1)     # (R, 128, 256)
        xcw = jax.lax.dot_general(
            xc0, maw_ref[...],
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=f32,
            precision=jax.lax.Precision.HIGHEST,
        )                                                # (R, 128, 256*rank)
        acc = None
        for r in range(rank):
            xc = xcw[..., 256 * r:256 * (r + 1)]
            yr, yi = xc[..., :C], xc[..., C:]
            yc = jnp.concatenate([yr, yi], axis=1)
            out = jax.lax.dot_general(
                mb_ref[r], yc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST,
            )
            acc = out if acc is None else acc + out
        acc = jnp.moveaxis(acc, 0, 1)
        o_ref[...] = jnp.stack([acc[:, :C], acc[:, C:]], axis=0)

    return kernel


def run_v2(a, mas, mbs, rank, blocks):
    maw = jnp.concatenate(
        [fused.lane_real_rep(mas[r]) for r in range(rank)], axis=1
    )                                                    # (256, 256*rank)
    mb = jax.vmap(fused.sublane_real_rep)(mbs)
    hi = AMPS // (C * C)
    R = blocks
    view = a.reshape(2, hi, C, C)
    out = pl.pallas_call(
        v2_kernel(rank),
        grid=(hi // R,),
        in_specs=[
            pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
            pl.BlockSpec((rank, 2 * C, 2 * C), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
    )(view, maw, mb)
    return out.reshape(2, -1)


# --- v3: wide lane + wide sublane, all bf16_3x ----------------------------

def v3_kernel(rank):
    def kernel(a_ref, mawh_ref, mawl_ref, mbwh_ref, mbwl_ref, o_ref):
        x = a_ref[...]                                   # (2, R, 128, 128)
        xc0 = jnp.concatenate([x[0], x[1]], axis=-1)     # (R, 128, 256)
        xh, xl = split(xc0)
        xcw = dot3(xh, xl, mawh_ref[...], mawl_ref[...],
                   (((2,), (0,)), ((), ())))             # (R, 128, 256*rank)
        # regroup rank chunks onto the sublane axis:
        # (R, 128, rank, 2, 128) -> (R, rank*256, 128)
        Rb = xcw.shape[0]
        y = xcw.reshape(Rb, C, rank * 2, C)
        y = jnp.moveaxis(y, 2, 1).reshape(Rb, rank * 2 * C, C)
        yh, yl = split(y)
        out = dot3_m_first(mbwh_ref[...], mbwl_ref[...], yh, yl,
                           (((1,), (1,)), ((), ())))     # (256, Rb, 128)
        out = jnp.moveaxis(out, 0, 1)
        o_ref[...] = jnp.stack([out[:, :C], out[:, C:]], axis=0)

    return kernel


def run_v3(a, mas, mbs, rank, blocks):
    maw = jnp.concatenate(
        [fused.lane_real_rep(mas[r]) for r in range(rank)], axis=1
    )
    mbw = jnp.concatenate(
        [fused.sublane_real_rep(mbs[r]) for r in range(rank)], axis=1
    )                                                    # (256, 256*rank)
    mawh, mawl = split(maw)
    mbwh, mbwl = split(mbw)
    hi = AMPS // (C * C)
    R = blocks
    view = a.reshape(2, hi, C, C)
    out = pl.pallas_call(
        v3_kernel(rank),
        grid=(hi // R,),
        in_specs=[
            pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
    )(view, mawh, mawl, mbwh, mbwl)
    return out.reshape(2, -1)


def run_v0(a, mas, mbs, rank, blocks):
    return fused.apply_window_stack(a, mas, mbs, num_qubits=N, k=7,
                                    precision="highest")


RUNNERS = {"v0": run_v0, "v1": run_v1, "v2": run_v2, "v3": run_v3}


def bench(name, rank, blocks):
    rng = np.random.default_rng(0)
    mas = jnp.asarray(np.stack([rand_u(rng, C) for _ in range(rank)]))
    mbs = jnp.asarray(np.stack([rand_u(rng, C) for _ in range(rank)]))
    a = jnp.zeros((2, AMPS), jnp.float32).at[0, 0].set(1.0)
    runner = RUNNERS[name]

    def chain_fn(K):
        @jax.jit
        def chain(a, mas, mbs):
            for _ in range(K):
                a = runner(a, mas, mbs, rank, blocks)
            return a[0, 0]
        return chain

    c1, c2 = chain_fn(K1), chain_fn(K2)
    try:
        float(c1(a, mas, mbs)); float(c2(a, mas, mbs))
        best = None
        for _ in range(3):
            t0 = time.perf_counter(); float(c1(a, mas, mbs)); t1 = time.perf_counter() - t0
            t0 = time.perf_counter(); float(c2(a, mas, mbs)); t2 = time.perf_counter() - t0
            dt = (t2 - t1) / (K2 - K1)
            best = dt if best is None else min(best, dt)
    except Exception as e:
        print(f"{name} rank{rank} blocks{blocks:2d}: FAILED {type(e).__name__} {str(e)[:90]}")
        return
    print(f"{name} rank{rank} blocks{blocks:2d}: {best*1e3:6.2f} ms/pass  {BYTES/best/1e9:6.1f} GB/s")


def accuracy(rank=4):
    # small-N correctness vs v0 highest
    n = 18
    amps = 1 << n
    rng = np.random.default_rng(3)
    st = rng.standard_normal((2, amps)).astype(np.float32)
    st /= np.sqrt((st ** 2).sum())
    mas = jnp.asarray(np.stack([rand_u(rng, C) for _ in range(rank)]))
    mbs = jnp.asarray(np.stack([rand_u(rng, C) for _ in range(rank)]))
    global AMPS
    saved = AMPS
    AMPS = amps
    outs = {}
    try:
        for name, runner in RUNNERS.items():
            try:
                o = runner(jnp.asarray(st), mas, mbs, rank, 4)
                outs[name] = np.asarray(jnp.asarray(o).reshape(2, -1))
            except Exception as e:
                print(f"acc {name}: FAILED {type(e).__name__} {str(e)[:80]}")
    finally:
        AMPS = saved
    # v0 on this size needs num_qubits=n; redo via direct call
    ref = np.asarray(fused.apply_window_stack(
        jnp.asarray(st), mas, mbs, num_qubits=n, k=7, precision="highest"))
    for name, o in outs.items():
        if name == "v0":
            continue
        d = np.abs(o - ref).max()
        print(f"acc {name} vs highest: max|diff| = {d:.3e}")


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} n={N} diff K={K1}->{K2}")
    accuracy(rank=4)
    for rank in (1, 2, 4):
        for name in ("v0", "v1", "v2", "v3"):
            blocks = max(1, 8 // rank)
            bench(name, rank, blocks)


# --- v4: wide lane + wide sublane, HIGHEST ---------------------------------

def v4_kernel(rank):
    def kernel(a_ref, maw_ref, mbw_ref, o_ref):
        x = a_ref[...]
        xc0 = jnp.concatenate([x[0], x[1]], axis=-1)     # (R, 128, 256)
        xcw = jax.lax.dot_general(
            xc0, maw_ref[...], dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=f32, precision=jax.lax.Precision.HIGHEST,
        )                                                # (R, 128, rank*256)
        Rb = xcw.shape[0]
        y = xcw.reshape(Rb, C, rank * 2, C)
        y = jnp.moveaxis(y, 2, 1).reshape(Rb, rank * 2 * C, C)
        out = jax.lax.dot_general(
            mbw_ref[...], y, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32, precision=jax.lax.Precision.HIGHEST,
        )                                                # (256, Rb, 128)
        out = jnp.moveaxis(out, 0, 1)
        o_ref[...] = jnp.stack([out[:, :C], out[:, C:]], axis=0)

    return kernel


def run_v4(a, mas, mbs, rank, blocks):
    maw = jnp.concatenate(
        [fused.lane_real_rep(mas[r]) for r in range(rank)], axis=1)
    mbw = jnp.concatenate(
        [fused.sublane_real_rep(mbs[r]) for r in range(rank)], axis=1)
    hi = AMPS // (C * C)
    R = blocks
    view = a.reshape(2, hi, C, C)
    out = pl.pallas_call(
        v4_kernel(rank),
        grid=(hi // R,),
        in_specs=[
            pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, 2 * C * rank), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2, R, C, C), lambda i: (0, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(view.shape, view.dtype),
        input_output_aliases={0: 0},
    )(view, maw, mbw)
    return out.reshape(2, -1)


RUNNERS["v4"] = run_v4

if __name__ == "__main__" and "--sweep" in sys.argv:
    pass
