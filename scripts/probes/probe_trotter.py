"""Where does config-5's 0.62 s K-diff go?  On-chip decomposition of the
Trotter/expec scan at 24q: per-term marginal cost via scans of varying
length, one product layer alone, one parity phase alone.

Writes scripts/probe_trotter_result.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    print("devices:", devs, flush=True)

    from quest_tpu.ops import kernels
    from quest_tpu.ops import paulis as P

    n = 24
    rng = np.random.default_rng(0)
    res = {"n": n}

    def state():
        a = rng.standard_normal((2, 1 << n)).astype(np.float32)
        a /= np.sqrt((a ** 2).sum())
        return jnp.asarray(a)

    def kdiff(label, run_k, reps=5):
        run_k(1)
        run_k(2)
        ds = []
        for _ in range(reps):
            t1 = run_k(1)
            t2 = run_k(2)
            ds.append(t2 - t1)
        ds.sort()
        res[label] = {"median": round(ds[len(ds) // 2], 4),
                      "min": round(min(ds), 4)}
        print(label, res[label], flush=True)

    # scan of T terms: marginal per-term cost
    for T in (2, 8, 16):
        codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
        angles = jnp.asarray(rng.normal(size=T))

        def run_k(k, codes=codes, angles=angles):
            a = state()
            t0 = time.perf_counter()
            for _ in range(k):
                a = P.trotter_scan(a, codes, angles, num_qubits=n,
                                   rep_qubits=n)
            float(jnp.sum(a[0, :1]))
            return time.perf_counter() - t0

        kdiff(f"trotter_scan_T{T}", run_k)

    # one product layer alone (concrete random 1q mats, window path)
    from functools import partial

    mats = jnp.asarray(rng.standard_normal((n, 2, 2, 2)).astype(np.float32))

    @partial(jax.jit, static_argnames="k")
    def layer_prog(a, m, k):
        for _ in range(k):
            a = P._product_layer(a, m, n)
        return a

    def run_layer(k):
        a = state()
        t0 = time.perf_counter()
        a = layer_prog(a, mats, k)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    kdiff("product_layer", run_layer)

    # parity phase alone (traced mask)
    @partial(jax.jit, static_argnames="k")
    def phase_prog(a, k):
        zlo = jnp.uint32(0x00AAAAAA)
        zhi = jnp.uint32(0)
        for _ in range(k):
            a = P._parity_phase_mask(a, jnp.float32(0.3), zlo, zhi, n)
        return a

    def run_phase(k):
        a = state()
        t0 = time.perf_counter()
        a = phase_prog(a, k)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    kdiff("parity_phase", run_phase)

    # expec scan
    for T in (4, 16):
        codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
        coeffs = jnp.asarray(rng.normal(size=T))

        def run_k(k, codes=codes, coeffs=coeffs):
            a = state()
            t0 = time.perf_counter()
            v = 0.0
            for _ in range(k):
                v = P.expec_pauli_sum_scan(a, codes, coeffs, num_qubits=n)
            float(v)
            return time.perf_counter() - t0

        kdiff(f"expec_scan_T{T}", run_k)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "probe_trotter_result.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
