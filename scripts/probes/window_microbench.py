"""Per-pass timing of apply_window_stack on the real TPU at 26 qubits.

Methodology: K chained passes inside ONE jitted program (single dispatch,
one device->host fetch at the end), so relay round-trip latency is
amortized to noise.  Prints ms/pass and effective HBM r+w bandwidth.
"""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import numpy as np
import jax.numpy as jnp

from quest_tpu.ops import fused, kernels

N = int(os.environ.get("QT_MB_QUBITS", "26"))
K = int(os.environ.get("QT_MB_CHAIN", "32"))
REPS = 3
DIM = fused.CLUSTER_DIM
nbytes = 2 * (1 << N) * 4
print(f"N={N}, chain={K}, pass traffic {2*nbytes/2**30:.2f} GiB r+w",
      flush=True)


def chain(k, rank, apply_a, apply_b):
    @partial(jax.jit, donate_argnums=0)
    def prog(amps, a, b):
        for _ in range(K):
            amps = fused.apply_window_stack(
                amps, a, b, num_qubits=N, k=k,
                apply_a=apply_a, apply_b=apply_b)
        return amps[0, 0]

    return prog


def mats(rank, seed):
    rng = np.random.default_rng(seed)
    m = np.zeros((rank, 2, DIM, DIM))
    for r in range(rank):
        m[r, 0] = np.eye(DIM) + 0.01 * rng.standard_normal((DIM, DIM))
        m[r, 1] = 0.01 * rng.standard_normal((DIM, DIM))
    return jnp.asarray(m / max(1, rank), jnp.float32)


def run(label, k, rank, apply_a=True, apply_b=True):
    prog = chain(k, rank, apply_a, apply_b)
    a, b = mats(rank, 1), mats(rank, 2)
    s = kernels.init_zero_state(1 << N, np.float32)
    out = prog(s, a, b)
    float(out)  # compile + settle
    best = 1e9
    for _ in range(REPS):
        s = kernels.init_zero_state(1 << N, np.float32)
        float(np.asarray(s[0, 0]))
        t0 = time.perf_counter()
        out = prog(s, a, b)
        float(out)
        best = min(best, (time.perf_counter() - t0) / K)
    print(f"{label}: {best*1e3:7.2f} ms/pass {2*nbytes/best/1e9:7.1f} GB/s",
          flush=True)


if __name__ == "__main__":
    for k in (7, 10, 13, 16, 19):
        for rank in (1, 2, 4):
            run(f"k={k:2d} rank={rank}", k, rank)
        run(f"k={k:2d} B-only", k, 1, apply_a=False)
    run("k= 7 A-only", 7, 1, apply_b=False)
