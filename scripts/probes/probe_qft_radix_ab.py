"""Same-process A/B of QFT radix settings (cancels session drift)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import numpy as np

from quest_tpu import circuit as CIRC
from quest_tpu.models import circuits

N = int(os.environ.get("QT_N", "26"))
REPS = int(os.environ.get("QT_REPS", "6"))
MULT = int(os.environ.get("QT_MULT", "4"))


def make(radix):
    os.environ["QT_QFT_RADIX"] = str(radix)

    def one(a):
        return CIRC._fused_qft_multilayer(a, N, N, None)

    def many(a):
        for _ in range(1 + MULT):
            a = CIRC._fused_qft_multilayer(a, N, N, None)
        return a

    return (jax.jit(one, donate_argnums=0), jax.jit(many, donate_argnums=0))


def fetch(out):
    return float(np.asarray(out.reshape(2, -1)[0, 0]))


def main():
    radices = [int(r) for r in os.environ.get("QT_RADICES", "3,4").split(",")]
    jits = {r: make(r) for r in radices}
    for r, (j1, j2) in jits.items():   # warm compiles
        fetch(j1(circuits.zero_state_canonical(N)))
        fetch(j2(circuits.zero_state_canonical(N)))
    best = {r: [1e9, 1e9] for r in radices}
    for _ in range(REPS):
        for r, (j1, j2) in jits.items():
            t0 = time.perf_counter()
            fetch(j1(circuits.zero_state_canonical(N)))
            best[r][0] = min(best[r][0], time.perf_counter() - t0)
            t0 = time.perf_counter()
            fetch(j2(circuits.zero_state_canonical(N)))
            best[r][1] = min(best[r][1], time.perf_counter() - t0)
    for r in radices:
        b1, b2 = best[r]
        print(f"radix {r}: {(b2 - b1) / MULT * 1e3:7.2f} ms"
              f"  (1x {b1 * 1e3:7.2f}  {1 + MULT}x {b2 * 1e3:7.2f})",
              flush=True)


if __name__ == "__main__":
    main()
