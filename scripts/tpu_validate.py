"""Patient TPU validation driver: waits for the chip claim, then times the
fused Pallas path vs the per-gate einsum path and writes JSON results.

Run in the background; progress prints are flushed so a tail shows where
it is. Results land in scripts/tpu_validate_result.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tpu_validate_result.json")


def log(*a):
    print(f"[{time.strftime('%H:%M:%S')}]", *a, flush=True)


def main():
    log("importing jax ...")
    import jax

    log("waiting for device claim (may block for a long time) ...")
    t0 = time.time()
    devs = jax.devices()
    log(f"claim granted after {time.time()-t0:.0f}s: {devs}")

    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.ops import cplx, fused, kernels
    from quest_tpu import circuit as C

    results = {"devices": str(devs)}
    rng = np.random.default_rng(0)

    def ru(k):
        d = 1 << k
        a = rng.standard_normal((d, d)) + 1j * rng.standard_normal((d, d))
        q, r = np.linalg.qr(a)
        return q * (np.diag(r) / np.abs(np.diag(r)))

    # -- step 1: tiny pallas compile (n=14, one grid step) --
    log("compiling fused kernel at n=14 ...")
    A = jnp.asarray(cplx.soa(ru(7)), jnp.float32)
    B = jnp.asarray(cplx.soa(ru(7)), jnp.float32)
    amps = jnp.zeros((2, 1 << 14), jnp.float32).at[0, 0].set(1.0)
    t0 = time.time()
    out = fused.apply_cluster_pair(amps, A, B, num_qubits=14, interpret=False)
    out[0, 0].block_until_ready()
    results["compile_n14_s"] = time.time() - t0
    log(f"n=14 fused compile+run: {results['compile_n14_s']:.1f}s")

    # correctness check vs einsum path at n=14
    amps0 = rng.standard_normal((2, 1 << 14)).astype(np.float32)
    amps0 /= np.sqrt((amps0 ** 2).sum())
    got = np.asarray(fused.apply_cluster_pair(
        jnp.asarray(amps0), A, B, num_qubits=14, interpret=False))
    ref = jnp.asarray(amps0)
    ref = kernels.apply_matrix(ref, A, num_qubits=14, targets=(0, 1, 2, 3, 4, 5, 6))
    ref = kernels.apply_matrix(ref, B, num_qubits=14,
                               targets=(7, 8, 9, 10, 11, 12, 13))
    err = float(np.abs(got - np.asarray(ref)).max())
    results["n14_max_err"] = err
    log(f"n=14 fused-vs-einsum max err: {err:.2e}")

    # -- step 2: n=26 timings --
    n = 26
    log("compiling fused kernel at n=26 ...")
    amps = jnp.zeros((2, 1 << n), jnp.float32).at[0, 0].set(1.0)
    t0 = time.time()
    amps = fused.apply_cluster_pair(amps, A, B, num_qubits=n, interpret=False)
    amps[0, 0].block_until_ready()
    results["compile_n26_s"] = time.time() - t0
    log(f"n=26 fused compile+run: {results['compile_n26_s']:.1f}s")

    t0 = time.time()
    for _ in range(10):
        amps = fused.apply_cluster_pair(amps, A, B, num_qubits=n, interpret=False)
    amps[0, 0].block_until_ready()
    dt = (time.time() - t0) / 10
    results["fused_pass_n26_ms"] = dt * 1e3
    results["fused_pass_n26_gbps"] = 2 * 2 * (1 << n) * 4 / dt / 1e9
    log(f"n=26 fused pass: {dt*1e3:.2f} ms ({results['fused_pass_n26_gbps']:.0f} GB/s r+w)")

    # single 1q gate via einsum path (one HBM pass per gate)
    u1 = jnp.asarray(cplx.soa(ru(1)), jnp.float32)
    log("compiling single 1q gate at n=26 ...")
    amps = kernels.apply_matrix(amps, u1, num_qubits=n, targets=(3,))
    amps[0, 0].block_until_ready()
    t0 = time.time()
    for _ in range(10):
        amps = kernels.apply_matrix(amps, u1, num_qubits=n, targets=(3,))
    amps[0, 0].block_until_ready()
    dt1 = (time.time() - t0) / 10
    results["gate_1q_n26_ms"] = dt1 * 1e3
    log(f"n=26 single 1q gate: {dt1*1e3:.2f} ms -> fused does 14 qubits in "
        f"{results['fused_pass_n26_ms']:.2f} ms ({14*dt1*1e3/results['fused_pass_n26_ms']:.1f}x)")

    # permute pass
    perm = tuple(list(range(12, 26)) + list(range(12)))
    log("compiling permute at n=26 ...")
    p = kernels.permute_qubits(amps, num_qubits=n, perm=perm)
    p[0, 0].block_until_ready()
    t0 = time.time()
    for _ in range(10):
        p = kernels.permute_qubits(p, num_qubits=n, perm=perm)
    p[0, 0].block_until_ready()
    results["permute_pass_n26_ms"] = (time.time() - t0) / 10 * 1e3
    log(f"n=26 permute pass: {results['permute_pass_n26_ms']:.2f} ms")

    with open(RESULT_PATH, "w") as f:
        json.dump(results, f, indent=1)
    log("results written to", RESULT_PATH)


if __name__ == "__main__":
    main()
