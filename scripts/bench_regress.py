#!/usr/bin/env python
"""Regression gate over the committed benchmark trajectory.

The repo carries one ``BENCH_r<N>.json`` artifact per benchmark round
(the driver's capture of ``bench.py`` / ``bench_suite.py`` output), but
until now the trajectory was write-only: nothing failed when a round
got slower.  This script (make verify-regress) closes the loop of
docs/design.md §21:

1. Every round is normalized to a flat ``{key: value}`` metric map with
   a per-key better-direction — the headline gate-apply rate (higher is
   better), every per-config K-diff / eager / fused timing median
   (lower is better), and per-config throughput rates.  Rounds whose
   ``parsed`` payload was lost to output truncation are recovered from
   the raw ``tail`` text by regex.
2. The candidate (default: the LATEST committed round; ``--current
   FILE`` for a fresh ``bench.py`` dict or ``bench_suite.py`` JSON-lines
   capture) is compared per key against the MEDIAN of all prior rounds
   carrying that key — the drift-resistant baseline: one anomalous
   round moves the median far less than a last-round or best-round
   baseline, so a regression is charged against the trajectory's
   consensus, not against noise.
3. Any key worse than the median baseline by more than ``--threshold``
   (default 15%) in its worse direction fails the gate (exit 1).
   Cross-backend comparisons (a CPU smoke run against the committed TPU
   trajectory) are skipped with a note — the numbers are not
   commensurable.
4. Dispatch-bound sentinel (docs/design.md §30): when a config's
   headline timing median sits within 10% of its OWN measured host
   dispatch floor (the ``sustained_k16_dispatch_bound`` companion
   metric bench.py records), the workload is limited by Python/XLA
   program dispatch, not by the kernels under test — an apparent
   slowdown there tracks host scheduling noise.  Such keys are labeled
   ``dispatch_bound`` instead of ``REGRESSION`` and do not fail the
   gate; the floor metric itself is informational and never gated.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# units where larger is better; timing medians are lower-better
_RATE_UNITS = ("per_sec", "per_second", "reduction", "speedup")

# variant sub-dicts of a bench.py per-config record that carry a
# {"median": ...} timing (kdiff, eager, fused_sweep_on, api_wall, ...)
_MEDIAN_RE = re.compile(r'"(\w+)": \{"median": ([-0-9.eE]+)')
_CONFIG_SPLIT_RE = re.compile(r'"(\d+)": \{"metric":')

# the per-config host dispatch floor bench.py measures alongside the
# timing it bounds (sustained k=16 back-to-back dispatch of the same
# program) — the §30 dispatch-bound sentinel's reference
_FLOOR_SUFFIX = "sustained_k16_dispatch_bound_median"
# a timing within this fraction ABOVE its floor is dispatch-bound
_FLOOR_SLACK = 0.10


def _key_config(key: str):
    """The config number a metric key charges — headline keys alias
    config 2 (bench.py's headline IS config 2's gate-apply rate)."""
    m = re.match(r"config(\d+):", key)
    if m:
        return m.group(1)
    return "2" if key.startswith("headline:") else None


def _dispatch_bound_configs(metrics: dict) -> set:
    """Configs whose headline timing median sits within _FLOOR_SLACK of
    their own measured dispatch floor: the run is host-dispatch-bound
    there, so timing deltas reflect scheduling noise, not kernels."""
    bound = set()
    for key, (floor, _) in metrics.items():
        m = re.match(r"config(\d+):" + _FLOOR_SUFFIX + "$", key)
        if not m or floor <= 0:
            continue
        ent = metrics.get(f"config{m.group(1)}:kdiff_median")
        if ent is not None and ent[0] <= (1.0 + _FLOOR_SLACK) * floor:
            bound.add(m.group(1))
    return bound


def _higher_better(unit: str) -> bool:
    return any(t in unit for t in _RATE_UNITS)


def _norm_configs(configs: dict, out: dict) -> None:
    for num, cfg in configs.items():
        if not isinstance(cfg, dict):
            continue
        for variant, sub in cfg.items():
            if isinstance(sub, dict) and "median" in sub:
                out[f"config{num}:{variant}_median"] = (
                    float(sub["median"]), False)
            elif variant.endswith("_per_sec") and isinstance(
                    sub, (int, float)):
                out[f"config{num}:{variant}"] = (float(sub), True)


def _final_json_line(tail: str):
    """``bench.py`` ends its stdout with ONE machine-parsable JSON
    summary line (keys: config/value/unit/seconds/backend).  A round
    whose ``parsed`` payload is None lost the driver's own parse to
    output truncation — but the final line survives whenever the capture
    window held the stream's tail, so prefer recovering THAT (an exact
    parse) over the positional regex sweep below."""
    for line in reversed((tail or "").splitlines()):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and "unit" in doc and "value" in doc:
            return doc
    return None


def _recover_from_tail(tail: str) -> dict:
    """A round whose ``parsed`` payload is None lost its final JSON to
    front-truncation of the captured output; the per-config variant
    medians survive in the text and are recovered positionally."""
    out: dict = {}
    marks = [(m.start(), m.group(1)) for m in _CONFIG_SPLIT_RE.finditer(tail)]
    for i, (pos, num) in enumerate(marks):
        end = marks[i + 1][0] if i + 1 < len(marks) else len(tail)
        seg = tail[pos:end]
        for variant, med in _MEDIAN_RE.findall(seg):
            out[f"config{num}:{variant}_median"] = (float(med), False)
        m = re.search(r'"amp_updates_per_sec": ([-0-9.eE]+)', seg)
        if m:
            out[f"config{num}:amp_updates_per_sec"] = (float(m.group(1)),
                                                       True)
    return out


def normalize_round(record: dict) -> tuple:
    """One ``BENCH_r*.json`` record -> (metrics, backend) where metrics
    is {key: (value, higher_better)}."""
    parsed = record.get("parsed")
    out: dict = {}
    backend = None
    if isinstance(parsed, dict):
        backend = parsed.get("backend")
        unit = parsed.get("unit", "")
        if "value" in parsed and unit:
            out[f"headline:{unit}"] = (float(parsed["value"]),
                                       _higher_better(unit))
        if isinstance(parsed.get("configs"), dict):
            _norm_configs(parsed["configs"], out)
    else:
        doc = _final_json_line(record.get("tail") or "")
        if doc is not None:
            backend = doc.get("backend")
            unit = doc.get("unit", "")
            if doc.get("value") is not None and unit:
                out[f"headline:{unit}"] = (float(doc["value"]),
                                           _higher_better(unit))
            if isinstance(doc.get("configs"), dict):
                _norm_configs(doc["configs"], out)
        if not out:
            out = _recover_from_tail(record.get("tail") or "")
    # bench.py's config 2 IS the headline metric (26q depth-20 gate-apply
    # rate): alias it so rounds whose top-level record was truncated away
    # still extend the multi-round headline trajectory
    if ("headline:amp_updates_per_sec" not in out
            and "config2:amp_updates_per_sec" in out):
        out["headline:amp_updates_per_sec"] = \
            out["config2:amp_updates_per_sec"]
    return out, backend


def normalize_current(path: str) -> tuple:
    """A fresh benchmark capture: either one bench.py JSON dict or
    bench_suite.py JSON lines (one ``{"config": N, ...}`` record per
    line; non-JSON lines ignored)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        return normalize_round({"parsed": doc})
    out: dict = {}
    backend = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict) or "config" not in rec:
            continue
        backend = rec.get("backend", backend)
        unit = rec.get("unit", "")
        num = rec["config"]
        if "value" in rec and unit:
            out[f"config{num}:{unit}"] = (float(rec["value"]),
                                          _higher_better(unit))
        if "seconds" in rec:
            out[f"config{num}:seconds"] = (float(rec["seconds"]), False)
    return out, backend


def load_rounds(bench_dir: str) -> list:
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                record = json.load(f)
        except ValueError:
            continue
        metrics, backend = normalize_round(record)
        if metrics:
            rounds.append({"name": os.path.basename(path),
                           "metrics": metrics, "backend": backend})
    return rounds


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional regression vs the median baseline "
                         "that fails the gate (default 0.15)")
    ap.add_argument("--current", default=None,
                    help="fresh benchmark capture to gate (bench.py JSON "
                         "or bench_suite JSON lines); default: the latest "
                         "committed BENCH_r*.json round")
    ap.add_argument("--bench-dir", default=REPO,
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--min-rounds", type=int, default=2,
                    help="prior rounds a key needs before it is gated — "
                         "a single-point baseline is last-round diffing, "
                         "not a drift-resistant median (default 2)")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.bench_dir)
    if args.current:
        cand_metrics, cand_backend = normalize_current(args.current)
        cand_name = args.current
        history = rounds
    else:
        if len(rounds) < 2:
            print("bench_regress: need >= 2 normalizable BENCH_r*.json "
                  "rounds (or --current); nothing to gate")
            return 0
        cand = rounds[-1]
        cand_metrics, cand_backend = cand["metrics"], cand["backend"]
        cand_name = cand["name"]
        history = rounds[:-1]
    if not cand_metrics:
        print(f"bench_regress: no metrics recognized in {cand_name}")
        return 1

    print(f"bench_regress: candidate={cand_name} "
          f"baseline=median of {len(history)} prior round(s) "
          f"threshold={args.threshold:.0%}")
    bound = _dispatch_bound_configs(cand_metrics)
    if bound:
        print(f"  note: config(s) {sorted(bound)} run at their measured "
              f"host dispatch floor ({_FLOOR_SUFFIX}); timing slowdowns "
              f"there are labeled dispatch_bound, not REGRESSION")
    failures = 0
    compared = 0
    for key in sorted(cand_metrics):
        if key.endswith(_FLOOR_SUFFIX):
            continue  # the floor itself is informational, never gated
        value, higher = cand_metrics[key]
        prior = []
        for r in history:
            if key not in r["metrics"]:
                continue
            if (cand_backend and r["backend"]
                    and r["backend"] != cand_backend):
                print(f"  SKIP {key}: backend {cand_backend} vs "
                      f"{r['backend']} trajectory (not commensurable)")
                prior = []
                break
            prior.append(r["metrics"][key][0])
        if not prior:
            continue
        if len(prior) < args.min_rounds:
            print(f"        note {key}: only {len(prior)} prior round(s) "
                  f"(< --min-rounds {args.min_rounds}); not gated")
            continue
        base = statistics.median(prior)
        compared += 1
        if base == 0:
            continue
        # signed fractional change in the WORSE direction
        delta = (base - value) / abs(base) if higher \
            else (value - base) / abs(base)
        tag = "ok"
        if delta > args.threshold:
            if _key_config(key) in bound:
                tag = "dispatch_bound"
            else:
                tag = "REGRESSION"
                failures += 1
        arrow = "higher-better" if higher else "lower-better"
        print(f"  {tag:>10} {key}: {value:.6g} vs median {base:.6g} "
              f"({arrow}, worse by {delta:+.1%})")
    if not compared:
        print("bench_regress: no overlapping keys with the trajectory; "
              "nothing gated")
        return 0
    if failures:
        print(f"bench_regress: FAIL — {failures} metric(s) regressed "
              f"> {args.threshold:.0%} vs the trajectory median")
        return 1
    print(f"bench_regress: PASS — {compared} metric(s) within "
          f"{args.threshold:.0%} of the trajectory median")
    return 0


if __name__ == "__main__":
    sys.exit(main())
