#!/usr/bin/env python
"""Dispatch-count / per-program-overhead breakdown of the bench.py
config-2 headline — the r04->r05 regression bisection (ISSUE 18).

## The bisection

BENCH_r04 recorded the 26q depth-20 headline at ~873 G amp-updates/sec;
BENCH_r05 recorded ~515 G.  Three facts pin the cause as a MEASUREMENT
REGIME, not an engine change:

1. No engine delta.  ``git diff`` between the two rounds' commits
   touches no ``quest_tpu/`` file (both artifacts also predate every
   growth PR, so "routing added by PR 12-14" — the issue's suspect —
   is chronologically impossible).
2. The r05 record is internally dispatch-bound.  Its config-2 K-diff
   median (0.1004 s/iter) EQUALS its own
   ``sustained_k16_dispatch_bound`` probe (0.101 s/iter, spread 0.0):
   the sustained probe intentionally measures the host-dispatch ceiling
   — 27 separately dispatched programs/iteration x ~3.7 ms relay
   dispatch ~= 0.100 s/iter — so when the paired K=2 estimator lands
   exactly on that ceiling with zero spread, the session's single-shot
   dispatch jitter swallowed the device marginal.  r04's 0.062 s
   resolved the device truth the same estimator usually sees.
3. The r05 ``parsed: null`` is the same session's capture window
   overflowing — bench.py now prints a short machine-parsable final
   line instead (and scripts/bench_regress.py prefers it).

## The fix this script quantifies

The lever arm of the dispatch-bound regime is PROGRAMS PER ITERATION.
The §29 window megakernel (QT_MEGAKERNEL) regroups consecutive fused
window passes into single-dispatch megawin groups: this script builds
the config-2 plan in both arms and reports the program count, the
per-op window-size histogram, a measured per-program dispatch-overhead
probe on THIS host, and the predicted dispatch-bound iteration floor
(programs x overhead) next to the measured chained-loop marginal — so
any future round can check mechanically which regime it measured.

Usage: python scripts/bench_dispatch.py [--n 16] [--depth 20] [--reps 3]
(defaults CPU-shrunk; on a TPU run --n 26 --depth 20 for the true
headline shape).  Prints one JSON line; diagnostic only, always exits 0.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from quest_tpu import circuit as C  # noqa: E402
from quest_tpu.models import circuits  # noqa: E402


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def dispatch_overhead_s(calls=200):
    """Median per-call cost of dispatching a TRIVIAL jitted program and
    blocking on its result: the fixed per-program overhead every
    separately dispatched plan op pays on this host/transport (the
    ~3.7 ms/program relay figure of the r05 record, measured fresh)."""
    @jax.jit
    def bump(x):
        return x + 1.0

    x = jnp.zeros(16384, jnp.float32)
    bump(x).block_until_ready()
    ts = []
    for _ in range(calls):
        t0 = time.perf_counter()
        bump(x).block_until_ready()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def _plan_breakdown(flag, n, depth, us):
    """Plan the config-2 circuit under one QT_MEGAKERNEL arm: program
    count and the per-op window-size (k) histogram."""
    os.environ["QT_MEGAKERNEL"] = flag
    plan = C.plan_circuit(circuits.bench_gate_list(n, depth, us), n)
    hist: dict = {}
    for op in plan:
        if op[0] == "winfused":
            hist[f"k={op[1]}"] = hist.get(f"k={op[1]}", 0) + 1
        elif op[0] == "megawin":
            key = "mega[" + ",".join(str(s[1]) for s in op[1]) + "]"
            hist[key] = hist.get(key, 0) + 1
        else:
            hist[op[0]] = hist.get(op[0], 0) + 1
    return plan, {"megakernel": flag, "programs_per_iter": len(plan),
                  "op_histogram": hist,
                  "stats": {k: v for k, v in C.stats(plan).items() if v}}


def _measured_marginal(plan, n, k=3, reps=3):
    """Best-of-reps chained-loop marginal for one planned program —
    device/XLA truth with no per-program dispatch in the loop."""
    ops = C.plan_to_device(plan, jnp.float32)

    def run():
        a = circuits.zero_state_canonical(n)
        t0 = time.perf_counter()
        for _ in range(k):
            a = C.execute_plan_chained(a, ops, n)
        float(circuits.amp00_canonical(a))
        return time.perf_counter() - t0

    run()
    return min(run() for _ in range(reps)) / k


def run(n=16, depth=20, reps=3):
    _fn, us = circuits.build_random_circuit(n, depth, seed=7)
    us = np.asarray(us)
    prev = os.environ.get("QT_MEGAKERNEL")
    try:
        arms = {}
        overhead = dispatch_overhead_s()
        for flag in ("off", "on"):
            plan, breakdown = _plan_breakdown(flag, n, depth, us)
            breakdown["chained_marginal_s"] = round(
                _measured_marginal(plan, n, reps=reps), 4)
            # the dispatch-bound floor an op-at-a-time driver pays: one
            # host dispatch per separately dispatched program
            breakdown["dispatch_floor_s"] = round(
                breakdown["programs_per_iter"] * overhead, 4)
            arms[flag] = breakdown
    finally:
        if prev is None:
            os.environ.pop("QT_MEGAKERNEL", None)
        else:
            os.environ["QT_MEGAKERNEL"] = prev
    return {
        "bench": "dispatch_breakdown",
        "n": n, "depth": depth,
        "backend": jax.default_backend(),
        "per_program_dispatch_s": round(overhead, 6),
        "arms": arms,
        "programs_saved": (arms["off"]["programs_per_iter"]
                           - arms["on"]["programs_per_iter"]),
        "dispatch_floor_saved_s": round(
            arms["off"]["dispatch_floor_s"] - arms["on"]["dispatch_floor_s"],
            4),
        "r04_r05_verdict": (
            "r05 headline was host-dispatch-bound (27 programs x ~3.7ms "
            "relay dispatch ~= its own sustained_k16 ceiling, spread 0); "
            "no quest_tpu/ change between rounds — megakernel grouping "
            "shrinks programs/iter, bench.py final-line output fixes the "
            "parsed:null capture loss"),
    }


def main():
    rec = run(n=_arg("--n", 16), depth=_arg("--depth", 20),
              reps=_arg("--reps", 3))
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
