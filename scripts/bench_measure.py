"""Shot-loop benchmark at 26q (VERDICT r3 item 2 'done' criterion):
host-MT measure (2 dispatches + 2 syncs/shot) vs the fused one-dispatch
program vs the whole-sequence single-dispatch program.

Writes scripts/bench_measure_result.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_measure_result.json")


def log(*a):
    print(f"[{time.strftime('%H:%M:%S')}]", *a, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    log("claiming device ...")
    devs = jax.devices()
    log(f"devices: {devs}")

    import quest_tpu as qt
    from quest_tpu.ops import measurement as M

    n = 26
    env = qt.createQuESTEnv()
    results = {"n": n, "devices": str(devs)}

    def prepare():
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            for t in range(n):
                qt.hadamard(q, t)
        q.amps.block_until_ready()
        return q

    qt.seedQuEST(env, [1])

    # -- host-MT path: calcProb dispatch + host draw + collapse dispatch
    os.environ["QT_HOST_MEASURE"] = "1"
    # warm EVERY per-target jit signature so the loop timing is pure
    # dispatch (prob + collapse jits are keyed on the static target)
    q = prepare()
    outs = [qt.measure(q, t) for t in range(n)]
    q = prepare()
    t0 = time.time()
    host_outs = [qt.measure(q, t) for t in range(n)]
    q.amps.block_until_ready()
    host_s = time.time() - t0
    results["host_loop_s"] = host_s
    results["host_per_shot_ms"] = 1e3 * host_s / n
    log(f"host path: {host_s:.3f} s ({1e3 * host_s / n:.1f} ms/shot)")
    del os.environ["QT_HOST_MEASURE"]

    # -- fused per-shot path (one dispatch per shot)
    q = prepare()
    for t in (0, 1):
        qt.measure(q, t)  # warm two target signatures
    # warm ALL target signatures so the loop timing is dispatch, not compile
    q = prepare()
    for t in range(n):
        qt.measure(q, t)
    q = prepare()
    t0 = time.time()
    fused_outs = [qt.measure(q, t) for t in range(n)]
    q.amps.block_until_ready()
    fused_s = time.time() - t0
    results["fused_loop_s"] = fused_s
    results["fused_per_shot_ms"] = 1e3 * fused_s / n
    log(f"fused per-shot: {fused_s:.3f} s ({1e3 * fused_s / n:.1f} ms/shot)")

    # -- sequence program: ONE dispatch for all 26 shots
    q = prepare()
    key, shot = M.KEYS.next_shots(n)
    amps, outs, probs = M.measure_sequence(
        q.amps, key, shot, num_qubits=n, targets=tuple(range(n)),
        is_density=False)
    outs.block_until_ready()  # compiled
    q = prepare()
    key, shot = M.KEYS.next_shots(n)
    t0 = time.time()
    amps, outs, probs = M.measure_sequence(
        q.amps, key, shot, num_qubits=n, targets=tuple(range(n)),
        is_density=False)
    outs.block_until_ready()
    seq_s = time.time() - t0
    results["sequence_s"] = seq_s
    results["sequence_per_shot_ms"] = 1e3 * seq_s / n
    results["speedup_fused_vs_host"] = host_s / fused_s
    results["speedup_sequence_vs_host"] = host_s / seq_s
    log(f"sequence: {seq_s:.3f} s ({1e3 * seq_s / n:.2f} ms/shot)")
    log(f"speedups vs host: fused {host_s / fused_s:.1f}x, "
        f"sequence {host_s / seq_s:.1f}x")

    with open(RESULT, "w") as f:
        json.dump(results, f, indent=2)
    log(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
