"""Overhead of the resilience layer on the bench config-6 workload.

Mirrors bench_suite.config6's gate stream (alternating shard-local and
sharded-target random 2q unitaries on the 8-shard dryrun mesh) and runs
it (a) as plain fusion windows and (b) through resilience.run_resumable
with the every=64 checkpoint+watchdog cadence, reporting wall clock and
the per-checkpoint cost (ISSUE 2 acceptance: measure the every=64
cadence overhead on config 6).

Usage: python scripts/bench_resilience.py [--n 10] [--depth 64] [--every 64]
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import circuit as C  # noqa: E402
from quest_tpu import fusion  # noqa: E402


def _arg(flag, default):
    return int(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def main():
    n = _arg("--n", 10)
    depth = _arg("--depth", 64)
    every = _arg("--every", 64)
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(11)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    soa = np.stack([u.real, u.imag])
    gates = []
    for _ in range(depth):
        gates.append(C.Gate((0, 1), soa))          # shard-local
        gates.append(C.Gate((n - 2, n - 1), soa))  # sharded targets

    def run_plain():
        qt.seedQuEST(env, [3])
        q = qt.createQureg(n, env)
        for cur in range(0, len(gates), every):
            fusion.start_gate_fusion(q)
            q._fusion.gates.extend(gates[cur:cur + every])
            fusion.stop_gate_fusion(q)
        return q.amps.block_until_ready()

    def run_resumable():
        qt.seedQuEST(env, [3])
        q = qt.createQureg(n, env)
        d = tempfile.mkdtemp(prefix="qt_bench_res_")
        try:
            qt.run_resumable(q, gates, d, every=every)
            return q.amps.block_until_ready()
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def best_of(fn, reps=5):
        fn()  # warm compile caches
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return min(times), sorted(times)[len(times) // 2]

    plain_best, plain_med = best_of(run_plain)
    res_best, res_med = best_of(run_resumable)
    n_ckpts = len(C.plan_checkpoint_boundaries(len(gates), every))
    out = {
        "config": 6,
        "metric": f"{n}q depth-{depth} resilience overhead (every={every})",
        "gates": len(gates),
        "checkpoints": n_ckpts,
        "plain_seconds_best": round(plain_best, 4),
        "resumable_seconds_best": round(res_best, 4),
        "overhead_seconds_best": round(res_best - plain_best, 4),
        "overhead_pct_best": round(100 * (res_best / plain_best - 1), 1),
        "per_checkpoint_seconds": round((res_best - plain_best)
                                        / max(n_ckpts, 1), 4),
        "plain_seconds_median": round(plain_med, 4),
        "resumable_seconds_median": round(res_med, 4),
        "devices": env.num_devices,
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
