"""Serving saturation A/B (ISSUE round-12 acceptance): the continuous
batcher (quest_tpu.serve.SimServer) must beat batch-at-once serving —
each request admitted to an EnsembleScheduler and drained as its own
bank the moment it reaches the head of the FCFS queue, the no-cross-
request-batching model every request/response simulator service uses —
by >= 2x circuits/sec on the SAME open-loop Poisson arrival trace, and
interactive p99 end-to-end latency under batch load + preemption must
stay within 2x of its unloaded value.

Both arms replay one seeded arrival trace whose rate is calibrated to
~4x the baseline's measured single-circuit service rate, so the
baseline saturates (its throughput IS its per-circuit service rate)
while the continuous arm's backlog coalesces into ensemble banks
between fusion windows.  Both arms warm their compile caches on the
full structure set before timing; the measured quantities are steady
state circuits/sec, bank occupancy, and per-class p50/p99 queue-wait
and end-to-end latency.

Usage: python scripts/bench_serve.py [--n 8] [--depth 6] [--jobs 48]
       [--interactive 16] [--interactive-depth 5] [--window 16]
       [--max-batch 16] [--rate-mult 4.0] [--reps 2]
       [--speedup-budget 2.0] [--latency-budget 2.0] [--no-check]
Exits non-zero when either budget fails on the best rep (unless
--no-check); like the other wall-clock benches, the record kept is the
best of ``--reps`` replays (scheduler noise damping).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# both arms must replay the LITERAL gate stream: the continuous arm is
# window-stepped (circuit optimizer suppressed — see
# optimizer.suppressed), so the batch-at-once baseline must not get an
# optimizer rewrite the serving path cannot
os.environ.setdefault("QT_OPTIMIZER", "off")

import jax  # noqa: E402

if jax.default_backend() == "cpu":
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from quest_tpu import batch as B  # noqa: E402
from quest_tpu import circuit as C  # noqa: E402
from quest_tpu import serve as S  # noqa: E402
from quest_tpu import telemetry as T  # noqa: E402


def _arg(flag, default, cast=int):
    return cast(sys.argv[sys.argv.index(flag) + 1]) \
        if flag in sys.argv else default


def _su2(rng):
    g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    u, _r = np.linalg.qr(g)
    return C.Gate, u


def _gate(target, u):
    return C.Gate((target,), np.stack([u.real, u.imag]))


def _circuit(rng, n, depth):
    """A depth-layered per-qubit random-SU(2) stream: every circuit
    shares one structure (so the continuous arm's backlog coalesces)
    while the matrices differ per submission (the per-element bank
    path)."""
    gates = []
    for _d in range(depth):
        for t in range(n):
            g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal(
                (2, 2))
            u, _r = np.linalg.qr(g)
            gates.append(_gate(t, u))
    return gates


def _poisson_trace(rng, count, rate):
    """Open-loop arrival offsets (seconds from t0) at ``rate``/sec."""
    return np.cumsum(rng.exponential(1.0 / rate, size=count))


def _pct(xs, p):
    return float(np.percentile(np.asarray(xs, dtype=float), p)) \
        if len(xs) else None


def _lat_ms(xs):
    return {"p50_ms": round(1e3 * _pct(xs, 50), 3) if xs else None,
            "p99_ms": round(1e3 * _pct(xs, 99), 3) if xs else None}


def run_baseline(env, n, circuits, trace):
    """Batch-at-once serving: FCFS, one EnsembleScheduler drain per
    request as it reaches the head of the queue — arrivals during a
    drain wait for the whole drain (no admission between windows)."""
    queue_wait, e2e = [], []
    t0 = time.perf_counter()
    for gates, due in zip(circuits, trace):
        now = time.perf_counter() - t0
        if due > now:
            time.sleep(due - now)
            now = due
        start = time.perf_counter() - t0
        sched = B.EnsembleScheduler(n, env, max_batch=1)
        sched.submit(gates)
        sched.drain()
        done = time.perf_counter() - t0
        queue_wait.append(start - due)
        e2e.append(done - due)
    makespan = (time.perf_counter() - t0) - trace[0]
    return {"circuits_per_sec": round(len(circuits) / makespan, 2),
            "makespan_seconds": round(makespan, 4),
            "queue_wait": _lat_ms(queue_wait), "e2e": _lat_ms(e2e)}


def run_continuous(env, n, arrivals, *, window, max_batch,
                   interactive_only=False):
    """Open-loop replay against a SimServer: admit every due arrival
    between fusion windows, step otherwise.  ``arrivals`` is a list of
    (due_seconds, gates, priority) sorted by due time."""
    srv = S.SimServer(env, window=window, max_batch=max_batch)
    srv.register_tenant("batch", max_pending=4096)
    srv.register_tenant("live", max_pending=4096)
    jobs = []
    try:
        i = 0
        t0 = time.perf_counter()
        while i < len(arrivals) or not all(j.done for _d, _p, j in jobs):
            now = time.perf_counter() - t0
            while i < len(arrivals) and arrivals[i][0] <= now:
                due, gates, prio = arrivals[i]
                jobs.append((due, prio, srv.submit(
                    gates, num_qubits=n, priority=prio, seed=i,
                    tenant="live" if prio == S.INTERACTIVE
                    else "batch")))
                i += 1
            if not srv.step() and i < len(arrivals):
                time.sleep(min(0.001, max(
                    0.0, arrivals[i][0] - (time.perf_counter() - t0))))
        out = {}
        for prio in ((S.INTERACTIVE,) if interactive_only
                     else (S.BATCH, S.INTERACTIVE)):
            qs = [j.t_start - j.t_submit for _d, p, j in jobs
                  if p == prio and j.t_start is not None]
            es = [j.t_done - j.t_submit for _d, p, j in jobs
                  if p == prio and j.t_done is not None]
            if not es:
                continue
            # class throughput: first arrival of the class to its last
            # completion (the sparse interactive stream riding on top
            # must not dilute the batch-class saturation number)
            due0 = min(d for d, p, _j in jobs if p == prio)
            done = max(j.t_done - t0 for _d, p, j in jobs
                       if p == prio)
            span = max(done - due0, 1e-9)
            out[prio] = {"count": len(es),
                         "circuits_per_sec": round(len(es) / span, 2),
                         "span_seconds": round(span, 4),
                         "queue_wait": _lat_ms(qs), "e2e": _lat_ms(es)}
        head = S.INTERACTIVE if interactive_only else S.BATCH
        out["circuits_per_sec"] = out[head]["circuits_per_sec"]
        out["makespan_seconds"] = out[head]["span_seconds"]
        return out
    finally:
        srv.close()


def run(*, n=8, depth=6, num_jobs=48, num_interactive=16, live_depth=5,
        window=16, max_batch=16, rate_mult=4.0, reps=2,
        speedup_budget=2.0, latency_budget=2.0):
    """Warm, calibrate, and replay the A/B trace ``reps`` times;
    returns the best rep's record (gate-pass count, then speedup)."""
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(31)
    batch_circs = [_circuit(rng, n, depth) for _ in range(num_jobs)]
    live_circs = [_circuit(rng, n, live_depth)
                  for _ in range(num_interactive)]

    # warm every compiled structure both arms touch: the B=1 drain and
    # each power-of-two bank size either class can form mid-trace (the
    # backlog coalesces into whatever size is waiting, so every shape
    # must be out of the compile path before timing)
    warm = B.EnsembleScheduler(n, env, max_batch=1)
    warm.submit(batch_circs[0])
    warm.drain()
    bank = 1
    while bank <= max_batch:
        srv = S.SimServer(env, window=window, max_batch=max_batch)
        try:
            for j, g in enumerate(batch_circs[:bank]):
                srv.submit(g, num_qubits=n, seed=j)
            srv.step()  # start the batch bank so the preempt path fires
            for j, g in enumerate(live_circs[:bank]):
                srv.submit(g, num_qubits=n, seed=j,
                           priority=S.INTERACTIVE)
            # drives preempt-to-checkpoint + resume at this bank size —
            # the first checkpoint/restore of a shape compiles its
            # rematerialization programs, which must not land inside
            # the timed trace
            srv.run_until_idle()
        finally:
            srv.close()
        bank *= 2

    # calibrate the open-loop rate off the baseline's measured
    # per-circuit service time: ~rate_mult x its capacity saturates it
    t0 = time.perf_counter()
    sched = B.EnsembleScheduler(n, env, max_batch=1)
    sched.submit(batch_circs[0])
    sched.drain()
    per_circuit_s = time.perf_counter() - t0
    rate = rate_mult / per_circuit_s

    best = None
    for _rep in range(reps):
        trace = _poisson_trace(rng, num_jobs, rate)
        baseline = run_baseline(env, n, batch_circs, trace)

        # the same trace, continuously batched, plus a sparse
        # interactive stream riding on top (the preemption load test)
        live_trace = _poisson_trace(
            rng, num_interactive, rate / max(6, num_jobs // 2))
        mixed = sorted(
            [(float(t), g, S.BATCH)
             for t, g in zip(trace, batch_circs)]
            + [(float(t), g, S.INTERACTIVE)
               for t, g in zip(live_trace, live_circs)],
            key=lambda a: a[0])
        T.reset()
        continuous = run_continuous(env, n, mixed, window=window,
                                    max_batch=max_batch)
        snap = T.snapshot()
        occ = snap.get("histograms", {}).get(
            "ensemble_bucket_occupancy", {}).get("", {})
        continuous["bank_occupancy_mean"] = round(
            occ["sum"] / occ["count"], 3) if occ.get("count") else None
        continuous["preemptions"] = T.counter_total("preemptions_total")
        continuous["resumes"] = T.counter_total("serve_resumes_total")

        # unloaded interactive reference: the same interactive stream
        # with no batch load at all
        unloaded = run_continuous(
            env, n,
            [(float(t), g, S.INTERACTIVE)
             for t, g in zip(live_trace, live_circs)],
            window=window, max_batch=max_batch,
            interactive_only=True) if num_interactive else {}

        speedup = (continuous["circuits_per_sec"]
                   / baseline["circuits_per_sec"])
        loaded_p99 = continuous.get(S.INTERACTIVE, {}).get(
            "e2e", {}).get("p99_ms")
        unloaded_p99 = unloaded.get(S.INTERACTIVE, {}).get(
            "e2e", {}).get("p99_ms")
        ratio = (loaded_p99 / unloaded_p99
                 if loaded_p99 and unloaded_p99 else None)
        rec = {
            "bench": "serve_saturation",
            "n": n, "depth": depth, "jobs": num_jobs,
            "interactive_jobs": num_interactive,
            "interactive_depth": live_depth,
            "window": window, "max_batch": max_batch,
            "arrival_rate_per_sec": round(rate, 2),
            "backend": jax.default_backend(),
            "devices": env.num_devices,
            "baseline": baseline,
            "continuous": continuous,
            "interactive_unloaded": unloaded,
            "speedup": round(speedup, 2),
            "interactive_p99_ratio": round(ratio, 2) if ratio else None,
        }
        def _score(r):
            ratio_r = r["interactive_p99_ratio"]
            gates = ((r["speedup"] >= speedup_budget)
                     + (ratio_r is None or ratio_r <= latency_budget))
            return (gates, r["speedup"])

        if best is None or _score(rec) > _score(best):
            best = rec
    return best


def main():
    # interactive depth: deep enough that the interactive job's own
    # execution dominates its e2e latency — the preemption
    # interference bound (one batch window + one checkpoint) is a
    # fixed cost, so a trivial circuit would measure only scheduler
    # granularity, not the policy.  Must differ from --depth
    # (same-structure circuits share a bucket).
    best = run(
        n=_arg("--n", 8), depth=_arg("--depth", 6),
        num_jobs=_arg("--jobs", 48),
        num_interactive=_arg("--interactive", 16),
        live_depth=_arg("--interactive-depth", 5),
        window=_arg("--window", 16),
        max_batch=_arg("--max-batch", 16),
        rate_mult=_arg("--rate-mult", 4.0, float),
        reps=_arg("--reps", 2),
        speedup_budget=_arg("--speedup-budget", 2.0, float),
        latency_budget=_arg("--latency-budget", 2.0, float))
    speedup_budget = _arg("--speedup-budget", 2.0, float)
    latency_budget = _arg("--latency-budget", 2.0, float)

    print(json.dumps(best), flush=True)
    if "--no-check" in sys.argv:
        return 0
    ok = True
    if best["speedup"] < speedup_budget:
        print(f"FAIL: continuous/baseline throughput "
              f"{best['speedup']:.2f}x is below the "
              f"{speedup_budget:.1f}x budget", file=sys.stderr)
        ok = False
    ratio = best["interactive_p99_ratio"]
    if ratio is not None and ratio > latency_budget:
        print(f"FAIL: loaded interactive p99 is {ratio:.2f}x unloaded "
              f"(budget {latency_budget:.1f}x)", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
