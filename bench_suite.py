"""Benchmark suite: one JSON line per BASELINE.json config.

Sizes marked (scaled) are reduced from the BASELINE.json pod-scale targets
to fit the single benchmarking chip (v5e, 16 GB HBM); the workload shape
(gate mix, reduction structure) is preserved.  bench.py remains the
driver-facing headline (config 2).

Usage: python bench_suite.py [--config N] [--all]
       QT_BENCH_CPU=1 for off-TPU smoke runs (tiny sizes).
"""

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if os.environ.get("QT_BENCH_CPU") == "1":
    # config 6's 8-shard dryrun needs the virtual mesh; the flag must be
    # set before jax initialises
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if os.environ.get("QT_BENCH_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

CPU = os.environ.get("QT_BENCH_CPU") == "1"


_LAST_COMPILE_S = [0.0]


def _time_best(fn, reps=3):
    """(best_seconds, last_result, compile_seconds) — result captured so
    callers never rerun the workload just to log it; the warm-up (compile +
    first run) wall is returned AND kept in _LAST_COMPILE_S for _emit
    (compile cost is a first-class metric for a traced-program
    framework).  Configs that time several variants pass the compile_s of
    the variant they report to _emit via _set_compile."""
    t0 = time.perf_counter()
    result = fn()  # warm-up/compile
    _LAST_COMPILE_S[0] = time.perf_counter() - t0
    best = math.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - t0)
    return best, result, _LAST_COMPILE_S[0]


def _set_compile(compile_s: float) -> None:
    _LAST_COMPILE_S[0] = compile_s


def _emit(config, metric, value, unit, seconds, extra=None):
    rec = {
        "config": config,
        "metric": metric,
        "value": value,
        "unit": unit,
        "seconds": seconds,
        "compile_plus_first_run_s": round(_LAST_COMPILE_S[0], 1),
        "backend": jax.default_backend(),
    }
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)


def config1():
    """12q hadamard + controlledRotateX chain + calcProbOfOutcome, through
    the imperative API (gate-at-a-time dispatch — the reference's model)."""
    import quest_tpu as qt

    n = 12
    env = qt.createQuESTEnv()

    def run():
        q = qt.createQureg(n, env)
        qt.hadamard(q, 0)
        for t in range(1, n):
            qt.controlledRotateX(q, t - 1, t, 0.3)
        return qt.calcProbOfOutcome(q, n - 1, 0)

    def run_fused():
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            for t in range(1, n):
                qt.controlledRotateX(q, t - 1, t, 0.3)
        return qt.calcProbOfOutcome(q, n - 1, 0)

    seconds, prob, compile_s = _time_best(run)
    fused_seconds, fused_prob, _ = _time_best(run_fused)
    _set_compile(compile_s)
    gates = n  # 1 H + (n-1) controlled rotations
    _emit(1, "12q API chain gate rate", gates * (1 << n) / seconds,
          "amp_updates_per_sec", seconds,
          {"prob": prob, "gatefusion_seconds": fused_seconds,
           "gatefusion_prob": fused_prob})


def config2():
    """Delegates to bench.py (26q depth-20 random circuit, fused path).
    The CPU smoke run shrinks the register: the full 26q plan through
    interpret-mode Pallas on CPU takes tens of minutes."""
    if CPU:
        os.environ.setdefault("QT_BENCH_QUBITS", "16")
        os.environ.setdefault("QT_BENCH_DEPTH", "4")
    import bench

    bench.main()


def config3():
    """QFT via fused controlled-phase ladders + swaps (cross-shard exercise
    on a mesh; single-chip here). Scaled 30q -> 26q (8 GB f32 SoA)."""
    import jax.numpy as jnp

    from quest_tpu.models import circuits
    from quest_tpu.ops import kernels

    n = 10 if CPU else 26
    jqft = jax.jit(lambda a: circuits.qft_circuit(a, n), donate_argnums=0)

    def run():
        amps = kernels.init_debug_state(1 << n, np.float32)
        amps /= np.sqrt(float(jnp.sum(amps * amps)))
        out = jqft(amps)
        # device-to-host fetch: under the axon relay block_until_ready
        # returns at enqueue time (see bench.py)
        float(np.asarray(out[0, 0]))
        return out

    seconds, _, _ = _time_best(run)
    gates = n + n * (n - 1) // 2 + n // 2  # H ladder + CPhase ladder + swaps
    _emit(3, f"{n}q QFT gate rate", gates * (1 << n) / seconds,
          "amp_updates_per_sec", seconds, {"gates": gates})


def config4():
    """Density-matrix noise: mixDepolarising + mixTwoQubitKrausMap +
    calcFidelity. Scaled 20q -> 13q rho (2^26 amps, chip-resident)."""
    import quest_tpu as qt

    n = 5 if CPU else 13
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(5)
    # random 2-qubit CPTP map (4 Kraus ops)
    raw = rng.standard_normal((4, 4, 4)) + 1j * rng.standard_normal((4, 4, 4))
    s = np.zeros((4, 4), dtype=complex)
    for k in raw:
        s += k.conj().T @ k
    w = np.linalg.inv(np.linalg.cholesky(s).conj().T)
    ops = [k @ w for k in raw]

    def run(k=1, fused=True):
        rho = qt.createDensityQureg(n, env)
        qt.initPlusState(rho)
        # fused: the whole noise block drains as ONE jitted program —
        # depol channels capture as ChannelItems (the one-pass
        # elementwise pair kernels, in call order) and the 2q Kraus map
        # as a superoperator fold; eager: one dispatch per channel
        if fused:
            with qt.gateFusion(rho):
                for _ in range(k):
                    for q in range(n):
                        qt.mixDepolarising(rho, q, 0.05)
                    qt.mixTwoQubitKrausMap(rho, 0, 1, ops)
        else:
            for _ in range(k):
                for q in range(n):
                    qt.mixDepolarising(rho, q, 0.05)
                qt.mixTwoQubitKrausMap(rho, 0, 1, ops)
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        return qt.calcFidelity(rho, psi)

    # ADVICE r3 (c): emit BOTH eager and fused timings so the faster
    # configuration stays measured and a regression in either is visible
    seconds, fidelity, compile_s = _time_best(run)
    sec2, _, _ = _time_best(lambda: run(2))
    eager_s, _, eager_compile = _time_best(lambda: run(fused=False))
    eager2, _, _ = _time_best(lambda: run(2, fused=False))
    _set_compile(compile_s)
    _emit(4, f"{n}q density noise+fidelity wall-clock", seconds, "seconds",
          seconds, {"fidelity": fidelity,
                    "kdiff_noise_device_s": round(sec2 - seconds, 3),
                    "eager_seconds": eager_s,
                    "eager_compile_s": round(eager_compile, 1),
                    "eager_kdiff_noise_device_s": round(eager2 - eager_s, 3)})


def config5():
    """calcExpecPauliHamil + applyTrotterCircuit on a random PauliHamil.
    Scaled 34q (pod) -> 24q (chip)."""
    import quest_tpu as qt

    n = 8 if CPU else 24
    terms = 16
    env = qt.createQuESTEnv()
    rng = np.random.default_rng(7)
    hamil = qt.createPauliHamil(n, terms)
    codes = rng.integers(0, 4, size=(terms, n))
    coeffs = rng.standard_normal(terms)
    qt.initPauliHamil(hamil, coeffs, codes)

    def run():
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        work = qt.createQureg(n, env)
        e = qt.calcExpecPauliHamil(psi, hamil, work)
        qt.applyTrotterCircuit(psi, hamil, 0.1, 2, 1)
        return e

    seconds, energy, _ = _time_best(run)
    _emit(5, f"{n}q PauliHamil expec+Trotter wall-clock", seconds, "seconds",
          seconds, {"energy": energy})


def config6():
    """Communication-avoiding lazy qubit remap (mpiQulacs-style) on the
    8-shard dryrun: a depth-d stream alternating shard-local and
    sharded-target 2q unitaries, run (a) lazily — relocalizations fold
    into the persistent logical->physical permutation, no swap-back, one
    rematerializing remap at the final read — vs (b) the reference's
    eager per-gate swap-in/swap-out (QuEST_cpu_distributed.c:1447-1545).
    The dispatch-level metric is the number of exchange programs issued
    (half-shard swap_sharded + batched remap_sharded dispatches) plus
    wall clock."""
    import quest_tpu as qt
    from quest_tpu.parallel import dist

    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        _emit(6, "8-shard lazy remap (SKIPPED: needs 8 amp shards)",
              0.0, "seconds", 0.0)
        return
    n = 10 if CPU else 24
    depth = 12
    rng = np.random.default_rng(11)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)

    counts = {"swap": 0, "remap": 0}
    orig_swap, orig_remap = dist.swap_sharded, dist.remap_sharded

    def counting_swap(*a, **k):
        counts["swap"] += 1
        return orig_swap(*a, **k)

    def counting_remap(*a, **k):
        counts["remap"] += 1
        return orig_remap(*a, **k)

    def run():
        q = qt.createQureg(n, env)
        for _ in range(depth):
            qt.multiQubitUnitary(q, [0, 1], u)          # shard-local
            qt.multiQubitUnitary(q, [n - 2, n - 1], u)  # sharded targets
        return qt.calcProbOfOutcome(q, 0, 0)

    dist.swap_sharded, dist.remap_sharded = counting_swap, counting_remap
    try:
        dist.use_lazy_remap(True)
        lazy_s, lazy_p, compile_s = _time_best(run)
        counts["swap"] = counts["remap"] = 0
        run()
        lazy_exchanges = counts["swap"] + counts["remap"]
        dist.use_lazy_remap(False)
        eager_s, eager_p, _ = _time_best(run)
        counts["swap"] = counts["remap"] = 0
        run()
        eager_exchanges = counts["swap"] + counts["remap"]
    finally:
        dist.swap_sharded, dist.remap_sharded = orig_swap, orig_remap
        dist.use_lazy_remap(True)
    _set_compile(compile_s)
    _emit(6, f"{n}q 8-shard lazy-remap wall-clock", lazy_s, "seconds",
          lazy_s,
          {"eager_seconds": eager_s,
           "lazy_exchange_dispatches": lazy_exchanges,
           "eager_exchange_dispatches": eager_exchanges,
           "exchange_reduction": round(
               eager_exchanges / max(lazy_exchanges, 1), 2),
           "prob_delta": abs(lazy_p - eager_p)})


def config7():
    """Pipelined chunked shard exchange A/B (ISSUE 3): the distributed
    hot-path exchanges (sharded-target 1q gate, half-shard swap, batched
    window remap) run monolithic (C=1) vs chunk-pipelined over a chunk
    sweep C in {1, 2, 4, 8} on the 8-shard dryrun, measuring wall clock,
    HLO collective-permute dispatch counts, and the per-exchange ICI
    volume (circuit.remap_exchange_bytes for the remap).  On CPU there is
    no async collective to overlap, so this config measures the OVERHEAD
    side of the pipeline (the fallback-threshold calibration —
    dist.PIPELINE_MIN_BYTES); the overlap win needs ICI (docs/design.md
    §17)."""
    import jax.numpy as jnp

    import quest_tpu as qt
    from quest_tpu import circuit as CIRC
    from quest_tpu.parallel import dist

    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        _emit(7, "8-shard pipelined exchange (SKIPPED: needs 8 amp shards)",
              0.0, "seconds", 0.0)
        return
    n = 20 if CPU else 26
    reps = 8          # exchanges per timed run (amortizes dispatch noise)
    rng = np.random.default_rng(13)
    h = (1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]])
    m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))
    sigma = dist.canonical_sigma(
        tuple([n - 1, 1] + list(range(2, n - 1)) + [0]))
    nloc = n - dist.num_shard_bits(env.mesh)
    shard_bytes = 2 * (1 << nloc) * (4 if jnp.zeros(()).dtype == jnp.float32
                                     else 8)

    def fresh():
        a = rng.standard_normal((2, 1 << n))
        a /= np.sqrt((a ** 2).sum())
        return jax.device_put(jnp.asarray(a), env.amp_sharding())

    def run_gate(c):
        a = fresh()
        for _ in range(reps):
            a = dist.apply_matrix_1q_sharded(
                a, m, mesh=env.mesh, num_qubits=n, target=n - 1, chunks=c)
        a.block_until_ready()
        return a

    def run_swap(c):
        a = fresh()
        for _ in range(reps):
            a = dist.swap_sharded(a, mesh=env.mesh, num_qubits=n,
                                  qb_low=0, qb_high=n - 1, chunks=c)
        a.block_until_ready()
        return a

    def run_remap(c):
        a = fresh()
        for _ in range(reps):
            a = dist.remap_sharded(a, mesh=env.mesh, num_qubits=n,
                                   sigma=sigma, chunks=(c, c))
        a.block_until_ready()
        return a

    def permute_count(c):
        jfn = jax.jit(lambda a: dist.apply_matrix_1q_sharded(
            a, m, mesh=env.mesh, num_qubits=n, target=n - 1, chunks=c),
            donate_argnums=0)
        txt = jfn.lower(fresh()).compile().as_text()
        return (txt.count(" collective-permute(")
                + txt.count(" collective-permute-start("))

    sweep = {}
    compile_s = 0.0
    for c in (1, 2, 4, 8):
        gate_s, _, cs = _time_best(lambda c=c: run_gate(c))
        swap_s, _, _ = _time_best(lambda c=c: run_swap(c))
        remap_s, _, _ = _time_best(lambda c=c: run_remap(c))
        if c == 1:
            compile_s = cs
            mono = gate_s
        sweep[f"C{c}"] = {
            "gate_s": round(gate_s, 4), "swap_s": round(swap_s, 4),
            "remap_s": round(remap_s, 4),
            "gate_permute_dispatches": permute_count(c) * reps,
        }
    auto = dist.exchange_chunks(shard_bytes)
    auto_s, _, _ = _time_best(lambda: run_gate(None))
    _set_compile(compile_s)
    _emit(7, f"{n}q 8-shard pipelined-exchange wall-clock (auto C={auto})",
          auto_s, "seconds", auto_s,
          {"monolithic_seconds": mono,
           "auto_over_monolithic": round(auto_s / mono, 3),
           "chunk_sweep": sweep,
           "shard_bytes": shard_bytes,
           "remap_exchange_bytes_per_shard": CIRC.remap_exchange_bytes(
               sigma, n, nloc),
           "pipeline_min_bytes": dist.PIPELINE_MIN_BYTES})


def config8():
    """Telemetry-instrumented fused chain (ISSUE 4): runs with
    QT_TELEMETRY=on and dumps the full metrics snapshot JSON
    (TELEMETRY_snapshot.json, next to this timing line) so a bench run
    leaves behind the exchange/window/dispatch accounting of its own
    workload.  The <5% enabled-mode overhead gate is the separate
    scripts/bench_telemetry.py guard (make verify-telemetry)."""
    import quest_tpu as qt
    from quest_tpu import telemetry

    n = 10 if CPU else 22
    depth = 8
    env = qt.createQuESTEnv()
    sharded = env.num_devices >= 8 and (1 << n) >= 8 * env.num_devices
    rng = np.random.default_rng(23)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    prev_mode = telemetry.mode_name()
    telemetry.configure("on")

    def run():
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            for _ in range(depth):
                for t in range(n):
                    qt.hadamard(q, t)
                qt.multiQubitUnitary(q, [0, 1], u)
                if sharded:  # exercise the window-remap accounting
                    qt.multiQubitUnitary(q, [n - 2, n - 1], u)
        return qt.calcProbOfOutcome(q, 0, 0)

    try:
        seconds, prob, compile_s = _time_best(run)
        telemetry.reset()
        run()  # the snapshot reflects exactly ONE instrumented run
        snap = telemetry.snapshot()
        path = os.path.abspath("TELEMETRY_snapshot.json")
        with open(path, "w") as f:
            json.dump(snap, f, indent=1)
        _set_compile(compile_s)
        _emit(8, f"{n}q telemetry-instrumented fused chain", seconds,
              "seconds", seconds,
              {"prob": prob, "snapshot_file": path,
               "exchanges_total": telemetry.counter_total(
                   "exchanges_total"),
               "exchange_bytes_total": telemetry.counter_total(
                   "exchange_bytes_total"),
               "fusion_windows_total": telemetry.counter_total(
                   "fusion_windows_total"),
               "dispatch_total": telemetry.counter_total(
                   "dispatch_total")})
    finally:
        telemetry.configure(prev_mode)


def config9():
    """Batched-vs-looped ensemble A/B (round-11): B copies of a depth-4
    layered ansatz as one (B, 2, 2^n) BatchedQureg bank against B
    independent scalar runs, B in {1, 4, 16, 64}.  The per-B timing rows
    (circuits/sec both arms, per-circuit latency, speedup) land in the
    standard BENCH artifact; the >= 4x-at-B=16 acceptance gate is the
    separate scripts/bench_batch.py guard (make verify-batch)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_batch

    n = 10 if CPU else 20
    t0 = time.perf_counter()
    _env, rows = bench_batch.run_ab(n, depth=4, batches=[1, 4, 16, 64],
                                    reps=3)
    _set_compile(0.0)  # warm-up folded into each row's own best-of loop
    at16 = next(r for r in rows if r["batch"] == 16)
    _emit(9, f"{n}q batched-vs-looped ensemble throughput",
          at16["batched_circuits_per_sec"], "circuits_per_sec",
          round(time.perf_counter() - t0, 3),
          {"speedup_at_16": at16["speedup"],
           "per_circuit_ms_at_16": at16["batched_per_circuit_ms"],
           "results": rows})


def config10():
    """Plan-explainer snapshot (ISSUE 8): dry-run the fusion planner over
    the config-6 workload (the 8-shard alternating local/sharded 2q
    stream) with introspect.explain_circuit — no device execution — and
    dump the per-window report (EXPLAIN_snapshot.json, the predictive
    twin of config 8's post-hoc TELEMETRY_snapshot.json).  The stream is
    then actually drained so the timing line carries the reconciliation
    verdict: predicted vs measured window-remap exchanges and
    model_drift_total (0 = the cost model holds)."""
    import quest_tpu as qt
    from quest_tpu import telemetry

    env = qt.createQuESTEnv()
    if env.num_devices < 8:
        _emit(10, "plan-explainer snapshot (SKIPPED: needs 8 amp shards)",
              0.0, "seconds", 0.0)
        return
    n = 10 if CPU else 24
    depth = 12
    rng = np.random.default_rng(11)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    prev_mode = telemetry.mode_name()
    telemetry.configure("on")
    try:
        q = qt.createQureg(n, env)
        qt.startGateFusion(q)
        for _ in range(depth):
            qt.multiQubitUnitary(q, [0, 1], u)          # shard-local
            qt.multiQubitUnitary(q, [n - 2, n - 1], u)  # sharded targets
        t0 = time.perf_counter()
        report = qt.explainCircuit(q)   # dry-run: nothing executes
        explain_s = time.perf_counter() - t0
        path = os.path.abspath("EXPLAIN_snapshot.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=1)
        telemetry.reset()
        qt.stopGateFusion(q)            # the real drain
        measured = telemetry.counter_sum("exchanges_total",
                                         op="window_remap")
        measured_bytes = telemetry.counter_sum("exchange_bytes_total",
                                               op="window_remap")
        _set_compile(0.0)  # the explainer never traces
        _emit(10, f"{n}q 8-shard plan-explainer dryrun", explain_s,
              "seconds", explain_s,
              {"snapshot_file": path,
               "windows": report["totals"]["windows"],
               "predicted_exchanges": report["totals"]["exchanges"],
               "predicted_exchange_bytes":
                   report["totals"]["exchange_bytes"],
               "measured_exchanges": measured,
               "measured_exchange_bytes": measured_bytes,
               "model_drift_total": telemetry.counter_total(
                   "model_drift_total")})
    finally:
        telemetry.configure(prev_mode)


def config11():
    """Budget-constrained A/B (ISSUE 9): the config-10 style alternating
    local/sharded 2q stream, run once unconstrained and once under a
    QT_HBM_BUDGET_BYTES pinned just below the unconstrained predicted
    peak — the memory governor walks its degradation ladder (exchange
    -chunk bump / program split / spill) and the run must still complete
    bit-identically.  Dumps the predictor numbers, ladder counters, and
    both timings (GOVERNOR_snapshot.json, the memory twin of config 8's
    TELEMETRY_snapshot.json)."""
    import warnings

    import quest_tpu as qt
    from quest_tpu import governor, telemetry

    env = qt.createQuESTEnv()
    n = 13 if CPU else 24
    depth = 6
    rng = np.random.default_rng(29)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)

    def run():
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            for _ in range(depth):
                qt.multiQubitUnitary(q, [0, 1], u)          # shard-local
                qt.multiQubitUnitary(q, [n - 2, n - 1], u)  # sharded
        amps = np.asarray(q.amps)
        qt.destroyQureg(q, env)
        return amps

    prev_mode = telemetry.mode_name()
    telemetry.configure("on")
    os.environ.pop("QT_HBM_BUDGET_BYTES", None)
    governor.reset()
    try:
        run()  # warm the plan + executor caches
        t0 = time.perf_counter()
        want = run()
        free_s = time.perf_counter() - t0

        # the unconstrained predicted peak for this exact stream
        os.environ["QT_HBM_BUDGET_BYTES"] = str(1 << 40)
        governor.reset()
        q = qt.createQureg(n, env)
        with qt.gateFusion(q):
            for _ in range(depth):
                qt.multiQubitUnitary(q, [0, 1], u)
                qt.multiQubitUnitary(q, [n - 2, n - 1], u)
            prediction = governor.explain_memory(q, q._fusion.gates)
        qt.destroyQureg(q, env)

        budget = prediction["predicted_total_bytes"] - 1
        os.environ["QT_HBM_BUDGET_BYTES"] = str(budget)
        governor.reset()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            run()  # warm under the constrained config
            t0 = time.perf_counter()
            got = run()
            governed_s = time.perf_counter() - t0
        identical = bool(np.array_equal(want, got))
        snap = {
            "budget_bytes": budget,
            "prediction": prediction,
            "bit_identical": identical,
            "unconstrained_seconds": round(free_s, 5),
            "governed_seconds": round(governed_s, 5),
            "degradations": telemetry.snapshot().get("counters", {}).get(
                "governor_degradations_total", {}),
            "spills_total": telemetry.counter_total("spills_total"),
            "spill_bytes_total": telemetry.counter_total(
                "spill_bytes_total"),
            "oom_retries_total": telemetry.counter_total(
                "oom_retries_total"),
        }
        path = os.path.abspath("GOVERNOR_snapshot.json")
        with open(path, "w") as f:
            json.dump(snap, f, indent=1)
        _set_compile(0.0)  # warmed above under each config
        _emit(11, f"{n}q budget-constrained governed drain", governed_s,
              "seconds", governed_s,
              {"snapshot_file": path,
               "unconstrained_seconds": round(free_s, 5),
               "governed_over_unconstrained": round(
                   governed_s / free_s, 3) if free_s else None,
               "budget_bytes": budget,
               "predicted_peak_bytes":
                   prediction["predicted_peak_bytes"],
               "bit_identical": identical})
    finally:
        os.environ.pop("QT_HBM_BUDGET_BYTES", None)
        governor.reset()
        telemetry.configure(prev_mode)


def config12():
    """Multi-tenant serving saturation A/B (ISSUE 11): a seeded
    open-loop Poisson arrival trace replayed against the continuous
    batcher (quest_tpu.serve.SimServer, window-granular admission +
    preempt-to-checkpoint) and against batch-at-once per-request
    EnsembleScheduler drains.  The timing line carries the serving
    headline (continuous circuits/sec) plus the A/B speedup, bank
    occupancy, and per-class p50/p99 latency; the >= 2x-throughput /
    <= 2x-interactive-p99 acceptance gates are the separate
    scripts/bench_serve.py guard (make verify-serve)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_serve

    n = 8
    t0 = time.perf_counter()
    # the trace length is NOT scaled down on CPU: the continuous win
    # comes from backlog coalescing into full banks, which a short
    # trace never builds
    rec = bench_serve.run(n=n, reps=1 if CPU else 2)
    _set_compile(0.0)  # warm-up/calibration folded into run()'s phases
    cont = rec["continuous"]
    _emit(12, f"{n}q continuous-batching serving throughput",
          cont["circuits_per_sec"], "circuits_per_sec",
          round(time.perf_counter() - t0, 3),
          {"speedup_vs_batch_at_once": rec["speedup"],
           "baseline_circuits_per_sec":
               rec["baseline"]["circuits_per_sec"],
           "bank_occupancy_mean": cont["bank_occupancy_mean"],
           "interactive_p99_ratio": rec["interactive_p99_ratio"],
           "interactive_e2e": cont.get("interactive", {}).get("e2e"),
           "preemptions": cont["preemptions"],
           "resumes": cont["resumes"],
           "arrival_rate_per_sec": rec["arrival_rate_per_sec"]})


def config13():
    """Pod-topology tier-aware planner A/B (ISSUE 12): the config-6
    style churn workload drained on the emulated slow-DCN 2x4 topology
    under the flat vs the hierarchical remap planner
    (scripts/bench_pod.py).  The timing line carries the measured DCN
    byte reduction (the headline — must be >= 2x, gated separately by
    make verify-pod) plus the modeled reduction, the weighted-cost
    ratio, and the bit-identity/drift checks."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_pod

    t0 = time.perf_counter()
    try:
        rec = bench_pod.run(n=10 if CPU else 24, reps=10)
    except RuntimeError as e:
        _emit(13, f"2x4 tier-aware DCN reduction (SKIPPED: {e})",
              0.0, "dcn_reduction_x", 0.0)
        return
    _set_compile(0.0)  # both arms warm inside run()
    _emit(13, f"{rec['n']}q 2x4 tier-aware DCN byte reduction",
          rec["measured_dcn_reduction"], "dcn_reduction_x",
          round(time.perf_counter() - t0, 3),
          {"modeled_dcn_reduction": rec["modeled_dcn_reduction"],
           "weighted_cost_reduction": rec["weighted_cost_reduction"],
           "flat_dcn_bytes": rec["flat"]["measured"].get("dcn", 0),
           "hier_dcn_bytes": rec["hier"]["measured"].get("dcn", 0),
           "bit_identical": rec["bit_identical"],
           "model_drift": rec["flat"]["drift"] + rec["hier"]["drift"],
           "topology": rec["topology"]})


def config14():
    """Circuit-optimizer A/B (ISSUE 13): QT_OPTIMIZER=on vs off on a
    config-2-style random circuit, a QFT-like phase-heavy ladder, and
    the config-6-style remap churn (scripts/bench_optimizer.py).  The
    timing line carries the headline wall-clock speedup plus per-workload
    exchange reductions and the parity/drift checks."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_optimizer

    t0 = time.perf_counter()
    try:
        rec = bench_optimizer.run(n=10 if CPU else 24,
                                  depth=24 if CPU else 60)
    except RuntimeError as e:
        _emit(14, f"optimizer A/B (SKIPPED: {e})", 0.0, "speedup_x", 0.0)
        return
    _set_compile(0.0)  # both arms warm inside run()
    w = rec["workloads"]
    _emit(14, f"{rec['n']}q circuit-optimizer wall-clock speedup",
          rec["optimizer_speedup_x"], "speedup_x",
          round(time.perf_counter() - t0, 3),
          {name: {"speedup_x": r["speedup_x"],
                  "exchange_reduction_x": r["exchange_reduction_x"],
                  "gates": f"{r['on']['gates_in']}->{r['on']['gates_out']}",
                  "max_abs_err": r["max_abs_err"],
                  "drift": r["on"]["drift"] + r["off"]["drift"]}
           for name, r in w.items()})


def config15():
    """Serving-layer chaos replay (ISSUE 14): the seeded fault-injection
    harness (scripts/chaos_serve.py) replays three deterministic
    multi-tenant traces — fault-free baseline vs a FaultPlan covering
    bank faults, checkpoint-IO faults, shard AND host loss + mesh heal,
    OOM bisection, and a NaN-poisoned job.  The timing line carries the
    non-poison availability headline (must be 100%, gated separately by
    make verify-chaos) plus failover MTTR, bit-identity, and the
    retry/quarantine/failover/heal counters."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import chaos_serve

    t0 = time.perf_counter()
    rec = chaos_serve.run()
    _set_compile(0.0)  # A/B replays warm inside run()
    _emit(15, "serving chaos replay non-poison availability",
          rec["availability_pct"], "chaos_availability_pct",
          round(time.perf_counter() - t0, 3),
          {"ok": rec["ok"],
           "failover_mttr_seconds": rec["failover_mttr_seconds"],
           "failovers": rec["failovers"],
           "heals": rec["heals"],
           "bank_retries": rec["bank_retries"],
           "quarantined": rec["quarantined"],
           "bit_identical": rec["bit_identical"],
           "completed": rec["completed"],
           "seeds": rec["seeds"]})


def config16():
    """Permutation fast paths + sparse state prep (ISSUE 15):
    QT_PERM_FAST=on vs off on a ripple-carry-adder-style CNOT/Toffoli
    chain, a relabel-only SWAP churn, and sparse clustered-state
    preparation (scripts/bench_sparse.py, arXiv:2504.08705).  Two
    timing lines: the permutation wall-clock speedup and the
    sparse-init speedup, each with the parity/drift/zero-collective
    checks in tow."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_sparse

    t0 = time.perf_counter()
    try:
        rec = bench_sparse.run(n=16 if CPU else 26,
                               depth=60 if CPU else 100)
    except RuntimeError as e:
        _emit(16, f"perm fast-path A/B (SKIPPED: {e})", 0.0,
              "perm_speedup_x", 0.0)
        return
    _set_compile(0.0)  # both arms warm inside run()
    seconds = round(time.perf_counter() - t0, 3)
    w = rec["workloads"]
    _emit(16, f"{rec['n']}q permutation-lowering wall-clock speedup",
          rec["perm_speedup_x"], "perm_speedup_x", seconds,
          {name: {"speedup_x": w[name]["speedup_x"],
                  "max_abs_err": w[name]["max_abs_err"],
                  "drift": w[name]["on"]["drift"]
                  + w[name]["off"]["drift"]}
           for name in ("relabel", "ripple")}
          | {"relabel_read_collectives":
             sum(w["relabel"]["read_collectives"].values()),
             "relabel_window_exchanges":
             w["relabel"]["on"]["window_remap_exchanges"]})
    _emit(16, f"{rec['n']}q sparse clustered-state init speedup",
          rec["sparse_init_speedup_x"], "sparse_init_speedup_x", seconds,
          {"nonzeros": w["sparse"]["sparse"]["nonzeros"],
           "max_abs_err": w["sparse"]["max_abs_err"]})


def config17():
    """Window megakernel (ISSUE 18 / docs/design.md §29):
    QT_MEGAKERNEL=on vs off on the dense-window drain
    (scripts/bench_megakernel.py).  One timing line —
    ``megakernel_speedup_x``, the chained-plan device marginal of the
    off arm over the on arm — with bit-parity, drift==0-both-arms, and
    megawin-routing checks in tow."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_megakernel

    t0 = time.perf_counter()
    # devices=1 under the CPU smoke mesh: sharding 14q across the 8
    # virtual devices leaves nloc below the fused-window size, so the
    # drain-half routing telemetry would be vacuous
    rec = bench_megakernel.run(n=14 if CPU else 22,
                               depth=60 if CPU else 40,
                               devices=1 if CPU else None)
    _set_compile(0.0)  # both arms warm inside run()
    seconds = round(time.perf_counter() - t0, 3)
    _emit(17, f"{rec['n']}q dense-window megakernel A/B speedup",
          rec["megakernel_speedup_x"], "megakernel_speedup_x", seconds,
          {"max_abs_err": rec["max_abs_err"],
           "drift": rec["drain"]["on"]["drift"]
           + rec["drain"]["off"]["drift"],
           "programs_per_iter_off":
           rec["plan"]["off"]["programs_per_iter"],
           "programs_per_iter_on": rec["plan"]["on"]["programs_per_iter"],
           "megawin_groups": rec["plan"]["on"]["megawin_groups"],
           "mega_dispatches": rec["drain"]["on"]["mega_dispatches"],
           "hbm_round_trips_per_window":
           rec["drain"]["on"]["hbm_round_trips_per_window"]})


def config18():
    """Observability front door (ISSUE 19 / docs/design.md §30): one
    chaotic serving run with the live HTTP endpoint up — scrapes
    /metrics and /healthz over the wire, dumps the per-job request
    traces (tracez span trees) and the incident flight records to a
    demo directory, and reports trace completeness.  The timing line
    carries the count of completed jobs whose span trees reconstruct
    complete, plus the flight-dump reasons and artifact paths."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import json
    import tempfile
    import urllib.request

    import chaos_serve as cs

    import quest_tpu as qt
    from quest_tpu import resilience as R
    from quest_tpu import serve as S
    from quest_tpu import telemetry as T

    t0 = time.perf_counter()
    demo_dir = tempfile.mkdtemp(prefix="qt_obs_demo_")
    old_dir = os.environ.get("QT_SERVE_FLIGHT_DIR")
    os.environ["QT_SERVE_FLIGHT_DIR"] = demo_dir
    try:
        env = qt.createQuESTEnv()
        plan_spec, poisoned = cs._schedule(11)
        server = S.SimServer(env, window=cs.WINDOW, max_batch=4,
                             retries=4, watchdog=1,
                             quarantine=(100, 3600.0),
                             faults=R.FaultPlan(plan_spec))
        try:
            host, port = server.serve_http()
            handles = []
            for i, (tenant, theta, prio, measure) in enumerate(
                    cs._trace(11)):
                handles.append(server.submit(
                    cs._circ(theta), num_qubits=cs.N, tenant=tenant,
                    priority=prio, measure=measure))
                if i % 3 == 2:
                    for _ in range(2):
                        server.step()
            server.run_until_idle(max_steps=cs.STEP_BOUND)
            base = f"http://{host}:{port}"
            metrics = urllib.request.urlopen(
                base + "/metrics").read().decode()
            healthz = json.loads(urllib.request.urlopen(
                base + "/healthz").read().decode())
            traces = {h.id: server.tracez(h) for h in handles}
            trace_path = os.path.join(demo_dir, "job_traces.json")
            with open(trace_path, "w") as f:
                json.dump(traces, f, sort_keys=True)
            done = sum(1 for h in handles if h.state == "done")
            complete = sum(1 for tz in traces.values()
                           if tz and tz.get("complete"))
            reasons = []
            for path in server.flight_dumps:
                with open(path) as f:
                    reasons.append(json.load(f)["reason"])
            dump_count = len(server.flight_dumps)
        finally:
            server.close()
    finally:
        if old_dir is None:
            os.environ.pop("QT_SERVE_FLIGHT_DIR", None)
        else:
            os.environ["QT_SERVE_FLIGHT_DIR"] = old_dir
    _set_compile(0.0)  # host-side scheduling demo; no fresh kernels
    _emit(18, "observability: complete request traces under chaos",
          float(complete), "traces_complete",
          round(time.perf_counter() - t0, 3),
          {"jobs_done": done,
           "poisoned": sorted(poisoned),
           "metrics_live": metrics == T.prometheus_text(),
           "healthz_status": healthz["status"],
           "flight_dumps": dump_count,
           "flight_dump_reasons": reasons,
           "demo_dir": demo_dir,
           "job_traces": trace_path})


def config19():
    """Cold-start elimination (ISSUE 20 / docs/design.md §31): the
    persistent AOT executable cache measured where it matters — the
    first-request latency of a FRESH PROCESS.  scripts/bench_coldstart
    launches the same sharded workload twice against one QT_AOT_CACHE
    directory (empty, then warm) in subprocesses; the second child must
    deserialize instead of compiling.  Emits the uncached/cached
    first-request ratio — higher is better, and a regression that
    reintroduces the compile collapses it toward 1."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_coldstart as bc

    t0 = time.perf_counter()
    rec = bc.run(check=False)
    _set_compile(rec["uncached_first_s"])  # the cost the cache removes
    _emit(19, "cold start: fresh-process first-request speedup",
          rec["value"], "coldstart_speedup_x",
          round(time.perf_counter() - t0, 3),
          {"uncached_first_s": rec["uncached_first_s"],
           "cached_first_s": rec["cached_first_s"],
           "cached_steady_s": rec["cached_steady_s"],
           "cached_hits": rec["cached_aot"]["hits"],
           "cached_puts": rec["cached_aot"]["puts"],
           "bit_identical": rec["bit_identical"]})


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config8, 9: config9, 10: config10,
           11: config11, 12: config12, 13: config13, 14: config14,
           15: config15, 16: config16, 17: config17, 18: config18,
           19: config19}


def main():
    if "--config" in sys.argv:
        which = [int(sys.argv[sys.argv.index("--config") + 1])]
    else:
        which = sorted(CONFIGS)
    for c in which:
        CONFIGS[c]()


if __name__ == "__main__":
    main()
