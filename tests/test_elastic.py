"""Elastic recovery suite (ISSUE 6): mesh-portable checkpoints, guarded
collectives, and degraded-mesh failover.

Pins the acceptance contract:

* a checkpoint written on an 8-shard mesh RESUMES on 4 / 1 / 16 shards
  BIT-IDENTICAL to an uninterrupted run on the target mesh — including a
  live non-identity logical->physical permutation at the kill point and
  the measurement-RNG state (the restore must overwrite a reseed);
* ``strict_mesh=True`` preserves the old refusal on any shard-count
  difference (both load_latest and loadQureg);
* runtime-config drift between save and resume (QT_EXCHANGE_CHUNKS,
  QT_TELEMETRY) does not perturb the resumed amplitudes;
* a corrupt LATEST pointer / corrupt or perm-garbled newest generation
  falls back on the elastic path exactly as on the same-mesh path;
* an injected ``shard_loss`` mid-run triggers automatic rollback + mesh
  shrink + resume with a correct final state, observable via
  failovers_total, the MTTR phase gauges, the degradation registry, and
  getEnvironmentString; an injected ``stall`` is absorbed by the guard's
  retry budget without failover.

Marked ``slow``: the tier-1 gate (-m 'not slow') runs within a hard
wall-clock budget the seed suite nearly fills; this suite's full
save/resume cycles run under ``make verify-elastic`` and
``make verify-faults`` instead.  The cheap unit contracts (guarded
dispatch, FaultPlan arming, _validated_perm) stay tier-1 in
test_resilience.py.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion as F
from quest_tpu import resilience as R
from quest_tpu import rng as qt_rng
from quest_tpu import telemetry as T
from quest_tpu.parallel import dist as PAR

pytestmark = [pytest.mark.faults, pytest.mark.slow]

N = 6  # 64 amps: shardable over 1..16 devices with local qubits to spare

H_SOA = np.stack([(1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]]),
                  np.zeros((2, 2))])
CX_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]]),
    np.zeros((4, 4)),
])

EVERY = 8
KILL_CURSOR = 3 * EVERY  # kill@3 -> last committed generation is gen 24


@pytest.fixture(autouse=True)
def _fast_retry(monkeypatch):
    monkeypatch.setenv("QT_RETRY_BASE_SECONDS", "0.001")


def _circuit(n=N, depth=4):
    """Entangling brickwork touching the sharded high qubits, so window
    drains leave a live permutation behind (same shape as
    test_resilience's)."""
    gates = []
    for _ in range(depth):
        for t in range(n):
            gates.append(CIRC.Gate((t,), H_SOA))
        for t in range(n - 1):
            gates.append(CIRC.Gate((t, t + 1), CX_SOA))
    return gates


def _fresh(env, n=N, seed=7):
    qt.seedQuEST(env, [seed])
    return qt.createQureg(n, env)


@pytest.fixture(scope="module")
def env4():
    return qt.createQuESTEnv(num_devices=4)


@pytest.fixture(scope="module")
def env1():
    return qt.createQuESTEnv(num_devices=1)


@pytest.fixture(scope="module")
def ref4(env4, tmp_path_factory):
    """Uninterrupted 4-device run: final amplitudes + the next host
    uniform draw (the RNG-state anchor for the elastic resumes)."""
    q = _fresh(env4)
    qt.run_resumable(q, _circuit(), str(tmp_path_factory.mktemp("ref4")),
                     every=EVERY)
    return np.asarray(q.amps), qt_rng.GLOBAL_RNG.uniform()


@pytest.fixture(scope="module")
def killed8(env, tmp_path_factory):
    """A checkpoint dir left by an 8-device run preempted before window 3
    — the source every elastic resume restores from (copied per test, so
    each resume genuinely starts mid-circuit)."""
    d = str(tmp_path_factory.mktemp("killed8"))
    q = _fresh(env)
    with pytest.raises(qt.SimulatedPreemption):
        qt.run_resumable(q, _circuit(), d, every=EVERY,
                         faults=qt.FaultPlan("kill@3"))
    return d


def _copy(src: str, tmp_path) -> str:
    dst = str(tmp_path / "ckpt")
    shutil.copytree(src, dst)
    return dst


def _resume(target_env, ckpt_dir: str, seed=999):
    """Resume the killed run on ``target_env``; the deliberately WRONG
    seed proves the restore overwrites the live RNG state."""
    q = _fresh(target_env, seed=seed)
    qt.run_resumable(q, _circuit(), ckpt_dir, every=EVERY)
    return q


class TestElasticResume:
    def test_killed_checkpoint_has_live_perm_and_mesh_meta(self, killed8,
                                                           env):
        """The source checkpoint really exercises the hard case: a
        non-identity logical->physical permutation, mid-circuit cursor,
        and the writing mesh's shard count in the metadata."""
        q, meta = R.load_latest(killed8, env)
        assert meta["cursor"] == KILL_CURSOR
        assert meta["mesh_shards"] == 8
        perm = meta["perm"]
        assert perm is not None
        assert sorted(perm) == list(range(N))
        assert perm != list(range(N))
        assert q._perm == tuple(perm)

    def test_resume_8_to_4_bit_identical(self, killed8, env4, ref4,
                                         tmp_path):
        before = T.counter_total("elastic_restores_total")
        q = _resume(env4, _copy(killed8, tmp_path))
        assert np.array_equal(np.asarray(q.amps), ref4[0])
        # the checkpointed RNG state (seed 7) overwrote the seed-999
        # reseed, so the post-run draw matches the uninterrupted run's
        assert qt_rng.GLOBAL_RNG.uniform() == ref4[1]
        assert T.counter_total("elastic_restores_total") > before

    def test_resume_8_to_1_bit_identical(self, killed8, env1, ref4,
                                         tmp_path):
        q = _resume(env1, _copy(killed8, tmp_path))
        assert q.env.num_devices == 1
        assert np.array_equal(np.asarray(q.amps), ref4[0])
        assert qt_rng.GLOBAL_RNG.uniform() == ref4[1]

    def test_same_mesh_resume_unchanged(self, killed8, env, ref4, tmp_path):
        """The elastic machinery must not perturb the classic same-mesh
        resume (8->8 == uninterrupted 4-dev run by cross-mesh equality)."""
        q = _resume(env, _copy(killed8, tmp_path))
        assert np.array_equal(np.asarray(q.amps), ref4[0])

    def test_strict_mesh_refuses_shard_count_change(self, killed8, env,
                                                    env4):
        with pytest.raises(qt.QuESTError, match="mesh mismatch"):
            R.load_latest(killed8, env4, strict_mesh=True)
        # same mesh still loads under strict
        q, meta = R.load_latest(killed8, env, strict_mesh=True)
        assert meta["cursor"] == KILL_CURSOR


_ELASTIC_16 = r"""
import sys
sys.path.insert(0, {repo!r})
import os
os.environ["QT_RETRY_BASE_SECONDS"] = "0.001"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import quest_tpu as qt
from quest_tpu import circuit as CIRC, resilience as R

qt.set_precision(2)
N = 6
H = np.stack([(1/np.sqrt(2))*np.array([[1.0,1],[1,-1]]), np.zeros((2,2))])
CX = np.stack([np.array([[1.0,0,0,0],[0,1,0,0],[0,0,0,1],[0,0,1,0]]),
               np.zeros((4,4))])
def circ(depth=4):
    g = []
    for _ in range(depth):
        for t in range(N): g.append(CIRC.Gate((t,), H))
        for t in range(N-1): g.append(CIRC.Gate((t,t+1), CX))
    return g

env16 = qt.createQuESTEnv()
assert env16.num_ranks == 16, env16.num_ranks
env8 = qt.createQuESTEnv(num_devices=8)

qt.seedQuEST(env16, [7]); q16 = qt.createQureg(N, env16)
qt.run_resumable(q16, circ(), "ref16", every=8)
a16 = np.asarray(q16.amps)

qt.seedQuEST(env8, [7]); q = qt.createQureg(N, env8)
try:
    qt.run_resumable(q, circ(), "killed8", every=8,
                     faults=qt.FaultPlan("kill@3"))
    raise SystemExit("kill did not fire")
except qt.SimulatedPreemption:
    pass
_, meta = R.load_latest("killed8", env8)
assert meta["mesh_shards"] == 8 and meta["cursor"] == 24, meta
assert meta["perm"] is not None and meta["perm"] != list(range(N)), meta

qt.seedQuEST(env16, [999]); q2 = qt.createQureg(N, env16)
qt.run_resumable(q2, circ(), "killed8", every=8)
assert np.array_equal(np.asarray(q2.amps), a16)
print("ELASTIC16 OK 8->16 bitwise")
"""


def test_resume_8_to_16_bit_identical(tmp_path):
    """The growing direction needs more devices than the in-process
    virtual backend holds, so it runs in a 16-device subprocess (same
    pattern as test_mesh_sweep's 16-device smoke)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env.pop("JAX_PLATFORMS", None)
    code = _ELASTIC_16.format(repo=repo)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd=str(tmp_path), capture_output=True,
                          text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "ELASTIC16 OK 8->16 bitwise" in proc.stdout


class TestConfigDrift:
    """Runtime config changed between save and resume must not perturb
    the resumed amplitudes (the checkpoint carries STATE, not config)."""

    def test_exchange_chunks_drift(self, env, env4, ref4, tmp_path,
                                   monkeypatch):
        d = str(tmp_path / "ck")
        monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "2")
        q = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), d, every=EVERY,
                             faults=qt.FaultPlan("kill@3"))
        monkeypatch.delenv("QT_EXCHANGE_CHUNKS")
        q2 = _resume(env4, d)
        assert np.array_equal(np.asarray(q2.amps), ref4[0])

    def test_telemetry_mode_drift(self, env, env4, ref4, tmp_path):
        d = str(tmp_path / "ck")
        old = T.mode_name()
        try:
            T.configure("on")
            q = _fresh(env)
            with pytest.raises(qt.SimulatedPreemption):
                qt.run_resumable(q, _circuit(), d, every=EVERY,
                                 faults=qt.FaultPlan("kill@3"))
            T.configure("off")
            q2 = _resume(env4, d)
        finally:
            T.configure(old)
        assert np.array_equal(np.asarray(q2.amps), ref4[0])


class TestElasticFallbacks:
    """Corruption handling must be no weaker on the cross-mesh path."""

    def test_corrupt_latest_pointer(self, killed8, env4, ref4, tmp_path):
        d = _copy(killed8, tmp_path)
        with open(os.path.join(d, "LATEST"), "w") as f:
            f.write("gen-NOT-A-CURSOR\n")
        q = _resume(env4, d)
        assert np.array_equal(np.asarray(q.amps), ref4[0])

    def test_corrupt_newest_generation_falls_back(self, killed8, env4,
                                                  ref4, tmp_path):
        d = _copy(killed8, tmp_path)
        R._corrupt_generation(os.path.join(d, R._gen_name(KILL_CURSOR)))
        with pytest.warns(UserWarning, match="unreadable"):
            q = _resume(env4, d)
        assert np.array_equal(np.asarray(q.amps), ref4[0])

    def test_missing_newest_generation_falls_back(self, killed8, env4,
                                                  ref4, tmp_path):
        d = _copy(killed8, tmp_path)
        shutil.rmtree(os.path.join(d, R._gen_name(KILL_CURSOR)))
        q = _resume(env4, d)
        assert np.array_equal(np.asarray(q.amps), ref4[0])

    def test_garbled_perm_treated_as_corrupt(self, killed8, env4, ref4,
                                             tmp_path):
        """A torn metadata write that mangles the carried permutation must
        fall back to the predecessor, not restore a wrong bit layout."""
        import json

        d = _copy(killed8, tmp_path)
        meta_path = os.path.join(d, R._gen_name(KILL_CURSOR),
                                 "qureg_meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        meta["perm"] = [0] * N  # not a permutation
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        with pytest.warns(UserWarning, match="unreadable"):
            q = _resume(env4, d)
        assert np.array_equal(np.asarray(q.amps), ref4[0])

@pytest.fixture
def _clean_failover_state():
    """Failover records a process-global degradation (warned once per
    name); drop the keys afterwards so runs stay independent."""
    yield
    for key in list(R.DEGRADATIONS):
        if key.startswith(("mesh_failover_", "loadQureg_mesh_")):
            del R.DEGRADATIONS[key]


class TestFailover:
    def test_shard_loss_triggers_rollback_shrink_resume(
            self, env, ref4, tmp_path, _clean_failover_state):
        before = T.counter_total("failovers_total")
        q = _fresh(env)
        plan = qt.FaultPlan("shard_loss@2")
        with pytest.warns(UserWarning, match="mesh_failover_8to4"):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"),
                             every=EVERY, faults=plan)
        assert plan.log == ["shard_loss@2"]
        # the run completed on the surviving half of the mesh...
        assert q.env.num_devices == 4
        # ...with the uninterrupted 4-device run's exact amplitudes
        assert np.array_equal(np.asarray(q.amps), ref4[0])
        assert T.counter_total("failovers_total") == before + 1
        assert "mesh_failover_8to4" in qt.degradation_report()
        # MTTR phase gauges: detect -> rollback -> reshard -> resume
        gauges = T.snapshot()["gauges"]
        for phase in ("detect", "rollback", "reshard", "resume"):
            name = f"failover_{phase}_seconds"
            assert name in gauges, f"missing MTTR gauge {name}"
            assert list(gauges[name].values())[0] >= 0.0
        # observable without touching telemetry internals
        assert "Failovers=" in qt.getEnvironmentString(env)

    def test_host_loss_fails_over_onto_surviving_host(
            self, ref4, tmp_path, monkeypatch, _clean_failover_state):
        """Satellite (ISSUE 12): a lost HOST on the emulated 2x4
        topology.  The fault reports a shard on host 1; the failover
        excludes that host's whole device range and resumes on the
        intact host's 1x4 mesh — bit-identically to the uninterrupted
        4-device run."""
        monkeypatch.setenv("QT_TOPOLOGY", "2x4")
        henv = qt.createQuESTEnv()
        if henv.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        assert (henv.topology.hosts, henv.topology.chips) == (2, 4)
        q = _fresh(henv)
        plan = qt.FaultPlan("host_loss@2")
        with pytest.warns(UserWarning, match="mesh_failover_8to4"):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"),
                             every=EVERY, faults=plan)
        assert plan.log == ["host_loss@2"]
        # the survivors are the intact host: chips preserved, one host
        assert q.env.num_devices == 4
        assert (q.env.topology.hosts, q.env.topology.chips) == (1, 4)
        assert np.array_equal(np.asarray(q.amps), ref4[0])
        report = qt.degradation_report()["mesh_failover_8to4"]
        assert "(host 1 excluded)" in report

    def test_host_loss_elastic_false_propagates(self, env, tmp_path):
        q = _fresh(env)
        with pytest.raises(PAR.ShardLossError):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"),
                             every=EVERY, faults=qt.FaultPlan("host_loss@2"),
                             elastic=False)

    def test_stall_absorbed_by_retry_budget(self, env, ref4, tmp_path):
        before = T.counter_total("exchange_timeouts_total")
        q = _fresh(env)
        plan = qt.FaultPlan("stall@1")
        qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=EVERY,
                         faults=plan)
        assert plan.log == ["stall@1"]
        assert q.env.num_devices == 8  # no failover
        assert T.counter_total("exchange_timeouts_total") > before
        assert np.array_equal(np.asarray(q.amps), ref4[0])

    def test_elastic_false_propagates_shard_loss(self, env, tmp_path):
        q = _fresh(env)
        with pytest.raises(PAR.ShardLossError):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"),
                             every=EVERY, faults=qt.FaultPlan("shard_loss@2"),
                             elastic=False)

    def test_shard_loss_before_first_checkpoint_raises(self, env, tmp_path):
        """No committed generation to roll back to -> a structured error,
        not a silent restart from |0...0>."""
        q = _fresh(env)
        with pytest.raises(qt.QuESTError, match="cannot fail over"):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"),
                             every=EVERY, faults=qt.FaultPlan("shard_loss@0"))

    def test_exchange_latency_histogram_recorded(self, env, tmp_path):
        q = _fresh(env)
        qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=EVERY)
        hists = T.snapshot()["histograms"]
        assert "exchange_latency_seconds" in hists
        assert any("op=" in k for k in hists["exchange_latency_seconds"])


class TestLoadQuregElastic:
    def test_cross_mesh_roundtrip(self, env, env4, tmp_path):
        q = _fresh(env)
        qt.hadamard(q, 0)
        qt.controlledNot(q, 0, N - 1)
        want = np.asarray(q.amps)
        qt.saveQureg(q, str(tmp_path / "ck"))
        before = T.counter_total("elastic_restores_total")
        q2 = qt.loadQureg(str(tmp_path / "ck"), env4)
        assert q2.env.num_devices == 4
        assert np.array_equal(np.asarray(q2.amps), want)
        assert T.counter_total("elastic_restores_total") > before

    def test_strict_mesh_refuses_shard_count_change(self, env, env4,
                                                    tmp_path):
        q = _fresh(env)
        qt.saveQureg(q, str(tmp_path / "ck"))
        with pytest.raises(qt.QuESTError, match="mesh mismatch"):
            qt.loadQureg(str(tmp_path / "ck"), env4, strict_mesh=True)
        # same mesh still loads under strict
        q2 = qt.loadQureg(str(tmp_path / "ck"), env, strict_mesh=True)
        assert np.array_equal(np.asarray(q2.amps), np.asarray(q.amps))

    def test_tiny_register_auto_shrinks_grown_mesh(
            self, env, tmp_path, _clean_failover_state):
        """A 2-qubit register (4 amps) saved then loaded on the 8-device
        env: the old structured error becomes an automatic reshard onto
        the largest usable sub-mesh, recorded as a degradation."""
        q = qt.createQureg(2, env)
        qt.hadamard(q, 0)
        want = np.asarray(q.amps)
        qt.saveQureg(q, str(tmp_path / "ck"))
        with pytest.warns(UserWarning, match="loadQureg_mesh_8to4"):
            q2 = qt.loadQureg(str(tmp_path / "ck"), env)
        assert q2.env.num_devices == 4
        assert np.array_equal(np.asarray(q2.amps), want)
        assert "loadQureg_mesh_8to4" in qt.degradation_report()

    def test_tiny_register_strict_keeps_grown_error(self, env, tmp_path):
        q = qt.createQureg(2, env)
        qt.saveQureg(q, str(tmp_path / "ck"))
        with pytest.raises(qt.QuESTError, match="mesh has grown"):
            qt.loadQureg(str(tmp_path / "ck"), env, strict_mesh=True)


class TestLiveReshard:
    def test_reshard_to_carries_live_perm(self, env, env4):
        """Qureg.reshard_to moves a register with a live permutation onto
        a smaller mesh without rematerializing canonical order — the
        canonical read afterwards matches a same-gates run on the target
        mesh bitwise."""
        gates = _circuit()[:KILL_CURSOR]
        q = _fresh(env)
        F.start_gate_fusion(q)
        q._fusion.gates.extend(gates)
        F.stop_gate_fusion(q)
        assert q._perm is not None  # the interesting case

        q.reshard_to(env4)
        assert q.env is env4
        assert q._perm is not None  # carried, not rematerialized

        want = _fresh(env4)
        F.start_gate_fusion(want)
        want._fusion.gates.extend(gates)
        F.stop_gate_fusion(want)
        assert np.array_equal(np.asarray(q.amps), np.asarray(want.amps))


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
