"""Permutation-gate fast paths + sparse state preparation (ISSUE 15,
docs/design.md §28).

Covers the acceptance contract:
  * permutation-lowered streams are BIT-IDENTICAL to the dense matmul
    path where the lowering is exact (pure relabel/gather) and within
    1e-10 elsewhere, on scalar, 8-shard, batched-bank and density
    registers, including seeded measurement through run_resumable;
  * relabel-only streams fold into the lazy permutation with ZERO
    window exchanges, and the deferred canonical-read remap compiles to
    ZERO collectives when every relabeled bit is shard-local
    (introspect.audit under CollectiveBudget(exact={}));
  * initSparseState round-trips bit-identically vs setAmps, admits
    under the governor at SPARSE cost and densifies lazily on first
    touch, and survives checkpoint/resume bit-identically;
  * scalar swapGate routes through ONE kernels.permute_qubits call
    (kernel count pinned), telemetry routes land in
    permutation_gates_total{route} / dispatch_total{family=permutation},
    and explainCircuit reports the permutation window kind.
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion as F
from quest_tpu import governor as G
from quest_tpu import telemetry as T
from quest_tpu.ops import kernels as K
from quest_tpu.parallel import dist as PAR

_SQ2 = 1.0 / np.sqrt(2.0)
X_SOA = np.stack([np.array([[0.0, 1], [1, 0]]), np.zeros((2, 2))])
H_SOA = np.stack([_SQ2 * np.array([[1.0, 1], [1, -1]]), np.zeros((2, 2))])
CX_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]]),
    np.zeros((4, 4)),
])
SWAP_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]]),
    np.zeros((4, 4)),
])


@pytest.fixture(scope="module")
def env1():
    return qt.createQuESTEnv(num_devices=1)


@pytest.fixture
def env8(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device dryrun mesh")
    return env


@pytest.fixture
def tele():
    mode = T.mode_name()
    T.configure("on")
    T.reset()
    yield
    T.reset()
    T.configure(mode)


@pytest.fixture
def fresh_gov(monkeypatch):
    monkeypatch.delenv("QT_HBM_BUDGET_BYTES", raising=False)
    monkeypatch.delenv("QT_MEM_POLICY", raising=False)
    G.reset()
    yield monkeypatch
    G.reset()


# ---------------------------------------------------------------------------
# randomized stream helpers

_PERM_OPS = ("pauliX", "controlledNot", "swapGate", "mcmqn")
_DENSE_OPS = ("hadamard", "tGate")


def _random_stream(rng, nq, depth, *, perm_only=False):
    """Random op list as (api_name, args) pairs: X / CNOT / SWAP /
    multi-controlled-multi-NOT, optionally interleaved with dense H/T."""
    names = _PERM_OPS if perm_only else _PERM_OPS + _DENSE_OPS
    ops = []
    for _ in range(depth):
        name = names[int(rng.integers(len(names)))]
        if name in ("hadamard", "tGate", "pauliX"):
            ops.append((name, (int(rng.integers(nq)),)))
        elif name == "controlledNot":
            c, t = (int(v) for v in rng.choice(nq, size=2, replace=False))
            ops.append((name, (c, t)))
        elif name == "swapGate":
            a, b = (int(v) for v in rng.choice(nq, size=2, replace=False))
            ops.append((name, (a, b)))
        else:  # Toffoli-shaped multiControlledMultiQubitNot
            if nq < 3:
                continue
            c1, c2, t = (int(v) for v in
                         rng.choice(nq, size=3, replace=False))
            ops.append(("multiControlledMultiQubitNot", ([c1, c2], [t])))
    return ops


def _apply_stream(q, ops):
    with qt.gateFusion(q):
        for name, args in ops:
            getattr(qt, name)(q, *args)
    return np.asarray(q.amps)


def _make_state(env, nq, kind="plus"):
    q = qt.createQureg(nq, env)
    if kind == "plus":
        qt.initPlusState(q)
    else:
        qt.initDebugState(q)
    return q


def _ab_arms(monkeypatch, env, nq, ops, kind="plus"):
    """Run the same stream with QT_PERM_FAST on then off; return both
    amplitude arrays (off arm = the dense baseline)."""
    monkeypatch.setenv("QT_PERM_FAST", "on")
    a_on = _apply_stream(_make_state(env, nq, kind), ops)
    monkeypatch.setenv("QT_PERM_FAST", "off")
    a_off = _apply_stream(_make_state(env, nq, kind), ops)
    return a_on, a_off


# ---------------------------------------------------------------------------


class TestClassification:
    def test_gate_families(self):
        assert CIRC.classify_permutation_gate(X_SOA)[0] == "xor"
        assert CIRC.classify_permutation_gate(SWAP_SOA)[0] == "relabel"
        assert CIRC.classify_permutation_gate(CX_SOA)[0] == "gather"
        assert CIRC.classify_permutation_gate(H_SOA) is None

    def test_compose_run_is_exact(self):
        gates = [CIRC.Gate((0,), X_SOA), CIRC.Gate((0, 1), CX_SOA),
                 CIRC.Gate((1, 2), SWAP_SOA)]
        union, pi = CIRC.compose_permutation_run(gates)
        assert tuple(union) == (0, 1, 2)
        d = 1 << len(union)
        # replay the integer table against a dense basis sweep
        mat = np.zeros((2, d, d))
        mat[0, np.arange(d), np.asarray(pi)] = 1.0
        acc = np.eye(d)
        for g in gates:
            gm = np.zeros((d, d))
            # embed each gate into the 3-bit space by brute force
            for i in range(d):
                bits = [(i >> b) & 1 for b in range(3)]
                sub = 0
                for k, t in enumerate(g.targets):
                    sub |= bits[t] << k
                col = int(np.argmax(g.mat[0][:, sub]))
                out = list(bits)
                for k, t in enumerate(g.targets):
                    out[t] = (col >> k) & 1
                j = sum(b << k for k, b in enumerate(out))
                gm[j, i] = 1.0
            acc = gm @ acc
        assert np.array_equal(mat[0], acc)


class TestParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_scalar_mixed_stream(self, env1, monkeypatch, seed):
        ops = _random_stream(np.random.default_rng(seed), 7, 40)
        a_on, a_off = _ab_arms(monkeypatch, env1, 7, ops)
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)

    @pytest.mark.parametrize("seed", [3, 4])
    def test_scalar_pure_perm_bit_identical(self, env1, monkeypatch, seed):
        ops = _random_stream(np.random.default_rng(seed), 7, 30,
                             perm_only=True)
        a_on, a_off = _ab_arms(monkeypatch, env1, 7, ops, kind="debug")
        assert np.array_equal(a_on, a_off)

    @pytest.mark.parametrize("seed", [5, 6])
    def test_sharded_parity_zero_drift(self, env8, monkeypatch, tele, seed):
        ops = _random_stream(np.random.default_rng(seed), 10, 30)
        a_on, a_off = _ab_arms(monkeypatch, env8, 10, ops)
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)
        # §21 reconciliation: the perm-priced plan drifts 0 in BOTH arms
        assert T.counter_total("model_drift_total") == 0

    def test_sharded_pure_perm_bit_identical(self, env8, monkeypatch):
        ops = _random_stream(np.random.default_rng(8), 9, 24,
                             perm_only=True)
        a_on, a_off = _ab_arms(monkeypatch, env8, 9, ops, kind="debug")
        assert np.array_equal(a_on, a_off)

    def test_batched_parity(self, env8, monkeypatch):
        # 1-2 qubit gates only: a BatchedQureg bank has no eager scalar
        # fallback for gates the capture path rejects
        ops = [op for op in _random_stream(np.random.default_rng(9), 5, 20)
               if op[0] != "multiControlledMultiQubitNot"]

        def run():
            bq = qt.createBatchedQureg(5, env8, 3)
            return _apply_stream(bq, ops)

        monkeypatch.setenv("QT_PERM_FAST", "on")
        a_on = run()
        monkeypatch.setenv("QT_PERM_FAST", "off")
        a_off = run()
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)

    def test_density_parity(self, env1, monkeypatch):
        ops = _random_stream(np.random.default_rng(10), 4, 16)

        def run():
            dq = qt.createDensityQureg(4, env1)
            return _apply_stream(dq, ops)

        monkeypatch.setenv("QT_PERM_FAST", "on")
        a_on = run()
        monkeypatch.setenv("QT_PERM_FAST", "off")
        a_off = run()
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)

    def test_resume_seeded_measurement_parity(self, env8, monkeypatch,
                                              tmp_path):
        n = 6
        gates = []
        for t in range(n):
            gates.append(CIRC.Gate((t,), H_SOA))
        for t in range(n - 1):
            gates.append(CIRC.Gate((t, t + 1), CX_SOA))
        gates.append(CIRC.Gate((0, n - 1), SWAP_SOA))
        gates.append(CIRC.Gate((2,), X_SOA))

        def run(flag, d):
            monkeypatch.setenv("QT_PERM_FAST", flag)
            qt.seedQuEST(env8, [7, 9])
            q = qt.createQureg(n, env8)
            qt.run_resumable(q, gates, str(tmp_path / d), every=2)
            a = np.asarray(q.amps)
            m = [qt.measure(q, t) for t in range(3)]
            return a, m

        a_on, m_on = run("on", "on")
        a_off, m_off = run("off", "off")
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)
        assert m_on == m_off

    def test_optimizer_suppressed_windowed_path(self, env8, monkeypatch,
                                                tmp_path):
        """The family survives run_resumable's windowed executor with the
        optimizer suppressed (resilience drains must stay unrewritten)."""
        monkeypatch.setenv("QT_OPTIMIZER", "off")
        gates = [CIRC.Gate((0,), H_SOA), CIRC.Gate((0, 1), CX_SOA),
                 CIRC.Gate((1, 2), CX_SOA), CIRC.Gate((0, 3), SWAP_SOA)]

        def run(flag, d):
            monkeypatch.setenv("QT_PERM_FAST", flag)
            qt.seedQuEST(env8, [3])
            q = qt.createQureg(5, env8)
            qt.run_resumable(q, gates, str(tmp_path / d), every=1)
            return np.asarray(q.amps)

        np.testing.assert_allclose(run("on", "a"), run("off", "b"),
                                   atol=1e-10, rtol=0)


class TestRelabelZeroCollective:
    def test_local_relabel_folds_and_compiles_collective_free(
            self, env8, tele):
        """SWAP-only stream on shard-LOCAL bits: the whole drain folds
        into the lazy perm (zero window exchanges, zero dispatched
        parts) and the deferred canonical-read remap compiles to ZERO
        collectives."""
        n = 6
        nloc = n - 3  # 8 shards -> 3 shard bits
        q = _make_state(env8, n, kind="debug")
        qt.startGateFusion(q)
        qt.swapGate(q, 0, 1)
        qt.swapGate(q, 1, 2)
        qt.swapGate(q, 0, 2)
        rep = qt.explainCircuit(q)
        assert any(w["kind"] == "relabel" for w in rep["windows"])
        c0 = T.counter_sum("exchanges_total", op="window_remap")
        _ = q._amps_raw()  # drain WITHOUT the canonical-read remap
        assert T.counter_sum("exchanges_total", op="window_remap") == c0
        assert T.counter_sum("permutation_gates_total",
                             route="relabel") >= 1
        assert T.counter_sum("permutation_gates_total",
                             route="exchange") == 0
        perm = q._perm
        assert perm is not None
        assert all(perm[b] == b for b in range(nloc, n))  # shard bits idle

        def canonical_read(a):
            return PAR.remap_sharded(
                a, mesh=env8.mesh, num_qubits=n,
                sigma=PAR.canonical_sigma(perm))

        with qt.CollectiveBudget(exact={}):
            audit = qt.audit(canonical_read, q._amps)
        assert sum(audit.collectives.values()) == 0
        # and the fold is still the right answer
        ref = np.asarray(_make_state(env8, n, kind="debug").amps)
        got = np.asarray(q.amps)
        want = ref[:, _relabel_index(n, ((0, 1), (1, 2), (0, 2)))]
        assert np.array_equal(got, want)

    def test_cross_shard_fold_defers_exchange(self, env8, tele,
                                              monkeypatch):
        """A SWAP touching a shard bit still folds (zero window parts);
        the composed ppermute is deferred to the canonical read and the
        route is counted as exchange."""
        n = 6
        q = _make_state(env8, n, kind="debug")
        with qt.gateFusion(q):
            qt.swapGate(q, 0, n - 1)  # bit 5 lives on the shard axis
        c_win = T.counter_sum("exchanges_total", op="window_remap")
        assert c_win == 0
        assert T.counter_sum("permutation_gates_total",
                             route="exchange") >= 1
        a_on = np.asarray(q.amps)
        monkeypatch.setenv("QT_PERM_FAST", "off")
        q2 = _make_state(env8, n, kind="debug")
        with qt.gateFusion(q2):
            qt.swapGate(q2, 0, n - 1)
        assert np.array_equal(a_on, np.asarray(q2.amps))


def _relabel_index(n, swaps):
    """Amplitude gather indices equivalent to a sequence of qubit swaps
    applied to the state (new[i] = old[src[i]])."""
    perm = list(range(n))
    for a, b in swaps:
        perm[a], perm[b] = perm[b], perm[a]
    idx = np.arange(1 << n)
    src = np.zeros_like(idx)
    for bit in range(n):
        src |= (((idx >> perm[bit]) & 1) << bit)
    return src


class TestSparseInit:
    def test_round_trip_vs_set_amps(self, env1):
        n, k = 6, 7
        rng = np.random.default_rng(5)
        idx = np.sort(rng.choice(1 << n, size=k, replace=False))
        vals = rng.standard_normal(k) + 1j * rng.standard_normal(k)
        vals /= np.linalg.norm(vals)
        q1 = qt.createQureg(n, env1)
        qt.initSparseState(q1, idx, vals)
        re = np.zeros(1 << n)
        im = np.zeros(1 << n)
        re[idx], im[idx] = vals.real, vals.imag
        q2 = qt.createQureg(n, env1)
        qt.setAmps(q2, 0, re, im, 1 << n)
        assert np.array_equal(np.asarray(q1.amps), np.asarray(q2.amps))

    def test_round_trip_sharded(self, env8):
        n = 6
        idx = [1, 17, 40, 63]
        vals = np.array([0.6, 0.48j, -0.48, 0.4])
        q1 = qt.createQureg(n, env8)
        qt.initSparseState(q1, idx, vals)
        re = np.zeros(1 << n)
        im = np.zeros(1 << n)
        re[idx], im[idx] = vals.real, vals.imag
        q2 = qt.createQureg(n, env8)
        qt.initStateFromAmps(q2, re, im)
        assert np.array_equal(np.asarray(q1.amps), np.asarray(q2.amps))

    def test_clustered_state(self, env1):
        q = qt.createQureg(5, env1)
        blocks = [[0.5, 0.5], [0.5j, -0.5]]
        qt.initSparseClusteredState(q, [4, 20], blocks)
        a = np.asarray(q.amps)
        dense = np.zeros(32, dtype=np.complex128)
        dense[4:6] = blocks[0]
        dense[20:22] = blocks[1]
        assert np.array_equal(a[0] + 1j * a[1], dense)

    def test_admits_at_sparse_cost_and_densifies_lazily(
            self, env1, fresh_gov, tele):
        n = 10
        q = qt.createQureg(n, env1)
        per = G.register_bytes_per_device(q)
        fresh_gov.setenv("QT_HBM_BUDGET_BYTES", str(int(per * 1.5)))
        qt.initSparseState(q, [0, 3], [0.6, 0.8])
        assert q._amps is None and q._spill is not None
        assert G.resident_bytes() == 0  # no dense footprint admitted
        # a second DENSE register still fits: the sparse one holds no HBM
        q2 = qt.createQureg(n, env1)
        qt.initZeroState(q2)
        a = np.asarray(q.amps)  # first touch densifies under admission
        assert a[0, 0] == 0.6 and a[0, 3] == 0.8
        assert abs(np.abs(a).sum() - 1.4) < 1e-12
        assert T.counter_total("sparse_inits_total") == 1
        assert T.counter_sum("dispatch_total", family="permutation") >= 1

    def test_rejects_when_even_sparse_does_not_fit(self, env1, fresh_gov):
        q = qt.createQureg(8, env1)
        fresh_gov.setenv("QT_HBM_BUDGET_BYTES", "16")
        with pytest.raises(qt.MemoryAdmissionError):
            qt.initSparseState(q, [0, 1, 2, 3], np.ones(4) / 2.0)

    def test_checkpoint_resume_bit_identity(self, env8, tmp_path):
        n = 6
        gates = [CIRC.Gate((t,), H_SOA) for t in range(4)]
        gates.append(CIRC.Gate((0, 5), SWAP_SOA))
        qt.seedQuEST(env8, [11])
        q = qt.createQureg(n, env8)
        qt.initSparseClusteredState(q, [4, 40], [[0.6], [0.8j]])
        qt.run_resumable(q, gates, str(tmp_path / "ck"), every=1)
        a = np.asarray(q.amps)
        qt.seedQuEST(env8, [11])
        q2 = qt.createQureg(n, env8)
        qt.run_resumable(q2, gates, str(tmp_path / "ck"), every=1)
        assert np.array_equal(a, np.asarray(q2.amps))

    def test_validation_errors(self, env1):
        q = qt.createQureg(4, env1)
        with pytest.raises(qt.QuESTError, match="duplicate"):
            qt.initSparseState(q, [3, 3], [0.5, 0.5])
        with pytest.raises(qt.QuESTError, match="Invalid amplitude"):
            qt.initSparseState(q, [16], [1.0])
        with pytest.raises(qt.QuESTError, match="non-empty"):
            qt.initSparseState(q, [], [])
        dq = qt.createDensityQureg(2, env1)
        with pytest.raises(qt.QuESTError):
            qt.initSparseState(dq, [0], [1.0])


class TestExplainAndTelemetry:
    def test_explain_scalar_perm_window_kind(self, env1, monkeypatch):
        monkeypatch.setenv("QT_OPTIMIZER", "off")
        q = qt.createQureg(6, env1)
        qt.startGateFusion(q)
        qt.pauliX(q, 0)
        qt.controlledNot(q, 0, 1)
        qt.swapGate(q, 2, 3)
        rep = qt.explainCircuit(q)
        kinds = [w["kind"] for w in rep["windows"]]
        assert "perm" in kinds
        assert rep["totals"]["perm_windows"] >= 1
        txt = rep.table()
        assert "perm" in txt and "perm_windows=" in txt
        _ = q.amps  # drain the buffer so the register is left clean

    def test_swap_scalar_single_permute_kernel(self, env1, monkeypatch,
                                               tele):
        q = qt.createQureg(5, env1)
        qt.initDebugState(q)
        ref = np.asarray(q.amps)
        monkeypatch.setattr(F, "capture_unitary",
                            lambda *a, **k: False)
        calls = {"permute": 0, "swap": 0}
        orig_p, orig_s = K.permute_qubits, K.swap_qubit_amps

        def spy_p(*a, **k):
            calls["permute"] += 1
            return orig_p(*a, **k)

        def spy_s(*a, **k):
            calls["swap"] += 1
            return orig_s(*a, **k)

        monkeypatch.setattr(K, "permute_qubits", spy_p)
        monkeypatch.setattr(K, "swap_qubit_amps", spy_s)
        qt.swapGate(q, 1, 3)
        assert calls == {"permute": 1, "swap": 0}
        assert T.counter_sum("permutation_gates_total",
                             route="relabel") == 1
        got = np.asarray(q.amps)
        assert np.array_equal(got, ref[:, _relabel_index(5, ((1, 3),))])
        # the off arm keeps the legacy pairwise kernel
        monkeypatch.setenv("QT_PERM_FAST", "off")
        qt.swapGate(q, 1, 3)
        assert calls["permute"] == 1 and calls["swap"] >= 1
        assert np.array_equal(np.asarray(q.amps), ref)

    def test_route_counters_and_env_string(self, env1, tele, monkeypatch):
        # optimizer off: a 2-gate perm run would otherwise coalesce into
        # a singleton, which rides the dense path by design
        monkeypatch.setenv("QT_OPTIMIZER", "off")
        q = qt.createQureg(5, env1)
        qt.initSparseState(q, [0], [1.0])
        with qt.gateFusion(q):
            qt.pauliX(q, 0)
            qt.controlledNot(q, 0, 1)
        _ = q.amps
        assert T.counter_sum("dispatch_total", family="permutation") >= 1
        routes = {r: T.counter_sum("permutation_gates_total", route=r)
                  for r in ("relabel", "gather", "exchange")}
        assert sum(routes.values()) >= 1
        s = qt.getEnvironmentString(env1)
        assert "PermFast=on" in s
        rep = T.perf_report()
        assert "permutation fast paths" in rep
        assert "sparse inits: 1" in rep

    def test_env_string_shows_disabled_flag(self, env1, monkeypatch,
                                            tele):
        monkeypatch.setenv("QT_PERM_FAST", "off")
        assert "PermFast=off" in qt.getEnvironmentString(env1)
