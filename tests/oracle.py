"""Dense NumPy reference simulator — the test oracle.

Python analogue of the reference's independent test oracle
(tests/utilities.{hpp,cpp}: QVector/QMatrix dense algebra, applyReferenceOp
building the full 2^N operator via Kronecker products and multiplying it
directly onto the state, utilities.cpp:304-360,728-791).  Deliberately
naive O(4^N) linear algebra — correctness only, no shared code with
quest_tpu kernels.

Conventions: qubit q = bit q of the state index (little-endian).  A density
matrix is a (2^N, 2^N) ndarray rho[r, c]; quest_tpu flattens column-major
(ket = row = low bits), i.e. flat[r + c*2^N] = rho[r, c].
"""

from __future__ import annotations

import numpy as np

I2 = np.eye(2, dtype=complex)
X = np.array([[0, 1], [1, 0]], dtype=complex)
Y = np.array([[0, -1j], [1j, 0]], dtype=complex)
Z = np.array([[1, 0], [0, -1]], dtype=complex)
H = np.array([[1, 1], [1, -1]], dtype=complex) / np.sqrt(2)
PAULIS = (I2, X, Y, Z)


def state_from_qureg(qureg) -> np.ndarray:
    """Gather the (possibly sharded) amps to a host ndarray — the analogue of
    the reference's MPI_Allgather toQVector (utilities.cpp:1085-1093)."""
    soa = np.asarray(qureg.amps)
    flat = soa[0] + 1j * soa[1]
    if qureg.is_density_matrix:
        dim = 1 << qureg.num_qubits_represented
        return flat.reshape(dim, dim).T  # flat[r + c*dim] -> rho[r, c]
    return flat


def debug_state(num_amps: int) -> np.ndarray:
    k = np.arange(num_amps)
    return ((2 * k) % 10) / 10 + 1j * ((2 * k + 1) % 10) / 10


def debug_density(num_qubits: int) -> np.ndarray:
    dim = 1 << num_qubits
    flat = debug_state(dim * dim)
    return flat.reshape(dim, dim).T


def full_operator(num_qubits: int, targets, matrix) -> np.ndarray:
    """Expand a 2^k matrix on `targets` (targets[0] = least-significant
    matrix bit) to the full 2^N operator (getFullOperatorMatrix,
    utilities.cpp:304-360)."""
    matrix = np.asarray(matrix, dtype=complex)
    k = len(targets)
    dim = 1 << num_qubits
    op = np.zeros((dim, dim), dtype=complex)
    for col in range(dim):
        tbits = 0
        for j, t in enumerate(targets):
            tbits |= ((col >> t) & 1) << j
        base = col
        for t in targets:
            base &= ~(1 << t)
        for rbits in range(1 << k):
            row = base
            for j, t in enumerate(targets):
                row |= ((rbits >> j) & 1) << t
            op[row, col] = matrix[rbits, tbits]
    return op


def controlled_operator(num_qubits: int, controls, targets, matrix,
                        control_states=None) -> np.ndarray:
    """Full operator acting only where every control bit matches its state."""
    dim = 1 << num_qubits
    if control_states is None:
        control_states = [1] * len(controls)
    base = full_operator(num_qubits, targets, matrix)
    op = np.eye(dim, dtype=complex)
    for col in range(dim):
        if all(((col >> c) & 1) == s for c, s in zip(controls, control_states)):
            op[:, col] = base[:, col]
    return op


def apply_to_statevec(state, num_qubits, targets, matrix, controls=(),
                      control_states=None) -> np.ndarray:
    op = controlled_operator(num_qubits, controls, targets, matrix, control_states)
    return op @ state


def apply_to_density(rho, num_qubits, targets, matrix, controls=(),
                     control_states=None) -> np.ndarray:
    op = controlled_operator(num_qubits, controls, targets, matrix, control_states)
    return op @ rho @ op.conj().T


def apply_kraus_to_density(rho, num_qubits, targets, kraus_ops) -> np.ndarray:
    out = np.zeros_like(rho)
    for k in kraus_ops:
        op = full_operator(num_qubits, targets, k)
        out += op @ rho @ op.conj().T
    return out


def pauli_product(num_qubits: int, targets, codes) -> np.ndarray:
    return full_operator(
        num_qubits, list(targets), _pauli_matrix_on_targets(codes)
    )


def _pauli_matrix_on_targets(codes):
    m = None
    for c in codes:
        p = PAULIS[int(c)]
        m = p if m is None else np.kron(p, m)
    return m


def pauli_sum_matrix(num_qubits: int, codes_2d, coeffs) -> np.ndarray:
    dim = 1 << num_qubits
    total = np.zeros((dim, dim), dtype=complex)
    for t, coeff in enumerate(coeffs):
        total += coeff * pauli_product(
            num_qubits, list(range(num_qubits)), codes_2d[t]
        )
    return total


def dft_matrix(num_qubits: int) -> np.ndarray:
    """QFT oracle (getDFT, utilities.cpp:652): amp_y = 1/sqrt(N) sum_x
    e^{2 pi i x y / N}."""
    dim = 1 << num_qubits
    x, y = np.meshgrid(np.arange(dim), np.arange(dim))
    return np.exp(2j * np.pi * x * y / dim) / np.sqrt(dim)


def random_state(num_qubits: int, rng) -> np.ndarray:
    dim = 1 << num_qubits
    v = rng.standard_normal(dim) + 1j * rng.standard_normal(dim)
    return v / np.linalg.norm(v)


def random_density(num_qubits: int, rng) -> np.ndarray:
    """Random mixed state (getRandomDensityMatrix, utilities.hpp:398)."""
    dim = 1 << num_qubits
    a = rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
    rho = a @ a.conj().T
    return rho / np.trace(rho)


def random_unitary(num_targets: int, rng) -> np.ndarray:
    """Haar-ish unitary via QR (getRandomUnitary, utilities.cpp:530)."""
    dim = 1 << num_targets
    a = rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
    q, r = np.linalg.qr(a)
    return q * (np.diag(r) / np.abs(np.diag(r)))


def random_kraus_map(num_targets: int, num_ops: int, rng):
    """Random CPTP map (getRandomKrausMap, utilities.cpp:578)."""
    dim = 1 << num_targets
    ops = [rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
           for _ in range(num_ops)]
    total = sum(k.conj().T @ k for k in ops)
    # normalise: S^{-1/2} K_i satisfies CPTP
    w, v = np.linalg.eigh(total)
    inv_sqrt = v @ np.diag(1 / np.sqrt(w)) @ v.conj().T
    return [k @ inv_sqrt for k in ops]


def set_qureg_from_array(qt, qureg, array) -> None:
    """Load an oracle state into a quest_tpu register."""
    if qureg.is_density_matrix:
        flat = np.asarray(array).T.ravel()  # rho[r,c] -> flat[r + c*dim]
        qt.setDensityAmps(qureg, flat.real, flat.imag)
    else:
        qt.initStateFromAmps(qureg, np.asarray(array).real, np.asarray(array).imag)
