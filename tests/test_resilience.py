"""Fault-tolerant execution suite (quest_tpu/resilience.py, ISSUE 2).

Covers the acceptance contract:
  * a kill injected mid-save leaves a loadable last-good checkpoint;
  * run_resumable after a simulated preemption produces amplitudes
    BIT-IDENTICAL to an uninterrupted run of the same circuit + seed,
    including on the multi-shard dryrun mesh with a live logical->physical
    permutation at the kill point;
  * the watchdog detects an injected NaN within one window cadence, and
    the rollback policy restores the last-good state;
  * transient IO errors are absorbed by the bounded-backoff retry
    wrapper; post-commit corruption falls back to the previous
    generation;
  * measurement-RNG state round-trips so resumed outcome streams match
    uninterrupted ones (host MT19937 and device-key paths).
"""

import json
import os
import warnings

import numpy as np
import pytest

import jax

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import resilience as R
from quest_tpu import rng as RNG
from quest_tpu.ops import measurement as M

pytestmark = pytest.mark.faults

N = 6  # 64 amps over the 8-device dryrun mesh -> 3 sharded qubits

H_SOA = np.stack([(1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]]),
                  np.zeros((2, 2))])
CX_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]]),
    np.zeros((4, 4)),
])


@pytest.fixture(autouse=True)
def _fast_retry(monkeypatch):
    monkeypatch.setenv("QT_RETRY_BASE_SECONDS", "0.001")


def _circuit(n=N, depth=4):
    """Entangling brickwork reaching every qubit — including the sharded
    high qubits, so drains leave a live permutation behind."""
    gates = []
    for _ in range(depth):
        for t in range(n):
            gates.append(CIRC.Gate((t,), H_SOA))
        for t in range(n - 1):
            gates.append(CIRC.Gate((t, t + 1), CX_SOA))
    return gates


def _fresh(env, n=N, seed=7):
    qt.seedQuEST(env, [seed])
    return qt.createQureg(n, env)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Amplitudes of the uninterrupted resumable run (every=8)."""
    env = qt.createQuESTEnv()
    q = _fresh(env)
    qt.run_resumable(q, _circuit(), str(tmp_path_factory.mktemp("ref")),
                     every=8)
    return np.asarray(q.amps)


class TestResumeBitExact:
    def test_uninterrupted_equals_plain_fusion_run(self, env, reference):
        """run_resumable is the same computation as one gateFusion drain
        per window — the checkpoint/watchdog layer must not perturb the
        numerics at all."""
        from quest_tpu import fusion

        q = _fresh(env)
        gates = _circuit()
        for cur in range(0, len(gates), 8):
            fusion.start_gate_fusion(q)
            q._fusion.gates.extend(gates[cur:cur + 8])
            fusion.stop_gate_fusion(q)
        np.testing.assert_array_equal(np.asarray(q.amps), reference)

    def test_kill_then_resume_bit_identical_multishard(self, env, tmp_path,
                                                       reference):
        if env.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        plan = qt.FaultPlan("kill@3")
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), ckpt, every=8, faults=plan)
        assert plan.log == ["kill@3"]
        # the kill point's last-good checkpoint carries a LIVE permutation
        loaded = R.load_latest(ckpt, env)
        assert loaded is not None
        meta = loaded[1]
        assert meta["cursor"] == 24
        assert meta["perm"] is not None
        assert meta["perm"] != list(range(N))
        # fresh register, fresh seed state: the process died
        q2 = _fresh(env)
        qt.run_resumable(q2, _circuit(), ckpt, every=8)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)

    def test_resume_bit_identical_with_pipelined_exchange(
            self, env, tmp_path, reference, monkeypatch):
        """ISSUE 3: the pipelined chunked exchange must not perturb the
        resume contract.  Snapshots taken mid-stream store RAW permuted
        amplitudes, whose layout is chunk-INDEPENDENT — the chunk count
        only reschedules the exchange, it never changes what lands where
        — so a run killed and resumed under QT_EXCHANGE_CHUNKS=4 stays
        bit-identical to the unchunked uninterrupted reference."""
        if env.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "4")
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), ckpt, every=8,
                             faults=qt.FaultPlan("kill@3"))
        q2 = _fresh(env)
        qt.run_resumable(q2, _circuit(), ckpt, every=8)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)

    def test_checkpoints_at_window_boundaries_only(self, env, tmp_path):
        """One fusion drain per window: a checkpoint can never land
        mid-window (fusion.py drain counter)."""
        q = _fresh(env)
        qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=8)
        assert q._drain_count == len(
            CIRC.plan_checkpoint_boundaries(len(_circuit()), 8))

    def test_resume_refuses_different_circuit(self, env, tmp_path):
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), ckpt, every=8,
                             faults=qt.FaultPlan("kill@2"))
        other = _circuit(depth=2)
        with pytest.raises(qt.QuESTError, match="different circuit"):
            qt.run_resumable(_fresh(env), other, ckpt, every=8)
        # a different cadence changes the window plans too
        with pytest.raises(qt.QuESTError, match="different circuit"):
            qt.run_resumable(_fresh(env), _circuit(), ckpt, every=4)


class TestKillMidSave:
    def test_mid_save_kill_leaves_loadable_last_good(self, env, tmp_path,
                                                     reference):
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        plan = qt.FaultPlan("killsave@2")
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), ckpt, every=8, faults=plan)
        assert plan.log == ["killsave@2"]
        loaded = R.load_latest(ckpt, env)
        assert loaded is not None
        # window 2's commit never happened: last-good is window 1's
        assert loaded[1]["cursor"] == 16
        q2 = _fresh(env)
        qt.run_resumable(q2, _circuit(), ckpt, every=8)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)


class TestCorruptCheckpoint:
    def test_corrupt_newest_falls_back_to_predecessor(self, env, tmp_path,
                                                      reference):
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), ckpt, every=8,
                             faults=qt.FaultPlan("corrupt@2,kill@3"))
        q2 = _fresh(env)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            qt.run_resumable(q2, _circuit(), ckpt, every=8)
        assert any("unreadable" in str(x.message) for x in w)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)

    def test_all_generations_corrupt_raises(self, env, tmp_path):
        ckpt = tmp_path / "ck"
        q = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q, _circuit(), str(ckpt), every=8,
                             faults=qt.FaultPlan("kill@3"))
        for gen in ckpt.glob("gen-*"):
            R._corrupt_generation(str(gen))
        with pytest.raises(qt.QuESTError, match="no loadable checkpoint"):
            qt.run_resumable(_fresh(env), _circuit(), str(ckpt), every=8)


class TestTransientIO:
    def test_retry_absorbs_transient_errors(self, env, tmp_path, reference):
        q = _fresh(env)
        plan = qt.FaultPlan("io@3")
        qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=8,
                         faults=plan)
        assert plan.log.count("io") == 3
        assert plan.io_budget == 0
        np.testing.assert_array_equal(np.asarray(q.amps), reference)

    def test_retry_io_bounded(self):
        calls = []

        def always_fails():
            calls.append(1)
            raise OSError("disk on fire")

        with pytest.raises(qt.QuESTError, match="failed after 3 attempts"):
            R.retry_io(always_fails, attempts=3, base_delay=0.0,
                       what="test-op")
        assert len(calls) == 3

    def test_retry_io_returns_value(self):
        assert R.retry_io(lambda: 42, attempts=2, base_delay=0.0) == 42


class TestWatchdog:
    def test_health_check_clean(self, env):
        q = _fresh(env)
        norm, finite = qt.checkQuregHealth(q)
        assert finite and abs(norm - 1.0) < 1e-12

    def test_nan_detected_within_one_window(self, env, tmp_path):
        q = _fresh(env)
        with pytest.raises(qt.NumericalHealthError) as ei:
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=8,
                             watchdog="raise", faults=qt.FaultPlan("nan@1"))
        # injected after window 1 ([8, 16)) -> caught by ITS OWN check
        assert ei.value.window == (8, 16)
        assert not ei.value.finite
        assert "window [8, 16)" in str(ei.value)

    def test_rollback_restores_last_good(self, env, tmp_path, reference):
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        with pytest.raises(qt.NumericalHealthError) as ei:
            qt.run_resumable(q, _circuit(), ckpt, every=8,
                             watchdog="rollback",
                             faults=qt.FaultPlan("nan@2"))
        assert ei.value.rolled_back_to == 16
        # register now holds the last-good (16-gate) state
        qp = _fresh(env)
        qt.run_resumable(qp, _circuit()[:16], str(tmp_path / "partial"),
                         every=8)
        np.testing.assert_array_equal(np.asarray(q._amps_raw()),
                                      np.asarray(qp._amps_raw()))
        # and re-entering run_resumable resumes to the full bit-exact end
        q2 = _fresh(env)
        qt.run_resumable(q2, _circuit(), ckpt, every=8)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)

    def test_renormalize_policy(self, env, tmp_path):
        q = _fresh(env)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=8,
                             watchdog="renormalize",
                             faults=qt.FaultPlan("scale@1"))
        assert any("renormalized" in str(x.message) for x in w)
        norm, finite = qt.checkQuregHealth(q)
        assert finite and abs(norm - 1.0) < 1e-10

    def test_renormalize_does_not_mask_nonfinite(self, env, tmp_path):
        """NaN is not drift: the renormalize policy must escalate."""
        q = _fresh(env)
        with pytest.raises(qt.NumericalHealthError):
            qt.run_resumable(q, _circuit(), str(tmp_path / "ck"), every=8,
                             watchdog="renormalize",
                             faults=qt.FaultPlan("inf@1"))

    def test_unknown_policy_rejected(self, env, tmp_path):
        with pytest.raises(qt.QuESTError, match="watchdog policy"):
            qt.run_resumable(_fresh(env), _circuit(),
                             str(tmp_path / "ck"), watchdog="panic")


class TestRNGStateRoundTrip:
    def test_host_mt_stream_resumes(self, env, monkeypatch):
        """seed -> measure x k -> snapshot -> restore -> measure matches
        an uninterrupted run (satellite: MT19937 state round-trip)."""
        monkeypatch.setenv("QT_HOST_MEASURE", "1")
        qt.seedQuEST(env, [11])
        q = qt.createQureg(4, env)
        qt.initPlusState(q)
        for _ in range(3):
            qt.measure(q, 0)
        snap = RNG.GLOBAL_RNG.get_state()
        amps = np.asarray(q.amps).copy()

        qa = qt.createQureg(4, env)
        qa.amps = qa.device_put(amps)
        uninterrupted = [qt.measure(qa, t) for t in (1, 2, 3)]

        RNG.GLOBAL_RNG.set_state(snap)
        qb = qt.createQureg(4, env)
        qb.amps = qb.device_put(amps)
        resumed = [qt.measure(qb, t) for t in (1, 2, 3)]
        assert resumed == uninterrupted

    def test_get_state_is_json_serializable(self):
        json.dumps(RNG.GLOBAL_RNG.get_state())

    def test_device_key_stream_resumes(self, env):
        qt.seedQuEST(env, [13])
        q = qt.createQureg(4, env)
        qt.initPlusState(q)
        qt.measure(q, 0)
        snap = M.KEYS.get_state()
        json.dumps(snap)  # checkpoint-metadata representable
        amps = np.asarray(q._amps_raw()).copy()
        uninterrupted = [qt.measure(q, t) for t in (1, 2, 3)]
        M.KEYS.set_state(snap)
        qb = qt.createQureg(4, env)
        qb.amps = qb.device_put(amps)
        resumed = [qt.measure(qb, t) for t in (1, 2, 3)]
        assert resumed == uninterrupted

    def test_resumed_run_continues_measurement_stream(self, env, tmp_path):
        """The generation metadata carries the RNG state: a measurement
        AFTER a resumed circuit matches the uninterrupted run's."""
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        qt.run_resumable(q, _circuit(), str(tmp_path / "ref"), every=8)
        want = qt.measureSequence(q, list(range(N)))[0]

        q2 = _fresh(env)
        with pytest.raises(qt.SimulatedPreemption):
            qt.run_resumable(q2, _circuit(), ckpt, every=8,
                             faults=qt.FaultPlan("kill@3"))
        q3 = _fresh(env)
        qt.run_resumable(q3, _circuit(), ckpt, every=8)
        got = qt.measureSequence(q3, list(range(N)))[0]
        assert got == want


class TestGracefulDegradation:
    def test_pallas_probe_failure_records_downgrade(self, env, monkeypatch):
        from quest_tpu.ops import paulis as P

        monkeypatch.setattr(P, "_PALLAS_OK", {})
        monkeypatch.setattr(R, "DEGRADATIONS", {})

        def boom():
            raise RuntimeError("mosaic lowering exploded")

        monkeypatch.setattr(P, "_probe_pallas_lowering", boom)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert P.pallas_lowering_ok() is False
        assert any("degraded" in str(x.message) for x in w)
        # cached: no second warning
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            assert P.pallas_lowering_ok() is False
        assert not w2
        assert "pallas-direct-rotation" in qt.degradation_report()
        assert "Degraded=[" in qt.getEnvironmentString(env)
        # and the production router takes the gather path
        amps = jax.numpy.zeros((2, 1 << P._PL_MIN_N), jax.numpy.float32)
        assert not P._pl_routable(amps, P._PL_MIN_N)

    def test_pallas_probe_success_reports_clean(self, env, monkeypatch):
        from quest_tpu.ops import paulis as P

        monkeypatch.setattr(P, "_PALLAS_OK", {})
        monkeypatch.setattr(R, "DEGRADATIONS", {})
        monkeypatch.setattr(P, "_probe_pallas_lowering", lambda: None)
        assert P.pallas_lowering_ok() is True
        assert qt.degradation_report() == {}
        assert "Degraded" not in qt.getEnvironmentString(env)


class TestFaultPlanParsing:
    def test_parse_and_env(self, monkeypatch):
        plan = qt.FaultPlan("kill@2, nan@5, io@4")
        assert ("kill", 2) in plan.events
        assert ("nan", 5) in plan.events
        assert plan.io_budget == 4
        monkeypatch.setenv("QT_FAULT_PLAN", "killsave@1")
        got = qt.FaultPlan.from_env()
        assert got is not None and ("killsave", 1) in got.events
        monkeypatch.delenv("QT_FAULT_PLAN")
        assert qt.FaultPlan.from_env() is None

    def test_unknown_kind_rejected(self):
        with pytest.raises(qt.QuESTError, match="unknown fault kind"):
            qt.FaultPlan("meteor@3")  # qlint: allow(fault-plan-spec): deliberately unknown kind — the test pins the rejection path

    def test_exchange_fault_kinds_parse(self):
        plan = qt.FaultPlan("stall@2, shard_loss@3")
        assert ("stall", 2) in plan.events
        assert ("shard_loss", 3) in plan.events

    def test_arm_and_take_exchange_faults(self):
        """Window-keyed arming moves stall/shard_loss into the pending
        slots the dispatch hook drains — shard loss first (it preempts
        the window), one fault per dispatch attempt, then clean."""
        plan = qt.FaultPlan("stall@1, shard_loss@1")
        assert plan.take_exchange_fault("drain") is None  # nothing armed
        plan.arm_exchange_window(0)
        assert plan.take_exchange_fault("drain") is None  # wrong window
        plan.arm_exchange_window(1)
        assert plan.take_exchange_fault("drain") == "shard_loss"
        assert plan.take_exchange_fault("drain") == "stall"
        assert plan.take_exchange_fault("drain") is None
        assert plan.log == ["stall@1", "shard_loss@1"]

    def test_oom_kind_parses_and_arms(self):
        plan = qt.FaultPlan("oom@2")
        assert ("oom", 2) in plan.events
        assert not plan.take_oom_fault()  # not armed yet
        plan.arm_exchange_window(2)
        assert plan.take_oom_fault()  # one event -> one synthetic OOM
        assert not plan.take_oom_fault()
        assert plan.log == ["oom@2"]


class TestOomNet:
    """oom@W: the memory governor's RESOURCE_EXHAUSTED net (ISSUE 9).
    One armed event makes a window's drain dispatch fail once — the net
    evicts idle registers, clears the plan caches, and retries; arming
    the SAME window twice burns the single retry and the failure
    propagates."""

    def test_evict_and_retry_fires_exactly_once(self, env, tmp_path,
                                                reference):
        from quest_tpu import telemetry as T

        q = _fresh(env)
        plan = qt.FaultPlan("oom@2")
        before = T.counter_total("oom_retries_total")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            qt.run_resumable(q, _circuit(), str(tmp_path), every=8,
                             faults=plan)
        assert plan.log.count("oom@2") == 1
        assert T.counter_total("oom_retries_total") == before + 1
        np.testing.assert_array_equal(np.asarray(q.amps), reference)

    def test_exhaustion_reraises(self, env, tmp_path):
        from quest_tpu import telemetry as T

        q = _fresh(env)
        plan = qt.FaultPlan("oom@2,oom@2")
        before = T.counter_total("oom_retries_total")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                qt.run_resumable(q, _circuit(), str(tmp_path), every=8,
                                 faults=plan)
        assert T.counter_total("oom_retries_total") == before + 1

    def test_plain_drain_arms_window_zero(self, env):
        """A gateFusion drain outside run_resumable counts as window 0,
        so oom@0 exercises the net without the checkpoint machinery."""
        from quest_tpu import telemetry as T

        u = np.linalg.qr(np.random.default_rng(5).normal(size=(4, 4)))[0]
        qa = _fresh(env)
        qb = _fresh(env)
        plan = qt.FaultPlan("oom@0")
        before = T.counter_total("oom_retries_total")
        R._ACTIVE_FAULTS[0] = plan
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with qt.gateFusion(qa):
                    qt.multiQubitUnitary(qa, [0, 1], u)
        finally:
            R._ACTIVE_FAULTS[0] = None
        with qt.gateFusion(qb):
            qt.multiQubitUnitary(qb, [0, 1], u)
        assert plan.log == ["oom@0"]
        assert T.counter_total("oom_retries_total") == before + 1
        np.testing.assert_array_equal(np.asarray(qa.amps),
                                      np.asarray(qb.amps))


@pytest.fixture
def _no_fault_hook():
    """Isolate guarded_dispatch tests from any leftover injection hook,
    and clean up the ones they install."""
    from quest_tpu.parallel import dist as PAR

    old = PAR.EXCHANGE_FAULT_HOOK[0]
    PAR.EXCHANGE_FAULT_HOOK[0] = None
    yield PAR
    PAR.EXCHANGE_FAULT_HOOK[0] = old


class TestGuardedDispatch:
    """Unit contract of dist.guarded_dispatch (the collective guard the
    elastic failover path is built on — tests/test_elastic.py drives it
    end to end through run_resumable)."""

    def test_passthrough_and_latency_histogram(self, _no_fault_hook):
        PAR = _no_fault_hook
        from quest_tpu import telemetry as T

        hist_key = ("exchange_latency_seconds",
                    (("op", "unit_test"), ("shards", "8")))
        T._HISTS.pop(hist_key, None)
        out = PAR.guarded_dispatch(lambda a, k=None: (a, k), 5, k=7,
                                   op="unit_test", shards=8)
        assert out == (5, 7)
        assert T._HISTS[hist_key].as_dict()["count"] == 1

    def test_transient_failure_retried(self, _no_fault_hook):
        PAR = _no_fault_hook
        calls = []

        def flaky(x):
            calls.append(x)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return x * 2

        assert PAR.guarded_dispatch(flaky, 21, op="unit_test") == 42
        assert len(calls) == 3

    def test_exhaustion_raises_shard_loss(self, _no_fault_hook,
                                          monkeypatch):
        PAR = _no_fault_hook
        monkeypatch.setenv("QT_EXCHANGE_RETRIES", "2")

        def always_fails(_x):
            raise RuntimeError("dead link")

        with pytest.raises(PAR.ShardLossError, match="after 2 attempts"):
            PAR.guarded_dispatch(always_fails, 1, op="unit_test")

    def test_injected_shard_loss_raises_immediately(self, _no_fault_hook):
        PAR = _no_fault_hook
        PAR.EXCHANGE_FAULT_HOOK[0] = lambda op: "shard_loss"
        with pytest.raises(PAR.ShardLossError, match="injected shard loss"):
            PAR.guarded_dispatch(lambda x: x, 1, op="unit_test")

    def test_injected_stall_absorbed_and_counted(self, _no_fault_hook):
        PAR = _no_fault_hook
        from quest_tpu import telemetry as T

        faults = iter(["stall"])
        PAR.EXCHANGE_FAULT_HOOK[0] = lambda op: next(faults, None)
        before = T.counter_value("exchange_timeouts_total", op="unit_test")
        assert PAR.guarded_dispatch(lambda x: x + 1, 1, op="unit_test") == 2
        after = T.counter_value("exchange_timeouts_total", op="unit_test")
        assert after == before + 1

    def test_deadline_overrun_counted_but_result_kept(self, _no_fault_hook,
                                                      monkeypatch):
        PAR = _no_fault_hook
        from quest_tpu import telemetry as T

        monkeypatch.setenv("QT_EXCHANGE_DEADLINE_S", "1e-9")  # all late
        before = T.counter_value("exchange_timeouts_total", op="unit_test")
        assert PAR.guarded_dispatch(lambda x: x, 9, op="unit_test") == 9
        after = T.counter_value("exchange_timeouts_total", op="unit_test")
        assert after == before + 1


class TestElasticContracts:
    """Fast unit contracts of the elastic restore path (the full
    save/resume + failover cycles live in tests/test_elastic.py, run by
    make verify-elastic)."""

    def test_validated_perm(self):
        assert R._validated_perm(None, 4) is None
        assert R._validated_perm([1, 0, 2, 3], 4) == (1, 0, 2, 3)
        with pytest.raises(ValueError):
            R._validated_perm([0, 0, 1, 2], 4)  # not a permutation
        with pytest.raises(ValueError):
            R._validated_perm([0, 1], 4)  # wrong length

    def test_shrink_env_validates(self, env):
        from quest_tpu import env as ENV

        with pytest.raises(ValueError):
            ENV.shrink_env(env, 3)  # not a power of two
        with pytest.raises(ValueError):
            ENV.shrink_env(env, 16)  # more devices than survive
        e2 = ENV.shrink_env(env, 2)
        assert e2.num_devices == 2
        assert e2.seeds == env.seeds  # RNG streams belong to the run


class TestBoundaries:
    def test_plan_checkpoint_boundaries(self):
        assert CIRC.plan_checkpoint_boundaries(44, 8) == [8, 16, 24, 32,
                                                          40, 44]
        assert CIRC.plan_checkpoint_boundaries(16, 8) == [8, 16]
        assert CIRC.plan_checkpoint_boundaries(16, 8, start=8) == [16]
        assert CIRC.plan_checkpoint_boundaries(16, 8, start=16) == []
        assert CIRC.plan_checkpoint_boundaries(3, 8) == [3]
        with pytest.raises(ValueError):
            CIRC.plan_checkpoint_boundaries(8, 0)

    def test_completed_run_resumes_to_noop(self, env, tmp_path, reference):
        ckpt = str(tmp_path / "ck")
        q = _fresh(env)
        qt.run_resumable(q, _circuit(), ckpt, every=8)
        # re-entering after completion replays nothing and changes nothing
        q2 = _fresh(env)
        qt.run_resumable(q2, _circuit(), ckpt, every=8)
        np.testing.assert_array_equal(np.asarray(q2.amps), reference)
        assert q2._drain_count == 0
