"""Decoherence channel tests (analogue of reference test_decoherence.cpp,
10 TEST_CASEs), all against the dense Kraus oracle on random mixed states."""

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N
ATOL = 1e-10


@pytest.fixture
def rho_pair(env):
    rng = np.random.default_rng(55)
    mat = oracle.random_density(N, rng)
    r = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, r, mat)
    return r, mat


@pytest.mark.parametrize("target", range(N))
def test_mix_dephasing(env, rho_pair, target):
    r, mat = rho_pair
    p = 0.3
    qt.mixDephasing(r, target, p)
    Z = oracle.full_operator(N, [target], oracle.Z)
    expect = (1 - p) * mat + p * Z @ mat @ Z
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("q1,q2", [(0, 1), (3, 1), (2, 4)])
def test_mix_two_qubit_dephasing(env, rho_pair, q1, q2):
    r, mat = rho_pair
    p = 0.5
    qt.mixTwoQubitDephasing(r, q1, q2, p)
    Z1 = oracle.full_operator(N, [q1], oracle.Z)
    Z2 = oracle.full_operator(N, [q2], oracle.Z)
    expect = (1 - p) * mat + (p / 3) * (
        Z1 @ mat @ Z1 + Z2 @ mat @ Z2 + Z1 @ Z2 @ mat @ Z2 @ Z1
    )
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("target", range(N))
def test_mix_depolarising(env, rho_pair, target):
    r, mat = rho_pair
    p = 0.6
    qt.mixDepolarising(r, target, p)
    X = oracle.full_operator(N, [target], oracle.X)
    Y = oracle.full_operator(N, [target], oracle.Y)
    Z = oracle.full_operator(N, [target], oracle.Z)
    expect = (1 - p) * mat + (p / 3) * (X @ mat @ X + Y @ mat @ Y + Z @ mat @ Z)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("target", [0, 2, 4])
def test_mix_damping(env, rho_pair, target):
    r, mat = rho_pair
    p = 0.35
    qt.mixDamping(r, target, p)
    k0 = np.array([[1, 0], [0, np.sqrt(1 - p)]])
    k1 = np.array([[0, np.sqrt(p)], [0, 0]])
    expect = oracle.apply_kraus_to_density(mat, N, [target], [k0, k1])
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("q1,q2", [(0, 1), (4, 2)])
def test_mix_two_qubit_depolarising(env, rho_pair, q1, q2):
    r, mat = rho_pair
    p = 0.7
    qt.mixTwoQubitDepolarising(r, q1, q2, p)
    expect = (1 - p) * mat
    for i in range(4):
        for j in range(4):
            if i == 0 and j == 0:
                continue
            P1 = oracle.full_operator(N, [q1], oracle.PAULIS[i])
            P2 = oracle.full_operator(N, [q2], oracle.PAULIS[j])
            expect = expect + (p / 15) * (P1 @ P2 @ mat @ P2 @ P1)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


def test_mix_pauli(env, rho_pair):
    r, mat = rho_pair
    px, py, pz = 0.1, 0.15, 0.2
    target = 3
    qt.mixPauli(r, target, px, py, pz)
    X = oracle.full_operator(N, [target], oracle.X)
    Y = oracle.full_operator(N, [target], oracle.Y)
    Z = oracle.full_operator(N, [target], oracle.Z)
    expect = (
        (1 - px - py - pz) * mat + px * X @ mat @ X + py * Y @ mat @ Y + pz * Z @ mat @ Z
    )
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


def test_mix_density_matrix(env):
    rng = np.random.default_rng(66)
    m1, m2 = oracle.random_density(N, rng), oracle.random_density(N, rng)
    r1 = qt.createDensityQureg(N, env)
    r2 = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, r1, m1)
    oracle.set_qureg_from_array(qt, r2, m2)
    qt.mixDensityMatrix(r1, 0.4, r2)
    np.testing.assert_allclose(
        oracle.state_from_qureg(r1), 0.6 * m1 + 0.4 * m2, atol=ATOL
    )


@pytest.mark.parametrize("num_ops", [1, 2, 4])
def test_mix_kraus_map(env, rho_pair, num_ops):
    r, mat = rho_pair
    rng = np.random.default_rng(77)
    ops = oracle.random_kraus_map(1, num_ops, rng)
    qt.mixKrausMap(r, 2, ops)
    expect = oracle.apply_kraus_to_density(mat, N, [2], ops)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("targets,num_ops", [((0, 1), 2), ((3, 1), 4)])
def test_mix_two_qubit_kraus_map(env, rho_pair, targets, num_ops):
    r, mat = rho_pair
    rng = np.random.default_rng(88)
    ops = oracle.random_kraus_map(2, num_ops, rng)
    qt.mixTwoQubitKrausMap(r, targets[0], targets[1], ops)
    expect = oracle.apply_kraus_to_density(mat, N, list(targets), ops)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


@pytest.mark.parametrize("targets,num_ops", [((2,), 2), ((0, 3), 3), ((1, 2, 4), 2)])
def test_mix_multi_qubit_kraus_map(env, rho_pair, targets, num_ops):
    r, mat = rho_pair
    rng = np.random.default_rng(99)
    ops = oracle.random_kraus_map(len(targets), num_ops, rng)
    qt.mixMultiQubitKrausMap(r, list(targets), ops)
    expect = oracle.apply_kraus_to_density(mat, N, list(targets), ops)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


def test_decoherence_validation(env):
    r = qt.createDensityQureg(N, env)
    q = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="valid only for density matrices"):
        qt.mixDephasing(q, 0, 0.1)
    with pytest.raises(qt.QuESTError, match="dephase error cannot exceed 1/2"):
        qt.mixDephasing(r, 0, 0.6)  # > 1/2
    with pytest.raises(qt.QuESTError, match="depolarising error cannot exceed 3/4"):
        qt.mixDepolarising(r, 0, 0.8)  # > 3/4
    with pytest.raises(qt.QuESTError, match=r"Probabilities must be in \[0, 1\]"):
        qt.mixDamping(r, 0, 1.2)
    with pytest.raises(qt.QuESTError, match="not a completely positive, trace preserving"):
        qt.mixKrausMap(r, 0, [np.eye(2) * 2])
    with pytest.raises(qt.QuESTError, match="cannot exceed the probability of no error"):
        qt.mixPauli(r, 0, 0.5, 0.4, 0.3)


def test_channels_captured_under_fusion_match_eager(env):
    """Inside gateFusion, channels are captured as superoperator gates and
    folded into the drain's passes; the result must equal the eager
    per-channel path exactly (same math, different batching)."""
    import numpy as np
    import oracle

    n = 4
    rng = np.random.default_rng(77)
    mat = oracle.random_density(n, rng)

    def run(fused):
        r = qt.createDensityQureg(n, env)
        oracle.set_qureg_from_array(qt, r, mat)
        def body():
            qt.hadamard(r, 0)
            qt.mixDepolarising(r, 1, 0.25)
            qt.mixDamping(r, 2, 0.4)
            qt.mixDephasing(r, 0, 0.1)
            qt.mixTwoQubitDephasing(r, 1, 3, 0.2)
            qt.controlledNot(r, 0, 3)
            qt.mixKrausMap(r, 3, [np.sqrt(0.7) * oracle.I2,
                                  np.sqrt(0.3) * oracle.X])
        if fused:
            with qt.gateFusion(r):
                body()
        else:
            body()
        return oracle.state_from_qureg(r)

    a = run(False)
    b = run(True)
    np.testing.assert_allclose(a, b, atol=1e-10)


@pytest.mark.parametrize("q1", range(5))
@pytest.mark.parametrize("q2", range(5))
def test_mix_two_qubit_depolarising_all_pairs(env, rho_pair, q1, q2):
    """Exhaustive geometry sweep of the round-4 dedicated orbit kernel
    (local elementwise + sharded <=2-ppermute variants replace the
    256x generic superoperator): every ordered target pair vs the
    15-Pauli oracle."""
    if q1 == q2:
        pytest.skip("targets must differ")
    r, mat = rho_pair
    p = 0.45
    qt.mixTwoQubitDepolarising(r, q1, q2, p)
    expect = (1 - p) * mat
    for i in range(4):
        for j in range(4):
            if i == 0 and j == 0:
                continue
            P1 = oracle.full_operator(N, [q1], oracle.PAULIS[i])
            P2 = oracle.full_operator(N, [q2], oracle.PAULIS[j])
            expect = expect + (p / 15) * (P1 @ P2 @ mat @ P2 @ P1)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)
