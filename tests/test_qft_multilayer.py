"""Multi-layer (radix-2^k) QFT passes vs the DFT oracle and the per-layer
fused path.

The reference QFT is one kernel sweep per Hadamard plus one per phase
ladder (agnostic_applyQFT, /root/reference/QuEST/src/QuEST_common.c:
836-898); the multilayer path runs k butterfly layers per HBM sweep
(fused.apply_qft_multi_hi / apply_qft_cluster_multi) and folds the lane
layers with the low bit-reversal passes (circuit._fused_qft_multilayer).
These tests run the Pallas kernels in interpret mode (plain XLA on the
CPU mesh) — the same bodies Mosaic compiles on a real TPU."""

import numpy as np
import jax.numpy as jnp
import pytest

from quest_tpu import circuit as CIRC
from quest_tpu.ops import fused


def _soa(v):
    return jnp.asarray(np.stack([v.real, v.imag]).astype(np.float32))


def _rand(n, seed):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    return v / np.linalg.norm(v)


@pytest.mark.parametrize("n", [15, 16, 18])
def test_multilayer_full_qft_matches_dft(n):
    v = _rand(n, n)
    out = np.asarray(CIRC._fused_qft_multilayer(_soa(v), n, n, True))
    got = out[0] + 1j * out[1]
    want = np.fft.ifft(v, norm="ortho")
    assert np.abs(got - want).max() < 2e-6


@pytest.mark.parametrize("n,cnt", [(17, 15), (18, 16)])
def test_multilayer_partial_run(n, cnt):
    v = _rand(n, 7 * n + cnt)
    out = np.asarray(CIRC._fused_qft_multilayer(_soa(v), n, cnt, True))
    got = (out[0] + 1j * out[1]).reshape(1 << (n - cnt), 1 << cnt)
    want = np.fft.ifft(v.reshape(1 << (n - cnt), 1 << cnt),
                       axis=1, norm="ortho")
    assert np.abs(got - want).max() < 2e-6


@pytest.mark.parametrize("radix", [1, 3, 5])
def test_multilayer_radix_sweep(radix, monkeypatch):
    monkeypatch.setenv("QT_QFT_RADIX", str(radix))
    n = 17
    v = _rand(n, 100 + radix)
    out = np.asarray(CIRC._fused_qft_multilayer(_soa(v), n, n, True))
    got = out[0] + 1j * out[1]
    want = np.fft.ifft(v, norm="ortho")
    assert np.abs(got - want).max() < 2e-6


def test_multi_hi_kernel_matches_per_layer():
    n = 17
    v = _rand(n, 3)
    out = fused.apply_qft_multi_hi(_soa(v), num_qubits=n, t_hi=16, t_lo=14,
                                   interpret=True)
    ref = _soa(v)
    for t in range(16, 13, -1):
        ref = fused.apply_qft_ladder_pallas(ref, num_qubits=n, target=t,
                                            interpret=True)
    assert float(jnp.abs(out - ref).max()) < 1e-7


def test_cluster_multi_kernel_matches_per_layer():
    n = 16
    v = _rand(n, 4)
    out = fused.apply_qft_cluster_multi(_soa(v), num_qubits=n, interpret=True)
    ref = _soa(v)
    for t in range(13, 6, -1):
        ref = fused.apply_qft_ladder_pallas(ref, num_qubits=n, target=t,
                                            interpret=True)
    assert float(jnp.abs(out - ref).max()) == 0.0


def test_fused_qft_routes_to_multilayer(monkeypatch):
    """fused_qft takes the multilayer path when enabled and agrees with the
    per-layer path on the same input."""
    n = 15
    v = _rand(n, 5)
    monkeypatch.setenv("QT_QFT_ML_INTERPRET", "1")
    out_ml = np.asarray(CIRC.fused_qft(_soa(v), n, 0, n))
    monkeypatch.setenv("QT_QFT_MULTILAYER", "0")
    out_pl = np.asarray(CIRC.fused_qft(_soa(v), n, 0, n))
    assert np.abs(out_ml - out_pl).max() < 2e-6


def test_sharded_qft_multilayer_local_layers(monkeypatch):
    """fused_qft_sharded with a shard big enough for multilayer local
    passes (nloc >= 15) matches the DFT oracle — the radix-2^k kernels
    running per shard inside the shard_map."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from quest_tpu.parallel import dist
    from quest_tpu.env import AMP_AXIS

    monkeypatch.setenv("QT_QFT_ML_INTERPRET", "1")
    n = 18                              # 8 shards -> nloc = 15
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), (AMP_AXIS,))
    v = _rand(n, 99)
    soa = jax.device_put(
        _soa(v), NamedSharding(mesh, P(None, AMP_AXIS)))
    out = np.asarray(dist.fused_qft_sharded(
        soa.reshape(2, -1), mesh=mesh, num_qubits=n))
    got = out[0] + 1j * out[1]
    want = np.fft.ifft(v, norm="ortho")
    assert np.abs(got - want).max() < 2e-6
