"""Float32 oracle sweep of the layout-safe big-state (n >= 14) kernel paths.

The main oracle suite runs at NUM_QUBITS=5 in float64, which exercises only
the small-n einsum paths of ops/kernels.py.  The n >= _BIG_N rewrite (slab
decomposition, lane matmuls, iota indicators, contiguous control slicing —
see the layout-safety note in ops/kernels.py) is covered here at n=14 in
float32, the production dtype, against a dense NumPy oracle.  This is the
test tier that catches stray default-precision (bf16-on-TPU) contractions
and big-path-only logic bugs (cf. reference test strategy SURVEY.md §4).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from quest_tpu.ops import calculations, cplx, kernels

N = 14
M = 1 << N
ATOL = 5e-6  # float32 single-pass kernels


def _rand_state(rng):
    psi = rng.normal(size=(2, M)).astype(np.float32)
    psi /= np.sqrt((psi ** 2).sum())
    return psi


def _rand_unitary(k, rng):
    d = 1 << k
    a = rng.normal(size=(d, d)) + 1j * rng.normal(size=(d, d))
    q, _ = np.linalg.qr(a)
    return q


def _dense_apply(psi, u, targets, n, controls=(), control_states=()):
    """Dense oracle: apply u (2^k x 2^k, bit j of the matrix index =
    targets[j]) on the full state, optionally controlled."""
    z = psi[0] + 1j * psi[1]
    idx = np.arange(1 << n)
    sel = np.ones(1 << n, dtype=bool)
    states = control_states or (1,) * len(controls)
    for c, s in zip(controls, states):
        sel &= ((idx >> c) & 1) == s
    k = len(targets)
    sub = np.zeros(1 << n, dtype=np.int64)
    for j, t in enumerate(targets):
        sub |= ((idx >> t) & 1) << j
    rest = idx.copy()
    for t in targets:
        rest &= ~(1 << t)
    out = z.copy()
    # group amplitudes by rest pattern, matvec the 2^k block
    order = np.lexsort((sub, rest))
    zi = z[order].reshape(-1, 1 << k)
    zi = zi @ u.T
    upd = np.empty_like(z)
    upd[order] = zi.reshape(-1)
    out[sel] = upd[sel]
    return np.stack([out.real, out.imag]).astype(np.float32)


TARGET_SETS = [
    (0,), (6,), (7,), (13,),
    (0, 1), (6, 7), (12, 13), (3, 10), (13, 2),
    (0, 7, 13), (5, 6, 7), (2, 9, 12),
]


@pytest.mark.parametrize("targets", TARGET_SETS)
def test_apply_matrix_oracle(targets):
    rng = np.random.default_rng(hash(targets) % 2 ** 31)
    psi = _rand_state(rng)
    u = _rand_unitary(len(targets), rng)
    got = np.asarray(kernels.apply_matrix(
        jnp.asarray(psi), cplx.soa(u, np.float32), num_qubits=N,
        targets=targets,
    ))
    want = _dense_apply(psi, u, targets, N)
    np.testing.assert_allclose(got, want, atol=ATOL)


CONTROL_CASES = [
    # controls straddling the lane boundary in every combination
    ((3,), (1,), (9,)),
    ((9,), (0,), (3,)),
    ((2, 11), (1, 1), (6,)),
    ((6, 7), (1, 0), (13,)),
    ((12, 1), (0, 1), (7, 0)),
]


@pytest.mark.parametrize("controls,states,targets", CONTROL_CASES)
def test_controlled_matrix_oracle(controls, states, targets):
    rng = np.random.default_rng(11)
    psi = _rand_state(rng)
    u = _rand_unitary(len(targets), rng)
    got = np.asarray(kernels.apply_matrix(
        jnp.asarray(psi), cplx.soa(u, np.float32), num_qubits=N,
        targets=targets, controls=controls, control_states=states,
    ))
    want = _dense_apply(psi, u, targets, N, controls, states)
    np.testing.assert_allclose(got, want, atol=ATOL)


@pytest.mark.parametrize("targets", [(0,), (9,), (2, 11), (0, 5, 9), (7, 8)])
def test_apply_diagonal_oracle(targets):
    rng = np.random.default_rng(5)
    psi = _rand_state(rng)
    k = len(targets)
    d = np.exp(1j * rng.normal(size=(1 << k,)))
    got = np.asarray(kernels.apply_diagonal(
        jnp.asarray(psi), cplx.soa(d, np.float32), num_qubits=N,
        targets=targets,
    ))
    want = _dense_apply(psi, np.diag(d), targets, N)
    np.testing.assert_allclose(got, want, atol=ATOL)


@pytest.mark.parametrize("controls,states,targets", [
    ((2,), (1,), (9, 13)), ((10,), (1,), (0, 4)),
])
def test_controlled_diagonal_oracle(controls, states, targets):
    rng = np.random.default_rng(6)
    psi = _rand_state(rng)
    k = len(targets)
    d = np.exp(1j * rng.normal(size=(1 << k,)))
    got = np.asarray(kernels.apply_diagonal(
        jnp.asarray(psi), cplx.soa(d, np.float32), num_qubits=N,
        targets=targets, controls=controls, control_states=states,
    ))
    want = _dense_apply(psi, np.diag(d), targets, N, controls, states)
    np.testing.assert_allclose(got, want, atol=ATOL)


@pytest.mark.parametrize("targets", [(0,), (3, 8), (1, 2, 13), (7, 9), (0, 6)])
def test_multi_qubit_not_oracle(targets):
    rng = np.random.default_rng(7)
    psi = _rand_state(rng)
    got = np.asarray(kernels.apply_multi_qubit_not(
        jnp.asarray(psi), num_qubits=N, targets=targets,
    ))
    x = np.array([[0, 1], [1, 0]], dtype=complex)
    u = np.eye(1, dtype=complex)
    for _ in targets:
        u = np.kron(x, u)
    want = _dense_apply(psi, u, targets, N)
    np.testing.assert_allclose(got, want, atol=ATOL)


@pytest.mark.parametrize("qubits", [(4,), (0, 9), (2, 7, 13)])
def test_parity_phase_oracle(qubits):
    rng = np.random.default_rng(8)
    psi = _rand_state(rng)
    theta = 0.731
    got = np.asarray(kernels.apply_parity_phase(
        jnp.asarray(psi), np.float32(theta), num_qubits=N, qubits=qubits,
    ))
    idx = np.arange(M)
    par = np.zeros(M, dtype=np.int64)
    for q in qubits:
        par ^= (idx >> q) & 1
    z = (psi[0] + 1j * psi[1]) * np.exp(-0.5j * theta * (1 - 2 * par))
    want = np.stack([z.real, z.imag]).astype(np.float32)
    np.testing.assert_allclose(got, want, atol=ATOL)


def test_swap_and_segment_swap_oracle():
    rng = np.random.default_rng(9)
    psi = _rand_state(rng)
    idx = np.arange(M)
    # single-bit swap through the dense path
    got = np.asarray(kernels.swap_qubit_amps(
        jnp.asarray(psi), num_qubits=N, qb1=2, qb2=12))
    b2, b12 = (idx >> 2) & 1, (idx >> 12) & 1
    src = (idx & ~((1 << 2) | (1 << 12))) | (b12 << 2) | (b2 << 12)
    np.testing.assert_allclose(got, psi[:, src], atol=0)
    # segment swap [10,13) <-> [7,10)
    got = np.asarray(kernels.swap_bit_segments(
        jnp.asarray(psi), num_qubits=N, a=10, b=7, m=3))
    segA = (idx >> 10) & 0b111
    segB = (idx >> 7) & 0b111
    src = (idx & ~(0b111111 << 7)) | (segB << 10) | (segA << 7)
    np.testing.assert_allclose(got, psi[:, src], atol=0)


def test_prob_and_histogram_oracle():
    rng = np.random.default_rng(10)
    psi = _rand_state(rng)
    probs = np.abs(psi[0] + 1j * psi[1]) ** 2
    idx = np.arange(M)
    p = calculations.calc_prob_of_outcome_statevec(
        jnp.asarray(psi), num_qubits=N, target=5, outcome=1)
    assert abs(float(p) - probs[((idx >> 5) & 1) == 1].sum()) < 1e-6
    qubits = (3, 11, 0)
    h = np.asarray(calculations.calc_prob_of_all_outcomes_statevec(
        jnp.asarray(psi), num_qubits=N, qubits=qubits))
    code = sum(((idx >> q) & 1) << j for j, q in enumerate(qubits))
    want = np.bincount(code, weights=probs, minlength=8)
    np.testing.assert_allclose(h, want, atol=1e-6)


def test_collapse_oracle():
    rng = np.random.default_rng(12)
    psi = _rand_state(rng)
    idx = np.arange(M)
    probs = np.abs(psi[0] + 1j * psi[1]) ** 2
    p1 = probs[((idx >> 9) & 1) == 1].sum()
    got = np.asarray(kernels.collapse_statevec(
        jnp.asarray(psi), np.float32(p1), num_qubits=N, target=9, outcome=1))
    z = (psi[0] + 1j * psi[1]) * (((idx >> 9) & 1) == 1) / np.sqrt(p1)
    want = np.stack([z.real, z.imag]).astype(np.float32)
    np.testing.assert_allclose(got, want, atol=ATOL)
