"""Gate-fusion context (quest_tpu/fusion.py): imperative API gates are
buffered and drained through the circuit scheduler with IDENTICAL
semantics to eager dispatch — only the number of HBM passes changes.
(No reference counterpart: QuEST dispatches gate-at-a-time, QuEST.c.)
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import fusion

N = 16  # >= 14 so the windowed scheduler engages


@pytest.fixture
def env():
    # fusion captures only on single-device amplitude meshes (sharded
    # registers use the explicit-distributed path); pin one device
    return qt.createQuESTEnv(num_devices=1)


def _layers(q, n, depth=3):
    for d in range(depth):
        for t in range(n):
            qt.hadamard(q, t)
        for t in range(d % 2, n - 1, 2):
            qt.controlledNot(q, t, t + 1)
    qt.controlledPhaseShift(q, 2, n - 1, 0.3)
    qt.multiStateControlledUnitary(
        q, [0, 9], [0, 1], 4, np.array([[0, 1], [1, 0]], complex))
    qt.tGate(q, 5)
    qt.rotateAroundAxis(q, 7, 0.4, qt.Vector(1.0, 1.0, 0.0))


def _rel_err(a, b):
    return np.abs(a - b).max() / np.abs(b).max()


class TestEquivalence:
    def test_statevector(self, env):
        q0 = qt.createQureg(N, env)
        qt.initPlusState(q0)
        _layers(q0, N)
        ref = np.asarray(q0.amps)

        q1 = qt.createQureg(N, env)
        qt.initPlusState(q1)
        with qt.gateFusion(q1):
            _layers(q1, N)
        assert _rel_err(np.asarray(q1.amps), ref) < 1e-5

    def test_density_matrix(self, env):
        def prog(q):
            qt.hadamard(q, 0)
            qt.controlledNot(q, 0, 5)
            qt.pauliY(q, 3)
            qt.phaseShift(q, 6, 0.7)

        q0 = qt.createDensityQureg(7, env)
        qt.initPlusState(q0)
        prog(q0)
        qt.mixDepolarising(q0, 2, 0.05)
        prog(q0)
        ref = np.asarray(q0.amps)

        q1 = qt.createDensityQureg(7, env)
        qt.initPlusState(q1)
        with qt.gateFusion(q1):
            prog(q1)
            qt.mixDepolarising(q1, 2, 0.05)  # implicit drain mid-context
            prog(q1)
        assert _rel_err(np.asarray(q1.amps), ref) < 1e-5


class TestDrainTriggers:
    def test_read_drains(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            assert len(q._fusion.gates) == 1
            p = qt.calcProbOfOutcome(q, 0, 0)  # reads amps -> drain
            assert len(q._fusion.gates) == 0
            assert abs(p - 0.5) < 1e-6

    def test_write_drains_in_order(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.pauliX(q, 0)
            qt.initZeroState(q)  # overwrites; buffered X must not leak after
            qt.hadamard(q, 1)
        assert abs(qt.calcProbOfOutcome(q, 0, 1)) < 1e-6
        assert abs(qt.calcProbOfOutcome(q, 1, 1) - 0.5) < 1e-6

    def test_large_gate_falls_back_eagerly(self, env):
        q = qt.createQureg(N, env)
        qt.initPlusState(q)
        u = np.eye(1 << 8, dtype=complex)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            qt.applyMatrixN(q, list(range(8)), u)  # 8 qubits > cap
            # the big gate drained the buffer before executing eagerly
            assert len(q._fusion.gates) == 0
        assert abs(qt.calcTotalProb(q) - 1.0) < 1e-5

    def test_context_exit_drains(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 3)
            assert len(q._fusion.gates) == 1
        assert q._fusion is None
        assert abs(qt.calcProbOfOutcome(q, 3, 0) - 0.5) < 1e-6


class TestSideChannels:
    def test_qasm_recorded_in_call_order(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        qt.startRecordingQASM(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            qt.controlledNot(q, 0, 1)
        qt.stopRecordingQASM(q)
        text = str(q.qasm_log)
        assert text.index("h q[0]") < text.index("cx q[0],q[1]")

    def test_validation_still_eager(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            with pytest.raises(qt.QuESTError):
                qt.hadamard(q, N)  # out of range

    def test_measure_drains(self, env):
        qt.seedQuEST(qt.createQuESTEnv(), [7])
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.pauliX(q, 4)
            outcome = qt.measure(q, 4)
        assert outcome == 1


class TestReviewRegressions:
    def test_failed_drain_restores_buffer(self, env, monkeypatch):
        # ADVICE r1: a drain that raises must not lose the buffered gates
        from quest_tpu import fusion as F

        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        qt.startGateFusion(q)
        qt.pauliX(q, 0)
        qt.hadamard(q, 1)
        assert len(q._fusion.gates) == 2

        def boom(qureg, gates):
            raise RuntimeError("injected drain failure")

        monkeypatch.setattr(F, "_run", boom)
        with pytest.raises(RuntimeError, match="injected"):
            F.drain(q)
        assert len(q._fusion.gates) == 2  # restored, not lost
        monkeypatch.undo()
        qt.stopGateFusion(q)
        assert qt.calcProbOfOutcome(q, 0, 1) == pytest.approx(1.0)

    def test_nested_contexts_keep_outer_buffering(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            with qt.gateFusion(q):  # inner context reuses the outer buffer
                qt.hadamard(q, 1)
            assert q._fusion is not None  # outer still active
            qt.hadamard(q, 2)
            assert len(q._fusion.gates) == 3
        assert q._fusion is None
        for t in (0, 1, 2):
            assert abs(qt.calcProbOfOutcome(q, t, 0) - 0.5) < 1e-6

    def test_wide_controlled_not_stays_cheap(self, env):
        # 20 targets under one control must NOT densify 2^20 x 2^20
        n = 22
        q = qt.createQureg(n, env)
        qt.initZeroState(q)
        qt.pauliX(q, n - 1)
        with qt.gateFusion(q):
            qt.multiControlledMultiQubitNot(q, [n - 1], list(range(20)))
        for t in range(20):
            assert abs(qt.calcProbOfOutcome(q, t, 1) - 1.0) < 1e-6

    def test_overwrite_discards_buffer_cheaply(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            qt.initClassicalState(q, 5)  # overwrite: buffer dropped unexecuted
            assert len(q._fusion.gates) == 0
        assert abs(qt.calcProbOfOutcome(q, 0, 1) - 1.0) < 1e-6
        assert abs(qt.calcProbOfOutcome(q, 2, 1) - 1.0) < 1e-6


class TestSwapCapture:
    def test_swap_gate_buffers(self, env):
        q = qt.createQureg(N, env)
        qt.initZeroState(q)
        qt.pauliX(q, 2)
        with qt.gateFusion(q):
            qt.hadamard(q, 0)
            qt.swapGate(q, 2, 9)          # buffered, not a drain
            assert len(q._fusion.gates) == 2
        assert abs(qt.calcProbOfOutcome(q, 9, 1) - 1.0) < 1e-6
        assert abs(qt.calcProbOfOutcome(q, 2, 1)) < 1e-6

    def test_swap_gate_density(self, env):
        r = qt.createDensityQureg(7, env)
        qt.initClassicalState(r, 1)
        with qt.gateFusion(r):
            qt.swapGate(r, 0, 6)
        assert abs(qt.calcProbOfOutcome(r, 6, 1) - 1.0) < 1e-6


class TestShardedFusion:
    """Fusion on SHARDED registers: local-bit gates buffer and drain as
    one shard_map program over the amplitude mesh; gates touching
    mesh-coordinate bits drain and run the explicit-distributed path."""

    def test_sharded_drain_matches_eager(self):
        env8 = qt.createQuESTEnv()  # 8 virtual devices -> 3 shard bits
        n = 17                      # nloc = 14: full window space local

        def prog(q):
            for t in range(14):
                qt.hadamard(q, t)
            for t in range(0, 13, 2):
                qt.controlledNot(q, t, t + 1)
            qt.pauliX(q, 16)         # mesh-coordinate bit: eager fallback
            qt.rotateZ(q, 5, 0.3)

        q1 = qt.createQureg(n, env8)
        qt.initZeroState(q1)
        with qt.gateFusion(q1):
            qt.hadamard(q1, 0)
            assert len(q1._fusion.gates) == 1
            prog(q1)
        got = np.asarray(q1.amps)
        extra = qt.createQureg(n, env8)
        qt.initZeroState(extra)
        qt.hadamard(extra, 0)
        prog(extra)
        np.testing.assert_allclose(got, np.asarray(extra.amps), atol=1e-6)
        assert abs(qt.calcTotalProb(q1) - 1.0) < 1e-5

    def test_global_bit_gate_buffers_through_lazy_remap(self):
        """A gate on a mesh-coordinate bit now BUFFERS too: the drain
        relocalizes it at window granularity through the lazy
        logical->physical permutation instead of bailing to the eager
        per-gate path (the communication-avoiding scheduler)."""
        env8 = qt.createQuESTEnv()
        q = qt.createQureg(17, env8)
        qt.initZeroState(q)
        with qt.gateFusion(q):
            qt.hadamard(q, 2)
            assert len(q._fusion.gates) == 1
            qt.hadamard(q, 15)   # >= nloc: stays buffered
            assert len(q._fusion.gates) == 2
        assert abs(qt.calcProbOfOutcome(q, 15, 0) - 0.5) < 1e-6
        assert abs(qt.calcProbOfOutcome(q, 2, 0) - 0.5) < 1e-6
        assert q._perm is None  # the read rematerialized canonical order


class TestChannelCapture:
    """Depolarise/damping captured as ChannelItems: the one-pass
    elementwise kernels run inside the drain program, interleaved in call
    order with gate segments (never the rank-4 superoperator fold)."""

    def test_channels_interleave_with_gates(self, env):
        n = 4
        def prog(r):
            qt.hadamard(r, 0)
            qt.mixDepolarising(r, 1, 0.1)
            qt.controlledNot(r, 0, 2)
            qt.mixDamping(r, 0, 0.2)
            qt.mixDepolarising(r, 3, 0.05)

        fused = qt.createDensityQureg(n, env)
        qt.initPlusState(fused)
        with qt.gateFusion(fused):
            prog(fused)
            # buffered: 2 gate entries x2 twins... entries stay buffered
            assert any(isinstance(g, fusion.ChannelItem)
                       for g in fused._fusion.gates)
        eager = qt.createDensityQureg(n, env)
        qt.initPlusState(eager)
        prog(eager)
        np.testing.assert_allclose(np.asarray(fused.amps),
                                   np.asarray(eager.amps), atol=1e-12)

    def test_channel_oracle(self, env):
        """Fused channel sequence against the dense Kraus oracle."""
        import oracle

        n = 3
        p1, p2 = 0.3, 0.4
        rng = np.random.default_rng(11)
        mat = oracle.random_density(n, rng)
        r = qt.createDensityQureg(n, env)
        oracle.set_qureg_from_array(qt, r, mat)
        with qt.gateFusion(r):
            qt.mixDepolarising(r, 2, p1)
            qt.mixDamping(r, 1, p2)
        X = oracle.full_operator(n, [2], oracle.X)
        Y = oracle.full_operator(n, [2], oracle.Y)
        Z = oracle.full_operator(n, [2], oracle.Z)
        ref = (1 - p1) * mat + (p1 / 3) * (
            X @ mat @ X + Y @ mat @ Y + Z @ mat @ Z)
        k0 = np.array([[1, 0], [0, np.sqrt(1 - p2)]])
        k1 = np.array([[0, np.sqrt(p2)], [0, 0]])
        ref = oracle.apply_kraus_to_density(ref, n, [1], [k0, k1])
        got = oracle.state_from_qureg(r)
        np.testing.assert_allclose(got, ref, atol=1e-10)

    def test_reprob_no_recompile_key(self, env):
        """Same shape, different probabilities -> same cached plan key."""
        n = 3
        keys = []
        for p in (0.1, 0.25):
            r = qt.createDensityQureg(n, env)
            qt.initPlusState(r)
            with qt.gateFusion(r):
                qt.hadamard(r, 0)
                qt.mixDepolarising(r, 1, p)
                items = list(r._fusion.gates)
                keys.append(fusion._plan_key(
                    items, r.num_qubits_in_state_vec, True))
        assert keys[0] == keys[1]

    def test_sharded_register_channel_capture(self):
        """On a sharded density register, shard-local channels capture and
        the drain (one shard_map) matches the eager path."""
        env8 = qt.createQuESTEnv()
        if env8.num_devices < 8:
            pytest.skip("needs 8 virtual devices")
        n = 7                    # 2n=14 on 8 shards -> nloc=11
        def prog(r):
            qt.hadamard(r, 0)
            qt.mixDepolarising(r, 1, 0.2)   # bits (1, 8): local
            qt.mixDamping(r, 0, 0.1)        # bits (0, 7): local
        fused = qt.createDensityQureg(n, env8)
        qt.initPlusState(fused)
        with qt.gateFusion(fused):
            prog(fused)
        eager = qt.createDensityQureg(n, env8)
        qt.initPlusState(eager)
        prog(eager)
        np.testing.assert_allclose(np.asarray(fused.amps),
                                   np.asarray(eager.amps), atol=1e-12)

    def test_sharded_bra_bit_channel_captured_via_remap(self):
        """A channel whose bra bit is a mesh coordinate is now CAPTURED:
        the drain's window remap pulls the bra bit shard-local (the pair
        kernel runs at the permuted positions — both channel kinds are
        (t, b)-symmetric) and the result matches the eager
        explicit-distributed path."""
        env8 = qt.createQuESTEnv()
        if env8.num_devices < 8:
            pytest.skip("needs 8 virtual devices")
        n = 7
        fused = qt.createDensityQureg(n, env8)
        qt.initPlusState(fused)
        with qt.gateFusion(fused):
            qt.hadamard(fused, 0)
            qt.mixDepolarising(fused, 6, 0.2)   # bra bit 13 >= nloc=11
            assert len(fused._fusion.gates) == 3  # H + bra twin + channel
        eager = qt.createDensityQureg(n, env8)
        qt.initPlusState(eager)
        qt.hadamard(eager, 0)
        qt.mixDepolarising(eager, 6, 0.2)
        np.testing.assert_allclose(np.asarray(fused.amps),
                                   np.asarray(eager.amps), atol=1e-12)

    def test_channel_sweep_path(self, env, monkeypatch):
        """With sweeps enabled (interpret opt-in on CPU), a noise layer on
        a >= 15-bit register drains through apply_pair_channel_sweep and
        matches the eager path."""
        monkeypatch.setenv("QT_CHAN_SWEEP_INTERPRET", "1")
        n = 8                              # nn = 16 >= 15
        def prog(r):
            qt.hadamard(r, 0)
            for q in range(n):
                qt.mixDepolarising(r, q, 0.04 + 0.01 * q)
            qt.mixDamping(r, 2, 0.3)
        fused = qt.createDensityQureg(n, env)
        qt.initPlusState(fused)
        with qt.gateFusion(fused):
            prog(fused)
        eager = qt.createDensityQureg(n, env)
        qt.initPlusState(eager)
        prog(eager)
        np.testing.assert_allclose(np.asarray(fused.amps),
                                   np.asarray(eager.amps), atol=1e-5)


def test_sharded_drain_channel_sweep(monkeypatch):
    """ADVICE r3 (a): the chansweep branch INSIDE the sharded drain's
    shard_map actually runs (needs nloc >= 15: a 9q rho over 8 devices
    gives nloc = 15) and matches the eager per-channel path.  f32 +
    QT_CHAN_SWEEP_INTERPRET=1 so channel_sweep_enabled engages on the
    CPU interpret path."""
    env = qt.createQuESTEnv()   # the full 8-device mesh, not the pinned
    if env.num_devices < 8:      # single-device fixture this module uses
        pytest.skip("needs the 8-device virtual mesh")
    monkeypatch.setenv("QT_CHAN_SWEEP_INTERPRET", "1")
    from quest_tpu.ops import fused as F
    calls = {"n": 0}
    real_sweep = F.apply_pair_channel_sweep

    def spy(*a, **k):
        calls["n"] += 1
        return real_sweep(*a, **k)

    monkeypatch.setattr(F, "apply_pair_channel_sweep", spy)
    qt.set_precision(1)
    try:
        nq = 9
        r1 = qt.createDensityQureg(nq, env)
        qt.initPlusState(r1)
        r2 = qt.createDensityQureg(nq, env)
        qt.initPlusState(r2)

        def noise(r):
            for t in range(6):   # bra bit t+9 < nloc=15 so channels capture
                qt.mixDepolarising(r, t, 0.03 + 0.01 * t)
            qt.hadamard(r, 0)
            for t in range(6):
                qt.mixDamping(r, t, 0.02)

        with qt.gateFusion(r1):
            noise(r1)
        noise(r2)
        assert calls["n"] >= 1, "chansweep branch never ran in the drain"
        np.testing.assert_allclose(np.asarray(r1.amps), np.asarray(r2.amps),
                                   atol=5e-6)
        assert abs(qt.calcTotalProb(r1) - 1.0) < 1e-5
    finally:
        qt.set_precision(2)
