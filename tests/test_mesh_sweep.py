"""Mesh-size sweep for the explicit distributed layer (VERDICT r4 item 4).

The reference runs its whole suite under ``mpirun -np {2,4,8,16}``
(/root/reference/examples/README.md:438-451); the suite otherwise pins
ONE mesh size (8 virtual devices, r=3, tests/conftest.py).  Every
shard-count-dependent branch gets exercised here at r in {1, 2, 3}
(2/4/8 devices, via createQuESTEnv(num_devices=...) truncating the
8-device virtual backend) with BOTH oracle parity and pinned HLO
collective counts, plus the boundary cases the single mesh never hits:

- nloc = r (the smallest register that still spans the mesh, n = 2r):
  _split_parity_mask's three branches and the 1q exchange at minimal
  local width;
- plan_relocalization free-pool exhaustion (more sharded targets than
  free local qubits) and the barely-enough case;
- a 16-device (r=4) smoke in a subprocess (the virtual backend holds 8
  devices per process), driving gate/trotter/expec/measure end-to-end.

The full-register fused-QFT guard ``nsv - r >= r`` (api_ops._try_fused
qft routing) can only go false at r >= 8 (WINDOW=14 forces nsv >= 14),
i.e. a 256-device mesh — its false branch is exercised structurally via
fused_qft_runs_sharded below and the guard arithmetic asserted directly.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import oracle
import quest_tpu as qt
from quest_tpu import introspect
from quest_tpu.ops import paulis as OPS_P
from quest_tpu.parallel import dist as PAR

from test_distributed_hlo import collective_ops  # noqa: F401 - API alias

MESH_SIZES = [2, 4, 8]


@pytest.fixture(scope="module", params=MESH_SIZES)
def swept_env(request):
    if len(jax.devices()) < request.param:
        pytest.skip(f"needs {request.param} virtual devices")
    return qt.createQuESTEnv(num_devices=request.param)


def _r(env):
    return PAR.num_shard_bits(env.mesh)


def _sharded(env, arr):
    return jax.device_put(jnp.asarray(arr), env.amp_sharding())


def _rand_soa(n, seed):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((2, 1 << n))
    a /= np.sqrt((a ** 2).sum())
    return a


# ---------------------------------------------------------------------------
# Scan composites: oracle parity + pinned collectives at every r
# ---------------------------------------------------------------------------


class TestTrotterScanSweep:
    def test_parity_vs_unsharded(self, swept_env):
        n = 8
        r = _r(swept_env)
        rng = np.random.default_rng(100 + r)
        a = _rand_soa(n, 100 + r)
        codes = jnp.asarray(rng.integers(0, 4, size=(5, n)), jnp.int32)
        angles = jnp.asarray(rng.normal(size=5))
        want = np.asarray(OPS_P.trotter_scan(
            jnp.asarray(a), codes, angles, num_qubits=n, rep_qubits=n))
        got = np.asarray(PAR.trotter_scan_sharded(
            _sharded(swept_env, a), codes, angles, mesh=swept_env.mesh,
            num_qubits=n, rep_qubits=n))
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_parity_at_nloc_equals_r(self, swept_env):
        """n = 2r: every local qubit is matched by a mesh bit — the
        smallest register the explicit layer accepts."""
        r = _r(swept_env)
        n = 2 * r
        rng = np.random.default_rng(200 + r)
        a = _rand_soa(n, 200 + r)
        codes = jnp.asarray(rng.integers(0, 4, size=(4, n)), jnp.int32)
        angles = jnp.asarray(rng.normal(size=4))
        want = np.asarray(OPS_P.trotter_scan(
            jnp.asarray(a), codes, angles, num_qubits=n, rep_qubits=n))
        got = np.asarray(PAR.trotter_scan_sharded(
            _sharded(swept_env, a), codes, angles, mesh=swept_env.mesh,
            num_qubits=n, rep_qubits=n))
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_hlo_direct_switch_permutes(self, swept_env):
        """The direct term body exchanges via ONE lax.switch over the
        2^r static XOR permutes: the module holds 2^r - 1
        collective-permutes (one per non-identity branch) regardless of
        term count."""
        n = 8
        r = _r(swept_env)
        amps = _sharded(swept_env, _rand_soa(n, 300 + r))
        codes = jnp.asarray(np.random.default_rng(0).integers(
            0, 4, size=(3, n)), jnp.int32)
        angles = jnp.asarray(np.linspace(0.1, 0.3, 3))

        def f(a):
            return PAR.trotter_scan_sharded(
                a, codes, angles, mesh=swept_env.mesh, num_qubits=n,
                rep_qubits=n)

        # same pin, through the public audit/budget API (introspect)
        with introspect.CollectiveBudget(
                exact={"collective-permute": 2 ** r - 1}):
            introspect.audit(f, amps, donate=True)


class TestExpecScanSweep:
    def test_parity_vs_unsharded(self, swept_env):
        n = 8
        r = _r(swept_env)
        rng = np.random.default_rng(400 + r)
        a = _rand_soa(n, 400 + r)
        codes = jnp.asarray(rng.integers(0, 4, size=(4, n)), jnp.int32)
        coeffs = jnp.asarray(rng.normal(size=4))
        want = float(OPS_P.expec_pauli_sum_scan(
            jnp.asarray(a), codes, coeffs, num_qubits=n))
        got = float(PAR.expec_pauli_sum_scan_sharded(
            _sharded(swept_env, a), codes, coeffs, mesh=swept_env.mesh,
            num_qubits=n))
        assert abs(got - want) < 1e-12

    def test_hlo_switch_permutes_one_allreduce(self, swept_env):
        """Direct body: one mesh-flip switch (2^r - 1 branch permutes,
        at most one executed per term) + ONE final psum."""
        n = 8
        r = _r(swept_env)
        amps = _sharded(swept_env, _rand_soa(n, 500 + r))
        codes = jnp.asarray(np.random.default_rng(1).integers(
            0, 4, size=(3, n)), jnp.int32)
        coeffs = jnp.asarray(np.linspace(1.0, 2.0, 3))

        def f(a):
            return PAR.expec_pauli_sum_scan_sharded(
                a, codes, coeffs, mesh=swept_env.mesh, num_qubits=n)

        report = introspect.audit(f, amps)
        hist = report.collectives
        assert report.count("collective-permute") == 2 ** r - 1, hist
        assert report.count("all-reduce") == 1, hist
        assert set(hist) <= {"collective-permute", "all-reduce",
                             "all-reduce-start"}, hist


# ---------------------------------------------------------------------------
# API end-to-end per mesh size: gates, channels, QFT, measurement
# ---------------------------------------------------------------------------


class TestApiSweep:
    def test_gates_reductions_measure(self, swept_env):
        """Sharded-target 1q gate, sharded control, 2q relocalization,
        reductions and fused measurement — through the public API at
        every mesh size."""
        n = 8
        q = qt.createQureg(n, swept_env)
        for t in range(n):
            qt.hadamard(q, t)
        qt.controlledNot(q, n - 1, 0)
        rng = np.random.default_rng(7)
        m = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
        u, _ = np.linalg.qr(m)
        qt.twoQubitUnitary(q, 2, n - 1, u)
        assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10
        p0 = qt.calcProbOfOutcome(q, n - 1, 0)
        assert 0.0 <= p0 <= 1.0 + 1e-12
        outcome, _ = qt.measureWithStats(q, n - 1)
        assert outcome in (0, 1)
        assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10

    def test_density_channels(self, swept_env):
        nq = 4
        rho = qt.createDensityQureg(nq, swept_env)
        qt.hadamard(rho, 0)
        qt.mixDepolarising(rho, nq - 1, 0.2)
        qt.mixDamping(rho, nq - 1, 0.1)
        qt.mixDephasing(rho, 0, 0.05)
        assert abs(qt.calcTotalProb(rho) - 1.0) < 1e-10

    def test_full_qft_vs_dft_oracle(self, swept_env):
        """applyFullQFT at window size on every mesh: r in {1,2,3} all
        satisfy the nsv - r >= r guard (14 - 3 = 11 >= 3), so the
        all-mesh fused kernel runs; parity against the dense DFT."""
        n = 14
        rng = np.random.default_rng(60 + _r(swept_env))
        vec = oracle.random_state(n, rng)
        q = qt.createQureg(n, swept_env)
        oracle.set_qureg_from_array(qt, q, vec)
        qt.applyFullQFT(q)
        want = oracle.dft_matrix(n) @ vec
        np.testing.assert_allclose(oracle.state_from_qureg(q), want,
                                   atol=1e-10)

    def test_partial_qft_mesh_run(self, swept_env):
        """A run reaching the mesh bits routes fused_qft_runs_sharded's
        ppermute layers + mixed reversal at every r."""
        n = 14
        r = _r(swept_env)
        start, count = 7, n - 7
        rng = np.random.default_rng(70 + r)
        vec = oracle.random_state(n, rng)
        q = qt.createQureg(n, swept_env)
        oracle.set_qureg_from_array(qt, q, vec)
        qt.applyQFT(q, list(range(start, start + count)))
        D = oracle.dft_matrix(count)
        want = oracle.full_operator(
            n, list(range(start, start + count)), D) @ vec
        np.testing.assert_allclose(oracle.state_from_qureg(q), want,
                                   atol=1e-10)


# ---------------------------------------------------------------------------
# Boundary cases
# ---------------------------------------------------------------------------


def test_qft_guard_arithmetic():
    """The full-register fused-QFT guard nsv - r >= r: with WINDOW=14
    forcing nsv >= 14 the false branch needs r >= 7 (a 128-device mesh)
    — assert the arithmetic so a future WINDOW change that makes the
    edge reachable shows up here."""
    from quest_tpu import circuit as CIRC

    assert CIRC.WINDOW == 14
    for r in (1, 2, 3, 4, 7):
        assert CIRC.WINDOW - r >= r  # guard true at every testable r
    assert CIRC.WINDOW - 8 < 8       # first false r: a 256-device mesh


class TestRelocalizationPool:
    def test_exhaustion_returns_none(self):
        """More sharded targets than free local qubits: (None, None) —
        the caller falls back (the reference rejects such ops outright,
        QuEST_validation.c:469-471)."""
        swaps, new_t = PAR.plan_relocalization(
            6, 2, targets=(0, 1, 4, 5))
        assert swaps is None and new_t is None

    def test_controls_shrink_the_pool(self):
        swaps, new_t = PAR.plan_relocalization(
            6, 2, targets=(4, 5), controls=(0,))
        assert swaps is None and new_t is None

    def test_barely_enough(self):
        swaps, new_t = PAR.plan_relocalization(
            6, 2, targets=(4, 5))
        assert swaps == ((0, 4), (1, 5)) and new_t == (0, 1)

    def test_end_to_end_fallback_still_correct(self, env):
        """A 3q unitary on a 2-local-qubit register (nloc < #targets
        after exclusion): the op still completes correctly through the
        fallback path on the virtual mesh."""
        if env.num_devices < 8:
            pytest.skip("needs the 8-device mesh")
        n = 5  # nloc = 2 on 8 devices
        rng = np.random.default_rng(81)
        vec = oracle.random_state(n, rng)
        q = qt.createQureg(n, env)
        oracle.set_qureg_from_array(qt, q, vec)
        m = rng.standard_normal((8, 8)) + 1j * rng.standard_normal((8, 8))
        u, _ = np.linalg.qr(m)
        qt.multiQubitUnitary(q, [2, 3, 4], u)
        want = oracle.full_operator(n, [2, 3, 4], u) @ vec
        np.testing.assert_allclose(oracle.state_from_qureg(q), want,
                                   atol=1e-10)


# ---------------------------------------------------------------------------
# 16-device smoke (subprocess: the in-process backend holds 8 devices)
# ---------------------------------------------------------------------------

_SMOKE_16 = r"""
import sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np, jax.numpy as jnp
import quest_tpu as qt
from quest_tpu.ops import paulis as OPS_P
from quest_tpu.parallel import dist as PAR

qt.set_precision(2)
env = qt.createQuESTEnv()
assert env.num_ranks == 16, env.num_ranks
n = 8
rng = np.random.default_rng(0)
a = rng.standard_normal((2, 1 << n)); a /= np.sqrt((a**2).sum())
codes = jnp.asarray(rng.integers(0, 4, size=(3, n)), jnp.int32)
angles = jnp.asarray(rng.normal(size=3))
want = np.asarray(OPS_P.trotter_scan(jnp.asarray(a), codes, angles,
                                     num_qubits=n, rep_qubits=n))
sh = jax.device_put(jnp.asarray(a), env.amp_sharding())
got = np.asarray(PAR.trotter_scan_sharded(
    sh, codes, angles, mesh=env.mesh, num_qubits=n, rep_qubits=n))
np.testing.assert_allclose(got, want, atol=1e-12)
ew = float(OPS_P.expec_pauli_sum_scan(jnp.asarray(a), codes,
                                      angles, num_qubits=n))
eg = float(PAR.expec_pauli_sum_scan_sharded(
    jax.device_put(jnp.asarray(a), env.amp_sharding()), codes, angles,
    mesh=env.mesh, num_qubits=n))
assert abs(ew - eg) < 1e-12, (ew, eg)
q = qt.createQureg(n, env)
for t in range(n):
    qt.hadamard(q, t)
qt.controlledNot(q, n - 1, 0)
assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10
o, _ = qt.measureWithStats(q, n - 1)
assert o in (0, 1)
assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10
print("SMOKE16 OK r=4")
"""


def test_sixteen_device_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env.pop("JAX_PLATFORMS", None)
    code = _SMOKE_16.format(repo=repo, tests=tests)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "SMOKE16 OK r=4" in proc.stdout
