"""Communication-avoiding lazy qubit remapping (the mpiQulacs-style
scheduler, arXiv:2203.16044): the distributed planner keeps the state in a
permuted physical order, schedules ONE batched remap per window of gates
instead of two half-shard exchanges per sharded-target gate, and only
rematerializes canonical order on a state read.

Covers the acceptance contract:
  * HLO-audited collective counts: a circuit with k sharded-target gates
    across w windows emits O(w) remap exchanges, not 2k half-shard
    ppermutes;
  * final amplitudes BIT-IDENTICAL to the eager swap-in/swap-out per-gate
    path (dist.use_lazy_remap(False));
  * every read (calcProbOfOutcome, measurement, checkpoint write, host
    gather) returns canonical-order results while a permutation is live —
    including reads interleaved mid-circuit.
"""

import numpy as np
import pytest

import jax

import oracle
import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion
from quest_tpu.ops import fused as F
from quest_tpu.parallel import dist

N = 6  # 64 amps over 8 devices -> nloc = 3: qubits 3, 4, 5 are sharded
ATOL = 1e-12

_COLLECTIVE_OPS = (
    "all-reduce", "all-reduce-start", "collective-permute",
    "collective-permute-start", "all-gather", "all-gather-start",
    "all-to-all", "reduce-scatter",
)


def _hlo_collectives(jitted, *args):
    txt = jitted.lower(*args).compile().as_text()
    hist = {}
    for op in _COLLECTIVE_OPS:
        c = txt.count(f" {op}(")
        if c:
            hist[op] = c
    return hist


@pytest.fixture(autouse=True)
def _require_multidevice(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device virtual mesh")


@pytest.fixture(autouse=True)
def _lazy_on():
    dist.use_lazy_remap(True)
    yield
    dist.use_lazy_remap(True)


def _rand_psi(env, rng, n=N):
    vec = oracle.random_state(n, rng)
    q = qt.createQureg(n, env)
    oracle.set_qureg_from_array(qt, q, vec)
    return q, vec


H_SOA = np.stack([(1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]]),
                  np.zeros((2, 2))])


# ---------------------------------------------------------------------------
# Unit level: the permutation algebra
# ---------------------------------------------------------------------------


class TestRemapAlgebra:
    def test_decompose_sigma_classes(self):
        # n=6, nloc=3, r=3: swap (0<->3), pure local swap (1<->2),
        # pure mesh swap (4<->5)
        sigma = (3, 2, 1, 0, 5, 4)
        mixed, local_perm, mesh_tau = dist.decompose_sigma(sigma, 3, 3)
        assert mixed == ((0, 0),)          # local bit 0 <-> mesh bit 0
        assert local_perm == (0, 2, 1)     # swap local bits 1, 2
        assert mesh_tau == (0, 2, 1)       # swap mesh bits 1, 2

    def test_remap_sharded_is_the_bit_permutation(self, env):
        rng = np.random.default_rng(3)
        q, vec = _rand_psi(env, rng)
        sigma = (3, 2, 1, 0, 5, 4)
        got = dist.remap_sharded(q.amps, mesh=env.mesh, num_qubits=N,
                                 sigma=sigma)
        out = np.asarray(got)[0] + 1j * np.asarray(got)[1]
        idx = np.arange(1 << N)
        dest = np.zeros_like(idx)
        for p in range(N):
            dest |= ((idx >> p) & 1) << sigma[p]
        expect = np.zeros_like(vec)
        expect[dest] = vec[idx]
        np.testing.assert_allclose(out, expect, atol=0)

    def test_plan_window_remap_keeps_residents(self):
        # wanted {0, 4}: 0 already local stays; 4 swaps with the local
        # slot whose resident is needed furthest (qubit 2, never again)
        sigma, perm = dist.plan_window_remap(
            6, 3, tuple(range(6)), [0, 4], next_use={1: 0, 0: 1})
        assert perm[0] == 0 and perm[4] == 2 and perm[2] == 4
        assert sigma[2] == 4 and sigma[4] == 2
        # already-local window: no movement
        sigma, perm = dist.plan_window_remap(6, 3, tuple(range(6)), [0, 1])
        assert sigma is None and perm == tuple(range(6))
        # over-capacity window is rejected, not mangled
        sigma, perm = dist.plan_window_remap(6, 3, tuple(range(6)),
                                             [0, 1, 2, 3])
        assert sigma is None and perm is None

    def test_plan_remap_windows_one_remap_per_window(self):
        # 3 windows of 3 distinct qubits on nloc=3: {3,4,5}, {0,1,2},
        # {3,4,5} — one sigma each, and window 2's sigma undoes nothing
        # (the permutation persists, no swap-back)
        bits = [(3,), (4,), (5,), (0,), (1,), (2,), (3,), (4,), (5,)]
        segments, final_perm = CIRC.plan_remap_windows(bits, 6, 3)
        assert [seg[0] for seg in segments] == [(0, 3), (3, 6), (6, 9)]
        assert all(seg[1] is not None for seg in segments)
        # every window's qubits are local under its perm
        for (i, j), _, perm in segments:
            for k in range(i, j):
                assert all(perm[b] < 3 for b in bits[k])
        assert sorted(final_perm) == list(range(6))


# ---------------------------------------------------------------------------
# HLO audit: O(windows) exchanges, not O(2 * sharded gates)
# ---------------------------------------------------------------------------


class TestWindowExchangeCounts:
    def test_drain_program_emits_one_exchange_per_window(self, env):
        """k = 18 sharded-target gates across w = 3 windows: the compiled
        drain program contains EXACTLY 3 half-shard exchanges per window
        (every window displaces all three local residents) = 9
        collective-permutes total — the per-gate path would cost 2k = 36."""
        n, nloc = N, 3
        items = []
        # window 1: 6 gates on {3, 4, 5}; window 2: 6 on {0, 1, 2} (which
        # window 1 evicted to mesh bits!); window 3: 6 on {3, 4, 5} again
        for block in ([3, 4, 5], [0, 1, 2], [3, 4, 5]):
            for t in block + block:
                items.append(CIRC.Gate((t,), H_SOA))
        k = sum(1 for it in items)          # 18 gates
        program, arrays, final_perm = fusion._split_items_sharded(
            items, n, nloc, None, False)
        remaps = [p for p in program if p[0] == "remap"]
        assert len(remaps) == 3             # ONE remap per window
        runner = fusion._plan_runner(nloc, program, env.mesh,
                                     F.matmul_precision_name())
        amps = qt.createQureg(n, env).amps
        hist = _hlo_collectives(runner, amps, tuple(arrays), ())
        assert set(hist) <= {"collective-permute"}, hist
        # each remap moves every qubit of its window across the boundary:
        # 3 half-shard exchanges per window, 9 total — far below the
        # per-gate path's 2k = 36 (audited: swap-in + swap-out per gate)
        assert hist.get("collective-permute", 0) == 9
        assert hist.get("collective-permute", 0) < 2 * k

    def test_final_materialization_is_one_remap(self, env):
        """Rematerializing canonical order from any live permutation is
        ONE batched remap: <= r mixed half-shard exchanges + <= 1 composed
        shard permutation, never per-gate."""
        perm = (3, 4, 5, 0, 1, 2)           # all six qubits displaced
        sigma = dist.canonical_sigma(perm)
        amps = qt.createQureg(N, env).amps

        def f(a):
            return dist.remap_sharded(a, mesh=env.mesh, num_qubits=N,
                                      sigma=sigma)

        hist = _hlo_collectives(jax.jit(f), amps)
        assert set(hist) <= {"collective-permute"}, hist
        assert hist.get("collective-permute", 0) <= 4  # r mixed + 1 composed

    def test_eager_amortization_one_swap_round_for_k_gates(self, env, monkeypatch):
        """The imperative (unfused) path through the lazy permutation:
        k repeated multi-target gates on the same sharded qubits cost ONE
        round of relocation swaps; with lazy remap disabled they cost 2k
        (the reference's per-gate swap-in/swap-out)."""
        rng = np.random.default_rng(21)
        u = oracle.random_unitary(2, rng)
        calls = []
        orig = dist.swap_sharded

        def counting(*a, **kw):
            calls.append(kw["qb_high"])
            return orig(*a, **kw)

        monkeypatch.setattr(dist, "swap_sharded", counting)
        q, vec = _rand_psi(env, rng)
        for _ in range(5):
            qt.multiQubitUnitary(q, [4, 5], u)
        assert len(calls) == 2              # one swap per sharded target, once
        calls.clear()
        dist.use_lazy_remap(False)
        q2, _ = _rand_psi(env, rng)
        for _ in range(5):
            qt.multiQubitUnitary(q2, [4, 5], u)
        assert len(calls) == 2 * 2 * 5      # 2 targets x (in + out) x 5 gates


# ---------------------------------------------------------------------------
# Bit-identity with the eager per-gate path
# ---------------------------------------------------------------------------


def _alternating_circuit(q, u1, u2):
    """Local and sharded targets interleaved; multi-target sharded gates
    force relocation."""
    qt.hadamard(q, 0)
    qt.multiQubitUnitary(q, [4, 5], u2)
    qt.unitary(q, 3, u1)
    qt.hadamard(q, 1)
    qt.multiQubitUnitary(q, [4, 5], u2)
    qt.controlledUnitary(q, 0, 4, u1)
    qt.multiQubitUnitary(q, [3, 4], u2)
    qt.pauliX(q, 5)
    qt.tGate(q, 4)
    qt.swapGate(q, 0, 5)


def _relocation_circuit(q, u2):
    """Multi-target sharded gates + pure-movement/diagonal gates: every
    gate runs the SAME arithmetic kernel (apply_matrix after relocation)
    under both the lazy and the eager swap-back path, so outputs are
    bitwise comparable.  (1q gates on sharded targets are excluded: the
    eager path combines them in the ppermute-exchange kernel while the
    lazy path applies them locally after a remap — mathematically equal,
    1-ulp different.)"""
    qt.hadamard(q, 0)
    qt.multiQubitUnitary(q, [4, 5], u2)
    qt.multiQubitUnitary(q, [4, 5], u2)
    qt.multiQubitUnitary(q, [3, 4], u2)
    qt.pauliX(q, 5)
    qt.tGate(q, 4)
    qt.swapGate(q, 0, 5)
    qt.multiQubitUnitary(q, [3, 5], u2)


class TestBitIdentity:
    def test_lazy_vs_eager_bitwise(self, env):
        rng = np.random.default_rng(31)
        u2 = oracle.random_unitary(2, rng)

        def run():
            q, _ = _rand_psi(env, np.random.default_rng(32))
            _relocation_circuit(q, u2)
            return np.asarray(q.amps)

        lazy = run()
        dist.use_lazy_remap(False)
        eager = run()
        np.testing.assert_array_equal(lazy, eager)

    def test_fused_drain_vs_eager(self, env):
        """The windowed-remap drain vs the eager per-gate swap-back path:
        remaps and relocation swaps are pure data movement, but the window
        planner may localize a gate to different physical slots than the
        per-gate relocalizer, where apply_matrix can take a different
        (mathematically identical) internal branch — equal to ~1 ulp,
        matching the pre-existing fused-vs-eager contract
        (test_fusion.test_sharded_drain_matches_eager)."""
        rng = np.random.default_rng(33)
        u2 = oracle.random_unitary(2, rng)

        def run(use_fusion):
            q, _ = _rand_psi(env, np.random.default_rng(34))
            if use_fusion:
                with qt.gateFusion(q):
                    _relocation_circuit(q, u2)
            else:
                _relocation_circuit(q, u2)
            return np.asarray(q.amps)

        fused_out = run(True)
        dist.use_lazy_remap(False)
        eager = run(False)
        np.testing.assert_allclose(fused_out, eager, atol=1e-14)

    def test_mixed_circuit_lazy_vs_eager(self, env):
        """Circuits mixing 1q sharded-target gates select different (but
        mathematically identical) kernels per path — equal to ~1 ulp."""
        rng = np.random.default_rng(37)
        u1 = oracle.random_unitary(1, rng)
        u2 = oracle.random_unitary(2, rng)

        def run():
            q, _ = _rand_psi(env, np.random.default_rng(38))
            _alternating_circuit(q, u1, u2)
            return np.asarray(q.amps)

        lazy = run()
        dist.use_lazy_remap(False)
        eager = run()
        np.testing.assert_allclose(lazy, eager, atol=1e-14)

    def test_lazy_vs_oracle(self, env):
        rng = np.random.default_rng(35)
        u1 = oracle.random_unitary(1, rng)
        u2 = oracle.random_unitary(2, rng)
        q, vec = _rand_psi(env, rng)
        _alternating_circuit(q, u1, u2)
        SW = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                       [0, 1, 0, 0], [0, 0, 0, 1]])
        T = np.diag([1, np.exp(1j * np.pi / 4)])
        e = oracle.apply_to_statevec(vec, N, [0], oracle.H)
        e = oracle.apply_to_statevec(e, N, [4, 5], u2)
        e = oracle.apply_to_statevec(e, N, [3], u1)
        e = oracle.apply_to_statevec(e, N, [1], oracle.H)
        e = oracle.apply_to_statevec(e, N, [4, 5], u2)
        e = oracle.apply_to_statevec(e, N, [4], u1, controls=[0])
        e = oracle.apply_to_statevec(e, N, [3, 4], u2)
        e = oracle.apply_to_statevec(e, N, [5], oracle.X)
        e = oracle.apply_to_statevec(e, N, [4], T)
        e = oracle.apply_to_statevec(e, N, [0, 5], SW)
        np.testing.assert_allclose(oracle.state_from_qureg(q), e, atol=ATOL)

    def test_density_twin_through_lazy_path(self, env):
        n = 4
        rng = np.random.default_rng(36)
        mat = oracle.random_density(n, rng)
        r = qt.createDensityQureg(n, env)
        oracle.set_qureg_from_array(qt, r, mat)
        u = oracle.random_unitary(2, rng)
        qt.multiQubitUnitary(r, [2, 3], u)   # bra bits 6, 7 sharded
        assert r._perm is not None
        U = oracle.full_operator(n, [2, 3], u)
        np.testing.assert_allclose(oracle.state_from_qureg(r),
                                   U @ mat @ U.conj().T, atol=1e-10)
        assert abs(qt.calcTotalProb(r) - 1.0) < 1e-10


# ---------------------------------------------------------------------------
# Reads rematerialize canonical order (interleaved mid-circuit)
# ---------------------------------------------------------------------------


class TestReadsMaterializeCanonical:
    def _permuted_state(self, env, rng):
        u2 = oracle.random_unitary(2, rng)
        q, vec = _rand_psi(env, rng)
        qt.multiQubitUnitary(q, [4, 5], u2)
        assert q._perm is not None          # laziness actually engaged
        return q, oracle.apply_to_statevec(vec, N, [4, 5], u2)

    def test_calc_prob_of_outcome_mid_circuit(self, env):
        rng = np.random.default_rng(41)
        q, expect = self._permuted_state(env, rng)
        p = np.abs(expect) ** 2
        idx = np.arange(1 << N)
        for t in (0, 4):
            want0 = p[(idx >> t) & 1 == 0].sum()
            assert abs(qt.calcProbOfOutcome(q, t, 0) - want0) < 1e-10
        # ... and the circuit continues correctly after the read
        qt.hadamard(q, 5)
        expect = oracle.apply_to_statevec(expect, N, [5], oracle.H)
        np.testing.assert_allclose(oracle.state_from_qureg(q), expect,
                                   atol=ATOL)

    def test_get_amp_and_total_prob(self, env):
        rng = np.random.default_rng(42)
        q, expect = self._permuted_state(env, rng)
        a = qt.getAmp(q, 5)
        assert abs(a - expect[5]) < 1e-12
        assert abs(qt.calcTotalProb(q) - 1.0) < 1e-12

    def test_measurement_with_live_perm(self, env):
        rng = np.random.default_rng(43)
        q, expect = self._permuted_state(env, rng)
        prob = qt.collapseToOutcome(q, 4, 0)
        idx = np.arange(1 << N)
        mask = ((idx >> 4) & 1) == 0
        want = (np.abs(expect) ** 2)[mask].sum()
        assert abs(prob - want) < 1e-10
        coll = expect * mask / np.sqrt(want)
        np.testing.assert_allclose(oracle.state_from_qureg(q), coll,
                                   atol=1e-10)

    def test_checkpoint_write_is_canonical(self, env, tmp_path):
        rng = np.random.default_rng(44)
        q, expect = self._permuted_state(env, rng)
        path = str(tmp_path / "state.csv")
        qt.writeStateToFile(q, path)
        q2 = qt.createQureg(N, env)
        assert qt.readStateFromFile(q2, path)
        np.testing.assert_allclose(oracle.state_from_qureg(q2), expect,
                                   atol=1e-12)

    def test_host_gather_is_canonical(self, env):
        rng = np.random.default_rng(45)
        q, expect = self._permuted_state(env, rng)
        raw = np.asarray(q.amps)            # the host-gather read
        np.testing.assert_allclose(raw[0] + 1j * raw[1], expect,
                                   atol=ATOL)
        assert q._perm is None

    def test_read_inside_fusion_context(self, env):
        rng = np.random.default_rng(46)
        q, vec = _rand_psi(env, rng)
        e = vec
        with qt.gateFusion(q):
            for t in (3, 4, 5, 0):
                qt.hadamard(q, t)
                e = oracle.apply_to_statevec(e, N, [t], oracle.H)
            p0 = qt.calcProbOfOutcome(q, 5, 0)   # drains + materializes
            idx = np.arange(1 << N)
            want = (np.abs(e) ** 2)[((idx >> 5) & 1) == 0].sum()
            assert abs(p0 - want) < 1e-10
            for t in (1, 5):
                qt.hadamard(q, t)
                e = oracle.apply_to_statevec(e, N, [t], oracle.H)
        np.testing.assert_allclose(oracle.state_from_qureg(q), e, atol=ATOL)
