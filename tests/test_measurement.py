"""Fused device-side measurement (ops/measurement.py): one compiled
prob -> threshold -> collapse program per shot, key-seeded determinism,
statistical correctness (chi^2), stream equality between the sequence
program and a loop of single shots, and the host-MT parity path.

Reference semantics: statevec_measureWithStats
(QuEST_common.c:374-380), generateMeasurementOutcome (:168-183),
densmatr_collapseToKnownProbOutcome (QuEST_cpu.c:785-860)."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.ops import measurement as M
import oracle

NQ = 5


def _ry(theta):
    c, s = np.cos(theta / 2), np.sin(theta / 2)
    return np.array([[c, -s], [s, c]])


def test_seeded_outcomes_deterministic(env):
    runs = []
    for _ in range(2):
        qt.seedQuEST(env, [424242])
        q = qt.createQureg(NQ, env)
        for t in range(NQ):
            qt.hadamard(q, t)
        runs.append([qt.measure(q, t) for t in range(NQ)])
    assert runs[0] == runs[1]


def test_measure_collapses_statevector(env):
    qt.seedQuEST(env, [7])
    rng = np.random.default_rng(5)
    vec = oracle.random_state(NQ, rng)
    q = qt.createQureg(NQ, env)
    oracle.set_qureg_from_array(qt, q, vec)
    outcome, prob = qt.measureWithStats(q, 2)
    # analytic projection for whichever outcome occurred
    idx = (np.arange(1 << NQ) >> 2) & 1
    keep = vec * (idx == outcome)
    p_ref = float(np.sum(np.abs(keep) ** 2))
    assert abs(prob - p_ref) < 1e-10
    np.testing.assert_allclose(oracle.state_from_qureg(q),
                               keep / np.sqrt(p_ref), atol=1e-10)


def test_measure_collapses_density_matrix(env):
    qt.seedQuEST(env, [8])
    rng = np.random.default_rng(6)
    mat = oracle.random_density(NQ, rng)
    r = qt.createDensityQureg(NQ, env)
    oracle.set_qureg_from_array(qt, r, mat)
    outcome, prob = qt.measureWithStats(r, 1)
    idx = (np.arange(1 << NQ) >> 1) & 1
    proj = np.diag((idx == outcome).astype(float))
    ref = proj @ mat @ proj
    p_ref = float(np.real(np.trace(ref)))
    assert abs(prob - p_ref) < 1e-10
    np.testing.assert_allclose(oracle.state_from_qureg(r), ref / p_ref,
                               atol=1e-10)
    assert abs(qt.calcTotalProb(r) - 1.0) < 1e-10


def test_degenerate_probabilities_short_circuit(env):
    qt.seedQuEST(env, [9])
    q = qt.createQureg(NQ, env)  # |00000>
    assert qt.measure(q, 3) == 0
    qt.pauliX(q, 3)
    o, p = qt.measureWithStats(q, 3)
    assert o == 1 and abs(p - 1.0) < 1e-12


def test_sequence_program_matches_single_shot_stream(env):
    """measure_sequence consumes the same shot indices as a loop of
    measure() calls, so the outcome streams are identical."""
    rng = np.random.default_rng(11)
    vec = oracle.random_state(NQ, rng)

    qt.seedQuEST(env, [31337])
    q = qt.createQureg(NQ, env)
    oracle.set_qureg_from_array(qt, q, vec)
    singles = [qt.measure(q, t) for t in range(NQ)]
    after_singles = oracle.state_from_qureg(q)

    qt.seedQuEST(env, [31337])
    q2 = qt.createQureg(NQ, env)
    oracle.set_qureg_from_array(qt, q2, vec)
    key, shot = M.KEYS.next_shots(NQ)
    amps, outs, probs = M.measure_sequence(
        q2.amps, key, shot, num_qubits=NQ, targets=tuple(range(NQ)),
        is_density=False)
    q2.amps = amps
    assert list(np.asarray(outs)) == singles
    np.testing.assert_allclose(oracle.state_from_qureg(q2), after_singles,
                               atol=1e-10)


def test_chi_square_outcome_distribution(env):
    """Bernoulli statistics: a product state of qubits rotated to
    p(0) = cos^2(theta/2) measured via the sequence program.  Each qubit
    of a product state measures independently, so n_qubits outcomes per
    preparation are i.i.d. samples.  chi^2 over 2 cells with 600 samples;
    threshold 10.83 = p < 0.001 (1 dof)."""
    theta = 1.2
    p0 = float(np.cos(theta / 2) ** 2)
    n = 12
    shots = 50
    qt.seedQuEST(env, [20260731])
    counts = [0, 0]
    u = _ry(theta)
    for _ in range(shots):
        q = qt.createQureg(n, env)
        for t in range(n):
            qt.unitary(q, t, u)
        key, shot = M.KEYS.next_shots(n)
        _, outs, _ = M.measure_sequence(
            q.amps, key, shot, num_qubits=n, targets=tuple(range(n)),
            is_density=False)
        for o in np.asarray(outs):
            counts[int(o)] += 1
    total = sum(counts)
    exp0 = total * p0
    exp1 = total * (1 - p0)
    chi2 = (counts[0] - exp0) ** 2 / exp0 + (counts[1] - exp1) ** 2 / exp1
    assert chi2 < 10.83, (counts, p0)


def test_host_mt_parity_path(env, monkeypatch):
    """QT_HOST_MEASURE=1 routes through the reference's host
    calcProb -> MT draw -> collapse sequence (strict stream parity)."""
    monkeypatch.setenv("QT_HOST_MEASURE", "1")
    qt.seedQuEST(env, [55])
    from quest_tpu.rng import GLOBAL_RNG
    # snapshot the MT stream: the host path must consume exactly one draw
    state_before = GLOBAL_RNG._rng.get_state()[1].copy()
    q = qt.createQureg(NQ, env)
    qt.hadamard(q, 0)
    o = qt.measure(q, 0)
    assert o in (0, 1)
    state_after = GLOBAL_RNG._rng.get_state()[1].copy()
    assert not np.array_equal(state_before, state_after)
    assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10


def test_collapse_to_outcome_still_exact(env):
    rng = np.random.default_rng(21)
    vec = oracle.random_state(NQ, rng)
    q = qt.createQureg(NQ, env)
    oracle.set_qureg_from_array(qt, q, vec)
    p = qt.collapseToOutcome(q, 0, 1)
    idx = np.arange(1 << NQ) & 1
    keep = vec * (idx == 1)
    p_ref = float(np.sum(np.abs(keep) ** 2))
    assert abs(p - p_ref) < 1e-10
    np.testing.assert_allclose(oracle.state_from_qureg(q),
                               keep / np.sqrt(p_ref), atol=1e-10)


def test_measure_sequence_public_api(env):
    """measureSequence = one-dispatch batched measurement matching the
    per-call stream, including QASM records and density registers."""
    qt.seedQuEST(env, [777])
    q = qt.createQureg(NQ, env)
    for t in range(NQ):
        qt.hadamard(q, t)
    qt.startRecordingQASM(q)
    outs, probs = qt.measureSequence(q, range(NQ))
    qt.stopRecordingQASM(q)
    assert len(outs) == NQ and all(o in (0, 1) for o in outs)
    assert all(abs(p - 0.5) < 1e-9 for p in probs)
    assert str(q.qasm_log).count("measure") >= NQ
    # density register
    r = qt.createDensityQureg(3, env)
    qt.initPlusState(r)
    outs2, probs2 = qt.measureSequence(r, [0, 1, 2])
    assert len(outs2) == 3
    assert abs(qt.calcTotalProb(r) - 1.0) < 1e-10


def test_measure_sequence_matches_measure_loop(env):
    qt.seedQuEST(env, [888])
    q1 = qt.createQureg(4, env)
    for t in range(4):
        qt.hadamard(q1, t)
    loop = [qt.measure(q1, t) for t in range(4)]
    qt.seedQuEST(env, [888])
    q2 = qt.createQureg(4, env)
    for t in range(4):
        qt.hadamard(q2, t)
    seq, _ = qt.measureSequence(q2, range(4))
    assert seq == loop


def test_measure_sequence_empty(env):
    q = qt.createQureg(3, env)
    assert qt.measureSequence(q, []) == ([], [])
