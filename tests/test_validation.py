"""Input-validation tests asserting the reference's error-message text.

Mirrors the reference suite's SECTION("input validation") discipline:
every REQUIRE_THROWS_WITH(..., Contains("...")) asserts a substring of the
message table (QuEST_validation.c:119-197), which quest_tpu reproduces
verbatim (quest_tpu/validation.py ERROR_MESSAGES).
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import validation as V

N = 5


def expect(msg_substr):
    return pytest.raises(qt.QuESTError, match=msg_substr)


@pytest.fixture
def q(env):
    return qt.createQureg(N, env)


@pytest.fixture
def rho(env):
    return qt.createDensityQureg(N, env)


# ---------------------------------------------------------------------------
# register creation / indexing
# ---------------------------------------------------------------------------


class TestCreation:
    def test_create_qureg(self, env):
        with expect("Invalid number of qubits. Must create >0."):
            qt.createQureg(0, env)
        with expect("Invalid number of qubits. Must create >0."):
            qt.createDensityQureg(-1, env)

    def test_num_ranks(self):
        with expect("power-of-2 number of node"):
            V.validate_num_ranks(3)

    def test_distrib_too_small(self):
        # the reference rejects; quest_tpu replicates and warns with the
        # reference's message text (validation.py docstring)
        with pytest.warns(UserWarning, match="at least one amplitude per node"):
            V.validate_num_qubits(1, "createQureg", num_ranks=4)

    def test_amp_index(self, q):
        with expect("Invalid amplitude index."):
            qt.getAmp(q, 1 << N)
        with expect("Invalid amplitude index."):
            qt.getAmp(q, -1)

    def test_num_amps(self, q):
        with expect("Invalid number of amplitudes."):
            qt.setAmps(q, 0, np.zeros(40), np.zeros(40), 40)
        with expect("More amplitudes given than exist in the statevector"):
            qt.setAmps(q, 30, np.zeros(4), np.zeros(4), 4)


# ---------------------------------------------------------------------------
# qubit-index / control-target validation
# ---------------------------------------------------------------------------


class TestQubitIndices:
    def test_target(self, q):
        for bad in (-1, N):
            with expect("Invalid target qubit."):
                qt.pauliX(q, bad)

    def test_control(self, q):
        with expect("Invalid control qubit."):
            qt.controlledNot(q, N, 0)

    def test_target_is_control(self, q):
        with expect("Control qubit cannot equal target qubit."):
            qt.controlledPhaseShift(q, 1, 1, 0.3)

    def test_target_in_controls(self, q):
        with expect("Control qubits cannot include target qubit."):
            qt.multiControlledUnitary(q, [0, 2], 2, np.eye(2))

    def test_control_target_collision(self, q):
        with expect("Control and target qubits must be disjoint."):
            qt.multiControlledMultiQubitUnitary(q, [0], [0, 1], np.eye(4))

    def test_targets_not_unique(self, q):
        with expect("The target qubits must be unique."):
            qt.multiQubitNot(q, [1, 1])

    def test_controls_not_unique(self, q):
        with expect("The control qubits should be unique."):
            qt.multiControlledUnitary(q, [1, 1], 2, np.eye(2))

    def test_qubits_not_unique(self, q):
        with expect("The qubits must be unique."):
            qt.multiControlledPhaseFlip(q, [1, 1])

    def test_num_targets(self, q):
        with expect("Invalid number of target qubits."):
            qt.multiQubitUnitary(q, [], np.eye(1))

    def test_num_controls(self, q):
        with expect("Invalid number of control qubits."):
            qt.multiControlledUnitary(q, list(range(N)), 0, np.eye(2))

    def test_control_bit_states(self, q):
        with expect("must be a bit sequence"):
            qt.multiStateControlledUnitary(q, [1, 2], [0, 2], 0, np.eye(2))


# ---------------------------------------------------------------------------
# matrices
# ---------------------------------------------------------------------------


class TestMatrices:
    def test_non_unitary(self, q):
        with expect("Matrix is not unitary."):
            qt.unitary(q, 0, np.array([[1, 0], [0, 2]]))
        with expect("Matrix is not unitary."):
            qt.twoQubitUnitary(q, 0, 1, np.eye(4) * 1.5)

    def test_non_unitary_complex_pair(self, q):
        with expect("Compact matrix formed by given complex numbers is not unitary."):
            qt.compactUnitary(q, 0, 0.9, 0.9)

    def test_unitary_size(self, q):
        with expect("The matrix size does not match the number of target qubits."):
            qt.applyMatrix2(q, 0, np.eye(4))
        with expect("The matrix size does not match the number of target qubits."):
            qt.multiQubitUnitary(q, [0, 1], np.eye(8))

    def test_zero_axis_vector(self, q):
        with expect("Invalid axis vector. Must be non-zero."):
            qt.rotateAroundAxis(q, 0, 0.5, (0.0, 0.0, 0.0))


# ---------------------------------------------------------------------------
# register kinds, outcomes, probabilities
# ---------------------------------------------------------------------------


class TestKindsAndProbs:
    def test_statevec_only(self, rho):
        with expect("Operation valid only for state-vectors."):
            qt.initStateFromAmps(rho, np.zeros(1 << N), np.zeros(1 << N))

    def test_densmatr_only(self, q):
        with expect("Operation valid only for density matrices."):
            qt.mixDephasing(q, 0, 0.1)
        with expect("Operation valid only for density matrices."):
            qt.calcPurity(q)

    def test_outcome(self, q):
        with expect("Invalid measurement outcome -- must be either 0 or 1."):
            qt.calcProbOfOutcome(q, 0, 2)

    def test_collapse_zero_prob(self, q):
        qt.initClassicalState(q, 0)   # P(q0 = 1) = 0
        with expect("Can't collapse to state with zero probability."):
            qt.collapseToOutcome(q, 0, 1)

    def test_mismatching_dims(self, env, q):
        other = qt.createQureg(N + 1, env)
        with expect("Dimensions of the qubit registers don't match."):
            qt.cloneQureg(other, q)

    def test_mismatching_types(self, env, q, rho):
        with expect("Registers must both be state-vectors or both be density matrices."):
            qt.cloneQureg(rho, q)

    def test_second_arg_statevec(self, env, rho):
        rho2 = qt.createDensityQureg(N, env)
        with expect("Second argument must be a state-vector."):
            qt.calcFidelity(rho, rho2)

    def test_prob_range(self, rho):
        with expect(r"Probabilities must be in \[0, 1\]."):
            qt.mixDamping(rho, 0, 1.2)

    def test_decoherence_caps(self, rho):
        with expect("single qubit dephase error cannot exceed 1/2"):
            qt.mixDephasing(rho, 0, 0.6)
        with expect("two-qubit qubit dephase error cannot exceed 3/4"):
            qt.mixTwoQubitDephasing(rho, 0, 1, 0.8)
        with expect("single qubit depolarising error cannot exceed 3/4"):
            qt.mixDepolarising(rho, 0, 0.8)
        with expect("two-qubit depolarising error cannot exceed 15/16"):
            qt.mixTwoQubitDepolarising(rho, 0, 1, 0.95)
        with expect("cannot exceed the probability of no error"):
            qt.mixPauli(rho, 0, 0.3, 0.3, 0.3)


# ---------------------------------------------------------------------------
# Pauli / Kraus / Hamiltonians / Trotter / DiagonalOp
# ---------------------------------------------------------------------------


class TestOperators:
    def test_pauli_code(self, q, env):
        workspace = qt.createQureg(N, env)
        with expect("Invalid Pauli code."):
            qt.calcExpecPauliProd(q, [0], [7], workspace)

    def test_kraus_counts(self, rho):
        with expect("At least 1 and at most 4 single qubit Kraus operators"):
            qt.mixKrausMap(rho, 0, [np.eye(2)] * 5)
        with expect("At least 1 and at most 16 two-qubit Kraus operators"):
            qt.mixTwoQubitKrausMap(rho, 0, 1, [np.eye(4)] * 17)

    def test_kraus_cptp(self, rho):
        with expect("not a completely positive, trace preserving"):
            qt.mixKrausMap(rho, 0, [np.eye(2) * 2])

    def test_kraus_dims(self, rho):
        with expect("Every Kraus operator must be of the same number of qubits"):
            qt.mixKrausMap(rho, 0, [np.eye(4)])

    def test_hamil_params(self, env):
        with expect("The number of qubits and terms in the PauliHamil must be strictly positive."):
            qt.createPauliHamil(0, 3)

    def test_hamil_dims(self, q):
        hamil = qt.createPauliHamil(N + 1, 1)
        with expect("The PauliHamil must act on the same number of qubits"):
            qt.applyPauliHamil(q, hamil, qt.createQureg(N, q.env))

    def test_trotter(self, q):
        hamil = qt.createPauliHamil(N, 1)
        with expect("The Trotterisation order must be 1, or an even number"):
            qt.applyTrotterCircuit(q, hamil, 0.1, 3, 1)
        with expect("The number of Trotter repetitions must be >=1."):
            qt.applyTrotterCircuit(q, hamil, 0.1, 2, 0)

    def test_diag_op_size(self, q, env):
        op = qt.createDiagonalOp(N + 1, env)
        with expect("equal number of qubits as that in the applied diagonal"):
            qt.applyDiagonalOp(q, op)

    def test_diag_hamil_not_diagonal(self, env):
        op = qt.createDiagonalOp(3, env)
        hamil = qt.createPauliHamil(3, 1)
        qt.initPauliHamil(hamil, [0.5], [[1, 0, 0]])   # an X term
        with expect("contained operators other than PAULI_Z and PAULI_I"):
            qt.initDiagonalOpFromPauliHamil(op, hamil)

    def test_num_sum_terms(self, q, env):
        workspace = qt.createQureg(N, env)
        with expect("Invalid number of terms in the Pauli sum."):
            qt.calcExpecPauliSum(q, [], [], workspace)


# ---------------------------------------------------------------------------
# phase functions
# ---------------------------------------------------------------------------


class TestPhaseFuncs:
    def test_bit_encoding(self, q):
        with expect("Invalid bit encoding."):
            qt.applyPhaseFunc(q, [0, 1], 5, [1.0], [1.0])

    def test_twos_complement_single_qubit(self, q):
        with expect("too few qubits to employ TWOS_COMPLEMENT"):
            qt.applyPhaseFunc(q, [0], qt.TWOS_COMPLEMENT, [1.0], [1.0])

    def test_num_subregisters(self, q):
        with expect("Invalid number of qubit subregisters"):
            qt.applyNamedPhaseFunc(q, [], [], qt.UNSIGNED, qt.NORM)

    def test_phase_func_name_params(self, q):
        with expect("Invalid number of parameters passed"):
            qt.applyParamNamedPhaseFunc(
                q, [0, 1], [1, 1], qt.UNSIGNED, qt.NORM, [1.0])

    def test_distance_needs_even_regs(self, q):
        with expect("require a strictly even number of sub-registers"):
            qt.applyNamedPhaseFunc(q, [0], [1], qt.UNSIGNED, qt.DISTANCE)

    def test_negative_exponent_needs_zero_override(self, q):
        with expect("negative exponent which would diverge at zero"):
            qt.applyPhaseFunc(q, [0, 1], qt.UNSIGNED, [1.0], [-1.0])

    def test_fractional_exponent_twos_complement(self, q):
        with expect("fractional exponent"):
            qt.applyPhaseFunc(q, [0, 1], qt.TWOS_COMPLEMENT, [1.0], [0.5])

    def test_override_index_unsigned(self, q):
        with expect("Invalid phase function override index, in the UNSIGNED encoding."):
            qt.applyPhaseFuncOverrides(
                q, [0, 1], qt.UNSIGNED, [1.0], [1.0], [4], [0.0])

    def test_override_index_twos_complement(self, q):
        with expect("in the TWOS_COMPLEMENT encoding."):
            qt.applyPhaseFuncOverrides(
                q, [0, 1], qt.TWOS_COMPLEMENT, [1.0], [1.0], [2], [0.0])

    def test_multi_var_negative_exponent(self, q):
        with expect("illegal negative exponent"):
            qt.applyMultiVarPhaseFunc(
                q, [0, 1], [1, 1], qt.UNSIGNED, [1.0, 1.0], [-1.0, 1.0],
                [1, 1])


class TestFiniteness:
    """ISSUE 2 satellite: NaN/Inf in user-supplied payloads is rejected
    up front (validation.validate_finite) — the reference never checks
    and a single NaN silently poisons the whole register."""

    MSG = "must be finite"

    def test_unitary_matrix_nan(self, q):
        m = np.eye(2, dtype=complex)
        m[0, 0] = np.nan
        with expect(self.MSG):
            qt.unitary(q, 0, m)

    def test_apply_matrix_n_inf(self, q):
        m = np.eye(4, dtype=complex)
        m[1, 2] = np.inf
        with expect(self.MSG):
            qt.applyMatrixN(q, [0, 1], m)

    def test_apply_matrix2_nan(self, q):
        with expect(self.MSG):
            qt.applyMatrix2(q, 0, np.array([[np.nan, 0], [0, 1]]))

    def test_set_amps_nan(self, q):
        with expect(self.MSG):
            qt.setAmps(q, 0, [np.nan, 0.0], [0.0, 0.0], 2)

    def test_set_amps_imag_inf(self, q):
        with expect(self.MSG):
            qt.setAmps(q, 0, [0.0, 0.0], [0.0, -np.inf], 2)

    def test_init_state_from_amps_nan(self, q):
        re = np.zeros(1 << N)
        re[3] = np.nan
        with expect(self.MSG):
            qt.initStateFromAmps(q, re, np.zeros(1 << N))

    def test_set_density_amps_nan(self, rho):
        d = 1 << (2 * N)
        re = np.zeros(d)
        re[0] = np.inf
        with expect(self.MSG):
            qt.setDensityAmps(rho, re, np.zeros(d))

    def test_init_diagonal_op_nan(self, env):
        op = qt.createDiagonalOp(3, env)
        with expect(self.MSG):
            qt.initDiagonalOp(op, [np.nan] * 8, [0.0] * 8)

    def test_set_diagonal_op_elems_inf(self, env):
        op = qt.createDiagonalOp(3, env)
        with expect(self.MSG):
            qt.setDiagonalOpElems(op, 0, [np.inf], [0.0], 1)

    def test_finite_inputs_pass(self, q, env):
        qt.unitary(q, 0, np.eye(2))
        qt.setAmps(q, 0, [0.5, 0.5], [0.0, 0.0], 2)
        op = qt.createDiagonalOp(3, env)
        qt.initDiagonalOp(op, [1.0] * 8, [0.0] * 8)

    def test_traced_values_skipped(self):
        """validate_finite must not materialize tracers (jitted callers)."""
        import jax

        def f(x):
            V.validate_finite(x, "jitfn")
            return x

        jax.jit(f)(np.ones(4))  # must not raise


def test_strict_parity_mode_escalates_warn_codes(env, monkeypatch):
    """QT_STRICT_VALIDATION=1 turns the two deliberately-warn-only codes
    into QuESTError, matching reference REQUIRE_THROWS_WITH suites."""
    import os
    import pytest as _pytest

    monkeypatch.setenv("QT_STRICT_VALIDATION", "1")
    from quest_tpu import validation as V
    with _pytest.raises(V.QuESTError, match="at least one amplitude per node"):
        V._warn_replicated("E_DISTRIB_QUREG_TOO_SMALL", "createQureg")
    with _pytest.raises(V.QuESTError, match="targets too many qubits"):
        V._warn("E_CANNOT_FIT_MULTI_QUBIT_MATRIX", "multiQubitUnitary")
