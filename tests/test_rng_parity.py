"""Documents the host measurement RNG's relationship to the reference's
mt19937ar (VERDICT r5 item b).

The README once claimed `QT_HOST_MEASURE=1` gives bitwise outcome-stream
parity with a seeded reference run.  It does not, and these tests pin
exactly why, against a minimal faithful mt19937ar implementation:

1. SEEDING diverges: `rng.py` seeds ``np.random.MT19937(key_array)``,
   which feeds the keys through numpy's SeedSequence hash — not the
   reference's ``init_by_array`` (seedQuEST -> init_by_array,
   QuEST_common.c:195-217) — so the same seeds produce a different
   624-word generator state.
2. The UNIFORM DRAW diverges: each host outcome consumes numpy's
   ``random_sample`` — the two-output 53-bit ``genrand_res53``
   construction — while the reference's generateMeasurementOutcome
   (QuEST_common.c:168-183) draws ONE 32-bit output via
   ``genrand_real1``.  Different value AND a different state advance per
   draw, even from an identical generator state.

What IS guaranteed (and pinned here): seeded host measurement streams
are bit-reproducible against themselves.
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import rng as qt_rng


class MT19937ar:
    """Minimal faithful port of the reference's mt19937ar.c (init_by_array
    seeding, genrand_int32 tempering, genrand_real1 / genrand_res53)."""

    def __init__(self):
        self.mt = [0] * 624
        self.mti = 625

    def init_genrand(self, s):
        self.mt[0] = s & 0xFFFFFFFF
        for i in range(1, 624):
            self.mt[i] = (1812433253
                          * (self.mt[i - 1] ^ (self.mt[i - 1] >> 30))
                          + i) & 0xFFFFFFFF
        self.mti = 624

    def init_by_array(self, key):
        self.init_genrand(19650218)
        i, j = 1, 0
        for _ in range(max(624, len(key))):
            self.mt[i] = ((self.mt[i]
                           ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30))
                              * 1664525))
                          + key[j] + j) & 0xFFFFFFFF
            i += 1
            j += 1
            if i >= 624:
                self.mt[0] = self.mt[623]
                i = 1
            if j >= len(key):
                j = 0
        for _ in range(623):
            self.mt[i] = ((self.mt[i]
                           ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30))
                              * 1566083941))
                          - i) & 0xFFFFFFFF
            i += 1
            if i >= 624:
                self.mt[0] = self.mt[623]
                i = 1
        self.mt[0] = 0x80000000

    def genrand_int32(self):
        if self.mti >= 624:
            for k in range(624):
                y = ((self.mt[k] & 0x80000000)
                     | (self.mt[(k + 1) % 624] & 0x7FFFFFFF))
                v = y >> 1
                if y & 1:
                    v ^= 0x9908B0DF
                self.mt[k] = self.mt[(k + 397) % 624] ^ v
            self.mti = 0
        y = self.mt[self.mti]
        self.mti += 1
        y ^= y >> 11
        y ^= (y << 7) & 0x9D2C5680
        y ^= (y << 15) & 0xEFC60000
        y ^= y >> 18
        return y & 0xFFFFFFFF

    def genrand_real1(self):
        # the reference's generateMeasurementOutcome draw
        return self.genrand_int32() * (1.0 / 4294967295.0)

    def genrand_res53(self):
        a = self.genrand_int32() >> 5
        b = self.genrand_int32() >> 6
        return (a * 67108864.0 + b) * (1.0 / 9007199254740992.0)


SEEDS = [1234, 5678]


def _state_key(rs: np.random.RandomState) -> np.ndarray:
    return rs.get_state()[1]


class TestSeedingDivergence:
    def test_numpy_key_array_seeding_is_not_init_by_array(self):
        """The generator STATE after quest_tpu's seeding differs from the
        reference's init_by_array over the same keys — numpy hashes the
        key array through SeedSequence instead."""
        ref = MT19937ar()
        ref.init_by_array(SEEDS)
        ours = np.random.RandomState(
            np.random.MT19937(np.array(SEEDS, dtype=np.uint32)))
        assert not np.array_equal(
            _state_key(ours), np.array(ref.mt, dtype=np.uint32))

    def test_first_host_draw_differs_from_reference(self):
        """End to end: seedQuEST's host stream does not reproduce the
        reference's first seeded measurement draw."""
        ref = MT19937ar()
        ref.init_by_array(SEEDS)
        qt_rng.GLOBAL_RNG.seed(SEEDS)
        assert qt_rng.GLOBAL_RNG.uniform() != ref.genrand_real1()


class TestDrawDivergence:
    def _numpy_from_ref_state(self, ref: MT19937ar) -> np.random.RandomState:
        rs = np.random.RandomState(np.random.MT19937(0))
        rs.set_state(("MT19937", np.array(ref.mt, dtype=np.uint32),
                      ref.mti, 0, 0.0))
        return rs

    def test_random_sample_is_genrand_res53(self):
        """From an IDENTICAL generator state, numpy's random_sample is
        bitwise mt19937ar's genrand_res53 (two 32-bit outputs per
        draw)..."""
        ref = MT19937ar()
        ref.init_by_array(SEEDS)
        rs = self._numpy_from_ref_state(ref)
        for _ in range(8):
            assert rs.random_sample() == ref.genrand_res53()

    def test_random_sample_is_not_genrand_real1(self):
        """...and genrand_res53 is NOT genrand_real1, the single-output
        draw the reference's generateMeasurementOutcome uses — so even
        a hypothetical init_by_array-seeded host stream would diverge on
        the first draw."""
        ref = MT19937ar()
        ref.init_by_array(SEEDS)
        rs = self._numpy_from_ref_state(ref)
        assert rs.random_sample() != ref.genrand_real1()


class TestSelfReproducibility:
    def test_host_measurement_stream_reproducible(self, env, monkeypatch):
        """The guarantee the docs DO make: same seeds -> same host
        measurement outcome stream."""
        monkeypatch.setenv("QT_HOST_MEASURE", "1")

        def stream():
            qt.seedQuEST(env, [11, 22])
            q = qt.createQureg(3, env)
            outs = []
            for _ in range(12):
                qt.hadamard(q, 0)
                outs.append(qt.measure(q, 0))
            qt.destroyQureg(q, env)
            return outs

        assert stream() == stream()

    def test_uniform_matches_numpy_stream(self):
        """The host draw is exactly numpy's random_sample over the seeded
        RandomState — the anchor for the divergence statements above."""
        qt_rng.GLOBAL_RNG.seed(SEEDS)
        mirror = np.random.RandomState(
            np.random.MT19937(np.array(SEEDS, dtype=np.uint32)))
        draws = [qt_rng.GLOBAL_RNG.uniform() for _ in range(8)]
        assert draws == list(mirror.random_sample(8))


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
