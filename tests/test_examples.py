"""Smoke-run the examples/ programs (the reference ships and documents its
demos as part of the library surface, examples/README.md)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, env_extra=None):
    env = dict(os.environ)
    env["QT_EXAMPLES_CPU"] = "1"
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=600, env=env,
    )


def test_tutorial():
    r = _run("tutorial_example.py")
    assert r.returncode == 0, r.stderr
    assert "Probability amplitude of |111>" in r.stdout


def test_bernstein_vazirani():
    r = _run("bernstein_vazirani.py")
    assert r.returncode == 0, r.stderr
    assert "recovered = 17" in r.stdout


@pytest.mark.parametrize("mode", [[], ["--fused"]])
def test_grover(mode):
    r = _run("grovers_search.py", *mode, env_extra={"QT_GROVER_QUBITS": "7"})
    assert r.returncode == 0, r.stderr
    assert "prob of solution" in r.stdout


def test_vqe_train():
    r = _run("vqe_train.py", env_extra={"QT_VQE_QUBITS": "6"})
    assert r.returncode == 0, r.stderr
    assert "done; final energy" in r.stdout


def test_trotter_evolution():
    r = _run("trotter_evolution.py", env_extra={"QT_EVOLVE_QUBITS": "8",
                                                "QT_EVOLVE_STEPS": "10"})
    assert r.returncode == 0, r.stderr
    assert "energy drift" in r.stdout and "OK" in r.stdout


def test_qaoa_maxcut():
    r = _run("qaoa_maxcut.py", env_extra={"QT_QAOA_QUBITS": "6"})
    assert r.returncode == 0, r.stderr
    assert "expected cut" in r.stdout


@pytest.mark.parametrize("mode", [[], ["--fused"]])
def test_phase_estimation(mode):
    # phi = 11/64 is exactly representable with 6 counting qubits, so the
    # measured estimate is deterministic
    r = _run("phase_estimation.py", *mode,
             env_extra={"QPE_QUBITS": "6", "QPE_PHI": "0.171875"})
    assert r.returncode == 0, r.stderr
    assert "estimate" in r.stdout
    assert "|error| = 0.0" in r.stdout


def test_shot_sampling():
    r = _run("shot_sampling.py",
             env_extra={"QT_SHOT_QUBITS": "6", "QT_SHOT_COUNT": "40"})
    assert r.returncode == 0, r.stderr
    assert "top-2 mass" in r.stdout
