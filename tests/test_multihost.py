"""Multi-process (multi-host analogue) validation: two OS processes,
gloo collectives over TCP — the DCN stand-in for the reference's
inter-node MPI (QuEST_cpu_distributed.c).  Runs the distributed kernel
layer across the process boundary; see scripts/multihost_smoke.py for
what is checked."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_multihost_smoke():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "multihost_smoke.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    if "Multiprocess computations aren't implemented on the CPU backend" in (
            r.stdout + r.stderr):
        # this jaxlib's CPU client cannot run cross-process collectives at
        # all (pre-0.5 limitation) — nothing the kernel layer can do
        pytest.skip("installed jaxlib lacks multiprocess CPU collectives")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    assert "MULTIHOST SMOKE: PASS" in r.stdout
