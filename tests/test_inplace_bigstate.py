"""In-place big-state kernels (ops/bigstate.py) and the 30q bit-reversal
path (circuit._bit_reversal_big), plus the planner's k in {8,9} pruning.

The sigma kernel runs in interpret mode at small n; the 30q reversal is
validated at the INDEX level (composing each op's permutation semantics
over random sample indices) since a 2^30 state cannot be materialized in
CI.  On-chip equivalence vs the out-of-place path was verified at 28q on
the real TPU (slices bit-identical; see BASELINE.md round-3 notes).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from quest_tpu import circuit as C
from quest_tpu.ops import bigstate, kernels


@pytest.mark.parametrize("n,g", [(9, 2), (12, 2), (13, 3), (16, 4)])
def test_sigma_swap_matches_permute(n, g):
    rng = np.random.default_rng(7)
    a = rng.normal(size=(2, 1 << n)).astype(np.float32)
    out = bigstate.apply_sigma_swap(
        jnp.asarray(a), num_qubits=n, group_bits=g, interpret=True)
    perm = bigstate.sigma_perm(n, g)
    ref = kernels.permute_qubits(jnp.asarray(a), num_qubits=n, perm=perm)
    np.testing.assert_array_equal(
        np.asarray(out).reshape(2, -1), np.asarray(ref).reshape(2, -1))


def test_sigma_perm_is_involution():
    for n, g in ((9, 2), (28, 7), (30, 7), (34, 7)):
        p = bigstate.sigma_perm(n, g)
        assert [p[p[q]] for q in range(n)] == list(range(n))


def _winfused_index_map(op, n):
    """Index map f with out[i] = in[f(i)] for a winfused op whose A/B are
    PERMUTATION matrices (the only kind _bit_reversal_big emits)."""
    _, k, a, b, a_used, b_used = op[:6]
    a = np.asarray(a)[0, 0]
    b = np.asarray(b)[0, 0]
    # out[l'] takes in[j] where A[l', j] == 1
    pl_ = np.argmax(a, axis=1)
    pw_ = np.argmax(b, axis=1)
    assert (a[np.arange(128), pl_] == 1).all()
    assert (b[np.arange(128), pw_] == 1).all()

    def f(i):
        l = i & 127
        w = (i >> k) & 127
        rest = i & ~(127 | (127 << k))
        return rest | int(pl_[l]) | (int(pw_[w]) << k)

    return f


def _sigma_index_map(n, g):
    perm = bigstate.sigma_perm(n, g)

    def f(i):
        j = 0
        for q in range(n):
            j |= ((i >> q) & 1) << perm[q]
        return j

    return f


def test_bit_reversal_big_composes_to_full_reversal():
    """_bit_reversal_big's op list, composed at the index level, is the
    full bit reversal — checked on random sample indices at n = 28..31."""
    rng = np.random.default_rng(3)
    for n in (28, 29, 30, 31):
        ops = C._bit_reversal_big(n, np.float32)
        assert ops[-1][0] == "sigma_swap"
        maps = []
        for op in ops:
            if op[0] == "winfused":
                maps.append(_winfused_index_map(op, n))
            elif op[0] == "sigma_swap":
                maps.append(_sigma_index_map(n, op[1]))
            else:  # pragma: no cover
                raise AssertionError(op[0])
        samples = rng.integers(0, 1 << n, size=2000)
        for i in samples:
            j = int(i)
            # ops applied in order op1..opm: total map = f1(f2(...fm(i)))
            for f in reversed(maps):
                j = f(j)
            expect = int(format(int(i), f"0{n}b")[::-1], 2)
            assert j == expect, (n, i, j, expect)


def test_planner_prunes_k8_but_keeps_last_resort():
    """k in {8,9} is pruned from window candidates (layout-hostile view),
    but a gate coverable ONLY by k=8 still folds there instead of falling
    back to a per-gate apply pass."""
    u = np.zeros((2, 4, 4), np.float32)
    u[0] = np.eye(4)[[0, 3, 2, 1]]  # CNOT-like, concrete
    n = 22
    # (8, 14) spans exactly bits 8..14: k=8 is the unique covering window
    gates = [C.Gate((8, 14), u)]
    for use_native in (False, True):
        ops = C.plan_circuit(gates, n, use_native=use_native)
        kinds = [op[0] for op in ops]
        # never a per-gate apply pass, and the unavoidable k=8 window is
        # used as the last resort (the controlled-form rewrite may split
        # the gate across an extra k=7 pass first)
        assert set(kinds) == {"winfused"}, (use_native, kinds)
        assert 8 in {op[1] for op in ops}, (use_native, ops)
    # an ordinary layered circuit avoids k in {8, 9}
    rng = np.random.default_rng(1)
    gates2 = []
    for q in range(n):
        z = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
        qm, r = np.linalg.qr(z)
        uu = qm * (np.diag(r) / np.abs(np.diag(r)))
        gates2.append(C.Gate(
            (q,), np.stack([uu.real, uu.imag]).astype(np.float32)))
    for q in range(0, n - 1, 2):
        gates2.append(C.Gate((q, q + 1), u))
    for use_native in (False, True):
        ops = C.plan_circuit(gates2, n, use_native=use_native)
        ks = {op[1] for op in ops if op[0] == "winfused"}
        assert not (ks & {8, 9}), (use_native, ks)


def test_chained_executor_matches_monolithic():
    """execute_plan_chained (canonical view) == execute_plan (flat)."""
    rng = np.random.default_rng(5)
    n = 15
    gates = []
    for d in range(3):
        for q in range(n):
            z = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
            qm, r = np.linalg.qr(z)
            u = qm * (np.diag(r) / np.abs(np.diag(r)))
            gates.append(C.Gate(
                (q,), np.stack([u.real, u.imag]).astype(np.float32)))
        cx = np.zeros((2, 4, 4), np.float32)
        cx[0] = np.eye(4)[[0, 3, 2, 1]]
        for q in range(d % 2, n - 1, 2):
            gates.append(C.Gate((q, q + 1), cx))
    fresh = lambda: kernels.init_zero_state(1 << n, np.float32)
    ref = np.asarray(C.execute_plan(fresh(), C.plan_circuit(gates, n), n))
    ops = C.plan_to_device(C.plan_circuit(gates, n), jnp.float32)
    out = np.asarray(C.execute_plan_chained(fresh(), ops, n)).reshape(2, -1)
    np.testing.assert_array_equal(out, ref)
