"""The fused Pallas direct-rotation/expectation kernels
(ops/paulis._direct_rotation_pallas / _expec_term_pallas) — the
production trotter_scan / expec-scan bodies for f32 TPU registers at
15 <= n <= 32 state bits.  Off-TPU the production routing takes the
gather form (_pl_routable), so these tests drive the kernels DIRECTLY —
pallas interpret mode on the CPU backend — and pin them against the
gather form, which the small-n API tests check against the dense
oracle; plus one absolute single-term oracle at a Pallas-sized
register."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu.ops import paulis as P


def _scan_with(rot, n, nq, codes, angles):
    is_density = n == 2 * nq

    @jax.jit
    def run(a):
        def body(carry, inp):
            cd, ang = inp
            ang = ang.astype(carry.dtype)
            carry = rot(carry, cd, ang, nq, 0, n, conj=False)
            if is_density:
                carry = rot(carry, cd, -ang, nq, nq, n, conj=True)
            return carry, None

        out, _ = jax.lax.scan(body, a, (codes, angles))
        return out

    return run


def test_pallas_statevec_matches_gather_form():
    n, T = 16, 6
    rng = np.random.default_rng(0)
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))
    a = rng.standard_normal((2, 1 << n))
    a /= np.sqrt((a ** 2).sum())
    got = np.asarray(_scan_with(P._direct_rotation_pallas, n, n, codes,
                                angles)(jnp.asarray(a)))
    want = np.asarray(_scan_with(P._direct_rotation, n, n, codes,
                                 angles)(jnp.asarray(a)))
    np.testing.assert_allclose(got, want, atol=1e-14)


def test_pallas_density_matches_gather_form():
    nq, T = 8, 5
    n = 2 * nq
    rng = np.random.default_rng(1)
    codes = jnp.asarray(rng.integers(0, 4, size=(T, nq)), jnp.int32)
    angles = jnp.asarray(rng.normal(size=T))
    a = rng.standard_normal((2, 1 << n))
    a /= np.sqrt((a ** 2).sum())
    got = np.asarray(_scan_with(P._direct_rotation_pallas, n, nq, codes,
                                angles)(jnp.asarray(a)))
    want = np.asarray(_scan_with(P._direct_rotation, n, nq, codes,
                                 angles)(jnp.asarray(a)))
    np.testing.assert_allclose(got, want, atol=1e-14)


def test_pallas_all_identity_term_is_noop():
    """The angle-zeroing (no global phase from identity terms) holds on
    the Pallas path too."""
    n = 15
    rng = np.random.default_rng(2)
    codes = jnp.zeros((1, n), jnp.int32)
    angles = jnp.asarray([0.7])
    a = rng.standard_normal((2, 1 << n))
    a /= np.sqrt((a ** 2).sum())
    got = np.asarray(_scan_with(P._direct_rotation_pallas, n, n, codes,
                                angles)(jnp.asarray(a)))
    np.testing.assert_allclose(got, np.asarray(a), atol=1e-15)


def test_pallas_single_term_vs_expm_oracle():
    """Absolute check at a Pallas-sized register: e^{-i th/2 P} for one
    random Pauli string vs the dense matrix exponential applied via the
    factored form cos I - i sin P (P applied by the dense oracle)."""
    import functools

    n = 15
    rng = np.random.default_rng(3)
    codes_row = rng.integers(0, 4, size=n)
    th = 0.83
    codes = jnp.asarray(codes_row[None, :], jnp.int32)
    angles = jnp.asarray([th])
    vec = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec /= np.linalg.norm(vec)
    a = np.stack([vec.real, vec.imag])
    got = np.asarray(_scan_with(P._direct_rotation_pallas, n, n, codes,
                                angles)(jnp.asarray(a)))
    P2 = [np.eye(2), np.array([[0, 1], [1, 0]]),
          np.array([[0, -1j], [1j, 0]]), np.array([[1, 0], [0, -1]])]
    # apply P without materialising the 2^15 x 2^15 operator: reshape
    # contraction per qubit
    pv = vec.reshape([2] * n)  # axis 0 = qubit n-1 (most significant)
    for q, c in enumerate(codes_row):
        if c == 0:
            continue
        ax = n - 1 - q
        pv = np.moveaxis(
            np.tensordot(P2[c], np.moveaxis(pv, ax, 0), axes=(1, 0)),
            0, ax)
    want_vec = np.cos(th / 2) * vec - 1j * np.sin(th / 2) * pv.reshape(-1)
    want = np.stack([want_vec.real, want_vec.imag])
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_pallas_expec_matches_gather_form():
    """The fused flip+sign+reduce expectation kernel (n >= 15) equals
    the gather+reduce form (which the small-n API tests pin to the dense
    oracle); the quad route bypasses the kernel, giving the reference
    value here."""
    n, T = 16, 6
    rng = np.random.default_rng(4)
    codes = jnp.asarray(rng.integers(0, 4, size=(T, n)), jnp.int32)
    coeffs = jnp.asarray(rng.normal(size=T))
    a = rng.standard_normal((2, 1 << n))
    a /= np.sqrt((a ** 2).sum())
    @jax.jit
    def pl_scan(av):
        def body(acc, inp):
            cd, coeff = inp
            v = coeff.astype(av.dtype) * P._expec_term_pallas(av, cd, n)
            return acc + v, None
        tot, _ = jax.lax.scan(body, jnp.zeros((), av.dtype),
                              (codes, coeffs))
        return tot

    got = float(pl_scan(jnp.asarray(a)))
    want = float(P.expec_pauli_sum_scan(jnp.asarray(a), codes, coeffs,
                                        num_qubits=n))
    assert abs(got - want) < 1e-12


def test_pallas_expec_block_partials_cancel():
    """ADVICE r5: the expectation kernel emits one partial per grid block
    and tree-reduces outside — exact cancellation across blocks.  At
    n = 17 (R/BR = 4 blocks) the uniform state's <Z_top> splits into
    per-block partials of opposite sign that must cancel to EXACTLY zero
    (the former single-cell sequential accumulation only cancelled up to
    its chained rounding); a two-term sum with opposing coefficients on
    the same string must cancel exactly as well."""
    n = 17
    dim = 1 << n
    a = jnp.full((2, dim), 0.0).at[0, :].set(1.0 / np.sqrt(dim))
    # Z on the top qubit: + on the low half, - on the high half
    z_top = jnp.asarray([[0] * (n - 1) + [3]], jnp.int32)

    @jax.jit
    def one_term(av):
        return P._expec_term_pallas(av, z_top[0], n)

    assert float(one_term(a)) == 0.0
    # cancelling coefficients on an identical random string
    rng = np.random.default_rng(5)
    row = jnp.asarray(rng.integers(0, 4, size=n), jnp.int32)
    v = rng.standard_normal((2, dim)).astype(np.float64)
    v /= np.sqrt((v ** 2).sum())
    av = jnp.asarray(v)

    @jax.jit
    def two_terms(av):
        t = P._expec_term_pallas(av, row, n)
        return 1.0 * t + (-1.0) * t

    assert float(two_terms(av)) == 0.0
    # and the per-block form still equals the gather form on a dense state
    got = float(jax.jit(one_term)(av))
    pv, _ = P._apply_pauli_traced(av, z_top[0], n, 0, n, conj=False)
    want = float(jnp.sum(av[0] * pv[0] + av[1] * pv[1]))
    assert abs(got - want) < 1e-12


def test_direct_max_n_derived_from_gather_split():
    """ADVICE r5: the direct-rotation cap is derived from the gather
    split width and the int32 max-index invariant, not hand-counted."""
    assert P._DIRECT_MAX_N == P._GATHER_LO_BITS + 31
    rows = 1 << (P._DIRECT_MAX_N - P._GATHER_LO_BITS)
    assert rows - 1 <= np.iinfo(np.int32).max
    assert 2 * rows - 1 > np.iinfo(np.int32).max  # the cap is tight


def test_cpu_routing_prefers_gather():
    """Off-TPU the production scans must not route the interpreted
    Pallas grid (hundreds of sequential interpreted steps per term)."""
    import quest_tpu.ops.paulis as PP

    a = jnp.zeros((2, 1 << 16))
    assert not PP._pl_routable(a, 16)  # CPU backend in the suite
