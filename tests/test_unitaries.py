"""Unitary-gate correctness vs the dense oracle — the analogue of the
reference's test_unitaries.cpp (41 TEST_CASEs, exhaustive GENERATE over
target/control combinations on 5-qubit debug states applied to both a
state-vector and a density matrix)."""

import itertools

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
ATOL = 1e-10


def check_gate(env, apply_fn, targets, matrix, controls=(), control_states=None):
    """Apply via API to psi and rho; compare against dense oracle."""
    psi = qt.createQureg(N, env)
    qt.initDebugState(psi)
    apply_fn(psi)
    ref = oracle.apply_to_statevec(
        oracle.debug_state(2 ** N), N, targets, matrix, controls, control_states
    )
    np.testing.assert_allclose(oracle.state_from_qureg(psi), ref, atol=ATOL)

    rho = qt.createDensityQureg(N, env)
    qt.initDebugState(rho)
    apply_fn(rho)
    ref_r = oracle.apply_to_density(
        oracle.debug_density(N), N, targets, matrix, controls, control_states
    )
    np.testing.assert_allclose(oracle.state_from_qureg(rho), ref_r, atol=ATOL)


# ---------------------------------------------------------------------------
# one-qubit gates, exhaustive over targets
# ---------------------------------------------------------------------------

S = np.diag([1, 1j]).astype(complex)
T = np.diag([1, np.exp(1j * np.pi / 4)]).astype(complex)


@pytest.mark.parametrize("target", range(N))
@pytest.mark.parametrize(
    "name,fn,matrix",
    [
        ("hadamard", lambda q, t: qt.hadamard(q, t), oracle.H),
        ("pauliX", lambda q, t: qt.pauliX(q, t), oracle.X),
        ("pauliY", lambda q, t: qt.pauliY(q, t), oracle.Y),
        ("pauliZ", lambda q, t: qt.pauliZ(q, t), oracle.Z),
        ("sGate", lambda q, t: qt.sGate(q, t), S),
        ("tGate", lambda q, t: qt.tGate(q, t), T),
    ],
)
def test_fixed_single_qubit_gates(env, name, fn, matrix, target):
    check_gate(env, lambda q: fn(q, target), [target], matrix)


@pytest.mark.parametrize("target", range(N))
def test_rotations(env, target):
    theta = 0.671
    rx = np.array(
        [[np.cos(theta / 2), -1j * np.sin(theta / 2)],
         [-1j * np.sin(theta / 2), np.cos(theta / 2)]]
    )
    ry = np.array(
        [[np.cos(theta / 2), -np.sin(theta / 2)],
         [np.sin(theta / 2), np.cos(theta / 2)]]
    )
    rz = np.diag([np.exp(-0.5j * theta), np.exp(0.5j * theta)])
    check_gate(env, lambda q: qt.rotateX(q, target, theta), [target], rx)
    check_gate(env, lambda q: qt.rotateY(q, target, theta), [target], ry)
    check_gate(env, lambda q: qt.rotateZ(q, target, theta), [target], rz)
    check_gate(
        env,
        lambda q: qt.phaseShift(q, target, theta),
        [target],
        np.diag([1, np.exp(1j * theta)]),
    )


def test_rotate_around_axis(env):
    theta, axis = 1.23, (1.0, -2.0, 0.5)
    n = np.array(axis) / np.linalg.norm(axis)
    m = (
        np.cos(theta / 2) * oracle.I2
        - 1j * np.sin(theta / 2) * (n[0] * oracle.X + n[1] * oracle.Y + n[2] * oracle.Z)
    )
    check_gate(env, lambda q: qt.rotateAroundAxis(q, 2, theta, axis), [2], m)
    check_gate(
        env,
        lambda q: qt.rotateAroundAxis(q, 1, theta, qt.Vector(*axis)),
        [1],
        m,
    )


def test_compact_unitary(env):
    alpha = 0.6 + 0.48j
    beta = 0.36 - 0.48j  # |a|^2+|b|^2 = 0.9252... must be 1; normalise
    norm = np.sqrt(abs(alpha) ** 2 + abs(beta) ** 2)
    alpha, beta = alpha / norm, beta / norm
    m = np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]])
    check_gate(env, lambda q: qt.compactUnitary(q, 3, alpha, beta), [3], m)


def test_unitary_random(env):
    rng = np.random.default_rng(0)
    u = oracle.random_unitary(1, rng)
    for t in range(N):
        check_gate(env, lambda q, t=t: qt.unitary(q, t, u), [t], u)


# ---------------------------------------------------------------------------
# controlled gates, exhaustive over (control, target) pairs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "ctrl,target", [(c, t) for c in range(N) for t in range(N) if c != t]
)
def test_controlled_not_y(env, ctrl, target):
    check_gate(env, lambda q: qt.controlledNot(q, ctrl, target), [target], oracle.X, [ctrl])
    check_gate(env, lambda q: qt.controlledPauliY(q, ctrl, target), [target], oracle.Y, [ctrl])


@pytest.mark.parametrize("ctrl,target", [(0, 4), (3, 1), (2, 0)])
def test_controlled_rotations(env, ctrl, target):
    theta = -0.37
    rx = np.array(
        [[np.cos(theta / 2), -1j * np.sin(theta / 2)],
         [-1j * np.sin(theta / 2), np.cos(theta / 2)]]
    )
    rz = np.diag([np.exp(-0.5j * theta), np.exp(0.5j * theta)])
    check_gate(env, lambda q: qt.controlledRotateX(q, ctrl, target, theta), [target], rx, [ctrl])
    check_gate(env, lambda q: qt.controlledRotateZ(q, ctrl, target, theta), [target], rz, [ctrl])
    check_gate(
        env,
        lambda q: qt.controlledPhaseShift(q, ctrl, target, theta),
        [target],
        np.diag([1, np.exp(1j * theta)]),
        [ctrl],
    )
    check_gate(
        env,
        lambda q: qt.controlledPhaseFlip(q, ctrl, target),
        [target],
        np.diag([1, -1]),
        [ctrl],
    )


def test_controlled_unitary_random(env):
    rng = np.random.default_rng(1)
    u = oracle.random_unitary(1, rng)
    check_gate(env, lambda q: qt.controlledUnitary(q, 1, 3, u), [3], u, [1])
    check_gate(
        env, lambda q: qt.multiControlledUnitary(q, [0, 2, 4], 3, u), [3], u, [0, 2, 4]
    )


def test_multi_state_controlled_unitary(env):
    rng = np.random.default_rng(2)
    u = oracle.random_unitary(1, rng)
    check_gate(
        env,
        lambda q: qt.multiStateControlledUnitary(q, [0, 2], [0, 1], 3, u),
        [3],
        u,
        [0, 2],
        [0, 1],
    )


def test_multi_controlled_phase(env):
    theta = 0.8
    check_gate(
        env,
        lambda q: qt.multiControlledPhaseShift(q, [0, 2, 3], theta),
        [3],
        np.diag([1, np.exp(1j * theta)]),
        [0, 2],
    )
    check_gate(
        env,
        lambda q: qt.multiControlledPhaseFlip(q, [1, 2, 4]),
        [4],
        np.diag([1, -1]),
        [1, 2],
    )


# ---------------------------------------------------------------------------
# NOT / swap families
# ---------------------------------------------------------------------------


def test_multi_qubit_not(env):
    x2 = np.kron(oracle.X, oracle.X)
    check_gate(env, lambda q: qt.multiQubitNot(q, [1, 3]), [1, 3], x2)
    check_gate(
        env,
        lambda q: qt.multiControlledMultiQubitNot(q, [0, 4], [1, 3]),
        [1, 3],
        x2,
        [0, 4],
    )


SWAP = np.array(
    [[1, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]], dtype=complex
)
SQRT_SWAP = np.array(
    [
        [1, 0, 0, 0],
        [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
        [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
        [0, 0, 0, 1],
    ]
)


@pytest.mark.parametrize("q1,q2", [(0, 1), (1, 4), (3, 2), (4, 0)])
def test_swap_gates(env, q1, q2):
    check_gate(env, lambda q: qt.swapGate(q, q1, q2), [q1, q2], SWAP)
    check_gate(env, lambda q: qt.sqrtSwapGate(q, q1, q2), [q1, q2], SQRT_SWAP)


# ---------------------------------------------------------------------------
# multi-qubit rotations
# ---------------------------------------------------------------------------


def _multi_z_matrix(k, theta):
    """exp(-i theta/2 Z x ... x Z) on k qubits."""
    signs = np.ones(1)
    for _ in range(k):
        signs = np.concatenate([signs, -signs])
    return np.diag(np.exp(-0.5j * theta * signs))


@pytest.mark.parametrize("qubits", [[0], [1, 3], [0, 2, 4], [0, 1, 2, 3, 4]])
def test_multi_rotate_z(env, qubits):
    theta = 0.91
    check_gate(
        env,
        lambda q: qt.multiRotateZ(q, qubits, theta),
        qubits,
        _multi_z_matrix(len(qubits), theta),
    )


def test_multi_controlled_multi_rotate_z(env):
    theta = -1.3
    check_gate(
        env,
        lambda q: qt.multiControlledMultiRotateZ(q, [0, 4], [1, 3], theta),
        [1, 3],
        _multi_z_matrix(2, theta),
        [0, 4],
    )


@pytest.mark.parametrize(
    "targets,paulis",
    [([0], [1]), ([1], [2]), ([2], [3]), ([0, 2], [1, 2]), ([1, 3, 4], [3, 1, 2]),
     ([0, 1], [2, 2]), ([2, 4], [0, 1])],
)
def test_multi_rotate_pauli(env, targets, paulis):
    theta = 0.77
    from scipy.linalg import expm

    p = oracle._pauli_matrix_on_targets(paulis)
    m = expm(-0.5j * theta * p)
    check_gate(
        env, lambda q: qt.multiRotatePauli(q, targets, paulis, theta), targets, m
    )


def test_multi_controlled_multi_rotate_pauli(env):
    theta = 0.52
    from scipy.linalg import expm

    paulis = [1, 3]
    p = oracle._pauli_matrix_on_targets(paulis)
    m = expm(-0.5j * theta * p)
    check_gate(
        env,
        lambda q: qt.multiControlledMultiRotatePauli(q, [0, 2], [1, 4], paulis, theta),
        [1, 4],
        m,
        [0, 2],
    )


# ---------------------------------------------------------------------------
# dense 2/N-qubit unitaries
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("t1,t2", [(0, 1), (2, 4), (3, 0), (4, 1)])
def test_two_qubit_unitary(env, t1, t2):
    rng = np.random.default_rng(3)
    u = oracle.random_unitary(2, rng)
    check_gate(env, lambda q: qt.twoQubitUnitary(q, t1, t2, u), [t1, t2], u)


def test_controlled_two_qubit_unitary(env):
    rng = np.random.default_rng(4)
    u = oracle.random_unitary(2, rng)
    check_gate(env, lambda q: qt.controlledTwoQubitUnitary(q, 2, 0, 3, u), [0, 3], u, [2])
    check_gate(
        env,
        lambda q: qt.multiControlledTwoQubitUnitary(q, [1, 2], 0, 3, u),
        [0, 3],
        u,
        [1, 2],
    )


@pytest.mark.parametrize("targets", [[0], [2, 0], [1, 3, 4], [3, 0, 2, 1]])
def test_multi_qubit_unitary(env, targets):
    rng = np.random.default_rng(5)
    u = oracle.random_unitary(len(targets), rng)
    check_gate(env, lambda q: qt.multiQubitUnitary(q, targets, u), targets, u)


def test_controlled_multi_qubit_unitary(env):
    rng = np.random.default_rng(6)
    u = oracle.random_unitary(2, rng)
    check_gate(env, lambda q: qt.controlledMultiQubitUnitary(q, 4, [1, 0], u), [1, 0], u, [4])
    check_gate(
        env,
        lambda q: qt.multiControlledMultiQubitUnitary(q, [4, 2], [1, 0], u),
        [1, 0],
        u,
        [4, 2],
    )


# ---------------------------------------------------------------------------
# input validation (reference SECTION("input validation") pattern)
# ---------------------------------------------------------------------------


def test_validation_errors(env):
    q = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.hadamard(q, N)
    with pytest.raises(qt.QuESTError, match="Invalid target"):
        qt.hadamard(q, -1)
    with pytest.raises(qt.QuESTError, match="Control qubit cannot equal target"):
        qt.controlledNot(q, 2, 2)
    with pytest.raises(qt.QuESTError, match="The target qubits must be unique"):
        qt.multiQubitNot(q, [1, 1])
    with pytest.raises(qt.QuESTError, match="Matrix is not unitary"):
        qt.unitary(q, 0, np.array([[1, 0], [0, 2]]))
    with pytest.raises(qt.QuESTError, match="Control qubits cannot include target qubit"):
        qt.multiControlledUnitary(q, [1, 2], 2, np.eye(2))
