"""Collective-emission audits: what XLA actually compiles per op family.

SURVEY.md §7.5 calls for benchmarking/verifying the explicit ppermute
layer against GSPMD propagation; VERDICT r1 item 4 asks for "a test that
counts/asserts the collectives in the compiled program per op family".
These tests lower each family against 8-way-sharded avals on the virtual
CPU mesh and assert which communication primitives appear:

- elementwise families (dephasing, DiagonalOp apply, phase functions,
  parity phases) must compile to ZERO collectives — their masks derive
  from the global index, which GSPMD computes per-shard (the reference's
  "no pairing" phase kernels, QuEST_cpu.c:3146-3361, have the same
  property: no MPI exchange);
- reductions must emit all-reduce (the reference's MPI_Allreduce,
  QuEST_cpu_distributed.c:35-117);
- the explicit distributed layer's sharded-target gates must emit
  collective-permute (the reference's pairwise MPI_Sendrecv, :489-517);
- amplitude-pair families on mesh-coordinate bits (depolarising,
  damping, the fused QFT's high ladders + bit reversal) must emit SOME
  collective (permute / all-to-all / all-gather), and the elementwise
  ones must not regress into them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import introspect
from quest_tpu.env import AMP_AXIS
from quest_tpu.introspect import CollectiveBudget
from quest_tpu.ops import density as D
from quest_tpu.ops import kernels as K
from quest_tpu.ops import phasefunc as PF
from quest_tpu.parallel import dist as PAR

# the audit recipe these tests pioneered is now the public runtime API
# (quest_tpu.introspect, ISSUE 8); the module-level names stay because
# test_mesh_sweep imports them
COLLECTIVE_RE = introspect.COLLECTIVE_RE
_COLLECTIVE_OPS = introspect.COLLECTIVE_OPS


def collective_ops(fn, *args, donate=False):
    """Histogram of ACTUAL collective instructions in the optimized HLO
    (exact opcode occurrences, not word matches) — introspect.audit."""
    return introspect.audit(fn, *args, donate=donate).collectives


@pytest.fixture(scope="module")
def env8():
    e = qt.createQuESTEnv()
    if e.num_ranks < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return e


def collectives(fn, *args, env=None, donate=False):
    """Compile fn against sharded args and histogram the loose collective
    word matches in the optimized HLO (introspect.audit's upper-bound
    view — metadata mentions included)."""
    return introspect.audit(fn, *args, donate=donate).matches


def sharded_state(env, n, seed=0):
    rng = np.random.default_rng(seed)
    amps = rng.standard_normal((2, 1 << n))
    amps /= np.sqrt((amps ** 2).sum())
    return jax.device_put(jnp.asarray(amps), env.amp_sharding())


class TestElementwiseFamiliesNoComm:
    """Index-derived elementwise ops must partition with zero collectives."""

    def test_dephasing_density(self, env8):
        nq = 7                       # rho -> 14 sv qubits, 3 sharded
        amps = sharded_state(env8, 2 * nq, 1)

        def f(a):
            return D.mix_dephasing(a, 0.3, num_qubits=nq, target=nq - 1)

        assert collectives(f, amps) == {}

    def test_two_qubit_dephasing_density(self, env8):
        nq = 7
        amps = sharded_state(env8, 2 * nq, 2)

        def f(a):
            return D.mix_two_qubit_dephasing(
                a, 0.3, num_qubits=nq, qubit1=0, qubit2=nq - 1)

        assert collectives(f, amps) == {}

    def test_diagonal_op_apply(self, env8):
        n = 14
        amps = sharded_state(env8, n, 3)
        op = jax.device_put(jnp.ones((1 << n,), amps.dtype),
                            env8.vec_sharding())

        def f(a):
            return K.apply_full_diagonal(a, op, op * 0.5)

        assert collectives(f, amps) == {}

    def test_phase_func(self, env8):
        n = 14
        amps = sharded_state(env8, n, 4)

        def f(a):
            return PF.apply_phase_func(
                a, np.asarray([0.5]), np.asarray([2.0]),
                np.zeros((0, 1), np.int64), np.zeros((0,), np.float64),
                num_qubits=n, qubits=tuple(range(6)), encoding=0)

        assert collectives(f, amps) == {}

    def test_parity_phase(self, env8):
        n = 14
        amps = sharded_state(env8, n, 5)

        def f(a):
            # parity phase across local AND mesh-coordinate bits
            return K.apply_parity_phase(a, 0.7, num_qubits=n,
                                        qubits=(0, n - 1))

        assert collectives(f, amps) == {}


class TestReductionsAllReduce:
    def test_total_prob_explicit(self, env8):
        amps = sharded_state(env8, 14, 6)

        def f(a):
            return PAR.total_prob_sharded(a, mesh=env8.mesh)

        hist = collectives(f, amps)
        assert hist.get("all-reduce", 0) >= 1, hist

    def test_expec_diagonal(self, env8):
        n = 14
        amps = sharded_state(env8, n, 7)
        op = jax.device_put(jnp.ones((1 << n,), amps.dtype),
                            env8.vec_sharding())

        def f(a):
            from quest_tpu.ops import calculations as C
            return C.calc_expec_diagonal_statevec(a, op, op * 0.0)

        hist = collectives(f, amps)
        assert hist.get("all-reduce", 0) >= 1, hist


class TestExplicitDistLayer:
    def test_sharded_target_gate_permutes(self, env8):
        n = 14
        amps = sharded_state(env8, n, 8)
        h = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
        m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))

        def f(a):
            return PAR.apply_matrix_1q_sharded(
                a, m, mesh=env8.mesh, num_qubits=n, target=n - 1)

        hist = collectives(f, amps)
        assert hist.get("collective-permute", 0) >= 1, hist

    def test_swap_sharded_permutes(self, env8):
        n = 14
        amps = sharded_state(env8, n, 9)

        def f(a):
            return PAR.swap_sharded(a, mesh=env8.mesh, num_qubits=n,
                                    qb_low=0, qb_high=n - 1)

        hist = collectives(f, amps)
        assert hist.get("collective-permute", 0) >= 1, hist


class TestGspmdAB:
    """SURVEY.md §7 layer 5's explicit-vs-GSPMD benchmark, pinned
    structurally (scripts/probes/probe_gspmd_ab.py carries the full
    measurement): for the representative 1q sharded-target gate the
    explicit layer exchanges 1 hypercube ppermute (one state pass of
    bytes) while GSPMD propagation of the SAME local kernel emits
    4 permutes + 2 all-gathers (~10.5x the exchanged bytes, measured
    7x wall on the virtual mesh) — the quantitative reason the explicit
    layer is the default."""

    def test_gspmd_1q_gate_collectives_exceed_explicit(self, env8):
        n = 14
        amps = sharded_state(env8, n, 50)
        h = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
        m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))

        def explicit(a):
            return PAR.apply_matrix_1q_sharded(
                a, m, mesh=env8.mesh, num_qubits=n, target=n - 1)

        def gspmd(a):
            out = K.apply_matrix(a, m, num_qubits=n, targets=(n - 1,))
            return jax.lax.with_sharding_constraint(
                out, env8.amp_sharding())

        hist_a = collective_ops(explicit, amps)
        hist_b = collective_ops(gspmd, amps)
        assert hist_a == {"collective-permute": 1}, hist_a
        # GSPMD must communicate MORE than the explicit path (today:
        # 4 permutes + 2 all-gathers); equal-or-fewer would mean XLA
        # caught up and the default deserves re-measurement
        assert sum(hist_b.values()) > 1, hist_b
        # and both compute the same state (fresh arrays: the explicit
        # kernel donates its input)
        out_a = np.asarray(explicit(sharded_state(env8, n, 50)))
        out_b = np.asarray(jax.jit(gspmd)(sharded_state(env8, n, 50)))
        np.testing.assert_allclose(out_a, out_b, atol=1e-12)


class TestPairFamiliesCommunicate:
    def test_explicit_depolarising_one_permute(self, env8):
        """The explicit pair-exchange channel is EXACTLY one
        collective-permute — the redesign of the reference's
        pack-and-exchange distributed decoherence
        (QuEST_cpu_distributed.c:553-852)."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 10)

        def f(a):
            return PAR.mix_pair_channel_sharded(
                a, 0.3, mesh=env8.mesh, num_qubits=nq, target=nq - 1,
                kind="depol")

        # the ambient budget checks every audit inside the block — the
        # same pin as asserting the histogram, through the public API
        with CollectiveBudget(exact={"collective-permute": 1}):
            introspect.audit(f, amps, donate=True)

    def test_explicit_damping_one_permute(self, env8):
        nq = 7
        amps = sharded_state(env8, 2 * nq, 12)

        def f(a):
            return PAR.mix_pair_channel_sharded(
                a, 0.3, mesh=env8.mesh, num_qubits=nq, target=nq - 1,
                kind="damping")

        with CollectiveBudget(exact={"collective-permute": 1}):
            introspect.audit(f, amps, donate=True)

    def test_gspmd_elementwise_depol_fallback_bounded(self, env8):
        """The GSPMD fallback (elementwise kernel under sharding
        propagation) is measurably WORSE than the explicit path — its
        flipped-copy gather costs all-gathers (measured: 6 all-gathers +
        1 permute here, vs the explicit kernel's single permute pinned
        above) — which is exactly why mixDepolarising/mixDamping route
        the explicit path on sharded registers.  This audit bounds the
        fallback so a regression to something pathological still fails."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 13)

        def f(a):
            return D.mix_depolarising(a, 0.3, num_qubits=nq, target=nq - 1)

        hist = collective_ops(f, amps, donate=True)
        assert set(hist) <= {"collective-permute", "all-gather"}, hist
        assert sum(hist.values()) <= 8, hist

    def test_diagonal_op_on_rho_gathers_only_the_op(self, env8):
        """applyDiagonalOp on a sharded rho replicates the (small) OP
        vector to every shard — the reference's copyDiagOpIntoMatrixPair-
        State (QuEST_cpu_distributed.c:1548-1587) — and must NOT gather
        the state.  Pinned by opcode (all-gathers only, bounded count)
        AND by gathered size (every all-gather in the HLO is op-sized,
        2^nq elements, never state-sized 2^2nq)."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 14)
        op = jax.device_put(jnp.ones((1 << nq,), amps.dtype),
                            env8.vec_sharding())

        def f(a, re, im):
            return D.apply_diagonal_op_density(a, re, im, num_qubits=nq)

        report = introspect.audit(f, amps, op, op * 0.5)
        hist = report.collectives
        assert set(hist) == {"all-gather"} and hist["all-gather"] <= 4, hist
        for line in report.text.splitlines():
            if " all-gather(" in line:
                assert f"[{1 << nq}]{{" in line, line  # op-sized, ever

    def test_api_routes_explicit_channel_on_sharded_rho(self, env8):
        """The API-level routing predicate sends sharded-bra channels to
        the explicit kernel (the audit above pins it at 1 permute)."""
        import quest_tpu as qt
        from quest_tpu import api_ops

        rho = qt.createDensityQureg(7, env8)
        assert api_ops._pair_channel_sharded(rho, 0.3, 6, "depol")
        assert abs(qt.calcTotalProb(rho) - 1.0) < 1e-5

    def test_fused_qft_sharded_exact_collectives(self, env8):
        """The explicit shard_map QFT emits EXACTLY r hypercube permutes
        (one per mesh-bit H exchange) + 1 all-to-all (the bit-reversal
        lanes<->mesh block swap)."""
        n = 14
        amps = sharded_state(env8, n, 11)
        r = PAR.num_shard_bits(env8.mesh)

        def f(a):
            return PAR.fused_qft_sharded(a, mesh=env8.mesh, num_qubits=n)

        with CollectiveBudget(exact={"collective-permute": r,
                                     "all-to-all": 1}):
            introspect.audit(f, amps, donate=True)


class TestScanCompositesExactCollectives:
    """The shard_map scan composites (VERDICT r3 item 1) compile to the
    pinned collective pattern: ppermute exchanges for sharded qubits in
    the rotation layers, one psum for the expectation reduce — nothing
    else (no state-sized gathers, no all-to-alls)."""

    def test_trotter_scan_sharded_direct_switch_permutes(self, env8):
        """The direct term body's mesh-flip lax.switch carries one static
        XOR ppermute per nonzero mesh mask: exactly 2^r - 1 collective-
        permutes in the scan body (all inside the switch — at most ONE
        executes per term), and no other collective.  This replaces the
        2*r rotate/unrotate-layer exchanges of the conjugation body
        (VERDICT round-5 item (a)): per-term exchange volume drops from
        2*r full shards to at most one."""
        n = 10
        amps = sharded_state(env8, n, 20)
        ndev = PAR.amp_axis_size(env8.mesh)
        codes = jnp.asarray(np.random.default_rng(0).integers(
            0, 4, size=(5, n)), jnp.int32)
        angles = jnp.asarray(np.linspace(0.1, 0.5, 5))

        def f(a):
            return PAR.trotter_scan_sharded(
                a, codes, angles, mesh=env8.mesh, num_qubits=n,
                rep_qubits=n)

        with CollectiveBudget(exact={"collective-permute": ndev - 1}):
            introspect.audit(f, amps, donate=True)

    def test_trotter_scan_sharded_density_two_switches(self, env8):
        """A density-matrix term rotates ket and bra separately: two
        mesh-flip switches per term, but the branch computations are
        identical (same static XOR permutes) so XLA shares them — the
        module still holds exactly 2^r - 1 collective-permutes."""
        nq = 5
        amps = sharded_state(env8, 2 * nq, 24)
        ndev = PAR.amp_axis_size(env8.mesh)
        codes = jnp.asarray(np.random.default_rng(4).integers(
            0, 4, size=(3, nq)), jnp.int32)
        angles = jnp.asarray(np.linspace(0.1, 0.3, 3))

        def f(a):
            return PAR.trotter_scan_sharded(
                a, codes, angles, mesh=env8.mesh, num_qubits=2 * nq,
                rep_qubits=nq)

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": ndev - 1}

    def test_expec_scan_sharded_permutes_plus_one_allreduce(self, env8):
        """One mesh-flip switch per term (2^r - 1 branch permutes, at
        most one executed) + ONE final psum (the reference's
        local-reduce + MPI_Allreduce, QuEST_cpu_distributed.c:35-51)."""
        n = 10
        amps = sharded_state(env8, n, 21)
        ndev = PAR.amp_axis_size(env8.mesh)
        codes = jnp.asarray(np.random.default_rng(1).integers(
            0, 4, size=(4, n)), jnp.int32)
        coeffs = jnp.asarray(np.linspace(1.0, 2.0, 4))

        def f(a):
            return PAR.expec_pauli_sum_scan_sharded(
                a, codes, coeffs, mesh=env8.mesh, num_qubits=n)

        report = introspect.audit(f, amps)
        hist = report.collectives
        assert report.count("collective-permute") == ndev - 1, hist
        assert report.count("all-reduce") == 1, hist
        assert set(hist) <= {"collective-permute", "all-reduce",
                             "all-reduce-start"}, hist


class TestQftRunsExactCollectives:
    """dist.fused_qft_runs_sharded compiles to the pinned pattern: one
    ppermute per mesh-bit layer, one ppermute per local<->mesh reversal
    swap, one composed ppermute for all mesh<->mesh reversal pairs —
    never a state gather."""

    def test_top_run_statevec(self, env8):
        """Run [7, 16) on n=16 over 8 devices (nloc=13): 3 mesh layers +
        3 mixed reversal swaps = 6 permutes, nothing else."""
        n = 16
        amps = sharded_state(env8, n, 22)
        r = PAR.num_shard_bits(env8.mesh)
        assert r == 3

        def f(a):
            return PAR.fused_qft_runs_sharded(
                a, mesh=env8.mesh, num_qubits=n, runs=((7, 9, False),))

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": 6}

    def test_density_full_qft(self, env8):
        """9q density (18 state bits, nloc=15): ket run is fully local
        (zero collectives), bra run costs 3 mesh layers + 3 mixed
        reversal swaps."""
        n = 18
        amps = sharded_state(env8, n, 23)

        def f(a):
            return PAR.fused_qft_runs_sharded(
                a, mesh=env8.mesh, num_qubits=n,
                runs=((0, 9, False), (9, 9, True)))

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": 6}

    def test_mesh_mesh_reversal_composes_to_one_permute(self, env8):
        """A run living entirely in the top bits ([nloc+? ..]): the
        mesh<->mesh reversal pairs fold into ONE composed shard
        permutation."""
        n = 16  # nloc = 13; run [13, 16) is all mesh bits
        amps = sharded_state(env8, n, 24)

        def f(a):
            return PAR.fused_qft_runs_sharded(
                a, mesh=env8.mesh, num_qubits=n, runs=((13, 3, False),))

        # 3 mesh layers + 1 composed reversal permute (pair 13<->15)
        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": 4}


class TestTwoQubitChannelsExactCollectives:
    """The explicit 2q decoherence + DiagonalOp-on-rho replication
    kernels (VERDICT r3 item 4) compile to the pinned collective
    pattern."""

    def test_two_qubit_depol_both_bra_sharded_two_permutes(self, env8):
        """Both bra bits on mesh coordinates: the orbit sum's recursive
        doubling = exactly 2 collective-permutes (the reference's 3-part
        pack-and-exchange does more, QuEST_cpu_distributed.c:553-852)."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 30)

        def f(a):
            return PAR.mix_two_qubit_depol_sharded(
                a, 0.3, mesh=env8.mesh, num_qubits=nq, qubit1=nq - 1,
                qubit2=nq - 2)

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": 2}

    def test_two_qubit_depol_one_bra_sharded(self, env8):
        """One bra bit sharded, one local: 1 permute + 1 local flip."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 31)

        def f(a):
            return PAR.mix_two_qubit_depol_sharded(
                a, 0.3, mesh=env8.mesh, num_qubits=nq, qubit1=0,
                qubit2=nq - 1)

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": 1}

    def test_diag_op_on_rho_two_op_sized_gathers(self, env8):
        """Explicit replication: exactly 2 all-gathers (re, im), each
        op-sized (2^nq), never state-sized — the reference's
        copyDiagOpIntoMatrixPairState (QuEST_cpu_distributed.c:1548-1587)."""
        nq = 7
        amps = sharded_state(env8, 2 * nq, 32)
        op = jax.device_put(jnp.ones((1 << nq,), amps.dtype),
                            env8.vec_sharding())

        def f(a, re, im):
            return PAR.apply_diag_op_density_sharded(
                a, re, im, mesh=env8.mesh, num_qubits=nq)

        report = introspect.audit(f, amps, op, op * 0.5, donate=True)
        hist = report.collectives
        assert report.count("all-gather") == 2, hist
        assert "collective-permute" not in hist, hist
        for line in report.text.splitlines():
            if " all-gather(" in line or " all-gather-start(" in line:
                assert f"[{1 << nq}]{{" in line, line

    def test_kraus_relocalization_route(self, env8):
        """A generic 2q Kraus map whose bra bits are sharded routes
        through SWAP-relocalization (2 ppermutes per sharded bit) and
        matches the dense Kraus oracle."""
        import oracle
        import quest_tpu as qt

        nq = 4
        rng = np.random.default_rng(33)
        mat = oracle.random_density(nq, rng)
        r = qt.createDensityQureg(nq, env8)
        oracle.set_qureg_from_array(qt, r, mat)
        ks = oracle.random_kraus_map(2, 3, rng)
        qt.mixTwoQubitKrausMap(r, nq - 1, nq - 2, ks)
        expect = np.zeros_like(mat)
        for k in ks:
            K2 = oracle.full_operator(nq, [nq - 1, nq - 2], k)
            expect = expect + K2 @ mat @ K2.conj().T
        np.testing.assert_allclose(oracle.state_from_qureg(r), expect,
                                   atol=1e-10)


class TestPipelinedExchange:
    """ISSUE 3 pins: the chunked double-buffered exchange
    (dist.exchange_pipelined) lowers to exactly C collective-permutes,
    every one of them CHUNK-sized (shard/C) — the transient exchange
    buffer is at most one chunk in flight plus one being consumed,
    <= shard/C + one chunk, where the monolithic path's recv buffer is a
    full shard — and the pipelined output is numerically identical to
    the monolithic one (bit-identical for pure relabelings and the
    elementwise gate combine; channels may differ by an XLA
    fusion/FMA-contraction ulp)."""

    N = 14

    def _state(self, env, seed):
        return sharded_state(env, self.N, seed)

    def _gate(self, env, chunks):
        h = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
        m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))

        def f(a):
            return PAR.apply_matrix_1q_sharded(
                a, m, mesh=env.mesh, num_qubits=self.N, target=self.N - 1,
                chunks=chunks)

        return f

    def test_exactly_c_chunk_sized_permutes(self, env8):
        n = self.N
        r = PAR.num_shard_bits(env8.mesh)
        shard_amps = 1 << (n - r)
        for C in (2, 4, 8):
            jfn = jax.jit(self._gate(env8, C), donate_argnums=0)
            txt = jfn.lower(self._state(env8, 60)).compile().as_text()
            cps = [ln for ln in txt.splitlines()
                   if " collective-permute(" in ln
                   or " collective-permute-start(" in ln]
            assert len(cps) == C, (C, txt.count("collective-permute"))
            # every exchange buffer is exactly chunk-sized: (2, shard/C)
            for ln in cps:
                assert f"[2,{shard_amps // C}]" in ln, (C, ln)

    def test_transient_memory_below_monolithic(self, env8):
        """Live-buffer accounting: the chunked program's temp allocation
        must undercut the monolithic one (whose recv buffer is a full
        shard) and stay within shard + 2 chunks — the update-slice
        epilogue's staging plus the two in-flight chunk buffers.  (On
        TPU the staging aliases away entirely; CPU buffer assignment
        keeps one copy, which this bound includes.)"""
        n = self.N
        r = PAR.num_shard_bits(env8.mesh)
        amps = self._state(env8, 61)
        shard_bytes = 2 * (1 << (n - r)) * amps.dtype.itemsize

        def temp(C):
            jfn = jax.jit(self._gate(env8, C), donate_argnums=0)
            ma = jfn.lower(self._state(env8, 61)).compile().memory_analysis()
            if ma is None:  # pragma: no cover - backend-dependent API
                pytest.skip("memory_analysis unavailable on this backend")
            return ma.temp_size_in_bytes

        mono = temp(1)
        slack = 4096  # scalar/index temporaries
        for C in (4, 8):
            chunked = temp(C)
            assert chunked < mono, (C, chunked, mono)
            assert chunked <= shard_bytes + 2 * (shard_bytes // C) + slack, (
                C, chunked, shard_bytes)

    def test_pipelined_bit_identical_gate_swap_remap(self, env8):
        n = self.N
        h = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
        m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))
        for C in (2, 4):
            a1 = np.asarray(PAR.apply_matrix_1q_sharded(
                self._state(env8, 62), m, mesh=env8.mesh, num_qubits=n,
                target=n - 1, controls=(0, 9, 12), control_states=(1, 0, 1),
                chunks=1))
            a2 = np.asarray(PAR.apply_matrix_1q_sharded(
                self._state(env8, 62), m, mesh=env8.mesh, num_qubits=n,
                target=n - 1, controls=(0, 9, 12), control_states=(1, 0, 1),
                chunks=C))
            np.testing.assert_array_equal(a1, a2)
            s1 = np.asarray(PAR.swap_sharded(
                self._state(env8, 63), mesh=env8.mesh, num_qubits=n,
                qb_low=2, qb_high=n - 1, chunks=1))
            s2 = np.asarray(PAR.swap_sharded(
                self._state(env8, 63), mesh=env8.mesh, num_qubits=n,
                qb_low=2, qb_high=n - 1, chunks=C))
            np.testing.assert_array_equal(s1, s2)
        sigma = PAR.canonical_sigma(
            (3, 1, 2, 0) + tuple(range(4, n - 3)) + (n - 1, n - 2, n - 3))
        r1 = np.asarray(PAR.remap_sharded(
            self._state(env8, 64), mesh=env8.mesh, num_qubits=n,
            sigma=sigma, chunks=(1, 1)))
        r4 = np.asarray(PAR.remap_sharded(
            self._state(env8, 64), mesh=env8.mesh, num_qubits=n,
            sigma=sigma, chunks=(4, 4)))
        np.testing.assert_array_equal(r1, r4)

    def test_pipelined_channels_and_trotter_match(self, env8):
        nq = 7
        rho = sharded_state(env8, 2 * nq, 65)
        for kind in ("depol", "damping"):
            c1 = np.asarray(PAR.mix_pair_channel_sharded(
                sharded_state(env8, 2 * nq, 65), 0.3, mesh=env8.mesh,
                num_qubits=nq, target=nq - 1, kind=kind, chunks=1))
            c4 = np.asarray(PAR.mix_pair_channel_sharded(
                sharded_state(env8, 2 * nq, 65), 0.3, mesh=env8.mesh,
                num_qubits=nq, target=nq - 1, kind=kind, chunks=4))
            np.testing.assert_allclose(c1, c4, atol=1e-14)
        n = 10
        codes = jnp.asarray(np.random.default_rng(2).integers(
            0, 4, size=(5, n)), jnp.int32)
        angles = jnp.asarray(np.linspace(0.1, 0.5, 5))
        t1 = np.asarray(PAR.trotter_scan_sharded(
            sharded_state(env8, n, 66), codes, angles, mesh=env8.mesh,
            num_qubits=n, rep_qubits=n, chunks=1))
        t2 = np.asarray(PAR.trotter_scan_sharded(
            sharded_state(env8, n, 66), codes, angles, mesh=env8.mesh,
            num_qubits=n, rep_qubits=n, chunks=2))
        np.testing.assert_array_equal(t1, t2)

    def test_trotter_chunk_override_is_monolithic_on_direct_body(self, env8):
        """The direct term body's switch exchange is monolithic by
        construction (the local gather mixes rows across any chunk
        boundary): a chunk override neither changes the collective count
        nor the result."""
        n = 10
        ndev = PAR.amp_axis_size(env8.mesh)
        amps = sharded_state(env8, n, 67)
        codes = jnp.asarray(np.random.default_rng(3).integers(
            0, 4, size=(5, n)), jnp.int32)
        angles = jnp.asarray(np.linspace(0.1, 0.5, 5))

        def f(a):
            return PAR.trotter_scan_sharded(
                a, codes, angles, mesh=env8.mesh, num_qubits=n,
                rep_qubits=n, chunks=2)

        assert collective_ops(f, amps, donate=True) == {
            "collective-permute": ndev - 1}

    def test_env_override_routes_wrappers(self, env8, monkeypatch):
        """QT_EXCHANGE_CHUNKS acts at DISPATCH time: the public wrappers
        re-resolve the chunk count per call, so flipping the env var
        mid-process retraces instead of reusing a stale schedule."""
        monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "4")
        jfn = jax.jit(self._gate(env8, None), donate_argnums=0)
        txt = jfn.lower(self._state(env8, 68)).compile().as_text()
        assert txt.count(" collective-permute(") == 4
        monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "1")
        jfn = jax.jit(self._gate(env8, None), donate_argnums=0)
        txt = jfn.lower(self._state(env8, 68)).compile().as_text()
        assert txt.count(" collective-permute(") == 1

    def test_auto_heuristic_small_shard_monolithic(self, env8):
        """The measured fallback rules: monolithic on the CPU backend
        (chunking is a flat 21-41% loss with no asynchrony to recoup —
        config 7), monolithic below PIPELINE_MIN_BYTES on accelerators,
        target-sized chunks above, structural limit always respected,
        non-power-of-two overrides rounded down."""
        assert PAR.exchange_chunks(1 << 40, backend="cpu") == 1
        assert PAR.exchange_chunks(PAR.PIPELINE_MIN_BYTES - 1,
                                   backend="tpu") == 1
        assert PAR.exchange_chunks(PAR.PIPELINE_MIN_BYTES * 64,
                                   backend="tpu") > 1
        assert PAR.exchange_chunks(1 << 40,
                                   backend="tpu") == PAR.MAX_EXCHANGE_CHUNKS
        assert PAR.exchange_chunks(1 << 40, limit=2, backend="tpu") == 2
        # the 14q/8-dev test states sit far below the threshold anyway:
        # the default path everywhere else in this suite is monolithic,
        # keeping every exact-collective pin above valid
        r = PAR.num_shard_bits(env8.mesh)
        assert 2 * (1 << (self.N - r)) * 8 < PAR.PIPELINE_MIN_BYTES


class TestMeasurementCollectives:
    def test_measure_fused_one_allreduce_no_gather(self, env8):
        """The fused measure program on a sharded register: the prob
        reduce lowers to all-reduce(s), the threshold draw is replicated
        (key broadcast = the reference's seed broadcast,
        QuEST_cpu_distributed.c:1384-1395), the conditional collapse is
        elementwise — and the STATE is never gathered."""
        import jax.random as jr

        from quest_tpu.ops import measurement as M

        n = 10
        amps = sharded_state(env8, n, 40)
        key = jr.PRNGKey(0)

        def f(a):
            out, o, p = M.measure_fused(
                a, key, 3, num_qubits=n, target=n - 1, is_density=False)
            return out, o, p

        hist = collective_ops(f, amps, donate=True)
        assert set(hist) <= {"all-reduce", "all-reduce-start"}, hist
        assert 1 <= sum(hist.values()) <= 3, hist
