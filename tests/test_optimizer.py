"""Cost-model-guided circuit optimizer (ISSUE 13): the pre-planner
rewrite contract.

The load-bearing acceptance facts pinned here:

  * cancellation and merging are SEMANTICS-PRESERVING — an optimized
    drain agrees with the unoptimized drain on every path (scalar,
    8-shard, batched bank), and a cancellation-only rewrite is
    BIT-identical to draining the stream with the cancelled pair simply
    absent;
  * the §21 reconciliation contract survives: ``model_drift_total == 0``
    on optimized sharded drains, because predictions are priced on the
    OPTIMIZED stream;
  * the optimizer mode is part of the fusion plan-cache key — flipping
    ``QT_OPTIMIZER`` retraces instead of replaying a stale plan;
  * telemetry counters / the explain section / the env-string fragment
    surface the rewrite's accounting.

tests/test_introspect.py pins the RAW planner model with the optimizer
forced off; this suite owns the optimized contract.
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion
from quest_tpu import introspect
from quest_tpu import optimizer as OPT
from quest_tpu import telemetry as T
from quest_tpu.validation import QuESTError


@pytest.fixture(autouse=True)
def opt_state(monkeypatch):
    """Default-on optimizer, no env override, clean rewrite cache."""
    monkeypatch.delenv("QT_OPTIMIZER", raising=False)
    OPT.set_circuit_optimizer(None)
    OPT.clear_cache()
    yield
    OPT.set_circuit_optimizer(None)
    OPT.clear_cache()


@pytest.fixture(autouse=True)
def tele():
    prev = T.mode_name()
    T.configure("on")
    T.reset()
    yield T
    T.reset()
    T.configure(prev)


@pytest.fixture
def env8(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return env


def _soa(m):
    m = np.asarray(m, dtype=complex)
    return np.stack([m.real, m.imag])


X = _soa([[0, 1], [1, 0]])
H = _soa(np.array([[1, 1], [1, -1]]) / np.sqrt(2))
Z = _soa([[1, 0], [0, -1]])
S = _soa([[1, 0], [0, 1j]])
TG = _soa([[1, 0], [0, np.exp(1j * np.pi / 4)]])
CX = _soa([[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]])


def _g(targets, mat):
    return CIRC.Gate(tuple(targets), mat)


def _opt(items, n=4, nloc=4, nsh=0, perm0=None):
    return OPT.optimize_items(items, n=n, nloc=nloc, nsh=nsh,
                              perm0=perm0, quiet=True)


# ---------------------------------------------------------------------------
# Unit: the rewrite itself
# ---------------------------------------------------------------------------


class TestRewrite:
    def test_xx_pair_cancels_exactly(self):
        out, stats = _opt([_g((0,), X), _g((0,), X)])
        assert out == []
        assert stats["removed"]["cancel"] == 2
        assert stats["gates_in"] == 2 and stats["gates_out"] == 0

    def test_cnot_pair_cancels_through_disjoint_gate(self):
        # the middle H(2) is support-disjoint, so the second CNOT reaches
        # back through it to cancel the first
        out, stats = _opt(
            [_g((0, 1), CX), _g((2,), H), _g((0, 1), CX)])
        assert [it.targets for it in out] == [(2,)]
        assert stats["removed"]["cancel"] == 2

    def test_hh_merges_not_cancels(self):
        # H·H is identity only up to rounding (off-diagonals ~2e-17 in
        # f64) — under "on" it must MERGE, preserving bit-exactness
        out, stats = _opt([_g((0,), H), _g((0,), H)])
        assert len(out) == 1
        assert stats["removed"]["merge"] == 1
        assert stats["removed"]["cancel"] == 0
        np.testing.assert_array_equal(
            out[0].mat, CIRC.soa_matmul(H, H))

    def test_aggressive_drops_near_identity(self):
        OPT.set_circuit_optimizer("aggressive")
        out, stats = _opt([_g((0,), H), _g((0,), H)])
        assert out == []
        assert stats["removed"]["cancel"] == 2

    def test_merge_matmul_order_is_new_at_old(self):
        # stream order S then T: the merged gate must be T @ S
        out, _ = _opt([_g((0,), S), _g((1,), H), _g((0,), TG)])
        merged = [it for it in out if it.targets == (0,)]
        assert len(merged) == 1
        np.testing.assert_allclose(
            merged[0].mat, CIRC.soa_matmul(TG, S), atol=1e-15)

    def test_channel_blocks_composition(self):
        # a channel on the same ket bit is a barrier: the two X's must
        # NOT compose across it, and the channel itself is never dropped
        ch = fusion.ChannelItem("depolarising", 0, 3, 0.1)
        out, stats = _opt([_g((0,), X), ch, _g((0,), X)], n=6, nloc=6)
        assert len(out) == 3 and out[1] is ch
        assert stats["removed"]["cancel"] == 0
        assert stats["removed"]["merge"] == 0

    def test_diag_run_coalesces_to_union_gate(self):
        # T(0) first merges into Z(0) through the commuting S(1); the
        # two surviving diagonals then coalesce into one union gate
        out, stats = _opt([_g((0,), Z), _g((1,), S), _g((0,), TG)])
        assert len(out) == 1
        assert stats["removed"]["merge"] == 1
        assert stats["removed"]["diag_coalesce"] == 1
        fused = out[0]
        assert fused.targets == (0, 1)
        # the fused diagonal equals the elementwise product of the run
        want = np.kron(np.diag([1, 1j]),          # S on qubit 1
                       np.diag([1, -1]) @ np.diag(
                           [1, np.exp(1j * np.pi / 4)]))  # Z·T on 0
        got = fused.mat[0] + 1j * fused.mat[1]
        np.testing.assert_allclose(got, want, atol=1e-15)

    def test_traced_stream_left_untouched(self):
        import jax.numpy as jnp

        items = [_g((0,), jnp.asarray(X)), _g((0,), jnp.asarray(X))]
        out, stats = _opt(items)
        assert out == items
        assert stats["gates_in"] == stats["gates_out"] == 2

    def test_off_mode_is_a_noop(self):
        OPT.set_circuit_optimizer("off")
        items = [_g((0,), X), _g((0,), X)]
        out, stats = _opt(items)
        assert out == items and stats["mode"] == "off"

    def test_mode_knob_validation_and_override(self, monkeypatch):
        with pytest.raises(QuESTError):
            qt.setCircuitOptimizer("bogus")
        monkeypatch.setenv("QT_OPTIMIZER", "aggressive")
        assert qt.getCircuitOptimizer() == "aggressive"
        qt.setCircuitOptimizer("off")           # override beats env
        assert qt.getCircuitOptimizer() == "off"
        qt.setCircuitOptimizer(None)
        assert qt.getCircuitOptimizer() == "aggressive"


# ---------------------------------------------------------------------------
# Integration: drain parity on every path
# ---------------------------------------------------------------------------


def _random_program(n, depth, seed):
    """Randomized API-level circuit mixing mergeable/cancellable/diagonal
    structure with generic entanglers."""
    rng = np.random.default_rng(seed)
    ops = []
    for _ in range(depth):
        kind = rng.integers(0, 10)
        t = int(rng.integers(0, n))
        u = int(rng.integers(0, n - 1))
        v = u + 1
        th = float(rng.uniform(0, 2 * np.pi))
        ops.append((kind, t, u, v, th))
    return ops


def _apply_program(q, ops):
    for kind, t, u, v, th in ops:
        if kind == 0:
            qt.hadamard(q, t)
        elif kind == 1:
            qt.pauliX(q, t)
        elif kind == 2:
            qt.tGate(q, t)
        elif kind == 3:
            qt.sGate(q, t)
        elif kind == 4:
            qt.rotateZ(q, t, th)
        elif kind == 5:
            qt.rotateX(q, t, th)
        elif kind == 6:
            qt.controlledNot(q, u, v)
        elif kind == 7:
            qt.controlledPhaseFlip(q, u, v)
        elif kind == 8:
            qt.swapGate(q, u, v)
        else:
            qt.phaseShift(q, t, th)


class TestDrainParity:
    # two seeds in tier-1; the deeper sweep rides the unfiltered
    # make verify-optimizer run (slow marker)
    @pytest.mark.parametrize(
        "seed", [0, 1,
                 pytest.param(2, marks=pytest.mark.slow),
                 pytest.param(3, marks=pytest.mark.slow)])
    def test_randomized_parity_scalar(self, env, seed):
        n = 5
        ops = _random_program(n, 40, seed)
        amps = {}
        for mode in ("on", "off", "aggressive"):
            qt.setCircuitOptimizer(mode)
            q = qt.createQureg(n, env)
            with qt.gateFusion(q):
                _apply_program(q, ops)
            amps[mode] = np.asarray(q.amps)
        np.testing.assert_allclose(amps["on"], amps["off"], atol=1e-10)
        np.testing.assert_allclose(amps["aggressive"], amps["off"],
                                   atol=1e-10)

    @pytest.mark.parametrize(
        "seed", [5, pytest.param(6, marks=pytest.mark.slow)])
    def test_randomized_parity_sharded_with_zero_drift(self, env8, seed):
        n = 7  # 3 sharded qubits over the 8-device mesh
        ops = _random_program(n, 48, seed)
        amps = {}
        for mode in ("on", "off"):
            qt.setCircuitOptimizer(mode)
            T.reset()
            q = qt.createQureg(n, env8)
            with qt.gateFusion(q):
                _apply_program(q, ops)
            amps[mode] = np.asarray(q.amps)
            # §21: predictions are priced on the stream the drain
            # actually executed, so the optimizer cannot introduce drift
            assert T.counter_total("model_drift_total") == 0
        np.testing.assert_allclose(amps["on"], amps["off"], atol=1e-10)

    def test_randomized_parity_batched_bank(self, env):
        # n chosen so 2-qubit gates stay shard-local on the 8-device
        # mesh (nloc = n - 3 >= 2): wider-than-local gates fall out of
        # the batched capture path entirely
        n, B = 6, 3
        ops = _random_program(n, 24, seed=9)
        thetas = np.linspace(0.2, 1.1, B)
        mats = np.stack([
            np.array([[np.cos(a), -np.sin(a)], [np.sin(a), np.cos(a)]],
                     dtype=complex) for a in thetas])
        amps = {}
        for mode in ("on", "off"):
            qt.setCircuitOptimizer(mode)
            bq = qt.createBatchedQureg(n, env, B)
            qt.startGateFusion(bq)
            _apply_program(bq, ops[:12])
            qt.applyBatchedUnitary(bq, (2,), mats)
            qt.pauliX(bq, 1)
            qt.pauliX(bq, 1)
            _apply_program(bq, ops[12:])
            qt.stopGateFusion(bq)
            amps[mode] = np.asarray(bq.amps)
        np.testing.assert_allclose(amps["on"], amps["off"], atol=1e-10)

    def test_density_channel_parity(self, env):
        amps = {}
        for mode in ("on", "off"):
            qt.setCircuitOptimizer(mode)
            q = qt.createDensityQureg(3, env)
            qt.startGateFusion(q)
            qt.hadamard(q, 0)
            qt.controlledNot(q, 0, 1)
            qt.mixDepolarising(q, 0, 0.05)
            qt.pauliX(q, 2)
            qt.pauliX(q, 2)
            qt.mixDamping(q, 1, 0.1)
            qt.stopGateFusion(q)
            amps[mode] = np.asarray(q.amps)
        np.testing.assert_allclose(amps["on"], amps["off"], atol=1e-12)

    def test_cancellation_only_stream_is_bit_identical(self, env):
        """A stream whose only rewrite is an exact-identity cancellation
        must drain BIT-identically to the stream with the pair absent."""
        base = [_g((1,), H), _g((0, 1), CX)]
        pair = [_g((0,), X), _g((0,), X)]

        qt.setCircuitOptimizer("on")
        q1 = qt.createQureg(6, env)
        fusion.start_gate_fusion(q1)
        q1._fusion.gates.extend(base + pair)
        fusion.stop_gate_fusion(q1)

        qt.setCircuitOptimizer("off")
        q2 = qt.createQureg(6, env)
        fusion.start_gate_fusion(q2)
        q2._fusion.gates.extend(base)
        fusion.stop_gate_fusion(q2)

        np.testing.assert_array_equal(np.asarray(q1.amps),
                                      np.asarray(q2.amps))

    def test_everything_cancels_drains_to_initial_state(self, env):
        q = qt.createQureg(6, env)
        with qt.gateFusion(q):
            qt.pauliX(q, 0)
            qt.pauliX(q, 0)
            qt.controlledNot(q, 1, 2)
            qt.controlledNot(q, 1, 2)
        want = np.zeros((2, 64))  # SoA planes of |0...0>
        want[0, 0] = 1.0
        np.testing.assert_array_equal(np.asarray(q.amps), want)

    def test_seeded_measurement_parity_through_run_resumable(
            self, env, tmp_path):
        """Cancel/merge-only rewrites keep the amplitude stream
        bit-identical, so a seeded measurement sequence after a
        run_resumable drain lands on the SAME outcomes on vs off."""
        n = 6
        gates = []
        for t in range(n):
            gates.append(_g((t,), H))
        gates += [_g((0,), X), _g((0,), X),
                  _g((1, 2), CX), _g((1, 2), CX),
                  _g((2,), TG), _g((2,), S)]
        outcomes = {}
        for mode in ("on", "off"):
            qt.setCircuitOptimizer(mode)
            qt.seedQuEST(env, [1234])
            q = qt.createQureg(n, env)
            qt.run_resumable(q, gates, str(tmp_path / f"ck-{mode}"),
                             every=4)
            outcomes[mode] = [qt.measure(q, t) for t in range(n)]
        assert outcomes["on"] == outcomes["off"]


# ---------------------------------------------------------------------------
# Scheduling composition: plan cache, windows, telemetry, reports
# ---------------------------------------------------------------------------


class TestComposition:
    def test_mode_flip_retraces_plan(self, env):
        """The optimizer mode is part of the fusion plan key: flipping it
        must MISS the plan cache (and re-plan), never replay a plan built
        under the other mode."""
        def drain(mode):
            qt.setCircuitOptimizer(mode)
            q = qt.createQureg(4, env)
            with qt.gateFusion(q):
                qt.hadamard(q, 0)
                qt.pauliX(q, 1)
                qt.pauliX(q, 1)
                qt.tGate(q, 2)
            return qt.calcTotalProb(q)

        drain("on")
        before = T.snapshot()["counters"]
        drain("on")     # identical stream + mode: cache hit
        drain("off")    # mode flip: forced miss
        after = T.snapshot()["counters"]

        def delta(name):
            return (sum(after.get(name, {}).values())
                    - sum(before.get(name, {}).values()))

        assert delta("fusion_plan_cache_hits_total") == 1
        assert delta("fusion_plan_cache_misses_total") == 1

    def test_sharded_windows_merged_and_exchange_reduction(self, env8):
        """The acceptance metric: on the pinned merge-across-commuting
        stream, the optimized drain issues FEWER window-remap exchanges
        and records optimizer_windows_merged_total — with zero drift."""
        rng = np.random.default_rng(3)
        g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
        u, _ = np.linalg.qr(g)
        n = 6

        def drain(mode):
            qt.setCircuitOptimizer(mode)
            T.reset()
            q = qt.createQureg(n, env8)
            qt.startGateFusion(q)
            for ts in [(0, 1), (n - 2, n - 1), (0, 1)]:
                qt.multiQubitUnitary(q, list(ts), u)
            qt.stopGateFusion(q)
            amps = np.asarray(q.amps)
            ex = T.counter_sum("exchanges_total", op="window_remap")
            merged = T.counter_total("optimizer_windows_merged_total")
            assert T.counter_total("model_drift_total") == 0
            return amps, ex, merged

        a_off, ex_off, _m0 = drain("off")
        a_on, ex_on, merged = drain("on")
        assert ex_on < ex_off
        assert merged >= 1
        np.testing.assert_allclose(a_on, a_off, atol=1e-12)

    def test_telemetry_counters_and_env_string(self, env):
        qt.setCircuitOptimizer("on")
        q = qt.createQureg(4, env)
        with qt.gateFusion(q):
            qt.pauliX(q, 0)
            qt.pauliX(q, 0)
            qt.hadamard(q, 1)
            qt.hadamard(q, 1)
        snap = T.snapshot()
        removed = snap["counters"].get(
            "optimizer_gates_removed_total", {})
        assert any("kind=cancel" in k for k in removed)
        assert any("kind=merge" in k for k in removed)
        assert sum(removed.values()) >= 3
        assert "optimizer_seconds" in snap["histograms"]
        s = qt.getEnvironmentString(env)
        assert "Optimizer=on" in s
        assert "removed=" in s

    def test_explain_section_and_reports(self, env8, capsys):
        q = qt.createQureg(6, env8)
        qt.startGateFusion(q)
        qt.pauliX(q, 0)
        qt.pauliX(q, 0)
        qt.tGate(q, 4)
        qt.sGate(q, 5)
        rep = introspect.explain_circuit(q)
        opt = rep["optimizer"]
        assert opt["mode"] == "on"
        assert opt["gates_in"] == 4
        assert opt["gates_out"] < opt["gates_in"]
        assert opt["removed"]["cancel"] == 2
        assert opt["tier_savings_bytes"] is not None
        assert opt["exchange_savings"] is not None
        qt.reportCircuitPlan(q)
        out = capsys.readouterr().out
        assert "optimizer: mode=on" in out
        # explain is a dry run: the buffer must still drain afterwards
        qt.stopGateFusion(q)
        T.report_perf(env8)
        out = capsys.readouterr().out
        assert "circuit optimizer" in out

    def test_explain_never_mutates_telemetry(self, env):
        q = qt.createQureg(4, env)
        qt.startGateFusion(q)
        qt.pauliX(q, 0)
        qt.pauliX(q, 0)
        before = T.snapshot()
        introspect.explain_circuit(q)
        assert T.snapshot() == before
        qt.stopGateFusion(q)

    def test_rewrite_cache_hit_skips_recompute(self, env):
        items = [_g((0,), X), _g((0,), X), _g((1,), H)]
        out1, s1 = _opt(items)
        out2, s2 = _opt(list(items))
        assert s1 == s2
        assert [it.targets for it in out1] == \
            [it.targets for it in out2] == [(1,)]
