"""Test harness configuration.

Mirrors the reference test strategy (SURVEY.md §4): the same suite runs on a
virtual multi-device mesh — the analogue of `mpirun -np 8` on one box
(examples/README.md:404-407) — by forcing 8 XLA host-platform devices
BEFORE jax initialises.  Tests compare against a dense NumPy oracle
(tests/oracle.py, the analogue of tests/utilities.cpp QVector/QMatrix) in
double precision.
"""

import os

# CPU selection happens via jax.config.update below (the JAX_PLATFORMS env
# var hangs backend init under the axon relay); the device-count flag must
# still be set before jax initialises.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Belt and braces: a pytest plugin may have imported jax before this conftest,
# in which case the env var alone is too late (the backend isn't initialised
# until first use, so the config update below still wins).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

import quest_tpu as qt  # noqa: E402

qt.set_precision(2)

# Reference suite fixes NUM_QUBITS=5 (tests/utilities.hpp:36)
NUM_QUBITS = 5


@pytest.fixture(scope="session")
def env():
    return qt.createQuESTEnv()


@pytest.fixture
def psi(env):
    q = qt.createQureg(NUM_QUBITS, env)
    qt.initDebugState(q)
    return q


@pytest.fixture
def rho(env):
    q = qt.createDensityQureg(NUM_QUBITS, env)
    qt.initDebugState(q)
    return q
