"""Serving-layer fault tolerance (docs/design.md §27).

Covers the PR's contracts:

- decorrelated-jitter backoff: the quest_tpu-RNG-seeded stream obeys
  the [base, min(64*base, 3*prev)] envelope, reproduces bit-identically
  under ``seed_backoff_jitter``, and replaces retry_io's deterministic
  1-2-4 ladder;
- failure isolation + job-level retry: a transient bank fault dissolves
  the bank (never fails the job), members retry in fresh banks, and a
  job completed under retry is BIT-IDENTICAL to its fault-free run —
  amplitudes, measurement outcomes, and key state (the pinned test);
- retry exhaustion: jobs past their budget fail with a per-job
  :class:`JobFailedError` carrying tenant/id/attempts/cause, surfaced
  identically by ``Job.result()`` and the async ``Service.wait``;
- poison-job quarantine: the watchdog's worst-element attribution on a
  batched bank bisects straight to the culprit (bank-mates complete
  bit-identically, free of retry charge), repeated OOM halves blindly,
  and the per-(tenant, structure) circuit breaker walks
  open -> half-open -> closed;
- elastic degraded-mode failover + mesh heal: host loss mid-run shrinks
  the serving mesh without dropping queued work, ``heal()`` re-expands
  onto the full mesh, and everything still completes bit-identically;
- the qlint fault-vocabulary pin: analysis.rules_trace's
  FaultPlanSpecRule.KINDS must track resilience.FaultPlan._KINDS.
"""

import ast
import asyncio
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as C
from quest_tpu import resilience as R
from quest_tpu import serve as S
from quest_tpu import telemetry as T

N = 4


@pytest.fixture(autouse=True)
def raw_stream(monkeypatch):
    """Window-stepped serving always runs with the optimizer suppressed;
    baselines here must be raw too (tests/test_serve.py rationale)."""
    monkeypatch.setenv("QT_OPTIMIZER", "off")
    from quest_tpu import optimizer as _opt
    _opt.clear_cache()
    yield


@pytest.fixture(autouse=True)
def fast_seeded_backoff(monkeypatch):
    """Millisecond backoff so retries finish inside the step bounds, and
    a pinned jitter stream so every test run draws the same delays."""
    monkeypatch.setenv(R._RETRY_BASE_ENV, "0.001")
    R.seed_backoff_jitter([20260805])
    yield
    R._JITTER_RNG[0] = None


def _h(t):
    m = np.array([[1.0, 1.0], [1.0, -1.0]]) / np.sqrt(2.0)
    return C.Gate((t,), np.stack([m, np.zeros((2, 2))]))


def _rz(t, theta):
    d = np.exp(1j * np.array([-theta / 2, theta / 2]))
    return C.Gate((t,), np.stack([np.diag(d.real), np.diag(d.imag)]))


def _circ(theta, depth=3, n=N):
    gates = []
    for d in range(depth):
        for q in range(n):
            gates.append(_h(q))
            gates.append(_rz(q, theta + 0.1 * q + d))
    return gates


def _snapshot(job):
    return {
        "amps": np.asarray(job.amps).tobytes(),
        "outcomes": tuple(job.outcomes),
        "key": np.asarray(job.key_state["key"]).tobytes(),
        "counter": int(job.key_state["counter"]),
    }


def _run_trace(env, thetas, *, faults=None, measure=(0, 2), **kw):
    """Submit one deterministic trace and drain it; returns the jobs."""
    srv = S.SimServer(env, window=4, max_batch=8, faults=faults, **kw)
    try:
        jobs = [srv.submit(_circ(t), num_qubits=N, seed=100 + i,
                           measure=measure)
                for i, t in enumerate(thetas)]
        srv.run_until_idle(max_steps=800)
        return jobs, srv.stats()
    finally:
        srv.close()


class TestBackoffJitter:
    def test_envelope(self):
        base = 0.01
        prev = None
        for _ in range(50):
            d = R.backoff_delay(base, prev)
            lo, hi = base, max(base, min(64 * base,
                                         3 * (prev or base)))
            assert lo <= d <= hi
            prev = d

    def test_cap_at_64x_base(self):
        base = 0.01
        d = base
        for _ in range(100):
            d = R.backoff_delay(base, d)
            assert d <= 64 * base

    def test_deterministic_under_seed(self):
        R.seed_backoff_jitter([7])
        a = [R.backoff_delay(0.01, None) for _ in range(10)]
        R.seed_backoff_jitter([7])
        b = [R.backoff_delay(0.01, None) for _ in range(10)]
        R.seed_backoff_jitter([8])
        c = [R.backoff_delay(0.01, None) for _ in range(10)]
        assert a == b
        assert a != c

    def test_chaos_seed_env_pins_stream(self, monkeypatch):
        monkeypatch.setenv(R._CHAOS_SEED_ENV, "424242")
        R.seed_backoff_jitter()
        a = [R.backoff_delay(0.01, None) for _ in range(5)]
        R.seed_backoff_jitter()
        assert a == [R.backoff_delay(0.01, None) for _ in range(5)]

    def test_jitter_stream_is_not_the_measurement_stream(self):
        from quest_tpu import rng as _rng
        R.backoff_delay(0.01, None)
        assert R._JITTER_RNG[0] is not None
        assert R._JITTER_RNG[0] is not _rng.GLOBAL_RNG

    def test_retry_io_sleeps_jittered_not_ladder(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(R.time, "sleep", sleeps.append)
        R._ACTIVE_FAULTS[0] = R.FaultPlan("io@3")
        try:
            R.seed_backoff_jitter([99])
            out = R.retry_io(lambda: "ok", attempts=4,
                             base_delay=0.001)
        finally:
            R._ACTIVE_FAULTS[0] = None
        assert out == "ok"
        assert len(sleeps) == 3
        # NOT the old deterministic 1-2-4 ladder...
        assert sleeps != [0.001, 0.002, 0.004]
        # ...but inside its bounded envelope, and reproducible
        prev = None
        for d in sleeps:
            assert 0.001 <= d <= max(0.001, 3 * (prev or 0.001))
            prev = d
        replay = []
        monkeypatch.setattr(R.time, "sleep", replay.append)
        R._ACTIVE_FAULTS[0] = R.FaultPlan("io@3")
        try:
            R.seed_backoff_jitter([99])
            R.retry_io(lambda: "ok", attempts=4, base_delay=0.001)
        finally:
            R._ACTIVE_FAULTS[0] = None
        assert replay == sleeps


class TestTransientRetry:
    THETAS = (0.3, 0.45, 0.6)

    def test_bank_fault_dissolves_and_completes_bit_identical(self, env):
        baseline, _ = _run_trace(env, self.THETAS)
        T.reset()
        jobs, stats = _run_trace(
            env, self.THETAS, faults=R.FaultPlan("bank_fault@1"))
        assert T.counter_sum("serve_bank_retries_total",
                             reason="transient") >= 1
        for b, j in zip(baseline, jobs):
            assert j.state == S.DONE
            assert j.attempts == 2          # one fault, one clean rerun
            assert j.errors and "injected bank fault" in j.errors[0]
            assert _snapshot(j) == _snapshot(b)
        assert stats["queued"] == 0 and stats["banks"] == 0

    def test_retry_gated_behind_backoff(self, env):
        jobs, _ = _run_trace(env, self.THETAS,
                             faults=R.FaultPlan("bank_fault@1"))
        assert all(j.backoff is not None and j.backoff >= 0.001
                   for j in jobs)

    def test_exhaustion_fails_with_error_chain(self, env):
        jobs, stats = _run_trace(env, (0.3,), retries=0,
                                 faults=R.FaultPlan("bank_fault@1"))
        (job,) = jobs
        assert job.state == S.FAILED
        assert job.attempts == 1
        assert len(job.errors) == 1
        with pytest.raises(S.JobFailedError) as ei:
            job.result()
        err = ei.value
        assert err.tenant == "default" and err.jid == job.id
        assert err.attempts == 1
        assert isinstance(err.cause, TimeoutError)
        # each result() call wraps fresh — per-job, never a shared raise
        with pytest.raises(S.JobFailedError) as ei2:
            job.result()
        assert ei2.value is not err and ei2.value.cause is err.cause
        assert stats["queued"] == 0

    def test_service_wait_raises_jobfailederror(self, env):
        async def main():
            srv = S.SimServer(env, window=4, max_batch=8, retries=0,
                              faults=R.FaultPlan("bank_fault@1"))
            try:
                async with S.Service(srv, idle_sleep=0.0005) as svc:
                    job = await svc.submit(_circ(0.3), num_qubits=N)
                    with pytest.raises(S.JobFailedError) as ei:
                        await svc.wait(job)
                    return ei.value
            finally:
                srv.close()

        err = asyncio.run(main())
        assert isinstance(err.cause, TimeoutError)


class TestPoisonQuarantine:
    THETAS = (0.2, 0.35, 0.5, 0.65)

    def test_worst_element_attribution_quarantines_culprit(self, env):
        baseline, _ = _run_trace(env, self.THETAS, watchdog=1)
        # job ids are per-server: the same trace reuses the same ids
        poison_id = baseline[2].id
        T.reset()
        jobs, stats = _run_trace(
            env, self.THETAS, watchdog=1,
            faults=R.FaultPlan(f"poison_job@{poison_id}"))
        assert jobs[2].id == poison_id
        # the culprit bisected straight to a singleton and quarantined
        assert jobs[2].state == S.FAILED
        with pytest.raises(S.JobFailedError) as ei:
            jobs[2].result()
        assert isinstance(ei.value.cause, R.NumericalHealthError)
        # bank-mates completed BIT-IDENTICALLY, uncharged by the poison
        for k in (0, 1, 3):
            assert jobs[k].state == S.DONE
            assert _snapshot(jobs[k]) == _snapshot(baseline[k])
        assert T.counter_sum("serve_jobs_quarantined_total",
                             tenant="default") == 1
        assert T.counter_sum("serve_bank_retries_total",
                             reason="poison") >= 1
        assert stats["queued"] == 0 and stats["banks"] == 0

    def test_health_error_carries_worst_element(self, env):
        from quest_tpu import batch as B
        q = B.createBatchedQureg(N, env, 4, seeds=[1, 2, 3, 4])
        amps = q._amps_raw()
        amps = amps.at[2, 0, 3].set(np.nan)
        q._set_amps_permuted(amps, q._perm)
        norm, finite, elem = R.check_bank_health(q)
        assert not finite and elem == 2

    def test_repeated_oom_bisects_blind_and_all_complete(self, env):
        baseline, _ = _run_trace(env, self.THETAS)
        T.reset()
        # two armed events burn the governor net's single retry: the
        # verdict is repeated-OOM with no element attribution -> halve
        jobs, stats = _run_trace(env, self.THETAS,
                                 faults=R.FaultPlan("oom@1,oom@1"))
        for b, j in zip(baseline, jobs):
            assert j.state == S.DONE
            assert _snapshot(j) == _snapshot(b)
        assert T.counter_sum("serve_bank_retries_total",
                             reason="poison") >= 1
        assert stats["queued"] == 0 and stats["banks"] == 0

    def test_breaker_lifecycle_unit(self):
        br = S._Breaker(2, 30.0)
        assert br.admits() and br.state == "closed"
        br.record_failure()
        assert br.admits()
        br.record_failure()
        assert br.state == "open" and not br.admits()
        br.open_seconds = 0.0
        assert br.admits()              # the half-open probe slot
        assert br.state == "half_open"
        assert not br.admits()          # only ONE probe at a time
        br.record_success()
        assert br.state == "closed" and br.admits()
        # a half-open probe that fails re-opens immediately
        br.record_failure()
        br.record_failure()
        br.open_seconds = 0.0
        assert br.admits()
        br.record_failure()
        assert br.state == "open"

    def test_quarantine_opens_breaker_per_tenant_structure(self, env):
        srv = S.SimServer(env, window=4, max_batch=8, watchdog=1,
                          quarantine=(1, 3600.0))
        try:
            bad = srv.submit(_circ(0.4), num_qubits=N, tenant="eve")
            srv.faults = R.FaultPlan(f"poison_job@{bad.id}")
            srv.run_until_idle(max_steps=400)
            assert bad.state == S.FAILED
            # same tenant + structure: breaker is OPEN -> rejected
            with pytest.raises(S.QuotaExceededError) as ei:
                srv.submit(_circ(0.4), num_qubits=N, tenant="eve")
            assert ei.value.kind == "quarantine"
            # another tenant's identical structure is unaffected
            ok = srv.submit(_circ(0.4), num_qubits=N, tenant="bob")
            # a DIFFERENT structure from the quarantined tenant too
            ok2 = srv.submit(_circ(0.4, depth=1), num_qubits=N,
                             tenant="eve")
            srv.faults = None
            srv.run_until_idle(max_steps=400)
            assert ok.state == S.DONE and ok2.state == S.DONE
            # after open_seconds the breaker half-opens: one probe
            # admitted, and its completion closes the breaker
            (br,) = [b for (t, _k), b in srv._breakers.items()
                     if t == "eve"]
            br.open_seconds = 0.0
            probe = srv.submit(_circ(0.4), num_qubits=N, tenant="eve")
            srv.run_until_idle(max_steps=400)
            assert probe.state == S.DONE
            assert br.state == "closed"
        finally:
            srv.close()


def _assert_same_result(job, base):
    """Degraded-mesh completion check: this suite runs at precision 2
    (conftest), where the sharded BATCHED einsum's reduction order — and
    so the last ulp — depends on the device count, so a job that ran
    windows on the shrunk mesh is compared to within that drift.  The
    strict cross-mesh bit-identity pin for the full failover/heal
    drain-and-regrow path is the chaos harness (scripts/chaos_serve.py,
    default precision, where the batched path IS bit-identical across
    mesh shapes)."""
    assert np.allclose(np.asarray(job.amps), np.asarray(base.amps),
                       rtol=0.0, atol=1e-13)
    assert [o for o, _p in job.outcomes] == [o for o, _p in
                                             base.outcomes]
    assert np.allclose([p for _o, p in job.outcomes],
                       [p for _o, p in base.outcomes],
                       rtol=0.0, atol=1e-13)


class TestFailoverHeal:
    THETAS = (0.25, 0.4, 0.55, 0.7, 0.85)

    def test_host_loss_then_heal_all_complete(self, env):
        baseline, _ = _run_trace(env, self.THETAS)
        T.reset()
        jobs, stats = _run_trace(
            env, self.THETAS,
            faults=R.FaultPlan("host_loss@3,heal@6"))
        for b, j in zip(baseline, jobs):
            assert j.state == S.DONE
            _assert_same_result(j, b)
        # healed back onto the full mesh, not still degraded
        assert not stats["degraded"]
        assert stats["devices"] == env.num_devices
        assert T.counter_total("serve_failovers_total") == 1
        assert T.counter_total("serve_heals_total") == 1
        assert T.gauge_max("serve_degraded") == 0.0
        assert T.gauge_max("serve_failover_mttr_seconds") is not None

    def test_post_heal_results_bit_identical(self, env):
        """The pinned heal contract: once healed, serving is back at
        full fidelity — jobs run on the healed mesh are BIT-IDENTICAL
        to the fault-free run, not merely close."""
        baseline, _ = _run_trace(env, self.THETAS)
        srv = S.SimServer(env, window=4, max_batch=8,
                          faults=R.FaultPlan("host_loss@0,heal@1"))
        try:
            # the loss and the heal both fire while the queue is empty
            for _ in range(2):
                srv.step()
            assert srv.stats()["devices"] == env.num_devices
            assert not srv.stats()["degraded"]
            jobs = [srv.submit(_circ(t), num_qubits=N, seed=100 + i,
                               measure=(0, 2))
                    for i, t in enumerate(self.THETAS)]
            srv.run_until_idle(max_steps=800)
        finally:
            srv.close()
        for b, j in zip(baseline, jobs):
            assert j.state == S.DONE
            assert _snapshot(j) == _snapshot(b)

    def test_degraded_serving_without_heal_still_completes(self, env):
        baseline, _ = _run_trace(env, self.THETAS)
        T.reset()
        jobs, stats = _run_trace(env, self.THETAS,
                                 faults=R.FaultPlan("shard_loss@2"))
        for b, j in zip(baseline, jobs):
            assert j.state == S.DONE
            _assert_same_result(j, b)
        # still on the shrunk mesh: degraded is VISIBLE, not silent
        assert stats["degraded"]
        assert stats["devices"] == env.num_devices // 2
        assert T.gauge_max("serve_degraded") == 1.0

    def test_heal_is_idempotent_when_not_degraded(self, env):
        srv = S.SimServer(env, window=4, max_batch=8)
        try:
            assert srv.heal() is False
        finally:
            srv.close()

    def test_failover_reprices_admission_on_live_env(self, env):
        srv = S.SimServer(env, window=4, max_batch=8,
                          faults=R.FaultPlan("shard_loss@1"))
        try:
            before = S._job_bytes_per_device(N, srv.env, False)
            srv.submit(_circ(0.3), num_qubits=N)
            srv.run_until_idle(max_steps=400)
            after = S._job_bytes_per_device(N, srv.env, False)
            # half the devices -> each holds twice the bytes
            assert after == 2 * before
        finally:
            srv.close()


class TestObservability:
    """§30: request traces, the flight recorder, and the /metrics front
    door, exercised through the real serve lifecycle (docs/design.md)."""

    THETAS = (0.3, 0.45, 0.6)

    def test_retried_job_trace_complete_and_well_nested(self, env):
        srv = S.SimServer(env, window=4, max_batch=8,
                          faults=R.FaultPlan("bank_fault@1"))
        try:
            jobs = [srv.submit(_circ(t), num_qubits=N, seed=100 + i,
                               measure=(0, 2))
                    for i, t in enumerate(self.THETAS)]
            srv.run_until_idle(max_steps=800)
            for j in jobs:
                assert j.state == S.DONE and j.attempts == 2
                tz = srv.tracez(j)
                assert tz["complete"] and not tz["open"]
                names = [e["name"] for e in tz["events"]]
                # the root "job" span opens first, the retry of the
                # killed bank is VISIBLE, and the lifecycle markers are
                # causally ordered admit -> retry -> complete
                assert names[0] == "job"
                assert names.count("serve.bank_join") == 2  # two banks
                assert names.index("serve.admit") \
                    < names.index("serve.retry") \
                    < names.index("serve.complete")
                assert "serve.window" in names
                # well-nested: ONE root span, everything else inside it
                roots = tz["tree"]
                assert len(roots) == 1 and roots[0]["name"] == "job"
                assert roots[0]["args"]["status"] == "done"
                assert len(roots[0]["children"]) == len(names) - 1
                # integer ids resolve to this server's traces too
                assert srv.tracez(j.id) == tz
        finally:
            srv.close()

    def test_quarantine_writes_parseable_flight_dump(self, env, tmp_path,
                                                     monkeypatch):
        monkeypatch.setenv(S._FLIGHT_DIR_ENV, str(tmp_path))
        srv = S.SimServer(env, window=4, max_batch=8, watchdog=1,
                          quarantine=(1, 3600.0))
        try:
            bad = srv.submit(_circ(0.4), num_qubits=N, tenant="eve")
            srv.faults = R.FaultPlan(f"poison_job@{bad.id}")
            srv.run_until_idle(max_steps=400)
            assert bad.state == S.FAILED
            assert srv.flight_dumps
            docs = []
            for p in srv.flight_dumps:
                with open(p) as f:
                    docs.append(json.load(f))
        finally:
            srv.close()
        (doc,) = [d for d in docs if d["reason"] == "quarantine"]
        assert doc["context"]["tenant"] == "eve"
        assert doc["context"]["job"] == bad.id
        assert doc["context"]["trace_id"] == bad.trace_id
        # the ring captured the incident's lead-up: the bisect verdict
        # and the quarantine lifecycle event itself
        kinds = [e["kind"] for e in doc["events"]]
        assert "bisect" in kinds
        assert any(e.get("name") == "serve.quarantine"
                   for e in doc["events"])

    def test_metrics_endpoint_byte_matches_exposition(self, env):
        srv = S.SimServer(env, window=4, max_batch=8)
        try:
            host, port = srv.serve_http()
            # idempotent: a second call returns the SAME address
            assert srv.serve_http() == (host, port)
            job = srv.submit(_circ(0.3), num_qubits=N, seed=100,
                             measure=(0, 2))
            srv.run_until_idle(max_steps=400)
            base = f"http://{host}:{port}"
            body = urllib.request.urlopen(base + "/metrics",
                                          timeout=10).read()
            assert body == T.prometheus_text().encode("utf-8")
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as r:
                hz = json.load(r)
            assert hz["status"] == "ok" and hz["queue_depth"] == 0
            assert hz["completed"] == 1 and hz["devices"] >= 1
            with urllib.request.urlopen(
                    base + f"/tracez/{job.trace_id}", timeout=10) as r:
                tz = json.load(r)
            assert tz["complete"]
            assert tz == srv.tracez(job)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/tracez/nope", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.close()


class TestLintFaultVocabulary:
    def test_rule_kinds_track_faultplan(self):
        from quest_tpu.analysis import rules_trace as RT
        assert set(RT.FaultPlanSpecRule.KINDS) == set(R.FaultPlan._KINDS)

    def test_rule_flags_unknown_kind(self):
        from quest_tpu.analysis import rules_trace as RT
        rule = RT.FaultPlanSpecRule()
        src = "plan = FaultPlan('kill@2,bogus@3')\n"
        findings = list(rule.check(ast.parse(src), src, "quest_tpu/x.py"))
        assert any("bogus" in f.message for f in findings)
        clean = "plan = FaultPlan('bank_fault@2,poison_job@1')\n"
        assert not list(rule.check(ast.parse(clean), clean,
                                   "quest_tpu/x.py"))
