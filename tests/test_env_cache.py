"""Compilation-cache wiring decisions (env._enable_compilation_cache):
opt-out env var, user-configured locations respected, CPU-backend skip
(cross-host AOT entries can SIGILL)."""

import jax
import pytest

import quest_tpu as qt
from quest_tpu import env as E


@pytest.fixture(autouse=True)
def _reset_wired(monkeypatch):
    monkeypatch.setattr(E, "_CACHE_WIRED", [False])
    yield


def _configured():
    return jax.config.jax_compilation_cache_dir


def test_opt_out(monkeypatch):
    monkeypatch.setenv("QT_NO_COMPILE_CACHE", "1")
    before = _configured()
    E._enable_compilation_cache()
    assert _configured() == before
    assert E._CACHE_WIRED == [False]  # may re-wire later without opt-out


def test_respects_user_jax_env_var(monkeypatch):
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/userspot")
    before = _configured()
    E._enable_compilation_cache()
    assert _configured() == before  # never overridden


def test_cpu_backend_skipped_by_default(monkeypatch):
    monkeypatch.delenv("QT_NO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("QT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("QT_COMPILE_CACHE_DIR", raising=False)
    if jax.config.jax_compilation_cache_dir:
        pytest.skip("cache already configured in this session")
    assert jax.default_backend() == "cpu"  # test harness forces CPU
    E._enable_compilation_cache()
    assert _configured() is None


def test_explicit_dir_forces_on_cpu(monkeypatch, tmp_path):
    monkeypatch.delenv("QT_NO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("QT_COMPILE_CACHE", raising=False)
    if jax.config.jax_compilation_cache_dir:
        pytest.skip("cache already configured in this session")
    monkeypatch.setenv("QT_COMPILE_CACHE_DIR", str(tmp_path / "qc"))
    try:
        E._enable_compilation_cache()
        assert _configured() == str(tmp_path / "qc")
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_qt_compile_cache_var_wires_and_reports(monkeypatch, tmp_path):
    """QT_COMPILE_CACHE=<dir> (the canonical spelling; *_DIR kept as an
    alias) wires the persistent cache anywhere — including CPU — and the
    hit/miss counters surface through getEnvironmentString."""
    monkeypatch.delenv("QT_NO_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("QT_COMPILE_CACHE_DIR", raising=False)
    if jax.config.jax_compilation_cache_dir:
        pytest.skip("cache already configured in this session")
    cache_dir = str(tmp_path / "qc2")
    monkeypatch.setenv("QT_COMPILE_CACHE", cache_dir)
    try:
        E._enable_compilation_cache()
        assert _configured() == cache_dir
        stats = E.compile_cache_stats()
        assert stats["dir"] == cache_dir
        env = qt.createQuESTEnv()
        s = qt.getEnvironmentString(env)
        assert f"CompileCache={cache_dir}" in s
        assert "hits=" in s and "misses=" in s
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        E._CACHE_STATS["dir"] = None


def test_environment_string_reports_exchange_config(monkeypatch):
    env = qt.createQuESTEnv()
    monkeypatch.delenv("QT_EXCHANGE_CHUNKS", raising=False)
    assert "ExchangeChunks=auto" in qt.getEnvironmentString(env)
    monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "4")
    assert "ExchangeChunks=4" in qt.getEnvironmentString(env)
