"""Multi-tenant serving layer (quest_tpu/serve.py).

Covers the PR's contracts:

- continuous batching: arrivals between fusion windows coalesce into a
  bucket's next bank instead of waiting for a global drain, and the
  served results are bit-identical to EnsembleScheduler.drain of the
  same circuits;
- admission control: structured QuotaExceededError on every limit
  (global backpressure, per-tenant pending, per-tenant analytic bytes,
  governor budget) — never unbounded queueing;
- scheduling: strict interactive-before-batch classes and weighted
  fair sharing between tenants within a class;
- preempt-to-checkpoint: a long batch job preempted by an interactive
  burst resumes BIT-IDENTICALLY to its uninterrupted run — amplitudes,
  live permutation path (same fused windows), per-element measurement
  key bank, and shot counters (the pinned test);
- the EnsembleScheduler occupancy fix: the batch_occupancy gauge
  aggregates real/padded over every bucket of a drain (padding
  excluded) instead of being overwritten by the last bucket;
- the async Service front end and the reportPerf serving section.
"""

import asyncio

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import batch as B
from quest_tpu import circuit as C
from quest_tpu import serve as S
from quest_tpu import telemetry as T

N = 4


@pytest.fixture(autouse=True)
def raw_stream(monkeypatch):
    """Serving pins window-stepped execution bit-identical to a plain
    drain of the SAME literal gate stream.  Window-stepped drains always
    run with the circuit optimizer suppressed (optimizer.suppressed —
    the checkpoint cursor indexes raw gates and resume may change
    mesh/perm), so the plain-drain baselines here must be raw too; the
    optimizer's own parity contracts live in tests/test_optimizer.py."""
    monkeypatch.setenv("QT_OPTIMIZER", "off")
    from quest_tpu import optimizer as _opt
    _opt.clear_cache()
    yield


def _h(t):
    m = np.array([[1.0, 1.0], [1.0, -1.0]]) / np.sqrt(2.0)
    return C.Gate((t,), np.stack([m, np.zeros((2, 2))]))


def _rz(t, theta):
    d = np.exp(1j * np.array([-theta / 2, theta / 2]))
    return C.Gate((t,), np.stack([np.diag(d.real), np.diag(d.imag)]))


def _circ(theta, depth=3, n=N):
    gates = []
    for d in range(depth):
        for q in range(n):
            gates.append(_h(q))
            gates.append(_rz(q, theta + 0.1 * q + d))
    return gates


@pytest.fixture
def server(env):
    srv = S.SimServer(env, window=4, max_batch=8)
    yield srv
    srv.close()


class TestSubmitAndResults:
    def test_results_match_ensemble_drain(self, env, server):
        thetas = [0.3 + 0.05 * i for i in range(5)]
        jobs = [server.submit(_circ(t), num_qubits=N, seed=i)
                for i, t in enumerate(thetas)]
        server.run_until_idle(max_steps=500)
        sched = B.EnsembleScheduler(N, env, max_batch=8)
        for t in thetas:
            sched.submit(_circ(t))
        expected = sched.drain()
        for job, exp in zip(jobs, expected):
            assert job.state == S.DONE
            assert np.array_equal(np.asarray(job.amps), np.asarray(exp))

    def test_result_before_completion_raises(self, server):
        job = server.submit(_circ(0.1), num_qubits=N)
        with pytest.raises(qt.QuESTError, match="before completion"):
            job.result()
        server.run_until_idle(max_steps=500)
        assert job.result() is job.amps

    def test_measurement_schedule_runs_per_element_streams(
            self, env, server):
        jobs = [server.submit(_circ(0.4), num_qubits=N, seed=7 + i,
                              measure=(0, 2))
                for i in range(3)]
        server.run_until_idle(max_steps=500)
        for job in jobs:
            assert len(job.outcomes) == 2
            assert all(o in (0, 1) for o, _p in job.outcomes)
            assert job.key_state["counter"] == 2
        # outcome streams are seed-keyed: a standalone bank seeded the
        # way the server seeds its padded bank (pad repeats the last
        # element) draws the exact same outcomes per element
        q = B.createBatchedQureg(N, env, 4, seeds=[7, 8, 9, 9])
        for g in _circ(0.4):
            q._fusion.gates.append(g)
        rounds = [B.measureBatched(q, t)[0] for t in (0, 2)]
        for i, job in enumerate(jobs):
            got = [o for o, _ in job.outcomes]
            assert got == [int(rounds[0][i]), int(rounds[1][i])]

    def test_mixed_structures_bucket_separately(self, server):
        a = server.submit(_circ(0.2, depth=1), num_qubits=N)
        b = server.submit(_circ(0.2, depth=2), num_qubits=N)
        c = server.submit(_circ(0.9, depth=1), num_qubits=N)
        server.run_until_idle(max_steps=500)
        assert a.state == b.state == c.state == S.DONE
        assert not np.array_equal(np.asarray(a.amps), np.asarray(b.amps))


class TestContinuousBatching:
    def test_arrival_mid_flight_coalesces_into_next_bank(self, server):
        T.reset()
        first = server.submit(_circ(0.1, depth=6), num_qubits=N)
        server.step()  # starts bank 0, runs its first window
        # arrivals while bank 0 is mid-flight: same fingerprint, so
        # they coalesce into the bucket's NEXT bank — no global drain
        late = [server.submit(_circ(0.1 + 0.01 * i, depth=6),
                              num_qubits=N) for i in range(3)]
        server.run_until_idle(max_steps=500)
        assert first.state == S.DONE
        assert all(j.state == S.DONE for j in late)
        snap = T.snapshot()
        # exactly two banks: the mid-flight one and one for all three
        # late arrivals (batch-at-once would have run each separately)
        assert snap["counters"]["serve_banks_total"][""] == 2

    def test_open_bank_absorbs_arrivals_before_first_window(self, server):
        T.reset()
        for i in range(3):
            server.submit(_circ(0.5), num_qubits=N, seed=i)
        server.run_until_idle(max_steps=500)
        snap = T.snapshot()
        assert snap["counters"]["serve_banks_total"][""] == 1
        # 3 real jobs in a padded-to-4 bank
        assert snap["gauges"]["serve_bank_occupancy"][""] == 0.75

    def test_per_tenant_bank_occupancy_gauge(self, server):
        T.reset()
        server.submit(_circ(0.5), num_qubits=N, tenant="a")
        server.submit(_circ(0.6), num_qubits=N, tenant="a")
        server.submit(_circ(0.7), num_qubits=N, tenant="b")
        server.run_until_idle(max_steps=500)
        snap = T.snapshot()
        occ = snap["gauges"]["bank_occupancy"]
        assert occ["tenant=a"] == 0.5   # 2 of the padded 4
        assert occ["tenant=b"] == 0.25


class TestAdmissionControl:
    def test_global_backpressure(self, env):
        srv = S.SimServer(env, window=4, max_batch=8, max_pending=2)
        try:
            srv.submit(_circ(0.1), num_qubits=N)
            srv.submit(_circ(0.2), num_qubits=N)
            with pytest.raises(S.QuotaExceededError) as ei:
                srv.submit(_circ(0.3), num_qubits=N)
            assert ei.value.kind == "backpressure"
            assert ei.value.limit == 2
        finally:
            srv.close()

    def test_tenant_pending_quota(self, server):
        server.register_tenant("small", max_pending=1)
        server.submit(_circ(0.1), num_qubits=N, tenant="small")
        with pytest.raises(S.QuotaExceededError) as ei:
            server.submit(_circ(0.2), num_qubits=N, tenant="small")
        assert ei.value.kind == "pending"
        assert ei.value.tenant == "small"
        # other tenants are unaffected
        server.submit(_circ(0.2), num_qubits=N, tenant="other")
        # completing the backlog frees the quota
        server.run_until_idle(max_steps=500)
        server.submit(_circ(0.3), num_qubits=N, tenant="small")

    def test_tenant_byte_quota_analytic_pricing(self, env, server):
        one_job = S._job_bytes_per_device(N, env, False)
        server.register_tenant("capped", max_bytes=one_job)
        server.submit(_circ(0.1), num_qubits=N, tenant="capped")
        with pytest.raises(S.QuotaExceededError) as ei:
            server.submit(_circ(0.2), num_qubits=N, tenant="capped")
        assert ei.value.kind == "bytes"
        assert ei.value.value == 2 * one_job

    def test_rejections_are_counted(self, env):
        T.reset()
        srv = S.SimServer(env, window=4, max_batch=8, max_pending=1)
        try:
            srv.submit(_circ(0.1), num_qubits=N, tenant="t")
            with pytest.raises(S.QuotaExceededError):
                srv.submit(_circ(0.2), num_qubits=N, tenant="t")
        finally:
            srv.close()
        assert T.counter_sum("serve_jobs_rejected_total",
                             kind="backpressure") == 1


class TestScheduling:
    def test_interactive_runs_before_batch_backlog(self, server):
        long_jobs = [server.submit(_circ(0.1 + i, depth=8), num_qubits=N)
                     for i in range(2)]
        vip = server.submit(_circ(0.9, depth=1), num_qubits=N,
                            priority=S.INTERACTIVE, tenant="vip")
        # the interactive job must complete within its own bank's
        # window count — it never waits for the batch backlog
        steps = 0
        while not vip.done and steps < 50:
            server.step()
            steps += 1
        assert vip.state == S.DONE
        assert any(not j.done for j in long_jobs)
        server.run_until_idle(max_steps=500)
        assert all(j.state == S.DONE for j in long_jobs)

    def test_weighted_fair_shares_windows(self, env):
        srv = S.SimServer(env, window=2, max_batch=2)
        try:
            srv.register_tenant("heavy", weight=4.0)
            srv.register_tenant("light", weight=1.0)
            # same depth per job; distinct structures so the tenants
            # never share a bank
            for i in range(4):
                srv.submit(_circ(0.1 * i, depth=4), num_qubits=N,
                           tenant="heavy")
                srv.submit(_circ(0.1 * i, depth=5), num_qubits=N,
                           tenant="light")
            srv.run_until_idle(max_steps=1000)
            h = srv.tenants["heavy"]
            li = srv.tenants["light"]
            assert h.completed == li.completed == 4
            # fair share: equal work means the heavier tenant ends at
            # ~1/4 the virtual time of the lighter one
            assert h.vtime < li.vtime
        finally:
            srv.close()

    def test_vtime_catches_up_after_idle(self, server):
        server.register_tenant("busy")
        for i in range(3):
            server.submit(_circ(0.2 * i, depth=4), num_qubits=N,
                          tenant="busy")
        server.run_until_idle(max_steps=500)
        busy_vt = server.tenants["busy"].vtime
        assert busy_vt > 0
        # a newcomer does not get credit for the time it was absent
        server.submit(_circ(0.7), num_qubits=N, tenant="newcomer")
        assert server.tenants["newcomer"].vtime >= busy_vt


class TestPreemption:
    def _run_long_job(self, env, interrupt: bool, mode="checkpoint"):
        """One long low-priority job, optionally interrupted by an
        interactive burst after 3 windows; returns its results."""
        srv = S.SimServer(env, window=4, max_batch=8, preempt=mode)
        try:
            job = srv.submit(_circ(0.5, depth=6), num_qubits=N,
                             tenant="batchy", seed=11, measure=(0, 2))
            for _ in range(3):
                srv.step()
            if interrupt:
                burst = [srv.submit(_circ(1.5, depth=1), num_qubits=N,
                                    tenant="vip", seed=40 + i,
                                    priority=S.INTERACTIVE)
                         for i in range(2)]
                while not all(b.done for b in burst):
                    srv.step()
                assert all(b.state == S.DONE for b in burst)
            srv.run_until_idle(max_steps=500)
            assert job.state == S.DONE
            return (np.asarray(job.amps).copy(), list(job.outcomes),
                    dict(job.key_state))
        finally:
            srv.close()

    def test_preempt_to_checkpoint_resume_bit_identical(self, env):
        """THE pinned preemption contract: a long job preempted to a
        checkpoint by an interactive burst and resumed is bit-identical
        to the uninterrupted run — final amplitudes (via the same
        window plan and live-perm path), measurement outcomes and
        probabilities, the per-element RNG key bank, and the shot
        counters."""
        amps_a, out_a, key_a = self._run_long_job(env, interrupt=False)
        T.reset()
        amps_b, out_b, key_b = self._run_long_job(env, interrupt=True)
        assert np.array_equal(amps_a, amps_b)
        assert out_a == out_b
        assert key_a == key_b          # key bank AND shot counter
        snap = T.snapshot()
        assert snap["counters"]["preemptions_total"][
            "mode=checkpoint"] >= 1
        assert snap["counters"]["serve_resumes_total"][""] >= 1

    def test_pause_mode_is_also_bit_identical(self, env):
        amps_a, out_a, key_a = self._run_long_job(
            env, interrupt=False, mode="pause")
        amps_b, out_b, key_b = self._run_long_job(
            env, interrupt=True, mode="pause")
        assert np.array_equal(amps_a, amps_b)
        assert out_a == out_b and key_a == key_b

    def test_preempt_off_disables_preemption(self, env):
        T.reset()
        srv = S.SimServer(env, window=4, max_batch=8, preempt="off")
        try:
            srv.submit(_circ(0.5, depth=6), num_qubits=N)
            srv.step()
            srv.submit(_circ(1.5, depth=1), num_qubits=N,
                       priority=S.INTERACTIVE)
            srv.run_until_idle(max_steps=500)
        finally:
            srv.close()
        assert T.counter_total("preemptions_total") == 0


class TestOccupancyAccounting:
    def test_drain_gauge_aggregates_across_buckets(self, env):
        """The satellite fix: two buckets (5/8 and 1/1) used to leave
        whichever ran LAST in the batch_occupancy gauge; now the gauge
        is the padding-excluded aggregate over the whole drain."""
        T.reset()
        sched = B.EnsembleScheduler(N, env, max_batch=8)
        for i in range(5):
            sched.submit(_circ(0.1 * i))       # one structure: 5/8
        sched.submit(_circ(0.9, depth=1))      # another: 1/1
        sched.drain()
        snap = T.snapshot()
        assert snap["gauges"]["batch_occupancy"][""] == \
            pytest.approx(6 / 9)
        # per-bucket histogram still records both buckets
        hist = snap["histograms"]["ensemble_bucket_occupancy"][""]
        assert hist["count"] == 2

    def test_bank_occupancy_with_real_count(self):
        class Fake:
            batch_size = 8

        occ = B.bank_occupancy(Fake(), real=5)
        assert occ == {"size": 5, "bucket": 8, "occupancy": 5 / 8}


class TestWindowExecutor:
    def test_executor_matches_monolithic_drain(self, env):
        gates = _circ(0.3, depth=5)
        q1 = qt.createQureg(N, env)
        q2 = qt.createQureg(N, env)
        qt.startGateFusion(q1)
        for g in gates:
            q1._fusion.gates.append(g)
        qt.stopGateFusion(q1)
        from quest_tpu.resilience import WindowExecutor

        ex = WindowExecutor(q2, gates, every=7)
        windows = 0
        while not ex.done:
            ex.step()
            windows += 1
        assert windows == ex.num_windows
        assert ex.cursor == len(gates)
        assert np.array_equal(np.asarray(q1.amps), np.asarray(q2.amps))

    def test_checkpoint_resume_midstream(self, env, tmp_path):
        from quest_tpu import resilience as R

        gates = _circ(0.3, depth=5)
        q1 = qt.createQureg(N, env)
        ex = R.WindowExecutor(q1, gates, every=7, fingerprint="fp-t")
        ex.step()
        ex.step()
        ex.checkpoint(str(tmp_path))
        cursor = ex.cursor
        # fresh register resumes from the generation
        q2, meta = R.load_latest(str(tmp_path), env)
        assert int(meta["cursor"]) == cursor
        ex2 = R.WindowExecutor(q2, gates, every=7, start=cursor)
        while not ex2.done:
            ex2.step()
        while not ex.done:
            ex.step()
        assert np.array_equal(np.asarray(q1.amps), np.asarray(q2.amps))


class TestAsyncService:
    def test_async_submit_and_wait(self, env):
        async def main():
            srv = S.SimServer(env, window=4, max_batch=8)
            try:
                async with S.Service(srv, idle_sleep=0.0005) as svc:
                    jobs = [await svc.submit(
                        _circ(0.2 + 0.1 * i), num_qubits=N, seed=i)
                        for i in range(3)]
                    done = [await svc.wait(j) for j in jobs]
                    return [j.state for j in done]
            finally:
                srv.close()

        states = asyncio.run(main())
        assert states == [S.DONE] * 3

    def test_async_quota_error_propagates(self, env):
        async def main():
            srv = S.SimServer(env, window=4, max_batch=8, max_pending=1)
            try:
                async with S.Service(srv) as svc:
                    await svc.submit(_circ(0.1), num_qubits=N)
                    with pytest.raises(S.QuotaExceededError):
                        await svc.submit(_circ(0.2), num_qubits=N)
            finally:
                srv.close()

        asyncio.run(main())


class TestReportPerf:
    def test_serving_section_in_perf_report(self, env, server):
        T.reset()
        server.submit(_circ(0.3), num_qubits=N, tenant="acme")
        server.run_until_idle(max_steps=500)
        report = T.perf_report()
        assert "serving (continuous batcher):" in report
        assert "jobs: submitted=1 completed=1" in report
        assert "queue_wait_seconds:" in report

    def test_stats_snapshot(self, server):
        server.submit(_circ(0.3), num_qubits=N, tenant="acme")
        st = server.stats()
        assert st["queued"] == 1
        assert st["tenants"]["acme"]["inflight"] == 1
        server.run_until_idle(max_steps=500)
        st = server.stats()
        assert st["completed"] == 1
        assert st["tenants"]["acme"]["inflight"] == 0


class TestServerLifecycle:
    def test_submit_after_close_raises(self, env):
        srv = S.SimServer(env)
        srv.close()
        with pytest.raises(qt.QuESTError, match="close"):
            srv.submit(_circ(0.1), num_qubits=N)

    def test_config_validation(self, env):
        with pytest.raises(qt.QuESTError, match="power of two"):
            S.SimServer(env, max_batch=3)
        with pytest.raises(qt.QuESTError, match="window"):
            S.SimServer(env, window=0)
        with pytest.raises(qt.QuESTError, match="preempt"):
            S.SimServer(env, preempt="sometimes")

    def test_env_knobs(self, env, monkeypatch):
        monkeypatch.setenv("QT_SERVE_WINDOW", "9")
        monkeypatch.setenv("QT_SERVE_MAX_BATCH", "32")
        monkeypatch.setenv("QT_SERVE_PREEMPT", "pause")
        srv = S.SimServer(env)
        try:
            assert srv.window == 9
            assert srv.max_batch == 32
            assert srv.preempt == "pause"
        finally:
            srv.close()

    def test_exports(self):
        assert qt.SimServer is S.SimServer
        assert qt.SimService is S.Service
        assert qt.QuotaExceededError is S.QuotaExceededError
        assert qt.WindowExecutor is not None
