"""Exhaustive target/control sweeps — the reference's GENERATE-everything
discipline (test_unitaries.cpp SECTIONs enumerate every target and every
control sublist on 5 qubits; utilities.hpp:1054-1182 custom generators).

These complement the spot-parametrized files: every (target, control)
geometry of the workhorse ops runs against the dense oracle, on psi AND
rho, in one sweep per op family."""

import itertools

import numpy as np
import pytest

import quest_tpu as qt
import oracle
from test_unitaries import check_gate

N = 5


def _u(rng, k):
    return oracle.random_unitary(k, rng)


class TestUnitaryAllGeometries:
    def test_unitary_every_target(self, env):
        rng = np.random.default_rng(20)
        for t in range(N):
            u = _u(rng, 1)
            check_gate(env, lambda q: qt.unitary(q, t, u), [t], u)

    def test_controlled_unitary_every_pair(self, env):
        rng = np.random.default_rng(21)
        for c, t in itertools.permutations(range(N), 2):
            u = _u(rng, 1)
            check_gate(
                env, lambda q: qt.controlledUnitary(q, c, t, u), [t], u, [c]
            )

    def test_two_qubit_unitary_every_pair(self, env):
        rng = np.random.default_rng(22)
        for t1, t2 in itertools.permutations(range(N), 2):
            u = _u(rng, 2)
            check_gate(
                env, lambda q: qt.twoQubitUnitary(q, t1, t2, u), [t1, t2], u
            )

    def test_multi_qubit_unitary_every_triple(self, env):
        rng = np.random.default_rng(23)
        for targs in itertools.permutations(range(N), 3):
            u = _u(rng, 3)
            check_gate(
                env,
                lambda q: qt.multiQubitUnitary(q, list(targs), u),
                list(targs), u,
            )

    def test_multi_controlled_unitary_every_control_subset(self, env):
        rng = np.random.default_rng(24)
        for t in range(N):
            others = [q for q in range(N) if q != t]
            for r in range(1, len(others) + 1):
                for ctrls in itertools.combinations(others, r):
                    u = _u(rng, 1)
                    check_gate(
                        env,
                        lambda q: qt.multiControlledUnitary(q, list(ctrls), t, u),
                        [t], u, list(ctrls),
                    )

    def test_multi_state_controlled_every_state_pattern(self, env):
        rng = np.random.default_rng(25)
        t = 2
        ctrls = [0, 4]
        for states in itertools.product([0, 1], repeat=2):
            u = _u(rng, 1)
            check_gate(
                env,
                lambda q: qt.multiStateControlledUnitary(
                    q, list(ctrls), list(states), t, u
                ),
                [t], u, list(ctrls), list(states),
            )

    def test_mcmq_unitary_geometries(self, env):
        rng = np.random.default_rng(26)
        for targs in itertools.combinations(range(N), 2):
            rest = [q for q in range(N) if q not in targs]
            for ctrls in itertools.combinations(rest, 2):
                u = _u(rng, 2)
                check_gate(
                    env,
                    lambda q: qt.multiControlledMultiQubitUnitary(
                        q, list(ctrls), list(targs), u
                    ),
                    list(targs), u, list(ctrls),
                )


class TestPhaseGeometries:
    def test_phase_shift_every_target(self, env):
        for t in range(N):
            theta = 0.37 + t
            m = np.diag([1.0, np.exp(1j * theta)])
            check_gate(env, lambda q: qt.phaseShift(q, t, theta), [t], m)

    def test_controlled_phase_flip_every_pair(self, env):
        m = np.diag([1.0, -1.0]).astype(complex)
        for a, b in itertools.combinations(range(N), 2):
            check_gate(env, lambda q: qt.controlledPhaseFlip(q, a, b), [b],
                       m, [a])

    def test_multi_rotate_z_every_subset(self, env):
        for r in range(1, N + 1):
            for qs in itertools.combinations(range(N), r):
                theta = 0.21 * r
                # oracle: exp(-i theta/2 Z x..x Z) on the subset
                d = np.ones(1, dtype=complex)
                zz = np.array([1.0, -1.0])
                par = np.zeros(2 ** r)
                idx = np.arange(2 ** r)
                for b in range(r):
                    par += (idx >> b) & 1
                d = np.exp(-0.5j * theta * (-1.0) ** par)
                check_gate(
                    env, lambda q: qt.multiRotateZ(q, list(qs), theta),
                    list(qs), np.diag(d),
                )


class TestMeasurementGeometries:
    @pytest.mark.parametrize("target", range(N))
    @pytest.mark.parametrize("outcome", [0, 1])
    def test_prob_and_collapse_every_target(self, env, target, outcome):
        psi = qt.createQureg(N, env)
        qt.initDebugState(psi)
        state = oracle.debug_state(2 ** N)
        idx = np.arange(2 ** N)
        mask = ((idx >> target) & 1) == outcome
        p_ref = float(np.sum(np.abs(state[mask]) ** 2))
        assert abs(qt.calcProbOfOutcome(psi, target, outcome) - p_ref) < 1e-10
        qt.collapseToOutcome(psi, target, outcome)
        ref = np.where(mask, state, 0.0) / np.sqrt(p_ref)
        np.testing.assert_allclose(
            oracle.state_from_qureg(psi), ref, atol=1e-10
        )
