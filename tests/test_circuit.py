"""Fused-circuit scheduler tests: Pallas cluster kernel (interpret mode on
CPU), the Python planner, the native C++ planner, and end-to-end circuit
equivalence against the gate-at-a-time kernel path (the reference's
execution model, QuEST/src/QuEST.c dispatch)."""

import numpy as np
import jax.numpy as jnp
import pytest

from quest_tpu import circuit as C
from quest_tpu import native
from quest_tpu.ops import cplx, fused, kernels

from oracle import random_unitary

CNOT = np.array(
    [[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]], dtype=complex
)


def _rand_state(rng, n):
    amps = rng.standard_normal((2, 1 << n)).astype(np.float32)
    return amps / np.sqrt((amps ** 2).sum())


def _apply_gatewise(amps0, gates, n):
    ref = jnp.asarray(amps0)
    for g in gates:
        ref = kernels.apply_matrix(
            ref, jnp.asarray(g.mat), num_qubits=n, targets=g.targets
        )
    return np.asarray(ref)


def _layered_circuit(rng, n, depth):
    gates = []
    for d in range(depth):
        for q in range(n):
            gates.append(C.Gate((q,), cplx.soa(random_unitary(1, rng)).astype(np.float32)))
        for q in range(d % 2, n - 1, 2):
            gates.append(C.Gate((q, q + 1), cplx.soa(CNOT).astype(np.float32)))
    return gates


class TestClusterKernel:
    def test_identity(self):
        rng = np.random.default_rng(0)
        amps = _rand_state(rng, 14)
        eye = np.stack([np.eye(128), np.zeros((128, 128))]).astype(np.float32)
        out = fused.apply_cluster_pair(
            jnp.asarray(amps), eye, eye, num_qubits=14
        )
        np.testing.assert_allclose(np.asarray(out), amps, atol=1e-6)

    @pytest.mark.parametrize("n", [14, 15, 17])
    def test_matches_gatewise(self, n):
        rng = np.random.default_rng(n)
        amps = _rand_state(rng, n)
        us = [random_unitary(1, rng) for _ in range(14)]
        ref = jnp.asarray(amps)
        for q in range(14):
            ref = kernels.apply_matrix(
                ref, jnp.asarray(cplx.soa(us[q]), jnp.float32),
                num_qubits=n, targets=(q,),
            )
        a = us[6]
        for u in us[5::-1]:
            a = np.kron(a, u)
        b = us[13]
        for u in us[12:6:-1]:
            b = np.kron(b, u)
        out = fused.apply_cluster_pair(
            jnp.asarray(amps),
            jnp.asarray(cplx.soa(a), jnp.float32),
            jnp.asarray(cplx.soa(b), jnp.float32),
            num_qubits=n,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)

    def test_too_small_raises(self):
        eye = np.stack([np.eye(128), np.zeros((128, 128))]).astype(np.float32)
        with pytest.raises(ValueError):
            fused.apply_cluster_pair(
                jnp.zeros((2, 1 << 10), jnp.float32), eye, eye, num_qubits=10
            )


class TestPermuteQubits:
    @pytest.mark.parametrize("n", [4, 8])
    def test_against_index_oracle(self, n):
        rng = np.random.default_rng(n)
        amps = _rand_state(rng, n)
        perm = tuple(rng.permutation(n).tolist())
        out = np.asarray(
            kernels.permute_qubits(jnp.asarray(amps), num_qubits=n, perm=perm)
        )
        idx = np.arange(1 << n)
        src = np.zeros_like(idx)
        for q in range(n):
            src |= ((idx >> q) & 1) << perm[q]
        np.testing.assert_allclose(out, amps[:, src], atol=0)

    def test_swap_equivalence(self):
        rng = np.random.default_rng(3)
        n = 6
        amps = _rand_state(rng, n)
        perm = list(range(n))
        perm[1], perm[4] = perm[4], perm[1]
        out = kernels.permute_qubits(
            jnp.asarray(amps), num_qubits=n, perm=tuple(perm)
        )
        ref = kernels.swap_qubit_amps(jnp.asarray(amps), num_qubits=n, qb1=1, qb2=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0)


class TestEmbedding:
    def test_embed_1q(self):
        rng = np.random.default_rng(5)
        u = random_unitary(1, rng)
        for b in range(7):
            e = cplx.unsoa(np.asarray(C.embed_in_cluster(cplx.soa(u), (b,))))
            expect = np.kron(
                np.kron(np.eye(1 << (6 - b)), u), np.eye(1 << b)
            )
            np.testing.assert_allclose(e, expect, atol=1e-12)

    def test_embed_2q_nonadjacent(self):
        rng = np.random.default_rng(6)
        u = random_unitary(2, rng)
        e = cplx.unsoa(np.asarray(C.embed_in_cluster(cplx.soa(u), (1, 4))))
        # oracle: E[i,j] = U[x(i), x(j)] when the other bits agree
        idx = np.arange(128)
        x = ((idx >> 1) & 1) | (((idx >> 4) & 1) << 1)
        rest = idx & ~0b10010
        expect = u[x[:, None], x[None, :]] * (rest[:, None] == rest[None, :])
        np.testing.assert_allclose(e, expect, atol=1e-12)

    def test_controlled_dense(self):
        rng = np.random.default_rng(7)
        u = random_unitary(1, rng)
        cu = cplx.unsoa(C.controlled_dense(cplx.soa(u), 1))
        expect = np.eye(4, dtype=complex)
        expect[2:, 2:] = u
        np.testing.assert_allclose(cu, expect, atol=1e-12)


class TestScheduler:
    @pytest.mark.parametrize("n,depth", [(14, 2), (15, 3), (16, 2)])
    def test_e2e_matches_gatewise(self, n, depth):
        rng = np.random.default_rng(100 + n)
        gates = _layered_circuit(rng, n, depth)
        amps0 = _rand_state(rng, n)
        ref = _apply_gatewise(amps0, gates, n)
        out = np.asarray(C.apply_circuit(jnp.asarray(amps0), gates, n))
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_pass_reduction(self):
        rng = np.random.default_rng(9)
        gates = _layered_circuit(rng, 16, 4)
        ops = C.plan_circuit_py(gates, 16)
        st = C.stats(ops)
        assert st["total_passes"] < len(gates) // 2

    def test_small_n_fallback(self):
        rng = np.random.default_rng(11)
        gates = [
            C.Gate((q,), cplx.soa(random_unitary(1, rng)).astype(np.float32))
            for q in range(5)
        ]
        ops = C.plan_circuit(gates, 5)
        assert all(o[0] == "apply" for o in ops)
        amps0 = _rand_state(rng, 5)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, 5))
        ref = _apply_gatewise(amps0, gates, 5)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_high_qubit_2q_gate(self):
        rng = np.random.default_rng(12)
        n = 16
        gates = [
            C.Gate((14, 15), cplx.soa(random_unitary(2, rng)).astype(np.float32)),
            C.Gate((0, 15), cplx.soa(CNOT).astype(np.float32)),
        ]
        amps0 = _rand_state(rng, n)
        ref = _apply_gatewise(amps0, gates, n)
        out = np.asarray(C.apply_circuit(jnp.asarray(amps0), gates, n))
        np.testing.assert_allclose(out, ref, atol=1e-5)


class TestNativeScheduler:
    def test_available(self):
        assert native.native_available(), "native scheduler failed to build"

    @pytest.mark.parametrize("n,depth", [(14, 2), (16, 3), (20, 2)])
    def test_plans_match_python(self, n, depth):
        rng = np.random.default_rng(200 + n)
        gates = _layered_circuit(rng, n, depth)
        ops_py = C.plan_circuit_py(gates, n)
        ops_nat = C.plan_circuit(gates, n, use_native=True, planner="paged")
        assert [o[0] for o in ops_py] == [o[0] for o in ops_nat]
        for a, b in zip(ops_py, ops_nat):
            if a[0] in ("permute", "segswap"):
                assert tuple(a[1:]) == tuple(b[1:])
            elif a[0] == "apply":
                assert tuple(a[1]) == tuple(b[1])
                np.testing.assert_allclose(np.asarray(a[2]), np.asarray(b[2]))
            else:
                np.testing.assert_allclose(
                    np.asarray(a[1]), np.asarray(b[1]), atol=1e-6
                )
                np.testing.assert_allclose(
                    np.asarray(a[2]), np.asarray(b[2]), atol=1e-6
                )

    def test_native_e2e(self):
        rng = np.random.default_rng(13)
        n = 15
        gates = _layered_circuit(rng, n, 2)
        amps0 = _rand_state(rng, n)
        ops = C.plan_circuit(gates, n, use_native=True, planner="paged")
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_empty_circuit(self):
        assert C.plan_circuit([], 16, use_native=True) == []

    def test_out_of_range_target_rejected(self):
        # native planner must reject bad targets (rc=3), falling back to
        # the Python planner's IndexError — never a silently wrong plan
        rng = np.random.default_rng(14)
        bad = [C.Gate((16,), cplx.soa(random_unitary(1, rng)).astype(np.float32))]
        assert native.plan_native([(16,)], 16) is None
        with pytest.raises(IndexError):
            C.plan_circuit(bad, 16, use_native=True)


class TestWindowedScheduler:
    """Offset-window planner (plan_circuit_windowed + apply_window_stack):
    zero-relocation passes whose sublane cluster sits at an arbitrary
    contiguous bit window [k, k+7)."""

    def test_schmidt_rank(self):
        rng = np.random.default_rng(21)
        cnot = cplx.soa(CNOT).astype(np.float32)
        terms = C.schmidt_terms_2q(cnot)
        assert len(terms) == 2
        cz = np.zeros((2, 4, 4), np.float32)
        cz[0] = np.diag([1, 1, 1, -1])
        assert len(C.schmidt_terms_2q(cz)) == 2
        u1 = random_unitary(1, rng)
        u2 = random_unitary(1, rng)
        prod = cplx.soa(np.kron(u2, u1)).astype(np.float32)
        assert len(C.schmidt_terms_2q(prod)) == 1
        dense = cplx.soa(random_unitary(2, rng)).astype(np.float32)
        assert len(C.schmidt_terms_2q(dense)) == 4

    def test_schmidt_small_angle_f64_keeps_rank2(self):
        # ADVICE r1: a fixed 1e-7 truncation silently flattened f64
        # controlled rotations with angle < ~1e-7 to rank 1
        theta = 1e-9
        cp = np.diag([1, 1, 1, np.exp(1j * theta)])
        terms = C.schmidt_terms_2q(cplx.soa(cp).astype(np.float64))
        assert len(terms) == 2
        acc = np.zeros((4, 4), complex)
        for lo, hi in terms:
            acc += np.kron(hi[0] + 1j * hi[1], lo[0] + 1j * lo[1])
        np.testing.assert_allclose(acc, cp, atol=1e-14)

    def test_schmidt_zero_matrix_rank1(self):
        # ADVICE r1: empty decompositions must not reach fold_cross
        zero = np.zeros((2, 4, 4), np.float64)
        terms = C.schmidt_terms_2q(zero)
        assert len(terms) == 1
        gates = [C.Gate((0, 9), zero)]
        ops = C.plan_circuit(gates, 12)
        amps = np.zeros((2, 1 << 12), np.float64)
        amps[0, 0] = 1.0
        out = np.asarray(C.execute_plan(jnp.asarray(amps), ops, 12))
        np.testing.assert_allclose(out, 0.0, atol=1e-15)

    def test_schmidt_reconstruction(self):
        rng = np.random.default_rng(22)
        for u in [CNOT, random_unitary(2, rng)]:
            soa = cplx.soa(u).astype(np.float64)
            acc = np.zeros((4, 4), complex)
            for lo, hi in C.schmidt_terms_2q(soa):
                acc += np.kron(hi[0] + 1j * hi[1], lo[0] + 1j * lo[1])
            np.testing.assert_allclose(acc, u, atol=1e-12)

    @pytest.mark.parametrize("k", [7, 9, 13])
    def test_window_stack_matches_gatewise(self, k):
        n = 20
        rng = np.random.default_rng(23 + k)
        amps = _rand_state(rng, n)
        ua = random_unitary(1, rng)
        ub = random_unitary(1, rng)
        ref = kernels.apply_matrix(
            jnp.asarray(amps), jnp.asarray(cplx.soa(ua).astype(np.float32)),
            num_qubits=n, targets=(3,))
        ref = kernels.apply_matrix(
            ref, jnp.asarray(cplx.soa(ub).astype(np.float32)),
            num_qubits=n, targets=(k + 2,))
        a = C.embed_in_cluster(cplx.soa(ua).astype(np.float32), (3,))
        b = C.embed_in_cluster(cplx.soa(ub).astype(np.float32), (2,))
        out = fused.apply_window_stack(
            jnp.asarray(amps), a[None], b[None], num_qubits=n, k=k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    @pytest.mark.parametrize("n,depth", [(14, 3), (16, 2), (20, 2)])
    def test_windowed_e2e(self, n, depth):
        rng = np.random.default_rng(300 + n)
        gates = _layered_circuit(rng, n, depth)
        # sprinkle far cross gates + a window-internal dense 2q gate
        gates.append(C.Gate((2, n - 1), cplx.soa(CNOT).astype(np.float32)))
        if n >= 16:
            gates.append(C.Gate(
                (n - 6, n - 3),
                cplx.soa(random_unitary(2, rng)).astype(np.float32)))
        ops = C.plan_circuit_windowed(gates, n)
        assert any(o[0] == "winfused" for o in ops)
        amps0 = _rand_state(rng, n)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_windowed_beats_paged_pass_count(self):
        rng = np.random.default_rng(31)
        gates = _layered_circuit(rng, 20, 4)
        win = C.stats(C.plan_circuit_windowed(gates, 20))
        paged = C.stats(C.plan_circuit_py(gates, 20))
        assert win["total_passes"] <= paged["total_passes"]
        assert win["segswap"] == 0  # zero-relocation by construction

    def test_rank_cap_respected(self):
        rng = np.random.default_rng(32)
        n = 15
        # many cross CNOTs straddling lane x window in sequence
        gates = []
        for i in range(6):
            gates.append(C.Gate((i % 7, 7 + (i % 7)),
                                cplx.soa(CNOT).astype(np.float32)))
        ops = C.plan_circuit_windowed(gates, n)
        for op in ops:
            if op[0] == "winfused":
                assert op[2].shape[0] <= C.RANK_CAP
        amps0 = _rand_state(rng, n)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=1e-5)


class TestMaskScheduling:
    """Diagonal-mask folding of crossing controlled gates (round 2):
    controlled-form 2q gates rewrite to W-sandwich + diagonal, and crossing
    diagonals fold into the pass's elementwise mask at zero rank cost."""

    def test_controlled_form_cnot(self):
        cf = C.controlled_form_2q(cplx.soa(CNOT).astype(np.float64))
        assert cf is not None
        pre, d4, post, acted = cf
        # reconstruct: U = (post on acted) . diag(d4) . (pre on acted)
        pre_c = pre[0] + 1j * pre[1]
        post_c = post[0] + 1j * post[1]
        d = d4[0] + 1j * d4[1]
        if acted == 1:
            full_pre = np.kron(pre_c, np.eye(2))
            full_post = np.kron(post_c, np.eye(2))
        else:
            full_pre = np.kron(np.eye(2), pre_c)
            full_post = np.kron(np.eye(2), post_c)
        u = full_post @ np.diag(d) @ full_pre
        np.testing.assert_allclose(u, CNOT, atol=1e-12)

    def test_controlled_form_random_controlled_v(self):
        rng = np.random.default_rng(9)
        for ctrl_bit in (0, 1):
            v = random_unitary(1, rng)
            u = np.eye(4, dtype=complex)
            if ctrl_bit == 0:           # control = matrix bit 0
                u[1::2, 1::2] = v
            else:                       # control = matrix bit 1
                u[2:, 2:] = v
            cf = C.controlled_form_2q(cplx.soa(u).astype(np.float64))
            assert cf is not None and cf[3] == 1 - ctrl_bit
        # generic dense 2q gate is NOT controlled-form
        dense = cplx.soa(random_unitary(2, rng)).astype(np.float64)
        assert C.controlled_form_2q(dense) is None
        # a fully diagonal gate is excluded (handled by diag4_2q directly)
        cz = np.diag([1, 1, 1, -1]).astype(complex)
        assert C.controlled_form_2q(cplx.soa(cz)) is None
        assert C.diag4_2q(cplx.soa(cz)) is not None

    def test_ladder_plan_is_all_rank1(self):
        # the headline circuit shape: every crossing CNOT must fold via the
        # mask, leaving every window pass at rank 1
        rng = np.random.default_rng(11)
        n, depth = 16, 4
        gates = _layered_circuit(rng, n, depth)
        ops = C.plan_circuit_windowed(gates, n)
        for op in ops:
            assert op[0] == "winfused"
            assert np.shape(op[2])[0] == 1      # rank 1
        assert any(len(op) > 6 and op[6] is not None for op in ops)

    def test_masked_plan_matches_gatewise(self):
        rng = np.random.default_rng(12)
        n = 15
        gates = _layered_circuit(rng, n, 3)
        # add crossing CPhase (diagonal, masks directly) and a
        # control-on-low CRz
        cphase = np.diag([1, 1, 1, np.exp(0.7j)]).astype(complex)
        gates.append(C.Gate((3, 9), cplx.soa(cphase).astype(np.float32)))
        crz = np.eye(4, dtype=complex)
        crz[1, 1], crz[3, 3] = np.exp(-0.4j), np.exp(0.4j)
        gates.append(C.Gate((2, 14), cplx.soa(crz).astype(np.float32)))
        amps0 = _rand_state(rng, n)
        ops = C.plan_circuit_windowed(gates, n)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_mask_only_pass(self):
        # a lone crossing CZ: pass with no matmul on either side, just mask
        n = 14
        cz = np.zeros((2, 4, 4), np.float64)
        cz[0] = np.diag([1, 1, 1, -1])
        gates = [C.Gate((0, 13), cz)]
        ops = C.plan_circuit_windowed(gates, n)
        assert len(ops) == 1 and ops[0][6] is not None
        rng = np.random.default_rng(13)
        amps0 = _rand_state(rng, n)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=1e-6)


class TestNativeWindowedScheduler:
    """Parity of the C++ windowed planner (qts_plan_windowed) with the
    Python reference implementation plan_circuit_windowed."""

    @pytest.mark.parametrize("n,depth", [(14, 2), (16, 3), (20, 2)])
    def test_plans_match_python(self, n, depth):
        # generic dense 2q gates only, so no masks appear in these plans
        # (mask-circuit parity is covered by
        # test_plans_match_python_with_masks)
        rng = np.random.default_rng(400 + n)
        gates = []
        for d in range(depth):
            for q in range(n):
                gates.append(C.Gate(
                    (q,), cplx.soa(random_unitary(1, rng)).astype(np.float32)))
            for q in range(d % 2, n - 1, 2):
                gates.append(C.Gate(
                    (q, q + 1),
                    cplx.soa(random_unitary(2, rng)).astype(np.float32)))
        gates.append(C.Gate(
            (2, n - 1), cplx.soa(random_unitary(2, rng)).astype(np.float32)))
        py = C.plan_circuit_windowed(gates, n)
        structural = native.plan_native_windowed(
            [g.targets for g in gates], n, C._gate_xranks(gates))
        assert structural is not None, "native windowed planner unavailable"
        nat = C.materialize_windowed_plan(structural, gates)
        assert [o[0] for o in py] == [o[0] for o in nat]
        for a, b in zip(py, nat):
            if a[0] == "winfused":
                assert a[1] == b[1]          # same window offset k
                np.testing.assert_allclose(
                    np.asarray(a[2]), np.asarray(b[2]), atol=1e-6)
                np.testing.assert_allclose(
                    np.asarray(a[3]), np.asarray(b[3]), atol=1e-6)
                assert a[4:6] == b[4:6]      # same apply_a/apply_b flags
                assert len(a) < 7 or a[6] is None   # no mask on these plans
            else:
                assert tuple(a[1]) == tuple(b[1])

    @pytest.mark.parametrize("n,depth", [(14, 3), (18, 2)])
    def test_plans_match_python_with_masks(self, n, depth):
        # CNOT ladders: the controlled-form rewrite + mask folds must agree
        # between the C++ planner (flags path) and the Python planner
        rng = np.random.default_rng(500 + n)
        gates = _layered_circuit(rng, n, depth)
        py = C.plan_circuit_windowed(gates, n)
        glist = C.rewrite_controlled_gates(gates)
        structural = native.plan_native_windowed(
            [g.targets for g in glist], n,
            C._gate_xranks(glist), C._gate_flags(glist))
        assert structural is not None, "native windowed planner unavailable"
        nat = C.materialize_windowed_plan(structural, glist)
        assert [o[0] for o in py] == [o[0] for o in nat]
        for a, b in zip(py, nat):
            if a[0] != "winfused":
                continue
            assert a[1] == b[1]
            np.testing.assert_allclose(np.asarray(a[2]), np.asarray(b[2]),
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(a[3]), np.asarray(b[3]),
                                       atol=1e-6)
            assert a[4:6] == b[4:6]
            ma, mb = a[6], b[6]
            assert (ma is None) == (mb is None)
            if ma is not None:
                np.testing.assert_allclose(ma, mb, atol=1e-12)

    def test_native_windowed_e2e(self):
        rng = np.random.default_rng(41)
        n = 15
        gates = _layered_circuit(rng, n, 2)
        amps0 = _rand_state(rng, n)
        ops = C.plan_circuit(gates, n, use_native=True, planner="windowed")
        assert any(o[0] == "winfused" for o in ops)
        out = np.asarray(C.execute_plan(jnp.asarray(amps0), ops, n))
        ref = _apply_gatewise(amps0, gates, n)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_unknown_planner_rejected(self):
        with pytest.raises(ValueError, match="unknown planner"):
            C.plan_circuit([], 16, planner="window")


class TestPallasQFTLadder:
    """The Pallas ladder kernels (high: pair bit >= 14 with SMEM-table
    phases; low: pair bit in the sublane axis) vs the XLA elementwise
    formulation — interpret mode, since real-TPU selection is gated by
    qft_ladder_supported."""

    @pytest.mark.parametrize("t", [7, 9, 10, 13, 14, 15, 17])
    @pytest.mark.parametrize("conj", [False, True])
    def test_matches_xla_formulation(self, t, conj, monkeypatch):
        n = 18
        rng = np.random.default_rng(600 + t)
        st = rng.standard_normal((2, 1 << n)).astype(np.float32)
        st /= np.sqrt((st ** 2).sum())
        # force the XLA elementwise formulation for the reference
        monkeypatch.setattr(fused, "qft_ladder_supported",
                            lambda *a, **k: False)
        ref = np.asarray(kernels.apply_qft_ladder(
            jnp.asarray(st), num_qubits=n, target=t, conj=conj))
        monkeypatch.undo()
        # the SHIPPED wrapper (builds the tables), interpret mode on CPU
        out = fused.apply_qft_ladder_pallas(
            jnp.asarray(st), num_qubits=n, target=t, conj=conj,
            interpret=True)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)

    def test_two_level_smem_table_split(self, monkeypatch):
        # shrink the split threshold so the high SMEM factor table is
        # non-trivial (nhi > 1) at a small, fast size — exercises the
        # l % SPLIT / l // SPLIT phase reconstruction used for t > 25
        monkeypatch.setattr(fused, "_TL_SPLIT", 4)
        n, t = 18, 17               # L = 8 > SPLIT -> nhi = 2
        rng = np.random.default_rng(7)
        st = rng.standard_normal((2, 1 << n)).astype(np.float32)
        st /= np.sqrt((st ** 2).sum())
        out = fused.apply_qft_ladder_pallas(
            jnp.asarray(st), num_qubits=n, target=t, interpret=True)
        monkeypatch.setattr(fused, "qft_ladder_supported",
                            lambda *a, **k: False)
        ref = np.asarray(kernels.apply_qft_ladder(
            jnp.asarray(st), num_qubits=n, target=t))
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_split_plan_sides_merges_adjacent_duals():
    """VERDICT r3 item 6: two adjacent rank-1 maskless dual-side passes
    rewrite to two B-only passes + ONE merged A pass (the A sides act on
    lanes [0,7), the B sides on windows >= 7 — disjoint, commuting), and
    the rewritten plan is numerically identical."""
    import jax.numpy as jnp

    from quest_tpu import circuit as C
    from quest_tpu.ops import kernels

    n = 16
    rng = np.random.default_rng(9)

    def ru():
        a = rng.standard_normal((128, 128)) + 1j * rng.standard_normal(
            (128, 128))
        q, r = np.linalg.qr(a)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag])

    ops = [("winfused", 7, ru()[None], ru()[None], True, True, None),
           ("winfused", 9, ru()[None], ru()[None], True, True, None)]
    split = C.split_plan_sides(ops)
    kinds = [(op[4], op[5]) for op in split]
    assert kinds == [(False, True), (False, True), (True, False)], kinds
    a = np.array(kernels.init_debug_state(1 << n, np.float64))
    a /= np.sqrt((a ** 2).sum())
    r1 = np.asarray(C.execute_plan(jnp.asarray(a), ops, n))
    r2 = np.asarray(C.execute_plan(jnp.asarray(a), split, n))
    np.testing.assert_allclose(r1, r2, atol=1e-11)


def test_split_plan_sides_leaves_singletons_and_masked():
    """A lone dual pass must NOT split (2 x 1.25 ms > 2.1 ms), and
    mask/rank-tied passes are barriers — exactly why the rewrite never
    engages on the 26q headline plan (see BASELINE.md round-4 profile)."""
    from quest_tpu import circuit as C

    rng = np.random.default_rng(10)
    m = rng.standard_normal((2, 128, 128))
    single = [("winfused", 7, m[None], m[None], True, True, None)]
    assert C.split_plan_sides(single) == single
    masked = [("winfused", 7, m[None], m[None], True, True, m),
              ("winfused", 9, m[None], m[None], True, True, None),
              ("winfused", 10, m[None], m[None], True, True, m)]
    assert C.split_plan_sides(masked) == masked


def test_split_plan_sides_multibit_lane_product_blocks_mask():
    """Review regression: an A-side product of X(l).X(m) touches BOTH
    lane bits (the single-flip-diagonal test missed it); a masked pass
    depending on either bit must stay a barrier, so the rewrite leaves
    the plan alone rather than reordering A past a non-commuting mask."""
    import jax.numpy as jnp

    from quest_tpu import circuit as C
    from quest_tpu.ops import kernels

    n = 16
    x = np.array([[0.0, 1.0], [1.0, 0.0]])
    xx = np.kron(np.eye(1 << 5), np.kron(x, x))  # X on lane bits 0, 1
    a_xx = np.stack([xx, np.zeros_like(xx)])
    rng = np.random.default_rng(12)

    def ru():
        a = rng.standard_normal((128, 128)) + 1j * rng.standard_normal(
            (128, 128))
        q, r = np.linalg.qr(a)
        u = q * (np.diag(r) / np.abs(np.diag(r)))
        return np.stack([u.real, u.imag])

    # CZ-style diagonal mask depending on lane bit 0
    lane_phase = np.where((np.arange(128) & 1) == 1, -1.0, 1.0)
    mask = np.stack([np.broadcast_to(lane_phase, (128, 128)).copy(),
                     np.zeros((128, 128))])
    ops = [("winfused", 7, a_xx[None], ru()[None], True, True, None),
           ("winfused", 9, ru()[None], ru()[None], False, True, mask),
           ("winfused", 9, ru()[None], ru()[None], True, True, None)]
    split = C.split_plan_sides(ops)
    a = np.array(kernels.init_debug_state(1 << n, np.float64))
    a /= np.sqrt((a ** 2).sum())
    r1 = np.asarray(C.execute_plan(jnp.asarray(a), ops, n))
    r2 = np.asarray(C.execute_plan(jnp.asarray(a), split, n))
    np.testing.assert_allclose(r1, r2, atol=1e-11)
    # and the masked pass must have stayed a barrier (no merged A pass
    # crossing it): the first op must still be dual-side
    assert split[0][4] and split[0][5]
