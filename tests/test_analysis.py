"""qlint static-analysis suite (quest_tpu/analysis, docs/design.md §23).

Three layers of evidence:

* **Fixture corpus** — one minimal snippet per rule: the rule flags its
  fixture (and ONLY its rule fires on it), and a minimally-corrected
  twin stays clean, so each rule's positive and negative behaviour is
  pinned independently.
* **Engine mechanics** — pragma parsing (reason mandatory, docstrings
  don't count, unknown rule ids rejected), baseline round-trip (reasons
  mandatory, stale entries surfaced).
* **The tree itself** — the full quest_tpu/tests/scripts walk must come
  back with zero unsuppressed findings, and the @sharded_contract
  declarations must match compiled HLO, with any perturbed declaration
  failing the check (drift detection is load-bearing, not decorative).
"""

import json
import textwrap

import pytest

from quest_tpu import contracts as C
from quest_tpu.analysis import engine


def run(src, path="quest_tpu/fake.py", rules=None):
    return engine.analyze_source(textwrap.dedent(src), path, rules=rules)


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# Fixture corpus: each rule flags its fixture and nothing else
# ---------------------------------------------------------------------------


class TestHostSyncInTraced:
    def test_item_in_jitted_function_flagged(self):
        fs = run(
            """
            import jax

            @jax.jit
            def norm(amps):
                return amps.item()
            """)
        assert rule_ids(fs) == ["host-sync-in-traced"]
        assert ".item()" in fs[0].message

    def test_float_cast_and_asarray_flagged(self):
        fs = run(
            """
            import jax
            import numpy as np

            @jax.jit
            def f(amps):
                x = float(amps)
                y = np.asarray(amps)
                return x, y
            """)
        assert rule_ids(fs) == ["host-sync-in-traced"] * 2

    def test_static_argnames_param_is_not_traced(self):
        fs = run(
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def f(amps, n):
                return amps * int(n)
            """)
        assert fs == []

    def test_registry_traced_function_flagged(self):
        # module-traced file: top-level defs with canonical array params
        fs = run(
            """
            def kernel(amps, target):
                return amps.tolist()
            """,
            path="quest_tpu/ops/kernels.py")
        assert rule_ids(fs) == ["host-sync-in-traced"]

    def test_host_helper_in_kernel_module_stays_clean(self):
        # differently-named params = host helper (kraus table builders)
        fs = run(
            """
            def build_table(mat):
                return float(mat[0])
            """,
            path="quest_tpu/ops/kernels.py")
        assert fs == []

    def test_untraced_function_may_sync(self):
        fs = run(
            """
            def get_amp(amps, i):
                return float(amps[i])
            """)
        assert fs == []


class TestTracerBranch:
    def test_if_on_traced_value_flagged(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(amps):
                if amps[0] > 0:
                    return amps
                return -amps
            """)
        assert rule_ids(fs) == ["tracer-branch"]

    def test_taint_propagates_through_assignment(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(amps):
                p = amps * amps
                while p.sum() > 0:
                    p = p - 1
                return p
            """)
        assert rule_ids(fs) == ["tracer-branch"]

    def test_branch_on_static_metadata_clean(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(amps, n):
                if amps.ndim == 2 and len(amps) > 1 and amps is not None:
                    return amps * n
                return amps
            """)
        assert fs == []


class TestTelemetryInTraced:
    def test_unguarded_mutation_flagged(self):
        fs = run(
            """
            import jax
            from quest_tpu import telemetry

            @jax.jit
            def f(amps):
                telemetry.inc("gates_total")
                return amps
            """)
        assert rule_ids(fs) == ["telemetry-in-traced"]

    def test_tracer_guard_suppresses(self):
        fs = run(
            """
            import jax
            from quest_tpu import telemetry

            @jax.jit
            def f(amps):
                if not isinstance(amps, jax.core.Tracer):
                    telemetry.inc("gates_total")
                return amps
            """)
        assert fs == []


class TestNondeterminism:
    def test_wall_clock_flagged(self):
        fs = run(
            """
            import time

            def stamp():
                return time.time()
            """)
        assert rule_ids(fs) == ["nondeterminism"]

    def test_unseeded_default_rng_flagged(self):
        fs = run(
            """
            import numpy as np

            def draw():
                return np.random.default_rng().random()
            """)
        assert rule_ids(fs) == ["nondeterminism"]

    def test_seeded_generator_clean(self):
        fs = run(
            """
            import numpy as np

            def draw(seed):
                return np.random.default_rng(seed).random()
            """)
        assert fs == []

    def test_rule_scoped_to_package(self):
        fs = run(
            """
            import time

            def stamp():
                return time.time()
            """,
            path="tests/fake_test.py")
        assert fs == []


class TestF64Literal:
    def test_jnp_dtype_literal_flagged(self):
        fs = run(
            """
            import jax.numpy as jnp

            def up(x):
                return jnp.asarray(x, dtype=jnp.float64)
            """)
        assert rule_ids(fs) == ["f64-literal"]

    def test_dtype_string_in_astype_flagged(self):
        fs = run(
            """
            def up(x):
                return x.astype("complex128")
            """)
        assert rule_ids(fs) == ["f64-literal"]

    def test_numpy_table_constant_allowed(self):
        fs = run(
            """
            import numpy as np

            def table(n):
                return np.arange(n, dtype=np.float64)
            """)
        assert fs == []

    def test_dtype_comparison_allowed(self):
        fs = run(
            """
            import numpy as np

            def is_double(x):
                return x.dtype == np.float64
            """)
        assert fs == []

    def test_precision_py_exempt(self):
        fs = run(
            """
            import jax.numpy as jnp
            REAL = jnp.float64
            """,
            path="quest_tpu/precision.py")
        assert fs == []


class TestBroadExcept:
    def test_bare_and_broad_flagged(self):
        fs = run(
            """
            def f(g):
                try:
                    return g()
                except Exception:
                    return None
            """)
        assert rule_ids(fs) == ["broad-except"]

    def test_cleanup_and_reraise_clean(self):
        fs = run(
            """
            def f(g, undo):
                try:
                    return g()
                except BaseException:
                    undo()
                    raise
            """)
        assert fs == []

    def test_narrow_except_clean(self):
        fs = run(
            """
            def f(g):
                try:
                    return g()
                except (ValueError, OSError):
                    return None
            """)
        assert fs == []


class TestOomSwallow:
    def test_oom_handling_outside_governor_flagged(self):
        fs = run(
            """
            def f(g):
                try:
                    return g()
                except RuntimeError as e:
                    if "RESOURCE_EXHAUSTED" in str(e):
                        return None
                    raise
            """)
        assert rule_ids(fs) == ["oom-swallow"]

    def test_governor_exempt(self):
        fs = run(
            """
            def oom_net(g):
                try:
                    return g()
                except RuntimeError as e:
                    if "RESOURCE_EXHAUSTED" not in str(e):
                        raise
                    return None
            """,
            path="quest_tpu/governor.py")
        assert fs == []


class TestLayerViolation:
    def test_upward_import_flagged(self):
        fs = run(
            """
            from quest_tpu import api
            """,
            path="quest_tpu/ops/fake.py")
        assert rule_ids(fs) == ["layer-violation"]
        assert "upward" in fs[0].message

    def test_api_lateral_import_flagged(self):
        fs = run(
            """
            from quest_tpu import debug
            """,
            path="quest_tpu/api.py")
        assert rule_ids(fs) == ["layer-violation"]
        assert "API functions must not call each other" in fs[0].message

    def test_shared_module_importing_layered_flagged(self):
        fs = run(
            """
            from quest_tpu import fusion
            """,
            path="quest_tpu/qureg.py")
        assert rule_ids(fs) == ["layer-violation"]

    def test_downward_and_shared_imports_clean(self):
        fs = run(
            """
            from quest_tpu import env
            from quest_tpu import validation
            from quest_tpu.ops import kernels
            """,
            path="quest_tpu/fusion.py")
        assert fs == []

    def test_lazy_function_scope_import_not_flagged(self):
        # the sanctioned cycle-breaking idiom
        fs = run(
            """
            def helper():
                from quest_tpu import api
                return api
            """,
            path="quest_tpu/ops/fake.py")
        assert fs == []


class TestCollectiveOutsideDist:
    def test_collective_callsite_flagged(self):
        fs = run(
            """
            from jax import lax

            def exchange(x):
                return lax.ppermute(x, "amp", [(0, 1)])
            """,
            path="quest_tpu/ops/fake.py")
        assert rule_ids(fs) == ["collective-outside-dist"]

    def test_direct_import_alias_flagged(self):
        fs = run(
            """
            from jax.lax import psum

            def total(x):
                return psum(x, "amp")
            """,
            path="tests/fake_test.py")
        assert rule_ids(fs) == ["collective-outside-dist"]

    def test_exchange_layer_exempt(self):
        fs = run(
            """
            from jax import lax

            def exchange(x):
                return lax.ppermute(x, "amp", [(0, 1)])
            """,
            path="quest_tpu/parallel/dist.py")
        assert fs == []


class TestContractMissing:
    def test_undeclared_wrapper_flagged(self):
        fs = run(
            """
            def swap_sharded(amps):
                return amps
            """,
            path="quest_tpu/parallel/dist.py")
        assert rule_ids(fs) == ["contract-missing"]

    def test_decorated_wrapper_clean(self):
        fs = run(
            """
            from quest_tpu.contracts import sharded_contract

            @sharded_contract(collectives={"collective-permute": 1},
                              max_exchange_bytes=512)
            def swap_sharded(amps):
                return amps
            """,
            path="quest_tpu/parallel/dist.py")
        assert fs == []


class TestParseError:
    def test_broken_file_reports_parse_error(self):
        fs = run("def f(:\n")
        assert rule_ids(fs) == ["parse-error"]


# ---------------------------------------------------------------------------
# Engine mechanics: pragmas, baseline
# ---------------------------------------------------------------------------


class TestSuppressions:
    SRC = """
        import time

        def stamp():
            # qlint: allow(nondeterminism): recorded upstream
            return time.time()
        """

    def test_pragma_suppresses_next_line(self):
        assert run(self.SRC) == []

    def test_pragma_on_same_line_suppresses(self):
        fs = run(
            """
            import time

            def stamp():
                return time.time()  # qlint: allow(nondeterminism): recorded
            """)
        assert fs == []

    def test_reasonless_pragma_is_a_finding(self):
        fs = run(
            """
            import time

            def stamp():
                # qlint: allow(nondeterminism)
                return time.time()
            """)
        # the bare pragma does NOT suppress, and is itself flagged
        assert rule_ids(fs) == ["bad-pragma", "nondeterminism"]

    def test_unknown_rule_id_is_a_finding(self):
        fs = run(
            """
            def f():
                # qlint: allow(no-such-rule): whatever
                return 1
            """)
        assert rule_ids(fs) == ["bad-pragma"]
        assert "no-such-rule" in fs[0].message

    def test_pragma_in_docstring_does_not_suppress(self):
        fs = run(
            '''
            import time

            def stamp():
                """Docs may show '# qlint: allow(nondeterminism): x'."""
                return time.time()
            ''')
        assert rule_ids(fs) == ["nondeterminism"]

    def test_wildcard_pragma_suppresses_all(self):
        fs = run(
            """
            import time

            def stamp():
                # qlint: allow(*): fixture exercising the wildcard
                return time.time()
            """)
        assert fs == []


class TestBaseline:
    def test_reasonless_entry_rejected(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"findings": [
            {"rule": "broad-except", "path": "x.py", "line": 3}]}))
        with pytest.raises(ValueError, match="no reason"):
            engine.load_baseline(str(p))

    def test_apply_baseline_splits_new_old_stale(self):
        f1 = engine.Finding("broad-except", "a.py", 3, 1, "m")
        f2 = engine.Finding("broad-except", "b.py", 9, 1, "m")
        baseline = [
            {"rule": "broad-except", "path": "a.py", "line": 3,
             "reason": "grandfathered"},
            {"rule": "f64-literal", "path": "gone.py", "line": 1,
             "reason": "file was deleted"},
        ]
        new, old, stale = engine.apply_baseline([f1, f2], baseline)
        assert new == [f2]
        assert old == [f1]
        assert [e["path"] for e in stale] == ["gone.py"]

    def test_committed_baseline_loads_and_is_empty(self):
        # the tree is clean by construction: the committed baseline must
        # stay empty (new debt gets fixed or pragma'd, not grandfathered)
        assert engine.load_baseline() == []


# ---------------------------------------------------------------------------
# The tree itself
# ---------------------------------------------------------------------------


class TestFullTree:
    def test_zero_unsuppressed_findings(self):
        findings = engine.analyze_paths()
        baseline = engine.load_baseline()
        new, _old, stale = engine.apply_baseline(findings, baseline)
        assert new == [], "\n".join(f.format() for f in new)
        assert stale == [], f"stale baseline entries: {stale}"

    def test_all_required_wrappers_registered(self):
        from quest_tpu.parallel import dist  # noqa: F401 - decorators run

        assert set(C.SHARDED_CONTRACTS) == set(C.REQUIRED_WRAPPERS)
        for name, contract in C.SHARDED_CONTRACTS.items():
            assert contract.collectives, name
            assert contract.max_exchange_bytes > 0, name


class TestContractHLO:
    @pytest.fixture(scope="class")
    def env8(self):
        from quest_tpu.analysis import hlocheck
        try:
            return hlocheck.ensure_mesh()
        except RuntimeError as e:
            pytest.skip(str(e))

    def test_declarations_match_compiled_hlo(self, env8):
        from quest_tpu.analysis import hlocheck
        assert hlocheck.verify_sharded_contracts(env=env8) == []

    def test_perturbed_collective_count_fails(self, env8):
        # drift detection is load-bearing: a declaration that disagrees
        # with the compiled histogram must FAIL, not quietly pass
        from quest_tpu.analysis import hlocheck
        base = C.SHARDED_CONTRACTS["swap_sharded"]
        perturbed = dict(C.SHARDED_CONTRACTS)
        perturbed["swap_sharded"] = C.ShardedContract(
            name="swap_sharded",
            collectives={"collective-permute": 2},
            max_exchange_bytes=base.max_exchange_bytes)
        errors = hlocheck.verify_sharded_contracts(
            env=env8, contracts=perturbed)
        assert any("swap_sharded" in e and "collective-permute" in e
                   for e in errors), errors

    def test_bytes_cap_below_measured_fails(self, env8):
        from quest_tpu.analysis import hlocheck
        base = C.SHARDED_CONTRACTS["swap_sharded"]
        perturbed = dict(C.SHARDED_CONTRACTS)
        perturbed["swap_sharded"] = C.ShardedContract(
            name="swap_sharded",
            collectives=dict(base.collectives),
            max_exchange_bytes=8)
        errors = hlocheck.verify_sharded_contracts(
            env=env8, contracts=perturbed)
        assert any("swap_sharded" in e and "max_exchange_bytes" in e
                   for e in errors), errors

    def test_perturbed_dcn_tier_cap_fails(self, env8):
        """Satellite (ISSUE 12): per-tier caps verify against the
        compiled routing tables under the forced 2x4 hosts x chips
        reading of the canonical mesh — a DCN cap below the measured
        cross-host payload must FAIL, not quietly pass.  The canonical
        remap (bit 0 <-> bit n-1) is a mixed transposition on the host
        mesh bit, so its collective-permute provably rides DCN."""
        from quest_tpu.analysis import hlocheck
        base = C.SHARDED_CONTRACTS["remap_sharded"]
        perturbed = dict(C.SHARDED_CONTRACTS)
        perturbed["remap_sharded"] = C.ShardedContract(
            name="remap_sharded",
            collectives=dict(base.collectives),
            max_exchange_bytes=base.max_exchange_bytes,
            max_tier_bytes={"ici": base.max_exchange_bytes, "dcn": 1})
        errors = hlocheck.verify_sharded_contracts(
            env=env8, contracts=perturbed)
        assert any("remap_sharded" in e and "max_tier_bytes[dcn]" in e
                   for e in errors), errors

    def test_unknown_contract_name_fails(self, env8):
        from quest_tpu.analysis import hlocheck
        perturbed = dict(C.SHARDED_CONTRACTS)
        perturbed["renamed_wrapper"] = C.ShardedContract(
            name="renamed_wrapper",
            collectives={"all-gather": 1},
            max_exchange_bytes=1 << 10)
        errors = hlocheck.verify_sharded_contracts(
            env=env8, contracts=perturbed)
        assert any("renamed_wrapper" in e for e in errors), errors
