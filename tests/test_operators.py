"""Operator tests (analogue of reference test_operators.cpp, 18 TEST_CASEs):
the apply* family — matrices, Pauli sums, Trotter circuits, diagonal ops,
phase functions, QFT."""

import numpy as np
import pytest
from scipy.linalg import expm

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N
ATOL = 1e-10


@pytest.fixture
def rng():
    return np.random.default_rng(123)


def _rand_psi(env, rng):
    vec = oracle.random_state(N, rng)
    q = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, q, vec)
    return q, vec


def _rand_rho(env, rng):
    mat = oracle.random_density(N, rng)
    q = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, q, mat)
    return q, mat


def test_set_weighted_qureg(env, rng):
    v1, v2, v3 = (oracle.random_state(N, rng) for _ in range(3))
    q1 = qt.createQureg(N, env)
    q2 = qt.createQureg(N, env)
    out = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, q1, v1)
    oracle.set_qureg_from_array(qt, q2, v2)
    oracle.set_qureg_from_array(qt, out, v3)
    f1, f2, fo = 0.3 - 0.1j, -1.2j, 0.5 + 0.2j
    qt.setWeightedQureg(f1, q1, f2, q2, fo, out)
    np.testing.assert_allclose(
        oracle.state_from_qureg(out), f1 * v1 + f2 * v2 + fo * v3, atol=ATOL
    )


def test_apply_matrix2_not_unitary_no_twin(env, rng):
    """apply* family: arbitrary matrix, left-multiply only (no rho twin)."""
    m = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    q, vec = _rand_psi(env, rng)
    qt.applyMatrix2(q, 2, m)
    expect = oracle.full_operator(N, [2], m) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)
    # density: M . rho, NOT M rho M^dag (SURVEY.md §2.3 semantic trap)
    r, mat = _rand_rho(env, rng)
    qt.applyMatrix2(r, 2, m)
    expect_r = oracle.full_operator(N, [2], m) @ mat
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect_r, atol=ATOL)


def test_apply_matrix4(env, rng):
    m = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    q, vec = _rand_psi(env, rng)
    qt.applyMatrix4(q, 1, 3, m)
    expect = oracle.full_operator(N, [1, 3], m) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


@pytest.mark.parametrize("targets", [[0], [2, 4], [1, 0, 3]])
def test_apply_matrix_n(env, rng, targets):
    dim = 1 << len(targets)
    m = rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
    q, vec = _rand_psi(env, rng)
    qt.applyMatrixN(q, targets, m)
    expect = oracle.full_operator(N, targets, m) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_multi_controlled_matrix_n(env, rng):
    m = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    q, vec = _rand_psi(env, rng)
    qt.applyMultiControlledMatrixN(q, [0, 4], [2], m)
    expect = oracle.controlled_operator(N, [0, 4], [2], m) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_pauli_sum(env, rng):
    num_terms = 3
    codes = rng.integers(0, 4, size=(num_terms, N))
    coeffs = rng.standard_normal(num_terms)
    q, vec = _rand_psi(env, rng)
    out = qt.createQureg(N, env)
    qt.applyPauliSum(q, codes, coeffs, out)
    expect = oracle.pauli_sum_matrix(N, codes, coeffs) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(out), expect, atol=ATOL)
    # input register untouched
    np.testing.assert_allclose(oracle.state_from_qureg(q), vec, atol=ATOL)


def test_apply_pauli_hamil(env, rng):
    num_terms = 4
    codes = rng.integers(0, 4, size=(num_terms, N))
    coeffs = rng.standard_normal(num_terms)
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes)
    q, vec = _rand_psi(env, rng)
    out = qt.createQureg(N, env)
    qt.applyPauliHamil(q, hamil, out)
    expect = oracle.pauli_sum_matrix(N, codes, coeffs) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(out), expect, atol=ATOL)


@pytest.mark.parametrize("order,reps,tol", [(1, 30, 2e-2), (2, 10, 1e-3), (4, 3, 1e-4)])
def test_apply_trotter_circuit(env, rng, order, reps, tol):
    """e^{-iHt} approximation converging with order/reps (reference
    test_operators.cpp applyTrotterCircuit)."""
    num_terms = 3
    codes = rng.integers(0, 4, size=(num_terms, N))
    coeffs = rng.standard_normal(num_terms) * 0.5
    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes)
    t = 0.7
    q, vec = _rand_psi(env, rng)
    qt.applyTrotterCircuit(q, hamil, t, order, reps)
    hmat = oracle.pauli_sum_matrix(N, codes, coeffs)
    expect = expm(-1j * hmat * t) @ vec
    got = oracle.state_from_qureg(q)
    # compare up to nothing: Trotter is exact in the limit; tolerance scales
    assert np.max(np.abs(got - expect)) < tol


def test_apply_diagonal_op(env, rng):
    op = qt.createDiagonalOp(N, env)
    vals = rng.standard_normal(DIM) + 1j * rng.standard_normal(DIM)
    qt.initDiagonalOp(op, vals.real, vals.imag)
    q, vec = _rand_psi(env, rng)
    qt.applyDiagonalOp(q, op)
    np.testing.assert_allclose(oracle.state_from_qureg(q), vals * vec, atol=ATOL)
    # density: left-multiply D.rho
    r, mat = _rand_rho(env, rng)
    qt.applyDiagonalOp(r, op)
    np.testing.assert_allclose(
        oracle.state_from_qureg(r), np.diag(vals) @ mat, atol=ATOL
    )


# ---------------------------------------------------------------------------
# Phase functions
# ---------------------------------------------------------------------------


def _phase_expect(vec, reg_qubits, encoding, phase_fn, overrides=None):
    """Oracle: multiply amp_i by exp(i theta(x1..xm)) decoding sub-registers
    from index bits."""
    out = np.empty_like(vec)
    for i in range(DIM):
        xs = []
        for qs in reg_qubits:
            v = sum(((i >> q) & 1) << j for j, q in enumerate(qs))
            if encoding == qt.TWOS_COMPLEMENT and v >= (1 << (len(qs) - 1)):
                v -= 1 << len(qs)
            xs.append(v)
        theta = None
        if overrides:
            for inds, ph in overrides:
                if tuple(xs) == tuple(inds):
                    theta = ph
                    break
        if theta is None:
            theta = phase_fn(xs)
        out[i] = vec[i] * np.exp(1j * theta)
    return out


def test_apply_phase_func_polynomial(env, rng):
    q, vec = _rand_psi(env, rng)
    qubits = [0, 2, 3]
    coeffs = [0.5, -1.2]
    expos = [1.0, 2.0]
    qt.applyPhaseFunc(q, qubits, qt.UNSIGNED, coeffs, expos)
    expect = _phase_expect(
        vec, [qubits], qt.UNSIGNED,
        lambda xs: sum(c * xs[0] ** e for c, e in zip(coeffs, expos)),
    )
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_phase_func_twos_complement_with_overrides(env, rng):
    q, vec = _rand_psi(env, rng)
    qubits = [1, 4, 0]
    coeffs = [0.8]
    expos = [3.0]
    overrides = [((-4,), 0.123), ((1,), -2.5)]
    qt.applyPhaseFuncOverrides(
        q, qubits, qt.TWOS_COMPLEMENT, coeffs, expos,
        [o[0][0] for o in overrides], [o[1] for o in overrides],
    )
    expect = _phase_expect(
        vec, [qubits], qt.TWOS_COMPLEMENT,
        lambda xs: coeffs[0] * float(xs[0]) ** expos[0],
        overrides,
    )
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_multi_var_phase_func(env, rng):
    q, vec = _rand_psi(env, rng)
    regs = [[0, 1], [2, 3, 4]]
    terms_per_reg = [2, 1]
    coeffs = [1.0, 0.5, -0.3]
    expos = [1.0, 2.0, 1.0]
    qt.applyMultiVarPhaseFunc(q, [0, 1, 2, 3, 4], [2, 3], qt.UNSIGNED, coeffs, expos, terms_per_reg)
    expect = _phase_expect(
        vec, regs, qt.UNSIGNED,
        lambda xs: 1.0 * xs[0] + 0.5 * xs[0] ** 2 - 0.3 * xs[1],
    )
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


@pytest.mark.parametrize(
    "func,params,phase_fn",
    [
        (qt.NORM, None, lambda xs: np.sqrt(sum(x * x for x in xs))),
        (qt.SCALED_NORM, [2.5], lambda xs: 2.5 * np.sqrt(sum(x * x for x in xs))),
        (
            qt.INVERSE_NORM,
            [7.0],
            lambda xs: 7.0 if sum(x * x for x in xs) == 0 else 1 / np.sqrt(sum(x * x for x in xs)),
        ),
        (qt.PRODUCT, None, lambda xs: float(np.prod(xs))),
        (
            qt.SCALED_INVERSE_PRODUCT,
            [3.0, 9.0],
            lambda xs: 9.0 if np.prod(xs) == 0 else 3.0 / float(np.prod(xs)),
        ),
        (qt.DISTANCE, None, lambda xs: np.sqrt((xs[1] - xs[0]) ** 2)),
        (
            qt.SCALED_INVERSE_SHIFTED_NORM,
            [0.5, 4.0, 1.0, -1.0],
            lambda xs: 4.0
            if (xs[0] - 1.0) ** 2 + (xs[1] + 1.0) ** 2 == 0
            else 0.5 / np.sqrt((xs[0] - 1.0) ** 2 + (xs[1] + 1.0) ** 2),
        ),
    ],
)
def test_apply_named_phase_func(env, rng, func, params, phase_fn):
    q, vec = _rand_psi(env, rng)
    regs = [[0, 3], [1, 4]]
    if params is None:
        qt.applyNamedPhaseFunc(q, [0, 3, 1, 4], [2, 2], qt.UNSIGNED, func)
    else:
        qt.applyParamNamedPhaseFunc(q, [0, 3, 1, 4], [2, 2], qt.UNSIGNED, func, params)
    expect = _phase_expect(vec, regs, qt.UNSIGNED, phase_fn)
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_named_phase_func_overrides(env, rng):
    q, vec = _rand_psi(env, rng)
    regs = [[0, 1, 2], [3, 4]]
    overrides = [((0, 0), 0.77), ((5, 2), -0.3)]
    qt.applyNamedPhaseFuncOverrides(
        q, [0, 1, 2, 3, 4], [3, 2], qt.UNSIGNED, qt.NORM,
        [i for o in overrides for i in o[0]], [o[1] for o in overrides],
    )
    expect = _phase_expect(
        vec, regs, qt.UNSIGNED,
        lambda xs: np.sqrt(sum(x * x for x in xs)), overrides,
    )
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


# ---------------------------------------------------------------------------
# QFT
# ---------------------------------------------------------------------------


def test_apply_full_qft(env, rng):
    q, vec = _rand_psi(env, rng)
    qt.applyFullQFT(q)
    expect = oracle.dft_matrix(N) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_apply_full_qft_density(env, rng):
    r, mat = _rand_rho(env, rng)
    qt.applyFullQFT(r)
    F = oracle.dft_matrix(N)
    np.testing.assert_allclose(
        oracle.state_from_qureg(r), F @ mat @ F.conj().T, atol=ATOL
    )


@pytest.mark.parametrize("qubits", [[0], [1, 3], [4, 2, 0]])
def test_apply_qft_subset(env, rng, qubits):
    """applyQFT on a qubit subset == full operator built from the DFT on
    those qubits (qubits[0] = least significant)."""
    q, vec = _rand_psi(env, rng)
    qt.applyQFT(q, qubits)
    sub_dft = oracle.dft_matrix(len(qubits))
    expect = oracle.full_operator(N, qubits, sub_dft) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_operator_validation(env, rng):
    q = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="matrix size does not match"):
        qt.applyMatrix2(q, 0, np.eye(4))
    with pytest.raises(qt.QuESTError, match="Trotterisation order"):
        hamil = qt.createPauliHamil(N, 1)
        qt.applyTrotterCircuit(q, hamil, 0.1, 3, 1)
    with pytest.raises(qt.QuESTError, match="Invalid bit encoding"):
        qt.applyPhaseFunc(q, [0, 1], 5, [1.0], [1.0])


# ---------------------------------------------------------------------------
# Fused QFT (windowed-scheduler gate stream; single-device registers >= 14
# state-vector qubits take this path, sharded ones the layered path)
# ---------------------------------------------------------------------------


def _norm_psi(rng, n):
    v = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    return v / np.linalg.norm(v)


@pytest.mark.parametrize("qubits", [None, [0, 3, 9, 13, 7], [13, 2, 5]])
def test_fused_qft_matches_layered(rng, qubits):
    env1 = qt.createQuESTEnv(num_devices=1)   # fused path
    env8 = qt.createQuESTEnv()                # sharded -> layered fallback
    n = 14
    vec = _norm_psi(rng, n)

    q1 = qt.createQureg(n, env1)
    qt.initStateFromAmps(q1, vec.real.copy(), vec.imag.copy())
    q8 = qt.createQureg(n, env8)
    qt.initStateFromAmps(q8, vec.real.copy(), vec.imag.copy())
    if qubits is None:
        qt.applyFullQFT(q1)
        qt.applyFullQFT(q8)
    else:
        qt.applyQFT(q1, qubits)
        qt.applyQFT(q8, qubits)
    np.testing.assert_allclose(
        oracle.state_from_qureg(q1), oracle.state_from_qureg(q8), atol=1e-10
    )


def test_fused_qft_density_matches_layered(rng):
    env1 = qt.createQuESTEnv(num_devices=1)
    env8 = qt.createQuESTEnv()
    n = 7  # state vector = 14 qubits
    r1 = qt.createDensityQureg(n, env1)
    qt.initDebugState(r1)
    r8 = qt.createDensityQureg(n, env8)
    qt.initDebugState(r8)
    qt.applyFullQFT(r1)
    qt.applyFullQFT(r8)
    np.testing.assert_allclose(
        oracle.state_from_qureg(r1), oracle.state_from_qureg(r8), atol=1e-9
    )


def test_fused_qft_contiguous_high_subset(rng):
    """Contiguous run starting >= 7 takes the fused sub-run branch
    (B-side-only group reversal at k = min(o, n-7))."""
    env1 = qt.createQuESTEnv(num_devices=1)
    env8 = qt.createQuESTEnv()
    n = 16
    vec = _norm_psi(rng, n)
    q1 = qt.createQureg(n, env1)
    qt.initStateFromAmps(q1, vec.real.copy(), vec.imag.copy())
    q8 = qt.createQureg(n, env8)
    qt.initStateFromAmps(q8, vec.real.copy(), vec.imag.copy())
    qubits = list(range(7, 16))   # contiguous, start=7, count=9
    qt.applyQFT(q1, qubits)
    qt.applyQFT(q8, qubits)
    np.testing.assert_allclose(
        oracle.state_from_qureg(q1), oracle.state_from_qureg(q8), atol=1e-10
    )


def test_trotter_scan_matches_per_term_path(env, rng):
    """The lax.scan Trotter body (paulis.trotter_scan) must reproduce the
    per-term multiRotatePauli stream exactly (QASM recording forces the
    per-term path)."""
    for is_rho in (False, True):
        for order in (1, 2, 4):
            terms = 6
            codes = rng.integers(0, 4, (terms, N))
            coeffs = rng.standard_normal(terms)
            h = qt.createPauliHamil(N, terms)
            qt.initPauliHamil(h, coeffs, codes)
            make = qt.createDensityQureg if is_rho else qt.createQureg
            q1, q2 = make(N, env), make(N, env)
            qt.initDebugState(q1)
            qt.initDebugState(q2)
            qt.startRecordingQASM(q1)      # forces the per-term path
            qt.applyTrotterCircuit(q1, h, 0.37, order, 2)
            qt.stopRecordingQASM(q1)
            qt.applyTrotterCircuit(q2, h, 0.37, order, 2)
            np.testing.assert_allclose(
                np.asarray(q1.amps), np.asarray(q2.amps), atol=1e-12)


def test_trotter_scan_window_branch(env, rng):
    """14-qubit register: the scan body's windowed _product_layer branch
    (n >= 14) — the one the 24q config-5 workload exercises — must also
    match the per-term path, and so must the scan-based expectation."""
    n, terms = 14, 5
    codes = rng.integers(0, 4, (terms, n))
    coeffs = rng.standard_normal(terms)
    h = qt.createPauliHamil(n, terms)
    qt.initPauliHamil(h, coeffs, codes)
    q1, q2 = qt.createQureg(n, env), qt.createQureg(n, env)
    qt.initPlusState(q1)
    qt.initPlusState(q2)
    qt.startRecordingQASM(q1)          # forces the per-term path
    qt.applyTrotterCircuit(q1, h, 0.23, 2, 1)
    qt.stopRecordingQASM(q1)
    qt.applyTrotterCircuit(q2, h, 0.23, 2, 1)
    np.testing.assert_allclose(
        np.asarray(q1.amps), np.asarray(q2.amps), atol=1e-12)
    w = qt.createQureg(n, env)
    e_scan = qt.calcExpecPauliHamil(q2, h, w)
    # reference: the unrolled (static-code) expectation path
    from quest_tpu.ops import paulis as P
    e_ref = float(P.calc_expec_pauli_sum_statevec(
        q2.amps, coeffs, num_qubits=n,
        codes_flat=tuple(int(c) for c in codes.ravel()), num_terms=terms))
    np.testing.assert_allclose(e_scan, e_ref, atol=1e-10)


def test_parity_sign_split_halves(monkeypatch):
    """The 64-bit-safe factored parity sign (paulis._parity_sign_dynamic)
    must match direct popcount parity across the lo/hi split boundary
    (exercised by shrinking the split so small n crosses it)."""
    import jax.numpy as jnp
    from quest_tpu.ops import paulis as P

    monkeypatch.setattr(P, "_PAR_LO_BITS", 3)
    n = 6
    rng2 = np.random.default_rng(8)
    for _ in range(5):
        mask = int(rng2.integers(0, 1 << n))
        lo = jnp.uint32(mask & ((1 << 3) - 1))
        hi = jnp.uint32(mask >> 3)
        s = np.asarray(P._parity_sign_dynamic(lo, hi, n, jnp.float64))
        idx = np.arange(1 << n)
        masked = idx & mask
        ref = 1.0 - 2.0 * np.array([bin(v).count("1") & 1 for v in masked])
        np.testing.assert_array_equal(s, ref)
