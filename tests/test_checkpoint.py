"""Checkpoint/resume + debug API tests (reference QuEST_debug.h surface
plus the orbax-backed persistence that exceeds reference parity,
SURVEY.md §5.4)."""

import numpy as np
import pytest

import quest_tpu as qt
import oracle


class TestOrbaxCheckpoint:
    def test_statevec_roundtrip(self, env, tmp_path):
        q = qt.createQureg(5, env)
        qt.initDebugState(q)
        qt.hadamard(q, 2)
        before = oracle.state_from_qureg(q)
        qt.saveQureg(q, str(tmp_path / "ckpt"))
        q2 = qt.loadQureg(str(tmp_path / "ckpt"), env)
        assert q2.num_qubits_represented == 5
        assert not q2.is_density_matrix
        np.testing.assert_allclose(oracle.state_from_qureg(q2), before, atol=0)

    def test_density_roundtrip(self, env, tmp_path):
        q = qt.createDensityQureg(3, env)
        qt.initPlusState(q)
        qt.mixDepolarising(q, 0, 0.1)
        before = np.asarray(q.amps)
        qt.saveQureg(q, str(tmp_path / "ckpt"))
        q2 = qt.loadQureg(str(tmp_path / "ckpt"), env)
        assert q2.is_density_matrix
        np.testing.assert_allclose(np.asarray(q2.amps), before, atol=0)

    def test_missing_checkpoint_raises(self, env, tmp_path):
        with pytest.raises(qt.QuESTError):
            qt.loadQureg(str(tmp_path / "nope"), env)

    def test_precision_mismatch_raises_structured(self, env, tmp_path):
        """ISSUE 2 satellite: a checkpoint written at prec 2 loaded at
        prec 1 must raise a QuESTError naming both sides, not fail deep
        inside orbax resharding."""
        q = qt.createQureg(4, env)
        qt.initDebugState(q)
        qt.saveQureg(q, str(tmp_path / "ckpt"))
        qt.set_precision(1)
        try:
            with pytest.raises(qt.QuESTError) as ei:
                qt.loadQureg(str(tmp_path / "ckpt"), env)
        finally:
            qt.set_precision(2)
        msg = str(ei.value)
        assert "float64" in msg and "float32" in msg
        assert "precision mismatch" in msg
        # back at the written precision the same checkpoint loads fine
        q2 = qt.loadQureg(str(tmp_path / "ckpt"), env)
        np.testing.assert_allclose(np.asarray(q2.amps), np.asarray(q.amps),
                                   atol=0)

    def test_mesh_grown_past_shardable_size_strict_raises(self, env,
                                                          tmp_path):
        """A register too small to put one amplitude on each device of a
        GROWN mesh: strict_mesh=True keeps the old refusal with both
        sides named; the default now auto-shrinks onto a usable sub-mesh
        (elastic restore — tests/test_elastic.py TestLoadQuregElastic)."""
        if env.num_devices < 2:
            pytest.skip("needs a multi-device mesh")
        q = qt.createQureg(1, env)  # 2 amps < 8 devices
        qt.saveQureg(q, str(tmp_path / "ckpt"))
        with pytest.raises(qt.QuESTError) as ei:
            qt.loadQureg(str(tmp_path / "ckpt"), env, strict_mesh=True)
        msg = str(ei.value)
        assert "mesh has grown" in msg
        assert f"{env.num_devices} devices" in msg
        from quest_tpu import resilience as R

        with pytest.warns(UserWarning, match="loadQureg_mesh_"):
            q2 = qt.loadQureg(str(tmp_path / "ckpt"), env)
        assert q2.env.num_devices == 2
        np.testing.assert_array_equal(np.asarray(q2.amps),
                                      np.asarray(q.amps))
        R.DEGRADATIONS.pop(f"loadQureg_mesh_{env.num_devices}to2", None)

    def test_transient_io_error_retried(self, env, tmp_path, monkeypatch):
        """saveQureg rides the bounded-backoff retry wrapper: two
        injected transient failures are absorbed."""
        from quest_tpu import resilience as R

        monkeypatch.setenv("QT_RETRY_BASE_SECONDS", "0.001")
        plan = qt.FaultPlan("io@2")
        monkeypatch.setattr(R, "_ACTIVE_FAULTS", [plan])
        q = qt.createQureg(4, env)
        qt.initDebugState(q)
        qt.saveQureg(q, str(tmp_path / "ckpt"))
        assert plan.io_budget == 0
        q2 = qt.loadQureg(str(tmp_path / "ckpt"), env)
        np.testing.assert_allclose(np.asarray(q2.amps), np.asarray(q.amps),
                                   atol=0)


class TestCSVRoundtrip:
    def test_write_read(self, env, tmp_path):
        q = qt.createQureg(4, env)
        qt.initDebugState(q)
        qt.rotateY(q, 1, 0.3)
        before = oracle.state_from_qureg(q)
        path = str(tmp_path / "state.csv")
        qt.writeStateToFile(q, path)
        q2 = qt.createQureg(4, env)
        assert qt.initStateFromSingleFile(q2, path, env)
        np.testing.assert_allclose(oracle.state_from_qureg(q2), before, atol=1e-12)

    def test_missing_file_returns_false(self, env, tmp_path):
        q = qt.createQureg(3, env)
        assert not qt.initStateFromSingleFile(q, str(tmp_path / "nofile.csv"), env)

    def test_truncated_file_returns_false(self, env, tmp_path):
        path = tmp_path / "short.csv"
        path.write_text("0.5, 0.0\n0.5, 0.0\n")  # 2 of 8 amps
        q = qt.createQureg(3, env)
        qt.initZeroState(q)
        before = np.asarray(q.amps).copy()
        assert not qt.initStateFromSingleFile(q, str(path), env)
        np.testing.assert_allclose(np.asarray(q.amps), before)  # untouched

    def test_malformed_file_returns_false(self, env, tmp_path):
        path = tmp_path / "bad.csv"
        path.write_text("0.5\n" * 8)  # missing imaginary column
        q = qt.createQureg(3, env)
        assert not qt.initStateFromSingleFile(q, str(path), env)

    def test_malformed_mid_stream_leaves_state_untouched(self, env, tmp_path):
        """The streamed reader only rebinds the register on full success
        — a bad line after some good ones must not corrupt the state."""
        path = tmp_path / "midbad.csv"
        path.write_text("0.5, 0.0\n0.5, 0.0\nnot-a-number\n0.5, 0.0\n")
        q = qt.createQureg(3, env)
        qt.initDebugState(q)
        before = np.asarray(q.amps).copy()
        assert not qt.readStateFromFile(q, str(path))
        np.testing.assert_allclose(np.asarray(q.amps), before)

    def test_read_streams_past_host_gather_cap(self, env, tmp_path,
                                               monkeypatch):
        """ADVICE r5: writeStateToFile streams any size to disk, and the
        streamed reader must load those files back — round-trip symmetry.
        Pin the message cap below the register size: the old reader
        hard-failed through _guard_host_gather here; the streamed one
        (tile-aligned ranged setAmps, no full-state host buffer) must
        succeed."""
        from quest_tpu import precision

        q = qt.createQureg(5, env)
        qt.initDebugState(q)
        qt.hadamard(q, 1)
        before = oracle.state_from_qureg(q)
        path = str(tmp_path / "big.csv")
        qt.writeStateToFile(q, path)
        monkeypatch.setitem(precision._MAX_AMPS_IN_MSG,
                            precision.get_precision(), 4)
        # the gather-guarded debug paths still refuse...
        with pytest.raises(qt.QuESTError):
            qt.compareStates(q, q, 1.0)
        # ...but the streamed reader round-trips
        q2 = qt.createQureg(5, env)
        assert qt.readStateFromFile(q2, path)
        np.testing.assert_allclose(oracle.state_from_qureg(q2), before,
                                   atol=1e-12)

    def test_garbage_binary_file_leaves_state_untouched(self, env,
                                                        tmp_path):
        """ISSUE 2 satellite: a corrupt (binary-garbage) file must report
        failure and restore nothing — the streamed reader only rebinds on
        full success."""
        path = tmp_path / "garbage.csv"
        path.write_bytes(b"\x00\xff\xfe corrupted \x80\x81\n" * 16)
        q = qt.createQureg(3, env)
        qt.initDebugState(q)
        before = np.asarray(q.amps).copy()
        assert not qt.readStateFromFile(q, str(path))
        np.testing.assert_allclose(np.asarray(q.amps), before)

    def test_nonfinite_values_rejected(self, env, tmp_path):
        """NaN/Inf in a state CSV is bit rot, not data: reject and leave
        the register untouched."""
        for bad in ("nan, 0.0", "0.0, inf", "-inf, 0.0"):
            path = tmp_path / "bad.csv"
            path.write_text("0.5, 0.0\n" + bad + "\n" + "0.5, 0.0\n" * 6)
            q = qt.createQureg(3, env)
            qt.initDebugState(q)
            before = np.asarray(q.amps).copy()
            assert not qt.readStateFromFile(q, str(path))
            np.testing.assert_allclose(np.asarray(q.amps), before)

    def test_corrupt_file_roundtrip_recovers(self, env, tmp_path):
        """Corrupt-file round-trip: write -> corrupt -> failed read leaves
        the target usable -> re-write -> read succeeds."""
        q = qt.createQureg(4, env)
        qt.initDebugState(q)
        qt.rotateY(q, 2, 0.4)
        before = oracle.state_from_qureg(q)
        path = tmp_path / "state.csv"
        qt.writeStateToFile(q, str(path))
        good = path.read_text()
        path.write_text(good[: len(good) // 2] + "\x00garbage")
        q2 = qt.createQureg(4, env)
        qt.initZeroState(q2)
        zero = np.asarray(q2.amps).copy()
        assert not qt.readStateFromFile(q2, str(path))
        np.testing.assert_allclose(np.asarray(q2.amps), zero)
        path.write_text(good)
        assert qt.readStateFromFile(q2, str(path))
        np.testing.assert_allclose(oracle.state_from_qureg(q2), before,
                                   atol=1e-12)

    def test_read_multi_chunk_stream(self, env, tmp_path, monkeypatch):
        """Force several flush chunks through the ranged-write path."""
        from quest_tpu import checkpoint

        monkeypatch.setattr(checkpoint, "_READ_CHUNK", 8)
        q = qt.createQureg(5, env)     # 32 amps -> 4 chunks
        qt.initDebugState(q)
        qt.rotateY(q, 3, 0.7)
        before = oracle.state_from_qureg(q)
        path = str(tmp_path / "chunks.csv")
        qt.writeStateToFile(q, path)
        q2 = qt.createQureg(5, env)
        assert qt.readStateFromFile(q2, path)
        np.testing.assert_allclose(oracle.state_from_qureg(q2), before,
                                   atol=1e-12)


class TestDebugAPI:
    @pytest.mark.parametrize("qubit,outcome", [(0, 0), (2, 1), (4, 0)])
    def test_init_state_of_single_qubit(self, env, qubit, outcome):
        q = qt.createQureg(5, env)
        qt.initStateOfSingleQubit(q, qubit, outcome)
        state = oracle.state_from_qureg(q)
        idx = np.arange(32)
        expect = np.where(
            ((idx >> qubit) & 1) == outcome, 1.0 / np.sqrt(16.0), 0.0
        ).astype(complex)
        np.testing.assert_allclose(state, expect, atol=1e-12)
        assert abs(qt.calcTotalProb(q) - 1.0) < 1e-10

    def test_invalid_outcome_raises(self, env):
        q = qt.createQureg(4, env)
        with pytest.raises(qt.QuESTError):
            qt.initStateOfSingleQubit(q, 1, 2)

    def test_compare_states(self, env):
        q1 = qt.createQureg(4, env)
        q2 = qt.createQureg(4, env)
        qt.initDebugState(q1)
        qt.initDebugState(q2)
        assert qt.compareStates(q1, q2, 1e-12)
        qt.rotateX(q2, 0, 1e-3)
        assert not qt.compareStates(q1, q2, 1e-6)
        assert qt.compareStates(q1, q2, 1.0)

    def test_compare_states_size_mismatch(self, env):
        q1 = qt.createQureg(3, env)
        q2 = qt.createQureg(4, env)
        assert not qt.compareStates(q1, q2, 1.0)


class TestProfiling:
    def test_timed(self, env):
        from quest_tpu.utils import profiling

        q = qt.createQureg(4, env)
        with profiling.timed("h", sync=None) as t:
            qt.hadamard(q, 0)
        assert t["seconds"] >= 0

    def test_annotate(self):
        from quest_tpu.utils import profiling

        with profiling.annotate("phase"):
            pass
