"""Distributed-path tests on the virtual 8-device mesh — the analogue of the
reference's `mpirun -np 8` single-box testing (examples/README.md:404-407).

Checks (a) the explicit ppermute kernels agree with the GSPMD path and with
the oracle on gates touching sharded qubits, (b) the half-shard SWAP
relocalization, (c) psum reductions, (d) a full mixed circuit."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.parallel import dist
import oracle

N = 6  # 2^6 = 64 amps over 8 devices -> nloc = 3: qubits 3,4,5 are sharded
ATOL = 1e-10


@pytest.fixture(autouse=True)
def _require_multidevice(env):
    if env.num_devices < 2:
        pytest.skip("needs the 8-device virtual mesh")


def _rand_psi(env, rng, n=N):
    vec = oracle.random_state(n, rng)
    q = qt.createQureg(n, env)
    oracle.set_qureg_from_array(qt, q, vec)
    return q, vec


def test_sharding_layout(env):
    q = qt.createQureg(N, env)
    assert q.num_chunks == env.num_devices
    # amps live sharded over the amp axis (slices are unhashable before
    # py3.12 — key on their bounds)
    shardings = {
        tuple((sl.start, sl.stop) for sl in s.index)
        for s in q.amps.addressable_shards
    }
    assert len(shardings) == env.num_devices


@pytest.mark.parametrize("target", range(N))
def test_1q_gate_all_targets_explicit_vs_oracle(env, target):
    """hadamard on every qubit — targets >= nloc exercise the ppermute
    exchange."""
    rng = np.random.default_rng(7)
    q, vec = _rand_psi(env, rng)
    qt.hadamard(q, target)
    expect = oracle.apply_to_statevec(vec, N, [target], oracle.H)
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


@pytest.mark.parametrize("target", [4, 5])
@pytest.mark.parametrize("ctrl", [0, 3])
def test_controlled_gate_sharded_target(env, target, ctrl):
    """Controls both local (0) and sharded (3) with a sharded target."""
    rng = np.random.default_rng(8)
    u = oracle.random_unitary(1, rng)
    q, vec = _rand_psi(env, rng)
    qt.controlledUnitary(q, ctrl, target, u)
    expect = oracle.apply_to_statevec(vec, N, [target], u, [ctrl])
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_explicit_matches_gspmd(env):
    """Same circuit under both code paths gives identical states."""
    rng = np.random.default_rng(9)
    u = oracle.random_unitary(1, rng)

    def run():
        q, _ = _rand_psi(env, np.random.default_rng(10))
        qt.hadamard(q, 5)
        qt.controlledUnitary(q, 1, 4, u)
        qt.unitary(q, 3, u)
        return oracle.state_from_qureg(q)

    try:
        dist.use_explicit_dist(True)
        a = run()
        dist.use_explicit_dist(False)
        b = run()
    finally:
        dist.use_explicit_dist(True)
    np.testing.assert_allclose(a, b, atol=ATOL)


@pytest.mark.parametrize("lo,hi", [(0, 3), (2, 5), (1, 4)])
def test_swap_sharded_half_exchange(env, lo, hi):
    rng = np.random.default_rng(11)
    q, vec = _rand_psi(env, rng)
    got = dist.swap_sharded(
        q.amps, mesh=env.mesh, num_qubits=N, qb_low=lo, qb_high=hi
    )
    q.amps = got
    SWAP = np.array([[1, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]])
    expect = oracle.apply_to_statevec(vec, N, [lo, hi], SWAP)
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_multiqubit_unitary_swap_relocalization(env):
    """Dense 2q unitary with both targets sharded: swap-relocalize, apply,
    undo (reference QuEST_cpu_distributed.c:1503-1545)."""
    rng = np.random.default_rng(12)
    u = oracle.random_unitary(2, rng)
    q, vec = _rand_psi(env, rng)
    qt.multiQubitUnitary(q, [4, 5], u)
    expect = oracle.apply_to_statevec(vec, N, [4, 5], u)
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


def test_plan_relocalization_collision_avoidance(env):
    swaps, new_targets = dist.plan_relocalization(6, 3, (4, 0), controls=(1,))
    # free local pool excludes targets {4,0}->{0} and control {1}: first free is 2
    assert swaps == ((2, 4),)
    assert new_targets == (2, 0)
    # impossible case: all local qubits blocked
    swaps, new_targets = dist.plan_relocalization(4, 1, (1, 2, 3), controls=(0,))
    assert swaps is None


def test_total_prob_psum(env):
    rng = np.random.default_rng(13)
    q, vec = _rand_psi(env, rng)
    got = float(dist.total_prob_sharded(q.amps, mesh=env.mesh))
    assert np.isclose(got, 1.0)


def test_gather_replicated(env):
    rng = np.random.default_rng(14)
    q, vec = _rand_psi(env, rng)
    full = np.asarray(dist.gather_replicated(q.amps, mesh=env.mesh))
    np.testing.assert_allclose(full[0] + 1j * full[1], vec, atol=ATOL)


def test_full_circuit_sharded_density(env):
    """Mixed circuit on a sharded density matrix (12-qubit flattened state
    over 8 devices)."""
    n = 4
    rng = np.random.default_rng(15)
    mat = oracle.random_density(n, rng)
    r = qt.createDensityQureg(n, env)
    oracle.set_qureg_from_array(qt, r, mat)
    u = oracle.random_unitary(1, rng)
    qt.hadamard(r, 3)
    qt.controlledUnitary(r, 0, 2, u)
    qt.mixDepolarising(r, 3, 0.2)
    H = oracle.full_operator(n, [3], oracle.H)
    CU = oracle.controlled_operator(n, [0], [2], u)
    m2 = CU @ (H @ mat @ H.conj().T) @ CU.conj().T
    expect = (1 - 0.2) * m2
    for P in (oracle.X, oracle.Y, oracle.Z):
        PP = oracle.full_operator(n, [3], P)
        expect = expect + (0.2 / 3) * PP @ m2 @ PP
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)
    assert np.isclose(qt.calcTotalProb(r), 1.0)


def test_fused_qft_sharded_matches_dft(env):
    """The fused QFT on an 8-way-sharded register must equal the dense DFT
    oracle (the sharded path runs the same ladder/reversal program under
    GSPMD — collectives audited in test_distributed_hlo.py)."""
    n = 14
    q = qt.createQureg(n, env)
    rng = np.random.default_rng(61)
    vec = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec /= np.linalg.norm(vec)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    qt.applyFullQFT(q)
    got = np.asarray(q.amps[0]) + 1j * np.asarray(q.amps[1])
    # ifft(vec, norm="ortho") == exp(+2*pi*i jk/N)/sqrt(N) @ vec, O(N log N)
    ref = np.fft.ifft(vec, norm="ortho")
    np.testing.assert_allclose(got, ref, atol=1e-10)


@pytest.mark.parametrize("kind", ["depol", "damping"])
@pytest.mark.parametrize("target", [0, 1, 2, 3])
def test_explicit_pair_channels_vs_oracle(env, kind, target):
    """mixDepolarising / mixDamping on a sharded density matrix route
    through the explicit one-ppermute pair-exchange kernel
    (dist.mix_pair_channel_sharded) whenever the bra target bit is a
    mesh-coordinate bit; every target is checked against the dense Kraus
    oracle (covers ket-local/bra-sharded AND both-sharded cases)."""
    n = 4
    p = 0.35
    rng = np.random.default_rng(40 + target)
    mat = oracle.random_density(n, rng)
    r = qt.createDensityQureg(n, env)
    oracle.set_qureg_from_array(qt, r, mat)
    if kind == "depol":
        qt.mixDepolarising(r, target, p)
        ks = [np.sqrt(1 - p) * oracle.I2, np.sqrt(p / 3) * oracle.X,
              np.sqrt(p / 3) * oracle.Y, np.sqrt(p / 3) * oracle.Z]
    else:
        qt.mixDamping(r, target, p)
        ks = [np.array([[1, 0], [0, np.sqrt(1 - p)]]),
              np.array([[0, np.sqrt(p)], [0, 0]])]
    expect = np.zeros_like(mat)
    for k in ks:
        K = oracle.full_operator(n, [target], k)
        expect = expect + K @ mat @ K.conj().T
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect, atol=ATOL)


# ---------------------------------------------------------------------------
# Scan-based composites + general-run QFT: one kernel set on sharded meshes
# (VERDICT r3 item 1 — the paths that used to bail to per-term/layered)
# ---------------------------------------------------------------------------


def _rand_hamil(qt_mod, n, nterms, rng):
    codes = rng.integers(0, 4, size=(nterms, n))
    coeffs = rng.normal(size=nterms)
    h = qt_mod.createPauliHamil(n, nterms)
    qt_mod.initPauliHamil(h, coeffs, codes.ravel())
    return h, codes, coeffs


def _hamil_matrix(n, codes, coeffs):
    mats = [oracle.I2, oracle.X, oracle.Y, oracle.Z]
    H = np.zeros((1 << n, 1 << n), complex)
    for t in range(codes.shape[0]):
        term = np.eye(1)
        for q in range(n - 1, -1, -1):
            term = np.kron(term, mats[codes[t, q]])
        H = H + coeffs[t] * term
    return H


def test_trotter_scan_sharded_vs_oracle(env):
    """applyTrotterCircuit on a sharded statevector runs the shard_map
    scan (dist.trotter_scan_sharded) and must match the dense
    first-order product-formula oracle."""
    n = 6
    rng = np.random.default_rng(71)
    q, vec = _rand_psi(env, rng, n)
    h, codes, coeffs = _rand_hamil(qt, n, 3, rng)
    t, reps = 0.21, 2
    qt.applyTrotterCircuit(q, h, t, 1, reps)
    expect = vec
    for _ in range(reps):
        for k in range(codes.shape[0]):
            term = _hamil_matrix(n, codes[k:k + 1], coeffs[k:k + 1])
            from scipy.linalg import expm
            expect = expm(-1j * term * (t / reps)) @ expect
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect,
                               atol=1e-10)


def test_trotter_scan_sharded_density(env):
    """Sharded density-matrix Trotter (bra twin layers included) matches
    the unitary-conjugation oracle."""
    n = 4
    rng = np.random.default_rng(72)
    mat = oracle.random_density(n, rng)
    r = qt.createDensityQureg(n, env)
    oracle.set_qureg_from_array(qt, r, mat)
    h, codes, coeffs = _rand_hamil(qt, n, 2, rng)
    t = 0.4
    qt.applyTrotterCircuit(r, h, t, 1, 1)
    from scipy.linalg import expm
    expect = mat
    for k in range(codes.shape[0]):
        term = _hamil_matrix(n, codes[k:k + 1], coeffs[k:k + 1])
        U = expm(-1j * term * t)
        expect = U @ expect @ U.conj().T
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect,
                               atol=1e-10)


def test_expec_pauli_sum_sharded_vs_oracle(env):
    """calcExpecPauliHamil on a sharded statevector runs the shard_map
    scan (dist.expec_pauli_sum_scan_sharded) and must match <psi|H|psi>."""
    n = 6
    rng = np.random.default_rng(73)
    q, vec = _rand_psi(env, rng, n)
    h, codes, coeffs = _rand_hamil(qt, n, 5, rng)
    got = qt.calcExpecPauliHamil(q, h)
    H = _hamil_matrix(n, codes, coeffs)
    expect = float(np.real(vec.conj() @ H @ vec))
    assert abs(got - expect) < 1e-10


@pytest.mark.parametrize("start,count", [(0, 4), (0, 6), (7, 5), (11, 3)])
def test_partial_qft_sharded_vs_oracle(env, start, count):
    """applyQFT on a sub-run of a sharded register routes through
    dist.fused_qft_runs_sharded (when the register is window-sized the
    fused path engages; below it the layered path runs — both must match
    the dense DFT oracle embedded on the run)."""
    n = 14 if start else 6
    rng = np.random.default_rng(74 + start + count)
    q, vec = _rand_psi(env, rng, n)
    qt.applyQFT(q, list(range(start, start + count)))
    D = oracle.dft_matrix(count)
    expect = oracle.full_operator(
        n, list(range(start, start + count)), D) @ vec
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect,
                               atol=1e-10)


def test_density_full_qft_sharded_vs_oracle(env):
    """applyFullQFT on a sharded density matrix (ket run + conjugated
    bra run through dist.fused_qft_runs_sharded) equals F rho F^dag."""
    n = 4
    rng = np.random.default_rng(77)
    mat = oracle.random_density(n, rng)
    r = qt.createDensityQureg(n, env)
    oracle.set_qureg_from_array(qt, r, mat)
    qt.applyFullQFT(r)
    F = oracle.dft_matrix(n)
    np.testing.assert_allclose(oracle.state_from_qureg(r),
                               F @ mat @ F.conj().T, atol=1e-10)


def test_runs_sharded_window_sized_register(env):
    """The general-run kernel on a register large enough for the fused
    window path (18 state bits over 8 devices -> nloc = 15): density
    full QFT vs the DFT oracle — run 1 executes circuit.fused_qft per
    shard, run 2 the ppermute mesh layers + mixed reversal."""
    n = 9
    r = qt.createDensityQureg(n, env)
    qt.initDebugState(r)
    mat0 = oracle.state_from_qureg(r)
    qt.applyFullQFT(r)
    F = oracle.dft_matrix(n)
    np.testing.assert_allclose(oracle.state_from_qureg(r),
                               F @ mat0 @ F.conj().T, atol=1e-9)
