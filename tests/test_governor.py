"""Memory-governed execution suite (quest_tpu/governor.py, ISSUE 9).

Covers the acceptance contract:
  * admission control — createQureg / createDensityQureg /
    createBatchedQureg are refused up front with a structured
    MemoryAdmissionError naming predicted vs available bytes when the
    register cannot fit under the per-device HBM budget;
  * the analytic drain predictor (explain_circuit's ``memory`` section)
    agrees with the measured ``hbm_watermark_bytes`` peak on the
    8-shard dryrun within 10%;
  * spill-to-host eviction round-trips bit-identically — amplitudes,
    live permutation, and the batched measurement-key bank — including
    a spilled register transparently restored inside run_resumable;
  * the pinned degradation-ladder scenario: a budget just below the
    unconstrained peak makes the drain degrade visibly (exchange-chunk
    bump / program split / spill, counted in
    governor_degradations_total) while completing bit-identically,
    and QT_MEM_POLICY=strict raises instead — before any dispatch;
  * the ru_maxrss platform fix: kilobytes on Linux, bytes on Darwin.
"""

import warnings

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import governor as G
from quest_tpu import resilience as R
from quest_tpu import telemetry as T
from quest_tpu.parallel import dist as PAR
from quest_tpu.utils import profiling


U2 = np.linalg.qr(np.random.default_rng(11).normal(size=(4, 4)))[0]
U2_SOA = np.stack([U2, np.zeros_like(U2)])

NBIG = 13  # 16 KiB per device on the 8-way dryrun mesh (f64)


@pytest.fixture(autouse=True)
def _fresh_governor(monkeypatch):
    """Each test starts with an empty ledger, no budget, default policy,
    and no leftover governor chunk override; degradation warnings from
    the ladder are expected, so they are not treated as errors."""
    monkeypatch.delenv("QT_HBM_BUDGET_BYTES", raising=False)
    monkeypatch.delenv("QT_MEM_POLICY", raising=False)
    monkeypatch.delenv("QT_EXCHANGE_CHUNKS", raising=False)
    monkeypatch.setenv("QT_RETRY_BASE_SECONDS", "0.001")
    G.reset()
    for k in list(R.DEGRADATIONS):
        if k.startswith("memory_governor"):
            R.DEGRADATIONS.pop(k)
    yield
    G.reset()


def _big_workload(q):
    """Two windows: a local gate, then a gate on the sharded top qubits
    forcing a remap exchange (the transient the chunk rung shrinks)."""
    with qt.gateFusion(q):
        qt.multiQubitUnitary(q, [0, 1], U2)
        qt.multiQubitUnitary(q, [NBIG - 2, NBIG - 1], U2)


def _predict(env, budget=1 << 40):
    """The unconstrained predictor numbers for _big_workload."""
    import os

    os.environ["QT_HBM_BUDGET_BYTES"] = str(budget)
    try:
        G.reset()
        q = qt.createQureg(NBIG, env)
        with qt.gateFusion(q):
            qt.multiQubitUnitary(q, [0, 1], U2)
            qt.multiQubitUnitary(q, [NBIG - 2, NBIG - 1], U2)
            rep = qt.explain_circuit(q)
        mem = rep["memory"]
        amps = np.asarray(q.amps)
        qt.destroyQureg(q, env)
        return mem, amps
    finally:
        del os.environ["QT_HBM_BUDGET_BYTES"]
        G.reset()


class TestAdmission:
    def test_within_budget_admits_and_tracks(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        assert G.resident_bytes() == G.register_bytes_per_device(q) == 16384
        qt.destroyQureg(q, env)
        assert G.resident_bytes() == 0

    def test_reject_math_and_error_attrs(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", "8192")
        with pytest.raises(qt.MemoryAdmissionError) as ei:
            qt.createQureg(NBIG, env)
        e = ei.value
        assert e.predicted_bytes == 16384
        assert e.available_bytes == 8192
        assert e.budget_bytes == 8192
        assert "createQureg" in str(e)
        assert "16384" in str(e) and "8192" in str(e)
        assert T.counter_total("admission_rejects_total") >= 1

    def test_reject_accounts_for_resident_registers(self, env, monkeypatch):
        # two big registers fit alone but not together
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(16384 + 1024))
        q1 = qt.createQureg(NBIG, env)
        with pytest.raises(qt.MemoryAdmissionError) as ei:
            qt.createQureg(NBIG, env)
        assert ei.value.available_bytes == 1024  # budget minus q1
        qt.destroyQureg(q1, env)
        q2 = qt.createQureg(NBIG, env)  # admitted once q1 is released
        qt.destroyQureg(q2, env)

    def test_density_admission(self, env, monkeypatch):
        # a 7-qubit density matrix is a 14-qubit register
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", "16384")
        with pytest.raises(qt.MemoryAdmissionError) as ei:
            qt.createDensityQureg(7, env)
        assert "createDensityQureg" in str(ei.value)
        assert ei.value.predicted_bytes == 32768

    def test_batched_admission_scales_with_batch(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(3 * 16384))
        b = qt.createBatchedQureg(NBIG, env, 3)  # exactly fits
        assert G.register_bytes_per_device(b) == 3 * 16384
        G.release(b)
        with pytest.raises(qt.MemoryAdmissionError) as ei:
            qt.createBatchedQureg(NBIG, env, 4)
        assert "createBatchedQureg" in str(ei.value)
        assert ei.value.predicted_bytes == 4 * 16384

    def test_no_budget_means_inert(self, env):
        assert not G.enabled()
        q = qt.createQureg(NBIG, env)  # no budget -> nothing refused
        qt.destroyQureg(q, env)

    def test_policy_off_disables_even_with_budget(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", "64")
        monkeypatch.setenv("QT_MEM_POLICY", "off")
        q = qt.createQureg(NBIG, env)
        qt.destroyQureg(q, env)


class TestPredictor:
    def test_explain_memory_section_shape(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        with qt.gateFusion(q):
            qt.multiQubitUnitary(q, [0, 1], U2)
            qt.multiQubitUnitary(q, [NBIG - 2, NBIG - 1], U2)
            rep = qt.explain_circuit(q)
        mem = rep["memory"]
        for key in ("policy", "budget_bytes", "state_bytes_per_device",
                    "pass_array_bytes", "live_multiplier",
                    "exchange_chunks", "predicted_peak_bytes",
                    "other_resident_bytes", "predicted_total_bytes",
                    "headroom_bytes", "fits"):
            assert key in mem, key
        assert mem["state_bytes_per_device"] == 16384
        assert mem["fits"] is True
        assert mem["predicted_peak_bytes"] >= mem["state_bytes_per_device"]
        assert "memory:" in rep.table()
        qt.destroyQureg(q, env)

    def test_predictor_matches_measured_watermark(self, env, monkeypatch):
        """Acceptance: explain_circuit's predicted peak agrees with the
        measured hbm_watermark_bytes peak within 10% on the 8-shard
        dryrun (the model gauge stands in for device memory_stats on
        CPU)."""
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        with qt.gateFusion(q):
            qt.multiQubitUnitary(q, [0, 1], U2)
            qt.multiQubitUnitary(q, [NBIG - 2, NBIG - 1], U2)
            rep = qt.explain_circuit(q)
        predicted = rep["memory"]["predicted_total_bytes"]
        # the context exit above ran the drain -> usage was recorded
        wm = profiling.memory_watermark()
        assert "model" in wm
        measured = wm["model"]["modeled_peak_bytes_in_use"]
        assert measured == G.modeled_watermark_bytes()
        assert abs(predicted - measured) <= 0.10 * measured
        gauges = T.snapshot().get("gauges", {})
        assert gauges.get("hbm_watermark_bytes", {}).get(
            "device=model") == measured
        qt.destroyQureg(q, env)

    def test_explain_is_side_effect_free(self, env, monkeypatch):
        """The memory section must not touch telemetry counters or the
        fusion plan cache (the pinned explain contract)."""
        from quest_tpu import fusion

        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        fusion._plan_cache.clear()
        with qt.gateFusion(q):
            qt.multiQubitUnitary(q, [0, 1], U2)
            qt.multiQubitUnitary(q, [NBIG - 2, NBIG - 1], U2)
            before_counters = dict(T.snapshot().get("counters", {}))
            qt.explain_circuit(q)
            assert len(fusion._plan_cache) == 0
            after_counters = dict(T.snapshot().get("counters", {}))
            assert after_counters == before_counters
        qt.destroyQureg(q, env)


class TestSpill:
    def test_spill_restore_amps_and_perm(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        _big_workload(q)  # leaves a live logical->physical permutation
        amps0 = np.asarray(q.amps)
        perm0 = tuple(q._perm) if q._perm is not None else None
        assert G.spill_register(q) == 16384
        assert q._amps is None
        assert T.counter_total("spills_total") >= 1
        assert T.counter_total("spill_bytes_total") >= 2 * (1 << NBIG) * 8
        # first touch restores lazily, bit-identically
        amps1 = np.asarray(q.amps)
        np.testing.assert_array_equal(amps0, amps1)
        perm1 = tuple(q._perm) if q._perm is not None else None
        assert perm0 == perm1
        assert T.counter_total("spill_restores_total") >= 1
        qt.destroyQureg(q, env)

    def test_spill_preserves_batched_key_bank(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        b = qt.createBatchedQureg(6, env, 3)
        qt.applyBatchedUnitary(b, [0], np.stack(
            [np.stack([np.eye(2), np.zeros((2, 2))])] * 3))
        qt.measureBatched(b, 0)  # advance the per-element key bank
        keys0 = np.asarray(b.key_state())
        amps0 = np.asarray(b.amps)
        assert G.spill_register(b) > 0
        np.testing.assert_array_equal(np.asarray(b.amps), amps0)
        np.testing.assert_array_equal(np.asarray(b.key_state()), keys0)

    def test_destroyed_register_still_raises(self, env):
        q = qt.createQureg(5, env)
        qt.destroyQureg(q, env)
        with pytest.raises(qt.QuESTError, match="destroyed"):
            _ = q.amps

    def test_spilled_register_resumes_via_run_resumable(
            self, env, tmp_path, monkeypatch):
        """A register spilled to host is transparently restored when
        run_resumable touches it — the resumed stream is bit-identical
        to the never-spilled run."""
        from quest_tpu import circuit as CIRC

        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        gates = [CIRC.Gate((0, 1), U2_SOA), CIRC.Gate((4, 5), U2_SOA),
                 CIRC.Gate((2, 3), U2_SOA), CIRC.Gate((0, 5), U2_SOA)]

        qt.seedQuEST(env, [3])
        ref = qt.createQureg(6, env)
        qt.run_resumable(ref, gates, str(tmp_path / "ref"), every=2)
        want = np.asarray(ref.amps)
        qt.destroyQureg(ref, env)

        qt.seedQuEST(env, [3])
        q = qt.createQureg(6, env)
        assert G.spill_register(q) > 0
        assert q._amps is None
        qt.run_resumable(q, gates, str(tmp_path / "spilled"), every=2)
        np.testing.assert_array_equal(np.asarray(q.amps), want)
        qt.destroyQureg(q, env)


class TestDegradationLadder:
    def test_chunk_bump_completes_bit_identically(self, env, monkeypatch):
        """Pinned scenario: QT_HBM_BUDGET_BYTES one byte below the
        unconstrained predicted peak -> the drain visibly degrades
        (exchange-chunk bump counted in governor_degradations_total)
        and still completes bit-identically."""
        mem, want = _predict(env)
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES",
                           str(mem["predicted_total_bytes"] - 1))
        before = T.counter_total("governor_degradations_total")
        q = qt.createQureg(NBIG, env)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _big_workload(q)
        np.testing.assert_array_equal(np.asarray(q.amps), want)
        assert T.counter_total("governor_degradations_total") > before
        snap = T.snapshot()["counters"]["governor_degradations_total"]
        assert any("chunks" in k or "split" in k for k in snap)
        assert any(k.startswith("memory_governor")
                   for k in qt.degradation_report())
        # the override is cleared once the drain ends
        assert PAR._GOVERNOR_CHUNKS[0] is None
        qt.destroyQureg(q, env)

    def test_spill_rung_evicts_idle_register(self, env, monkeypatch):
        """When shrinking transients cannot make the drain fit, the
        ladder spills LRU-idle registers to host; the spilled register
        restores bit-identically afterwards."""
        idle = qt.createQureg(NBIG, env)
        idle_amps = np.asarray(idle.amps)
        active = qt.createQureg(6, env)
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES",
                           str(G.register_bytes_per_device(idle)))
        spills0 = T.counter_total("spills_total")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with qt.gateFusion(active):
                qt.multiQubitUnitary(active, [0, 1], U2)
        assert T.counter_total("spills_total") > spills0
        assert idle._amps is None  # evicted
        monkeypatch.delenv("QT_HBM_BUDGET_BYTES")
        np.testing.assert_array_equal(np.asarray(idle.amps), idle_amps)
        qt.destroyQureg(idle, env)
        qt.destroyQureg(active, env)

    def test_strict_raises_before_dispatch(self, env, monkeypatch):
        """QT_MEM_POLICY=strict refuses the drain with a structured
        error naming predicted vs available bytes instead of degrading.
        Nothing was dispatched: the gates stay queued, and lifting the
        budget lets the SAME drain complete bit-identically."""
        mem, want = _predict(env)
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES",
                           str(mem["predicted_total_bytes"] - 1))
        monkeypatch.setenv("QT_MEM_POLICY", "strict")
        rejects0 = T.counter_total("admission_rejects_total")
        q = qt.createQureg(NBIG, env)
        with pytest.raises(qt.MemoryAdmissionError) as ei:
            _big_workload(q)
        e = ei.value
        assert e.predicted_bytes == mem["predicted_total_bytes"]
        assert e.available_bytes == mem["predicted_total_bytes"] - 1
        assert str(e.predicted_bytes) in str(e)
        assert T.counter_total("admission_rejects_total") > rejects0
        # the refused gates are still queued; with the constraint lifted
        # the drain proceeds and matches the unconstrained run
        monkeypatch.delenv("QT_HBM_BUDGET_BYTES")
        monkeypatch.delenv("QT_MEM_POLICY")
        np.testing.assert_array_equal(np.asarray(q.amps), want)
        qt.destroyQureg(q, env)

    def test_env_chunk_override_wins_over_ladder(self, env, monkeypatch):
        """An explicit QT_EXCHANGE_CHUNKS pin is operator intent — the
        ladder must not silently fight it (it skips the chunk rung and
        goes straight to splitting/spilling)."""
        mem, want = _predict(env)
        monkeypatch.setenv("QT_EXCHANGE_CHUNKS", "1")
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES",
                           str(mem["predicted_total_bytes"] - 1))
        chunks0 = T.counter_value("governor_degradations_total",
                                  rung="chunks")
        q = qt.createQureg(NBIG, env)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _big_workload(q)
        np.testing.assert_array_equal(np.asarray(q.amps), want)
        assert T.counter_value("governor_degradations_total",
                               rung="chunks") == chunks0
        qt.destroyQureg(q, env)


class TestMaxRss:
    """Satellite: ru_maxrss is kilobytes on Linux but BYTES on macOS —
    the old unconditional *1024 inflated Darwin watermarks 1024x."""

    class _FakeResource:
        RUSAGE_SELF = 0

        class _Usage:
            ru_maxrss = 2048

        @classmethod
        def getrusage(cls, _who):
            return cls._Usage()

    def test_linux_scales_kilobytes(self):
        assert profiling._maxrss_bytes(
            res=self._FakeResource, platform="linux") == 2048 * 1024

    def test_darwin_reports_bytes(self):
        assert profiling._maxrss_bytes(
            res=self._FakeResource, platform="darwin") == 2048

    def test_live_platform_positive(self):
        assert profiling._maxrss_bytes() > 0


class TestSurfaces:
    def test_environment_string_reports_governor(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        s = qt.getEnvironmentString(env)
        assert "MemGovernor=degrade" in s
        assert str(1 << 30) in s

    def test_perf_report_summary_line(self, env, monkeypatch):
        monkeypatch.setenv("QT_HBM_BUDGET_BYTES", str(1 << 30))
        q = qt.createQureg(NBIG, env)
        _big_workload(q)
        line = G.summary_line()
        assert line is not None and "governor" in line
        assert line in T.perf_report()
        qt.destroyQureg(q, env)

    def test_invalid_policy_degrades_to_default(self, env, monkeypatch):
        monkeypatch.setenv("QT_MEM_POLICY", "aggressive")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert G.policy() == "degrade"
