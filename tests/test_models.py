"""Model-family tests: VQE and QAOA training workloads built on the
simulator (trainability is capability beyond the reference — it has no
autodiff; energies are checked against dense NumPy oracles)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

import oracle
from quest_tpu.models import qaoa as qaoa_mod
from quest_tpu.models import vqe as vqe_mod


class TestVQE:
    def test_energy_matches_dense_oracle(self):
        n, depth, terms = 5, 2, 4
        codes, coeffs = vqe_mod.random_hamiltonian(n, terms, seed=1)
        model = vqe_mod.VQE(n, depth, codes, coeffs)
        params = model.init_params(jax.random.PRNGKey(0)).astype(jnp.float64)

        amps = np.asarray(model.apply_ansatz(params))
        psi = amps[0] + 1j * amps[1]
        h = oracle.pauli_sum_matrix(n, codes, coeffs)
        expect = float(np.real(psi.conj() @ h @ psi))
        got = float(model.energy(params))
        assert abs(got - expect) < 1e-8

    def test_training_decreases_energy(self):
        n, depth, terms = 4, 2, 4
        codes, coeffs = vqe_mod.random_hamiltonian(n, terms, seed=2)
        model = vqe_mod.VQE(n, depth, codes, coeffs)
        opt = optax.adam(5e-2)
        params = model.init_params(jax.random.PRNGKey(1))
        state = opt.init(params)
        step = jax.jit(model.make_train_step(opt))
        first = None
        for i in range(30):
            params, state, e = step(params, state)
            if first is None:
                first = float(e)
        assert float(e) < first


class TestQAOA:
    def _dense_cut(self, n, edges):
        idx = np.arange(1 << n)
        c = np.zeros(1 << n)
        for i, j, w in edges:
            c += w * (((idx >> i) & 1) != ((idx >> j) & 1))
        return c

    def test_cost_view_matches_dense(self):
        n = 5
        edges = qaoa_mod.random_graph(n, 6, seed=3)
        model = qaoa_mod.QAOA(n, edges, depth=1)
        from quest_tpu.ops.kernels import _split2

        hi, lo = _split2(n)
        got = np.broadcast_to(
            np.asarray(model._cost_2d(jnp.float64)), (1 << hi, 1 << lo)
        ).reshape(-1)
        # (2^hi, 2^lo) row-major: flat index = ihi * 2^lo + ilo IS the
        # amplitude index
        np.testing.assert_allclose(got, self._dense_cut(n, edges), atol=1e-12)

    def test_expected_cut_matches_dense(self):
        n = 4
        edges = qaoa_mod.random_graph(n, 4, seed=4)
        model = qaoa_mod.QAOA(n, edges, depth=2)
        params = jnp.asarray([0.3, 0.5, -0.2, 0.7], jnp.float64)

        amps = np.asarray(model.state(params))
        psi = amps[0] + 1j * amps[1]
        np.testing.assert_allclose(np.sum(np.abs(psi) ** 2), 1.0, atol=1e-10)
        expect = float(np.abs(psi) ** 2 @ self._dense_cut(n, edges))
        got = float(model.expected_cut(params))
        assert abs(got - expect) < 1e-8

    def test_depth0_gives_mean_cut(self):
        # p=0: |+>^n, every edge cut with probability 1/2
        n = 4
        edges = qaoa_mod.random_graph(n, 5, seed=5)
        model = qaoa_mod.QAOA(n, edges, depth=0)
        got = float(model.expected_cut(jnp.zeros((0,), jnp.float64)))
        expect = 0.5 * sum(w for _, _, w in edges)
        assert abs(got - expect) < 1e-9

    def test_training_increases_cut(self):
        n = 5
        edges = qaoa_mod.random_graph(n, 6, seed=6)
        model = qaoa_mod.QAOA(n, edges, depth=2)
        opt = optax.adam(5e-2)
        params = model.init_params(jax.random.PRNGKey(2))
        state = opt.init(params)
        step = jax.jit(model.make_train_step(opt))
        cuts = []
        for _ in range(40):
            params, state, cut = step(params, state)
            cuts.append(float(cut))
        assert cuts[-1] > cuts[0]
        # never exceeds the true max cut
        assert cuts[-1] <= self._dense_cut(n, edges).max() + 1e-6
