"""Window megakernel (ISSUE 18, docs/design.md §29).

Covers the acceptance contract:
  * ``group_megawins`` is a PURE regroup of the winfused plan — flattening
    the megawin groups reproduces the ungrouped plan tuple-for-tuple, and
    executing the grouped plan is bit-identical to the per-pass route on
    scalar, 8-shard, batched-bank and density registers;
  * the fallback ladder decomposes bit-identically at every rung:
    QT_MEGAKERNEL=off plans no groups, auto excludes non-TPU backends and
    f64 states, a failed Mosaic lowering probe lands in the degradation
    registry, and a megawin op executed where the kernel is not
    executable falls back to the per-pass sequence;
  * a fused dense window group is ONE apply_window_megastack dispatch
    (call count pinned == megawin group count) and the sharded megawin
    program compiles to ZERO collectives in BOTH arms
    (introspect.audit under CollectiveBudget(exact={}));
  * telemetry routes land in megakernel_dispatch_total{route},
    ``model_drift_total == 0`` in both arms (§21 prices the grouping
    identically), and explainCircuit reports the ``mega`` window kind.
"""

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion as F
from quest_tpu import introspect
from quest_tpu import resilience as R
from quest_tpu import telemetry as T
from quest_tpu.ops import fused

NQ = 14  # smallest register with a full fused window

_SQ2 = 1.0 / np.sqrt(2.0)
H_SOA = np.stack([_SQ2 * np.array([[1.0, 1], [1, -1]]), np.zeros((2, 2))])
CX_SOA = np.stack([
    np.array([[1.0, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]]),
    np.zeros((4, 4)),
])


@pytest.fixture(scope="module")
def env1():
    return qt.createQuESTEnv(num_devices=1)


@pytest.fixture
def env8(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device dryrun mesh")
    return env


@pytest.fixture
def tele():
    mode = T.mode_name()
    T.configure("on")
    T.reset()
    yield
    T.reset()
    T.configure(mode)


@pytest.fixture
def dense(monkeypatch):
    """The dense-window A/B environment: QT_PERM_FAST=off in BOTH arms so
    CNOT ladders fuse into dense windows instead of perm-splitting every
    dense run down to one ungroupable winfused pass."""
    monkeypatch.setenv("QT_PERM_FAST", "off")
    return monkeypatch


def _units(rng, nq, depth):
    """(depth, nq) complex Haar 2x2s."""
    z = (rng.standard_normal((depth, nq, 2, 2))
         + 1j * rng.standard_normal((depth, nq, 2, 2)))
    us = np.empty_like(z)
    for d in range(depth):
        for t in range(nq):
            q, r = np.linalg.qr(z[d, t])
            us[d, t] = q * (np.diag(r) / np.abs(np.diag(r)))
    return us


def _gate_list(nq, depth, rng):
    """Dense Gate list (1q Haar layers + CNOT ladder) for plan tests."""
    us = _units(rng, nq, depth)
    gates = []
    for d in range(depth):
        for t in range(nq):
            gates.append(CIRC.Gate(
                (t,), np.stack([us[d, t].real, us[d, t].imag])))
        for t in range(nq - 1):
            if (d + t) % 2 == 0:
                gates.append(CIRC.Gate((t, t + 1), CX_SOA))
    return gates


def _rand_state(nq, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((2, 1 << nq))
    a /= np.sqrt((a ** 2).sum())
    return jnp.asarray(a, dtype)


def _plan_arms(gates, nq, monkeypatch):
    monkeypatch.setenv("QT_MEGAKERNEL", "off")
    off = CIRC.plan_circuit(gates, nq)
    monkeypatch.setenv("QT_MEGAKERNEL", "on")
    on = CIRC.plan_circuit(gates, nq)
    return off, on


@pytest.fixture(scope="module")
def arms():
    """One (off, on) plan pair shared by every plan-level test: planning
    dominates the suite's runtime, so trace once and reuse."""
    gates = _gate_list(NQ, 8, np.random.default_rng(0))
    old = os.environ.get("QT_MEGAKERNEL")
    try:
        os.environ["QT_MEGAKERNEL"] = "off"
        off = CIRC.plan_circuit(gates, NQ)
        os.environ["QT_MEGAKERNEL"] = "on"
        on = CIRC.plan_circuit(gates, NQ)
    finally:
        if old is None:
            os.environ.pop("QT_MEGAKERNEL", None)
        else:
            os.environ["QT_MEGAKERNEL"] = old
    return off, on


def _apply_layers(q, us, ladder=True):
    nq, depth = us.shape[1], us.shape[0]
    with qt.gateFusion(q):
        for d in range(depth):
            for t in range(nq):
                qt.unitary(q, t, us[d, t])
            if ladder:
                for t in range(nq - 1):
                    if (d + t) % 2 == 0:
                        qt.controlledNot(q, t, t + 1)
    return np.asarray(q.amps)


def _flatten(plan):
    out = []
    for op in plan:
        if op[0] == "megawin":
            out.extend(op[1])
        else:
            out.append(op)
    return out


# ---------------------------------------------------------------------------


class TestGrouping:
    def test_off_plans_no_megawin_on_groups(self, arms):
        off, on = arms
        assert CIRC.stats(off)["megawin"] == 0
        st = CIRC.stats(on)
        assert st["megawin"] > 0 and st["megawin_ops"] > st["megawin"]
        # grouping is a PURE regroup: flattening the groups reproduces
        # the per-pass plan op-for-op (kinds, window offsets, operands)
        flat = _flatten(on)
        assert len(flat) == len(off)
        for a, b in zip(flat, off):
            assert a[0] == b[0]
            for fa, fb in zip(a[1:], b[1:]):
                if isinstance(fa, np.ndarray) or isinstance(fb, np.ndarray):
                    assert np.array_equal(np.asarray(fa), np.asarray(fb))
                else:
                    assert fa == fb

    def test_wide_window_stays_ungrouped(self, arms):
        ops = [op for op in arms[0] if op[0] == "winfused"]
        assert len(ops) >= 3
        # a k=12 pass needs G=32 VMEM block rows — over every row cap, so
        # it must stay on the per-pass route and split its neighbours
        wide = ("winfused", 12) + ops[1][2:]
        grouped = CIRC.group_megawins(
            [ops[0], ops[1], wide, ops[2]], 26)
        assert wide in grouped
        for op in grouped:
            if op[0] == "megawin":
                assert wide not in op[1]

    def test_groups_of_one_left_ungrouped(self, arms):
        ops = [op for op in arms[0] if op[0] == "winfused"]
        assert CIRC.group_megawins([ops[0]], NQ) == [ops[0]]

    def test_plan_key_retraces_on_mode_flip(self, monkeypatch):
        items = [CIRC.Gate((0,), H_SOA)]
        monkeypatch.setenv("QT_MEGAKERNEL", "off")
        k_off = F._plan_key(items, NQ, True)
        monkeypatch.setenv("QT_MEGAKERNEL", "on")
        k_on = F._plan_key(items, NQ, True)
        assert k_off != k_on

    def test_mode_parsing(self, monkeypatch):
        for raw, want in (("on", "on"), ("1", "on"), ("TRUE", "on"),
                          ("off", "off"), ("0", "off"), ("no", "off"),
                          ("auto", "auto"), ("bogus", "auto")):
            monkeypatch.setenv("QT_MEGAKERNEL", raw)
            assert fused.megakernel_mode() == want
        monkeypatch.delenv("QT_MEGAKERNEL")
        assert fused.megakernel_mode() == "auto"


class TestParity:
    def test_scalar_plan_bit_identical(self, arms):
        off, on = arms
        assert CIRC.stats(on)["megawin"] > 0
        # execute_plan consumes (donates) the state: fresh one per arm
        a_off = np.asarray(CIRC.execute_plan(
            _rand_state(NQ, 0), CIRC.plan_to_device(off, jnp.float32),
            NQ))
        a_on = np.asarray(CIRC.execute_plan(
            _rand_state(NQ, 0), CIRC.plan_to_device(on, jnp.float32),
            NQ))
        # same block body, same order: the megakernel is BIT-identical
        assert np.array_equal(a_off, a_on)

    @pytest.mark.slow
    def test_scalar_plan_bit_identical_deep(self, monkeypatch):
        gates = _gate_list(NQ, 10, np.random.default_rng(1))
        off, on = _plan_arms(gates, NQ, monkeypatch)
        assert CIRC.stats(on)["megawin"] > 0
        a_off = np.asarray(CIRC.execute_plan(
            _rand_state(NQ, 1), CIRC.plan_to_device(off, jnp.float32),
            NQ))
        a_on = np.asarray(CIRC.execute_plan(
            _rand_state(NQ, 1), CIRC.plan_to_device(on, jnp.float32),
            NQ))
        assert np.array_equal(a_off, a_on)

    def test_fallback_decomposition_bit_identical(self, arms, monkeypatch):
        """The ladder's bottom rung: a megawin op executed where the
        kernel is not executable decomposes to the per-pass sequence."""
        off, on = arms
        dev = CIRC.plan_to_device(on, jnp.float32)
        monkeypatch.setenv("QT_MEGAKERNEL", "on")  # kernel route
        a_on = np.asarray(CIRC.execute_plan(_rand_state(NQ, 3), dev, NQ))
        monkeypatch.setenv("QT_MEGAKERNEL", "off")  # not executable now
        a_dec = np.asarray(CIRC.execute_plan(_rand_state(NQ, 3), dev, NQ))
        a_off = np.asarray(CIRC.execute_plan(
            _rand_state(NQ, 3), CIRC.plan_to_device(off, jnp.float32), NQ))
        assert np.array_equal(a_dec, a_off)
        assert np.array_equal(a_dec, a_on)

    def test_scalar_drain_parity_routes_and_drift(self, env1, dense, tele):
        us = _units(np.random.default_rng(4), NQ, 6)
        dense.setenv("QT_MEGAKERNEL", "off")
        q = qt.createQureg(NQ, env1)
        qt.initDebugState(q)
        a_off = _apply_layers(q, us)
        assert T.counter_sum("megakernel_dispatch_total", route="mega") == 0
        assert T.counter_total("model_drift_total") == 0
        T.reset()
        dense.setenv("QT_MEGAKERNEL", "on")
        q = qt.createQureg(NQ, env1)
        qt.initDebugState(q)
        a_on = _apply_layers(q, us)
        assert T.counter_sum("megakernel_dispatch_total", route="mega") > 0
        assert T.counter_total("model_drift_total") == 0
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)

    @pytest.mark.slow
    def test_sharded_drain_parity(self, env8, dense, tele):
        """8-shard dryrun: nloc = 15 is the smallest local size whose
        remap windows hold more than one fused window to group."""
        n = 18
        us = _units(np.random.default_rng(5), n, 2)
        dense.setenv("QT_MEGAKERNEL", "off")
        q = qt.createQureg(n, env8)
        qt.initDebugState(q)
        a_off = _apply_layers(q, us)
        assert T.counter_total("model_drift_total") == 0
        T.reset()
        dense.setenv("QT_MEGAKERNEL", "on")
        q = qt.createQureg(n, env8)
        qt.initDebugState(q)
        a_on = _apply_layers(q, us)
        assert T.counter_sum("megakernel_dispatch_total", route="mega") > 0
        assert T.counter_total("model_drift_total") == 0
        np.testing.assert_allclose(a_on, a_off, atol=1e-10, rtol=0)

    def test_batched_bank_parity(self, env1, dense):
        us = _units(np.random.default_rng(6), NQ, 4)
        amps = {}
        for flag in ("off", "on"):
            dense.setenv("QT_MEGAKERNEL", flag)
            bq = qt.createBatchedQureg(NQ, env1, 2)
            qt.initPlusState(bq)
            amps[flag] = _apply_layers(bq, us)
        np.testing.assert_allclose(amps["on"], amps["off"],
                                   atol=1e-10, rtol=0)

    def test_density_parity(self, env1, dense):
        nq = 7  # 14 amplitude qubits: one full fused window
        us = _units(np.random.default_rng(7), nq, 4)
        amps = {}
        for flag in ("off", "on"):
            dense.setenv("QT_MEGAKERNEL", flag)
            q = qt.createDensityQureg(nq, env1)
            qt.initPlusState(q)
            amps[flag] = _apply_layers(q, us)
        np.testing.assert_allclose(amps["on"], amps["off"],
                                   atol=1e-10, rtol=0)


class TestDispatchPins:
    def test_one_megastack_call_per_group(self, arms, monkeypatch):
        """A fused dense window group is ONE kernel dispatch: the call
        count equals the plan's megawin group count exactly."""
        plan = arms[1]
        monkeypatch.setenv("QT_MEGAKERNEL", "on")
        groups = CIRC.stats(plan)["megawin"]
        assert groups > 0
        calls = []
        real = fused.apply_window_megastack

        def spy(amps, subops, **kw):
            calls.append(len(subops))
            return real(amps, subops, **kw)

        monkeypatch.setattr(fused, "apply_window_megastack", spy)
        CIRC.execute_plan(_rand_state(NQ, 8),
                          CIRC.plan_to_device(plan, jnp.float32), NQ)
        assert len(calls) == groups
        assert sum(calls) == CIRC.stats(plan)["megawin_ops"]

    def test_explain_reports_mega_kind(self, env1, monkeypatch):
        gates = _gate_list(NQ, 4, np.random.default_rng(9))
        q = qt.createQureg(NQ, env1)
        monkeypatch.setenv("QT_PERM_FAST", "off")  # dense windows
        monkeypatch.setenv("QT_MEGAKERNEL", "on")
        rep = qt.explainCircuit(q, gates)
        assert rep["totals"]["mega_windows"] > 0
        kinds = {w.get("kind") for w in rep["windows"]}
        assert "mega" in kinds
        assert "mega_windows=" in rep.table()
        monkeypatch.setenv("QT_MEGAKERNEL", "off")
        rep = qt.explainCircuit(q, gates)
        assert rep["totals"]["mega_windows"] == 0


class TestFallbackLadder:
    def test_auto_gates_on_backend_and_dtype(self, monkeypatch):
        monkeypatch.setenv("QT_MEGAKERNEL", "auto")
        # pretend a real TPU whose lowering probe passed
        monkeypatch.setattr(fused, "_interpret_default", lambda: False)
        monkeypatch.setattr(fused, "_MEGA_OK", {"ok": True})
        assert fused.megakernel_planning()
        assert fused.megakernel_executable(jnp.float32)
        assert not fused.megakernel_executable(jnp.float64)
        # interpret-mode (non-TPU) backend: plan nothing, execute nothing
        monkeypatch.setattr(fused, "_interpret_default", lambda: True)
        assert not fused.megakernel_planning()
        assert not fused.megakernel_executable(jnp.float32)
        # the knob overrides both directions
        monkeypatch.setenv("QT_MEGAKERNEL", "on")
        assert fused.megakernel_executable(jnp.float64)
        monkeypatch.setenv("QT_MEGAKERNEL", "off")
        monkeypatch.setattr(fused, "_interpret_default", lambda: False)
        assert not fused.megakernel_planning()
        assert not fused.megakernel_executable(jnp.float32)

    def test_probe_failure_lands_in_degradation_registry(self, monkeypatch):
        """Force the one-shot Mosaic probe to really run on this (CPU)
        backend: it must fail, downgrade megakernel_executable, and
        record pallas-window-megakernel in the degradation registry."""
        monkeypatch.setenv("QT_MEGAKERNEL", "auto")
        monkeypatch.setattr(fused, "_interpret_default", lambda: False)
        monkeypatch.setattr(fused, "_MEGA_OK", {})
        monkeypatch.setattr(R, "DEGRADATIONS", {})
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert not fused.megakernel_executable(jnp.float32)
        assert "pallas-window-megakernel" in R.degradation_report()
        # cached: the second call must not re-probe (dict already decided)
        assert fused._MEGA_OK == {"ok": False}
        assert not fused.megakernel_lowering_ok()


class TestCollectives:
    def test_sharded_megawin_program_zero_collectives(self, env8, arms,
                                                      monkeypatch):
        """The megawin route adds ZERO collectives: the whole group stays
        shard-local, so the compiled shard_map program in BOTH arms has
        an empty collective histogram (the §29 acceptance pin)."""
        from jax.sharding import PartitionSpec as P

        from quest_tpu.env import AMP_AXIS, shard_map

        n, nloc = 17, 14
        off, on = arms  # nloc == NQ: the shared plan pair is shard-local
        assert CIRC.stats(on)["megawin"] > 0
        amps = jax.device_put(_rand_state(n, 10), env8.amp_sharding())
        for plan in (off, on):
            dev = CIRC.plan_to_device(plan, jnp.float32)

            def f(a, _dev=dev):
                def kernel(local):
                    return CIRC.execute_plan(local, _dev, nloc)

                return shard_map(
                    kernel, mesh=env8.mesh,
                    in_specs=(P(None, AMP_AXIS),),
                    out_specs=P(None, AMP_AXIS), check_vma=False)(a)

            with introspect.CollectiveBudget(exact={}):
                introspect.audit(f, amps, donate=True)
