"""Batched registers (quest_tpu/batch.py): bit-parity with independent
runs, ensemble scheduling retrace bounds, trajectory-vs-density
convergence, and checkpoint/resume of register banks.

The batching contract is EXACT equality, not tolerance: a (B, 2, 2^n)
bank run through the vmapped fusion drain must produce bit-identical
amplitudes — and, with the per-element key bank, bit-identical
measurement outcome streams — to B independent scalar runs (including on
the 8-device dryrun mesh and across a checkpoint/resume cycle)."""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quest_tpu as qt
import quest_tpu.circuit as C
from quest_tpu import resilience as R
from quest_tpu import telemetry as T
from quest_tpu.ops import measurement as M
from quest_tpu.validation import QuESTError

NQ = 6
NB = 4


def _random_unitary(rng, k=1):
    g = rng.standard_normal((1 << k, 1 << k)) \
        + 1j * rng.standard_normal((1 << k, 1 << k))
    u, _ = np.linalg.qr(g)
    return u


def _apply_circuit(q, depth=2):
    """A fixed mixed circuit touching low, middle and mesh-coordinate
    qubits (the top bits shard on the 8-device mesh)."""
    for d in range(depth):
        for t in range(NQ):
            qt.hadamard(q, t)
        qt.controlledNot(q, NQ - 1, 0)
        qt.rotateZ(q, 2, 0.3 + 0.1 * d)
        qt.swapGate(q, 1, NQ - 2)


class TestBatchedVsLooped:
    def test_gates_bit_parity(self, env):
        bq = qt.createBatchedQureg(NQ, env, NB)
        _apply_circuit(bq)
        bank = np.asarray(bq.amps)
        assert bank.shape == (NB, 2, 1 << NQ)
        for i in range(NB):
            qi = qt.createQureg(NQ, env)
            with qt.gateFusion(qi):
                _apply_circuit(qi)
            assert np.array_equal(bank[i], np.asarray(qi.amps))

    def test_per_element_matrices_bit_parity(self, env):
        rng = np.random.default_rng(1)
        mats = [_random_unitary(rng) for _ in range(NB)]
        bq = qt.createBatchedQureg(NQ, env, NB)
        qt.applyBatchedUnitary(bq, (1,), np.stack(mats))
        qt.hadamard(bq, 0)  # shared gate mixed into the same drain
        bank = np.asarray(bq.amps)
        for i in range(NB):
            qi = qt.createQureg(NQ, env)
            with qt.gateFusion(qi):
                qt.unitary(qi, 1, mats[i])
                qt.hadamard(qi, 0)
            assert np.array_equal(bank[i], np.asarray(qi.amps))

    def test_density_bank_bit_parity(self, env):
        rng = np.random.default_rng(2)
        mats = [_random_unitary(rng) for _ in range(NB)]
        nq = 3
        bq = qt.createBatchedQureg(nq, env, NB, is_density_matrix=True)
        qt.applyBatchedUnitary(bq, (1,), np.stack(mats))
        qt.hadamard(bq, 0)
        bank = np.asarray(bq.amps)
        for i in range(NB):
            qi = qt.createDensityQureg(nq, env)
            with qt.gateFusion(qi):
                qt.unitary(qi, 1, mats[i])
                qt.hadamard(qi, 0)
            assert np.array_equal(bank[i], np.asarray(qi.amps))

    def test_seeded_measurement_bit_parity(self, env):
        seeds = [[100 + i] for i in range(NB)]
        bq = qt.createBatchedQureg(NQ, env, NB, seeds=seeds)
        for t in range(NQ):
            qt.hadamard(bq, t)
        outs1, probs1 = qt.measureBatched(bq, 2)
        outs2, _ = qt.measureBatched(bq, 0)
        bank = np.asarray(bq.amps)
        for i in range(NB):
            qi = qt.createQureg(NQ, env)
            M.KEYS.seed(seeds[i])
            with qt.gateFusion(qi):
                for t in range(NQ):
                    qt.hadamard(qi, t)
            o1, p1 = qt.measureWithStats(qi, 2)
            o2, _ = qt.measureWithStats(qi, 0)
            assert (o1, o2) == (int(outs1[i]), int(outs2[i]))
            assert p1 == probs1[i]
            assert np.array_equal(bank[i], np.asarray(qi.amps))

    def test_expectation_bit_parity(self, env):
        from quest_tpu.ops import paulis as OPS_P

        rng = np.random.default_rng(3)
        mats = [_random_unitary(rng) for _ in range(NB)]
        codes = rng.integers(0, 4, size=(3, NQ)).astype(np.int32)
        coeffs = np.linspace(0.5, 1.5, 3)
        bq = qt.createBatchedQureg(NQ, env, NB)
        qt.applyBatchedUnitary(bq, (0,), np.stack(mats))
        vals = qt.calcExpecPauliSumBatched(bq, codes, coeffs)
        from quest_tpu import fusion as F

        for i in range(NB):
            qi = qt.createQureg(NQ, env)
            qt.unitary(qi, 0, mats[i])
            if F._shard_bits(qi):
                from quest_tpu.parallel import dist as PAR

                want = float(PAR.expec_pauli_sum_scan_sharded(
                    qi.amps, codes, coeffs,
                    mesh=env.mesh, num_qubits=NQ))
            else:
                want = float(OPS_P.expec_pauli_sum_scan(
                    qi.amps, codes, coeffs, num_qubits=NQ))
            assert vals[i] == want

    def test_scalar_init_broadcasts(self, env):
        bq = qt.createBatchedQureg(NQ, env, NB)
        qt.hadamard(bq, 0)
        qt.initZeroState(bq)  # scalar (2, 2^n) write lifts to the bank
        bank = np.asarray(bq.amps)
        assert bank.shape == (NB, 2, 1 << NQ)
        assert np.all(bank[:, 0, 0] == 1.0)
        assert np.abs(bank).sum() == NB

    def test_eager_fallback_is_structured_error(self, env):
        bq = qt.createBatchedQureg(NQ, env, NB)
        with pytest.raises(QuESTError, match="BatchedQureg"):
            qt.multiRotateZ(bq, [0, 1], 0.3)  # parity phase: no capture
        with pytest.raises(QuESTError, match="measureBatched"):
            qt.measure(bq, 0)


class TestEnsembleScheduler:
    @staticmethod
    def _ansatz(theta):
        h = np.stack([np.array([[1, 1], [1, -1]]) / np.sqrt(2),
                      np.zeros((2, 2))])
        rz = np.stack([np.diag([np.cos(theta / 2), np.cos(theta / 2)]),
                       np.diag([-np.sin(theta / 2), np.sin(theta / 2)])])
        return [C.Gate((0,), h), C.Gate((1,), rz), C.Gate((2,), h)]

    def test_results_match_independent_runs(self, env):
        sched = qt.EnsembleScheduler(NQ, env, max_batch=8)
        circuits = [self._ansatz(0.1 * (k + 1)) for k in range(5)]
        for c in circuits:
            sched.submit(c)
        res = sched.drain()
        assert len(res) == 5
        for k, c in enumerate(circuits):
            qi = qt.createQureg(NQ, env)
            with qt.gateFusion(qi):
                qi._fusion.gates.extend(c)
            assert np.array_equal(res[k], np.asarray(qi.amps))

    def test_occupancy_and_throughput_telemetry(self, env):
        mode = T.mode_name()
        T.configure("on")
        try:
            before = dict(T.snapshot()["counters"])
            sched = qt.EnsembleScheduler(NQ, env, max_batch=8)
            for k in range(5):  # pads to a bucket of 8
                sched.submit(self._ansatz(0.2 * (k + 1)))
            sched.drain()
            snap = T.snapshot()
            total = snap["counters"].get("ensemble_circuits_total", {})
            prev = before.get("ensemble_circuits_total", {}).get("", 0)
            assert total.get("", 0) - prev == 5
            assert snap["gauges"]["batch_occupancy"][""] == 5 / 8
            assert snap["gauges"]["ensemble_circuits_per_sec"][""] > 0
        finally:
            T.configure(mode)

    def test_retrace_count_bounded_by_buckets(self, env):
        """Submissions of ONE structure at many batch sizes retrace at
        most once per power-of-two bucket size, never per submission:
        padding quantizes the (B, 2, 2^n) shapes entering jit."""
        mode = T.mode_name()
        T.configure("on")
        try:
            sched = qt.EnsembleScheduler(NQ, env, max_batch=8)

            def drained_retraces(counts):
                t0 = T.snapshot()["counters"].get(
                    "fusion_retrace_total", {}).get("", 0)
                for cnt in counts:
                    for k in range(cnt):
                        sched.submit(self._ansatz(0.05 * (k + 1)))
                    sched.drain()
                t1 = T.snapshot()["counters"].get(
                    "fusion_retrace_total", {}).get("", 0)
                return t1 - t0

            # 13 submissions over drains of 1, 3, 4 and 5 circuits hit
            # buckets {1, 2, 4, 8}: <= 4 retraces, NOT 13
            retraces = drained_retraces([1, 3, 4, 5])
            assert retraces <= 4, retraces
            # the same bucket sizes again: zero new traces
            assert drained_retraces([1, 3, 4, 5]) == 0
        finally:
            T.configure(mode)

    def test_mixed_structures_grouped(self, env):
        sched = qt.EnsembleScheduler(NQ, env, max_batch=8)
        a = self._ansatz(0.3)
        b = self._ansatz(0.4)[:2]  # different structure (2 gates)
        sched.submit(a)
        sched.submit(b)
        sched.submit(self._ansatz(0.5))
        res = sched.drain()
        assert len(res) == 3
        qi = qt.createQureg(NQ, env)
        with qt.gateFusion(qi):
            qi._fusion.gates.extend(b)
        assert np.array_equal(res[1], np.asarray(qi.amps))


class TestTrajectories:
    @staticmethod
    def _noisy_ops(theta=0.7):
        ry = np.array([[np.cos(theta / 2), -np.sin(theta / 2)],
                       [np.sin(theta / 2), np.cos(theta / 2)]])
        ry_soa = np.stack([ry, np.zeros((2, 2))])
        ops = [C.Gate((0,), ry_soa), ("dephasing", 0, 0.2),
               C.Gate((1,), ry_soa), ("depolarising", 1, 0.15),
               ("damping", 0, 0.25)]
        return ops, ry

    def test_converges_to_density_channels(self, env):
        """The trajectory-mean expectation converges to the exact density
        evolution (ops/density.py channels) of the same noisy circuit —
        the stochastic unraveling is the same CPTP map."""
        ops, ry = self._noisy_ops()
        nq = 2
        codes = np.array([[3, 0], [0, 3], [1, 1]], dtype=np.int32)
        coeffs = np.array([1.0, 0.5, 0.25])
        out = qt.run_trajectories(ops, nq, env, 256,
                                  observable=(codes, coeffs), seed=5)
        rho = qt.createDensityQureg(nq, env)
        qt.unitary(rho, 0, ry)
        qt.mixDephasing(rho, 0, 0.2)
        qt.unitary(rho, 1, ry)
        qt.mixDepolarising(rho, 1, 0.15)
        qt.mixDamping(rho, 0, 0.25)
        h = qt.createPauliHamil(nq, 3)
        h.pauli_codes[:] = codes
        h.term_coeffs[:] = coeffs
        exact = qt.calcExpecPauliHamil(rho, h, qt.createQureg(nq, env))
        assert out["values"].shape == (256,)
        assert out["sem"] > 0
        assert abs(out["mean"] - exact) < max(5 * out["sem"], 0.05)

    def test_seed_reproducible(self, env):
        ops, _ = self._noisy_ops()
        codes = np.array([[3, 0]], dtype=np.int32)
        coeffs = np.array([1.0])
        a = qt.run_trajectories(ops, 2, env, 16,
                                observable=(codes, coeffs), seed=9)
        b = qt.run_trajectories(ops, 2, env, 16,
                                observable=(codes, coeffs), seed=9)
        assert np.array_equal(a["values"], b["values"])

    def test_trajectories_stay_normalized(self, env):
        """Every Kraus branch renormalizes its trajectory — the bank
        stays a bank of unit state vectors (the MCWF invariant)."""
        ops, _ = self._noisy_ops()
        out = qt.run_trajectories(ops, 2, env, 32, seed=3)
        norms = (out["amps"] ** 2).sum(axis=(1, 2))
        np.testing.assert_allclose(norms, 1.0, atol=1e-12)


class TestBatchedCheckpointResume:
    @staticmethod
    def _gates(rng, count=12):
        return [C.Gate((k % NQ,),
                       np.stack([(u := _random_unitary(rng)).real, u.imag]))
                for k in range(count)]

    def test_save_load_round_trip(self, env):
        bq = qt.createBatchedQureg(NQ, env, NB)
        _apply_circuit(bq, depth=1)
        want = np.asarray(bq.amps)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "bank")
            qt.saveQureg(bq, p)
            q2 = qt.loadQureg(p, env)
            assert isinstance(q2, qt.BatchedQureg)
            assert q2.batch_size == NB
            assert np.array_equal(np.asarray(q2.amps), want)

    def test_resumed_run_bit_identical(self, env):
        rng = np.random.default_rng(9)
        gates = self._gates(rng)
        seeds = [[7 + i] for i in range(NB)]
        with tempfile.TemporaryDirectory() as d:
            bq = qt.createBatchedQureg(NQ, env, NB, seeds=seeds)
            qt.run_resumable(bq, gates, os.path.join(d, "ck"), every=4)
            full = np.asarray(bq.amps)

            ck2 = os.path.join(d, "ck2")
            bq2 = qt.createBatchedQureg(NQ, env, NB, seeds=seeds)
            with pytest.raises(R.SimulatedPreemption):
                qt.run_resumable(bq2, gates, ck2, every=4,
                                 faults=R.FaultPlan("kill@2"))
            bq3 = qt.createBatchedQureg(NQ, env, NB, seeds=seeds)
            qt.run_resumable(bq3, gates, ck2, every=4)
            assert np.array_equal(full, np.asarray(bq3.amps))
            assert bq3.key_state()["counters"] == bq.key_state()["counters"]

    def test_batched_checkpoint_refuses_scalar_register(self, env):
        rng = np.random.default_rng(10)
        gates = self._gates(rng, count=8)
        with tempfile.TemporaryDirectory() as d:
            ck = os.path.join(d, "ck")
            bq = qt.createBatchedQureg(NQ, env, NB)
            qt.run_resumable(bq, gates, ck, every=4)
            scalar = qt.createQureg(NQ, env)
            with pytest.raises(QuESTError, match="batch mismatch"):
                qt.run_resumable(scalar, gates, ck, every=4)
            wrong = qt.createBatchedQureg(NQ, env, NB * 2)
            with pytest.raises(QuESTError, match="batch mismatch"):
                qt.run_resumable(wrong, gates, ck, every=4)

    def test_health_covers_every_element(self, env):
        bq = qt.createBatchedQureg(NQ, env, NB)
        norm, finite = qt.checkQuregHealth(bq)
        assert finite and abs(norm - 1.0) < 1e-12
        # corrupt ONE element: the reported norm must be the outlier
        bank = np.array(bq.amps)
        bank[2] *= 2.0
        bq.amps = jnp.asarray(bank)
        norm, finite = qt.checkQuregHealth(bq)
        assert abs(norm - 4.0) < 1e-12


class TestBatchedTelemetry:
    def test_dispatch_and_exchange_weighted_by_batch(self, env):
        """dispatch_total counts B logical gate applications per batched
        call, and window_remap exchange bytes scale by B — telemetry
        stays truthful under batching."""
        if env.num_devices < 2:
            pytest.skip("needs a sharded mesh for exchange accounting")
        mode = T.mode_name()
        T.configure("on")
        try:
            def unitary_count():
                c = T.snapshot()["counters"].get("dispatch_total", {})
                return c.get("family=unitary", 0)

            def remap_bytes():
                c = T.snapshot()["counters"].get(
                    "exchange_bytes_total", {})
                return sum(v for k, v in c.items() if "op=remap" in k)

            u0, b0 = unitary_count(), remap_bytes()
            qs = qt.createQureg(NQ, env)
            with qt.gateFusion(qs):
                qt.hadamard(qs, NQ - 1)  # mesh-coordinate bit: remaps
            _ = qs.amps
            u_scalar = unitary_count() - u0
            b_scalar = remap_bytes() - b0

            u1, b1 = unitary_count(), remap_bytes()
            bq = qt.createBatchedQureg(NQ, env, NB)
            qt.hadamard(bq, NQ - 1)
            _ = bq.amps
            assert unitary_count() - u1 == NB * u_scalar
            assert remap_bytes() - b1 == NB * b_scalar > 0
        finally:
            T.configure(mode)
