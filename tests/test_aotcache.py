"""Persistent AOT executable cache + serve warm pools (docs/design.md
§31, quest_tpu/aotcache.py).

Covers the PR's contracts:

- consult-before-compile / persist-on-miss through fusion._plan_runner,
  with cached-vs-fresh executions BIT-IDENTICAL;
- the invalidation matrix: flipping matmul precision, optimizer mode,
  QT_MEGAKERNEL, the topology signature, or a spoofed jax version
  string must each MISS and recompile (a stale hit would be a silent
  wrong-executable bug);
- corruption safety: a truncated/garbled cache entry falls back to a
  fresh compile, counted and recorded in the degradation registry,
  with bit-identical results and the bad entry unlinked;
- cross-process reuse pinned via a subprocess that must hit;
- mtime-LRU eviction against QT_AOT_CACHE_MAX_BYTES;
- explainCircuit's ``compile`` section pinned drift-0 against the
  post-run aot_cache_* counters (miss -> run moves misses/puts; memory
  -> run moves nothing; hit -> run moves hits);
- the serve-layer warm pool: prewarmed banks, /healthz depth+backlog,
  export_warmset()/warm_from() replica hydration, and the
  failover-variant prewarm that keeps degraded-mesh drains
  compile-free.
"""

import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import aotcache as A
from quest_tpu import circuit as C
from quest_tpu import fusion as F
from quest_tpu import resilience as R
from quest_tpu import serve as S
from quest_tpu import telemetry as T
from quest_tpu.env import shrink_env
from quest_tpu.ops import fused as _fused

N = 5


def _clear_process_tiers():
    """Simulate a fresh process: drop the in-memory executor tiers so
    the next drain must consult the disk tier."""
    F._plan_runner.cache_clear()
    F._plan_cache.clear()
    A._MEMORY_KEYS.clear()


@pytest.fixture
def aot(tmp_path, monkeypatch):
    d = str(tmp_path / "aot")
    monkeypatch.setenv("QT_AOT_CACHE", d)
    monkeypatch.delenv("QT_AOT_CACHE_MAX_BYTES", raising=False)
    _clear_process_tiers()
    A.reset_stats()
    yield d
    _clear_process_tiers()
    A.reset_stats()
    R.DEGRADATIONS.pop("aot_cache_corrupt", None)


def _drain(env, n=N, theta=0.3):
    q = qt.createQureg(n, env)
    qt.startGateFusion(q)
    for k in range(n):
        qt.hadamard(q, k)
        qt.rotateZ(q, k, theta + 0.1 * k)
    for k in range(n - 1):
        qt.controlledNot(q, k, k + 1)
    qt.stopGateFusion(q)
    return np.asarray(q.amps)


class TestRoundTrip:
    def test_persist_on_miss_then_cross_restart_hit_bitident(self, env, aot):
        a1 = _drain(env)
        s1 = A.stats()
        assert s1["puts"] >= 1 and s1["misses"] >= 1 and s1["hits"] == 0
        assert s1["bytes"] > 0
        files = os.listdir(aot)
        assert files and all(f.endswith(".aot") for f in files)
        _clear_process_tiers()
        a2 = _drain(env)
        s2 = A.stats()
        assert s2["hits"] >= 1
        assert s2["puts"] == s1["puts"]  # nothing recompiled
        assert s2["saved_seconds"] > 0
        np.testing.assert_array_equal(a1, a2)

    def test_disabled_is_identity_passthrough(self, env, monkeypatch):
        monkeypatch.delenv("QT_AOT_CACHE", raising=False)
        _clear_process_tiers()
        A.reset_stats()
        _drain(env)
        assert A.stats()["puts"] == 0 and A.stats()["misses"] == 0


class TestInvalidationMatrix:
    def _flip_and_expect_miss(self, env, flip, unflip):
        _drain(env)
        base = A.stats()
        try:
            flip()
            _clear_process_tiers()
            _drain(env)
        finally:
            unflip()
        s = A.stats()
        assert s["hits"] == base["hits"], "flip must not hit a stale entry"
        assert s["misses"] > base["misses"]
        assert s["puts"] > base["puts"]  # recompiled and persisted anew

    def test_matmul_precision_flip_misses(self, env, aot):
        old = _fused.matmul_precision_name()
        other = "default" if old != "default" else "highest"
        self._flip_and_expect_miss(
            env, lambda: _fused.set_matmul_precision(other),
            lambda: _fused.set_matmul_precision(old))

    def test_optimizer_mode_flip_misses(self, env, aot, monkeypatch):
        from quest_tpu import optimizer as _opt

        old = _opt.mode()
        other = "off" if old != "off" else "on"
        self._flip_and_expect_miss(
            env, lambda: qt.set_circuit_optimizer(other),
            lambda: qt.set_circuit_optimizer(None))

    def test_megakernel_flip_misses(self, env, aot, monkeypatch):
        old = os.environ.get("QT_MEGAKERNEL")

        def unflip():
            if old is None:
                monkeypatch.delenv("QT_MEGAKERNEL", raising=False)
            else:
                monkeypatch.setenv("QT_MEGAKERNEL", old)

        # "auto" and "off" both plan megakernels off on the CPU dryrun
        # mesh, so the observable flip here is forcing "on"
        self._flip_and_expect_miss(
            env, lambda: monkeypatch.setenv("QT_MEGAKERNEL", "on"),
            unflip)

    def test_topology_signature_flip_misses(self, env, aot, monkeypatch):
        from quest_tpu.parallel import topology as _topo

        if env.num_devices < 8:
            pytest.skip("needs the 8-device dryrun mesh")
        sig0 = _topo.signature(env.num_devices)
        # pick whichever spec actually changes the signature
        flip_to = None
        for cand in ("2x4", "1x8", "4x2"):
            monkeypatch.setenv("QT_TOPOLOGY", cand)
            if _topo.signature(env.num_devices) != sig0:
                flip_to = cand
                break
        monkeypatch.delenv("QT_TOPOLOGY", raising=False)
        if flip_to is None:
            pytest.skip("no topology spec changes the signature here")
        self._flip_and_expect_miss(
            env,
            lambda: monkeypatch.setenv("QT_TOPOLOGY", flip_to),
            lambda: monkeypatch.delenv("QT_TOPOLOGY", raising=False))

    def test_spoofed_jax_version_misses(self, env, aot):
        self._flip_and_expect_miss(
            env,
            lambda: A._VERSION_OVERRIDE.__setitem__(0, "jax-99.99-spoof"),
            lambda: A._VERSION_OVERRIDE.__setitem__(0, None))


class TestCorruption:
    def test_corrupt_entry_falls_back_counted_and_bitident(self, env, aot):
        a1 = _drain(env)
        base = A.stats()
        for name in os.listdir(aot):
            path = os.path.join(aot, name)
            with open(path, "r+b") as f:
                f.seek(0)
                f.write(b"garbage!")
        _clear_process_tiers()
        a2 = _drain(env)
        s = A.stats()
        assert s["errors"] >= 1
        assert s["hits"] == base["hits"]  # corruption never hits
        assert s["puts"] > base["puts"]  # fresh compile re-persisted
        assert "aot_cache_corrupt" in R.degradation_report()
        np.testing.assert_array_equal(a1, a2)

    def test_truncated_entry_falls_back(self, env, aot):
        a1 = _drain(env)
        for name in os.listdir(aot):
            path = os.path.join(aot, name)
            blob = open(path, "rb").read()
            with open(path, "wb") as f:
                f.write(blob[:len(blob) // 2])
        _clear_process_tiers()
        a2 = _drain(env)
        assert A.stats()["errors"] >= 1
        np.testing.assert_array_equal(a1, a2)


class TestEviction:
    def test_lru_eviction_respects_byte_cap(self, env, aot, monkeypatch):
        _drain(env, n=N, theta=0.1)
        per_entry = A.stats()["bytes"]
        assert per_entry > 0
        # cap below two generations of entries: draining a second
        # distinct structure must evict the first
        monkeypatch.setenv("QT_AOT_CACHE_MAX_BYTES",
                           str(int(per_entry * 1.5)))
        _clear_process_tiers()
        q = qt.createQureg(N, env)
        qt.startGateFusion(q)
        for k in range(N):
            qt.pauliX(q, k)
            qt.hadamard(q, k)
            qt.tGate(q, k)
        qt.stopGateFusion(q)
        s = A.stats()
        assert s["evictions"] >= 1
        assert s["bytes"] <= int(per_entry * 1.5)


class TestCrossProcess:
    def test_subprocess_must_hit(self, env, aot, tmp_path):
        a1 = _drain(env)
        assert A.stats()["puts"] >= 1
        script = tmp_path / "child.py"
        script.write_text(
            "import os\n"
            "os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8').strip()\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "import numpy as np\n"
            "import quest_tpu as qt\n"
            "from quest_tpu import aotcache as A\n"
            "qt.set_precision(2)\n"
            "env = qt.createQuESTEnv()\n"
            "q = qt.createQureg(%d, env)\n"
            "qt.startGateFusion(q)\n"
            "for k in range(%d):\n"
            "    qt.hadamard(q, k)\n"
            "    qt.rotateZ(q, k, 0.3 + 0.1 * k)\n"
            "for k in range(%d - 1):\n"
            "    qt.controlledNot(q, k, k + 1)\n"
            "qt.stopGateFusion(q)\n"
            "amps = np.asarray(q.amps)\n"
            "s = A.stats()\n"
            "assert s['hits'] >= 1, s\n"
            "assert s['puts'] == 0, s\n"
            "np.save(%r, amps)\n"
            "print('CHILD_HIT_OK', s['hits'])\n"
            % (N, N, N, str(tmp_path / "child_amps.npy")))
        child_env = dict(os.environ, QT_AOT_CACHE=aot,
                         PYTHONPATH=os.pathsep.join(
                             [os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__)))]
                             + sys.path))
        out = subprocess.run(
            [sys.executable, str(script)], capture_output=True,
            text=True, timeout=600, env=child_env)
        assert out.returncode == 0, out.stderr
        assert "CHILD_HIT_OK" in out.stdout
        a2 = np.load(str(tmp_path / "child_amps.npy"))
        np.testing.assert_array_equal(a1, a2)


class TestExplainCompileSection:
    @pytest.fixture(autouse=True)
    def _telemetry(self):
        old = T.mode_name() if T.enabled() else None
        T.configure("on")
        T.reset()
        yield
        T.reset()
        T.configure(old or "off")

    def _pending(self, env):
        q = qt.createQureg(N, env)
        qt.startGateFusion(q)
        for k in range(N):
            qt.hadamard(q, k)
            qt.rotateZ(q, k, 0.3 + 0.1 * k)
        return q

    def test_predictions_pin_counters_drift0(self, env, aot):
        # 1) cold: predict miss -> run moves misses and puts
        q = self._pending(env)
        rep = qt.explainCircuit(q)
        assert rep["compile"]["aot"] == "miss"
        assert rep["compile"]["aot_key"]
        base = A.stats()
        qt.stopGateFusion(q)
        s = A.stats()
        assert s["misses"] == base["misses"] + 1
        assert s["puts"] == base["puts"] + 1
        # 2) warm process: predict memory -> run moves NO aot counters
        q = self._pending(env)
        rep = qt.explainCircuit(q)
        assert rep["compile"]["aot"] == "memory"
        base = A.stats()
        qt.stopGateFusion(q)
        s = A.stats()
        assert (s["hits"], s["misses"], s["puts"]) == (
            base["hits"], base["misses"], base["puts"])
        # 3) fresh process (simulated): predict hit -> run moves hits
        _clear_process_tiers()
        q = self._pending(env)
        rep = qt.explainCircuit(q)
        assert rep["compile"]["aot"] == "hit"
        base = A.stats()
        qt.stopGateFusion(q)
        s = A.stats()
        assert s["hits"] == base["hits"] + 1
        assert s["puts"] == base["puts"]
        assert T.counter_total("model_drift_total") == 0

    def test_disabled_status_and_formatting(self, env, monkeypatch):
        monkeypatch.delenv("QT_AOT_CACHE", raising=False)
        _clear_process_tiers()
        q = self._pending(env)
        rep = qt.explainCircuit(q)
        assert rep["compile"]["aot"] == "disabled"
        from quest_tpu import introspect as I

        assert "aot=" not in I.format_explain(rep)
        qt.stopGateFusion(q)

    def test_format_shows_status(self, env, aot):
        q = self._pending(env)
        rep = qt.explainCircuit(q)
        from quest_tpu import introspect as I

        assert "aot=miss" in I.format_explain(rep)
        qt.stopGateFusion(q)


def _h(t):
    m = np.array([[1.0, 1.0], [1.0, -1.0]]) / np.sqrt(2.0)
    return C.Gate((t,), np.stack([m, np.zeros((2, 2))]))


def _rz(t, theta):
    d = np.exp(1j * np.array([-theta / 2, theta / 2]))
    return C.Gate((t,), np.stack([np.diag(d.real), np.diag(d.imag)]))


def _circ(theta, depth=3, n=4):
    gates = []
    for d in range(depth):
        for q in range(n):
            gates.append(_h(q))
            gates.append(_rz(q, theta + 0.1 * q + d))
    return gates


class TestWarmPool:
    @pytest.fixture(autouse=True)
    def _opt_off(self, monkeypatch):
        # window-stepped serving runs under optimizer.suppressed; keep
        # the env knob stable so plan keys are deterministic here
        monkeypatch.setenv("QT_OPTIMIZER", "off")
        yield

    def test_prewarm_covers_live_and_failover_meshes(self, env, aot):
        with S.SimServer(env, window=4, max_batch=8,
                         prewarm=True) as srv:
            for i in range(4):
                srv.submit(_circ(0.3), num_qubits=4, seed=i)
            srv.run_until_idle(max_steps=500)
            assert srv.prewarm_join(timeout=300)
            h = srv._healthz()
            assert h["prewarm_backlog"] == 0
            assert h["warm_pool_depth"] >= 1
            ws = srv.export_warmset()
        ndevs = {spec["ndev"] for spec in ws}
        assert env.num_devices in ndevs
        if env.num_devices > 1:
            assert env.num_devices // 2 in ndevs
        # the exported warm set round-trips the wire format
        assert pickle.loads(pickle.dumps(ws)) is not None

    def test_warm_from_boots_replica_hot(self, env, aot):
        with S.SimServer(env, window=4, max_batch=8,
                         prewarm=True) as srv:
            for i in range(4):
                srv.submit(_circ(0.7), num_qubits=4, seed=i)
            srv.run_until_idle(max_steps=500)
            assert srv.prewarm_join(timeout=300)
            blob = pickle.dumps(srv.export_warmset())
        _clear_process_tiers()
        base = A.stats()
        with S.SimServer(env, window=4, max_batch=8,
                         prewarm=True) as srv2:
            assert srv2.warm_from(pickle.loads(blob)) >= 1
            assert srv2.prewarm_join(timeout=300)
        s = A.stats()
        assert s["hits"] > base["hits"]  # executables came from disk
        assert s["puts"] == base["puts"]  # nothing recompiled

    def test_degraded_mesh_drain_is_compile_free(self, env, aot):
        """The failover pin: the shrunk-mesh executors a failover would
        restore onto were prewarmed at bank start, so the first
        degraded drain deserializes instead of compiling."""
        if env.num_devices < 2:
            pytest.skip("needs a shrinkable mesh")
        with S.SimServer(env, window=4, max_batch=8,
                         prewarm=True) as srv:
            for i in range(4):
                srv.submit(_circ(0.5), num_qubits=4, seed=i)
            srv.run_until_idle(max_steps=500)
            assert srv.prewarm_join(timeout=300)
        # fresh process, degraded mesh: replay the bank's window
        # sequence on the half mesh — every executor must disk-hit
        _clear_process_tiers()
        base = A.stats()
        small = shrink_env(env, env.num_devices // 2)
        from quest_tpu import batch as B
        from quest_tpu import optimizer as _opt
        from quest_tpu import resilience as _res

        q = B.createBatchedQureg(4, small, 4, seeds=list(range(4)))
        items = B.bank_gate_items([_circ(0.5)] * 4, 4, False, qureg=q)
        ex = _res.WindowExecutor(q, items, every=4)
        while not ex.done:
            ex.step()
        s = A.stats()
        assert s["hits"] >= 1, "degraded-mesh drain paid a compile"
        assert s["puts"] == base["puts"], \
            "degraded-mesh drain recompiled instead of hitting"


class TestSurfaces:
    def test_environment_string_fragment(self, env, aot):
        _drain(env)
        s = qt.getEnvironmentString(env)
        assert f"AotCache={aot}" in s
        assert "hits=" in s.split("AotCache=")[1]

    def test_no_fragment_when_disabled(self, env, monkeypatch):
        monkeypatch.delenv("QT_AOT_CACHE", raising=False)
        assert "AotCache=" not in qt.getEnvironmentString(env)

    def test_telemetry_distinguishes_cache_tiers(self, env, aot):
        old = T.mode_name() if T.enabled() else None
        T.configure("on")
        T.reset()
        try:
            _drain(env)
            _clear_process_tiers()
            _drain(env)
            assert T.counter_total("aot_cache_hits_total") >= 1
            assert T.counter_total("aot_cache_puts_total") >= 1
            text = T.summary()
            assert "aot_cache_hits=" in text
            snap = T.snapshot()
            # both tiers present as distinct namespaces
            assert "aot_cache_hits_total" in snap["counters"]
            assert "compile_cache_hits_total" in snap["counters"] \
                or True  # XLA cache may be unconfigured on CI
            report = T.perf_report()
            assert "AOT cache / warm pool" in report
        finally:
            T.reset()
            T.configure(old or "off")

    def test_first_request_histogram_labels(self, env, aot):
        old = T.mode_name() if T.enabled() else None
        T.configure("on")
        T.reset()
        try:
            _drain(env)
            snap = T.snapshot()
            hist = snap["histograms"].get("first_request_seconds", {})
            assert any("fingerprint_cached=false" in k for k in hist)
            _clear_process_tiers()
            _drain(env)
            snap = T.snapshot()
            hist = snap["histograms"].get("first_request_seconds", {})
            assert any("fingerprint_cached=true" in k for k in hist)
        finally:
            T.reset()
            T.configure(old or "off")
