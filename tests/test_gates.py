"""Measurement / collapse tests (analogue of reference test_gates.cpp, 3
TEST_CASEs: collapseToOutcome, measure, measureWithStats — statistical ops
tested by repeats on random states, asserting the post-collapse state equals
the analytically renormalised reference, test_gates.cpp:121-160)."""

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N
ATOL = 1e-10


def _collapsed(vec, target, outcome):
    mask = ((np.arange(DIM) >> target) & 1) == outcome
    prob = np.sum(np.abs(vec[mask]) ** 2)
    out = np.where(mask, vec, 0)
    return out / np.sqrt(prob), prob


@pytest.mark.parametrize("target", range(N))
@pytest.mark.parametrize("outcome", [0, 1])
def test_collapse_to_outcome_statevec(env, target, outcome):
    rng = np.random.default_rng(31)
    vec = oracle.random_state(N, rng)
    q = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, q, vec)
    prob = qt.collapseToOutcome(q, target, outcome)
    expect, eprob = _collapsed(vec, target, outcome)
    assert np.isclose(prob, eprob)
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)


@pytest.mark.parametrize("target", [0, 2, 4])
@pytest.mark.parametrize("outcome", [0, 1])
def test_collapse_to_outcome_density(env, target, outcome):
    rng = np.random.default_rng(32)
    mat = oracle.random_density(N, rng)
    r = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, r, mat)
    prob = qt.collapseToOutcome(r, target, outcome)
    mask = ((np.arange(DIM) >> target) & 1) == outcome
    proj = np.diag(mask.astype(float))
    expect_m = proj @ mat @ proj
    eprob = np.real(np.trace(expect_m))
    expect_m = expect_m / eprob
    assert np.isclose(prob, eprob)
    np.testing.assert_allclose(oracle.state_from_qureg(r), expect_m, atol=ATOL)


@pytest.mark.parametrize("target", range(N))
def test_measure_repeats(env, target):
    """10 repeats per qubit on random states (reference pattern)."""
    rng = np.random.default_rng(33 + target)
    for rep in range(10):
        vec = oracle.random_state(N, rng)
        q = qt.createQureg(N, env)
        oracle.set_qureg_from_array(qt, q, vec)
        outcome, prob = qt.measureWithStats(q, target)
        assert outcome in (0, 1)
        expect, eprob = _collapsed(vec, target, outcome)
        assert np.isclose(prob, eprob)
        np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)
        # post-measurement probability of that outcome is now 1
        assert np.isclose(qt.calcProbOfOutcome(q, target, outcome), 1.0)


def test_measure_density(env):
    rng = np.random.default_rng(44)
    mat = oracle.random_density(N, rng)
    r = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, r, mat)
    outcome, prob = qt.measureWithStats(r, 2)
    assert np.isclose(qt.calcProbOfOutcome(r, 2, outcome), 1.0)
    assert np.isclose(qt.calcTotalProb(r), 1.0)


def test_measure_statistics(env):
    """Outcome frequencies follow the amplitudes (|psi> = sqrt(0.2)|0> +
    sqrt(0.8)|1>)."""
    qt.seedQuEST(env, [99])
    hits = 0
    trials = 400
    for _ in range(trials):
        q = qt.createQureg(1, env)
        qt.initStateFromAmps(q, [np.sqrt(0.2), np.sqrt(0.8)], [0, 0])
        hits += qt.measure(q, 0)
    freq = hits / trials
    assert abs(freq - 0.8) < 0.07


def test_gate_validation(env):
    q = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="outcome"):
        qt.collapseToOutcome(q, 0, 2)
    with pytest.raises(qt.QuESTError, match="zero probability"):
        qt.collapseToOutcome(q, 0, 1)  # |0...0> has no 1-amplitude


@pytest.mark.parametrize("target", [0, 2, 4])
def test_measure_repeats_density(env, target):
    """10 repeats per qubit on random density matrices, asserting the
    post-collapse matrix equals the analytic projection (reference
    test_gates.cpp density-matrix section)."""
    rng = np.random.default_rng(53 + target)
    for rep in range(10):
        mat = oracle.random_density(N, rng)
        r = qt.createDensityQureg(N, env)
        oracle.set_qureg_from_array(qt, r, mat)
        outcome, prob = qt.measureWithStats(r, target)
        assert outcome in (0, 1)
        mask = ((np.arange(DIM) >> target) & 1) == outcome
        proj = np.diag(mask.astype(float))
        expect_m = proj @ mat @ proj
        eprob = np.real(np.trace(expect_m))
        assert np.isclose(prob, eprob)
        np.testing.assert_allclose(
            oracle.state_from_qureg(r), expect_m / eprob, atol=ATOL)


def test_measure_statistics_random_state(env):
    """Outcome frequencies on a fixed random multi-qubit state match the
    marginal probabilities within sampling tolerance (the distribution
    itself, not just the post-collapse state)."""
    qt.seedQuEST(env, [1234])
    rng = np.random.default_rng(77)
    n = 3
    vec = oracle.random_state(n, rng)
    trials = 300
    for target in range(n):
        p1 = float(np.sum(
            np.abs(vec[((np.arange(1 << n) >> target) & 1) == 1]) ** 2))
        hits = 0
        for _ in range(trials):
            q = qt.createQureg(n, env)
            oracle.set_qureg_from_array(qt, q, vec)
            hits += qt.measure(q, target)
        freq = hits / trials
        # 3.5 sigma of the binomial
        tol = 3.5 * np.sqrt(p1 * (1 - p1) / trials) + 1e-9
        assert abs(freq - p1) < tol, (target, freq, p1, tol)


def test_destroyed_qureg_access_raises(env):
    q = qt.createQureg(N, env)
    qt.destroyQureg(q, env)
    with pytest.raises(qt.QuESTError, match="destroyed"):
        qt.calcTotalProb(q)


def test_report_state_per_rank(env, tmp_path, monkeypatch):
    """reportState writes one CSV per amplitude chunk (per-rank files,
    reference QuEST_common.c:229-245) instead of gathering to one file."""
    monkeypatch.chdir(tmp_path)
    q = qt.createQureg(N, env)
    qt.initPlusState(q)
    qt.reportState(q)
    import glob
    files = sorted(glob.glob("state_rank_*.csv"))
    assert files, "no per-rank state files written"
    rows = 0
    for fn in files:
        with open(fn) as f:
            lines = [ln for ln in f if ln.strip()]
        if fn.endswith("_0.csv"):
            assert lines[0].startswith("real")
            lines = lines[1:]
        rows += len(lines)
    assert rows == DIM
    amp = 1.0 / np.sqrt(DIM)
    first = open(files[0]).readlines()[1].split(",")
    assert abs(float(first[0]) - amp) < 1e-9
