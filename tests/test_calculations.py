"""Calculation tests (analogue of reference test_calculations.cpp, 19
TEST_CASEs): probabilities, inner products, purity, fidelity, expectation
values."""

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N
ATOL = 1e-10


@pytest.fixture
def rng():
    return np.random.default_rng(21)


def _rand_psi(env, rng):
    vec = oracle.random_state(N, rng)
    q = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, q, vec)
    return q, vec


def _rand_rho(env, rng):
    mat = oracle.random_density(N, rng)
    q = qt.createDensityQureg(N, env)
    oracle.set_qureg_from_array(qt, q, mat)
    return q, mat


def test_calc_total_prob(env, rng):
    q, vec = _rand_psi(env, rng)
    assert np.isclose(qt.calcTotalProb(q), 1.0)
    r, mat = _rand_rho(env, rng)
    assert np.isclose(qt.calcTotalProb(r), np.real(np.trace(mat)))


@pytest.mark.parametrize("target", range(N))
@pytest.mark.parametrize("outcome", [0, 1])
def test_calc_prob_of_outcome(env, rng, target, outcome):
    q, vec = _rand_psi(env, rng)
    mask = ((np.arange(DIM) >> target) & 1) == outcome
    expect = np.sum(np.abs(vec[mask]) ** 2)
    assert np.isclose(qt.calcProbOfOutcome(q, target, outcome), expect)
    r, mat = _rand_rho(env, rng)
    expect_r = np.real(np.sum(np.diag(mat)[mask]))
    assert np.isclose(qt.calcProbOfOutcome(r, target, outcome), expect_r)


@pytest.mark.parametrize("qubits", [[0], [1, 3], [4, 0, 2], [0, 1, 2, 3, 4]])
def test_calc_prob_of_all_outcomes(env, rng, qubits):
    q, vec = _rand_psi(env, rng)
    probs = np.abs(vec) ** 2
    k = len(qubits)
    expect = np.zeros(2 ** k)
    for i in range(DIM):
        out = sum(((i >> q) & 1) << j for j, q in enumerate(qubits))
        expect[out] += probs[i]
    np.testing.assert_allclose(qt.calcProbOfAllOutcomes(q, qubits), expect, atol=ATOL)
    r, mat = _rand_rho(env, rng)
    d = np.real(np.diag(mat))
    expect_r = np.zeros(2 ** k)
    for i in range(DIM):
        out = sum(((i >> q) & 1) << j for j, q in enumerate(qubits))
        expect_r[out] += d[i]
    np.testing.assert_allclose(qt.calcProbOfAllOutcomes(r, qubits), expect_r, atol=ATOL)


def test_calc_inner_product(env, rng):
    q1, v1 = _rand_psi(env, rng)
    q2, v2 = _rand_psi(env, rng)
    expect = np.vdot(v1, v2)
    got = qt.calcInnerProduct(q1, q2)
    assert np.isclose(got, expect)


def test_calc_density_inner_product(env, rng):
    r1, m1 = _rand_rho(env, rng)
    r2, m2 = _rand_rho(env, rng)
    expect = np.real(np.trace(m1.conj().T @ m2))
    assert np.isclose(qt.calcDensityInnerProduct(r1, r2), expect)


def test_calc_purity(env, rng):
    r, mat = _rand_rho(env, rng)
    expect = np.real(np.trace(mat @ mat))
    assert np.isclose(qt.calcPurity(r), expect)


def test_calc_fidelity(env, rng):
    q1, v1 = _rand_psi(env, rng)
    q2, v2 = _rand_psi(env, rng)
    assert np.isclose(qt.calcFidelity(q1, q2), np.abs(np.vdot(v1, v2)) ** 2)
    r, mat = _rand_rho(env, rng)
    expect = np.real(np.vdot(v1, mat @ v1))
    assert np.isclose(qt.calcFidelity(r, q1), expect)


def test_calc_hilbert_schmidt_distance(env, rng):
    r1, m1 = _rand_rho(env, rng)
    r2, m2 = _rand_rho(env, rng)
    expect = np.sqrt(np.sum(np.abs(m1 - m2) ** 2))
    assert np.isclose(qt.calcHilbertSchmidtDistance(r1, r2), expect)


@pytest.mark.parametrize(
    "targets,codes",
    [([0], [3]), ([2], [1]), ([1, 4], [2, 3]), ([0, 2, 3], [1, 1, 2])],
)
def test_calc_expec_pauli_prod(env, rng, targets, codes):
    q, vec = _rand_psi(env, rng)
    op = oracle.pauli_product(N, targets, codes)
    expect = np.real(np.vdot(vec, op @ vec))
    assert np.isclose(qt.calcExpecPauliProd(q, targets, codes), expect)
    r, mat = _rand_rho(env, rng)
    expect_r = np.real(np.trace(op @ mat))
    assert np.isclose(qt.calcExpecPauliProd(r, targets, codes), expect_r)


def test_calc_expec_pauli_sum_and_hamil(env, rng):
    num_terms = 4
    codes = rng.integers(0, 4, size=(num_terms, N))
    coeffs = rng.standard_normal(num_terms)
    q, vec = _rand_psi(env, rng)
    hmat = oracle.pauli_sum_matrix(N, codes, coeffs)
    expect = np.real(np.vdot(vec, hmat @ vec))
    assert np.isclose(qt.calcExpecPauliSum(q, codes, coeffs), expect)

    hamil = qt.createPauliHamil(N, num_terms)
    qt.initPauliHamil(hamil, coeffs, codes)
    assert np.isclose(qt.calcExpecPauliHamil(q, hamil), expect)

    r, mat = _rand_rho(env, rng)
    expect_r = np.real(np.trace(hmat @ mat))
    assert np.isclose(qt.calcExpecPauliHamil(r, hamil), expect_r)


def test_calc_expec_diagonal_op(env, rng):
    op = qt.createDiagonalOp(N, env)
    vals = rng.standard_normal(DIM) + 1j * rng.standard_normal(DIM)
    qt.initDiagonalOp(op, vals.real, vals.imag)
    q, vec = _rand_psi(env, rng)
    expect = np.sum(np.abs(vec) ** 2 * vals)
    assert np.isclose(qt.calcExpecDiagonalOp(q, op), expect)
    r, mat = _rand_rho(env, rng)
    expect_r = np.sum(np.diag(mat) * vals)
    assert np.isclose(qt.calcExpecDiagonalOp(r, op), expect_r)


def test_get_amp_family(env, rng):
    q, vec = _rand_psi(env, rng)
    assert np.isclose(qt.getAmp(q, 7), vec[7])
    assert np.isclose(qt.getRealAmp(q, 3), vec[3].real)
    assert np.isclose(qt.getImagAmp(q, 3), vec[3].imag)
    assert np.isclose(qt.getProbAmp(q, 5), np.abs(vec[5]) ** 2)
    r, mat = _rand_rho(env, rng)
    assert np.isclose(qt.getDensityAmp(r, 2, 3), mat[2, 3])


def test_calc_validation(env):
    q = qt.createQureg(N, env)
    r = qt.createDensityQureg(N, env)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.calcInnerProduct(q, r)
    with pytest.raises(qt.QuESTError, match="density matri"):
        qt.calcPurity(q)
    with pytest.raises(qt.QuESTError, match="density matri"):
        qt.calcDensityInnerProduct(q, q)
    q3 = qt.createQureg(3, env)
    with pytest.raises(qt.QuESTError, match="Dimensions"):
        qt.calcFidelity(q, q3)
