"""Layout-safe element access (ops/element.py): canonical-view reads and
ranged writes match the flat reference behavior, the public API routes
through them, and the full-state host-gather guard trips at the
reference's message cap (MPI_MAX_AMPS_IN_MSG, QuEST_precision.h:32-61;
toQVector guard utilities.cpp:1073-1074)."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import precision
from quest_tpu.ops import element as E
import oracle

import jax.numpy as jnp


def _canonical(flat):
    n = int(np.log2(flat.shape[1]))
    return jnp.asarray(flat).reshape(2, 1 << (n - 14), 128, 128)


@pytest.mark.parametrize("index", [0, 1, 127, 128, (1 << 14) - 1, 1 << 14,
                                   (1 << 15) + 12345, (1 << 16) - 1])
def test_get_amp_pair_canonical_matches_flat(index):
    n = 16
    rng = np.random.default_rng(3)
    flat = rng.standard_normal((2, 1 << n))
    can = _canonical(flat)
    got = np.asarray(E.get_amp_pair(can, index))
    np.testing.assert_allclose(got, flat[:, index], rtol=1e-12)
    got_flat = np.asarray(E.get_amp_pair(jnp.asarray(flat), index))
    np.testing.assert_allclose(got_flat, flat[:, index], rtol=1e-12)


@pytest.mark.parametrize("start,m", [
    (0, 5),                       # head of first block
    (100, 1 << 14),               # spans two blocks, both partial
    (1 << 14, 1 << 14),           # exactly one full block
    (5, 3 * (1 << 14)),           # partial + 2 full + partial
    ((1 << 16) - 7, 7),           # tail of last block
])
def test_set_amp_range_canonical_matches_flat(start, m):
    n = 16
    rng = np.random.default_rng(4)
    flat = rng.standard_normal((2, 1 << n))
    vals = rng.standard_normal((2, m))
    expect = flat.copy()
    expect[:, start:start + m] = vals
    got = np.asarray(
        E.set_amp_range(_canonical(flat), start, vals)).reshape(2, -1)
    np.testing.assert_allclose(got, expect, rtol=1e-12)


def test_api_get_set_roundtrip(env):
    rng = np.random.default_rng(5)
    q = qt.createQureg(5, env)
    vec = oracle.random_state(5, rng)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    for i in (0, 7, 31):
        a = qt.getAmp(q, i)
        assert abs(a - vec[i]) < 1e-12
        assert abs(qt.getProbAmp(q, i) - abs(vec[i]) ** 2) < 1e-12
    qt.setAmps(q, 3, [0.5, 0.25], [0.1, -0.1], 2)
    assert abs(qt.getAmp(q, 3) - (0.5 + 0.1j)) < 1e-12
    assert abs(qt.getAmp(q, 4) - (0.25 - 0.1j)) < 1e-12
    assert abs(qt.getAmp(q, 5) - vec[5]) < 1e-12


def test_get_density_amp(env):
    rng = np.random.default_rng(6)
    r = qt.createDensityQureg(4, env)
    mat = oracle.random_density(4, rng)
    oracle.set_qureg_from_array(qt, r, mat)
    for row, col in ((0, 0), (3, 9), (15, 15)):
        assert abs(qt.getDensityAmp(r, row, col) - mat[row, col]) < 1e-12


def test_host_gather_guard_trips(env, monkeypatch):
    monkeypatch.setitem(precision._MAX_AMPS_IN_MSG,
                        precision.get_precision(), 16)
    q1 = qt.createQureg(5, env)
    q2 = qt.createQureg(5, env)
    from quest_tpu import debug
    with pytest.raises(qt.QuESTError, match="too many amplitudes"):
        debug.compareStates(q1, q2, 1e-10)
    # writeStateToFile streams tile-aligned blocks and is exempt from
    # the single-buffer cap (ADVICE r4: the reference's reportState CSV
    # path streams per-rank chunks with no such cap)
    from quest_tpu import checkpoint
    checkpoint.writeStateToFile(q1, "/tmp/qt_guard_test.csv")
    with open("/tmp/qt_guard_test.csv") as f:
        lines = [ln for ln in f if not ln.startswith("#")]
    assert len(lines) == q1.num_amps_total
    with pytest.raises(qt.QuESTError, match="too many amplitudes"):
        qt.reportStateToScreen(q1)


def test_guard_not_tripped_at_normal_sizes(env):
    q1 = qt.createQureg(5, env)
    q2 = qt.createQureg(5, env)
    from quest_tpu import debug
    assert debug.compareStates(q1, q2, 1e-10)
