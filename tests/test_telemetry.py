"""Unified telemetry layer (quest_tpu/telemetry.py, ISSUE 4).

Covers the acceptance contract:
  * counter/label semantics (canonical label order, accumulation,
    per-series isolation) and histogram bucket bookkeeping;
  * span nesting emits Chrome-trace "X" events with the schema Perfetto
    loads, and ``write_trace`` round-trips them through JSON;
  * ``snapshot()`` / ``prometheus_text()`` agree series-for-series;
  * ``QT_TELEMETRY=off`` yields an empty snapshot, empty exposition,
    and never creates trace files;
  * the pinned 8-shard dryrun circuit's exchange count and byte totals
    match ``circuit.remap_exchange_bytes``'s cost model EXACTLY;
  * the fusion drain, resilience, and measurement instrumentation all
    report into the same registry, and ``run_resumable`` logs one JSON
    line per checkpoint/restore/watchdog event.
"""

import collections
import json
import logging
import re
import threading
import time

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import fusion
from quest_tpu import resilience as R
from quest_tpu import telemetry as T
from quest_tpu.parallel import dist

H_SOA = np.stack([(1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]]),
                  np.zeros((2, 2))])


@pytest.fixture(autouse=True)
def tele():
    """Telemetry on + a clean registry per test; the session mode is
    restored afterwards so other suites see their configured default."""
    prev = T.mode_name()
    T.configure("on")
    T.reset()
    yield T
    T.reset()
    T.configure(prev)


def _sum(series: dict) -> float:
    return sum(series.values())


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_accumulates(self):
        T.inc("widgets_total")
        T.inc("widgets_total", 2)
        assert T.counter_total("widgets_total") == 3

    def test_labels_are_canonical_and_isolated(self):
        """Label ORDER never splits a series; label VALUES always do."""
        T.inc("exchanges_total", 1, op="remap", chunks="4")
        T.inc("exchanges_total", 2, chunks="4", op="remap")
        T.inc("exchanges_total", 5, op="swap", chunks="4")
        snap = T.snapshot()["counters"]["exchanges_total"]
        assert snap["chunks=4,op=remap"] == 3
        assert snap["chunks=4,op=swap"] == 5
        assert T.counter_value("exchanges_total", op="remap", chunks=4) == 3

    def test_non_string_label_values_coerced(self):
        T.inc("c_total", 1, chunks=8)
        assert T.counter_value("c_total", chunks="8") == 1

    def test_gauge_overwrites(self):
        T.set_gauge("g", 1.0, device="d0")
        T.set_gauge("g", 7.5, device="d0")
        assert T.snapshot()["gauges"]["g"]["device=d0"] == 7.5

    def test_histogram_stats_and_buckets(self):
        for v in (0.0005, 0.05, 0.05, 3.0):
            T.observe("lat_seconds", v)
        h = T.snapshot()["histograms"]["lat_seconds"][""]
        assert h["count"] == 4
        assert h["min"] == 0.0005 and h["max"] == 3.0
        assert abs(h["sum"] - 3.1005) < 1e-12
        # cumulative le-buckets are monotone and end at the total count
        cums = list(h["buckets"].values())
        assert cums == sorted(cums) and cums[-1] == 4
        assert h["buckets"]["0.001"] == 1      # 0.0005
        assert h["buckets"]["0.1"] == 3        # + the two 0.05s

    def test_snapshot_folds_legacy_registries(self):
        """env._CACHE_STATS and the degradation registry surface as
        series of the same namespace (satellite: one consolidated view,
        old accessors keep working)."""
        snap = T.snapshot()
        assert "compile_cache_hits_total" in snap["counters"]
        assert "compile_cache_misses_total" in snap["counters"]
        from quest_tpu import env as E

        assert set(E.compile_cache_stats()) == {"hits", "misses", "dir"}

    def test_degradation_becomes_series(self, monkeypatch):
        monkeypatch.setattr(R, "DEGRADATIONS", {}, raising=True)
        with pytest.warns(UserWarning):
            R.record_degradation("unit_test", "synthetic downgrade")
        snap = T.snapshot()
        assert snap["counters"]["degradations_total"]["name=unit_test"] == 1
        assert snap["gauges"]["degradation_active"]["name=unit_test"] == 1.0
        assert R.degradation_report() == {"unit_test": "synthetic downgrade"}


# ---------------------------------------------------------------------------
# Off mode
# ---------------------------------------------------------------------------


class TestOffMode:
    def test_off_yields_empty_everything(self):
        T.inc("pre_total")
        T.configure("off")
        T.inc("post_total")
        assert T.snapshot() == {}
        assert T.prometheus_text() == ""
        assert T.counter_total("post_total") == 0
        # recording resumes (and the pre-off series survives) on re-enable
        T.configure("on")
        assert T.counter_total("pre_total") == 1
        assert T.counter_total("post_total") == 0

    def test_off_no_trace_files(self, tmp_path):
        T.configure("off")
        with T.span("invisible"):
            pass
        out = T.write_trace(str(tmp_path / "t.json"))
        assert out is None
        assert list(tmp_path.iterdir()) == []

    def test_env_var_resolution(self, monkeypatch):
        monkeypatch.setenv("QT_TELEMETRY", "off")
        assert T.configure() == "off"
        monkeypatch.setenv("QT_TELEMETRY", "trace")
        assert T.configure() == "trace"
        monkeypatch.delenv("QT_TELEMETRY")
        assert T.configure() == "on"  # the always-on default

    def test_off_dispatch_is_silent(self, env):
        T.configure("off")
        q = qt.createQureg(3, env)
        qt.hadamard(q, 0)
        qt.measure(q, 0)
        assert T.snapshot() == {}


# ---------------------------------------------------------------------------
# Spans and Chrome trace
# ---------------------------------------------------------------------------


class TestSpans:
    def test_span_records_duration_histogram(self):
        with T.span("unit.work"):
            pass
        h = T.snapshot()["histograms"]["span_seconds"]["name=unit.work"]
        assert h["count"] == 1 and h["sum"] >= 0

    def test_nested_spans_chrome_schema(self, tmp_path):
        T.configure("trace")
        with T.span("outer", phase="drain"):
            with T.span("inner"):
                pass
        path = T.write_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert [e["name"] for e in events] == ["inner", "outer"]
        for e in events:
            assert e["ph"] == "X" and e["cat"] == "quest_tpu"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        inner, outer = events
        # proper nesting: inner starts after outer and ends before it
        assert inner["tid"] == outer["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
        assert outer["args"] == {"phase": "drain"}

    def test_write_trace_drains_buffer(self, tmp_path):
        T.configure("trace")
        with T.span("once"):
            pass
        assert T.write_trace(str(tmp_path / "a.json")) is not None
        assert T.write_trace(str(tmp_path / "b.json")) is None
        assert not (tmp_path / "b.json").exists()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


_PROM_LINE = re.compile(r"^(\w+)(?:\{(.*)\})? ([-+0-9.e]+)$")


def _parse_prom(text: str) -> dict:
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels, value = m.groups()
        labels = ",".join(
            part.replace('"', "") for part in (labels or "").split(","))
        out[(name, labels)] = float(value)
    return out


class TestPrometheus:
    def test_round_trip_matches_snapshot(self):
        T.inc("exchanges_total", 3, op="remap", chunks="2")
        T.inc("exchanges_total", 1, op="swap", chunks="1")
        T.set_gauge("hbm_bytes", 123.0, device="cpu0")
        T.observe("lat_seconds", 0.02)
        parsed = _parse_prom(T.prometheus_text())
        snap = T.snapshot()
        for name, series in snap["counters"].items():
            for labels, v in series.items():
                assert parsed[(name, labels)] == pytest.approx(v)
        for name, series in snap["gauges"].items():
            for labels, v in series.items():
                assert parsed[(name, labels)] == pytest.approx(v)
        # histogram triplet: _count/_sum/_bucket with cumulative le
        assert parsed[("lat_seconds_count", "")] == 1
        assert parsed[("lat_seconds_sum", "")] == pytest.approx(0.02)
        assert parsed[("lat_seconds_bucket", "le=+Inf")] == 1

    def test_type_lines_present(self):
        T.inc("a_total")
        T.set_gauge("b", 1)
        T.observe("c_seconds", 0.5)
        text = T.prometheus_text()
        assert "# TYPE a_total counter" in text
        assert "# TYPE b gauge" in text
        assert "# TYPE c_seconds histogram" in text


class TestPrometheusStrictConformance:
    """The exposition must parse under a REAL Prometheus text-format
    parser (prometheus_client), not just our own reader — the regression
    this pins: non-finite values rendered as Python's ``inf``/``nan``
    (which Prometheus rejects) instead of ``+Inf``/``-Inf``/``NaN``."""

    @pytest.fixture(autouse=True)
    def _parser(self):
        pytest.importorskip("prometheus_client")

    def _families(self):
        from prometheus_client.parser import text_string_to_metric_families

        return {f.name: f for f in
                text_string_to_metric_families(T.prometheus_text())}

    def test_full_registry_parses(self):
        T.inc("exchanges_total", 3, op="remap", chunks="2")
        T.inc("exchanges_total", 1, op="swap", chunks="1")
        T.set_gauge("hbm_bytes", 123.0, device='weird"dev\\0')
        T.observe("lat_seconds", 0.02)
        T.observe("lat_seconds", 5.0)
        T.observe("fusion_window_gates", 3)
        fams = self._families()
        samples = {(s.name, tuple(sorted(s.labels.items()))): s.value
                   for f in fams.values() for s in f.samples}
        assert samples[("exchanges_total",
                        (("chunks", "2"), ("op", "remap")))] == 3
        assert samples[("hbm_bytes",
                        (("device", 'weird"dev\\0'),))] == 123.0

    def test_nonfinite_values_spelled_per_spec(self):
        T.set_gauge("g_inf", float("inf"), k="a")
        T.set_gauge("g_ninf", float("-inf"), k="a")
        T.set_gauge("g_nan", float("nan"), k="a")
        text = T.prometheus_text()
        assert 'g_inf{k="a"} +Inf' in text
        assert 'g_ninf{k="a"} -Inf' in text
        assert 'g_nan{k="a"} NaN' in text
        fams = self._families()
        import math

        vals = {s.metric_name if hasattr(s, "metric_name") else s.name:
                s.value for f in fams.values() for s in f.samples}
        assert math.isinf(vals["g_inf"]) and vals["g_inf"] > 0
        assert math.isinf(vals["g_ninf"]) and vals["g_ninf"] < 0
        assert math.isnan(vals["g_nan"])

    def test_histogram_semantics_cumulative_and_inclusive(self):
        """Cumulative le buckets with INCLUSIVE upper bounds, the +Inf
        bucket equal to _count, and consistent _sum — checked through
        the real parser's sample view."""
        bounds = T.HIST_BOUNDS["fusion_window_gates"]
        T.observe("fusion_window_gates", 1)    # == first bound: inclusive
        T.observe("fusion_window_gates", 2)    # == second bound: inclusive
        T.observe("fusion_window_gates", 10_000)  # beyond the last bound
        fams = self._families()
        f = fams["fusion_window_gates"]
        buckets = {s.labels["le"]: s.value for s in f.samples
                   if s.name == "fusion_window_gates_bucket"}
        count = next(s.value for s in f.samples
                     if s.name == "fusion_window_gates_count")
        total = next(s.value for s in f.samples
                     if s.name == "fusion_window_gates_sum")
        assert buckets[repr(float(bounds[0]))] == 1  # le=1 contains v==1
        assert buckets[repr(float(2))] == 2          # le=2 contains v==2
        assert buckets["+Inf"] == count == 3
        assert total == pytest.approx(1 + 2 + 10_000)
        # cumulative monotone over ascending bounds
        ordered = [buckets[repr(float(b))] for b in bounds] + \
            [buckets["+Inf"]]
        assert ordered == sorted(ordered)


# ---------------------------------------------------------------------------
# The 8-shard dryrun: exchange accounting vs the cost model
# ---------------------------------------------------------------------------


def _expected_remap_cost(bit_sets, n, nloc, r, itemsize):
    """Re-derive what the drain + final canonical read must exchange,
    straight from the scheduling layer's own cost model."""
    count = 0
    nbytes = 0
    segments, final_perm = CIRC.plan_remap_windows(bit_sets, n, nloc, None)
    sigmas = [s for _ij, s, _p in segments if s is not None]
    if final_perm is not None and list(final_perm) != list(range(n)):
        sigmas.append(dist.canonical_sigma(final_perm))
    for sigma in sigmas:
        mixed, _lp, mesh_tau = dist.decompose_sigma(sigma, nloc, r)
        count += len(mixed) + (1 if mesh_tau is not None else 0)
        nbytes += CIRC.remap_exchange_bytes(sigma, n, nloc, itemsize)
    return count, nbytes


class TestExchangeAccounting:
    @pytest.fixture(autouse=True)
    def _mesh(self, env):
        if env.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        dist.use_explicit_dist(True)
        dist.use_lazy_remap(True)
        yield

    def test_pinned_dryrun_matches_remap_cost_model(self, env):
        """Acceptance: the pinned 8-shard circuit's telemetry exchange
        count and byte totals equal circuit.remap_exchange_bytes's model
        EXACTLY — one windowed remap inside the drain plus the canonical
        rematerialization on the final read, nothing else."""
        n, r = 6, dist.num_shard_bits(env.mesh)
        nloc = n - r
        rng = np.random.default_rng(3)
        g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
        u, _ = np.linalg.qr(g)
        q = qt.createQureg(n, env)
        itemsize = np.dtype(q.dtype).itemsize
        bit_sets = [(0, 1), (n - 2, n - 1), (0, 1)]
        exp_count, exp_bytes = _expected_remap_cost(
            bit_sets, n, nloc, r, itemsize)
        assert exp_count > 0 and exp_bytes > 0  # the circuit IS sharded
        T.reset()
        with qt.gateFusion(q):
            for a, b in bit_sets:
                qt.multiQubitUnitary(q, [a, b], u)
        _ = qt.calcProbOfOutcome(q, 0, 0)  # drains + rematerializes
        snap = T.snapshot()
        got_bytes = _sum(snap["counters"]["exchange_bytes_total"])
        got_count = _sum(snap["counters"]["exchanges_total"])
        assert got_bytes == exp_bytes
        assert got_count == exp_count
        # and both op families are present: the in-drain window remap
        # and the canonical-order rematerialization on read (flat 1x8
        # topology: every hop rides ICI)
        assert "op=window_remap,tier=ici" \
            in snap["counters"]["exchange_bytes_total"]
        assert "op=remap,tier=ici" \
            in snap["counters"]["exchange_bytes_total"]

    def test_tier_split_sums_to_cost_model(self, env, monkeypatch):
        """Satellite (ISSUE 12): under the emulated 2x4 topology the
        tier-labeled byte series sum EXACTLY to the flat cost-model
        totals, and each tier individually matches the tier-aware
        model (circuit.remap_exchange_bytes_tiers)."""
        monkeypatch.setenv("QT_TOPOLOGY", "2x4")
        n, r = 6, dist.num_shard_bits(env.mesh)
        nloc = n - r
        rng = np.random.default_rng(3)
        g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
        u, _ = np.linalg.qr(g)
        q = qt.createQureg(n, env)
        itemsize = np.dtype(q.dtype).itemsize
        bit_sets = [(0, 1), (n - 2, n - 1), (0, 1)]
        exp_count, exp_bytes = _expected_remap_cost(
            bit_sets, n, nloc, r, itemsize)
        # per-tier expectation straight from the tier-aware cost model,
        # over the same sigmas the drain + final read will dispatch
        exp_tier = {"ici": 0, "dcn": 0}
        segments, final_perm = CIRC.plan_remap_windows(
            bit_sets, n, nloc, None)
        sigmas = [s for _ij, s, _p in segments if s is not None]
        if final_perm is not None and list(final_perm) != list(range(n)):
            sigmas.append(dist.canonical_sigma(final_perm))
        for sigma in sigmas:
            for tier, b in CIRC.remap_exchange_bytes_tiers(
                    sigma, n, nloc, itemsize).items():
                exp_tier[tier] += b
        assert sum(exp_tier.values()) == exp_bytes  # model is a split
        T.reset()
        with qt.gateFusion(q):
            for a, b in bit_sets:
                qt.multiQubitUnitary(q, [a, b], u)
        _ = qt.calcProbOfOutcome(q, 0, 0)
        series = T.snapshot()["counters"]["exchange_bytes_total"]
        got_tier = {t: sum(v for k, v in series.items()
                           if f"tier={t}" in k) for t in ("ici", "dcn")}
        assert got_tier == exp_tier
        assert sum(got_tier.values()) == exp_bytes

    def test_eager_1q_exchange_payload(self, env):
        """A sharded-target 1q gate records one full-shard exchange with
        the resolved chunk config."""
        n = 6
        amps = qt.createQureg(n, env).amps
        T.reset()
        out = dist.apply_matrix_1q_sharded(
            amps, H_SOA.reshape(2, 2, 2), mesh=env.mesh, num_qubits=n,
            target=n - 1, chunks=2)
        out.block_until_ready()
        shard_bytes = 2 * (1 << (n - dist.num_shard_bits(env.mesh))) \
            * amps.dtype.itemsize
        assert T.counter_value("exchanges_total",
                               op="matrix_1q", chunks=2, tier="ici") == 1
        assert T.counter_value("exchange_bytes_total",
                               op="matrix_1q", tier="ici") == shard_bytes

    def test_swap_records_half_shard(self, env):
        n = 6
        amps = qt.createQureg(n, env).amps
        T.reset()
        dist.swap_sharded(amps, mesh=env.mesh, num_qubits=n,
                          qb_low=0, qb_high=n - 1).block_until_ready()
        shard_bytes = 2 * (1 << (n - dist.num_shard_bits(env.mesh))) \
            * amps.dtype.itemsize
        assert T.counter_value("exchange_bytes_total",
                               op="swap", tier="ici") == shard_bytes // 2

    def test_no_double_count_inside_user_jit(self, env):
        """A wrapper reached while TRACING a user jit must not record —
        dispatch-time accounting, not trace-time."""
        import jax

        n = 6
        amps = qt.createQureg(n, env).amps
        jfn = jax.jit(lambda a: dist.swap_sharded(
            a, mesh=env.mesh, num_qubits=n, qb_low=0, qb_high=n - 1))
        T.reset()
        jfn(amps).block_until_ready()
        jfn(amps).block_until_ready()
        assert T.counter_total("exchanges_total") == 0


# ---------------------------------------------------------------------------
# Fusion, dispatch, measurement instrumentation
# ---------------------------------------------------------------------------


class TestHotLayerHooks:
    def test_drain_and_plan_cache_counters(self, env):
        n = 5
        rng = np.random.default_rng(11)
        g = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
        u, _ = np.linalg.qr(g)

        def run_once():
            q = qt.createQureg(n, env)
            with qt.gateFusion(q):
                for t in range(n):
                    qt.unitary(q, t, u)
            return qt.calcTotalProb(q)

        run_once()  # not measured: may hit stale session-wide caches
        before = T.snapshot()
        run_once()
        after = T.snapshot()

        def delta(name):
            return (_sum(after["counters"].get(name, {}))
                    - _sum(before["counters"].get(name, {})))

        assert delta("fusion_drains_total") == 1
        assert delta("fusion_plan_cache_hits_total") == 1
        assert delta("fusion_plan_cache_misses_total") == 0
        assert delta("fusion_retrace_total") == 0  # same program shape
        assert delta("fusion_windows_total") >= 1
        assert after["counters"]["dispatch_total"]["family=unitary"] \
            >= before["counters"]["dispatch_total"]["family=unitary"] + n
        h = after["histograms"]["fusion_drain_gates"][""]
        assert h["count"] >= 2 and h["max"] >= n

    def test_measurement_shot_counters(self, env):
        q = qt.createQureg(3, env)
        qt.hadamard(q, 0)
        T.reset()
        qt.measure(q, 0)
        qt.measureSequence(q, [0, 1, 2])
        assert T.counter_total("measurement_shots_total") == 4

    def test_environment_string_has_consolidated_block(self, env):
        qt.hadamard(qt.createQureg(2, env), 0)
        s = qt.getEnvironmentString(env)
        assert "[telemetry: on" in s
        assert "dispatch=" in s
        T.configure("off")
        assert "[telemetry: off]" in qt.getEnvironmentString(env)

    def test_report_perf_prints_counters(self, env, capsys):
        qt.hadamard(qt.createQureg(2, env), 0)
        qt.reportPerf(env)
        out = capsys.readouterr().out
        assert "quest_tpu perf report" in out
        assert "dispatch_total{family=unitary}" in out
        assert "EnvType=quest_tpu" in out


# ---------------------------------------------------------------------------
# Profiling satellites
# ---------------------------------------------------------------------------


class TestProfilingHooks:
    def test_timed_observes_histogram(self):
        from quest_tpu.utils import profiling

        with profiling.timed("unit_block") as t:
            pass
        assert "seconds" in t
        h = T.snapshot()["histograms"]["timed_seconds"]["label=unit_block"]
        assert h["count"] == 1
        assert abs(h["sum"] - t["seconds"]) < 1e-9

    def test_memory_watermark_per_device(self):
        import jax

        from quest_tpu.utils import profiling

        wm = profiling.memory_watermark()
        assert len(wm) == len(jax.local_devices())
        # CPU backend exposes no stats: the graceful fallback is {}
        for stats in wm.values():
            assert isinstance(stats, dict)


# ---------------------------------------------------------------------------
# Resilience instrumentation + structured run logging
# ---------------------------------------------------------------------------


class TestResilienceHooks:
    def test_checkpoint_metrics_and_json_log(self, env, tmp_path, caplog):
        n, every = 4, 2
        gates = [CIRC.Gate((t,), H_SOA) for t in range(n)]
        q = qt.createQureg(n, env)
        T.reset()
        with caplog.at_level(logging.INFO, logger="quest_tpu.resilience"):
            qt.run_resumable(q, gates, str(tmp_path / "ck"), every=every)
        snap = T.snapshot()
        assert _sum(snap["counters"]["checkpoints_total"]) == 2
        assert snap["histograms"]["checkpoint_commit_seconds"][""]["count"] \
            == 2
        verdicts = snap["counters"]["watchdog_verdicts_total"]
        assert verdicts["policy=raise,verdict=ok"] == 2
        # one JSON line per event, each carrying the run context
        events = [json.loads(rec.message) for rec in caplog.records]
        kinds = [e["event"] for e in events]
        assert kinds.count("checkpoint") == 2
        assert kinds.count("watchdog") == 2
        run_ids = {e["run"] for e in events}
        assert len(run_ids) == 1
        for e in events:
            assert "elapsed" in e
            if e["event"] == "checkpoint":
                assert e["generation"].startswith("gen-")
                assert "window" in e and "seconds" in e

    def test_restore_logs_and_counts(self, env, tmp_path, caplog):
        n, every = 4, 2
        gates = [CIRC.Gate((t,), H_SOA) for t in range(n)]
        ck = str(tmp_path / "ck")
        qt.run_resumable(qt.createQureg(n, env), gates, ck, every=every)
        T.reset()
        q2 = qt.createQureg(n, env)
        with caplog.at_level(logging.INFO, logger="quest_tpu.resilience"):
            qt.run_resumable(q2, gates, ck, every=every)
        assert T.counter_total("checkpoint_restores_total") == 1
        events = [json.loads(rec.message) for rec in caplog.records]
        assert events[0]["event"] == "restore"
        assert events[0]["cursor"] == n  # resumed at the finished cursor

    def test_io_retry_counter(self, env, tmp_path):
        q = qt.createQureg(4, env)
        plan = qt.FaultPlan("io@2")
        T.reset()
        qt.run_resumable(q, [CIRC.Gate((0,), H_SOA)],
                         str(tmp_path / "ck"), every=1, faults=plan)
        assert T.counter_total("checkpoint_io_retries_total") == 2
        assert plan.log == ["io", "io"]


class TestServingResilienceSeries:
    """The serving fault-tolerance series names are operator contract
    (dashboards and alerts key on them) — pinned against the exposition
    byte-for-byte, plus the perf_report "serving resilience" block."""

    def _record(self):
        T.inc("serve_bank_retries_total", 2, reason="transient")
        T.inc("serve_bank_retries_total", reason="failover")
        T.inc("serve_bank_retries_total", reason="poison")
        T.inc("serve_jobs_quarantined_total", tenant="acme")
        T.inc("serve_failovers_total")
        T.inc("serve_heals_total")
        T.set_gauge("serve_degraded", 1.0)
        T.set_gauge("serve_failover_mttr_seconds", 0.025)

    def test_pinned_prometheus_names(self):
        self._record()
        text = T.prometheus_text()
        assert 'serve_bank_retries_total{reason="transient"} 2' in text
        assert 'serve_bank_retries_total{reason="failover"} 1' in text
        assert 'serve_bank_retries_total{reason="poison"} 1' in text
        assert 'serve_jobs_quarantined_total{tenant="acme"} 1' in text
        assert "\nserve_failovers_total 1" in text
        assert "\nserve_heals_total 1" in text
        assert "\nserve_degraded 1" in text
        assert "\nserve_failover_mttr_seconds 0.025" in text

    def test_perf_report_serving_resilience_block(self):
        self._record()
        report = T.perf_report()
        assert "serving resilience:" in report
        assert "bank retries: total=4 " \
               "(transient=2 failover=1 poison=1)" in report
        assert "quarantined=1 failovers=1 heals=1 degraded=1" in report
        assert "failover_mttr_seconds=0.025" in report

    def test_block_absent_when_no_faults(self):
        T.inc("serve_jobs_submitted_total", tenant="acme")
        assert "serving resilience:" not in T.perf_report()

    def test_environment_string_serve_fragment(self, env):
        from quest_tpu.env import get_environment_string
        assert "Serve=" not in get_environment_string(env)
        self._record()
        s = get_environment_string(env)
        assert "Serve=retries:4,quarantined:1,failovers:1," \
               "heals:1,degraded:1" in s


# ---------------------------------------------------------------------------
# Bounded Chrome-trace ring (docs/design.md §30)
# ---------------------------------------------------------------------------


class TestBoundedTraceRing:
    """The trace buffer is a bounded ring: overflow drops the OLDEST
    event, counts ``trace_events_dropped_total``, and ``write_trace``
    notes the drops (then resets the accounting for the next capture)."""

    def test_overflow_drops_oldest_and_counts(self, monkeypatch):
        T.configure("trace")
        monkeypatch.setattr(T, "_TRACE_MAX", 4)
        for i in range(7):
            with T.span("ring", seq=i):
                pass
        assert len(T._TRACE_EVENTS) == 4
        assert [e["args"]["seq"] for e in T._TRACE_EVENTS] == \
            ["3", "4", "5", "6"]
        assert T.counter_total("trace_events_dropped_total") == 3

    def test_write_trace_notes_drops_then_resets(self, monkeypatch,
                                                 tmp_path):
        T.configure("trace")
        monkeypatch.setattr(T, "_TRACE_MAX", 2)
        for _ in range(5):
            with T.span("w"):
                pass
        path = T.write_trace(str(tmp_path / "t.json"))
        with open(path) as f:
            doc = json.load(f)
        assert len(doc["traceEvents"]) == 2
        assert doc["otherData"]["trace_events_dropped"] == 3
        # the drain reset the drop accounting: a fresh capture that
        # does not overflow writes no otherData note
        with T.span("w"):
            pass
        with open(T.write_trace(str(tmp_path / "t2.json"))) as f:
            assert "otherData" not in json.load(f)


class TestThreadExactness:
    """The registry lock makes concurrent upserts exact (§30): no lost
    increments or observations under contended writers on the inc /
    inc_key / observe / set_gauge hot paths."""

    def test_concurrent_writers_exact_totals(self):
        workers, per = 8, 400
        fast = T.counter_key("contended_fast_total", lane="x")
        barrier = threading.Barrier(workers)

        def work(k):
            barrier.wait()
            for _ in range(per):
                T.inc("contended_total", worker=k % 2)
                T.inc_key(fast)
                T.observe("contended_seconds", 1e-6)
                T.set_gauge("contended_gauge", float(k))

        threads = [threading.Thread(target=work, args=(k,))
                   for k in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert T.counter_total("contended_total") == workers * per
        assert T.counter_total("contended_fast_total") == workers * per
        hd = T.snapshot()["histograms"]["contended_seconds"][""]
        assert hd["count"] == workers * per
        assert hd["sum"] == pytest.approx(workers * per * 1e-6)


# ---------------------------------------------------------------------------
# Flight recorder (docs/design.md §30)
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_oldest_dropped(self, monkeypatch):
        monkeypatch.setattr(T, "_FLIGHT", collections.deque(maxlen=3))
        for i in range(6):
            T.flight_event("tick", seq=i)
        evs = T.flight_snapshot()
        assert [e["seq"] for e in evs] == [3, 4, 5]
        assert all(e["kind"] == "tick" for e in evs)

    def test_dump_parseable_and_ring_not_drained(self, tmp_path):
        T.flight_event("bank_dissolved", bank=1, reason="transient",
                       jobs=3)
        T.flight_event("admission_rejected", tenant="acme",
                       reason="queue_full", limit=4)
        path = T.dump_flight(str(tmp_path / "f.json"), reason="quarantine",
                             tenant="acme", job=7, error=ValueError("boom"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["reason"] == "quarantine"
        assert doc["context"]["tenant"] == "acme"
        assert doc["context"]["job"] == 7  # primitives pass through
        assert doc["context"]["error"] == "boom"  # non-primitives -> str
        assert [e["kind"] for e in doc["events"]] == \
            ["bank_dissolved", "admission_rejected"]
        assert doc["events"][0]["jobs"] == 3
        assert T.counter_value("flight_dumps_total",
                               reason="quarantine") == 1
        # the ring is NOT drained: a later incident still sees the
        # earlier context in its own dump
        p2 = T.dump_flight(str(tmp_path / "g.json"), reason="failover")
        with open(p2) as f:
            assert len(json.load(f)["events"]) == 2

    def test_reserved_keys_and_stringification(self):
        T.flight_event("k", ts=-1.0, kind="spoof", err=ValueError("x"),
                       n=2)
        ev = T.flight_snapshot()[-1]
        assert ev["kind"] == "k" and ev["ts"] >= 0  # reserved keys win
        assert ev["err"] == "x" and ev["n"] == 2
        json.dumps(ev)  # always JSON-serializable

    def test_off_mode_records_nothing_writes_nothing(self, tmp_path):
        T.configure("off")
        T.flight_event("tick")
        target = tmp_path / "f.json"
        assert T.dump_flight(str(target), reason="x") is None
        assert not target.exists()
        T.configure("on")
        assert T.flight_snapshot() == []


# ---------------------------------------------------------------------------
# Request-scoped tracing (docs/design.md §30)
# ---------------------------------------------------------------------------


class TestRequestTraces:
    def _lifecycle(self, tid):
        """The serve-layer shape: one root "job" span wrapping points
        (admit/complete), a nested span, and an externally-timed span."""
        T.trace_begin(tid, "job", tenant="acme")
        T.trace_point(tid, "serve.admit", queue_depth=1)
        with T.trace_span(tid, "serve.window", bank=0):
            pass
        T.trace_add(tid, "serve.window", t0=time.perf_counter(),
                    dur=1e-3, bank=0, window=1)
        T.trace_point(tid, "serve.complete", outcomes=2)
        T.trace_end(tid, status="done")

    def test_complete_trace_well_nested(self):
        self._lifecycle("s0-j1")
        tz = T.tracez("s0-j1")
        assert tz["complete"] and not tz["open"] and tz["dropped"] == 0
        assert [e["name"] for e in tz["events"]] == \
            ["job", "serve.admit", "serve.window", "serve.window",
             "serve.complete"]
        roots = tz["tree"]
        assert len(roots) == 1 and roots[0]["name"] == "job"
        assert roots[0]["args"] == {"tenant": "acme", "status": "done"}
        assert [c["name"] for c in roots[0]["children"]] == \
            ["serve.admit", "serve.window", "serve.window",
             "serve.complete"]

    def test_index_unknown_id_and_open_spans(self):
        self._lifecycle("a")
        T.trace_begin("b", "job")
        assert T.tracez("nope") is None
        idx = T.tracez()["traces"]
        assert idx["a"]["complete"] and idx["a"]["events"] == 5
        assert idx["b"]["open"] == ["job"] and not idx["b"]["complete"]
        assert T.trace_ids() == ["a", "b"]
        assert T.tracez("b")["open"][0]["name"] == "job"

    def test_id_eviction_oldest_first(self, monkeypatch):
        monkeypatch.setattr(T, "_TRACEZ_IDS", 2)
        for tid in ("t1", "t2", "t3"):
            T.trace_point(tid, "x")
        assert T.trace_ids() == ["t2", "t3"]
        assert T.tracez("t1") is None

    def test_per_id_event_cap_counts_drops(self, monkeypatch):
        monkeypatch.setattr(T, "_TRACEZ_EVENTS", 3)
        for i in range(5):
            T.trace_point("t", "p", seq=i)
        tz = T.tracez("t")
        assert len(tz["events"]) == 3 and tz["dropped"] == 2
        assert [e["args"]["seq"] for e in tz["events"]] == ["2", "3", "4"]

    def test_mirrors_into_flight_ring(self):
        self._lifecycle("s1-j2")
        kinds = {(e["kind"], e.get("name")) for e in T.flight_snapshot()}
        assert ("event", "serve.admit") in kinds
        assert ("span", "job") in kinds

    def test_off_mode_records_nothing(self):
        T.configure("off")
        T.trace_begin("t", "job")
        T.trace_point("t", "x")
        T.trace_end("t")
        T.configure("on")
        assert T.tracez("t") is None


# ---------------------------------------------------------------------------
# Per-op wall-time attribution (docs/design.md §30)
# ---------------------------------------------------------------------------


class TestPerOpAttribution:
    def test_report_flags_dispatch_bound_route(self):
        T.observe("plan_route_seconds", 0.0100, route="winfused")
        T.observe("plan_route_seconds", 0.0102, route="winfused")
        T.observe("plan_route_seconds", 0.5, route="megawin")
        T.set_gauge("per_program_dispatch_seconds", 0.0095)
        rep = T.perf_report()
        assert "per-op attribution" in rep
        lines = {l.split(":")[0].strip(): l for l in rep.splitlines()
                 if "route=" in l}
        assert "dispatch_bound" in lines["route=winfused"]
        assert "dispatch_bound" not in lines["route=megawin"]

    def test_no_floor_gauge_no_verdict(self):
        T.observe("plan_route_seconds", 1e-4, route="winfused")
        rep = T.perf_report()
        assert "per-op attribution" in rep
        assert "dispatch_bound" not in rep

    def test_drain_records_route_series(self, env):
        h = (1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]],
                                        dtype=complex)
        q = qt.createQureg(4, env)
        with qt.gateFusion(q):
            for t in range(4):
                qt.unitary(q, t, h)
        qt.calcTotalProb(q)
        routes = T.snapshot()["histograms"].get("plan_route_seconds", {})
        assert routes, "drain recorded no per-route attribution"
        assert T.counter_total("plan_route_dispatch_total") >= 1


class TestMemoryWatermarkGauge:
    def test_watermark_published_for_metrics(self, env):
        from quest_tpu.utils import profiling
        profiling.memory_watermark()
        series = T.snapshot()["gauges"].get(
            "device_memory_watermark_bytes", {})
        assert series, "no watermark gauge published"
        assert all(v >= 0 for v in series.values())
        assert "device_memory_watermark_bytes" in T.prometheus_text()
