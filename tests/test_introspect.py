"""Execution introspection (ISSUE 8): plan explainer, HLO audit API,
and the predicted-vs-measured reconciliation contract.

The load-bearing acceptance test is the pinned 8-shard dryrun:
explain_circuit's predicted window-remap exchange count and per-shard
ICI bytes must equal (a) an independent re-derivation from the
scheduling layer's own cost model and (b) the telemetry counters after
actually draining the same stream — with ``model_drift_total == 0``.
An injected planner-policy perturbation (forced chunk-key override,
scaled prediction) must be detected as nonzero drift with exactly ONE
structured JSON log line.
"""

import json
import logging

import jax.numpy as jnp
import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import batch as B
from quest_tpu import circuit as CIRC
from quest_tpu import fusion
from quest_tpu import introspect
from quest_tpu import telemetry as T
from quest_tpu.parallel import dist


@pytest.fixture(autouse=True)
def raw_planner(monkeypatch):
    """This suite pins the RAW planner cost model (window counts and
    exchange predictions derived from the literal gate stream), so the
    circuit optimizer is disabled here; its own contract is pinned by
    tests/test_optimizer.py."""
    monkeypatch.setenv("QT_OPTIMIZER", "off")
    from quest_tpu import optimizer as _opt
    _opt.clear_cache()
    yield


@pytest.fixture(autouse=True)
def tele():
    """Telemetry on + a clean registry per test (mode restored after)."""
    prev = T.mode_name()
    T.configure("on")
    T.reset()
    yield T
    T.reset()
    T.configure(prev)


@pytest.fixture
def env8(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return env


def _u4(seed=3):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    return u


N = 6
BIT_SETS = [(0, 1), (N - 2, N - 1), (0, 1)]


def _buffered_qureg(env, n=N, bit_sets=None, u=None):
    q = qt.createQureg(n, env)
    qt.startGateFusion(q)
    for ts in (bit_sets or BIT_SETS):
        qt.multiQubitUnitary(q, list(ts), u if u is not None else _u4())
    return q


def _model_window_cost(bit_sets, n, nloc, r, itemsize):
    """Independent re-derivation of the drain's window-remap cost (the
    final canonical read is accounted separately as final_remap)."""
    count = 0
    nbytes = 0
    segments, final_perm = CIRC.plan_remap_windows(
        list(bit_sets), n, nloc, None)
    for _ij, sigma, _p in segments:
        if sigma is None:
            continue
        count += dist.remap_exchange_count(sigma, nloc, r)
        nbytes += CIRC.remap_exchange_bytes(sigma, n, nloc, itemsize)
    return count, nbytes, final_perm


# ---------------------------------------------------------------------------
# Plan explainer
# ---------------------------------------------------------------------------


class TestExplainCircuit:
    def test_dry_run_does_not_drain(self, env8):
        q = _buffered_qureg(env8)
        report = qt.explainCircuit(q)
        # the buffer is untouched, nothing executed, no counters moved
        assert len(q._fusion.gates) == len(BIT_SETS)
        assert report["items"] == len(BIT_SETS)
        assert T.counter_total("fusion_drains_total") == 0
        assert T.counter_total("exchanges_total") == 0

    def test_pinned_8shard_dryrun_matches_cost_model_and_counters(
            self, env8):
        """The acceptance contract: explainer == cost model == telemetry,
        exactly, with zero model drift."""
        q = _buffered_qureg(env8)
        n = N
        r = q.num_chunks.bit_length() - 1
        nloc = n - r
        itemsize = np.dtype(q.dtype).itemsize
        report = qt.explainCircuit(q)

        # against the scheduling layer's own cost model
        count, nbytes, final_perm = _model_window_cost(
            BIT_SETS, n, nloc, r, itemsize)
        assert report["totals"]["exchanges"] == count
        assert report["totals"]["exchange_bytes"] == nbytes
        sigma_read = dist.canonical_sigma(final_perm)
        assert report["final_remap"]["exchanges"] == \
            dist.remap_exchange_count(sigma_read, nloc, r)
        assert report["final_remap"]["exchange_bytes"] == \
            CIRC.remap_exchange_bytes(sigma_read, n, nloc, itemsize)


        # shape pins for this workload: 3 windows ([0,1] local, the
        # [4,5] window remaps, the return to [0,1] remaps again)
        assert report["totals"]["windows"] == 3
        assert report["windows"][0]["sigma"] is None
        assert report["windows"][1]["exchanges"] > 0
        assert report["register"]["shards"] == 8

        # against reality: drain + read, then diff the counters
        T.reset()
        _ = q.amps
        assert T.counter_sum("exchanges_total", op="window_remap") == \
            report["totals"]["exchanges"]
        assert T.counter_sum("exchange_bytes_total", op="window_remap") == \
            report["totals"]["exchange_bytes"]
        # the canonical-read rematerialization (op=remap) closes the gap
        # to the _with_read totals
        assert T.counter_total("exchanges_total") == \
            report["totals"]["exchanges_with_read"]
        assert T.counter_sum("exchange_bytes_total", op="remap") + \
            T.counter_sum("exchange_bytes_total", op="window_remap") == \
            report["totals"]["exchange_bytes_with_read"]
        # the drain ran its own reconciliation: the model held
        assert T.counter_total("model_drift_total") == 0
        assert T.counter_total("predicted_exchanges_total") == \
            report["totals"]["exchanges"]
        assert T.counter_total("fusion_windows_total") == \
            report["totals"]["plan_windows"]

    def test_plan_cache_and_retrace_prediction(self, env8):
        u = _u4()
        q1 = _buffered_qureg(env8, u=u)
        rep1 = qt.explainCircuit(q1)
        assert rep1["plan"]["cacheable"]
        if rep1["plan"]["cache"] == "miss":
            assert rep1["plan"]["retrace_expected"] is True
        _ = q1.amps  # populate the plan cache
        q2 = _buffered_qureg(env8, u=u)
        rep2 = qt.explainCircuit(q2)
        assert rep2["plan"]["cache"] == "hit"
        assert rep2["plan"]["retrace_expected"] is False

    def test_explicit_gate_list_and_unsharded(self, env):
        # 2 qubits < 8 devices -> the register is replicated, the plan
        # has no remap schedule at all
        q = qt.createQureg(2, env)
        u = _u4()
        report = qt.explainCircuit(q, [((0, 1), np.stack(
            [u.real, u.imag]))])
        assert report["items"] == 1
        assert report["register"]["shard_bits"] == 0
        assert report["totals"]["exchange_bytes"] == 0
        assert report["final_remap"] is None

    def test_json_serializable_and_table(self, env8, capsys):
        q = _buffered_qureg(env8)
        report = qt.explainCircuit(q)
        txt = json.dumps(report)  # must not raise
        assert "window_remap" not in txt or True
        table = report.table()
        assert "circuit plan: 6 qubits, 8 shard(s)" in table
        assert "bytes/shard" in table
        assert "totals: plan_windows=" in table
        qt.reportCircuitPlan(q)
        assert "circuit plan" in capsys.readouterr().out

    def test_batched_register_occupancy_and_scaling(self, env8):
        bsz = 3
        bq = qt.createBatchedQureg(N, env8, bsz)
        mats = np.stack([_u4(s) for s in range(bsz)])
        qt.applyBatchedUnitary(bq, (0, 1), mats)
        qt.applyBatchedUnitary(bq, (N - 2, N - 1), mats)
        report = qt.explainCircuit(bq)
        occ = report["register"]["batch"]
        assert occ["size"] == 3 and occ["bucket"] == 4
        assert occ["occupancy"] == pytest.approx(3 / 4)
        # predicted exchanges scale by the batch width
        r = report["register"]["shard_bits"]
        nloc = N - r
        itemsize = report["register"]["itemsize"]
        count, nbytes, _fp = _model_window_cost(
            [(0, 1), (N - 2, N - 1)], N, nloc, r, itemsize)
        assert report["totals"]["exchanges"] == count * bsz
        assert report["totals"]["exchange_bytes"] == nbytes * bsz
        # and the drain reconciles at the same scale: zero drift
        T.reset()
        _ = bq.amps
        assert T.counter_sum("exchanges_total", op="window_remap") == \
            report["totals"]["exchanges"]
        assert T.counter_total("model_drift_total") == 0

    def test_bank_occupancy_helper(self):
        class Fake:
            batch_size = 5

        occ = B.bank_occupancy(Fake())
        assert occ == {"size": 5, "bucket": 8, "occupancy": 5 / 8}
        assert B.bank_occupancy(object()) == {
            "size": 0, "bucket": 0, "occupancy": 1.0}


# ---------------------------------------------------------------------------
# HLO audit + collective budgets
# ---------------------------------------------------------------------------


class TestAudit:
    def _gate(self, env, n=10):
        h = (1 / np.sqrt(2)) * np.array([[1, 1], [1, -1]])
        m = jnp.asarray(np.stack([h, np.zeros((2, 2))]))

        def f(a):
            return dist.apply_matrix_1q_sharded(
                a, m, mesh=env.mesh, num_qubits=n, target=n - 1)

        import jax

        rng = np.random.default_rng(0)
        amps = rng.standard_normal((2, 1 << n))
        amps /= np.sqrt((amps ** 2).sum())
        return f, jax.device_put(jnp.asarray(amps), env.amp_sharding())

    def test_exact_collective_histogram_and_cost(self, env8):
        f, amps = self._gate(env8)
        report = introspect.audit(f, amps, donate=True)
        assert report.collectives == {"collective-permute": 1}
        assert report.count("collective-permute") == 1
        assert report.total == 1
        # the loose word-regex view is an upper bound on the exact one
        assert report.matches.get("collective-permute", 0) >= 1
        assert " collective-permute(" in report.text
        # cost_analysis is backend-dependent; when present the fields
        # are numeric
        if report.flops is not None:
            assert report.flops >= 0
        assert isinstance(report.cost, dict)
        assert isinstance(report.as_dict()["collectives"], dict)

    def test_no_collectives_on_local_fn(self, env8):
        def f(x):
            return x * 2.0

        report = introspect.audit(f, jnp.ones((8,)))
        assert report.collectives == {} and report.total == 0


class TestCollectiveBudget:
    def _hist(self, **h):
        return {k.replace("_", "-"): v for k, v in h.items()}

    def test_max_budget_passes_and_fails(self):
        b = introspect.CollectiveBudget(collective_permute=2)
        b.check(self._hist(collective_permute=2))
        with pytest.raises(introspect.CollectiveBudgetError):
            b.check(self._hist(collective_permute=3))
        # the -start async variant counts against the same family
        with pytest.raises(introspect.CollectiveBudgetError):
            b.check({"collective-permute": 2,
                     "collective-permute-start": 1})

    def test_exact_total_and_allow(self):
        introspect.CollectiveBudget(
            exact={"all-reduce": 1}).check({"all-reduce": 1})
        with pytest.raises(introspect.CollectiveBudgetError):
            introspect.CollectiveBudget(
                exact={"all-reduce": 1}).check({"all-reduce": 2})
        with pytest.raises(introspect.CollectiveBudgetError):
            introspect.CollectiveBudget(total=1).check(
                self._hist(all_gather=1, all_reduce=1))
        introspect.CollectiveBudget(allow=("all-reduce",)).check(
            {"all-reduce": 4, "all-reduce-start": 1})
        with pytest.raises(introspect.CollectiveBudgetError):
            introspect.CollectiveBudget(allow=("all-reduce",)).check(
                {"all-to-all": 1})

    def test_ambient_budget_checks_audits(self, env8):
        f, amps = TestAudit()._gate(env8)
        with introspect.CollectiveBudget(collective_permute=1):
            introspect.audit(f, amps, donate=True)
        with pytest.raises(introspect.CollectiveBudgetError):
            with introspect.CollectiveBudget(total=0):
                introspect.audit(f, amps, donate=True)
        # the stack unwinds: audits outside the block are unchecked
        assert introspect._BUDGET_STACK == []
        introspect.audit(f, amps, donate=True)


# ---------------------------------------------------------------------------
# Reconciliation + drift injection
# ---------------------------------------------------------------------------


class TestReconciliation:
    def _drain(self, env):
        q = _buffered_qureg(env)
        _ = q.amps
        return q

    def test_clean_drain_zero_drift_no_log(self, env8, caplog):
        with caplog.at_level(logging.INFO, logger="quest_tpu.introspect"):
            self._drain(env8)
        assert T.counter_total("model_drift_total") == 0
        assert caplog.records == []

    def test_forced_chunk_override_detected_as_drift(self, env8, caplog):
        """The acceptance criterion's injected planner-policy
        perturbation: a forced chunk-count override in the PREDICTION
        must disagree with the measured chunk key — nonzero drift, one
        structured log line."""
        with caplog.at_level(logging.WARNING,
                             logger="quest_tpu.introspect"):
            with introspect.perturb_prediction(chunks="4"):
                self._drain(env8)
        assert T.counter_value("model_drift_total", kind="chunks") == 1
        lines = [rec for rec in caplog.records
                 if rec.name == "quest_tpu.introspect"]
        assert len(lines) == 1
        payload = json.loads(lines[0].getMessage())
        assert payload["event"] == "model_drift"
        assert payload["kinds"] == ["chunks"]
        assert payload["drift"]["chunks"]["predicted"] == "4"

    def test_scaled_prediction_drifts_on_count_and_bytes(self, env8,
                                                         caplog):
        with caplog.at_level(logging.WARNING,
                             logger="quest_tpu.introspect"):
            with introspect.perturb_prediction(scale=2):
                self._drain(env8)
        assert T.counter_value("model_drift_total", kind="count") == 1
        assert T.counter_value("model_drift_total", kind="bytes") == 1
        lines = [rec for rec in caplog.records
                 if rec.name == "quest_tpu.introspect"]
        assert len(lines) == 1  # ONE line per reconciliation, not per kind
        payload = json.loads(lines[0].getMessage())
        assert payload["kinds"] == ["bytes", "count"]

    def test_env_var_perturbation(self, env8, monkeypatch):
        monkeypatch.setenv("QT_INTROSPECT_PERTURB", "scale=3")
        self._drain(env8)
        assert T.counter_total("model_drift_total") >= 1

    def test_perf_report_reconciliation_section(self, env8):
        self._drain(env8)
        text = T.perf_report()
        assert "reconciliation (window remaps, predicted vs measured):" \
            in text
        assert "cost model holds" in text
        pred = T.counter_sum("predicted_exchanges_total", op="window_remap")
        assert f"exchanges: predicted={int(pred)}" in text

    def test_perf_report_flags_drift(self, env8):
        with introspect.perturb_prediction(scale=2):
            self._drain(env8)
        assert "MODEL DRIFT" in T.perf_report()


# ---------------------------------------------------------------------------
# HBM watermark satellite
# ---------------------------------------------------------------------------


class TestWatermark:
    def test_drain_samples_watermark_gauge(self, env8):
        q = _buffered_qureg(env8)
        _ = q.amps
        peak = T.gauge_max("hbm_watermark_bytes")
        assert peak is not None and peak > 0

    def test_environment_string_surfaces_peak(self, env8):
        q = _buffered_qureg(env8)
        _ = q.amps
        s = qt.getEnvironmentString(env8)
        assert f"HbmPeak={int(T.gauge_max('hbm_watermark_bytes'))}" in s

    def test_perf_report_memory_line(self, env8):
        q = _buffered_qureg(env8)
        _ = q.amps
        assert "memory: hbm_watermark_bytes peak=" in T.perf_report()

    def test_gauge_gated_by_mode(self, env8):
        T.configure("off")
        q = _buffered_qureg(env8)
        _ = q.amps
        T.configure("on")
        assert T.gauge_max("hbm_watermark_bytes") is None
