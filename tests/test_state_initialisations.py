"""State initialisation tests (analogue of reference
test_state_initialisations.cpp, 9 TEST_CASEs)."""

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N
ATOL = 1e-12


def test_init_blank_state(env):
    q = qt.createQureg(N, env)
    qt.initBlankState(q)
    np.testing.assert_allclose(oracle.state_from_qureg(q), np.zeros(DIM), atol=ATOL)
    r = qt.createDensityQureg(N, env)
    qt.initBlankState(r)
    np.testing.assert_allclose(oracle.state_from_qureg(r), np.zeros((DIM, DIM)), atol=ATOL)


def test_init_zero_state(env):
    q = qt.createQureg(N, env)
    qt.initDebugState(q)
    qt.initZeroState(q)
    expect = np.zeros(DIM, complex)
    expect[0] = 1
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)
    r = qt.createDensityQureg(N, env)
    qt.initZeroState(r)
    em = np.zeros((DIM, DIM), complex)
    em[0, 0] = 1
    np.testing.assert_allclose(oracle.state_from_qureg(r), em, atol=ATOL)


def test_init_plus_state(env):
    q = qt.createQureg(N, env)
    qt.initPlusState(q)
    np.testing.assert_allclose(
        oracle.state_from_qureg(q), np.full(DIM, 1 / np.sqrt(DIM)), atol=ATOL
    )
    r = qt.createDensityQureg(N, env)
    qt.initPlusState(r)
    np.testing.assert_allclose(
        oracle.state_from_qureg(r), np.full((DIM, DIM), 1 / DIM), atol=ATOL
    )


@pytest.mark.parametrize("ind", [0, 1, 13, DIM - 1])
def test_init_classical_state(env, ind):
    q = qt.createQureg(N, env)
    qt.initClassicalState(q, ind)
    expect = np.zeros(DIM, complex)
    expect[ind] = 1
    np.testing.assert_allclose(oracle.state_from_qureg(q), expect, atol=ATOL)
    r = qt.createDensityQureg(N, env)
    qt.initClassicalState(r, ind)
    em = np.zeros((DIM, DIM), complex)
    em[ind, ind] = 1
    np.testing.assert_allclose(oracle.state_from_qureg(r), em, atol=ATOL)


def test_init_pure_state(env):
    rng = np.random.default_rng(7)
    vec = oracle.random_state(N, rng)
    src = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, src, vec)
    # statevec <- statevec copy
    dst = qt.createQureg(N, env)
    qt.initPureState(dst, src)
    np.testing.assert_allclose(oracle.state_from_qureg(dst), vec, atol=ATOL)
    # rho <- |psi><psi|
    rho = qt.createDensityQureg(N, env)
    qt.initPureState(rho, src)
    np.testing.assert_allclose(
        oracle.state_from_qureg(rho), np.outer(vec, vec.conj()), atol=ATOL
    )


def test_init_debug_state(env):
    q = qt.createQureg(N, env)
    qt.initDebugState(q)
    np.testing.assert_allclose(
        oracle.state_from_qureg(q), oracle.debug_state(DIM), atol=ATOL
    )


def test_init_state_from_amps_and_set_amps(env):
    rng = np.random.default_rng(8)
    vec = oracle.random_state(N, rng)
    q = qt.createQureg(N, env)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    np.testing.assert_allclose(oracle.state_from_qureg(q), vec, atol=ATOL)
    # partial overwrite
    sub = rng.standard_normal(4) + 1j * rng.standard_normal(4)
    qt.setAmps(q, 3, sub.real, sub.imag, 4)
    vec2 = vec.copy()
    vec2[3:7] = sub
    np.testing.assert_allclose(oracle.state_from_qureg(q), vec2, atol=ATOL)


def test_clone_qureg(env):
    rng = np.random.default_rng(9)
    vec = oracle.random_state(N, rng)
    src = qt.createQureg(N, env)
    oracle.set_qureg_from_array(qt, src, vec)
    dst = qt.createQureg(N, env)
    qt.cloneQureg(dst, src)
    np.testing.assert_allclose(oracle.state_from_qureg(dst), vec, atol=ATOL)
    # mutating the clone must not touch the source
    qt.pauliX(dst, 0)
    np.testing.assert_allclose(oracle.state_from_qureg(src), vec, atol=ATOL)
    clone = qt.createCloneQureg(src, env)
    np.testing.assert_allclose(oracle.state_from_qureg(clone), vec, atol=ATOL)


def test_init_validation(env):
    q = qt.createQureg(N, env)
    with pytest.raises(qt.QuESTError, match="Invalid state index"):
        qt.initClassicalState(q, DIM)
    with pytest.raises(qt.QuESTError, match="Incorrect number of amplitudes"):
        qt.initStateFromAmps(q, np.zeros(3), np.zeros(3))
    rho = qt.createDensityQureg(N, env)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.setAmps(rho, 0, np.zeros(1), np.zeros(1), 1)
    with pytest.raises(qt.QuESTError, match="state-vector"):
        qt.initPureState(q, rho)
