"""Route-selection guard rails (VERDICT r3 item 8): assert WHICH path
each composite picks under monkeypatched backend/mesh/explicit-dist
predicates, so a silently inverted routing predicate fails tests even
though the guarded branch itself cannot execute on this host (the
real-TPU fallback only matters on hardware we don't have in CI).

Spies replace the terminal kernels and record the call — no numerics
here (oracle parity is covered in test_distributed.py)."""

import numpy as np
import pytest

import jax

import quest_tpu as qt
from quest_tpu import api_ops
from quest_tpu.parallel import dist as PAR

N = 6  # spans the 8-device mesh (nloc = 3)


@pytest.fixture
def env(env=None):
    e = qt.createQuESTEnv()
    if e.num_devices < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return e


@pytest.fixture
def hamil():
    h = qt.createPauliHamil(N, 3)
    rng = np.random.default_rng(0)
    qt.initPauliHamil(h, rng.normal(size=3),
                      rng.integers(0, 4, size=(3, N)))
    return h


def _spy(monkeypatch, module, name, result=None, passthrough=False):
    calls = []
    real = getattr(module, name)

    def stub(*a, **k):
        calls.append((a, k))
        if passthrough:
            return real(*a, **k)
        return a[0] if result == "first_arg" else result

    monkeypatch.setattr(module, name, stub)
    return calls


def test_trotter_routes_explicit_sharded(env, hamil, monkeypatch):
    calls = _spy(monkeypatch, PAR, "trotter_scan_sharded",
                 result="first_arg")
    q = qt.createQureg(N, env)
    qt.applyTrotterCircuit(q, hamil, 0.1, 1, 1)
    assert len(calls) == 1, "sharded register must take the shard_map scan"


def test_trotter_gspmd_optout_on_fake_tpu_takes_per_term(env, hamil,
                                                         monkeypatch):
    """use_explicit_dist(False) + a TPU backend: raw Pallas cannot
    partition under GSPMD, so the per-term path must run (flipping
    _gspmd_pallas_unsafe would silently re-enable the broken route)."""
    from quest_tpu import api
    from quest_tpu.ops import paulis as P

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    per_term = _spy(monkeypatch, api, "multiRotatePauli")
    scan = _spy(monkeypatch, P, "trotter_scan", result="first_arg")
    sharded = _spy(monkeypatch, PAR, "trotter_scan_sharded",
                   result="first_arg")
    q = qt.createQureg(N, env)
    PAR.use_explicit_dist(False)
    try:
        qt.applyTrotterCircuit(q, hamil, 0.1, 1, 1)
    finally:
        PAR.use_explicit_dist(True)
    assert len(per_term) == 3 and not scan and not sharded


def test_trotter_gspmd_scan_on_cpu_mesh(env, hamil, monkeypatch):
    """Explicit off on the virtual CPU mesh: the GSPMD scan is safe
    (interpret-mode kernels partition as plain XLA) and must be used."""
    from quest_tpu.ops import paulis as P

    scan = _spy(monkeypatch, P, "trotter_scan", result="first_arg")
    q = qt.createQureg(N, env)
    PAR.use_explicit_dist(False)
    try:
        qt.applyTrotterCircuit(q, hamil, 0.1, 1, 1)
    finally:
        PAR.use_explicit_dist(True)
    assert len(scan) == 1


def test_expec_routes_explicit_sharded(env, hamil, monkeypatch):
    calls = _spy(monkeypatch, PAR, "expec_pauli_sum_scan_sharded",
                 result=np.float64(0.0))
    q = qt.createQureg(N, env)
    qt.calcExpecPauliHamil(q, hamil)
    assert len(calls) == 1


def test_expec_gspmd_optout_on_fake_tpu_takes_per_term(env, hamil,
                                                       monkeypatch):
    from quest_tpu.ops import paulis as P

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    per_term = _spy(monkeypatch, P, "calc_expec_pauli_sum_statevec",
                    result=np.float64(0.0))
    sharded = _spy(monkeypatch, PAR, "expec_pauli_sum_scan_sharded",
                   result=np.float64(0.0))
    q = qt.createQureg(N, env)
    PAR.use_explicit_dist(False)
    try:
        qt.calcExpecPauliHamil(q, hamil)
    finally:
        PAR.use_explicit_dist(True)
    assert len(per_term) == 1 and not sharded


def test_qft_routes_full_vs_runs_vs_layered(env, monkeypatch):
    full = _spy(monkeypatch, PAR, "fused_qft_sharded", result="first_arg")
    runs = _spy(monkeypatch, PAR, "fused_qft_runs_sharded",
                result="first_arg")
    n = 14
    q = qt.createQureg(n, env)
    qt.applyFullQFT(q)
    assert len(full) == 1 and not runs
    q2 = qt.createQureg(n, env)
    qt.applyQFT(q2, list(range(0, 9)))
    assert len(runs) == 1
    r = qt.createDensityQureg(7, env)
    qt.applyFullQFT(r)
    assert len(runs) == 2
    assert runs[-1][1]["runs"] == ((0, 7, False), (7, 7, True))


def test_qft_gspmd_optout_on_fake_tpu_takes_layered(env, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    runs = _spy(monkeypatch, PAR, "fused_qft_runs_sharded",
                result="first_arg")
    q = qt.createQureg(14, env)
    PAR.use_explicit_dist(False)
    try:
        assert api_ops._qft_fused(q, list(range(0, 9))) is False
    finally:
        PAR.use_explicit_dist(True)
    assert not runs


def test_pair_channel_routes_sharded_vs_local(env, monkeypatch):
    sharded = _spy(monkeypatch, PAR, "mix_pair_channel_sharded",
                   result="first_arg")
    nq = 5  # 10 state bits, nloc = 7: bra bit t+5 >= 7 iff t >= 2
    r = qt.createDensityQureg(nq, env)
    qt.mixDepolarising(r, nq - 1, 0.1)     # bra sharded -> explicit
    assert len(sharded) == 1
    qt.mixDepolarising(r, 0, 0.1)          # bra local -> elementwise
    assert len(sharded) == 1


def test_two_qubit_depol_routes(env, monkeypatch):
    sharded = _spy(monkeypatch, PAR, "mix_two_qubit_depol_sharded",
                   result="first_arg")
    nq = 5
    r = qt.createDensityQureg(nq, env)
    qt.mixTwoQubitDepolarising(r, nq - 1, nq - 2, 0.1)
    assert len(sharded) == 1
    qt.mixTwoQubitDepolarising(r, 0, 1, 0.1)   # both bras local
    assert len(sharded) == 1


def test_diag_op_on_rho_routes_explicit(env, monkeypatch):
    sharded = _spy(monkeypatch, PAR, "apply_diag_op_density_sharded",
                   result="first_arg")
    nq = 5
    r = qt.createDensityQureg(nq, env)
    op = qt.createDiagonalOp(nq, env)
    qt.applyDiagonalOp(r, op)
    assert len(sharded) == 1
    PAR.use_explicit_dist(False)
    try:
        qt.applyDiagonalOp(r, op)
    finally:
        PAR.use_explicit_dist(True)
    assert len(sharded) == 1
