"""Donation audit regression tests (ISSUE 3 satellite).

Sweep result: every jitted state-TRANSFORMING entry point in
ops/kernels.py, ops/element.py, ops/density.py and parallel/dist.py
carries ``donate_argnums=0`` so the output reuses the input state's HBM
(the reductions in ops/calculations.py are read-only — donation does not
apply).  The one gap the audit closed is the three-register combine
``set_weighted_qureg`` (ops/kernels.py): it cannot donate blindly
(callers may alias ``out`` with q1/q2 — donating a buffer that is also a
live argument is undefined), so the API layer now routes the common
non-aliased call through ``set_weighted_qureg_donated``.

These tests assert donation is REAL, not just requested: the compiled
program's entry must carry a non-trivial input_output_alias for
parameter 0, and at runtime the donated buffer must actually be consumed
(jax invalidates it — ``is_deleted()``) with, on single-device arrays,
the output landing in the donated input's buffer."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quest_tpu as qt
from quest_tpu.ops import kernels as K
from quest_tpu.parallel import dist as PAR


def _entry_alias(compiled) -> bool:
    """Does the optimized HLO alias an input parameter to the output?"""
    txt = compiled.as_text()
    head = txt.split("\n", 1)[0]
    return "input_output_alias" in head and "(0, {}" in head


class TestAliasInCompiledProgram:
    def test_set_weighted_qureg_donated_aliases(self):
        a = jnp.ones((2, 256))
        facs = jnp.asarray(np.ones((2, 3)))
        c = K.set_weighted_qureg_donated.lower(a, a * 2, a * 3, facs).compile()
        assert _entry_alias(c)

    def test_set_weighted_qureg_plain_does_not_alias(self):
        """The alias-safe variant must NOT donate: callers pass out as an
        input too."""
        a = jnp.ones((2, 256))
        facs = jnp.asarray(np.ones((2, 3)))
        c = K.set_weighted_qureg.lower(a, a * 2, a * 3, facs).compile()
        assert not _entry_alias(c)

    @pytest.mark.parametrize("name", [
        "apply_matrix", "apply_diagonal", "apply_parity_phase",
        "permute_qubits", "collapse_statevec", "apply_full_diagonal",
    ])
    def test_kernel_entry_points_alias(self, name):
        """Spot-check the audited kernel families: donation must survive
        compilation (XLA can silently drop unusable aliases — an
        accidental layout/dtype change would turn donation into a copy
        without failing any numeric test)."""
        n = 10
        a = jnp.ones((2, 1 << n))
        fn = getattr(K, name)
        if name == "apply_matrix":
            m = jnp.asarray(np.stack([np.eye(2), np.zeros((2, 2))]))
            c = fn.lower(a, m, num_qubits=n, targets=(0,)).compile()
        elif name == "apply_diagonal":
            d = jnp.asarray(np.stack([np.ones(2), np.zeros(2)]))
            c = fn.lower(a, d, num_qubits=n, targets=(0,)).compile()
        elif name == "apply_parity_phase":
            c = fn.lower(a, 0.3, num_qubits=n, qubits=(0, 3)).compile()
        elif name == "permute_qubits":
            c = fn.lower(a, num_qubits=n,
                         perm=tuple(reversed(range(n)))).compile()
        elif name == "collapse_statevec":
            c = fn.lower(a, 0.5, num_qubits=n, target=0,
                         outcome=0).compile()
        else:
            c = fn.lower(a, a[0], a[1]).compile()
        assert _entry_alias(c), name

    def test_dist_sharded_gate_aliases(self, env):
        if env.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        n = 12
        a = jax.device_put(jnp.ones((2, 1 << n)), env.amp_sharding())
        m = jnp.asarray(np.stack([np.eye(2), np.zeros((2, 2))]))
        c = PAR._apply_matrix_1q_sharded.lower(
            a, m, mesh=env.mesh, num_qubits=n, target=n - 1, controls=(),
            control_states=(), chunks=4).compile()
        assert _entry_alias(c)


class TestRuntimeBufferReuse:
    def test_donated_input_consumed_and_buffer_reused(self):
        a = jnp.ones((2, 256))
        q1 = a * 2.0
        q2 = a * 3.0
        # real factors (fOut, f1, f2) = (1, 1, 1): out = a + q1 + q2
        facs = jnp.asarray(np.stack([np.ones(3), np.zeros(3)]))
        ptr = a.unsafe_buffer_pointer()
        out = K.set_weighted_qureg_donated(a, q1, q2, facs)
        assert a.is_deleted()
        assert not q1.is_deleted() and not q2.is_deleted()
        assert out.unsafe_buffer_pointer() == ptr  # reused, not copied
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full((2, 256), 6.0))

    def test_plain_variant_leaves_inputs_alive(self):
        a = jnp.ones((2, 256))
        facs = jnp.asarray(np.ones((2, 3)))
        K.set_weighted_qureg(a, a, a, facs)
        assert not a.is_deleted()


class TestApiRouting:
    def _facs(self):
        return 1.0, 2.0, 0.5

    def test_non_aliased_call_donates(self, env):
        n = 5
        q1 = qt.createQureg(n, env)
        q2 = qt.createQureg(n, env)
        out = qt.createQureg(n, env)
        qt.initDebugState(q1)
        qt.initPlusState(q2)
        f1, f2, fo = self._facs()
        before = np.asarray(q1.amps) * f1 + np.asarray(q2.amps) * f2 \
            + np.asarray(out.amps) * fo
        buf = out.amps          # materialize, then watch it get consumed
        qt.setWeightedQureg(f1, q1, f2, q2, fo, out)
        assert buf.is_deleted()
        np.testing.assert_allclose(np.asarray(out.amps), before, atol=1e-13)

    def test_aliased_call_stays_correct(self, env):
        n = 5
        q2 = qt.createQureg(n, env)
        out = qt.createQureg(n, env)
        qt.initDebugState(out)
        qt.initPlusState(q2)
        f1, f2, fo = self._facs()
        expect = np.asarray(out.amps) * (f1 + fo) + np.asarray(q2.amps) * f2
        qt.setWeightedQureg(f1, out, f2, q2, fo, out)  # out aliases q1
        np.testing.assert_allclose(np.asarray(out.amps), expect, atol=1e-13)
