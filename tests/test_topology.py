"""Hierarchical DCN x ICI topology layer (parallel/topology.py, ISSUE 12).

Covers the acceptance contract:
  * the pure topology model: spec parsing, resolution against the live
    device count (with the load-bearing silent fallback), tier
    classification of mesh bits / XOR masks / collective pairs, host
    arithmetic, degraded-mesh shrinking, and the planner/weight knobs;
  * HLO-pinned collective PLACEMENT on the emulated 2x4 arrangement:
    exact per-tier collective-permute counts via ``introspect.audit``'s
    ``tier_counts`` under a ``CollectiveBudget`` — single-mesh-bit
    exchanges on chip bits ride ICI only, host-bit exchanges ride DCN;
  * flat-vs-hierarchical planner bit-identity: ``QT_TOPOLOGY_PLANNER``
    changes WHERE bytes move, never what is computed;
  * predicted-vs-measured per-tier reconciliation: a clean drain on the
    emulated 2x4 topology ends with ``model_drift_total == 0`` and
    tier-exact predicted byte series;
  * the operator surface: ``getEnvironmentString``'s ``Topology=`` line
    and ``reportPerf``'s per-tier byte section.
"""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import circuit as CIRC
from quest_tpu import env as E
from quest_tpu import introspect
from quest_tpu import telemetry as T
from quest_tpu.introspect import CollectiveBudget
from quest_tpu.parallel import dist
from quest_tpu.parallel import topology as TOPO

H_SOA = np.stack([(1 / np.sqrt(2)) * np.array([[1.0, 1], [1, -1]]),
                  np.zeros((2, 2))])


def _u4(seed=3):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    u, _ = np.linalg.qr(g)
    return u


# ---------------------------------------------------------------------------
# The pure model (no jax, no mesh)
# ---------------------------------------------------------------------------


class TestModel:
    def test_parse_spec(self):
        assert TOPO.parse_spec("2x4") == (2, 4)
        assert TOPO.parse_spec(" 4X2 ") == (4, 2)
        assert TOPO.parse_spec("2×4") == (2, 4)  # unicode ×
        for bad in (None, "", "8", "2x4x2", "ax4", "0x8", "-2x4"):
            assert TOPO.parse_spec(bad) is None

    def test_resolve_exact_factoring(self, monkeypatch):
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        t = TOPO.resolve(8)
        assert (t.hosts, t.chips) == (2, 4)
        assert t.ici_bits == 2 and t.dcn_bits == 1
        assert t.num_devices == 8
        assert t.describe() == "2x4 (ici=2, dcn=1)"

    def test_resolve_fallback_single_host(self, monkeypatch):
        """A spec that does not factor the live mesh is silently ignored
        — the survivors of a failover keep classifying consistently
        while the env var still says the old shape."""
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        t = TOPO.resolve(4)  # 2*4 != 4
        assert (t.hosts, t.chips) == (1, 4)
        assert t.dcn_bits == 0
        # and non-pow2 specs fall back too
        assert TOPO.resolve(8, "3x3") == TOPO.Topology(1, 8)

    def test_resolve_default_is_flat(self, monkeypatch):
        monkeypatch.delenv(TOPO.TOPOLOGY_ENV, raising=False)
        t = TOPO.resolve(8)
        assert (t.hosts, t.chips) == (1, 8)
        assert all(t.tier_of_bit(b) == "ici" for b in range(3))

    def test_tier_classification(self):
        t = TOPO.Topology(2, 4)
        assert [t.tier_of_bit(b) for b in range(3)] == ["ici", "ici", "dcn"]
        assert t.tier_of_mask(0b011) == "ici"
        assert t.tier_of_mask(0b100) == "dcn"
        assert t.tier_of_mask(0b101) == "dcn"  # any host bit -> DCN
        assert t.tier_of_pair(0, 3) == "ici"   # same host
        assert t.tier_of_pair(0, 4) == "dcn"   # host 0 <-> host 1
        assert t.tier_of_pair(5, 1) == "dcn"

    def test_host_arithmetic(self):
        t = TOPO.Topology(2, 4)
        assert [t.host_of(s) for s in range(8)] == [0] * 4 + [1] * 4
        assert list(t.host_range(1)) == [4, 5, 6, 7]

    def test_invalid_topology_rejected(self):
        with pytest.raises(ValueError):
            TOPO.Topology(3, 4)

    def test_shrink(self):
        t = TOPO.Topology(2, 4)
        s = TOPO.shrink(t, 4)      # host loss: 2x4 -> 1x4
        assert (s.hosts, s.chips) == (1, 4)
        s = TOPO.shrink(t, 2)      # sub-host shrink: collapse
        assert (s.hosts, s.chips) == (1, 2)
        assert TOPO.shrink(None, 8).hosts == 1
        s = TOPO.shrink(TOPO.Topology(4, 2), 4)
        assert (s.hosts, s.chips) == (2, 2)

    def test_split_pair_list(self):
        pairs = [(0, 1), (1, 0), (0, 4), (2, 2), (6, 7)]
        assert TOPO.split_pair_list(pairs, 4) == {"ici": 3, "dcn": 1}
        # chips=8 (flat): nothing crosses a host
        assert TOPO.split_pair_list(pairs, 8) == {"ici": 4, "dcn": 0}

    def test_planner_mode_and_weights(self, monkeypatch):
        monkeypatch.delenv(TOPO.PLANNER_ENV, raising=False)
        assert TOPO.planner_mode() == "hier"
        monkeypatch.setenv(TOPO.PLANNER_ENV, "flat")
        assert TOPO.planner_mode() == "flat"
        monkeypatch.setenv(TOPO.PLANNER_ENV, "anything-else")
        assert TOPO.planner_mode() == "hier"

        monkeypatch.delenv(TOPO.WEIGHT_DCN_ENV, raising=False)
        assert TOPO.tier_weights() == TOPO.DEFAULT_TIER_WEIGHTS
        monkeypatch.setenv(TOPO.WEIGHT_DCN_ENV, "16")
        assert TOPO.tier_weights()["dcn"] == 16.0
        monkeypatch.setenv(TOPO.WEIGHT_DCN_ENV, "junk")
        assert TOPO.tier_weights()["dcn"] == \
            TOPO.DEFAULT_TIER_WEIGHTS["dcn"]

    def test_signature_tracks_knobs(self, monkeypatch):
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        a = TOPO.signature(8)
        monkeypatch.setenv(TOPO.PLANNER_ENV, "flat")
        b = TOPO.signature(8)
        monkeypatch.delenv(TOPO.PLANNER_ENV, raising=False)
        monkeypatch.setenv(TOPO.WEIGHT_DCN_ENV, "32")
        c = TOPO.signature(8)
        assert len({a, b, c}) == 3  # each knob splits the plan cache

    def test_hierarchical_enabled(self, monkeypatch):
        monkeypatch.delenv(TOPO.PLANNER_ENV, raising=False)
        assert TOPO.hierarchical_enabled(TOPO.Topology(2, 4))
        assert not TOPO.hierarchical_enabled(TOPO.Topology(1, 8))
        assert not TOPO.hierarchical_enabled(None)
        monkeypatch.setenv(TOPO.PLANNER_ENV, "flat")
        assert not TOPO.hierarchical_enabled(TOPO.Topology(2, 4))


# ---------------------------------------------------------------------------
# Tier-aware cost model consistency
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_remap_tiers_sum_to_flat_model(self):
        """The per-tier split of any remap is EXACT: tier bytes sum to
        remap_exchange_bytes, tier counts to remap_exchange_count."""
        n, r = 6, 3
        nloc = n - r
        t24 = TOPO.Topology(2, 4)
        perms = [
            (n - 1,) + tuple(range(1, n - 1)) + (0,),   # mixed 0<->5
            (3, 1, 2, 0, 4, 5),                          # mixed 0<->3
            (0, 1, 2, 4, 3, 5),                          # mesh tau 3<->4
            (5, 4, 2, 3, 1, 0),                          # mixed + tau
        ]
        for perm in perms:
            sigma = dist.canonical_sigma(perm)
            tiers = dist.remap_exchange_tiers(sigma, nloc, r, 16, t24)
            assert sum(b for _c, b in tiers.values()) == \
                CIRC.remap_exchange_bytes(sigma, n, nloc, 16)
            assert sum(c for c, _b in tiers.values()) == \
                dist.remap_exchange_count(sigma, nloc, r)

    def test_remap_tier_placement(self):
        n, r = 6, 3
        nloc = n - r
        t24 = TOPO.Topology(2, 4)
        # local bit 0 <-> mesh bit 0 (qubit 3): intra-host half-shard
        sigma = dist.canonical_sigma((3, 1, 2, 0, 4, 5))
        tiers = dist.remap_exchange_tiers(sigma, nloc, r, 16, t24)
        assert tiers.get("dcn", (0, 0)) == (0, 0)
        assert tiers["ici"][0] == 1
        # local bit 0 <-> mesh bit 2 (qubit 5): crosses the host boundary
        sigma = dist.canonical_sigma(
            (n - 1,) + tuple(range(1, n - 1)) + (0,))
        tiers = dist.remap_exchange_tiers(sigma, nloc, r, 16, t24)
        assert tiers.get("ici", (0, 0)) == (0, 0)
        assert tiers["dcn"][0] == 1

    def test_circuit_tier_bytes_wrapper(self):
        n, nloc = 6, 3
        sigma = dist.canonical_sigma((3, 1, 2, 0, 4, 5))
        out = CIRC.remap_exchange_bytes_tiers(sigma, n, nloc, 16,
                                              TOPO.Topology(2, 4))
        assert set(out) <= {"ici", "dcn"}
        assert sum(out.values()) == \
            CIRC.remap_exchange_bytes(sigma, n, nloc, 16)

    def test_planner_parks_evictees_on_dcn(self, monkeypatch):
        """The tier-aware planner's observable choice: when a window
        needs qubits resident on both tiers, the DCN slot receives the
        COLDEST evictee (flat planning follows request order instead)."""
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        n, nloc = 6, 3
        perm = tuple(range(n))  # qubits 3,4,5 on mesh bits 0,1,2
        # next window wants 3 (ici bit 0) and 5 (dcn bit 2) local; of
        # the current locals, 0 is hottest and 2 coldest
        next_use = {3: 1, 5: 2, 0: 3, 1: 4, 2: 5}
        monkeypatch.setenv(TOPO.PLANNER_ENV, "hier")
        sig_h, perm_h = dist.plan_window_remap(n, nloc, perm, (3, 5),
                                               next_use)
        monkeypatch.setenv(TOPO.PLANNER_ENV, "flat")
        sig_f, perm_f = dist.plan_window_remap(n, nloc, perm, (3, 5),
                                               next_use)
        assert sig_h is not None and sig_f is not None

        def parked_on_dcn(new_perm):
            # which qubit ends on mesh bit 2 (global position 5)
            return list(new_perm).index(5)

        assert parked_on_dcn(perm_h) == 2   # coldest local -> DCN slot
        assert parked_on_dcn(perm_f) == 1   # flat request order parks 1
        # same work either way: identical hop count and byte volume
        assert dist.remap_exchange_count(sig_h, nloc, 3) == \
            dist.remap_exchange_count(sig_f, nloc, 3)
        assert CIRC.remap_exchange_bytes(sig_h, n, nloc, 16) == \
            CIRC.remap_exchange_bytes(sig_f, n, nloc, 16)


# ---------------------------------------------------------------------------
# HLO placement pins on the emulated 2x4 arrangement
# ---------------------------------------------------------------------------


@pytest.fixture
def mesh8(env):
    if env.num_devices < 8:
        pytest.skip("needs the 8-device virtual mesh")
    dist.use_explicit_dist(True)
    dist.use_lazy_remap(True)
    return env


class TestHloPlacement:
    """Exact per-tier collective counts in compiled programs, reading
    the 8 shards as 2 hosts x 4 chips.  The classification is pure
    arithmetic over each instruction's ``source_target_pairs`` — no env
    var needed at compile time."""

    CHIPS = 4

    def test_chip_bit_exchange_rides_ici(self, mesh8):
        n = 6
        amps = qt.createQureg(n, mesh8).amps
        with CollectiveBudget(exact={"collective-permute": 1}):
            report = introspect.audit(
                lambda a: dist.apply_matrix_1q_sharded(
                    a, H_SOA.reshape(2, 2, 2), mesh=mesh8.mesh,
                    num_qubits=n, target=3, chunks=1),  # mesh bit 0
                amps, donate=True)
        assert report.tier_counts(self.CHIPS) == {"ici": 1, "dcn": 0}

    def test_host_bit_exchange_rides_dcn(self, mesh8):
        n = 6
        amps = qt.createQureg(n, mesh8).amps
        with CollectiveBudget(exact={"collective-permute": 1}):
            report = introspect.audit(
                lambda a: dist.apply_matrix_1q_sharded(
                    a, H_SOA.reshape(2, 2, 2), mesh=mesh8.mesh,
                    num_qubits=n, target=n - 1, chunks=1),  # mesh bit 2
                amps, donate=True)
        assert report.tier_counts(self.CHIPS) == {"ici": 0, "dcn": 1}

    def test_mesh_tau_within_hosts_rides_ici(self, mesh8):
        """A shard-index permutation moving only chip bits (mesh 0<->1)
        never leaves the host."""
        n = 6
        amps = qt.createQureg(n, mesh8).amps
        sigma = dist.canonical_sigma((0, 1, 2, 4, 3, 5))
        with CollectiveBudget(exact={"collective-permute": 1}):
            report = introspect.audit(
                lambda a: dist.remap_sharded(
                    a, mesh=mesh8.mesh, num_qubits=n, sigma=sigma,
                    chunks=(1, 1)),
                amps, donate=True)
        assert report.tier_counts(self.CHIPS) == {"ici": 1, "dcn": 0}

    def test_mixed_remap_to_host_bit_rides_dcn(self, mesh8):
        n = 6
        amps = qt.createQureg(n, mesh8).amps
        sigma = dist.canonical_sigma(
            (n - 1,) + tuple(range(1, n - 1)) + (0,))
        with CollectiveBudget(exact={"collective-permute": 1}):
            report = introspect.audit(
                lambda a: dist.remap_sharded(
                    a, mesh=mesh8.mesh, num_qubits=n, sigma=sigma,
                    chunks=(1, 1)),
                amps, donate=True)
        assert report.tier_counts(self.CHIPS) == {"ici": 0, "dcn": 1}

    def test_flat_reading_sees_no_dcn(self, mesh8):
        """The same compiled program read as 1x8 (chips=8) classifies
        everything ICI — the tier split is a VIEW of the routing table,
        not a recompilation."""
        n = 6
        amps = qt.createQureg(n, mesh8).amps
        report = introspect.audit(
            lambda a: dist.apply_matrix_1q_sharded(
                a, H_SOA.reshape(2, 2, 2), mesh=mesh8.mesh,
                num_qubits=n, target=n - 1, chunks=1),
            amps, donate=True)
        counts = report.tier_counts(8)
        assert counts["dcn"] == 0 and counts["ici"] >= 1


# ---------------------------------------------------------------------------
# Planner A/B bit-identity + per-tier reconciliation
# ---------------------------------------------------------------------------


def _churn_drain(env, n=6, seed=3):
    """A fused circuit whose windows force remaps across both tiers."""
    u = _u4(seed)
    q = qt.createQureg(n, env)
    with qt.gateFusion(q):
        for a, b in [(0, 1), (n - 2, n - 1), (0, n - 1), (1, 2)]:
            qt.multiQubitUnitary(q, [a, b], u)
    return np.asarray(q.amps)


class TestPlannerEquivalence:
    def test_flat_vs_hier_bit_identical(self, mesh8, monkeypatch):
        """Acceptance: topology only changes WHERE bytes move.  The same
        circuit drained under the flat and the hierarchical planner
        yields bitwise-identical amplitudes."""
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        monkeypatch.setenv(TOPO.PLANNER_ENV, "flat")
        flat = _churn_drain(mesh8)
        monkeypatch.setenv(TOPO.PLANNER_ENV, "hier")
        hier = _churn_drain(mesh8)
        assert np.array_equal(flat, hier)
        # and both agree with the untopologized baseline
        monkeypatch.delenv(TOPO.TOPOLOGY_ENV)
        assert np.array_equal(flat, _churn_drain(mesh8))

    def test_clean_drain_reconciles_per_tier(self, mesh8, monkeypatch):
        """Acceptance: a clean 2x4 drain ends with zero model drift and
        the predicted per-tier byte series matching the measured ones
        exactly."""
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        prev = T.mode_name()
        T.configure("on")
        try:
            T.reset()
            _churn_drain(mesh8)
            assert T.counter_total("model_drift_total") == 0
            for tier in TOPO.TIERS:
                assert T.counter_sum(
                    "predicted_exchange_bytes_total",
                    op="window_remap", tier=tier) == \
                    T.counter_sum("exchange_bytes_total",
                                  op="window_remap", tier=tier)
            # something actually crossed the emulated host boundary
            assert T.counter_sum("exchange_bytes_total", tier="dcn") > 0
        finally:
            T.reset()
            T.configure(prev)

    def test_explain_reports_tier_totals(self, mesh8, monkeypatch):
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        n = 6
        u = _u4()
        q = qt.createQureg(n, mesh8)
        qt.startGateFusion(q)
        for a, b in [(0, 1), (n - 2, n - 1)]:
            qt.multiQubitUnitary(q, [a, b], u)
        report = qt.explainCircuit(q)
        t = report["totals"]
        assert t["topology"] == "2x4 (ici=2, dcn=1)"
        assert sum(t["tier_bytes"].values()) == t["exchange_bytes"]
        w = TOPO.tier_weights()
        assert t["weighted_exchange_cost"] == pytest.approx(
            sum(w[k] * v for k, v in t["tier_bytes"].items()))
        assert "tier bytes:" in report.table()


# ---------------------------------------------------------------------------
# Operator surface
# ---------------------------------------------------------------------------


class TestOperatorSurface:
    def test_environment_string_topology_line(self, env, monkeypatch):
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        e = qt.createQuESTEnv()
        if e.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        assert e.topology is not None
        assert "Topology=2x4 (ici=2, dcn=1)" in qt.getEnvironmentString(e)

    def test_report_perf_tier_section(self, env, capsys):
        prev = T.mode_name()
        T.configure("on")
        try:
            T.reset()
            T.record_exchange("unit", 1, 512, chunks=1, tier="ici")
            T.record_exchange("unit", 1, 256, chunks=1, tier="dcn")
            qt.reportPerf(env)
            out = capsys.readouterr().out
            assert "exchange tiers" in out
            assert "ici" in out and "dcn" in out
        finally:
            T.reset()
            T.configure(prev)

    def test_shrunk_env_keeps_chips(self, monkeypatch):
        monkeypatch.setenv(TOPO.TOPOLOGY_ENV, "2x4")
        e = qt.createQuESTEnv()
        if e.num_devices < 8:
            pytest.skip("needs the 8-device virtual mesh")
        small = E.shrink_env(e, 4, exclude_indices=list(range(4, 8)))
        assert (small.topology.hosts, small.topology.chips) == (1, 4)
        assert small.num_devices == 4
