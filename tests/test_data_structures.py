"""Data-structure tests (analogue of reference test_data_structures.cpp, 23
TEST_CASEs): registers, matrices, PauliHamil (incl. file IO), DiagonalOp,
QASM logging."""

import os
import tempfile

import numpy as np
import pytest

import quest_tpu as qt
import oracle

N = 5
DIM = 1 << N


def test_create_qureg_metadata(env):
    q = qt.createQureg(N, env)
    assert qt.getNumQubits(q) == N
    assert qt.getNumAmps(q) == DIM
    assert q.num_chunks == env.num_devices
    assert q.num_amps_per_chunk * q.num_chunks == DIM
    r = qt.createDensityQureg(N, env)
    assert r.num_amps_total == DIM * DIM
    assert r.num_qubits_in_state_vec == 2 * N
    with pytest.raises(qt.QuESTError):
        qt.createQureg(0, env)
    with pytest.raises(qt.QuESTError):
        qt.createQureg(-3, env)


def test_complex_matrix_n(env):
    m = qt.createComplexMatrixN(3)
    assert m.shape == (8, 8)
    reals = np.arange(64).reshape(8, 8)
    imags = -np.arange(64).reshape(8, 8)
    qt.initComplexMatrixN(m, reals, imags)
    assert m[1, 2] == 10 - 10j
    m2 = qt.getStaticComplexMatrixN([[1, 0], [0, 1]], [[0, 0], [0, 0]])
    np.testing.assert_array_equal(m2, np.eye(2))


def test_pauli_hamil_create_init(env):
    h = qt.createPauliHamil(N, 3)
    assert h.num_qubits == N and h.num_sum_terms == 3
    assert np.all(h.pauli_codes == 0)  # identity-initialised (QuEST.c:1394)
    coeffs = [0.5, -1.0, 2.0]
    codes = np.array([[1, 0, 0, 0, 0], [0, 2, 0, 3, 0], [3, 3, 3, 3, 3]])
    qt.initPauliHamil(h, coeffs, codes)
    np.testing.assert_array_equal(h.pauli_codes, codes)
    with pytest.raises(qt.QuESTError):
        qt.createPauliHamil(0, 1)
    with pytest.raises(qt.QuESTError):
        qt.initPauliHamil(h, coeffs, np.full((3, N), 7))


def test_pauli_hamil_from_file(env):
    content = "0.5 1 0 2\n-1.5 3 3 0\n2.0 0 0 0\n"
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write(content)
        path = f.name
    try:
        h = qt.createPauliHamilFromFile(path)
        assert h.num_qubits == 3
        assert h.num_sum_terms == 3
        np.testing.assert_allclose(h.term_coeffs, [0.5, -1.5, 2.0])
        np.testing.assert_array_equal(
            h.pauli_codes, [[1, 0, 2], [3, 3, 0], [0, 0, 0]]
        )
    finally:
        os.unlink(path)
    with pytest.raises(qt.QuESTError, match="file"):
        qt.createPauliHamilFromFile("/nonexistent/file.txt")


def test_diagonal_op(env):
    op = qt.createDiagonalOp(N, env)
    assert op.num_qubits == N
    vals_re = np.arange(DIM, dtype=float)
    vals_im = -np.arange(DIM, dtype=float)
    qt.initDiagonalOp(op, vals_re, vals_im)
    qt.syncDiagonalOp(op)  # no-op, must not raise
    np.testing.assert_allclose(np.asarray(op.real), vals_re)
    qt.setDiagonalOpElems(op, 4, [100.0, 200.0], [0.0, 0.0], 2)
    assert float(np.asarray(op.real)[4]) == 100.0
    assert float(np.asarray(op.real)[6]) == 6.0
    with pytest.raises(qt.QuESTError):
        qt.setDiagonalOpElems(op, DIM - 1, [1.0, 2.0], [0.0, 0.0], 2)


def test_diagonal_op_from_pauli_hamil(env):
    h = qt.createPauliHamil(3, 2)
    qt.initPauliHamil(h, [1.0, 0.5], np.array([[3, 0, 0], [0, 3, 3]]))
    op = qt.createDiagonalOp(3, env)
    qt.initDiagonalOpFromPauliHamil(op, h)
    # d_i = 1.0*(-1)^{b0} + 0.5*(-1)^{b1+b2}
    idx = np.arange(8)
    expect = 1.0 * (1 - 2.0 * (idx & 1)) + 0.5 * (
        (1 - 2.0 * ((idx >> 1) & 1)) * (1 - 2.0 * ((idx >> 2) & 1))
    )
    np.testing.assert_allclose(np.asarray(op.real), expect)
    # X/Y codes are rejected
    h2 = qt.createPauliHamil(3, 1)
    qt.initPauliHamil(h2, [1.0], np.array([[1, 0, 0]]))
    with pytest.raises(qt.QuESTError, match="PAULI_Z"):
        qt.initDiagonalOpFromPauliHamil(op, h2)


def test_diagonal_op_from_file(env):
    content = "1.0 3 0\n0.5 0 3\n"
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write(content)
        path = f.name
    try:
        op = qt.createDiagonalOpFromPauliHamilFile(path, env)
        idx = np.arange(4)
        expect = 1.0 * (1 - 2.0 * (idx & 1)) + 0.5 * (1 - 2.0 * ((idx >> 1) & 1))
        np.testing.assert_allclose(np.asarray(op.real), expect)
    finally:
        os.unlink(path)


def test_qasm_recording(env):
    q = qt.createQureg(3, env)
    qt.startRecordingQASM(q)
    qt.hadamard(q, 0)
    qt.controlledNot(q, 0, 1)
    qt.rotateX(q, 2, 0.5)
    qt.tGate(q, 0)
    qt.measure(q, 1)
    qt.stopRecordingQASM(q)
    qt.pauliX(q, 0)  # after stop: not recorded
    text = str(q.qasm_log)
    assert "OPENQASM 2.0;" in text
    assert "h q[0];" in text
    assert "cx q[0],q[1];" in text
    assert "Rx(0.5) q[2];" in text
    assert "t q[0];" in text
    assert "measure q[1] -> c[1];" in text
    assert text.count("x q[0];") == 0
    with tempfile.NamedTemporaryFile("r", suffix=".qasm", delete=False) as f:
        path = f.name
    try:
        qt.writeRecordedQASMToFile(q, path)
        assert open(path).read() == text
    finally:
        os.unlink(path)
    qt.clearRecordedQASM(q)
    assert "h q[0];" not in str(q.qasm_log)


def test_qasm_control_state_sandwich(env):
    q = qt.createQureg(3, env)
    qt.startRecordingQASM(q)
    u = np.eye(2)
    qt.multiStateControlledUnitary(q, [0, 1], [0, 1], 2, u)
    text = str(q.qasm_log)
    # control-on-zero wrapped in an X sandwich (QuEST_qasm.c:363-380)
    assert text.count("x q[0];") == 2


def test_environment_reporting(env, capsys):
    qt.reportQuESTEnv(env)
    out = capsys.readouterr().out
    assert "quest_tpu" in out
    s = qt.getEnvironmentString(env)
    assert "Devices" in s
    qt.syncQuESTEnv(env)
    assert qt.syncQuESTSuccess(1) == 1
    q = qt.createQureg(2, env)
    qt.reportQuregParams(q)
    out = capsys.readouterr().out
    assert "4" in out


def test_qasm_phase_func_symbolic_records(env):
    """Phase functions are recorded as the reference's multi-line symbolic
    comment blocks (qasm_recordPhaseFunc / ...NamedPhaseFunc,
    QuEST_qasm.c:490-891): the scalar form, the sub-register symbol lines,
    override kets, and shift deltas."""
    q = qt.createQureg(5, env)
    qt.startRecordingQASM(q)
    qt.applyPhaseFuncOverrides(q, [0, 3, 2], 0, [0.5, -1.3], [2.0, 4.0],
                               [0, 1], [0.45, -0.5])
    qt.applyNamedPhaseFunc(q, [0, 1, 2, 3], [2, 2], 0, 0)      # NORM
    qt.applyParamNamedPhaseFunc(q, [0, 1, 2, 3], [2, 2], 0, 4,
                                [-1.0, 0.0, 0.5, -0.2])
    qt.applyMultiVarPhaseFunc(q, [0, 1, 2, 3], [2, 2], 0,
                              [0.5, -1.0], [2.0, 1.0], [1, 1])
    txt = str(q.qasm_log)
    for frag in (
        "applyPhaseFunc() multiplied a complex scalar of the form",
        "exp(i (0.5 x^2 - 1.3 x^4))",
        "{0, 3, 2}",
        "though with overrides",
        "|0> -> exp(i 0.45)",
        "|1> -> exp(i (-0.5))",
        "exp(i sqrt(x^2 + y^2))",
        "|x> = {0, 1}",
        "|y> = {2, 3}",
        "with the additional parameters",
        "delta0 = 0.5",
        "delta1 = -0.2",
        "applyMultiVarPhaseFunc() multiplied a complex scalar of the form",
    ):
        assert frag in txt, frag
