"""Quad precision (QuEST_PREC=4, QuEST_precision.h:55-68): the recorded
scope decision (f64 storage — mirroring the reference's own GPU-quad
prohibition, QuEST/CMakeLists.txt:69-73 — with double-double-compensated
reductions where extended precision is observable) plus the REAL_EPS /
message-cap table extension."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import precision
from quest_tpu.ops import calculations as C

import jax.numpy as jnp


@pytest.fixture
def quad():
    qt.set_precision(4)
    yield
    qt.set_precision(2)


def test_precision_table_extended(quad):
    assert precision.get_precision() == 4
    assert precision.real_eps() == 1e-14
    assert precision.max_amps_in_msg() == 1 << 27
    assert precision.real_dtype() == jnp.float64


def test_invalid_precision_rejected():
    with pytest.raises(ValueError, match="quest_prec"):
        qt.set_precision(3)


def test_quad_sum_survives_cross_block_cancellation():
    """Per-block-exact partials of wildly varying signed magnitude: the
    plain pairwise tree loses the small term to rounding at 1e16; the
    Neumaier double-double combine keeps it."""
    B = C._QUAD_BLOCK
    v = np.zeros(4 * B)
    v[0] = 1e16
    v[B] = 1.0
    v[2 * B] = -1e16
    v[3 * B] = 1e-3
    got = float(C.quad_sum(jnp.asarray(v)))
    assert got == pytest.approx(1.0 + 1e-3, abs=1e-12)


def test_quad_total_prob_and_inner_product(env, quad):
    rng = np.random.default_rng(3)
    n = 6
    q = qt.createQureg(n, env)
    vec = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec /= np.linalg.norm(vec)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    assert abs(qt.calcTotalProb(q) - 1.0) < 1e-14
    q2 = qt.createQureg(n, env)
    vec2 = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec2 /= np.linalg.norm(vec2)
    qt.initStateFromAmps(q2, vec2.real, vec2.imag)
    ip = qt.calcInnerProduct(q, q2)
    assert abs(ip - np.vdot(vec, vec2)) < 1e-13


_A = 2.0 ** 53  # block magnitude: ulp(256*_A) = 512, so unit terms
                # vanish from a plain f64 accumulator mid-cancellation


def _cancel_vec():
    """(2, 1024) SoA pattern [+A x256][0][+1.0 x256][-A x256]: any
    deterministic plain-f64 reduce loses the unit block while the
    accumulator sits at 256*A (verified: the plain kernels return 0.0);
    the 256-aligned quad block partials + Neumaier combine keep it."""
    v = np.zeros((2, 1024))
    v[0, 0:256] = _A
    v[0, 512:768] = 1.0
    v[0, 768:1024] = _A
    return v


def _assert_plain_loses(plain, true_val):
    """The constructions are built so today's XLA reduce demonstrably
    loses them at f64; if a future backend starts compensating sums the
    demonstration (not the quad feature) becomes moot — skip with a
    note rather than failing CI on a backend-numerics improvement."""
    if abs(plain - true_val) <= 100.0:
        pytest.skip("XLA's plain f64 reduce now survives this "
                    "construction; the quad path remains verified above")


def test_quad_expec_pauli_sum_cross_block_cancellation(env, quad):
    """Z on qubit 8 signs the [768,1024) block negative: true value 256,
    plain f64 scan returns 0 (VERDICT r4 item 5: the expectation scans
    accumulate double-double at prec 4)."""
    from quest_tpu.ops import paulis as P

    n = 10
    amps = jnp.asarray(_cancel_vec())
    codes = np.zeros((1, n), np.int32)
    codes[0, 8] = 3
    plain = float(P.expec_pauli_sum_scan(
        amps, jnp.asarray(codes), jnp.asarray(np.ones(1)), num_qubits=n))
    quad_v = float(P.expec_pauli_sum_scan(
        amps, jnp.asarray(codes), jnp.asarray(np.ones(1)), num_qubits=n,
        quad=True))
    assert quad_v == pytest.approx(256.0, abs=1e-9)
    _assert_plain_loses(plain, 256.0)


def test_quad_expec_pauli_api_routes_quad(env, quad):
    """The public calcExpecPauliSum at prec 4 survives the construction
    the plain path loses."""
    n = 10
    q = qt.createQureg(n, env)
    v = _cancel_vec()
    qt.setAmps(q, 0, v[0], v[1], 1 << n)
    got = qt.calcExpecPauliSum(
        q, [0] * 8 + [3] + [0] * (n - 9), [1.0])
    assert got == pytest.approx(256.0, abs=1e-9)


def test_quad_fidelity_cross_block_cancellation(env, quad):
    """<psi|rho|psi> with rho columns [+A|0|+1|-A] and psi = 1...1: true
    256; the plain matmul+reduce kernel returns 0."""
    n = 5
    dim = 1 << n
    w = np.zeros((dim, dim))
    w[0:8, :] = _A
    w[16:24, :] = 1.0
    w[24:32, :] = -_A
    rho = qt.createDensityQureg(n, env)
    qt.setDensityAmps(rho, w.reshape(-1), np.zeros(dim * dim))
    psi = qt.createQureg(n, env)
    qt.setAmps(psi, 0, np.ones(dim), np.zeros(dim), dim)
    from quest_tpu.ops import calculations as CC

    plain = float(CC.calc_fidelity_density(rho.amps, psi.amps,
                                           num_qubits=n))
    assert qt.calcFidelity(rho, psi) == pytest.approx(256.0, abs=1e-9)
    _assert_plain_loses(plain, 256.0)


def test_quad_density_inner_product_cancellation(env, quad):
    n = 5
    dim2 = 1 << (2 * n)
    r1 = np.zeros(dim2)
    r2 = np.zeros(dim2)
    r1[0:256] = 1.0
    r2[0:256] = _A
    r1[512:768] = 1.0
    r2[512:768] = 1.0
    r1[768:1024] = -1.0
    r2[768:1024] = _A
    a = qt.createDensityQureg(n, env)
    b = qt.createDensityQureg(n, env)
    qt.setDensityAmps(a, r1, np.zeros(dim2))
    qt.setDensityAmps(b, r2, np.zeros(dim2))
    from quest_tpu.ops import calculations as CC

    plain = float(CC.calc_density_inner_product(a.amps, b.amps))
    assert qt.calcDensityInnerProduct(a, b) == pytest.approx(256.0,
                                                            abs=1e-9)
    _assert_plain_loses(plain, 256.0)


def test_quad_expec_diagonal_cancellation(env, quad):
    """calcExpecDiagonalOp at prec 4: d = (-1)^{bit 8} against the
    cancellation state (plain returns 0, true 256)."""
    n = 10
    q = qt.createQureg(n, env)
    v = _cancel_vec()
    qt.setAmps(q, 0, np.sqrt(np.abs(v[0])) * np.sign(v[0]),
               np.zeros(1 << n), 1 << n)
    # |amp|^2 reproduces the magnitude pattern; signs live in d
    d = qt.createDiagonalOp(n, env)
    d_re = 1.0 - 2.0 * (((np.arange(1 << n) >> 8) & 1).astype(float))
    qt.initDiagonalOp(d, d_re, np.zeros(1 << n))
    got = qt.calcExpecDiagonalOp(q, d)
    assert got.real == pytest.approx(256.0, abs=1e-9)


def test_quad_nonneg_reductions_route_and_agree(env, quad):
    """Purity / prob-of-outcome / Hilbert-Schmidt are non-negative sums
    (condition number 1 — no cancellation to construct), so the quad
    variants are checked for routing + agreement with the dense oracle."""
    rng = np.random.default_rng(11)
    n = 5
    dim = 1 << n
    m = rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
    rho_m = m @ m.conj().T
    rho_m /= np.trace(rho_m).real
    a = qt.createDensityQureg(n, env)
    qt.setDensityAmps(a, rho_m.T.reshape(-1).real,
                      rho_m.T.reshape(-1).imag)
    assert qt.calcPurity(a) == pytest.approx(
        float(np.sum(np.abs(rho_m) ** 2)), rel=1e-12)
    b = qt.createDensityQureg(n, env)
    m2 = rng.standard_normal((dim, dim)) + 1j * rng.standard_normal((dim, dim))
    rho2 = m2 @ m2.conj().T
    rho2 /= np.trace(rho2).real
    qt.setDensityAmps(b, rho2.T.reshape(-1).real,
                      rho2.T.reshape(-1).imag)
    assert qt.calcHilbertSchmidtDistance(a, b) == pytest.approx(
        float(np.sqrt(np.sum(np.abs(rho_m - rho2) ** 2))), rel=1e-12)
    vec = rng.standard_normal(dim) + 1j * rng.standard_normal(dim)
    vec /= np.linalg.norm(vec)
    q = qt.createQureg(n, env)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    p0 = float(np.sum(np.abs(vec[::2]) ** 2))  # qubit 0 = 0
    assert qt.calcProbOfOutcome(q, 0, 0) == pytest.approx(p0, rel=1e-12)


def test_quad_expec_scan_sharded_parity(env, quad):
    """The sharded expec scan at prec 4 (per-shard double-double
    partials, then ONE all-gather of the (T,) per-shard term values and
    a deterministic Neumaier combine — a plain psum would re-lose
    cross-shard cancellation at f64) matches the oracle on a
    mesh-spanning register — the one-kernel-set contract holds at
    quad too."""
    n = 10
    rng = np.random.default_rng(5)
    vec = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec /= np.linalg.norm(vec)
    q = qt.createQureg(n, env)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    h = qt.createPauliHamil(n, 3)
    codes = rng.integers(0, 4, size=(3, n))
    coeffs = rng.standard_normal(3)
    qt.initPauliHamil(h, coeffs, codes)
    got = qt.calcExpecPauliHamil(q, h)
    # dense oracle
    import functools
    P2 = [np.eye(2), np.array([[0, 1], [1, 0]]),
          np.array([[0, -1j], [1j, 0]]), np.array([[1, 0], [0, -1]])]
    H = np.zeros((1 << n, 1 << n), complex)
    for k in range(3):
        term = functools.reduce(np.kron,
                                [P2[c] for c in codes[k][::-1]])
        H = H + coeffs[k] * term
    expect = float(np.real(vec.conj() @ H @ vec))
    assert abs(got - expect) < 1e-10


def test_quad_register_lifecycle(env, quad):
    """The full gate path runs at prec 4 (f64 storage, tighter eps)."""
    q = qt.createQureg(5, env)
    qt.hadamard(q, 0)
    for t in range(1, 5):
        qt.controlledNot(q, t - 1, t)
    assert abs(qt.calcProbOfOutcome(q, 4, 0) - 0.5) < 1e-14
    assert q.amps.dtype == jnp.float64
