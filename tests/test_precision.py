"""Quad precision (QuEST_PREC=4, QuEST_precision.h:55-68): the recorded
scope decision (f64 storage — mirroring the reference's own GPU-quad
prohibition, QuEST/CMakeLists.txt:69-73 — with double-double-compensated
reductions where extended precision is observable) plus the REAL_EPS /
message-cap table extension."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu import precision
from quest_tpu.ops import calculations as C

import jax.numpy as jnp


@pytest.fixture
def quad():
    qt.set_precision(4)
    yield
    qt.set_precision(2)


def test_precision_table_extended(quad):
    assert precision.get_precision() == 4
    assert precision.real_eps() == 1e-14
    assert precision.max_amps_in_msg() == 1 << 27
    assert precision.real_dtype() == jnp.float64


def test_invalid_precision_rejected():
    with pytest.raises(ValueError, match="quest_prec"):
        qt.set_precision(3)


def test_quad_sum_survives_cross_block_cancellation():
    """Per-block-exact partials of wildly varying signed magnitude: the
    plain pairwise tree loses the small term to rounding at 1e16; the
    Neumaier double-double combine keeps it."""
    B = C._QUAD_BLOCK
    v = np.zeros(4 * B)
    v[0] = 1e16
    v[B] = 1.0
    v[2 * B] = -1e16
    v[3 * B] = 1e-3
    got = float(C.quad_sum(jnp.asarray(v)))
    assert got == pytest.approx(1.0 + 1e-3, abs=1e-12)


def test_quad_total_prob_and_inner_product(env, quad):
    rng = np.random.default_rng(3)
    n = 6
    q = qt.createQureg(n, env)
    vec = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec /= np.linalg.norm(vec)
    qt.initStateFromAmps(q, vec.real, vec.imag)
    assert abs(qt.calcTotalProb(q) - 1.0) < 1e-14
    q2 = qt.createQureg(n, env)
    vec2 = rng.standard_normal(1 << n) + 1j * rng.standard_normal(1 << n)
    vec2 /= np.linalg.norm(vec2)
    qt.initStateFromAmps(q2, vec2.real, vec2.imag)
    ip = qt.calcInnerProduct(q, q2)
    assert abs(ip - np.vdot(vec, vec2)) < 1e-13


def test_quad_register_lifecycle(env, quad):
    """The full gate path runs at prec 4 (f64 storage, tighter eps)."""
    q = qt.createQureg(5, env)
    qt.hadamard(q, 0)
    for t in range(1, 5):
        qt.controlledNot(q, t - 1, t)
    assert abs(qt.calcProbOfOutcome(q, 4, 0) - 0.5) < 1e-14
    assert q.amps.dtype == jnp.float64
