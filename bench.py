"""Benchmark driver: prints ONE JSON line.

Headline keys (the driver contract) = BASELINE.json config 2: 26-qubit
depth-20 random circuit, amplitude-updates/sec vs the measured reference
CPU record.  The same line now carries a ``configs`` object with ALL
FIVE BASELINE.json configs (VERDICT r3 item 3), each reporting
{median, min, spread, reps} of K-diff device seconds (or wall-clock
where noted) so per-round regressions are visible mechanically:

  1: 12q API chain (imperative dispatch) + the same chain as ONE jitted
     program (K-diff device truth for the gate set itself)
  2: 26q depth-20 random circuit, chained window-pass executor
  3: 30q full QFT (the BASELINE-stated size), multilayer chained
  4: 13q density noise block — eager per-channel AND fused-drain with
     channel sweeps on/off (the r3 text/code contradiction, measured)
  5: 24q PauliHamil expectation + Trotter (scan paths)

Timing: a device->host fetch through the axon relay costs ~100 ms and
dispatch more — fixed per-call harness overheads — and the shared chip
drifts on a seconds scale.  Large-K contrast
(T[K iters] - best T[1 iter]) / (K - 1), K in {4, 8, 16}, cancels the
fixed overheads AND bounds drift's reach (one spike moves one rep);
median/min/spread over reps are reported (VERDICT r4 item 3).  Config 2
alone uses paired K=2 differences: its iteration is 27 small programs,
and sustained large K crosses into the host-dispatch-bound regime
(~3.7 ms/program through the relay) — that rate is reported separately
as sustained_k16_dispatch_bound.  The
persistent XLA compilation cache (quest_tpu.env) makes every session
after the first start warm; per-config compile_s records what THIS
session paid.

QT_BENCH_CONFIGS=2,3 restricts the set; QT_BENCH_CPU=1 shrinks sizes
for off-TPU smoke runs.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("QT_BENCH_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import quest_tpu as qt
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels

CPU = os.environ.get("QT_BENCH_CPU") == "1"
BASELINE_AMPS_PER_SEC = 3.493e8   # scripts/ref_bench.c record, BASELINE.md

N = int(os.environ.get("QT_BENCH_QUBITS", "16" if CPU else "26"))
DEPTH = int(os.environ.get("QT_BENCH_DEPTH", "4" if CPU else "20"))
REPS = int(os.environ.get("QT_BENCH_REPS", "3" if CPU else "5"))


def kdiff_stats(run_k, reps=REPS, warm=True, khi=2):
    """Drift-resistant marginal cost per iteration (VERDICT r4 item 3).

    khi >= 4: large-K contrast marg = (T[K] - min_j T_j[1]) / (K - 1) —
    the subtrahend is the drift-free best single run (negative minima
    cannot arise from an inflated T[1] draw), one drift spike moves one
    rep, and T1's dispatch jitter enters only as jitter/(K-1).

    khi == 2: PAIRED same-rep differences d_i = T_i[2] - T_i[1] — at 1x
    nothing divides the jitter down, so the best-T1 subtrahend would
    fold the full ~0.04 s dispatch jitter into the marginal (measured:
    it reported 0.100 for a workload paired-d2 puts at 0.06); the
    median over reps guards the paired form instead.  Used where large
    K would cross into the host-dispatch-bound regime (config 2's
    27-small-program iterations — BASELINE.md round-5 correction)."""
    assert khi >= 2, "large-K contrast needs khi >= 2"
    t0 = time.perf_counter()
    run_k(1)
    compile_s = time.perf_counter() - t0
    if warm:
        run_k(khi)
    t1s, tks = [], []
    for _ in range(reps):
        t1s.append(run_k(1))
        tks.append(run_k(khi))
    if khi == 2:
        # paired same-rep differences: the best-T1 subtrahend would fold
        # T1's full dispatch jitter (~0.04 s) into a 1x marginal — at
        # khi=2 nothing divides it down.  Pairing keeps the estimate
        # unbiased; the median over reps guards it (round-4 form).
        margs = [tk - t1 for t1, tk in zip(t1s, tks)]
        # each paired marg absorbs its own T1 draw, so the estimator's
        # spread must come from the margs themselves (the raw-T[k] form
        # below would under-report it)
        spread = max(margs) - min(margs)
    else:
        # large K: one drift spike moves one rep, and the T1 jitter
        # enters only as jitter/(K-1)
        t1_best = min(t1s)
        margs = [(tk - t1_best) / (khi - 1) for tk in tks]
        spread = (max(tks) - min(tks)) / (khi - 1)
    return {
        "median": round(statistics.median(margs), 4),
        "min": round(min(margs), 4),
        "spread": round(spread, 4),
        "reps": reps,
        "khi": khi,
        "wall_single": round(min(t1s), 4),
        "compile_s": round(compile_s, 1),
    }


def wall_stats(run, reps=REPS):
    t0 = time.perf_counter()
    run()
    compile_s = time.perf_counter() - t0
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        walls.append(time.perf_counter() - t0)
    return {
        "median": round(statistics.median(walls), 4),
        "min": round(min(walls), 4),
        "spread": round(max(walls) - min(walls), 4),
        "reps": reps,
        "compile_s": round(compile_s, 1),
    }


def config1(env):
    """12q hadamard + controlledRotateX chain + calcProbOfOutcome:
    imperative API wall-clock AND the same chain as one jitted program
    measured by K-diff (VERDICT r3 weak-3: the device cost of the
    gate-at-a-time path is dispatch-bound; this pins the device part)."""
    n = 12

    def api_run():
        q = qt.createQureg(n, env)
        qt.hadamard(q, 0)
        for t in range(1, n):
            qt.controlledRotateX(q, t - 1, t, 0.3)
        return qt.calcProbOfOutcome(q, n - 1, 0)

    api = wall_stats(api_run, reps=3)

    from functools import partial

    @partial(jax.jit, static_argnames="k")
    def prog(amps, k):
        c, s = np.cos(0.15), np.sin(0.15)
        rx_soa = jnp.asarray(
            np.stack([[[c, 0], [0, c]], [[0, -s], [-s, 0]]]), amps.dtype)
        h = jnp.asarray(np.array(
            [[[1, 1], [1, -1]], [[0, 0], [0, 0]]]) / np.sqrt(2), amps.dtype)
        for _ in range(k):
            amps = kernels.apply_matrix(amps, h, num_qubits=n, targets=(0,))
            for t in range(1, n):
                amps = kernels.apply_matrix(
                    amps, rx_soa, num_qubits=n, targets=(t,),
                    controls=(t - 1,))
        return amps, calculations.calc_prob_of_outcome_statevec(
            amps, num_qubits=n, target=n - 1, outcome=0)

    def run_k(k):
        a = kernels.init_zero_state(1 << n, np.float32)
        t0 = time.perf_counter()
        _, p = prog(jnp.asarray(a), k)
        float(p)
        return time.perf_counter() - t0

    jit_k = kdiff_stats(run_k, khi=16)
    return {"metric": "12q API chain", "api_wall": api,
            "single_jit_kdiff": jit_k}


def config2(env):
    from quest_tpu import circuit as C

    fn, us = circuits.build_random_circuit(N, DEPTH, seed=7)
    num_gates = DEPTH * N + sum(
        1 for d in range(DEPTH) for t in range(N - 1) if (d + t) % 2 == 0)
    plan = C.plan_circuit(circuits.bench_gate_list(N, DEPTH, np.asarray(us)), N)
    pstats = C.stats(plan)
    ops = C.plan_to_device(plan, jnp.float32)
    prob_box = [None]

    def run_k(k):
        a = circuits.zero_state_canonical(N)
        t0 = time.perf_counter()
        for _ in range(k):
            a = C.execute_plan_chained(a, ops, N)
        prob_box[0] = float(circuits.prob_top_zero_canonical(a))
        return time.perf_counter() - t0

    # DEVICE-time marginal: khi=2.  Config 2 is the one config whose
    # iteration is 27 SMALL programs, so at large K the host dispatch
    # rate through the relay (~3.7 ms/program, rock-stable ~0.101 s/iter
    # at K=16) becomes the bottleneck and the contrast measures the
    # harness, not the chip — measured side by side: d2 = 0.058-0.08 vs
    # d16 = 0.101 in the same reps (BASELINE.md round-5).  khi=2 keeps
    # the device marginal via paired per-rep differences, median of 7
    # reps (NOT the best-T1 subtrahend — that folds the full dispatch
    # jitter into a 1x marginal).  The sustained (dispatch-bound) rate
    # is reported alongside for transparency.
    st = kdiff_stats(run_k, reps=7, khi=2)
    # warm=False: st's runs above already compiled and warmed run_k;
    # drop the sustained call's meaningless compile_s reading too
    sustained = kdiff_stats(run_k, reps=2, khi=16, warm=False)
    sustained.pop("compile_s", None)
    # the rate claims the MEDIAN paired diff: a single favorable-drift
    # pair can deflate the min as easily as a spike inflates it (one run
    # recorded min 0.0097 vs median 0.0589 — a 6x over-claim if used);
    # a non-positive median means the session was too noisy to measure —
    # report null rather than a clamped absurdity
    rate = (num_gates * float(1 << N) / st["median"]
            if st["median"] > 0 else None)
    from quest_tpu.ops import fused as _fused

    return {"metric": f"{N}q depth-{DEPTH} random circuit",
            "kdiff": st, "gates": num_gates,
            "amp_updates_per_sec": rate,
            "sustained_k16_dispatch_bound": sustained,
            # dispatch-count breakdown (r04->r05 diagnosis + §29): the
            # number of separately dispatched programs one iteration
            # chains — the host-dispatch-bound regime's lever arm — and
            # how many the megakernel planner grouped away
            "programs_per_iter": len(plan),
            "megakernel": _fused.megakernel_mode(),
            "megawin_groups": pstats.get("megawin", 0),
            "megawin_grouped_ops": pstats.get("megawin_ops", 0),
            "prob_check": prob_box[0]}


def config3(env):
    from quest_tpu import circuit as C

    n = 14 if CPU else 30   # fused path needs n >= WINDOW (14)
    amp_box = [None]

    def run_k(k):
        a = circuits.zero_state_canonical(n)
        t0 = time.perf_counter()
        for _ in range(k):
            a = C.fused_qft(a, n, 0, n)
        amp_box[0] = float(circuits.amp00_canonical(a))
        return time.perf_counter() - t0

    st = kdiff_stats(run_k, reps=4, khi=8)
    # the last timed run chains an EVEN number of QFTs: QFT^2 maps
    # |0..0> back to |0..0> (it is the index-negation permutation), so
    # amp0 ~= 1 — an in-artifact correctness check; an odd run would
    # give 2^(-n/2)
    return {"metric": f"{n}q full QFT (chained multilayer)", "kdiff": st,
            "amp0_after_k2": amp_box[0], "amp0_expect_k2": 1.0}


def config4(env):
    """13q rho noise block: eager per-channel vs fused drain, the fused
    drain with channel sweeps ON and OFF (VERDICT r3 item 5 + weak-4,
    ADVICE r3 (c))."""
    n = 5 if CPU else 13
    rng = np.random.default_rng(5)
    raw = rng.standard_normal((4, 4, 4)) + 1j * rng.standard_normal((4, 4, 4))
    s = np.zeros((4, 4), dtype=complex)
    for k in raw:
        s += k.conj().T @ k
    w = np.linalg.inv(np.linalg.cholesky(s).conj().T)
    kops = [k @ w for k in raw]
    fid_box = [None]

    def noise(rho, k):
        for _ in range(k):
            for q in range(n):
                qt.mixDepolarising(rho, q, 0.05)
            qt.mixTwoQubitKrausMap(rho, 0, 1, kops)

    def run_variant(fused, k):
        rho = qt.createDensityQureg(n, env)
        qt.initPlusState(rho)
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        if fused:
            with qt.gateFusion(rho):
                noise(rho, k)
        else:
            noise(rho, k)
        fid_box[0] = qt.calcFidelity(rho, psi)
        return time.perf_counter() - t0

    out = {"metric": f"{n}q density noise + fidelity"}
    out["eager"] = kdiff_stats(lambda k: run_variant(False, k), reps=2,
                               khi=4)
    prev = os.environ.get("QT_CHAN_SWEEP")
    try:
        os.environ["QT_CHAN_SWEEP"] = "1"
        out["fused_sweep_on"] = kdiff_stats(
            lambda k: run_variant(True, k), reps=2, khi=4)
        os.environ["QT_CHAN_SWEEP"] = "0"
        out["fused_sweep_off"] = kdiff_stats(
            lambda k: run_variant(True, k), reps=2, khi=4)
    finally:
        if prev is None:
            os.environ.pop("QT_CHAN_SWEEP", None)
        else:
            os.environ["QT_CHAN_SWEEP"] = prev
    out["fidelity"] = fid_box[0]
    return out


def config5(env):
    n = 8 if CPU else 24
    terms = 16
    rng = np.random.default_rng(7)
    hamil = qt.createPauliHamil(n, terms)
    qt.initPauliHamil(hamil, rng.standard_normal(terms),
                      rng.integers(0, 4, size=(terms, n)))
    e_box = [None]

    def run_k(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            e_box[0] = qt.calcExpecPauliHamil(psi, hamil)
            qt.applyTrotterCircuit(psi, hamil, 0.1, 2, 1)
        return time.perf_counter() - t0

    st = kdiff_stats(run_k, reps=4, khi=8)

    # component marginals (probe_config5_decomp decomposition carried
    # in-artifact): the trotter stream pipelines across iterations (its
    # API marginal IS device truth), while each calcExpecPauliHamil
    # returns a float — one relay round-trip of serialization per call
    # that an on-host deployment doesn't pay
    def run_trotter(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            qt.applyTrotterCircuit(psi, hamil, 0.1, 2, 1)
        qt.calcTotalProb(psi)
        return time.perf_counter() - t0

    def run_expec(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        t0 = time.perf_counter()
        for _ in range(k):
            e_box[0] = qt.calcExpecPauliHamil(psi, hamil)
        return time.perf_counter() - t0

    # device truth (the corrected metric, BASELINE.md round-5): the same
    # per-iteration [expec + trotter] workload pipelined on device with
    # ONE fetch at the end — what an in-process caller (the reference's
    # own deployment model) pays; the API kdiff above additionally eats
    # one relay round-trip per iteration from the synchronous float
    # return of calcExpecPauliHamil
    from quest_tpu.api_ops import _trotter_schedule
    from quest_tpu.ops import paulis as OPS_P

    seq = _trotter_schedule(terms, 0.1, 2, 1)
    t_idx = np.asarray([t for t, _ in seq])
    facs = np.asarray([f for _, f in seq])
    codes_tr = jnp.asarray(
        np.asarray(hamil.pauli_codes)[t_idx].astype(np.int32))
    angles_tr = jnp.asarray(
        2.0 * facs * np.asarray(hamil.term_coeffs, np.float64)[t_idx])
    codes_ex = jnp.asarray(np.asarray(hamil.pauli_codes, np.int32))
    coeffs_ex = jnp.asarray(np.asarray(hamil.term_coeffs, np.float64))

    def run_device(k):
        psi = qt.createQureg(n, env)
        qt.initPlusState(psi)
        a = psi.amps
        e = None
        t0 = time.perf_counter()
        for _ in range(k):
            e = OPS_P.expec_pauli_sum_scan(a, codes_ex, coeffs_ex,
                                           num_qubits=n)
            a = OPS_P.trotter_scan(a, codes_tr, angles_tr,
                                   num_qubits=n, rep_qubits=n)
        float(e)
        float(jnp.sum(a[0, :1]))
        return time.perf_counter() - t0

    return {"metric": f"{n}q PauliHamil expec + Trotter", "kdiff": st,
            "trotter_kdiff": kdiff_stats(run_trotter, reps=2, khi=8),
            "expec_kdiff": kdiff_stats(run_expec, reps=2, khi=8),
            "fused_device_kdiff": kdiff_stats(run_device, reps=2, khi=8),
            "energy": e_box[0]}


def main():
    env = qt.createQuESTEnv()
    want = [int(c) for c in os.environ.get(
        "QT_BENCH_CONFIGS", "1,2,3,4,5").split(",")]
    runners = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}
    configs = {}
    t_start = time.time()
    for c in want:
        t0 = time.time()
        try:
            configs[str(c)] = runners[c](env)
        except Exception as e:  # record, keep the artifact complete
            configs[str(c)] = {"error": repr(e)[:300]}
        configs[str(c)]["config_total_s"] = round(time.time() - t0, 1)

    c2 = configs.get("2", {})
    best = c2.get("kdiff", {}).get("min")   # "seconds" stays the min;
    value = c2.get("amp_updates_per_sec")   # the rate uses the median
    baseline_shape = (N == 26 and DEPTH == 20) and value is not None
    summary = {
        # "config" keys the line into scripts/bench_regress.py's
        # JSON-lines normalizer — the machine-parsable contract that
        # replaced re-grepping the text tail (a r05 parsed:null artifact
        # came from the old everything-on-one-line stdout outgrowing the
        # capture window)
        "config": 2,
        "metric": f"{N}q depth-{DEPTH} random-circuit gate-apply rate",
        "value": value,
        "unit": "amp_updates_per_sec",
        "vs_baseline": (value / BASELINE_AMPS_PER_SEC
                        if baseline_shape else None),
        "seconds": best,
        "seconds_median": c2.get("kdiff", {}).get("median"),
        "seconds_spread": c2.get("kdiff", {}).get("spread"),
        "programs_per_iter": c2.get("programs_per_iter"),
        "megakernel": c2.get("megakernel"),
        "megawin_groups": c2.get("megawin_groups"),
        "backend": jax.default_backend(),
        "total_bench_s": round(time.time() - t_start, 1),
    }
    # full per-config results go to a FILE: the one-line-of-everything
    # stdout artifact outgrew tail capture and truncated to parsed:null
    # (VERDICT r5).  stdout keeps a short headline any capture window
    # holds; the file carries the timing-methodology note and configs.
    out_path = os.environ.get("QT_BENCH_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"BENCH_{time.strftime('%Y%m%d_%H%M%S')}.json")
    full = dict(summary)
    full["timing"] = (
        "config-2 headline: paired K=2 diffs (T[2x]-T[1x] per rep, 7 "
        "reps) — device-time marginal; other configs large-K contrast "
        "(T[Kx]-best T[1x])/(K-1), K in {4,8,16}; removes fixed relay "
        "fetch overhead, bounds drift; sustained dispatch-bound rate "
        "reported separately")
    full["configs"] = configs
    with open(out_path, "w") as f:
        json.dump(full, f, indent=1)
    summary["results_file"] = out_path
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
