"""Benchmark driver: prints ONE JSON line with the headline metric.

Workload = BASELINE.json config 2: 26-qubit state-vector, depth-20 random
circuit of 1q unitaries + CNOT ladder, single chip.  Metric: amplitude-
updates per second (gates x 2^N / device-seconds) — the gate-apply rate
of BASELINE.json.

Execution (round 3): CHAINED — the plan runs as a sequence of per-pass
cached jitted programs with the state held in the canonical
(2, nb, 128, 128) tiled view between calls (circuit.execute_plan_chained).
vs the round-2 monolithic whole-circuit trace this removes the full-state
boundary layout copy and cuts compile from minutes to ~30 s, and is what
lets the same code scale to 30 qubits (see BASELINE.md round-3 section).

vs_baseline compares against the reference QuEST CPU backend (upstream
sagudeloo/QuEST built -DMULTITHREADED=1, Release, double precision)
running the IDENTICAL circuit shape on the build host (single hardware
core — see BASELINE.md for the measured record).
"""

import json
import os
import sys
import time

# quest_tpu imports resolve from this file's directory. (If you need
# PYTHONPATH instead, APPEND to it — replacing it drops /root/.axon_site
# and breaks axon TPU plugin discovery; see .claude/skills/verify/SKILL.md.)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("QT_BENCH_CPU") == "1":
    # local testing off-TPU; NB the JAX_PLATFORMS env var hangs under the
    # axon relay, the config update is the reliable route
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import quest_tpu as qt
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels

# Reference QuEST CPU (unmodified /root/reference sources, CPU backend,
# double precision, this build host's single hardware core), IDENTICAL
# circuit shape, measured via scripts/ref_bench.c:
# {"n": 26, "depth": 20, "gates": 770, "seconds": 147.927,
#  "amp_updates_per_sec": 3.493e8} — see BASELINE.md. amp-updates/sec:
BASELINE_AMPS_PER_SEC = 3.493e8

N = int(os.environ.get("QT_BENCH_QUBITS", "26"))
DEPTH = int(os.environ.get("QT_BENCH_DEPTH", "20"))
REPS = int(os.environ.get("QT_BENCH_REPS", "5"))
# Fused scheduler path (windowed plan + Pallas window kernels) vs per-gate
# einsum path; identical circuit either way.  The chained executor needs
# the canonical view (n >= 15).
FUSED = os.environ.get("QT_BENCH_FUSED", "1") == "1" and N >= 15


def main():
    from quest_tpu import circuit as C

    fn, us = circuits.build_random_circuit(N, DEPTH, seed=7)
    num_gates = DEPTH * N + sum(
        1 for d in range(DEPTH) for t in range(N - 1) if (d + t) % 2 == 0
    )

    if FUSED:
        ops = C.plan_to_device(
            C.plan_circuit(circuits.bench_gate_list(N, DEPTH, np.asarray(us)),
                           N),
            jnp.float32)

        def run_k(k):
            a = circuits.zero_state_canonical(N)
            t0 = time.perf_counter()
            for _ in range(k):
                a = C.execute_plan_chained(a, ops, N)
            p = float(circuits.prob_top_zero_canonical(a))
            return time.perf_counter() - t0, p
    else:
        from functools import partial

        def mk(k):
            @partial(jax.jit, donate_argnums=0)
            def p(amps, us):
                prob = None
                for _ in range(k):
                    amps = fn(amps, us)
                    prob = calculations.calc_prob_of_outcome_statevec(
                        amps, num_qubits=N, target=N - 1, outcome=0
                    )
                return amps, prob
            return p

        progs = {1: mk(1), 2: mk(2)}

        def run_k(k):
            a = kernels.init_zero_state(1 << N, np.float32)
            t0 = time.perf_counter()
            _, p = progs[k](a, us)
            p = float(p)
            return time.perf_counter() - t0, p

    # Timing methodology: a device->host fetch through the axon relay
    # costs ~100 ms and dispatch more — FIXED per-call harness overheads
    # (a production TPU dispatches in <1 ms).  A single-call wall clock
    # therefore measures the relay, not the framework.  We K-difference:
    # T(2 circuits) - T(1 circuit) = pure device time per circuit; both
    # overheads cancel.  min + spread over REPS reps are reported.
    t0 = time.perf_counter()
    _, prob = run_k(1)
    compile_s = time.perf_counter() - t0
    run_k(2)

    t1s, t2s = [], []
    for _ in range(REPS):
        t1, prob = run_k(1)
        t2, _ = run_k(2)
        t1s.append(t1)
        t2s.append(t2)
    wall = min(t1s)
    best = min(t2s) - min(t1s)
    assert best > 0, (
        f"non-positive K-diff ({best:.4f}s): relay noise exceeded device "
        f"time; raise QT_BENCH_REPS (t1s={t1s}, t2s={t2s})"
    )
    spread = (max(t2s) - min(t2s)) + (max(t1s) - min(t1s))

    value = num_gates * float(1 << N) / best
    # the reference constant was measured at the 26q depth-20 shape; a
    # shrunk smoke run must not report a ratio of incommensurate workloads
    baseline_shape = (N == 26 and DEPTH == 20)
    print(
        json.dumps(
            {
                "metric": f"{N}q depth-{DEPTH} random-circuit gate-apply rate",
                "value": value,
                "unit": "amp_updates_per_sec",
                "vs_baseline": (value / BASELINE_AMPS_PER_SEC
                                if baseline_shape else None),
                "seconds": best,
                "seconds_spread": round(spread, 4),
                "wall_seconds_single_call": wall,
                "compile_plus_first_run_s": round(compile_s, 1),
                "reps": REPS,
                "timing": "K-diff (min T[2x] - min T[1x] over reps; removes fixed relay fetch+dispatch overhead)",
                "gates": num_gates,
                "backend": jax.default_backend(),
                "mode": "chained" if FUSED else "per-gate",
                "prob_check": float(prob),
            }
        )
    )


if __name__ == "__main__":
    main()
