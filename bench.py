"""Benchmark driver: prints ONE JSON line with the headline metric.

Workload = BASELINE.json config 2: 26-qubit state-vector, depth-20 random
circuit of 1q unitaries + CNOT ladder, single chip, whole circuit traced
into one jitted XLA program.  Metric: amplitude-updates per second
(gates x 2^N / wall-clock) — the gate-apply rate of BASELINE.json.

vs_baseline compares against the reference QuEST CPU backend (upstream
sagudeloo/QuEST built -DMULTITHREADED=1, Release, double precision) running
the IDENTICAL circuit shape on the build host (single hardware core —
see BASELINE.md for the measured record).
"""

import json
import os
import sys
import time

# quest_tpu imports resolve from this file's directory. (If you need
# PYTHONPATH instead, APPEND to it — replacing it drops /root/.axon_site
# and breaks axon TPU plugin discovery; see .claude/skills/verify/SKILL.md.)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("QT_BENCH_CPU") == "1":
    # local testing off-TPU; NB the JAX_PLATFORMS env var hangs under the
    # axon relay, the config update is the reliable route
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import quest_tpu as qt
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels

# Reference QuEST CPU (unmodified /root/reference sources, CPU backend,
# double precision, this build host's single hardware core), IDENTICAL
# circuit shape, measured via scripts/ref_bench.c:
# {"n": 26, "depth": 20, "gates": 770, "seconds": 147.927,
#  "amp_updates_per_sec": 3.493e8} — see BASELINE.md. amp-updates/sec:
BASELINE_AMPS_PER_SEC = 3.493e8

N = int(os.environ.get("QT_BENCH_QUBITS", "26"))
DEPTH = int(os.environ.get("QT_BENCH_DEPTH", "20"))
REPS = int(os.environ.get("QT_BENCH_REPS", "3"))
# Fused scheduler path (Pallas cluster kernel + permutes, quest_tpu.circuit)
# vs per-gate einsum path; identical circuit either way.
FUSED = os.environ.get("QT_BENCH_FUSED", "1") == "1" and N >= 14


def _build_fused_program():
    """Same circuit as circuits.build_random_circuit, as a scheduled plan:
    gate matrices stay traced args so angle changes never recompile."""
    import numpy as _np

    from quest_tpu import circuit as C

    # CNOT with control = matrix bit 0 (= targets[0] = q), target = bit 1:
    # flips bit 1 on states where bit 0 is set (indices 1 <-> 3)
    cnot = _np.zeros((2, 4, 4), _np.float32)
    cnot[0] = _np.array(
        [[1, 0, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0], [0, 1, 0, 0]], _np.float32
    )

    def program(amps, us):
        gates = []
        for d in range(DEPTH):
            for q in range(N):
                gates.append(C.Gate((q,), us[d, q]))
            for q in range(d % 2, N - 1, 2):
                gates.append(C.Gate((q, q + 1), cnot))
        amps = C.apply_circuit(amps, gates, N)
        prob = calculations.calc_prob_of_outcome_statevec(
            amps, num_qubits=N, target=N - 1, outcome=0
        )
        return amps, prob

    return program


def main():
    fn, unitaries = circuits.build_random_circuit(N, DEPTH, seed=7)

    if FUSED:
        program = _build_fused_program()
    else:
        def program(amps, us):
            amps = fn(amps, us)
            prob = calculations.calc_prob_of_outcome_statevec(
                amps, num_qubits=N, target=N - 1, outcome=0
            )
            return amps, prob

    # Timing methodology: a device->host fetch through the axon relay
    # costs ~100 ms and dispatch another ~50 ms — FIXED per-call overheads
    # of the test harness (a production TPU dispatches in <1 ms), measured
    # 2026-07-30: scalar jit+fetch = 102-108 ms regardless of payload.  A
    # single-call wall clock would therefore measure the relay, not the
    # framework.  We K-difference instead: T(2 circuits in one program) -
    # T(1 circuit) = pure device time per circuit; both overheads cancel.
    # The raw single-call wall clock is also reported for transparency.
    def prog_K(K):
        def p(amps, us):
            prob = None
            for _ in range(K):
                amps, prob = program(amps, us)
            return amps, prob
        return jax.jit(p, donate_argnums=0)

    jprog1, jprog2 = prog_K(1), prog_K(2)

    num_gates = DEPTH * N + sum(
        1 for d in range(DEPTH) for t in range(N - 1) if (d + t) % 2 == 0
    )

    def run(jp):
        amps = kernels.init_zero_state(1 << N, np.float32)
        t0 = time.perf_counter()
        _, prob = jp(amps, unitaries)
        float(prob)  # the only reliable device sync under the relay
        return time.perf_counter() - t0, float(prob)

    run(jprog1)  # compile
    run(jprog2)

    # min(T2) - min(T1): differencing the per-arm minima (not per-rep
    # pairs) so relay-latency noise on one arm cannot deflate the estimate
    t1s, t2s = [], []
    for _ in range(REPS):
        t1, prob = run(jprog1)
        t2, _ = run(jprog2)
        t1s.append(t1)
        t2s.append(t2)
    wall = min(t1s)
    best = min(t2s) - min(t1s)
    assert best > 0, (
        f"non-positive K-diff ({best:.4f}s): relay noise exceeded device "
        f"time; raise QT_BENCH_REPS (t1s={t1s}, t2s={t2s})"
    )

    value = num_gates * float(1 << N) / best
    # the reference constant was measured at the 26q depth-20 shape; a
    # shrunk smoke run must not report a ratio of incommensurate workloads
    baseline_shape = (N == 26 and DEPTH == 20)
    print(
        json.dumps(
            {
                "metric": f"{N}q depth-{DEPTH} random-circuit gate-apply rate",
                "value": value,
                "unit": "amp_updates_per_sec",
                "vs_baseline": (value / BASELINE_AMPS_PER_SEC
                                if baseline_shape else None),
                "seconds": best,
                "wall_seconds_single_call": wall,
                "timing": "K-diff (T[2x]-T[1x]; removes ~150ms fixed relay fetch+dispatch overhead)",
                "gates": num_gates,
                "backend": jax.default_backend(),
                "fused": FUSED,
                "prob_check": float(prob),
            }
        )
    )


if __name__ == "__main__":
    main()
