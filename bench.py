"""Benchmark driver: prints ONE JSON line with the headline metric.

Workload = BASELINE.json config 2: 26-qubit state-vector, depth-20 random
circuit of 1q unitaries + CNOT ladder, single chip, whole circuit traced
into one jitted XLA program.  Metric: amplitude-updates per second
(gates x 2^N / wall-clock) — the gate-apply rate of BASELINE.json.

vs_baseline compares against the reference QuEST CPU backend (upstream
sagudeloo/QuEST built -DMULTITHREADED=1, Release, double precision) running
the IDENTICAL circuit shape on the build host (single hardware core —
see BASELINE.md for the measured record).
"""

import json
import os
import sys
import time

# quest_tpu imports resolve from this file's directory. (If you need
# PYTHONPATH instead, APPEND to it — replacing it drops /root/.axon_site
# and breaks axon TPU plugin discovery; see .claude/skills/verify/SKILL.md.)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("QT_BENCH_CPU") == "1":
    # local testing off-TPU; NB the JAX_PLATFORMS env var hangs under the
    # axon relay, the config update is the reliable route
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import quest_tpu as qt
from quest_tpu.models import circuits
from quest_tpu.ops import calculations, kernels

# Reference QuEST CPU (unmodified /root/reference sources, CPU backend,
# double precision, this build host's single hardware core), IDENTICAL
# circuit shape, measured via scripts/ref_bench.c:
# {"n": 26, "depth": 20, "gates": 770, "seconds": 147.927,
#  "amp_updates_per_sec": 3.493e8} — see BASELINE.md. amp-updates/sec:
BASELINE_AMPS_PER_SEC = 3.493e8

N = int(os.environ.get("QT_BENCH_QUBITS", "26"))
DEPTH = int(os.environ.get("QT_BENCH_DEPTH", "20"))
REPS = int(os.environ.get("QT_BENCH_REPS", "3"))
# Fused scheduler path (Pallas cluster kernel + permutes, quest_tpu.circuit)
# vs per-gate einsum path; identical circuit either way.
FUSED = os.environ.get("QT_BENCH_FUSED", "1") == "1" and N >= 14


def _build_fused_program():
    """Same circuit as circuits.build_random_circuit, as a scheduled plan:
    gate matrices stay traced args so angle changes never recompile."""
    import numpy as _np

    from quest_tpu import circuit as C

    # CNOT with control = matrix bit 0 (= targets[0] = q), target = bit 1:
    # flips bit 1 on states where bit 0 is set (indices 1 <-> 3)
    cnot = _np.zeros((2, 4, 4), _np.float32)
    cnot[0] = _np.array(
        [[1, 0, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0], [0, 1, 0, 0]], _np.float32
    )

    def program(amps, us):
        gates = []
        for d in range(DEPTH):
            for q in range(N):
                gates.append(C.Gate((q,), us[d, q]))
            for q in range(d % 2, N - 1, 2):
                gates.append(C.Gate((q, q + 1), cnot))
        amps = C.apply_circuit(amps, gates, N)
        prob = calculations.calc_prob_of_outcome_statevec(
            amps, num_qubits=N, target=N - 1, outcome=0
        )
        return amps, prob

    return program


def main():
    fn, unitaries = circuits.build_random_circuit(N, DEPTH, seed=7)

    if FUSED:
        program = _build_fused_program()
    else:
        def program(amps, us):
            amps = fn(amps, us)
            prob = calculations.calc_prob_of_outcome_statevec(
                amps, num_qubits=N, target=N - 1, outcome=0
            )
            return amps, prob

    jprog = jax.jit(program, donate_argnums=0)

    num_gates = DEPTH * N + sum(
        1 for d in range(DEPTH) for t in range(N - 1) if (d + t) % 2 == 0
    )

    amps = kernels.init_zero_state(1 << N, np.float32)
    # warm-up (compile)
    amps, prob = jprog(amps, unitaries)
    float(prob)

    times = []
    for _ in range(REPS):
        amps = kernels.init_zero_state(1 << N, np.float32)
        float(np.asarray(amps[0, 0]))  # sync before starting the clock
        t0 = time.perf_counter()
        amps, prob = jprog(amps, unitaries)
        # device-to-host fetch: under the axon relay block_until_ready
        # returns at enqueue time, so only a materialization bounds the
        # full execution
        float(prob)
        times.append(time.perf_counter() - t0)

    best = min(times)
    value = num_gates * float(1 << N) / best
    print(
        json.dumps(
            {
                "metric": f"{N}q depth-{DEPTH} random-circuit gate-apply rate",
                "value": value,
                "unit": "amp_updates_per_sec",
                "vs_baseline": value / BASELINE_AMPS_PER_SEC,
                "seconds": best,
                "gates": num_gates,
                "backend": jax.default_backend(),
                "fused": FUSED,
                "prob_check": float(prob),
            }
        )
    )


if __name__ == "__main__":
    main()
