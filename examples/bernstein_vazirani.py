"""Bernstein-Vazirani with quest_tpu.

Finds a secret bit-string with a single oracle query, as the reference
demonstrates (/root/reference/examples/bernstein_vazirani_circuit.c):
ancilla qubit 0 in |->, H on the input register, CNOTs encoding the secret
into the ancilla, H again, then measure the input register.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import quest_tpu as qt


def main():
    num_qubits = 9
    secret = 2 ** 4 + 1

    env = qt.createQuESTEnv()
    qureg = qt.createQureg(num_qubits, env)
    qt.initZeroState(qureg)

    # ancilla (qubit 0) to |1>, then everything to the Hadamard basis
    qt.pauliX(qureg, 0)
    for q in range(num_qubits):
        qt.hadamard(qureg, q)

    # oracle: CNOT each secret bit onto the ancilla (secret bit i lives on
    # qubit i+1, matching the reference's layout)
    for q in range(1, num_qubits):
        if (secret >> (q - 1)) & 1:
            qt.controlledNot(qureg, q, 0)

    # back out of the Hadamard basis; input register now encodes the secret
    for q in range(1, num_qubits):
        qt.hadamard(qureg, q)

    found = 0
    for q in range(1, num_qubits):
        found |= qt.measure(qureg, q) << (q - 1)

    print(f"secret = {secret}, recovered = {found}")
    assert found == secret

    qt.destroyQureg(qureg, env)
    qt.destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
