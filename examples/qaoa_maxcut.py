"""QAOA MaxCut training with quest_tpu.

Maximises the expected cut of a random weighted graph with a p-layer QAOA
ansatz; the whole step (diagonal cost phases, RX mixers, cut expectation,
gradient, Adam) is one jitted differentiable program — see
quest_tpu/models/qaoa.py.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import numpy as np
import optax

from quest_tpu.models import qaoa as qaoa_mod


def main():
    n = int(os.environ.get("QT_QAOA_QUBITS", "12"))
    edges = qaoa_mod.random_graph(n, 2 * n, seed=1)
    model = qaoa_mod.QAOA(n, edges, depth=3)

    opt = optax.adam(5e-2)
    params = model.init_params(jax.random.PRNGKey(0))
    state = opt.init(params)
    step = jax.jit(model.make_train_step(opt))

    total_w = sum(w for _, _, w in edges)
    print(f"QAOA MaxCut: {n} qubits, {len(edges)} edges, total weight {total_w:.2f}")
    for i in range(60):
        params, state, cut = step(params, state)
        if i % 10 == 0 or i == 59:
            print(f"  step {i:3d}  expected cut = {float(cut):.4f}")
    print("done")


if __name__ == "__main__":
    main()
