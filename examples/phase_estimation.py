"""Quantum Phase Estimation with quest_tpu.

Estimates the eigenphase phi of U = diag(1, e^{2 pi i phi}) acting on a
one-qubit eigenstate |1>, using an m-qubit counting register:

    1. Hadamard every counting qubit,
    2. controlled-U^(2^k) from counting qubit k (controlledPhaseShift —
       U is diagonal, so the controlled power is a phase shift),
    3. INVERSE QFT on the counting register,
    4. measure: the counting register collapses near round(phi * 2^m).

The reference ships no QPE example; this demonstrates the same API
surface its QFT machinery serves (applyQFT / controlledPhaseShift /
swapGate, QuEST.h:6536,1640,3768).  The inverse QFT is built from the
public API (swaps + reversed H/controlled-phase ladder — the adjoint of
agnostic_applyQFT, /root/reference/QuEST/src/QuEST_common.c:836-898),
and the whole circuit optionally runs inside gateFusion so it drains as
a handful of fused passes.
"""

import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import quest_tpu as qt


def inverse_qft(qureg, qubits):
    """Adjoint of the textbook QFT on ``qubits`` (ascending significance):
    undo the final swap network, then each layer's controlled-phase ladder
    (negated angles) and Hadamard in reverse order."""
    n = len(qubits)
    for i in range(n // 2):
        qt.swapGate(qureg, qubits[i], qubits[n - 1 - i])
    for j in range(n):
        for k in range(j):
            qt.controlledPhaseShift(
                qureg, qubits[k], qubits[j], -math.pi / (1 << (j - k)))
        qt.hadamard(qureg, qubits[j])


def run(num_counting, phi, fused=False):
    env = qt.createQuESTEnv()
    n = num_counting + 1
    eigen = num_counting                      # the eigenstate qubit
    q = qt.createQureg(n, env)
    qt.initClassicalState(q, 1 << eigen)      # |1> on the eigenstate qubit

    def circuit():
        for k in range(num_counting):
            qt.hadamard(q, k)
        for k in range(num_counting):
            # controlled-U^(2^k): U diagonal -> one phase shift
            qt.controlledPhaseShift(
                q, k, eigen, 2 * math.pi * phi * (1 << k))
        inverse_qft(q, list(range(num_counting)))

    if fused:
        with qt.gateFusion(q):
            circuit()
    else:
        circuit()

    outcome = 0
    for k in range(num_counting):
        outcome |= qt.measure(q, k) << k
    qt.destroyQureg(q, env)
    qt.destroyQuESTEnv(env)
    return outcome / (1 << num_counting)


def main():
    num_counting = int(os.environ.get("QPE_QUBITS", "8"))
    phi = float(os.environ.get("QPE_PHI", "0.3828125"))  # 98/256: exact at m=8
    fused = "--fused" in sys.argv
    est = run(num_counting, phi, fused=fused)
    print(f"phi = {phi}")
    print(f"estimate ({num_counting} counting qubits"
          f"{', fused' if fused else ''}) = {est}")
    print(f"|error| = {abs(est - phi)} (<= {1 / (1 << num_counting)} "
          f"guaranteed for exactly-representable phases)")


if __name__ == "__main__":
    main()
