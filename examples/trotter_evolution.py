"""Hamiltonian time evolution with quest_tpu: Trotterised dynamics of a
transverse-field Ising chain, with energy and magnetisation tracked.

The reference exposes the same workload through applyTrotterCircuit +
calcExpecPauliHamil (QuEST.h:5455, 4285) executed gate-at-a-time; here
every Trotter step runs as ONE scanned device program whose term body is
a direct Pauli rotation (one split-axis gather + fused combine — see
docs/design.md §13), so a 100-step evolution is 100 dispatches, not
100 x terms x 3 kernel sweeps.

Physics check carried in-output: the evolution conserves <H> (H commutes
with e^{-iHt}) to float precision, while the transverse magnetisation
<sum_q X_q> oscillates — the standard TFIM quench signature.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import quest_tpu as qt


def tfim_hamiltonian(n, j=1.0, h=0.7):
    """H = -J sum ZZ - h sum X as a PauliHamil (codes, coeffs)."""
    terms = []
    coeffs = []
    for q in range(n - 1):
        row = [0] * n
        row[q] = row[q + 1] = 3          # Z Z
        terms.append(row)
        coeffs.append(-j)
    for q in range(n):
        row = [0] * n
        row[q] = 1                        # X
        terms.append(row)
        coeffs.append(-h)
    return np.asarray(terms), np.asarray(coeffs)


def main():
    n = int(os.environ.get("QT_EVOLVE_QUBITS", "12"))
    steps = int(os.environ.get("QT_EVOLVE_STEPS", "20"))
    dt = 0.05

    env = qt.createQuESTEnv()
    codes, coeffs = tfim_hamiltonian(n)
    hamil = qt.createPauliHamil(n, len(coeffs))
    qt.initPauliHamil(hamil, coeffs, codes)

    # X magnetisation observable
    mx_codes = []
    for q in range(n):
        row = [0] * n
        row[q] = 1
        mx_codes.append(row)
    mx = qt.createPauliHamil(n, n)
    qt.initPauliHamil(mx, np.ones(n), np.asarray(mx_codes))

    # quench from the fully polarised |0...0> state
    psi = qt.createQureg(n, env)
    qt.initZeroState(psi)

    e0 = qt.calcExpecPauliHamil(psi, hamil)
    print(f"TFIM chain n={n}, J=1, h=0.7, dt={dt}, order-2 Trotter")
    print(f"t=0.00  <H>={e0:+.6f}  <Mx>="
          f"{qt.calcExpecPauliHamil(psi, mx):+.6f}")

    drift_max = 0.0
    for s in range(1, steps + 1):
        qt.applyTrotterCircuit(psi, hamil, dt, 2, 1)
        if s % max(1, steps // 5) == 0:
            e = qt.calcExpecPauliHamil(psi, hamil)
            m = qt.calcExpecPauliHamil(psi, mx)
            drift_max = max(drift_max, abs(e - e0))
            print(f"t={s * dt:.2f}  <H>={e:+.6f}  <Mx>={m:+.6f}")

    tot = qt.calcTotalProb(psi)
    print(f"energy drift |<H>(t) - <H>(0)| <= {drift_max:.2e} "
          f"(conserved up to Trotter error O(dt^2) + float precision)")
    print(f"totalProb = {tot:.8f}")
    assert drift_max < 2e-3 * abs(e0), (drift_max, e0)
    assert abs(tot - 1.0) < 1e-4, tot
    print("OK")


if __name__ == "__main__":
    main()
