"""Grover's search with quest_tpu.

Same algorithm the reference demonstrates
(/root/reference/examples/grovers_search.c): amplitude amplification of a
randomly chosen marked element via oracle + diffuser built from
pauliX / multiControlledPhaseFlip / hadamard API calls.

This file shows BOTH execution styles the framework offers:
  --api    gate-at-a-time imperative API (reference style; default)
  --fused  the whole search traced once through the fused-circuit
           scheduler (quest_tpu.circuit), compiling to a few passes
           over HBM per iteration instead of one pass per gate.
"""

import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import quest_tpu as qt


def apply_oracle(qureg, num_qubits, sol):
    """Flip the sign of |sol>: X-conjugate a controlled-Z on all qubits."""
    for q in range(num_qubits):
        if not (sol >> q) & 1:
            qt.pauliX(qureg, q)
    qt.multiControlledPhaseFlip(qureg, list(range(num_qubits)))
    for q in range(num_qubits):
        if not (sol >> q) & 1:
            qt.pauliX(qureg, q)


def apply_diffuser(qureg, num_qubits):
    """2|+><+| - I via H / X conjugation of the all-qubit phase flip."""
    for q in range(num_qubits):
        qt.hadamard(qureg, q)
    for q in range(num_qubits):
        qt.pauliX(qureg, q)
    qt.multiControlledPhaseFlip(qureg, list(range(num_qubits)))
    for q in range(num_qubits):
        qt.pauliX(qureg, q)
    for q in range(num_qubits):
        qt.hadamard(qureg, q)


def run_api(num_qubits, sol, num_reps):
    env = qt.createQuESTEnv()
    qureg = qt.createQureg(num_qubits, env)
    qt.initPlusState(qureg)
    for r in range(num_reps):
        apply_oracle(qureg, num_qubits, sol)
        apply_diffuser(qureg, num_qubits)
        print(f"prob of solution |{sol}> = {qt.getProbAmp(qureg, sol):g}")
    qt.destroyQureg(qureg, env)
    qt.destroyQuESTEnv(env)


def run_fused(num_qubits, sol, num_reps):
    import jax.numpy as jnp

    from quest_tpu.models import circuits
    from quest_tpu.ops import calculations

    amps = circuits.grover_circuit(num_qubits, sol)
    prob = calculations.calc_prob_of_all_outcomes_statevec(
        amps, num_qubits=num_qubits, qubits=tuple(range(num_qubits))
    )[sol]
    print(f"prob of solution |{sol}> after {num_reps} fused reps = {float(prob):g}")


def main():
    num_qubits = int(os.environ.get("QT_GROVER_QUBITS", "12"))
    num_elems = 2 ** num_qubits
    num_reps = math.ceil(math.pi / 4 * math.sqrt(num_elems))
    print(f"numQubits: {num_qubits}, numElems: {num_elems}, numReps: {num_reps}")

    rng = np.random.default_rng()
    sol = int(rng.integers(num_elems))

    if "--fused" in sys.argv:
        run_fused(num_qubits, sol, num_reps)
    else:
        run_api(num_qubits, sol, num_reps)


if __name__ == "__main__":
    main()
