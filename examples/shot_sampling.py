"""Shot sampling with quest_tpu: the fused measurement path.

The reference's measurement loop (measure() per qubit) is irreducibly
one host round-trip per qubit — a full-state probability reduce, a host
Mersenne-Twister draw, then a collapse sweep (QuEST_common.c:374-380).
quest_tpu compiles the whole chain to ONE device program per shot, and
``measureSequence`` batches a whole readout register into a single
dispatch (on a v5e at 26 qubits: 510 -> 8 ms per measured qubit).

The demo prepares a GHZ-like state plus local rotations, takes repeated
full-register shots (re-preparing between shots, as a sampling workload
does), and prints the bitstring histogram.  Seeded via seedQuEST, so
runs are reproducible.
"""

import os
import sys
from collections import Counter

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import quest_tpu as qt


def prepare(env, n):
    q = qt.createQureg(n, env)
    with qt.gateFusion(q):          # the prep drains as few fused passes
        qt.hadamard(q, 0)
        for t in range(1, n):
            qt.controlledNot(q, t - 1, t)
        for t in range(n):
            qt.rotateY(q, t, 0.15 * (t + 1))
    return q


def main():
    n = int(os.environ.get("QT_SHOT_QUBITS", "10"))
    shots = int(os.environ.get("QT_SHOT_COUNT", "200"))
    env = qt.createQuESTEnv()
    qt.seedQuEST(env, [1234])

    counts = Counter()
    for _ in range(shots):
        q = prepare(env, n)
        outcomes, _probs = qt.measureSequence(q, range(n))
        counts["".join(map(str, reversed(outcomes)))] += 1

    print(f"{shots} shots on {n} qubits -> {len(counts)} distinct bitstrings")
    for bits, c in counts.most_common(5):
        print(f"  |{bits}> : {c}")
    # GHZ correlations survive the local rotations: samples cluster
    # around |0..0> and |1..1> (few bit flips from either pole)
    def flips(bits):
        return min(bits.count("1"), bits.count("0"))
    near_pole = sum(c for b, c in counts.items() if flips(b) <= 2)
    top2 = sum(c for _, c in counts.most_common(2))
    print(f"top-2 mass: {top2 / shots:.2f}; "
          f"within 2 flips of a pole: {near_pole / shots:.2f}")


if __name__ == "__main__":
    main()
