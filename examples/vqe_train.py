"""VQE training with quest_tpu: gradient descent on a PauliHamil energy.

The reference library can *evaluate* <psi|H|psi> (calcExpecPauliHamil,
QuEST.h:4285) but has no autodiff and no optimizer; a VQE around it needs
finite differences in user code. Here the whole step — ansatz, energy,
gradient, Adam update — is one jitted XLA program (quest_tpu.models.vqe),
and a parameter batch can be sharded over a (dp, amps) mesh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import numpy as np
import optax

from quest_tpu.models import vqe as vqe_mod


def main():
    num_qubits = int(os.environ.get("QT_VQE_QUBITS", "10"))
    depth, num_terms, steps = 3, 6, 60

    codes, coeffs = vqe_mod.random_hamiltonian(num_qubits, num_terms, seed=11)
    model = vqe_mod.VQE(num_qubits, depth, codes, coeffs, mesh=None)
    optimizer = optax.adam(5e-2)

    params = model.init_params(jax.random.PRNGKey(0))
    opt_state = optimizer.init(params)
    step = jax.jit(model.make_train_step(optimizer))

    print(f"VQE: {num_qubits} qubits, depth {depth}, {num_terms} Pauli terms")
    for i in range(steps):
        params, opt_state, energy = step(params, opt_state)
        if i % 10 == 0 or i == steps - 1:
            print(f"  step {i:3d}  energy = {float(energy):+.6f}")

    print("done; final energy", float(energy))


if __name__ == "__main__":
    main()
