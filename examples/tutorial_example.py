"""quest_tpu tutorial: a basic 3-qubit circuit.

Walks the same ground as the reference tutorial
(/root/reference/examples/tutorial_example.c): environment setup, a small
circuit mixing named gates, compact/controlled unitaries and a Toffoli as
an N-qubit matrix, then state interrogation and measurement.

Run:  python examples/tutorial_example.py          (TPU if available)
      QT_EXAMPLES_CPU=1 python examples/tutorial_example.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("QT_EXAMPLES_CPU") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import quest_tpu as qt


def main():
    # -- environment (once per program) --
    env = qt.createQuESTEnv()
    print("-" * 55)
    print("Running the quest_tpu tutorial:")
    print("\tBasic circuit involving a system of 3 qubits.")
    print("-" * 55)

    qubits = qt.createQureg(3, env)
    qt.initZeroState(qubits)

    print("\nThis is our environment:")
    qt.reportQuregParams(qubits)
    qt.reportQuESTEnv(env)

    # -- apply circuit --
    qt.hadamard(qubits, 0)
    qt.controlledNot(qubits, 0, 1)
    qt.rotateY(qubits, 2, 0.1)
    qt.multiControlledPhaseFlip(qubits, [0, 1, 2])

    u = np.array([[0.5 + 0.5j, 0.5 - 0.5j],
                  [0.5 - 0.5j, 0.5 + 0.5j]])
    qt.unitary(qubits, 0, u)

    a, b = 0.5 + 0.5j, 0.5 - 0.5j
    qt.compactUnitary(qubits, 1, a, b)

    qt.rotateAroundAxis(qubits, 2, 3.14 / 2, (1.0, 0.0, 0.0))
    qt.controlledCompactUnitary(qubits, 0, 1, a, b)
    qt.multiControlledUnitary(qubits, [0, 1], 2, u)

    # Toffoli as an explicit 3-qubit matrix
    toff = np.eye(8, dtype=complex)
    toff[6, 6] = toff[7, 7] = 0.0
    toff[6, 7] = toff[7, 6] = 1.0
    qt.multiQubitUnitary(qubits, [0, 1, 2], toff)

    # -- study the output state --
    print("\nCircuit output:")
    print(f"Probability amplitude of |111>: {qt.getProbAmp(qubits, 7):g}")
    print(
        "Probability of qubit 2 being in state 1: "
        f"{qt.calcProbOfOutcome(qubits, 2, 1):g}"
    )

    outcome = qt.measure(qubits, 0)
    print(f"Qubit 0 was measured in state {outcome}")
    outcome, prob = qt.measureWithStats(qubits, 2)
    print(f"Qubit 2 collapsed to {outcome} with probability {prob:g}")

    qt.destroyQureg(qubits, env)
    qt.destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
