"""OPENQASM 2.0 circuit logger.

Python re-implementation of the reference's QASM subsystem
(QuEST_qasm.{h,c}): a per-register growable text log recording each API gate
(here: a list of lines — Python strings make the reference's heap-buffer
mechanics at QuEST_qasm.c:93-119 unnecessary).  Behavioural parity:

- gate-name table matches QuEST_qasm.c:39-53 ("x","y","z","t","s","h",
  "Rx","Ry","Rz","U","swap","sqrtswap"); controls stack a "c" prefix per
  control qubit (addGateToQASM, QuEST_qasm.c:139-177).
- 2x2 unitaries/compact-unitaries/axis rotations are decomposed to
  U(rz2, ry, rz1) via ZYZ angles (QuEST_qasm.c:196-237).
- controlled phase-shifts / unitaries emit an extra uncontrolled Rz to
  restore the global phase the controlled decomposition discards
  (QuEST_qasm.c:248-299,341-361).
- control-on-0 is wrapped in an X sandwich (QuEST_qasm.c:363-380);
  multi-target NOT unrolls to per-target (c)x (QuEST_qasm.c:382-394).
- measurement -> "measure q[i] -> c[i]" (:411-420); initZero -> "reset"
  (:428-434); non-representable ops are logged as comments
  (qasm_recordComment, QuEST_qasm.c:121).
"""

from __future__ import annotations

import cmath
import math
from typing import Optional, Sequence


class QASMLogger:
    def __init__(self, num_qubits: int):
        self.is_logging = False
        self.num_qubits = num_qubits
        self.lines = [
            "OPENQASM 2.0;",
            f"qreg q[{num_qubits}];",
            f"creg c[{num_qubits}];",
        ]

    # -- recording control (QuEST.h:3351-3390) --
    def start(self):
        self.is_logging = True

    def stop(self):
        self.is_logging = False

    def clear(self):
        self.lines = self.lines[:3]

    def __str__(self):
        return "\n".join(self.lines) + "\n"

    # -- emitters --
    def _add(self, line: str):
        self.lines.append(line)

    def comment(self, text: str):
        if self.is_logging:
            self._add(f"// {text}")

    def _gate_str(
        self,
        name: str,
        controls: Sequence[int],
        target: int,
        params: Sequence[float] = (),
    ) -> str:
        full = "c" * len(controls) + name
        if params:
            full += "(" + ",".join(_fmt(p) for p in params) + ")"
        qubits = ",".join(f"q[{c}]" for c in controls)
        if qubits:
            qubits += ","
        qubits += f"q[{target}]"
        return f"{full} {qubits};"

    def gate(
        self,
        name: str,
        controls: Sequence[int] = (),
        target: int = 0,
        params: Sequence[float] = (),
        control_states: Optional[Sequence[int]] = None,
    ):
        if not self.is_logging:
            return
        zero_ctrls = (
            [c for c, s in zip(controls, control_states) if s == 0]
            if control_states is not None
            else []
        )
        for c in zero_ctrls:
            self._add(self._gate_str("x", (), c))
        self._add(self._gate_str(name, controls, target, params))
        for c in zero_ctrls:
            self._add(self._gate_str("x", (), c))

    def unitary_2x2(self, matrix, controls: Sequence[int], target: int,
                    control_states: Optional[Sequence[int]] = None):
        """Decompose to U(rz2, ry, rz1); when controlled, also emit the
        global-phase-restoring Rz (QuEST_qasm.c:341-361)."""
        if not self.is_logging:
            return
        import numpy as np

        m = np.asarray(matrix, dtype=complex)
        alpha, beta, phase = _complex_pair_and_phase(m)
        rz2, ry, rz1 = _zyz_from_complex_pair(alpha, beta)
        if controls and abs(phase) > 1e-12:
            # restore discarded global phase as uncontrolled Rz on control
            self._add(self._gate_str("Rz", (), controls[0], [2 * phase]))
        self.gate("U", controls, target, [rz2, ry, rz1], control_states)

    def phase_shift(self, angle: float, controls: Sequence[int], target: int):
        """Rz with half-angle global-phase fix (QuEST_qasm.c:248-299)."""
        if not self.is_logging:
            return
        if controls:
            self._add(self._gate_str("Rz", (), controls[0], [angle / 2]))
        self.gate("Rz", controls, target, [angle])

    def measure(self, qubit: int):
        if self.is_logging:
            self._add(f"measure q[{qubit}] -> c[{qubit}];")

    def init_zero(self):
        if self.is_logging:
            self._add("reset q;")


def _fmt(p: float) -> str:
    return f"{p:g}"


def _complex_pair_and_phase(m):
    """Factor a 2x2 unitary into global phase * [[a, -b*],[b, a*]]
    (getComplexPairAndPhaseFromUnitary, QuEST_qasm.c)."""
    det = m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0]
    phase = cmath.phase(det) / 2
    g = cmath.exp(-1j * phase)
    return m[0, 0] * g, m[1, 0] * g, phase


def _zyz_from_complex_pair(alpha, beta):
    """U = Rz(rz2) Ry(ry) Rz(rz1) angles from a (alpha, beta) Givens pair
    (getZYZRotAnglesFromComplexPair, QuEST_qasm.c:196-237)."""
    alpha_mag = abs(alpha)
    ry = 2 * math.acos(min(1.0, max(0.0, alpha_mag)))
    alpha_phase = cmath.phase(alpha) if alpha_mag > 1e-15 else 0.0
    beta_phase = cmath.phase(beta) if abs(beta) > 1e-15 else 0.0
    rz2 = -alpha_phase + beta_phase
    rz1 = -alpha_phase - beta_phase
    return rz2, ry, rz1
