"""OPENQASM 2.0 circuit logger.

Python re-implementation of the reference's QASM subsystem
(QuEST_qasm.{h,c}): a per-register growable text log recording each API gate
(here: a list of lines — Python strings make the reference's heap-buffer
mechanics at QuEST_qasm.c:93-119 unnecessary).  Behavioural parity:

- gate-name table matches QuEST_qasm.c:39-53 ("x","y","z","t","s","h",
  "Rx","Ry","Rz","U","swap","sqrtswap"); controls stack a "c" prefix per
  control qubit (addGateToQASM, QuEST_qasm.c:139-177).
- 2x2 unitaries/compact-unitaries/axis rotations are decomposed to
  U(rz2, ry, rz1) via ZYZ angles (QuEST_qasm.c:196-237).
- controlled phase-shifts / unitaries emit an extra uncontrolled Rz to
  restore the global phase the controlled decomposition discards
  (QuEST_qasm.c:248-299,341-361).
- control-on-0 is wrapped in an X sandwich (QuEST_qasm.c:363-380);
  multi-target NOT unrolls to per-target (c)x (QuEST_qasm.c:382-394).
- measurement -> "measure q[i] -> c[i]" (:411-420); initZero -> "reset"
  (:428-434); non-representable ops are logged as comments
  (qasm_recordComment, QuEST_qasm.c:121).
"""

from __future__ import annotations

import cmath
import math
from typing import Optional, Sequence


class QASMLogger:
    def __init__(self, num_qubits: int):
        self.is_logging = False
        self.num_qubits = num_qubits
        self.lines = [
            "OPENQASM 2.0;",
            f"qreg q[{num_qubits}];",
            f"creg c[{num_qubits}];",
        ]

    # -- recording control (QuEST.h:3351-3390) --
    def start(self):
        self.is_logging = True

    def stop(self):
        self.is_logging = False

    def clear(self):
        self.lines = self.lines[:3]

    def __str__(self):
        return "\n".join(self.lines) + "\n"

    # -- emitters --
    def _add(self, line: str):
        self.lines.append(line)

    def comment(self, text: str):
        if self.is_logging:
            self._add(f"// {text}")

    def _gate_str(
        self,
        name: str,
        controls: Sequence[int],
        target: int,
        params: Sequence[float] = (),
    ) -> str:
        full = "c" * len(controls) + name
        if params:
            full += "(" + ",".join(_fmt(p) for p in params) + ")"
        qubits = ",".join(f"q[{c}]" for c in controls)
        if qubits:
            qubits += ","
        qubits += f"q[{target}]"
        return f"{full} {qubits};"

    def gate(
        self,
        name: str,
        controls: Sequence[int] = (),
        target: int = 0,
        params: Sequence[float] = (),
        control_states: Optional[Sequence[int]] = None,
    ):
        if not self.is_logging:
            return
        zero_ctrls = (
            [c for c, s in zip(controls, control_states) if s == 0]
            if control_states is not None
            else []
        )
        for c in zero_ctrls:
            self._add(self._gate_str("x", (), c))
        self._add(self._gate_str(name, controls, target, params))
        for c in zero_ctrls:
            self._add(self._gate_str("x", (), c))

    def unitary_2x2(self, matrix, controls: Sequence[int], target: int,
                    control_states: Optional[Sequence[int]] = None):
        """Decompose to U(rz2, ry, rz1); when controlled, also emit the
        global-phase-restoring Rz (QuEST_qasm.c:341-361)."""
        if not self.is_logging:
            return
        import numpy as np

        m = np.asarray(matrix, dtype=complex)
        alpha, beta, phase = _complex_pair_and_phase(m)
        rz2, ry, rz1 = _zyz_from_complex_pair(alpha, beta)
        if controls and abs(phase) > 1e-12:
            # restore discarded global phase as uncontrolled Rz on control
            self._add(self._gate_str("Rz", (), controls[0], [2 * phase]))
        self.gate("U", controls, target, [rz2, ry, rz1], control_states)

    def phase_shift(self, angle: float, controls: Sequence[int], target: int):
        """Rz with half-angle global-phase fix (QuEST_qasm.c:248-299)."""
        if not self.is_logging:
            return
        if controls:
            self._add(self._gate_str("Rz", (), controls[0], [angle / 2]))
        self.gate("Rz", controls, target, [angle])

    # -- phase-function records (multi-line symbolic comments) -----------
    # Mirrors the reference's record shapes (qasm_recordPhaseFunc /
    # qasm_recordMultiVarPhaseFunc / qasm_recordNamedPhaseFunc,
    # QuEST_qasm.c:490-891): the applied scalar rendered symbolically with
    # per-register symbols, the informing sub-registers, and overrides.

    def _sym(self, num_regs: int, r: int) -> str:
        if num_regs <= 7:
            return "xyztrvu"[r]
        if num_regs <= 24:
            return "abcdefghjklmnpqrstuvwxyz"[r]
        return f"x{r}"

    def _enc_str(self, encoding: int) -> str:
        return "an unsigned" if encoding == 0 else "a two's complement"

    def _poly_str(self, coeffs, exponents, sym: str, first_signed=True) -> str:
        parts = []
        for t, (c, e) in enumerate(zip(coeffs, exponents)):
            mag = c if (t == 0 and first_signed) else abs(c)
            term = (f"{_fmt(mag)} {sym}^{_fmt(e)}" if e > 0
                    else f"{_fmt(mag)} {sym}^({_fmt(e)})")
            if t:
                parts.append(" + " if c > 0 else " - ")
            parts.append(term)
        return "".join(parts)

    def _override_lines(self, regs, inds, phases):
        if len(phases) == 0:
            return
        self.comment("  though with overrides")
        nr = len(regs)
        for row, ph in zip(inds, phases):
            if nr == 1:
                ket = f"|{int(row[0])}>"
            else:
                ket = "|" + ", ".join(
                    f"{self._sym(nr, r)}={int(row[r])}" for r in range(nr)) + ">"
            val = f"exp(i {_fmt(ph)})" if ph >= 0 else f"exp(i ({_fmt(ph)}))"
            self._add(f"//     {ket} -> {val}")

    def _reg_lines(self, regs, encoding):
        self.comment(
            f"  upon substates informed by qubits (under "
            f"{self._enc_str(encoding)} binary encoding)")
        nr = len(regs)
        for r, qs in enumerate(regs):
            body = ", ".join(str(q) for q in qs)
            self._add(f"//     |{self._sym(nr, r)}> = {{{body}}}")

    def phase_func(self, qubits, encoding, coeffs, exponents,
                   override_inds, override_phases):
        if not self.is_logging:
            return
        self.comment(
            "Here, applyPhaseFunc() multiplied a complex scalar of the form")
        self._add(f"//     exp(i ({self._poly_str(coeffs, exponents, 'x')}))")
        self.comment(
            f"  upon every substate |x>, informed by qubits (under "
            f"{self._enc_str(encoding)} binary encoding)")
        self._add("//     {" + ", ".join(str(q) for q in qubits) + "}")
        self._override_lines([qubits], override_inds, override_phases)

    def multi_var_phase_func(self, regs, encoding, coeffs, exponents,
                             terms_per_reg, override_inds, override_phases):
        if not self.is_logging:
            return
        self.comment("Here, applyMultiVarPhaseFunc() multiplied a complex "
                     "scalar of the form")
        self.comment("    exp(i (")
        nr = len(regs)
        pos = 0
        for r, nt in enumerate(terms_per_reg):
            cs = coeffs[pos:pos + nt]
            es = exponents[pos:pos + nt]
            pos += nt
            lead = " + " if cs[0] > 0 else " - "
            body = self._poly_str(
                [abs(cs[0])] + list(cs[1:]), es, self._sym(nr, r))
            tail = " ))" if r == nr - 1 else ""
            self._add(f"//         {lead}{body}{tail}")
        self._reg_lines(regs, encoding)
        self._override_lines(regs, override_inds, override_phases)

    def named_phase_func(self, regs, encoding, func_code, params,
                         override_inds, override_phases):
        if not self.is_logging:
            return
        from .ops import phasefunc as PF

        self.comment(
            "Here, applyNamedPhaseFunc() multiplied a complex scalar of form")
        nr = len(regs)
        syms = [self._sym(nr, r) for r in range(nr)]
        params = list(params)
        scaled = func_code in (
            PF.SCALED_NORM, PF.SCALED_INVERSE_NORM,
            PF.SCALED_INVERSE_SHIFTED_NORM, PF.SCALED_PRODUCT,
            PF.SCALED_INVERSE_PRODUCT, PF.SCALED_DISTANCE,
            PF.SCALED_INVERSE_DISTANCE, PF.SCALED_INVERSE_SHIFTED_DISTANCE)
        coef = ""
        if scaled and params:
            coef = (f"{_fmt(params[0])} " if params[0] > 0
                    else f"({_fmt(params[0])}) ")
        norm_family = func_code in (
            PF.NORM, PF.SCALED_NORM, PF.INVERSE_NORM, PF.SCALED_INVERSE_NORM,
            PF.SCALED_INVERSE_SHIFTED_NORM)
        prod_family = func_code in (
            PF.PRODUCT, PF.SCALED_PRODUCT, PF.INVERSE_PRODUCT,
            PF.SCALED_INVERSE_PRODUCT)
        if norm_family:
            if func_code in (PF.NORM, PF.SCALED_NORM):
                opener, closer = "sqrt(", ")"
            elif func_code == PF.INVERSE_NORM:
                opener, closer = "1 / sqrt(", ")"
            else:
                opener, closer = "/ sqrt(", ")"
            if func_code == PF.SCALED_INVERSE_SHIFTED_NORM:
                terms = []
                for r, s in enumerate(syms):
                    d = params[2 + r] if len(params) > 2 + r else 0.0
                    terms.append(f"({s}^2-{_fmt(abs(d))})" if d >= 0
                                 else f"({s}^2+{_fmt(abs(d))})")
                body = " + ".join(terms)
            else:
                body = " + ".join(f"{s}^2" for s in syms)
            self._add(f"//     exp(i {coef}{opener}{body}{closer})")
        elif prod_family:
            if func_code == PF.INVERSE_PRODUCT:
                opener, closer = "1 / (", ")"
            elif func_code == PF.SCALED_INVERSE_PRODUCT:
                opener, closer = "/ (", ")"
            else:
                opener, closer = "", ""
            body = " ".join(syms)
            self._add(f"//     exp(i {coef}{opener}{body}{closer})")
        else:  # distance family: pairs (x1-x2)^2 + ...
            if func_code in (PF.DISTANCE, PF.SCALED_DISTANCE):
                opener, closer = "sqrt(", ")"
            elif func_code == PF.INVERSE_DISTANCE:
                opener, closer = "1 / sqrt(", ")"
            else:
                opener, closer = "/ sqrt(", ")"
            terms = []
            for k in range(nr // 2):
                a, b = syms[2 * k], syms[2 * k + 1]
                if func_code == PF.SCALED_INVERSE_SHIFTED_DISTANCE:
                    d = params[2 + k] if len(params) > 2 + k else 0.0
                    terms.append(f"({a}-{b}-{_fmt(d)})^2" if d >= 0
                                 else f"({a}-{b}+{_fmt(abs(d))})^2")
                else:
                    terms.append(f"({a}-{b})^2")
            self._add(f"//     exp(i {coef}{opener}{' + '.join(terms)}{closer})")
        # divergence-override parameter (the value at singular points)
        if func_code in (PF.INVERSE_NORM, PF.INVERSE_PRODUCT,
                         PF.INVERSE_DISTANCE) and params:
            self.comment(f"  (interpreted as {_fmt(params[0])} at "
                         "singularities)")
        self._reg_lines(regs, encoding)
        if func_code in (PF.SCALED_INVERSE_SHIFTED_NORM,
                         PF.SCALED_INVERSE_SHIFTED_DISTANCE):
            self.comment("  with the additional parameters")
            nd = nr if func_code == PF.SCALED_INVERSE_SHIFTED_NORM else nr // 2
            for k in range(nd):
                d = params[2 + k] if len(params) > 2 + k else 0.0
                self._add(f"//     delta{k} = {_fmt(d)}")
        self._override_lines(regs, override_inds, override_phases)

    def measure(self, qubit: int):
        if self.is_logging:
            self._add(f"measure q[{qubit}] -> c[{qubit}];")

    def init_zero(self):
        if self.is_logging:
            self._add("reset q;")


def _fmt(p: float) -> str:
    return f"{p:g}"


def _complex_pair_and_phase(m):
    """Factor a 2x2 unitary into global phase * [[a, -b*],[b, a*]]
    (getComplexPairAndPhaseFromUnitary, QuEST_qasm.c)."""
    det = m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0]
    phase = cmath.phase(det) / 2
    g = cmath.exp(-1j * phase)
    return m[0, 0] * g, m[1, 0] * g, phase


def _zyz_from_complex_pair(alpha, beta):
    """U = Rz(rz2) Ry(ry) Rz(rz1) angles from a (alpha, beta) Givens pair
    (getZYZRotAnglesFromComplexPair, QuEST_qasm.c:196-237)."""
    alpha_mag = abs(alpha)
    ry = 2 * math.acos(min(1.0, max(0.0, alpha_mag)))
    alpha_phase = cmath.phase(alpha) if alpha_mag > 1e-15 else 0.0
    beta_phase = cmath.phase(beta) if abs(beta) > 1e-15 else 0.0
    rz2 = -alpha_phase + beta_phase
    rz1 = -alpha_phase - beta_phase
    return rz2, ry, rz1
